// Native data-pipeline kernels for distributeddataparallel_tpu.
//
// The reference reaches native code through torch's C++ DataLoader workers
// and the DDP Reducer (SURVEY.md §2b); this library is the TPU framework's
// own native layer for the host-side hot loops:
//
//   - gather_rows_f32 / gather_norm_u8: the per-batch fancy-index copy
//     (and the fused uint8 -> normalized float32 transform of ref
//     dpp.py:32's ToTensor+Normalize), multithreaded with the GIL
//     released (called via ctypes from data.loader).
//   - chw_to_hwc_f32: layout conversion for CHW-stored datasets (CIFAR
//     pickle payloads) into the NHWC layout TPUs want.
//   - plan_buckets: the DDP Reducer's 25 MiB reverse-order bucket
//     assignment (parallel.data_parallel.bucket_gradients planning).
//
// Build: csrc/Makefile -> libddp_native.so, loaded lazily by
// distributeddataparallel_tpu/native/__init__.py (pure-Python fallbacks
// keep every feature working without the toolchain).

#include <cstdint>
#include <cstring>
#include <algorithm>
#include <thread>
#include <vector>

namespace {

// Run fn(begin, end) over [0, n) split across up to max_threads threads.
template <typename Fn>
void parallel_for(int64_t n, int max_threads, Fn fn) {
  int hw = static_cast<int>(std::thread::hardware_concurrency());
  int threads = std::max(1, std::min(max_threads, hw));
  if (threads == 1 || n < 2) {
    fn(static_cast<int64_t>(0), n);
    return;
  }
  std::vector<std::thread> pool;
  int64_t chunk = (n + threads - 1) / threads;
  for (int t = 0; t < threads; ++t) {
    int64_t begin = t * chunk;
    int64_t end = std::min(n, begin + chunk);
    if (begin >= end) break;
    pool.emplace_back([=] { fn(begin, end); });
  }
  for (auto& th : pool) th.join();
}

}  // namespace

extern "C" {

// out[i, :] = src[idx[i], :]; rows are row_elems float32 each.
void ddp_gather_rows_f32(const float* src, const int64_t* idx, int64_t n_idx,
                         int64_t row_elems, float* out, int max_threads) {
  parallel_for(n_idx, max_threads, [=](int64_t b, int64_t e) {
    for (int64_t i = b; i < e; ++i) {
      std::memcpy(out + i * row_elems, src + idx[i] * row_elems,
                  sizeof(float) * static_cast<size_t>(row_elems));
    }
  });
}

// out[i, :] = (src[idx[i], :] / 255 - shift) / scale  (u8 -> f32 fused with
// the reference's ToTensor + Normalize transform, ref dpp.py:32).
void ddp_gather_norm_u8(const uint8_t* src, const int64_t* idx, int64_t n_idx,
                        int64_t row_elems, float shift, float scale,
                        float* out, int max_threads) {
  const float inv255 = 1.0f / 255.0f;
  const float inv_scale = 1.0f / scale;
  parallel_for(n_idx, max_threads, [=](int64_t b, int64_t e) {
    for (int64_t i = b; i < e; ++i) {
      const uint8_t* s = src + idx[i] * row_elems;
      float* o = out + i * row_elems;
      for (int64_t j = 0; j < row_elems; ++j) {
        o[j] = (static_cast<float>(s[j]) * inv255 - shift) * inv_scale;
      }
    }
  });
}

// (N, C, H, W) f32 -> (N, H, W, C): the NHWC layout XLA wants on TPU.
void ddp_chw_to_hwc_f32(const float* src, int64_t n, int64_t c, int64_t h,
                        int64_t w, float* out, int max_threads) {
  parallel_for(n, max_threads, [=](int64_t b, int64_t e) {
    for (int64_t i = b; i < e; ++i) {
      const float* img = src + i * c * h * w;
      float* o = out + i * h * w * c;
      for (int64_t ch = 0; ch < c; ++ch) {
        const float* plane = img + ch * h * w;
        for (int64_t p = 0; p < h * w; ++p) {
          o[p * c + ch] = plane[p];
        }
      }
    }
  });
}

// Fused training-augmentation gather: for each output row i,
//   out[i] = normalize(flip_i(crop_i(src[idx[i]])))
// in ONE pass over the uint8 source — the gather, the RandomCrop(pad)
// (virtual padding: out-of-bounds source pixels become `fill`, already
// in normalized units), the optional horizontal flip, and the
// ToTensor+Normalize transform never materialize intermediates.
// Layout: src (N, H, W, C) u8; oy/ox in [0, 2*pad]; flip 0/1 per row.
// Crop-then-flip order matches data/transforms.py: the flipped output
// pixel (y, x) reads the crop at (y, w-1-x).
void ddp_gather_augment_u8(const uint8_t* src, const int64_t* idx,
                           int64_t n_idx, int64_t h, int64_t w, int64_t c,
                           const int64_t* oy, const int64_t* ox,
                           const uint8_t* flip, int64_t pad, float shift,
                           float scale, float fill, float* out,
                           int max_threads) {
  const float inv255 = 1.0f / 255.0f;
  const float inv_scale = 1.0f / scale;
  parallel_for(n_idx, max_threads, [=](int64_t b, int64_t e) {
    for (int64_t i = b; i < e; ++i) {
      const uint8_t* img = src + idx[i] * h * w * c;
      float* o = out + i * h * w * c;
      const int64_t dy = oy[i] - pad;
      const int64_t dx = ox[i] - pad;
      const bool fl = flip[i] != 0;
      for (int64_t y = 0; y < h; ++y) {
        const int64_t sy = y + dy;
        const bool row_ok = sy >= 0 && sy < h;
        for (int64_t x = 0; x < w; ++x) {
          const int64_t cx = fl ? (w - 1 - x) : x;
          const int64_t sx = cx + dx;
          float* op = o + (y * w + x) * c;
          if (row_ok && sx >= 0 && sx < w) {
            const uint8_t* sp = img + (sy * w + sx) * c;
            for (int64_t ch = 0; ch < c; ++ch) {
              op[ch] =
                  (static_cast<float>(sp[ch]) * inv255 - shift) * inv_scale;
            }
          } else {
            for (int64_t ch = 0; ch < c; ++ch) op[ch] = fill;
          }
        }
      }
    }
  });
}

// DDP Reducer bucket planning: walk leaves in REVERSE order (last-produced
// grads first), start a new bucket when adding a leaf would exceed
// bucket_bytes (a leaf larger than bucket_bytes gets its own bucket).
// out_bucket[i] = bucket id of leaf i (ids ordered by reduction order).
// Returns the number of buckets.
int64_t ddp_plan_buckets(const int64_t* leaf_bytes, int64_t n_leaves,
                         int64_t bucket_bytes, int64_t* out_bucket) {
  int64_t bucket = 0;
  int64_t used = 0;
  bool open = false;
  for (int64_t k = n_leaves - 1; k >= 0; --k) {
    int64_t b = leaf_bytes[k];
    if (open && used + b > bucket_bytes) {
      ++bucket;
      used = 0;
    }
    out_bucket[k] = bucket;
    used += b;
    open = true;
  }
  return open ? bucket + 1 : 0;
}

}  // extern "C"
