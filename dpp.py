#!/usr/bin/env python
"""Single-entrypoint data-parallel trainer — the reference `dpp.py`, TPU-native.

Usage (mirrors `python dpp.py` of the reference, ref dpp.py:60-65, plus the
flags SURVEY.md §5 notes the reference hard-codes):

    python dpp.py                              # toy CNN on synthetic data
    python dpp.py --model resnet18 --dataset cifar10 --device tpu
    python dpp.py --device cpu --fake-devices 8   # 8-way DP on one CPU

Structure intentionally parallels the reference script:
  setup()  -> runtime.init_process_group + mesh        (ref dpp.py:20-21)
  train()  -> build data/model/loss/optimizer, loop    (ref dpp.py:27-57)
  main()   -> device selection + launch                (ref dpp.py:60-62)

Differences by design (SURVEY.md §2d): self-contained init (no
MASTER_ADDR/PORT), no download race, multi-host capable, checkpoint/resume
and eval available, logging off the hot path.
"""

from __future__ import annotations

import argparse
import os
import sys
import time


def _dataset_arg(v: str) -> str:
    """Parse-time --dataset validation (argparse choices can't express the
    shards:DIR / tokens:FILE forms): typos fail at parse for CLI and
    programmatic train(parse_args([...])) callers alike, instead of
    falling through to the CIFAR-10 default in build_dataset."""
    if v in ("synthetic", "cifar10", "synthetic-lm") or v.startswith(
        ("shards:", "tokens:")
    ):
        return v
    raise argparse.ArgumentTypeError(
        f"{v!r} is not one of synthetic | cifar10 | synthetic-lm | "
        "shards:DIR | tokens:FILE"
    )


def parse_args(argv=None):
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--device", choices=["tpu", "cpu", "cuda", "auto"], default="auto",
                   help="backend selector (north-star --device flag)")
    p.add_argument("--fake-devices", type=int, default=0,
                   help="force N host-platform devices (CPU DP simulation)")
    p.add_argument("--model", default="cnn",
                   choices=["mlp", "cnn", "resnet18", "resnet50", "gpt2", "llama"],
                   help="model family (resnet18 matches the reference)")
    p.add_argument("--dataset", default=None, type=_dataset_arg,
                   help="one of synthetic | cifar10 | synthetic-lm | "
                        "shards:DIR (streaming memmapped image shards, "
                        "ImageNet-scale path; DIR or DIR/{train,val}) | "
                        "tokens:FILE (memmapped real-token LM corpus, "
                        ".npy stream or rows; eval reads the sibling val "
                        "split); default: synthetic-lm for gpt2/llama, "
                        "synthetic otherwise")
    p.add_argument("--seq-len", type=int, default=128,
                   help="LM sequence length")
    p.add_argument("--token-stride", type=int, default=None,
                   help="window-start spacing for tokens:FILE flat streams "
                        "(< seq-len overlaps windows; default seq-len). "
                        "Train split only — eval keeps non-overlapping "
                        "windows so its mean is over distinct text")
    p.add_argument("--dropout", type=float, default=0.0,
                   help="LM residual/embedding dropout rate (GPT-2 style). "
                        "Trains under DP/ZeRO/TP/EP/CP incl. scanned+remat "
                        "stacks (per-layer rngs split through the scan); "
                        "--fsdp/--pp reject it")
    p.add_argument("--vocab-size", type=int, default=256,
                   help="LM vocab size (synthetic data; real data overrides)")
    p.add_argument("--layers", type=int, default=None,
                   help="override the model family's layer count")
    p.add_argument("--d-model", type=int, default=None,
                   help="override the model family's width")
    p.add_argument("--data-root", default="data")
    p.add_argument("--pretrained", default=None, metavar="FILE",
                   help="initialize from a pretrained checkpoint before "
                        "training (ref dpp.py:14's pretrained=True analog): "
                        "torchvision ResNet state_dict, HF GPT-2 or Llama "
                        "tensors (.safetensors or torch .pth), or this "
                        "framework's save_params safetensors — the format "
                        "is sniffed from the key names")
    p.add_argument("--epochs", type=int, default=5)          # ref dpp.py:27
    p.add_argument("--batch-size", type=int, default=32,     # ref dpp.py:35
                   help="per-replica batch (global = batch × replicas)")
    p.add_argument("--lr", type=float, default=0.01)         # ref dpp.py:41
    p.add_argument("--momentum", type=float, default=0.0)
    p.add_argument("--optimizer", choices=["sgd", "adam", "adamw"],
                   default="sgd",
                   help="sgd mirrors the reference (ref dpp.py:41); "
                        "adam/adamw for the LM configs")
    p.add_argument("--weight-decay", type=float, default=0.0,
                   help="decoupled weight decay (adamw; ignored otherwise)")
    p.add_argument("--lr-schedule", choices=["constant", "cosine", "linear"],
                   default="constant",
                   help="learning-rate schedule over the whole run "
                        "(optional --warmup-steps linear warmup first)")
    p.add_argument("--warmup-steps", type=int, default=0,
                   help="linear LR warmup steps before the schedule")
    p.add_argument("--min-lr", type=float, default=0.0,
                   help="floor the cosine/linear decay at this LR")
    p.add_argument("--grad-clip", type=float, default=None,
                   help="clip the synced gradient to this global L2 norm "
                        "(torch clip_grad_norm_ analog; axis-aware exact "
                        "norm under every composition: --zero/--fsdp flat "
                        "chunks, --tp/--ep/--pp model-axis shards)")
    p.add_argument("--seed", type=int, default=0)            # ref dpp.py:29
    p.add_argument("--accum-steps", type=int, default=1,
                   help="gradient accumulation (DDP no_sync analog)")
    p.add_argument("--workers", type=int, default=0,
                   help="background input-pipeline threads (0 = inline)")
    p.add_argument("--augment", action="store_true",
                   help="standard CIFAR training augmentation (random "
                        "crop pad 4 + horizontal flip), deterministic per "
                        "(seed, epoch, step); image datasets only")
    p.add_argument("--cp", type=int, default=1,
                   help="context-parallel degree: shard the sequence over "
                        "a 'seq' mesh axis with collective attention (LM only)")
    p.add_argument("--cp-impl", choices=["ring", "ulysses"], default="ring",
                   help="sequence-parallel attention collective: 'ring' "
                        "(blockwise ppermute ring, O(S/N) memory) or "
                        "'ulysses' (all_to_all to head-sharded layout; "
                        "local flash attention over the full sequence, "
                        "needs num_heads %% cp == 0)")
    p.add_argument("--tp", type=int, default=1,
                   help="tensor-parallel degree: Megatron column/row "
                        "sharding of attention heads + MLP hidden over a "
                        "'model' mesh axis (LM only)")
    p.add_argument("--pp", type=int, default=1,
                   help="pipeline-parallel degree: GPipe stages over a "
                        "'pipe' mesh axis, layer stack sharded per stage "
                        "(scanned LM models only)")
    p.add_argument("--pp-microbatches", type=int, default=None,
                   help="pipeline microbatches per step (default: --pp)")
    p.add_argument("--pp-schedule", default="gpipe",
                   choices=["gpipe", "1f1b", "zb"],
                   help="pipeline schedule: gpipe (AD through the tick "
                        "loop, O(microbatches) activation memory), 1f1b "
                        "(interleaved manual backward, O(stages) activation "
                        "memory — the Megatron-LM 1F1B schedule), or zb "
                        "(ZB-H1-style zero-bubble: backward split into "
                        "activation-grad B and weight-grad W units so W "
                        "fills the warm-up/drain bubble; same memory as "
                        "1f1b)")
    p.add_argument("--pp-virtual", type=int, default=1,
                   help="interleaved 1F1B: virtual layer chunks per stage "
                        "(Megatron interleaved schedule; requires "
                        "--pp-schedule 1f1b, layers divisible by "
                        "pp x virtual; shrinks the warm-up/drain bubble)")
    p.add_argument("--moe-experts", type=int, default=0,
                   help="replace every block's MLP with N routed experts "
                        "(LM only)")
    p.add_argument("--moe-top-k", type=int, default=1,
                   help="experts per token: 1 = switch routing, "
                        "2 = Mixtral-style renormalized top-2")
    p.add_argument("--ep", type=int, default=1,
                   help="expert-parallel degree: shard MoE experts over "
                        "an 'expert' mesh axis (requires --moe-experts)")
    p.add_argument("--moe-aux-weight", type=float, default=0.01,
                   help="weight of the switch load-balance auxiliary loss")
    p.add_argument("--moe-capacity-factor", type=float, default=0.0,
                   help="> 0 switches MoE to token-choice dispatch with "
                        "capacity ceil(K*T/E * factor) per expert (GShard "
                        "convention, overflow drops through the residual; "
                        "under --ep tokens travel via all_to_all); 0 = "
                        "dense einsum dispatch (every token through every "
                        "local expert — exact, right for tiny E)")
    p.add_argument("--fsdp-gather", choices=["f32", "bf16"], default="f32",
                   help="dtype for FSDP weight gathers: bf16 halves "
                        "collective bytes and gathered-weight residency "
                        "(f32 master storage either way)")
    p.add_argument("--zero", type=int, nargs="?", const=1, default=0,
                   choices=[0, 1, 2, 3], metavar="LEVEL",
                   help="ZeRO weight-update sharding across the data axis. "
                        "--zero (or --zero 1): optimizer state 1/N "
                        "(reduce_scatter + sharded update + all_gather). "
                        "--zero 2: bucketed reduce-scatter straight into "
                        "the 1/N flat grad shard (the full flat f32 grad "
                        "copy never materializes). --zero 3: params stay "
                        "sharded between steps too (1/N stored), gathered "
                        "per bucket inside the step. Levels 2/3 are "
                        "data-axis only and compose with --bucket-mb and "
                        "--overlap")
    p.add_argument("--moment-dtype", choices=["f32", "bf16", "int8"],
                   default=None,
                   help="optimizer-moment storage under --zero: bf16 or "
                        "blockwise int8 with stochastic rounding "
                        "(error-compensated, ops/quant.py) halve/quarter "
                        "the moment bytes; f32 = unchanged")
    p.add_argument("--fsdp", action="store_true",
                   help="fully-sharded data parallelism (ZeRO-3): params, "
                        "grads, and optimizer state all 1/N per device; "
                        "weights gathered one layer at a time inside the "
                        "step (scanned LM models, pure DP mesh)")
    p.add_argument("--bucket-mb", type=float, default=None,
                   help="explicit DDP-style gradient bucket size in MiB "
                        "(default: let XLA schedule the all-reduce)")
    p.add_argument("--overlap", action="store_true",
                   help="demonstrated comm/compute overlap (ref dpp.py:52): "
                        "chained reverse-order gradient buckets + TPU "
                        "async-collective/latency-hiding compiler options, "
                        "so each bucket's all-reduce hides under the "
                        "remaining backward (see OVERLAP.md)")
    p.add_argument("--grad-compress", choices=["bf16", "powersgd"],
                   default=None,
                   help="comm-hook gradient compression (torch DDP "
                        "ddp_comm_hooks analog). bf16: gradients cross "
                        "the wire in bfloat16, half the f32 bytes; "
                        "composes with --overlap/--bucket-mb/"
                        "--accum-steps/--grad-clip (clip sees "
                        "decompressed grads). powersgd: rank-r low-rank "
                        "factors with per-replica error feedback "
                        "(orders of magnitude fewer wire bytes, lossy; "
                        "DP/CP only)")
    p.add_argument("--powersgd-rank", type=int, default=4,
                   help="PowerSGD approximation rank (with "
                        "--grad-compress powersgd)")
    p.add_argument("--buffer-sync", choices=["mean", "broadcast"],
                   default="mean",
                   help="BatchNorm-style buffer consistency across replicas: "
                        "'mean' averages running stats (SyncBN-flavored), "
                        "'broadcast' adopts replica 0's (exact DDP "
                        "broadcast_buffers semantics)")
    p.add_argument("--compile-cache", default=None, metavar="DIR",
                   help="persistent compilation cache + AOT executable "
                        "store rooted at DIR (env: DDP_COMPILE_CACHE). "
                        "Spawned/respawned gang members inherit it, so a "
                        "supervised restart reloads the serialized train "
                        "step instead of recompiling")
    p.add_argument("--dispatch-depth", type=int, default=2,
                   help="bounded async dispatch: keep up to K steps in "
                        "flight; the host syncs only at metrics-window and "
                        "checkpoint/eval boundaries (the nan guard then "
                        "observes each step's flag with a lag of at most "
                        "K).  0 = fully synchronous per-step loop")
    p.add_argument("--remat", choices=["auto", "on", "off"], default="auto",
                   help="activation rematerialization for LM models: "
                        "'auto' keeps the model family's default "
                        "(gpt2/llama: on), on/off force it — the knob the "
                        "autotuner searches")
    p.add_argument("--autotune", choices=["search", "apply", "off"],
                   default="off",
                   help="attribution-driven autotuning (tuning/): 'search' "
                        "runs a cost-model-pruned, measured search before "
                        "training and applies + persists the winner; "
                        "'apply' loads a previously-persisted TunedConfig "
                        "for this topology/model fingerprint (falling back "
                        "LOUDLY to the CLI values on any mismatch) and "
                        "starts training with zero search trials")
    p.add_argument("--tune-dir", default=None, metavar="DIR",
                   help="TunedConfig store directory (default: "
                        "<--compile-cache>/tuned when a compile cache is "
                        "set, else .ddp_tune)")
    p.add_argument("--tune-trials", type=int, default=3,
                   help="measured candidates per search (top-K after "
                        "analytic pruning)")
    p.add_argument("--tune-steps", type=int, default=4,
                   help="measured steps per candidate window")
    p.add_argument("--log-every", type=int, default=100)     # ref dpp.py:54
    p.add_argument("--steps-per-epoch", type=int, default=None,
                   help="cap steps per epoch (smoke runs)")
    p.add_argument("--num-examples", type=int, default=2048,
                   help="synthetic dataset size")
    p.add_argument("--checkpoint-dir", default=None)
    p.add_argument("--resume", action="store_true")
    p.add_argument("--max-restarts", type=int, default=0,
                   help="supervise the worker and restart it up to N "
                        "times on any crash — preemption, watchdog "
                        "exit, injected chaos (torchrun --max-restarts "
                        "analog).  Requires --checkpoint-dir; each "
                        "restart resumes from the newest intact "
                        "checkpoint")
    p.add_argument("--elastic", action="store_true",
                   help="elastic gang runtime (runtime.elastic_gang): on "
                        "a member death (chaos worker-kill, peer failure "
                        "detector) the survivors agree on the next "
                        "membership epoch, rebuild the mesh one device "
                        "smaller, and reshard the LIVE train state in "
                        "memory — no checkpoint restore, no process "
                        "restart.  Data reshards deterministically "
                        "(every sample still seen exactly once per "
                        "pass); with --compile-cache the N±1 step "
                        "executables are pre-compiled in the background "
                        "so the resize lands on an AOT hit.  DP and "
                        "--zero 1 layouts over the data axis only")
    p.add_argument("--min-procs", type=int, default=1,
                   help="with --elastic: smallest gang worth resizing "
                        "down to — fewer survivors than this is a "
                        "failure (supervised restart territory), not a "
                        "smaller gang")
    p.add_argument("--elastic-dir", default=None, metavar="DIR",
                   help="rendezvous store root for --elastic (env: "
                        "DDP_ELASTIC_DIR); defaults to EVENTS_DIR/gang "
                        "or CHECKPOINT_DIR/.gang")
    p.add_argument("--step-timeout", type=float, default=None,
                   help="wall-clock deadline in seconds per train step "
                        "(armed after the first, compile-bearing step): "
                        "a wedged step logs a diagnostic, best-effort "
                        "checkpoints the last completed state, and "
                        "exits 75 instead of hanging — with "
                        "--max-restarts the supervisor then restarts")
    p.add_argument("--chaos", default=None, metavar="SPEC",
                   help="deterministic fault injection for testing the "
                        "recovery paths (utils.chaos; also via the "
                        "DDP_CHAOS env var): comma-separated "
                        "ckpt-io@N[:K] | nan-grad@S | slow-step@S[:SEC] "
                        "| preempt@S | worker-kill@S[:R] | "
                        "bitflip@S[:R][:leaf]")
    p.add_argument("--nan-guard", action="store_true",
                   help="skip-step numerical guard: a step whose "
                        "gradients contain NaN/Inf applies NO update "
                        "(params/opt state/hook state keep their "
                        "values) and is counted; --max-bad-steps "
                        "consecutive bad steps abort the run.  Adds "
                        "one host sync per step")
    p.add_argument("--max-bad-steps", type=int, default=5,
                   help="with --nan-guard: consecutive non-finite-grad "
                        "steps tolerated before the run aborts as "
                        "diverged")
    p.add_argument("--integrity-every", type=int, default=0, metavar="N",
                   help="silent-data-corruption defense "
                        "(training.integrity): every N steps the train "
                        "step digests its input state's bit patterns "
                        "per data rank and all_gathers the digests — "
                        "one extra sub-KB collective on cadence, zero "
                        "extra host syncs off cadence.  A mismatch "
                        "skips that step's update, names the corrupt "
                        "rank by majority vote (2-rank gangs fall back "
                        "to a shadow-replay tiebreak), and with "
                        "--elastic evicts it through the gang resize "
                        "path: no restart, no checkpoint read.  0 "
                        "disables.  Plain DP and --zero 1 only")
    p.add_argument("--integrity-shadow", action="store_true",
                   help="with --integrity-every: on cadence, re-run the "
                        "step on a copy of the same inputs and compare "
                        "result digests — catches TRANSIENT compute SDC "
                        "even at DP=1 (two runs of one deterministic "
                        "program must agree bitwise).  Roughly doubles "
                        "the cost of cadence steps; detections are "
                        "reported (sdc_detect, rank=-1) but nothing is "
                        "evicted")
    p.add_argument("--eval", action="store_true", help="run eval after each epoch")
    p.add_argument("--decode-quant", choices=["int8"], default=None,
                   help="serve --generate with int8-quantized matrices "
                        "(ops.quant): ~half the per-step HBM weight "
                        "bytes of bf16, <1%% per-channel quantization "
                        "error")
    p.add_argument("--generate", type=int, default=0,
                   help="after training, greedily generate N tokens from a "
                        "training prompt via the KV-cache decode path "
                        "(LM models with replicated params: plain DP/ZeRO)")
    p.add_argument("--profile-dir", default=None,
                   help="write a jax.profiler trace for epoch 0 here "
                        "(legacy whole-epoch capture; --profile-steps "
                        "supersedes it when both are given)")
    p.add_argument("--events-dir", default=None, metavar="DIR",
                   help="observability: write schema-versioned JSONL "
                        "events (spans, metrics snapshots, fault events) "
                        "to DIR, one file per worker (env: "
                        "DDP_EVENTS_DIR).  With --max-restarts the "
                        "supervisor also logs restart attempts and "
                        "merges everything into DIR/timeline.jsonl on "
                        "exit")
    p.add_argument("--metrics-every", type=int, default=100,
                   help="export a metrics-registry snapshot every N "
                        "steps into the event log (host-only work: no "
                        "device sync).  0 disables periodic export; "
                        "end-of-run export always happens")
    p.add_argument("--mfu", action="store_true",
                   help="report MFU/HFU per throughput window from the "
                        "analytic cost model (observability.cost_model): "
                        "model FLOPs/s over the chips' peak.  Computed at "
                        "window boundaries only — zero per-step cost.  "
                        "Supported for cnn/mlp and the LM models")
    p.add_argument("--memory-telemetry", action="store_true",
                   help="sample device/live-array memory at throughput-"
                        "window boundaries (observability.memory) and "
                        "record the train step's compiler memory budget "
                        "once after the first step (costs one extra AOT "
                        "compile of the step program)")
    p.add_argument("--alerts", nargs="?", const="", default=None,
                   metavar="SPEC",
                   help="observability: evaluate SLO alert rules at "
                        "throughput-window boundaries (zero extra host "
                        "syncs) and emit `alert` events + registry "
                        "counters.  Bare --alerts enables every rule at "
                        "defaults; SPEC overrides thresholds, e.g. "
                        "--alerts mfu_floor=0.3,step_spike=2.5 "
                        "(rules: step_spike, mfu_floor, goodput_floor, "
                        "restart_storm, sdc_storm, loader_starved, "
                        "mem_growth).  "
                        "Watch live with scripts/ddp_monitor.py")
    p.add_argument("--runs-dir", default=None, metavar="DIR",
                   help="longitudinal run store: append this run's "
                        "run_summary (MFU, step-time percentiles, memory "
                        "HWM, goodput, restarts, alerts) to "
                        "DIR/index.jsonl at run end (env: DDP_RUNS_DIR); "
                        "gate later runs with scripts/perf_gate.py")
    p.add_argument("--profile-steps", default=None, metavar="A:B",
                   help="capture a jax.profiler trace covering global "
                        "steps [A, B) — a windowed alternative to "
                        "--profile-dir's whole-epoch trace.  Traces go "
                        "to --profile-dir if set, else "
                        "EVENTS_DIR/xprof.  Also arms capture-on-"
                        "anomaly: the first nan-guard trip or watchdog "
                        "fire grabs a short trace")
    p.add_argument("--bw-probe", action="store_true",
                   help="measure grad all-reduce bandwidth utilization "
                        "over the data axis before training")
    p.add_argument("--lint-step", action="store_true",
                   help="graph-lint the selected train step "
                        "(analysis.graph_lint) on the first batch and "
                        "abort on violations — trace-only, so it fails "
                        "fast BEFORE the first XLA compile")
    p.add_argument("--coordinator", default=None,
                   help="host:port for multi-process rendezvous")
    p.add_argument("--num-processes", type=int, default=None)
    p.add_argument("--process-id", type=int, default=None)
    args = p.parse_args(argv)
    # Resolve the dataset default here so direct train(parse_args([...]))
    # callers (tests, notebooks) get the same behavior as main().
    if args.dataset is None:
        args.dataset = "synthetic-lm" if is_lm(args) else "synthetic"
    # Env fallback so supervised respawns (fresh interpreters launched
    # with the original argv) and library callers pick up a cache the
    # parent enabled without threading the flag everywhere.
    if args.compile_cache is None:
        args.compile_cache = os.environ.get("DDP_COMPILE_CACHE") or None
    if args.events_dir is None:
        args.events_dir = os.environ.get("DDP_EVENTS_DIR") or None
    if args.runs_dir is None:
        args.runs_dir = os.environ.get("DDP_RUNS_DIR") or None
    if args.alerts is None and os.environ.get("DDP_ALERTS") is not None:
        args.alerts = os.environ.get("DDP_ALERTS")
    if args.elastic_dir is None:
        args.elastic_dir = os.environ.get("DDP_ELASTIC_DIR") or None
    if args.elastic and os.environ.get("DDP_ELASTIC_WORLD"):
        # A resize-respawn from the elastic supervisor: the gang comes
        # back at the surviving size, not the argv's original one.
        args.fake_devices = int(os.environ["DDP_ELASTIC_WORLD"])
    if args.alerts is not None:
        from distributeddataparallel_tpu.observability.alerts import (
            parse_alert_spec,
        )

        try:
            parse_alert_spec(args.alerts)
        except ValueError as e:
            raise SystemExit(f"--alerts: {e}") from None
    if args.dispatch_depth < 0:
        raise SystemExit(
            f"--dispatch-depth must be >= 0, got {args.dispatch_depth}"
        )
    if args.mfu and args.model in ("resnet18", "resnet50"):
        raise SystemExit(
            "--mfu: no analytic cost model for resnet yet (supported: "
            "cnn, mlp, gpt2, llama) — a wrong FLOP count would report a "
            "confidently wrong MFU"
        )
    if args.profile_steps is not None:
        from distributeddataparallel_tpu.observability import (
            parse_profile_steps,
        )

        try:
            parse_profile_steps(args.profile_steps)
        except ValueError as e:
            raise SystemExit(str(e)) from None
    return args


def select_device(args) -> None:
    """Select the backend (the north-star --device flag) before first use.

    Uses ``jax.config`` rather than env vars so it also works where the
    interpreter pre-imports jax (env-var platform selection is captured at
    import time).  Must run before any computation initializes a backend.
    """
    import jax

    if args.fake_devices:
        if args.device not in ("auto", "cpu"):
            raise SystemExit("--fake-devices requires --device cpu")
        from distributeddataparallel_tpu.compat import configure_cpu_devices

        configure_cpu_devices(args.fake_devices)
    elif args.device == "cpu":
        jax.config.update("jax_platforms", "cpu")
    elif args.device in ("tpu", "cuda"):
        # Prefer the named platform, fall back to whatever the env's TPU
        # plugin registered under (e.g. 'axon' here), then cpu.
        plats = os.environ.get("JAX_PLATFORMS", args.device)
        jax.config.update("jax_platforms", plats)
    # auto: leave the environment's selection in place.


def setup(args):
    """init_process_group + mesh (analog of ref dpp.py:20-21)."""
    import distributeddataparallel_tpu as ddp

    ddp.init_process_group(
        None if args.device == "auto" else args.device,
        coordinator_address=args.coordinator,
        num_processes=args.num_processes,
        process_id=args.process_id,
    )
    # One general mesh builder: whatever parallelism axes are requested,
    # in canonical order (data outermost, then seq/pipe/expert/model) —
    # unsupported combinations were already rejected by validate_args.
    n = ddp.global_device_count()
    axes, sizes = ["data"], []
    for degree, name in (
        (args.cp, "seq"),
        (args.pp, "pipe"),
        (args.ep, "expert"),
        (args.tp, "model"),
    ):
        if degree > 1:
            axes.append(name)
            sizes.append(degree)
    denom = 1
    for d in sizes:
        denom *= d
    if n % denom:
        raise SystemExit(
            f"requested parallelism ({' x '.join(f'{a}={d}' for a, d in zip(axes[1:], sizes))}) "
            f"does not divide {n} devices"
        )
    return ddp.make_mesh(tuple(axes), shape=(n // denom, *sizes))


def is_lm(args) -> bool:
    return args.model in ("gpt2", "llama")


def validate_args(args) -> None:
    lm_ds = args.dataset == "synthetic-lm" or str(args.dataset).startswith(
        "tokens:"
    )
    if is_lm(args) and not lm_ds:
        raise SystemExit(
            f"--model {args.model} is a language model; it trains on "
            f"--dataset synthetic-lm or tokens:FILE (got {args.dataset!r})"
        )
    if not is_lm(args) and lm_ds:
        raise SystemExit(
            f"--dataset {args.dataset} requires an LM model "
            f"(--model gpt2|llama), got --model {args.model}"
        )
    if args.cp > 1:
        if not is_lm(args):
            raise SystemExit("--cp requires an LM model (--model gpt2|llama)")
        if args.seq_len % args.cp:
            raise SystemExit("--seq-len must be divisible by --cp")
    if args.tp > 1:
        if not is_lm(args):
            raise SystemExit("--tp requires an LM model (--model gpt2|llama)")
    if args.pp > 1:
        if not is_lm(args):
            raise SystemExit("--pp requires an LM model (--model gpt2|llama)")
        if args.eval and args.cp > 1:
            raise SystemExit("--pp --eval does not support --cp")
        if args.accum_steps > 1:
            raise SystemExit(
                "--pp's microbatch loop IS the accumulation; use "
                "--pp-microbatches instead of --accum-steps"
            )
        if args.bucket_mb:
            raise SystemExit("--pp does not support --bucket-mb")
        if args.layers and args.layers % (args.pp * args.pp_virtual):
            raise SystemExit(
                f"--layers {args.layers} must be divisible by --pp "
                f"{args.pp}"
                + (f" x --pp-virtual {args.pp_virtual}"
                   if args.pp_virtual > 1 else "")
            )
        if args.pp_schedule == "zb":
            M = args.pp_microbatches or args.pp
            if M < args.pp:
                raise SystemExit(
                    f"--pp-schedule zb needs --pp-microbatches >= --pp "
                    f"(got {M} < {args.pp}): with fewer microbatches than "
                    f"stages the steady state never forms and there is no "
                    f"W work to fill the bubble — use 1f1b"
                )
            if args.cp > 1:
                raise SystemExit(
                    "--pp-schedule zb does not compose with --cp yet; "
                    "use --pp-schedule 1f1b for context-parallel pipelines"
                )
            if args.moe_experts and args.moe_aux_weight:
                raise SystemExit(
                    "--pp-schedule zb does not support the MoE aux loss "
                    "(the B/W split has no aux cotangent path); set "
                    "--moe-aux-weight 0 or use --pp-schedule 1f1b"
                )
        if args.pp_virtual > 1:
            if args.pp_schedule not in ("1f1b", "zb"):
                raise SystemExit(
                    "--pp-virtual requires --pp-schedule 1f1b or zb"
                )
            if args.zero:
                # ZeRO's flat layouts flatten the PERMUTED local shards;
                # the elastic reshard's logical-geometry assumption would
                # silently break — reject until the flats are
                # interleave-aware.
                raise SystemExit("--pp-virtual does not compose with "
                                 "--zero yet")
            if args.eval or args.generate:
                # The GPipe eval path and the decode path assume the
                # contiguous logical layer layout.
                raise SystemExit("--pp-virtual does not support "
                                 "--eval/--generate")
    elif args.pp_virtual > 1:
        raise SystemExit("--pp-virtual requires --pp > 1")
    if args.fsdp:
        if not is_lm(args):
            raise SystemExit("--fsdp requires an LM model (--model gpt2|llama)")
        bad = [
            f for f, on in (
                ("--zero", args.zero),
                ("--pp", args.pp > 1), ("--cp", args.cp > 1),
                ("--ep", args.ep > 1), ("--moe-experts", bool(args.moe_experts)),
                ("--bucket-mb", bool(args.bucket_mb)),
            ) if on
        ]
        if bad:
            raise SystemExit(
                f"--fsdp composes with --tp only; drop {', '.join(bad)}"
            )
    if args.augment and is_lm(args):
        raise SystemExit("--augment is for image datasets only")
    if args.dropout:
        # ONE consistent gate (VERDICT r4 item 7) instead of per-module
        # ValueErrors: the layouts that re-drive the forward themselves
        # (FSDP's per-layer gathers, the pipeline tick loops) have no
        # dropout-rng plumbing; everything else trains with it.
        if not is_lm(args):
            raise SystemExit("--dropout applies to LM models "
                             "(--model gpt2|llama)")
        if not 0.0 < args.dropout < 1.0:
            raise SystemExit("--dropout must be in (0, 1)")
        if args.fsdp or args.pp > 1:
            raise SystemExit(
                "--dropout trains under DP/ZeRO/TP/EP/CP (scan + remat "
                "included); --fsdp and --pp do not support it"
            )
    if args.grad_clip is not None and args.grad_clip <= 0:
        raise SystemExit("--grad-clip must be > 0")
    if args.max_restarts:
        if args.max_restarts < 0:
            raise SystemExit("--max-restarts must be >= 0")
        if not args.checkpoint_dir:
            # A restart without a checkpoint replays the run from zero —
            # that is a retry loop, not fault tolerance.
            raise SystemExit("--max-restarts requires --checkpoint-dir "
                             "(restarts resume from the last checkpoint)")
    if args.step_timeout is not None and args.step_timeout <= 0:
        raise SystemExit("--step-timeout must be > 0 seconds")
    if args.min_procs < 1:
        raise SystemExit("--min-procs must be >= 1")
    if args.elastic:
        bad = [
            f for f, on in (
                ("--fsdp", args.fsdp), ("--pp", args.pp > 1),
                ("--tp", args.tp > 1), ("--ep", args.ep > 1),
                ("--cp", args.cp > 1),
            ) if on
        ]
        if bad:
            raise SystemExit(
                f"--elastic resizes over the data axis only; drop "
                f"{', '.join(bad)}"
            )
        if args.zero >= 2:
            raise SystemExit(
                "--elastic supports plain DP and --zero 1; the ZeRO-2/3 "
                "resident weight shards resize through supervised "
                "restart + elastic_restore instead"
            )
        if args.moment_dtype:
            raise SystemExit(
                "--elastic does not compose with --moment-dtype: the "
                "in-memory reshard has no dequant/requant path for "
                "low-bit moments"
            )
        if args.grad_compress:
            raise SystemExit(
                "--elastic does not compose with --grad-compress: the "
                "hook state layout is replica-count-dependent"
            )
        if not (args.elastic_dir or args.events_dir or args.checkpoint_dir):
            raise SystemExit(
                "--elastic needs a rendezvous root: --elastic-dir, or "
                "--events-dir/--checkpoint-dir to derive one"
            )
    if args.chaos:
        from distributeddataparallel_tpu.utils.chaos import parse_chaos_spec

        try:
            parse_chaos_spec(args.chaos)
        except ValueError as e:
            raise SystemExit(f"--chaos: {e}")
    if args.nan_guard:
        if args.fsdp or args.pp > 1:
            # Those step factories own their update loops; the guard is
            # wired through make_train_step only.
            raise SystemExit("--nan-guard supports the DP/ZeRO/TP/EP/CP "
                             "step; drop --fsdp/--pp")
        if args.max_bad_steps < 1:
            raise SystemExit("--max-bad-steps must be >= 1")
    if args.integrity_every:
        if args.integrity_every < 0:
            raise SystemExit("--integrity-every must be >= 0")
        # The digest compares state that must be bitwise-replicated over
        # the data axis — sharded/model-parallel layouts have no such
        # replicated domain (mirrors the make_train_step gate).
        bad = [
            f for f, on in (
                ("--fsdp", args.fsdp), ("--pp", args.pp > 1),
                ("--tp", args.tp > 1), ("--ep", args.ep > 1),
                ("--cp", args.cp > 1),
            ) if on
        ]
        if bad:
            raise SystemExit(
                f"--integrity-every compares replicated data-axis state; "
                f"drop {', '.join(bad)}"
            )
        if args.zero >= 2:
            raise SystemExit(
                "--integrity-every supports plain DP and --zero 1; "
                "ZeRO-2/3 shard the comparable state away"
            )
    elif args.integrity_shadow:
        raise SystemExit(
            "--integrity-shadow needs a cadence: set --integrity-every N"
        )
    if args.zero >= 2:
        # Levels 2/3 shard the update over the data axis only; the
        # model-axis compositions ride ZeRO-1's flat layouts.
        bad = [
            f for f, on in (
                ("--tp", args.tp > 1), ("--ep", args.ep > 1),
                ("--pp", args.pp > 1),
            ) if on
        ]
        if bad:
            raise SystemExit(
                f"--zero {args.zero} shards over the data axis only; "
                f"drop {', '.join(bad)} or use --zero 1"
            )
    if args.moment_dtype and not args.zero:
        raise SystemExit("--moment-dtype rides the ZeRO sharded update; "
                         "add --zero")
    if args.autotune != "off":
        # The tuner owns the generic DP/ZeRO knobs; layouts with their
        # own step factories (and llama/resnet scale) are out of its
        # search space.
        bad = [
            f for f, on in (
                ("--fsdp", args.fsdp), ("--pp", args.pp > 1),
                ("--tp", args.tp > 1), ("--ep", args.ep > 1),
                ("--cp", args.cp > 1), ("--elastic", args.elastic),
            ) if on
        ]
        if bad:
            raise SystemExit(
                f"--autotune searches the DP/ZeRO space only; drop "
                f"{', '.join(bad)}"
            )
        if args.model not in ("mlp", "cnn", "gpt2"):
            raise SystemExit(
                "--autotune supports --model mlp|cnn|gpt2 (the tuning "
                f"harness registry); got {args.model!r}"
            )
        if args.tune_trials < 1:
            raise SystemExit("--tune-trials must be >= 1")
        if args.tune_steps < 1:
            raise SystemExit("--tune-steps must be >= 1")
    if args.remat != "auto" and not is_lm(args):
        raise SystemExit("--remat applies to LM models (--model gpt2|llama)")
    if args.overlap:
        # ZeRO-1/FSDP/PP own their reductions (reduce_scatter /
        # per-layer gathers / stage collectives) — the chained-bucket
        # overlap path is the plain-DP all-reduce's.  ZeRO-2/3 already
        # reduce per bucket, so --overlap there only adds the
        # latency-hiding compiler options.
        bad = [
            f for f, on in (
                ("--zero", args.zero == 1), ("--fsdp", args.fsdp),
                ("--pp", args.pp > 1),
            ) if on
        ]
        if bad:
            raise SystemExit(
                f"--overlap applies to the DP all-reduce; drop {', '.join(bad)}"
            )
    if args.grad_compress and (args.zero or args.fsdp or args.pp > 1):
        # Those layouts own their reductions (reduce_scatter / per-layer
        # gathers / stage collectives); the comm hook is the plain-DP
        # all-reduce's.
        raise SystemExit(
            "--grad-compress applies to the DP all-reduce; drop "
            "--zero/--fsdp/--pp"
        )
    if args.decode_quant and not args.generate:
        raise SystemExit("--decode-quant only affects --generate; add "
                         "--generate N")
    if args.grad_compress == "powersgd":
        if args.tp > 1 or args.ep > 1:
            # The model-axis placement helpers shard (params, opt); the
            # hook-state layout under TP/EP is untested — reject rather
            # than misplace it.
            raise SystemExit(
                "--grad-compress powersgd supports DP/CP layouts; drop "
                "--tp/--ep"
            )
        if args.overlap:
            raise SystemExit(
                "--grad-compress powersgd replaces the bucketed "
                "all-reduce --overlap schedules; pick one mechanism"
            )
        if args.powersgd_rank < 1:
            raise SystemExit("--powersgd-rank must be >= 1")
    if args.generate:
        if not is_lm(args):
            raise SystemExit("--generate requires an LM model")
        if (args.tp > 1 and not args.fsdp) or args.pp > 1 or args.ep > 1:
            # Decode runs on replicated params.  FSDP (incl. FSDP x TP)
            # is exempt: its eval/generate path host-gathers the sharded
            # flats back to the full model layout first (fsdp_gather_params
            # -- the tested --fsdp --tp 2 --generate CLI path).
            raise SystemExit(
                "--generate needs replicated params (no --tp/--pp/--ep; "
                "--fsdp [--tp N] generates via the host gather)"
            )
    if args.moe_experts and not is_lm(args):
        raise SystemExit("--moe-experts requires an LM model")
    if args.moe_experts and not 1 <= args.moe_top_k <= args.moe_experts:
        raise SystemExit(
            f"--moe-top-k {args.moe_top_k} must be in [1, {args.moe_experts}]"
        )
    if args.moe_top_k != 1 and not args.moe_experts:
        raise SystemExit("--moe-top-k requires --moe-experts")
    if args.moe_capacity_factor and not args.moe_experts:
        raise SystemExit("--moe-capacity-factor requires --moe-experts")
    if args.moe_capacity_factor < 0:
        raise SystemExit("--moe-capacity-factor must be >= 0")
    if args.ep > 1:
        if not args.moe_experts:
            raise SystemExit("--ep requires --moe-experts")
        if args.moe_experts % args.ep:
            raise SystemExit(
                f"--moe-experts {args.moe_experts} must be divisible by "
                f"--ep {args.ep}"
            )
        if args.pp > 1 and args.tp > 1:
            raise SystemExit("--ep with BOTH --pp and --tp is untested")
        if args.cp > 1 and (args.pp > 1 or args.tp > 1):
            raise SystemExit(
                "--ep with --cp composes pairwise only (no extra --pp/--tp)"
            )


def elastic_store_dir(args) -> str:
    """The rendezvous root shared by trainer and supervisor (both derive
    it from the same argv, so a respawn finds the same store)."""
    if args.elastic_dir:
        return args.elastic_dir
    if args.events_dir:
        return os.path.join(args.events_dir, "gang")
    return os.path.join(args.checkpoint_dir, ".gang")


class _SwappableStream:
    """Iterator of ``(batch_idx, batch)`` whose underlying loader can be
    swapped mid-epoch: the elastic resize replaces the remainder of the
    epoch with a tail loader resharded for the new world, and the batch
    index keeps counting — the global step stays monotone across the
    swap."""

    def __init__(self, loader):
        self._it = iter(loader)
        self._idx = -1

    def __iter__(self):
        return self

    def __next__(self):
        self._idx += 1
        return self._idx, next(self._it)

    def swap(self, loader) -> None:
        close = getattr(self._it, "close", None)
        if close is not None:
            close()
        self._it = iter(loader)


def build_model(args, num_classes: int = 10, vocab_size: int | None = None):
    from distributeddataparallel_tpu import models

    if args.model == "mlp":
        return models.TinyMLP(num_classes=num_classes)
    if args.model == "cnn":
        return models.SimpleCNN(num_classes=num_classes)
    if args.model == "resnet18":
        from distributeddataparallel_tpu.models.resnet import ResNet18
        return ResNet18(num_classes=num_classes, stem="cifar")
    if args.model == "resnet50":
        from distributeddataparallel_tpu.models.resnet import ResNet50
        return ResNet50(num_classes=num_classes)
    if is_lm(args):
        from distributeddataparallel_tpu.models import transformer as tfm

        family = tfm.gpt2_124m if args.model == "gpt2" else tfm.llama3_8b
        overrides = dict(
            vocab_size=vocab_size or args.vocab_size,
            max_seq_len=args.seq_len,
        )
        if args.cp > 1:
            overrides["cp_axis"] = "seq"
            overrides["cp_impl"] = args.cp_impl
        if args.tp > 1:
            overrides["tp_axis"] = "model"
        if args.pp > 1 or args.fsdp:
            # GPipe/FSDP operate on the scanned layer stack's leading dim.
            overrides["scan_layers"] = True
        if args.dropout:
            overrides["dropout_rate"] = args.dropout
        if args.moe_experts:
            overrides["moe_experts"] = args.moe_experts
            overrides["moe_top_k"] = args.moe_top_k
            overrides["moe_capacity_factor"] = args.moe_capacity_factor
        if args.ep > 1:
            overrides["ep_axis"] = "expert"
        if args.layers:
            overrides["num_layers"] = args.layers
        if args.remat != "auto":
            overrides["remat"] = args.remat == "on"
        if args.d_model:
            # Scale heads with width (head_dim 16, even for RoPE) instead of
            # keeping the family's head count, which would give tiny or odd
            # head dims at small widths.
            if args.d_model % 16:
                raise SystemExit("--d-model must be a multiple of 16")
            heads = max(1, args.d_model // 16)
            overrides.update(
                d_model=args.d_model, d_ff=4 * args.d_model, num_heads=heads
            )
            if args.model == "llama":
                # Largest kv count <= heads/4 that divides heads (GQA
                # requires num_heads % num_kv_heads == 0) — and that the
                # TP degree divides (kv heads shard over the model axis).
                kv = max(
                    (
                        d for d in range(1, max(heads // 4, args.tp) + 1)
                        if heads % d == 0 and d % args.tp == 0
                    ),
                    default=None,
                )
                if kv is None:
                    raise SystemExit(
                        f"no GQA kv-head count divides heads={heads} and "
                        f"is divisible by --tp {args.tp}; pick a larger "
                        f"--d-model"
                    )
                overrides["num_kv_heads"] = kv
        cfg = family(**overrides)
        if args.overlap and cfg.scan_layers:
            # Scanned stacks hold every layer grad inside the backward
            # while-loop; overlap needs the reduction to fire in there
            # (sync_grad_in_backward) — the step then skips the "layers"
            # subtree (presynced, wired at make_train_step below).
            import dataclasses as _dc

            cfg = _dc.replace(cfg, grad_sync_axis="data",
                              grad_sync_compress=args.grad_compress)
        return tfm.TransformerLM(cfg)
    raise NotImplementedError(f"--model {args.model}")


def build_dataset(args, train=True):
    from distributeddataparallel_tpu import data

    if str(args.dataset).startswith("tokens:"):
        # Memmapped real-token corpus (data.tokens).  FILE trains; eval
        # reads FILE's sibling val split: DIR/val.npy when FILE is
        # DIR/train.npy, else STEM.val.npy next to STEM.npy.
        path = args.dataset.split(":", 1)[1]
        if not train:
            base = os.path.basename(path)
            if base in ("train.npy", "train"):
                path = os.path.join(os.path.dirname(path), "val.npy")
            else:
                path = (path[:-4] if path.endswith(".npy") else path) \
                    + ".val.npy"
            if not os.path.exists(path):
                raise SystemExit(
                    f"--eval with --dataset tokens: needs a val split at "
                    f"{path}"
                )
        return data.TokenFileDataset(
            path, seq_len=args.seq_len,
            stride=(args.token_stride if train else None),
        )
    if is_lm(args) or args.dataset == "synthetic-lm":
        return data.SyntheticLM(
            num_examples=args.num_examples, seq_len=args.seq_len,
            vocab_size=args.vocab_size,
            seed=args.seed if train else args.seed + 1,
        )
    if args.dataset == "synthetic":
        return data.SyntheticClassification(
            num_examples=args.num_examples, seed=args.seed if train else args.seed + 1
        )
    if str(args.dataset).startswith("shards:"):
        # Streaming memmapped shard directory (data.sharded): the
        # ImageNet-scale path — per-batch disk reads, never full-RAM.
        root = args.dataset.split(":", 1)[1]
        split = os.path.join(root, "train" if train else "val")
        if os.path.isdir(split):
            root = split
        elif not train:
            raise SystemExit(
                f"--eval with --dataset shards: needs {split} "
                "(no val split in the shard directory)"
            )
        # device_normalize: ship raw u8 to the chip (4x fewer host->device
        # bytes, no host float conversion); normalize fuses into the
        # compiled step (ops.normalize_u8_images).
        return data.ShardedImageDataset(root, device_normalize=True)
    from distributeddataparallel_tpu import native

    # u8 storage + fused native normalize-on-gather when the native lib
    # is available (identical numerics, less RAM, faster input path).
    return data.load_cifar10(
        args.data_root, train=train, keep_u8=native.available()
    )


def build_optimizer(args, total_steps: int):
    """Optimizer + LR schedule from flags.

    The reference hardcodes ``optim.SGD(lr=0.01)`` (ref dpp.py:41,
    SURVEY §2b optimizer row); ``--optimizer sgd`` with the default
    constant schedule reproduces that.  adam/adamw + warmup-cosine are
    the standard LM-config surface.  Schedule state is one scalar step
    count, so every composition (ZeRO flat chunks included) carries it
    unchanged.
    """
    import optax

    if args.lr_schedule == "constant" and not args.warmup_steps:
        lr = args.lr
    else:
        decay = max(total_steps - args.warmup_steps, 1)
        if args.lr_schedule == "cosine":
            sched = optax.cosine_decay_schedule(
                args.lr, decay,
                alpha=(args.min_lr / args.lr) if args.lr else 0.0,
            )
        elif args.lr_schedule == "linear":
            sched = optax.linear_schedule(args.lr, args.min_lr, decay)
        else:
            sched = optax.constant_schedule(args.lr)
        if args.warmup_steps:
            warm = optax.linear_schedule(0.0, args.lr, args.warmup_steps)
            sched = optax.join_schedules([warm, sched], [args.warmup_steps])
        lr = sched
    if args.optimizer == "sgd":
        return optax.sgd(lr, momentum=args.momentum or None)
    if args.optimizer == "adam":
        return optax.adam(lr)
    return optax.adamw(lr, weight_decay=args.weight_decay)


def _apply_trial_to_args(args, config: dict, *, n_chips: int = 0) -> None:
    """Overwrite the tunable knobs on ``args`` with a TunedConfig.

    Only the knobs the tuner owns are touched — everything else
    (model, dataset, steps, parallelism axes) keeps its CLI value, so
    an applied record can never change WHAT trains, only how fast.
    A persisted batch that would starve the dataset (global batch >
    examples, possible when a record tuned against one --num-examples
    is replayed against a smaller one) keeps the CLI batch/accum
    instead of training zero steps.
    """
    from distributeddataparallel_tpu.tuning import TrialConfig
    from distributeddataparallel_tpu.utils.logging import get_logger

    trial = TrialConfig.from_dict(config)
    cap = (args.num_examples // n_chips
           if n_chips and getattr(args, "num_examples", None) else None)
    if cap is not None and trial.batch_per_chip > cap:
        get_logger().warning(
            "tuned batch %d/chip needs %d examples but --num-examples "
            "is %d — keeping --batch-size %d (re-run --autotune search "
            "against this dataset)",
            trial.batch_per_chip, trial.batch_per_chip * n_chips,
            args.num_examples, args.batch_size,
        )
    else:
        args.batch_size = trial.batch_per_chip
        args.accum_steps = trial.accum_steps
    args.zero = trial.zero
    # dpp stores "no override" as None; the tuner's explicit "f32" is
    # the same thing (and would trip the --moment-dtype-needs---zero
    # gate at zero=0 if kept literal).
    args.moment_dtype = (
        None if trial.moment_dtype == "f32" else trial.moment_dtype
    )
    args.bucket_mb = trial.bucket_mb
    args.dispatch_depth = trial.dispatch_depth
    if is_lm(args):
        args.remat = "on" if trial.remat else "off"


def _tune_dir_for(args) -> str:
    if args.tune_dir:
        return args.tune_dir
    if args.compile_cache:
        return os.path.join(args.compile_cache, "tuned")
    return ".ddp_tune"


def _run_autotune(args, mesh, events=None) -> None:
    """``--autotune`` entry: mutate ``args`` in place before anything
    model-shaped is built.

    ``apply`` loads the persisted TunedConfig for this (topology, model,
    toolchain) fingerprint and replays it — zero search trials, loud
    fallback to the CLI defaults on any key mismatch.  ``search`` runs
    the full prune→measure pipeline on the live mesh first, persists
    the winner, then applies it; the next run can use ``apply``.
    """
    from distributeddataparallel_tpu.tuning import (
        TrialConfig,
        TuningStore,
        default_tuned_key,
        search_model,
    )
    from distributeddataparallel_tpu.utils.logging import get_logger

    log = get_logger()
    model = "gpt2-small" if args.model == "gpt2" else args.model
    n_chips = int(mesh.shape["data"])
    name = f"{model}@d{n_chips}"
    seq = args.seq_len if is_lm(args) else 128
    store = TuningStore(_tune_dir_for(args))
    key = default_tuned_key(model, mesh, seq=seq)

    if args.autotune == "apply":
        record = store.load(name, key)
        applied = record is not None
        if applied:
            _apply_trial_to_args(args, record["config"], n_chips=n_chips)
            log.info(
                "autotune apply: %r -> %s (score %s, tuned %s)",
                name, record["config"], record.get("score"),
                os.path.join(store.root, name),
            )
        else:
            log.warning(
                "autotune apply: no matching TunedConfig %r under %s — "
                "running with the CLI defaults (use --autotune search "
                "to create one)", name, store.root,
            )
        if events is not None:
            events.emit(
                "tune_result",
                mode="apply",
                winner=record["config"] if applied else None,
                applied=applied,
                score=record.get("score") if applied else None,
                store_path=store.root,
            )
        return

    exec_store = None
    if args.compile_cache:
        from distributeddataparallel_tpu.training.warm_start import (
            ExecutableStore,
        )

        exec_store = ExecutableStore(args.compile_cache)
    # Cap the space by what the dataset can feed: a winner whose global
    # batch exceeds --num-examples would train zero steps when applied.
    from distributeddataparallel_tpu.tuning import default_space_for

    space = default_space_for(model)
    if getattr(args, "num_examples", None):
        import dataclasses

        cap = max(1, args.num_examples // n_chips)
        fit = tuple(b for b in space.batch_per_chip if b <= cap)
        space = dataclasses.replace(
            space, batch_per_chip=fit or (min(cap, args.batch_size),)
        )
    # The CLI flags as given ARE the hand-picked baseline: it is always
    # measured and always eligible to win, so the reported gain_frac is
    # an honest "what did tuning buy over what I typed".
    baseline = TrialConfig(
        batch_per_chip=args.batch_size,
        accum_steps=args.accum_steps,
        remat=(args.remat == "on" if args.remat != "auto"
               else is_lm(args) and args.model == "gpt2"),
        zero=args.zero,
        moment_dtype=args.moment_dtype or "f32",
        bucket_mb=args.bucket_mb,
        dispatch_depth=args.dispatch_depth,
    )
    summary = search_model(
        model,
        mesh=mesh,
        seq=seq,
        space=space,
        top_k=args.tune_trials,
        measure_steps=args.tune_steps,
        seed=args.seed,
        baseline=baseline,
        tune_store=store,
        store_name=name,
        key=key,
        exec_store=exec_store,
        events=events,
    )
    winner = summary.get("winner")
    if winner is None:
        log.warning(
            "autotune search measured no viable trial — keeping the "
            "CLI defaults"
        )
        return
    _apply_trial_to_args(args, winner["config"], n_chips=n_chips)
    log.info(
        "autotune search: winner %s (gain %+.1f%% vs baseline), "
        "persisted to %s",
        winner["trial"],
        100.0 * (summary.get("gain_frac") or 0.0),
        summary.get("store_path"),
    )


def train(args) -> float:
    """Per-job trainer (analog of ref dpp.py:27-57). Returns final loss."""
    # Library/test callers reach train() without going through main();
    # run the flag-combination gate here too (idempotent) so unsupported
    # compositions fail with the SAME SystemExit messages either way —
    # not a per-module ValueError deep inside a step factory.
    validate_args(args)
    import jax
    import jax.numpy as jnp
    import optax

    import distributeddataparallel_tpu as ddp
    from distributeddataparallel_tpu.data import DataLoader
    from distributeddataparallel_tpu.ops import accuracy, cross_entropy_loss
    from distributeddataparallel_tpu.training.train_step import make_eval_step
    from distributeddataparallel_tpu.utils import (
        StepTimer,
        allreduce_bandwidth,
        log0,
        profile_trace,
        warn0,
        warn_all,
    )

    if args.compile_cache:
        # Before the first compile: the persistent cache makes every
        # later start of this process shape — including a supervised
        # respawn — a cache hit instead of a recompile.
        from distributeddataparallel_tpu.training.warm_start import (
            enable_compile_cache,
        )

        enable_compile_cache(args.compile_cache)

    mesh = setup(args)
    n_replicas = mesh.shape["data"]
    log0(
        "world: %d process(es), %d device(s), %d-way DP, global batch %d",
        ddp.get_world_size(), ddp.global_device_count(), n_replicas,
        args.batch_size * n_replicas,
    )

    # Observability (distributeddataparallel_tpu.observability): one
    # schema-versioned JSONL event log + metrics registry per process,
    # and an XLA-profiler orchestrator for windowed / on-anomaly capture.
    # Everything stays host-side — emitting an event or exporting a
    # snapshot never reads a device value, so none of it adds a sync.
    events = tracer = registry = prof = None
    if args.events_dir or args.profile_steps:
        from distributeddataparallel_tpu.observability import (
            EventLog,
            JsonlExporter,
            MetricsRegistry,
            ProfilerOrchestrator,
            TextExporter,
            Tracer,
            events_path,
            parse_profile_steps,
        )

        proc = jax.process_index()
        if args.events_dir:
            events = EventLog(events_path(args.events_dir, proc), proc)
            events.emit(
                "run_start",
                argv=sys.argv[1:],
                attempt=int(os.environ.get("DDP_RESTART_ATTEMPT", "0") or 0),
                devices=ddp.global_device_count(),
            )
            registry = MetricsRegistry()
            registry.add_exporter(JsonlExporter(events))
            if proc == 0:
                # Rank-0 plaintext /metrics-style snapshot, refreshed at
                # every export — the file a human or node scraper reads.
                registry.add_exporter(
                    TextExporter(os.path.join(args.events_dir, "metrics.txt"))
                )
            tracer = Tracer(events, registry)
        # Trace destination: --profile-dir when given, else a subdir of
        # the events dir.  The orchestrator is armed whenever it has
        # somewhere to write — --profile-steps drives the window, and
        # the first nan-guard trip or watchdog fire grabs a short
        # anomaly capture either way.
        prof_dir = args.profile_dir or (
            os.path.join(args.events_dir, "xprof") if args.events_dir
            else None
        )
        if prof_dir:
            prof = ProfilerOrchestrator(
                prof_dir,
                window=parse_profile_steps(args.profile_steps),
                events=events,
            )

    def _span(name, **attrs):
        if tracer is not None:
            return tracer.span(name, **attrs)
        import contextlib

        return contextlib.nullcontext()

    # Autotune BEFORE anything batch-shaped exists: apply replays a
    # persisted winner (zero trials), search measures on the live mesh
    # and persists one.  Either way the tuned knobs land on ``args`` and
    # the loader/model/step below are built from them.
    if args.autotune != "off":
        _run_autotune(args, mesh, events)

    cp = args.cp > 1
    if cp:
        from distributeddataparallel_tpu.data import shard_lm_batch

        # CP: host-side input/target shift + DP×CP placement, inside the
        # loader's prefetch pipeline.
        place_fn = lambda b: shard_lm_batch(b["tokens"], mesh)
    else:
        place_fn = None
    dataset = build_dataset(args, train=True)
    augment = None
    if args.augment:  # validated LM-free in validate_args
        from distributeddataparallel_tpu.data import CifarAugment
        augment = CifarAugment()  # fused native u8 path when available
    loader = DataLoader(
        dataset, per_replica_batch=args.batch_size, mesh=mesh,
        shuffle=True, seed=args.seed, place_fn=place_fn,
        workers=args.workers, augment=augment,
    )
    # Structured starvation events land in the same per-worker log as
    # everything else (events is None without --events-dir — the loader
    # then only warns).
    loader.events = events

    lm = is_lm(args)
    num_classes = getattr(dataset, "num_classes", None)
    if not lm and hasattr(dataset, "num_classes") and num_classes is None:
        raise SystemExit(
            "shard manifest lacks num_classes — rewrite the shards with "
            "write_image_shards(..., num_classes=...) so the classifier "
            "head can be sized"
        )
    model = build_model(
        args,
        num_classes=num_classes or 10,
        vocab_size=getattr(dataset, "vocab_size", None),
    )
    rng = jax.random.PRNGKey(args.seed)            # ref dpp.py:29 analog
    if lm:
        sample = jnp.zeros((1, args.seq_len), jnp.int32)
        init_model = model
        if cp:
            # Init outside shard_map with a non-CP twin config: ring
            # attention and cp_positions need the seq axis bound, but the
            # param structure is identical either way.
            import dataclasses

            from distributeddataparallel_tpu.models import TransformerLM

            init_model = TransformerLM(
                dataclasses.replace(model.cfg, cp_axis=None)
            )
        variables = init_model.init(rng, sample)
    else:
        shape = getattr(dataset, "image_shape", None) or dataset.images.shape[1:]
        sample = jnp.zeros((1,) + tuple(shape), jnp.float32)
        variables = model.init(rng, sample)
    if args.pretrained:
        # Fine-tune flow (ref dpp.py:14-15): replace the random init with
        # converted pretrained weights; every sharded placement below
        # (DP broadcast / ZeRO / TP / EP / PP / FSDP) then distributes
        # the pretrained tree exactly like a fresh one.
        from distributeddataparallel_tpu.models.io import load_pretrained

        variables = load_pretrained(args.pretrained, model, variables)
        log0("loaded pretrained weights from %s", args.pretrained)
    params = variables["params"]
    # Non-param collections (BatchNorm running stats for ResNets) become
    # framework-managed model state — the torch "buffers" DDP broadcasts.
    model_state = {k: v for k, v in variables.items() if k != "params"}
    has_ms = bool(model_state)

    spe = loader.steps_per_epoch                         # ref dpp.py:41
    if args.steps_per_epoch:
        spe = min(spe, args.steps_per_epoch)
    tx = build_optimizer(args, total_steps=max(spe * args.epochs, 1))
    if args.fsdp:
        # Fully-sharded: params/grads/opt state 1/N per device; the step
        # gathers one layer at a time (parallel/fsdp.py).
        state = ddp.fsdp_state(
            model.cfg, params, tx, mesh, apply_fn=model.apply,
            tp_axis="model" if args.tp > 1 else None,
        )
    elif args.zero:
        # With --tp/--ep/--pp, zero_state places params in the sharded
        # layout itself and shards the flat opt state over ALL the axes.
        if args.tp == 1 and args.ep == 1 and args.pp == 1:
            params = ddp.broadcast_params(params, mesh)
        model_state = ddp.broadcast_params(model_state, mesh)
        state = ddp.zero_state(
            apply_fn=model.apply, params=params, tx=tx, mesh=mesh,
            tp_axis="model" if args.tp > 1 else None,
            ep_axis="expert" if args.ep > 1 else None,
            pp_axis="pipe" if args.pp > 1 else None,
            model_state=model_state,
            level=args.zero,
            moment_dtype=args.moment_dtype,
            bucket_bytes=(
                int(args.bucket_mb * 1024 * 1024)
                if args.bucket_mb and args.zero >= 2 else None
            ),
        )
    elif args.pp > 1:
        state = ddp.TrainState.create(
            apply_fn=model.apply, params=params, tx=tx, model_state=model_state
        )
        # PP layout: the stacked layer dim sharded over the 'pipe' axis
        # (plus Megatron / expert trailing-dim sharding under --tp/--ep).
        state = ddp.shard_state_pp(
            state, mesh,
            tp_axis="model" if args.tp > 1 else None,
            ep_axis="expert" if args.ep > 1 else None,
            virtual=args.pp_virtual,
        )
    elif args.ep > 1:
        state = ddp.TrainState.create(
            apply_fn=model.apply, params=params, tx=tx, model_state=model_state
        )
        if args.tp > 1:
            # Combined EP x TP placement (disjoint leaf sets) — ONE spec
            # source shared with the train step's in_specs.
            from distributeddataparallel_tpu.parallel.expert_parallel import (
                shard_state_model_axes,
            )

            state = shard_state_model_axes(
                state, mesh, tp_axis="model", ep_axis="expert"
            )
        else:
            state = ddp.shard_state_ep(state, mesh)
    elif args.tp > 1:
        state = ddp.TrainState.create(
            apply_fn=model.apply, params=params, tx=tx, model_state=model_state
        )
        # TP layout: Megatron param sharding over the 'model' axis,
        # replicated over 'data' (the broadcast analog for a 2-D mesh).
        state = ddp.shard_state_tp(state, mesh)
    else:
        state = ddp.TrainState.create(
            apply_fn=model.apply, params=params, tx=tx, model_state=model_state
        )
        state = ddp.broadcast_params(state, mesh)   # DDP ctor broadcast analog
        if args.grad_compress == "powersgd":
            # Low-rank comm-hook state: warm Q replicated, per-replica
            # error residuals allocated DIRECTLY in their sharded layout
            # (leading data-axis dim) — no full-tree transient on one
            # device.
            from distributeddataparallel_tpu.parallel.powersgd import (
                powersgd_state,
            )

            state = state.replace(
                comm_state=powersgd_state(
                    state.params, int(mesh.shape["data"]),
                    args.powersgd_rank, seed=args.seed, mesh=mesh,
                )
            )

    # Streaming shard datasets ship raw u8 images; normalize in-graph
    # (ops.normalize_u8_images — XLA fuses it under the first conv).
    if getattr(dataset, "device_normalize", False):
        from distributeddataparallel_tpu.ops import normalize_u8_images

        _img = lambda batch: normalize_u8_images(batch["image"])
    else:
        _img = lambda batch: batch["image"]

    if lm:
        from distributeddataparallel_tpu.ops import lm_cross_entropy

        # CP batches arrive pre-split (the next-token shift crosses shard
        # boundaries, so the host does it — see shard_lm_batch); plain LM
        # batches carry raw tokens and shift here.
        if cp:
            extract = lambda batch: (batch["inputs"], batch["targets"])
        else:
            extract = lambda batch: (
                batch["tokens"][:, :-1], batch["tokens"][:, 1:]
            )

        def _train_apply_kwargs(rng):
            # Dropout: the step's rng is already folded per data (and
            # cp) position, so masks decorrelate across replicas while
            # tp/ep peers — which re-run identical replicated compute —
            # share one mask by construction.  The scan splits it again
            # per layer (scanned_layer_cls split_rngs) and remat replays
            # the same mask deterministically.
            if args.dropout:
                return {"deterministic": False, "rngs": {"dropout": rng}}
            return {}

        if args.moe_experts and args.moe_aux_weight > 0:
            from distributeddataparallel_tpu.models.transformer import (
                moe_aux_from_intermediates,
            )

            def loss_fn(params, batch, rng):
                inputs, targets = extract(batch)
                logits, col = model.apply(
                    {"params": params}, inputs, mutable=["intermediates"],
                    **_train_apply_kwargs(rng),
                )
                aux = moe_aux_from_intermediates(col)
                loss = (
                    lm_cross_entropy(logits, targets)
                    + args.moe_aux_weight * aux
                )
                return loss, {
                    "accuracy": accuracy(logits, targets),
                    "moe_aux": aux,
                }
        else:
            def loss_fn(params, batch, rng):
                inputs, targets = extract(batch)
                logits = model.apply(
                    {"params": params}, inputs, **_train_apply_kwargs(rng)
                )
                loss = lm_cross_entropy(logits, targets)
                return loss, {"accuracy": accuracy(logits, targets)}
    elif has_ms:
        def loss_fn(params, ms, batch, rng):
            logits, new_vars = model.apply(
                {"params": params, **ms}, _img(batch), train=True,
                mutable=list(ms.keys()),
            )
            loss = cross_entropy_loss(logits, batch["label"])  # ref dpp.py:40
            aux = {"accuracy": accuracy(logits, batch["label"])}
            return loss, (aux, new_vars)
    else:
        def loss_fn(params, batch, rng):
            logits = model.apply({"params": params}, _img(batch))
            loss = cross_entropy_loss(logits, batch["label"])  # ref dpp.py:40
            return loss, {"accuracy": accuracy(logits, batch["label"])}

    # Off-cadence twin for --integrity-every (built in the generic
    # branch below; the layouts the other branches build are rejected
    # by the integrity CLI gate above).
    step_fn_off = None
    if args.fsdp:
        # FSDP: the step factory takes the model CONFIG (it decomposes
        # the transformer into embed / layer scan / head around the
        # per-layer weight gathers).
        step_fn = ddp.make_fsdp_train_step(
            model.cfg, mesh=mesh, grad_clip=args.grad_clip,
            accum_steps=args.accum_steps,
            tp_axis="model" if args.tp > 1 else None,
            gather_dtype=jnp.bfloat16 if args.fsdp_gather == "bf16" else None,
        )
    elif args.pp > 1:
        # GPipe: the step factory takes the model CONFIG (it decomposes
        # the transformer into embed / stage stack / head itself); the
        # microbatch loop is the accumulation.
        M = args.pp_microbatches or args.pp
        if args.batch_size % M:
            raise SystemExit(
                f"--batch-size {args.batch_size} must be divisible by "
                f"--pp-microbatches {M}"
            )
        if model.cfg.num_layers % (args.pp * args.pp_virtual):
            raise SystemExit(
                f"model layer count {model.cfg.num_layers} must be "
                f"divisible by --pp {args.pp}"
                + (f" x --pp-virtual {args.pp_virtual}"
                   if args.pp_virtual > 1 else "")
            )
        step_fn = ddp.make_pp_train_step(
            model.cfg, mesh=mesh, microbatches=M, zero=args.zero,
            moe_aux_weight=args.moe_aux_weight if args.moe_experts else 0.0,
            schedule=args.pp_schedule, grad_clip=args.grad_clip,
            virtual=args.pp_virtual,
        )
    else:
        # One factory for the other compositions: DP × {accum, buckets,
        # ZeRO} × CP/TP.  Factored over the mesh so the elastic resize
        # can rebuild the identical step for the shrunken world.
        def build_step_fn(for_mesh, integrity=True):
            return ddp.make_train_step(
                loss_fn, mesh=for_mesh, accum_steps=args.accum_steps,
                bucket_bytes=int(args.bucket_mb * 1024 * 1024) if args.bucket_mb else None,
                overlap=args.overlap,
                with_model_state=has_ms, zero=args.zero,
                buffer_sync=args.buffer_sync,
                cp_axis="seq" if cp else None,
                tp_axis="model" if args.tp > 1 else None,
                ep_axis="expert" if args.ep > 1 else None,
                grad_clip=args.grad_clip,
                grad_compress=args.grad_compress,
                presynced=(
                    (lambda p: p[0] == "layers")
                    if getattr(getattr(model, "cfg", None),
                               "grad_sync_axis", None)
                    else None
                ),
                nonfinite_guard=args.nan_guard,
                integrity_every=(
                    (args.integrity_every or None) if integrity else None
                ),
            )

        step_fn = build_step_fn(mesh)
        if args.integrity_every:
            # Off-cadence twin: the digest-armed program carries an
            # in-graph cadence cond, and routing the state past that
            # conditional has a measurable per-step cost even on the
            # cond's zero branch.  The host loop already mirrors the
            # cadence gate (IntegrityChecker.due on a host counter — no
            # sync), so off-cadence steps dispatch this bit-identical
            # plain program instead and pay exactly nothing; the digest
            # program runs only on the 1-in-N cadence steps.
            step_fn_off = build_step_fn(mesh, integrity=False)

    # Graph lint wants the RAW factory step: the warm-start wrapper below
    # may swap in a deserialized AOT executable, which cannot be traced.
    lint_target = step_fn if args.lint_step else None
    # Same constraint for the GL002 fingerprint the run_summary carries
    # (perf_gate uses it to tell graph changes from environment drift).
    fp_target = step_fn

    warm_report = {}
    if args.compile_cache:
        # AOT executable store under the cache dir: load the serialized
        # train step on restart, compile-and-save otherwise.  The key
        # must cover everything the CLI can change about the compiled
        # program — including optimizer hyperparameters, which optax
        # bakes into the executable as constants (a stale-lr binary
        # would train silently wrong, which is exactly what the key
        # check turns into a loud JIT fallback).
        from distributeddataparallel_tpu.training.warm_start import (
            ExecutableStore,
            executable_key,
            warm_train_step,
        )

        warm_store = ExecutableStore(os.path.join(args.compile_cache, "aot"))

        def _exec_key(fn, for_mesh):
            return executable_key(
                mesh=for_mesh,
                model_config=getattr(model, "cfg", None),
                step_signature=getattr(fn, "aot_signature", None),
                extra={
                    "model": args.model,
                    "batch_size": args.batch_size,
                    "seq_len": args.seq_len if lm else None,
                    "optimizer": args.optimizer,
                    "lr": args.lr,
                    "momentum": args.momentum,
                    "weight_decay": args.weight_decay,
                    "lr_schedule": args.lr_schedule,
                    "warmup_steps": args.warmup_steps,
                    "min_lr": args.min_lr,
                    "fsdp": args.fsdp,
                    "pp": args.pp,
                    "pp_schedule": args.pp_schedule,
                    "pp_virtual": args.pp_virtual,
                },
            )

        def _wrap_warm(fn, for_mesh, name="train_step"):
            # Per-topology store names ("train_step@d7", ...): the
            # elastic resize re-wraps against the entry the background
            # pre-compiler saved for exactly that device count.
            return warm_train_step(
                fn,
                store=warm_store,
                key=_exec_key(fn, for_mesh),
                name=name,
                on_ready=lambda rep: warm_report.update(rep),
            )

        step_fn = _wrap_warm(step_fn, mesh)
        if step_fn_off is not None:
            # Distinct store entry: the twin's aot_signature differs
            # only in integrity_every=None.
            step_fn_off = _wrap_warm(
                step_fn_off, mesh, name="train_step_off"
            )

    def full_params():
        """The replicated param tree for eval/generate: under FSDP the
        sharded flats are gathered back to the model layout (reads the
        CURRENT state)."""
        if args.fsdp:
            # Host-side assembly: no device-memory spike from the gather
            # itself (a device-side replicated gather would OOM at the 8B
            # scale FSDP exists for).  Before committing back to device,
            # cast to the model's compute dtype on HOST — the bf16 copy
            # is what decode runs on and is half the f32 tree.  (f32
            # configs commit f32: those are the small/test models.)
            host = ddp.fsdp_gather_params(
                model.cfg, state, mesh,
                tp_axis="model" if args.tp > 1 else None, host=True,
            )
            if model.cfg.dtype == jnp.bfloat16:
                import ml_dtypes

                host = jax.tree.map(
                    lambda x: x.astype(ml_dtypes.bfloat16), host
                )
            return jax.tree.map(jnp.asarray, host)
        if args.zero >= 3:
            # ZeRO-3 stores params as a flat 1/N shard; reassemble the
            # model-layout tree (device-side: the zero3 scale ceiling is
            # the opt+param residency, and eval needs the full tree
            # resident anyway).
            from distributeddataparallel_tpu.parallel.zero import (
                zero3_gather_params,
            )

            return zero3_gather_params(state, mesh)
        return state.params

    # Fault-tolerance wiring (training.fault_tolerance / utils.chaos):
    # the injector is a no-op unless --chaos / DDP_CHAOS asks for faults;
    # the counters make any recovery visible in the end-of-run log.
    from distributeddataparallel_tpu.training.fault_tolerance import (
        NonFiniteBreaker,
        ResilientCheckpointer,
        StepWatchdog,
    )
    from distributeddataparallel_tpu.utils.chaos import (
        FaultInjector,
        SimulatedPreemption,
    )
    from distributeddataparallel_tpu.utils.metrics import FaultCounters

    counters = FaultCounters()
    # Set by the launcher's supervision loop: which incarnation this is.
    counters.restarts = int(os.environ.get("DDP_RESTART_ATTEMPT", "0") or 0)
    if registry is not None:
        # Every subsystem's telemetry registers here instead of owning a
        # private dict; values are pulled lazily at export time (pure
        # host reads — the loader gauge is a qsize() call).
        registry.bind("faults", counters.summary)
        registry.bind("loader_prefetch_depth", lambda: loader.prefetch_depth)
    if args.chaos:
        # Marker state under the checkpoint dir: each chaos entry fires
        # at most once ACROSS supervised restarts.
        injector = FaultInjector(
            args.chaos,
            state_dir=(
                os.path.join(args.checkpoint_dir, ".chaos")
                if args.checkpoint_dir else None
            ),
        )
    else:
        injector = FaultInjector.from_env()
    # Injections land in the event stream next to their effects
    # (nan_skip / ckpt_retry / restart_attempt) — the gang timeline's
    # cause-and-effect pairs.
    injector.events = events
    breaker = NonFiniteBreaker(args.max_bad_steps) if args.nan_guard else None

    # Elastic gang runtime: on this CPU-simulation topology one process
    # hosts every fake-device rank as a gang member (the per-"proc"
    # analog used repo-wide), so the coordinator registers them all and
    # the resize is an in-process mesh rebuild.  On real multi-host TPU
    # the same coordinator runs one-member-per-process.
    gang = None

    def _data_mesh(m):
        return ddp.make_mesh(("data",), devices=jax.devices()[:m])

    if args.elastic:
        from distributeddataparallel_tpu.runtime.elastic_gang import (
            ElasticGangCoordinator,
        )

        _hb_env = os.environ.get("DDP_HEARTBEAT_TIMEOUT")
        _sus_env = os.environ.get("DDP_SUSPECT_AFTER")
        gang = ElasticGangCoordinator(
            elastic_store_dir(args),
            world=[f"proc{i}" for i in range(n_replicas)],
            min_size=args.min_procs,
            events=events,
            heartbeat_timeout_s=float(_hb_env) if _hb_env else None,
            suspect_after_s=float(_sus_env) if _sus_env else None,
        )
        gang.start()
        # The chaos worker-kill/host-kill/proposer-kill entries tombstone
        # members through the coordinator (and worker-join resurrects
        # them); the next poll() on the survivors runs the resize.  The
        # coordinator consults the injector back for slow-heartbeat
        # suppression, and fault breadcrumbs land in the store root so
        # the supervisor's gang_verdict can name the triggering fault.
        injector.gang = gang
        gang.chaos = injector
        injector.hosts = {
            str(i): f"proc{i}" for i in range(n_replicas)
        }
        injector.store_root = elastic_store_dir(args)
        if injector.fault_log is None:
            injector.fault_log = os.path.join(
                elastic_store_dir(args), "faults.jsonl"
            )

    precompiler = None

    def _launch_precompiler(live_state, live_batch, live_rng):
        """Background AOT compiles of the N±1 train steps (the
        topology-portable key family): a later resize re-wraps the step
        under the per-topology store name and lands on the executable
        compiled here instead of paying a cold compile mid-resize."""
        from distributeddataparallel_tpu.runtime.elastic_gang import (
            batch_template_for,
            state_template_for,
        )
        from distributeddataparallel_tpu.training.warm_start import (
            BackgroundPrecompiler,
        )

        rng_t = jax.ShapeDtypeStruct(live_rng.shape, live_rng.dtype)
        n_now = mesh.shape["data"]
        jobs = []
        for m in (n_now - 1, n_now + 1):
            if m < max(args.min_procs, 1) or m > len(jax.devices()):
                continue
            tgt = _data_mesh(m)
            fn = build_step_fn(tgt)
            st = state_template_for(live_state, mesh, tgt, zero=args.zero)
            bt = batch_template_for(live_batch, mesh, tgt)
            jobs.append((
                f"train_step@d{m}",
                _exec_key(fn, tgt),
                lambda fn=fn, st=st, bt=bt: (fn, (st, bt, rng_t)),
            ))
        return BackgroundPrecompiler(warm_store, jobs).start()

    ckpt = None
    start_epoch = 0
    preempted = {"signal": None}
    if args.checkpoint_dir:
        from distributeddataparallel_tpu.training.elastic import (
            elastic_restore,
            topology_meta,
        )

        ckpt = ResilientCheckpointer(
            args.checkpoint_dir, injector=injector, counters=counters,
            events=events,
        )
        flat_tp = (
            "model"
            if ((args.fsdp or args.zero) and args.tp > 1)
            else None
        )
        flat_ep = "expert" if (args.zero and args.ep > 1) else None
        # The pipe degree is recorded for EVERY pp run (not just ZeRO
        # flats): interleaved-1F1B storage (--pp-virtual) bakes the
        # (pp, virtual) geometry into the layer ROW ORDER, and the
        # restore guard needs both recorded to reject a mismatch.
        flat_pp = "pipe" if args.pp > 1 else None
        ckpt_meta = topology_meta(
            mesh,
            "fsdp" if args.fsdp
            else f"zero{args.zero}" if args.zero
            else "replicated",
            tp_axis=flat_tp,
            ep_axis=flat_ep,
            pp_axis=flat_pp,
            pp_virtual=args.pp_virtual,
        )
        if args.resume:
            # Elastic resume: the flat ZeRO/FSDP layouts reshard when the
            # checkpoint was written at a different topology.  FSDP
            # reshards across the data AND Megatron TP degrees; ZeRO-1
            # reshards across data AND any of its model axes (tp/ep/pp —
            # incl. PP stage-count changes).  Replicated layouts (plain
            # DP, and TP/EP/PP param layouts without flat opt state)
            # carry N-independent global shapes, so orbax re-slices them
            # to the new mesh on its own.
            state, start_epoch = elastic_restore(
                ckpt, state, mesh,
                layout=ckpt_meta["layout"],
                cfg=model.cfg if args.fsdp else None,
                tp_axis=flat_tp,
                ep_axis=flat_ep,
                pp_axis=flat_pp,
                pp_virtual=args.pp_virtual,
            )
        # Preemption handling (TPU-VM maintenance events deliver SIGTERM):
        # finish the in-flight step, checkpoint, exit cleanly.  Epoch
        # granularity: --resume continues from the NEXT epoch — the
        # interrupted epoch's remaining batches are skipped (the loader's
        # position isn't part of the state; params stay monotone, no
        # batch is ever applied twice).  The reference has no failure
        # handling at all beyond fail-fast join (ref dpp.py:62; SURVEY §5).
        import signal

        def _on_term(signum, frame):
            preempted["signal"] = signum
            log0("signal %d: will checkpoint at the current epoch and exit",
                 signum)

        try:
            signal.signal(signal.SIGTERM, _on_term)
        except ValueError:
            pass  # non-main thread (library use): no handler, no harm

    # Multi-host agreement cadence: the host-level allgather below forces
    # a cross-process sync, so it runs every k batches, not every batch
    # (bounded k-step response to the signal, 1/k the sync cost).
    PREEMPT_CHECK_EVERY = 8

    def preempt_agreed(batch_idx: int) -> bool:
        """Do ALL processes agree to stop?  SIGTERM delivery can straddle
        a batch boundary across hosts; acting on the local flag alone
        would send processes into mismatched collectives (a hang, and no
        checkpoint).  Multi-host: agree via a host-level allgather on a
        fixed batch cadence — every process calls it at the same batch
        indices, so the collective order stays uniform; any one signaled
        process stops everyone."""
        if ddp.get_world_size() == 1:
            return preempted["signal"] is not None
        if batch_idx % PREEMPT_CHECK_EVERY:
            return False
        import numpy as np
        from jax.experimental import multihost_utils

        flags = multihost_utils.process_allgather(
            np.array([preempted["signal"] is not None], np.int32)
        )
        return bool(flags.sum() > 0)

    # Evaluation is exact over the padded tail: the loader emits a per-row
    # "valid" mask (0 on sampler-padded duplicate rows) and the masked eval
    # steps take per-row metrics, so padded rows contribute nothing.
    # Under --tp/--ep, eval runs directly on the sharded params (same
    # model, same per-layer psums) — no gathered replica is ever
    # materialized, and the specs come from the SAME source the train
    # step compiled with.
    eval_param_specs = None
    if args.tp > 1 or args.ep > 1:
        from distributeddataparallel_tpu.parallel.expert_parallel import (
            model_axes_param_specs,
        )

        eval_param_specs = model_axes_param_specs(
            state.params,
            tp_axis="model" if args.tp > 1 else None,
            ep_axis="expert" if args.ep > 1 else None,
        )
    eval_step = None
    if args.eval and args.pp > 1:
        # Pipelined forward-only eval: same microbatch ticks as training,
        # masked exactly over the sampler-padded tail.
        from distributeddataparallel_tpu.parallel import make_pp_eval_step

        eval_step = make_pp_eval_step(
            model.cfg, mesh=mesh,
            microbatches=args.pp_microbatches or args.pp,
        )
        eval_loader = DataLoader(
            build_dataset(args, train=False), per_replica_batch=args.batch_size,
            mesh=mesh, shuffle=False, seed=args.seed, drop_last=False,
            with_mask=True,
        )
    elif args.eval and args.fsdp:
        # Streaming masked eval over the sharded flats: per-layer gathers,
        # no full replicated tree, no 2x-params transient (ADVICE r2).
        eval_step = ddp.make_fsdp_eval_step(
            model.cfg, mesh=mesh,
            tp_axis="model" if args.tp > 1 else None,
            gather_dtype=jnp.bfloat16 if args.fsdp_gather == "bf16" else None,
        )
        eval_loader = DataLoader(
            build_dataset(args, train=False), per_replica_batch=args.batch_size,
            mesh=mesh, shuffle=False, seed=args.seed, drop_last=False,
            with_mask=True,
        )
    elif args.eval and cp:
        from distributeddataparallel_tpu.data import shard_lm_batch
        from distributeddataparallel_tpu.ops import (
            per_example_accuracy,
            per_example_cross_entropy,
        )
        from distributeddataparallel_tpu.parallel import make_cp_eval_step

        def metric_fn(params, batch):
            logits = model.apply({"params": params}, batch["inputs"])
            return {
                "loss": per_example_cross_entropy(logits, batch["targets"]),
                "accuracy": per_example_accuracy(logits, batch["targets"]),
            }
        eval_step = make_cp_eval_step(
            metric_fn, mesh=mesh, masked=True,
            param_specs=eval_param_specs,
        )
        eval_loader = DataLoader(
            build_dataset(args, train=False), per_replica_batch=args.batch_size,
            mesh=mesh, shuffle=False, seed=args.seed, drop_last=False,
            with_mask=True,
            place_fn=lambda b: shard_lm_batch(
                b["tokens"], mesh, valid=b["valid"]
            ),
        )
    elif args.eval:
        from distributeddataparallel_tpu.ops import (
            per_example_accuracy,
            per_example_cross_entropy,
        )

        if lm:
            def metric_fn(params, batch):
                toks = batch["tokens"]
                logits = model.apply({"params": params}, toks[:, :-1])
                return {
                    "loss": per_example_cross_entropy(logits, toks[:, 1:]),
                    "accuracy": per_example_accuracy(logits, toks[:, 1:]),
                }
        elif has_ms:
            def metric_fn(params, ms, batch):
                logits = model.apply(
                    {"params": params, **ms}, _img(batch), train=False
                )
                return {
                    "loss": per_example_cross_entropy(logits, batch["label"]),
                    "accuracy": per_example_accuracy(logits, batch["label"]),
                }
        else:
            def metric_fn(params, batch):
                logits = model.apply({"params": params}, _img(batch))
                return {
                    "loss": per_example_cross_entropy(logits, batch["label"]),
                    "accuracy": per_example_accuracy(logits, batch["label"]),
                }
        eval_step = make_eval_step(
            metric_fn, mesh=mesh, with_model_state=has_ms, masked=True,
            param_specs=eval_param_specs,
        )
        # drop_last=False: evaluation must cover the tail of the eval set
        # (sampler padding keeps per-replica counts equal, so the one
        # ragged final batch still shards evenly — worth the extra compile).
        eval_loader = DataLoader(
            build_dataset(args, train=False), per_replica_batch=args.batch_size,
            mesh=mesh, shuffle=False, seed=args.seed, drop_last=False,
            with_mask=True,
        )

    if len(loader) == 0:
        raise SystemExit(
            f"no training steps: dataset gives {loader.steps_per_epoch} "
            f"batches per replica (dataset too small for "
            f"--batch-size {args.batch_size} × {n_replicas} replicas)"
        )
    if args.bw_probe:
        probe = allreduce_bandwidth(mesh)
        log0(
            "all-reduce probe: %d dev, %.0f MB -> %.1f GB/s bus BW, "
            "%.1f%% of %s GB/s ICI peak",
            probe["devices"], probe["payload_mb"], probe["bus_bw_gb_s"],
            100 * probe["utilization"],
            f"{probe['peak_gb_s']:.0f}" if probe["peak_gb_s"] else "unknown",
        )

    # Throughput accounting: tokens/step for LMs, images/step otherwise
    # (the BASELINE tokens/s/chip and img/s/chip metrics).
    if lm:
        items_per_step, unit = args.batch_size * n_replicas * args.seq_len, "tok"
    else:
        items_per_step, unit = args.batch_size * n_replicas, "img"
    timer = StepTimer(window=max(20, args.log_every))

    # Performance attribution (observability.{cost_model,memory,goodput}):
    # MFU/HFU from the analytic FLOP model, memory sampling, and a
    # wall-clock goodput ledger.  Everything below is constructed once
    # here and consulted only at window boundaries / run edges — the hot
    # path never sees it.
    mfu_meter = mem_tel = goodput = None
    if events is not None:
        from distributeddataparallel_tpu.observability import GoodputLedger

        goodput = GoodputLedger()
    if args.mfu:
        from distributeddataparallel_tpu.observability import (
            MFUMeter,
            mlp_fwd_flops,
            peak_flops_for,
            simple_cnn_fwd_flops,
            train_step_flops,
            transformer_fwd_flops,
        )

        gbatch = args.batch_size * n_replicas
        remat = False
        if lm:
            # The LM step trains on the shifted sequence: seq_len-1
            # positions do forward/backward work.
            fwd = transformer_fwd_flops(
                model.cfg, batch=gbatch, seq_len=args.seq_len - 1
            )
            remat = bool(getattr(model.cfg, "remat", False))
        else:
            shape = tuple(
                getattr(dataset, "image_shape", None)
                or dataset.images.shape[1:]
            )
            if args.model == "cnn":
                fwd = simple_cnn_fwd_flops(
                    batch=gbatch, image_shape=shape,
                    num_classes=num_classes or 10,
                )
            else:  # mlp (resnet rejected in parse_args)
                in_features = 1
                for d in shape:
                    in_features *= int(d)
                fwd = mlp_fwd_flops(
                    batch=gbatch, in_features=in_features,
                    num_classes=num_classes or 10,
                )
        step_flops = train_step_flops(
            fwd, remat=remat,
            flop_signature=getattr(step_fn, "flop_signature", None),
        )
        peak = peak_flops_for(jax.devices()[0])
        mfu_meter = MFUMeter(
            step_flops,
            n_chips=ddp.global_device_count(),
            peak_flops_per_chip=peak,
            registry=registry,
            events=events,
        )
        log0(
            "mfu: %.3e model FLOPs/step (%.3e hw) over %d chip(s), "
            "peak %s FLOP/s/chip",
            step_flops["model_flops"], step_flops["hardware_flops"],
            ddp.global_device_count(),
            f"{peak:.2e}" if peak else "unknown",
        )
    if args.memory_telemetry:
        from distributeddataparallel_tpu.observability import MemoryTelemetry

        mem_tel = MemoryTelemetry(
            registry=registry, events=events, devices=jax.local_devices()
        )
    steps_total = (
        registry.counter("steps_total") if registry is not None else None
    )
    # Alerting + run summary: both consume ONLY numbers the window
    # boundary below already computed (same zero-extra-syncs discipline
    # as the meters above — bench.py pins it).
    alert_engine = None
    if args.alerts is not None:
        from distributeddataparallel_tpu.observability import (
            AlertEngine,
            parse_alert_spec,
        )

        alert_engine = AlertEngine(
            parse_alert_spec(args.alerts),
            events=events,
            registry=registry,
            on_fire=lambda a: warn0(
                "alert [%s] at step %s: value %s vs threshold %s",
                a["rule"], a["step"], a.get("value"), a.get("threshold"),
            ),
        )
    summary_builder = None
    if events is not None or args.runs_dir:
        from distributeddataparallel_tpu.observability import (
            RunSummaryBuilder,
        )

        summary_builder = RunSummaryBuilder()

    # Bounded async dispatch (training.warm_start.BoundedDispatch): the
    # loop no longer blocks the host every step — up to --dispatch-depth
    # steps stay in flight, and each step's guard handle (the nan flag
    # when --nan-guard is armed, else the loss) is settled when it falls
    # out of the window or at a boundary drain.  Numerically inert: the
    # devices execute the identical step sequence either way; only WHEN
    # the host reads the results changes.
    from distributeddataparallel_tpu.training.fault_tolerance import (
        note_warm_start,
    )
    from distributeddataparallel_tpu.training.warm_start import (
        BoundedDispatch,
    )

    dispatch = BoundedDispatch(args.dispatch_depth)

    def settle(handle, where) -> None:
        """Host-sync one in-flight step: read the nan flag into the
        breaker (which may raise TrainingDiverged — within depth steps
        of the threshold crossing), or just block on the handle."""
        if breaker is None:
            jax.block_until_ready(handle)
            return
        bad = float(handle)
        if bad:
            counters.nonfinite_steps += 1
            e, b = where
            if events is not None:
                events.emit("nan_skip", step=e * spe + b, epoch=e, batch=b)
            if prof is not None:
                # First anomaly grabs a short trace of the steps right
                # after the blow-up — while it is still happening.
                prof.trigger_anomaly("nan_grad", e * spe + b)
            warn0(
                "non-finite gradients at epoch %d batch %d:"
                " update skipped", e, b,
            )
        breaker.observe(bad)

    def drain() -> None:
        """Boundary sync: settle everything in flight.  Runs at metrics
        windows, log lines, checkpoint/eval edges, and epoch ends, so
        those points always observe fully-synced state and the nan
        guard's decision point is never crossed unobserved."""
        for h, w in dispatch.drain():
            settle(h, w)

    # Step watchdog: a wedged collective or infeed stall should produce a
    # diagnostic and a best-effort checkpoint, not a silent hang.  Armed
    # only after the first step completes so compile time never counts
    # against the deadline.
    watchdog = None
    if args.step_timeout:
        def _on_wedge(diag):
            counters.watchdog_fires += 1
            last = diag.get("last_known_state") or {}
            if events is not None:
                events.emit(
                    "watchdog_fire",
                    seconds_since_heartbeat=diag.get(
                        "seconds_since_heartbeat"
                    ),
                    last_known_state=last,
                )
                events.flush()  # the process is about to exit 75
            if prof is not None:
                # immediate=True: the loop is wedged — there may never
                # be another step to close a windowed capture on.
                prof.trigger_anomaly(
                    "watchdog",
                    int(last.get("epoch", 0)) * spe
                    + int(last.get("batch", 0)),
                    immediate=True,
                )
            if ckpt is None:
                return
            # Best-effort: saving may itself block on the wedged
            # computation, in which case the watchdog's grace timer
            # still terminates the process.
            try:
                last = diag.get("last_known_state") or {}
                ckpt.save(state, int(last.get("epoch", start_epoch)),
                          meta=ckpt_meta)
            # ddplint: allow[broad-except] — the process is exiting
            except Exception:  # noqa: BLE001 — the process is exiting
                warn_all("watchdog: emergency checkpoint failed")
        watchdog = StepWatchdog(args.step_timeout, on_timeout=_on_wedge)

    # Global step index for the chaos schedule: stable across restarts
    # because it is (epoch, batch)-derived, not a live counter.
    spe = len(loader)
    if args.steps_per_epoch:
        spe = min(spe, args.steps_per_epoch)

    last_loss = float("nan")
    warm_logged = False

    # Silent-data-corruption defense (training.integrity): the compiled
    # step already carries the cadence-gated digest (integrity_every was
    # passed to the factory); this host side mirrors the cadence gate —
    # ONE device sync pre-loop, then pure host arithmetic — votes on the
    # gathered digest matrix when a check lands, and evicts the corrupt
    # rank through the elastic gang.
    integrity = None
    integrity_shadow_fn = None
    integrity_step = 0
    sdc_source = None  # voted-healthy rank to re-replicate from on evict
    if args.integrity_every:
        from distributeddataparallel_tpu.training import (
            integrity as integrity_mod,
        )

        integrity = integrity_mod.IntegrityChecker(
            every=args.integrity_every,
            leaf_names=integrity_mod.digest_leaf_names(
                integrity_mod.digest_parts(state, args.zero)
            ),
            events=events, counters=counters,
        )

        def _integrity_rearm(for_step_fn, for_mesh, world):
            # The replay tiebreak only exists where the vote cannot
            # decide (exactly 2 ranks); shadow mode replaces it (the
            # double-execution check needs the pre-step copy for
            # itself).  Rebuilt on every topology change.
            nonlocal integrity_shadow_fn
            integrity.arbiter = (
                integrity_mod.ShadowArbiter(
                    for_step_fn,
                    integrity_mod.make_digest_fn(
                        for_mesh, zero_level=args.zero
                    ),
                )
                if world == 2 and not args.integrity_shadow else None
            )
            integrity_shadow_fn = (
                integrity_mod.make_digest_fn(for_mesh, zero_level=args.zero)
                if args.integrity_shadow else None
            )

        _integrity_rearm(step_fn, mesh, n_replicas)
        integrity_step = int(jax.device_get(state.step))

    # Per-step RNG is a pure function of (seed, epoch, batch): a --resume'd
    # run continues the exact stochastic stream (dropout etc.) the
    # uninterrupted run would have used, instead of replaying epoch-0 keys.
    base_rng = jax.random.PRNGKey(args.seed + 1)
    try:
        for epoch in range(start_epoch, args.epochs):    # ref dpp.py:44
            epoch_rng = jax.random.fold_in(base_rng, epoch)
            # Legacy whole-epoch trace only when the windowed capture
            # isn't driving the (global, single-slot) profiler.
            with _span("epoch", epoch=epoch), profile_trace(
                args.profile_dir
                if epoch == start_epoch and not args.profile_steps
                else None,
                sync=lambda: state.params,  # resolves to latest state at exit
            ):
                loader.set_epoch(epoch)                  # ref dpp.py:46
                stream = _SwappableStream(loader)
                for batch_idx, batch in stream:          # ref dpp.py:47
                    if args.steps_per_epoch \
                            and batch_idx >= args.steps_per_epoch:
                        break
                    gstep = epoch * spe + batch_idx
                    if prof is not None:
                        prof.on_step_start(gstep)
                    injector.before_step(gstep)   # slow-step / preempt
                    batch = injector.corrupt_batch(batch, gstep)
                    # Silent HBM corruption: XOR one bit of one param
                    # leaf on one rank (chaos bitflip; a no-op without a
                    # matching entry).
                    state = injector.corrupt_state(state, gstep, mesh=mesh)
                    sub = jax.random.fold_in(epoch_rng, batch_idx)
                    sdc_pend = None
                    if (
                        integrity is not None
                        and integrity.due(integrity_step)
                        and (integrity.arbiter is not None
                             or integrity_shadow_fn is not None)
                    ):
                        # The replay tiebreak / shadow re-execution needs
                        # this step's input state, and the step donates
                        # it — copy before dispatch, only on cadence.
                        sdc_pend = integrity_mod.copy_tree(state)
                    if lint_target is not None:
                        # First batch: everything the step consumes is
                        # now concrete, and nothing is compiled yet —
                        # trace-only lint fails fast before the compile.
                        from distributeddataparallel_tpu.analysis import (
                            graph_lint,
                            schedule_lint,
                            shard_flow,
                        )
                        from distributeddataparallel_tpu.observability.memory import (
                            hbm_budget_bytes,
                        )

                        rep = graph_lint.lint_train_step(
                            lint_target, state, batch, sub
                        )
                        if summary_builder is not None:
                            summary_builder.sample(
                                collective_fp=rep.fingerprint
                            )
                        fp_target = None
                        flow = shard_flow.analyze_step(
                            lint_target, state, batch, sub,
                            mode=rep.mode,
                            hbm_budget_bytes=hbm_budget_bytes(),
                        )
                        all_findings = rep.findings + flow.findings
                        ir = getattr(lint_target, "schedule_ir", None)
                        if ir is None and getattr(
                            lint_target, "comm_schedule", None
                        ) is not None:
                            ir = lint_target.comm_schedule(state.params)
                        if ir is not None:
                            hops = sum(
                                c.effective_count
                                for c in (rep.collectives or [])
                                if c.prim == ir.hop_prim
                                and ir.hop_axis in c.axes and c.nonscalar
                            )
                            all_findings += schedule_lint.lint_schedule(
                                ir,
                                manifest=getattr(
                                    lint_target, "collective_manifest",
                                    None,
                                ),
                                traced_hops=hops,
                                bubble=getattr(
                                    lint_target, "bubble_accounting",
                                    None,
                                ),
                                where=f"sched:{rep.mode}:{ir.kind}",
                            )
                        lint_target = None
                        if all_findings:
                            raise SystemExit(
                                "--lint-step: train step violates its "
                                "SPMD invariants:\n" + "\n".join(
                                    str(f) for f in all_findings
                                )
                            )
                        log0(
                            "lint-step [%s] clean: collective fp=%s %s "
                            "flow-collectives=%d%s",
                            rep.mode, rep.fingerprint,
                            rep.collective_counts,
                            len(flow.collectives),
                            f" schedule={ir.kind}" if ir is not None
                            else "",
                        )
                    if fp_target is not None:
                        # One trace on the first batch to stamp the
                        # run_summary with the GL002 collective
                        # fingerprint (skipped if --lint-step already
                        # computed it above).
                        if summary_builder is not None:
                            from distributeddataparallel_tpu.analysis import (
                                graph_lint,
                            )

                            try:
                                summary_builder.sample(
                                    collective_fp=graph_lint.collective_fingerprint(
                                        graph_lint.collect_collectives(
                                            jax.make_jaxpr(fp_target)(
                                                state, batch, sub
                                            )
                                        )
                                    )
                                )
                            # ddplint: allow[broad-except] — fingerprint is
                            # telemetry; an untraceable step must not kill
                            # the run
                            except Exception:  # noqa: BLE001
                                pass
                        fp_target = None
                    # The step span times host-side dispatch (plus any
                    # window-overflow settles) — the honest per-step
                    # number for an async loop; device wall time lands
                    # in the readings at drain boundaries.
                    with _span("step", step=gstep):
                        # Off cadence the plain twin runs — bit-identical
                        # update, no digest machinery in the program at
                        # all (the host counter mirrors the in-graph
                        # cadence gate, so the two never disagree).
                        use_fn = (
                            step_fn_off
                            if step_fn_off is not None
                            and integrity is not None
                            and not integrity.due(integrity_step)
                            else step_fn
                        )
                        state, metrics = use_fn(state, batch, sub)
                        # Bounded async dispatch: enqueue this step's
                        # guard handle and settle only what falls out of
                        # the K-deep window (the old pattern blocked
                        # here every step when the nan guard was armed).
                        guard = (
                            metrics["nonfinite_grad"]
                            if breaker is not None
                            else metrics["loss"]
                        )
                        for h, w in dispatch.push(guard, (epoch, batch_idx)):
                            settle(h, w)
                    if integrity is not None:
                        on_cadence = integrity.due(integrity_step)
                        integrity_step += 1
                        if on_cadence:
                            import numpy as np

                            # The ONLY integrity host sync, and only on
                            # cadence: fetch the (n_ranks, n_leaves)
                            # digest matrix the step just gathered.
                            mat = np.asarray(
                                jax.device_get(metrics["sdc_digest"])
                            )
                            verdict = integrity.check(mat, step=gstep)
                            if verdict.ok:
                                if integrity.arbiter is not None:
                                    integrity.arbiter.commit(sdc_pend)
                                if (
                                    integrity_shadow_fn is not None
                                    and sdc_pend is not None
                                ):
                                    # Transient-SDC probe: same program,
                                    # same inputs, second execution —
                                    # any digest disagreement is compute
                                    # corruption, catchable even at DP=1.
                                    shadow_state, _ = step_fn(
                                        sdc_pend, batch, sub
                                    )
                                    live_d = np.asarray(jax.device_get(
                                        integrity_shadow_fn(state)
                                    ))
                                    shad_d = np.asarray(jax.device_get(
                                        integrity_shadow_fn(shadow_state)
                                    ))
                                    if not (live_d == shad_d).all():
                                        integrity.note_shadow_mismatch(
                                            step=gstep
                                        )
                            elif verdict.corrupt and gang is not None:
                                # Closed loop: tombstone the corrupt
                                # rank(s); this iteration's gang.poll()
                                # below lands the resize, resharding the
                                # survivors' verified live state from a
                                # voted-healthy source rank.  The step
                                # that detected the mismatch already
                                # discarded its own update, so nothing
                                # the liar sent ever reached the
                                # surviving params.  No restart budget,
                                # no checkpoint read.
                                sdc_source = next(
                                    r for r in range(n_replicas)
                                    if r not in verdict.corrupt
                                )
                                for bad in verdict.corrupt:
                                    gang.kill(str(bad))
                                    integrity.note_eviction(bad, step=gstep)
                                log0(
                                    "integrity: digest mismatch at step "
                                    "%d — rank(s) %s corrupt (%s, leaves "
                                    "%s); evicting via elastic resize",
                                    gstep, list(verdict.corrupt),
                                    verdict.method, list(verdict.leaves),
                                )
                            else:
                                # Detection without an eviction path (no
                                # --elastic, or an unresolved tie): the
                                # update was discarded in-program, so
                                # state is still clean — stop loudly
                                # rather than train on with known-bad
                                # hardware.
                                raise SystemExit(
                                    f"integrity: replica digest mismatch "
                                    f"at step {gstep} "
                                    f"(corrupt={list(verdict.corrupt)}, "
                                    f"tie={verdict.tie}) and no eviction "
                                    f"path — rerun with --elastic, or "
                                    f"restore from a verified checkpoint"
                                )
                        if integrity.arbiter is not None:
                            integrity.arbiter.hold(batch, sub)
                    if steps_total is not None:
                        steps_total.inc()  # host int increment, no sync
                    if prof is not None:
                        prof.on_step_end(gstep)
                    if watchdog is not None:
                        if watchdog.running:
                            watchdog.beat(epoch=epoch, batch=batch_idx)
                        else:
                            jax.block_until_ready(state.step)
                            watchdog.start(epoch=epoch, batch=batch_idx)
                    reading = timer.tick(items_per_step, sync=state.step)
                    if timer.compile_s is not None and not warm_logged:
                        # First step done: record how it was acquired
                        # (aot / cache-hit / cold / jit) + time-to-ready,
                        # per incarnation — the restart path's warm-start
                        # regression signal.
                        warm_logged = True
                        note_warm_start(
                            counters,
                            mode=warm_report.get("mode", "jit"),
                            first_step_s=timer.compile_s,
                            events=events,
                        )
                        if goodput is not None:
                            goodput.add("compile", timer.compile_s)
                        if (
                            gang is not None
                            and args.compile_cache
                            and precompiler is None
                        ):
                            # First step done (live avals now known):
                            # queue the N±1 pre-compiles off-thread.
                            precompiler = _launch_precompiler(
                                state, batch, sub
                            )
                        if events is not None and "pp_phase_counts" in metrics:
                            # Measured-schedule counters: the compiled
                            # scan counted useful (valid) slots per
                            # stage per phase; emit them once with the
                            # factory's analytic accounting so the
                            # report can reconstruct the measured
                            # bubble post hoc.
                            from distributeddataparallel_tpu.observability.pipeline import (
                                phase_counts_payload,
                            )
                            events.emit("pp_phase", **phase_counts_payload(
                                jax.device_get(metrics["pp_phase_counts"]),
                                schedule=args.pp_schedule,
                                n_stages=args.pp,
                                virtual=args.pp_virtual,
                                microbatches=args.pp_microbatches or args.pp,
                                accounting=getattr(
                                    step_fn, "bubble_accounting", None
                                ),
                                step=gstep,
                            ))
                        if mem_tel is not None:
                            # One-time compiler memory budget for the
                            # step program.  lower().compile() is a
                            # SECOND compile (the jit cache does not
                            # serve AOT lowering), so it runs here —
                            # after the first step was timed — and only
                            # under --memory-telemetry.
                            lower = getattr(step_fn, "lower", None)
                            if lower is not None:
                                t_aot = time.perf_counter()
                                try:
                                    mem_tel.note_executable(
                                        lower(state, batch, sub).compile(),
                                        label="train_step",
                                    )
                                # ddplint: allow[broad-except] — optional
                                # telemetry; backends without AOT memory
                                # analysis must degrade, not abort train
                                except Exception:  # noqa: BLE001
                                    warn0(
                                        "memory-telemetry: step memory "
                                        "analysis unavailable"
                                    )
                                if goodput is not None:
                                    goodput.add(
                                        "compile",
                                        time.perf_counter() - t_aot,
                                    )
                                timer.reset()  # don't bill the window
                    if reading:
                        drain()  # window boundary: fully-synced state
                        if registry is not None:
                            # StepTimer readings feed the registry; the
                            # values are already host floats.
                            g = registry.gauge
                            g("items_per_s").set(reading["items_per_s"])
                            g("items_per_s_per_chip").set(
                                reading["items_per_s_per_chip"]
                            )
                            g("steps_per_s").set(reading["steps_per_s"])
                        if mfu_meter is not None:
                            att = mfu_meter.on_reading(reading, step=gstep)
                            if att["mfu"] is not None:
                                log0(
                                    "mfu: %.2f%% (hfu %.2f%%, "
                                    "%.3e model FLOP/s)",
                                    100 * att["mfu"], 100 * att["hfu"],
                                    att["model_flops_per_s"],
                                )
                        mem_sample = None
                        if mem_tel is not None:
                            # Window boundary: drain() already ran, so
                            # this never introduces a sync of its own.
                            mem_sample = mem_tel.sample(gstep)
                        window_step_s = (
                            1.0 / reading["steps_per_s"]
                            if reading["steps_per_s"] else None
                        )
                        window_mfu = (
                            att["mfu"] if mfu_meter is not None else None
                        )
                        window_hwm = (
                            mem_sample.get("live_hwm_bytes")
                            if mem_sample else None
                        )
                        if summary_builder is not None:
                            summary_builder.sample(
                                step_s=window_step_s,
                                mfu=window_mfu,
                                live_hwm_bytes=window_hwm,
                                steps_total=gstep + 1,
                            )
                        if alert_engine is not None:
                            # Same boundary discipline as the meters
                            # above: every signal is a host float this
                            # block already computed — evaluating the
                            # rules can never force a device sync.
                            gsum = (
                                goodput.summary()
                                if goodput is not None else {}
                            )
                            alert_engine.observe(
                                step=gstep,
                                step_s=window_step_s,
                                mfu=window_mfu,
                                live_hwm_bytes=window_hwm,
                                goodput=gsum.get("goodput"),
                                elapsed_s=gsum.get("total_s"),
                                prefetch_depth=(
                                    loader.prefetch_depth
                                    if args.workers > 0 else None
                                ),
                                restarts=counters.restarts,
                                sdc_detects=counters.sdc_detects,
                                gang_suspects=(
                                    len(gang.suspects_now)
                                    if gang is not None else 0
                                ),
                            )
                        log0(
                            "throughput: %.0f %s/s (%.1f %s/s/chip)",
                            reading["items_per_s"], unit,
                            reading["items_per_s_per_chip"], unit,
                        )
                    if (
                        registry is not None
                        and args.metrics_every
                        and gstep % args.metrics_every == 0
                    ):
                        # Periodic snapshot into the event log: pure
                        # host reads (counters, gauges, the loader's
                        # qsize), so this cadence adds no device sync.
                        registry.export(step=gstep)
                    if batch_idx % args.log_every == 0:  # ref dpp.py:54-55
                        drain()
                        last_loss = float(metrics["loss"])
                        log0("Epoch %d, Batch %d, Loss: %.4f",
                             epoch, batch_idx, last_loss)
                    if ckpt is not None and preempt_agreed(batch_idx):
                        drain()  # checkpoint edge: fully-synced state
                        t_ck = time.perf_counter()
                        with _span("ckpt_save", epoch=epoch):
                            ckpt.save(state, epoch, meta=ckpt_meta)
                            ckpt.wait()
                        if goodput is not None:
                            goodput.add(
                                "checkpoint", time.perf_counter() - t_ck
                            )
                        log0("preempted: checkpoint saved mid-epoch %d; "
                             "--resume continues from epoch %d",
                             epoch, epoch + 1)
                        ddp.destroy_process_group()
                        return float(metrics["loss"])
                    if gang is not None:
                        decision = gang.poll()
                        if decision is not None:
                            # RESIZE, not restart: survivors agreed on
                            # membership epoch k+1 — rebuild the mesh one
                            # (or more) members smaller and keep going
                            # with the LIVE state.  Nothing below reads a
                            # checkpoint.
                            t_rs = time.perf_counter()
                            drain()  # nothing in flight crosses the swap
                            from distributeddataparallel_tpu.data.sharded import (  # noqa: E501
                                resize_index_plan,
                            )
                            from distributeddataparallel_tpu.runtime.elastic_gang import (  # noqa: E501
                                measure_downtime,
                                reshard_live_state,
                            )

                            old_world = n_replicas
                            new_world = decision.new_size
                            old_mesh, mesh = mesh, _data_mesh(new_world)
                            # Checkpoint-free shrink: host round-trip of
                            # the live arrays through the positional
                            # flat-reshard math (training.elastic).
                            # After an SDC eviction the replicated
                            # leaves re-replicate from the voted-healthy
                            # rank — device_get's default (device 0's
                            # buffer) would resurrect the corruption
                            # when rank 0 was the liar.
                            state = reshard_live_state(
                                state, old_mesh, mesh, zero=args.zero,
                                source=sdc_source,
                            )
                            sdc_source = None
                            # Exactly-once data: the unconsumed tail of
                            # this epoch's permutation, reshuffled under
                            # an epoch-keyed reseed and dealt to the new
                            # world.
                            plan = resize_index_plan(
                                len(dataset),
                                per_replica_batch=args.batch_size,
                                old_world=old_world,
                                new_world=new_world,
                                consumed_steps=batch_idx + 1,
                                seed=args.seed, epoch=epoch,
                                membership_epoch=decision.epoch,
                            )
                            tail = DataLoader(
                                dataset,
                                per_replica_batch=args.batch_size,
                                mesh=mesh, shuffle=True, seed=args.seed,
                                place_fn=place_fn, workers=args.workers,
                                augment=augment, index_shards=plan,
                            )
                            tail.events = events
                            stream.swap(tail)
                            step_fn = build_step_fn(mesh)
                            if step_fn_off is not None:
                                step_fn_off = build_step_fn(
                                    mesh, integrity=False
                                )
                            if args.compile_cache:
                                # The per-topology store name the
                                # background pre-compiler saved — a
                                # resize lands on an AOT load.
                                step_fn = _wrap_warm(
                                    step_fn, mesh,
                                    name=f"train_step@d{new_world}",
                                )
                                if step_fn_off is not None:
                                    step_fn_off = _wrap_warm(
                                        step_fn_off, mesh,
                                        name=f"train_step_off@d{new_world}",
                                    )
                            n_replicas = new_world
                            if integrity is not None:
                                # New mesh, new step: rebuild the shadow
                                # digest fn and (de)arm the 2-rank
                                # replay tiebreak for the new world.
                                _integrity_rearm(step_fn, mesh, new_world)
                            items_per_step = (
                                args.batch_size * n_replicas * args.seq_len
                                if lm
                                else args.batch_size * n_replicas
                            )
                            if ckpt is not None:
                                ckpt_meta = topology_meta(
                                    mesh,
                                    f"zero{args.zero}" if args.zero
                                    else "replicated",
                                )
                            if eval_step is not None:
                                eval_step = make_eval_step(
                                    metric_fn, mesh=mesh,
                                    with_model_state=has_ms, masked=True,
                                )
                                eval_loader = DataLoader(
                                    build_dataset(args, train=False),
                                    per_replica_batch=args.batch_size,
                                    mesh=mesh, shuffle=False,
                                    seed=args.seed, drop_last=False,
                                    with_mask=True,
                                )
                            if mfu_meter is not None:
                                mfu_meter = None
                                warn0(
                                    "elastic resize: MFU meter disabled "
                                    "(chip count changed mid-run)"
                                )
                            downtime = measure_downtime(t_rs)
                            if events is not None:
                                events.emit(
                                    "resize_downtime",
                                    epoch=decision.epoch,
                                    seconds=round(downtime, 3),
                                )
                            if goodput is not None:
                                goodput.add("resize", downtime)
                            log0(
                                "elastic resize: %d -> %d replicas "
                                "(membership epoch %d, left: %s) in "
                                "%.2fs — no checkpoint read",
                                old_world, new_world, decision.epoch,
                                list(decision.left), downtime,
                            )
                            timer.reset()  # don't bill the window
            drain()  # epoch edge: eval/checkpoint see fully-synced state
            last_loss = float(metrics["loss"])
            if eval_step is not None:
                # Masked eval: each step returns (masked means, valid-row
                # count); weighting means by counts is exactly the mean over
                # unique samples — sampler pad duplicates contribute nothing.
                # FSDP streams over the sharded flats; everything else gets
                # the (possibly gathered) model-layout tree.
                t_ev = time.perf_counter()
                with _span("eval", epoch=epoch):
                    eval_params = state.params if args.fsdp else full_params()
                    evals = []
                    for b in eval_loader:
                        m, cnt = (
                            eval_step(eval_params, state.model_state, b)
                            if has_ms and not cp
                            else eval_step(eval_params, b)
                        )
                        evals.append((m, float(cnt)))
                    # Free the gathered copy NOW — keeping a full
                    # replicated param tree alive through the next
                    # training epoch would undo exactly the memory FSDP
                    # shards away.
                    del eval_params
                if goodput is not None:
                    goodput.add("eval", time.perf_counter() - t_ev)
                if evals:
                    total = sum(n for _, n in evals)
                    mean = {
                        k: float(sum(float(e[k]) * n for e, n in evals) / total)
                        for k in evals[0][0]
                    }
                    log0("Epoch %d eval: %s", epoch, mean)
            if ckpt is not None:
                t_ck = time.perf_counter()
                with _span("ckpt_save", epoch=epoch):
                    ckpt.save(state, epoch, meta=ckpt_meta)
                if goodput is not None:
                    goodput.add("checkpoint", time.perf_counter() - t_ck)
            if eval_step is not None or ckpt is not None:
                # Don't let eval/checkpoint wall time pollute throughput.
                timer.reset()
    except SimulatedPreemption as pe:
        # Chaos preemption dies the way a real one does — abruptly and
        # nonzero, WITHOUT a parting checkpoint — so the supervisor
        # (--max-restarts) resumes from the last durable epoch.
        warn_all("%s", pe)
        raise SystemExit(1) from pe
    # ddplint: allow[broad-except] — re-raises after releasing the group
    except BaseException:
        # Divergence (nan-guard breaker) or any other abort must not
        # strand the process group: the next train() in this process —
        # a supervised respawn runs in a fresh one — would hit the
        # init-twice guard.
        ddp.destroy_process_group()
        raise
    finally:
        if watchdog is not None:
            watchdog.stop()
        if prof is not None:
            prof.close()
        if registry is not None:
            # Final snapshot always lands, whatever the exit path.
            try:
                registry.export(final=True)
            # ddplint: allow[broad-except] — telemetry must not mask exit
            except Exception:  # noqa: BLE001 — telemetry must not mask
                pass
        run_summary = None
        if summary_builder is not None:
            exc = sys.exc_info()[1]
            try:
                run_summary = summary_builder.build(
                    goodput=goodput.summary() if goodput is not None else None,
                    restarts=counters.restarts,
                    alerts_total=(
                        len(alert_engine.fired)
                        if alert_engine is not None else 0
                    ),
                    status="ok" if exc is None else type(exc).__name__,
                )
            # ddplint: allow[broad-except] — telemetry must not mask exit
            except Exception:  # noqa: BLE001
                run_summary = None
        if events is not None:
            exc = sys.exc_info()[1]
            if goodput is not None:
                # The run's own wall-time attribution, just before
                # run_end; the offline reconstruction adds what this
                # incarnation cannot see (inter-incarnation restart gaps).
                events.emit("goodput", **goodput.summary())
            if run_summary is not None:
                # The ~10 numbers this incarnation boils down to — what
                # the runs store and perf gate consume.
                events.emit("run_summary", **run_summary)
            events.emit(
                "run_end",
                status="ok" if exc is None else type(exc).__name__,
                faults=counters.summary(),
            )
            events.close()
            if jax.process_index() == 0 and not os.environ.get(
                "_DDP_SUPERVISED"
            ):
                # Unsupervised runs merge their own gang timeline; under
                # supervision the launcher does it after the LAST
                # incarnation, so the merge sees every attempt's events.
                from distributeddataparallel_tpu.observability import (
                    merge_timeline,
                )

                merge_timeline(args.events_dir)
        if (
            run_summary is not None
            and args.runs_dir
            and jax.process_index() == 0
            and not os.environ.get("_DDP_SUPERVISED")
        ):
            # Longitudinal store: one line per run.  Supervised runs are
            # appended by the launcher instead, whose summary spans every
            # incarnation (this one would only cover the last).
            from distributeddataparallel_tpu.observability import append_run

            try:
                append_run(args.runs_dir, run_summary, source="trainer")
            # ddplint: allow[broad-except] — telemetry must not mask exit
            except Exception:  # noqa: BLE001
                warn0("runs-dir: could not append run summary")
    if counters.total:
        log0("fault summary: %s", counters.summary())

    if args.generate:
        # Demo of the KV-cache decode path: greedily continue a training
        # prompt with the trained params (models.generate).  Replicated
        # params only (plain DP / ZeRO) — sharded-layout serving is not
        # wired into the CLI.
        import numpy as np

        from distributeddataparallel_tpu.models import generate as _gen

        prompt = jnp.asarray(
            dataset.tokens[:2, : max(args.seq_len // 4, 1)], jnp.int32
        )
        n_new = min(args.generate, model.cfg.max_seq_len - prompt.shape[1])
        gen_model = model
        if args.fsdp and model.cfg.tp_axis is not None:
            # FSDP x TP: full_params() reassembled the FULL unsharded
            # tree, so decode runs on a TP-free twin config.
            import dataclasses

            from distributeddataparallel_tpu.models import TransformerLM

            gen_model = TransformerLM(
                dataclasses.replace(model.cfg, tp_axis=None)
            )
        out = _gen(
            gen_model, full_params(), prompt, n_new,
            quantize=args.decode_quant,
        )
        log0("generate: prompt %s -> %s (last 8 tokens: %s)%s",
             prompt.shape, out.shape, np.asarray(out[0, -8:]).tolist(),
             " [int8 weights]" if args.decode_quant else "")

    if ckpt is not None:
        ckpt.wait()
    if precompiler is not None:
        # XLA calls std::terminate if the interpreter tears down while
        # the background thread is mid-compile — wait the N±1 jobs out.
        precompiler.join(timeout=300)
    if gang is not None:
        # Clean exit: deregister the hosted members so a later run in
        # the same store starts from an empty gang, not ghost members.
        gang.stop()
    ddp.destroy_process_group()                          # ref dpp.py:57
    return last_loss


def _worker(process_id, argv, result_file=None):
    """Supervised-run payload: one full train() in a child process.

    Module-level (not a closure) so the spawn start method can pickle it;
    the ``if __name__`` guard below keeps the re-import from recursing.
    ``result_file``, when given, receives the final loss — the only
    channel a crashed-and-restarted child has back to its test harness.
    """
    del process_id  # single-process gangs; jax sees a local mesh
    args = parse_args(argv)
    validate_args(args)
    select_device(args)
    loss = train(args)
    if result_file:
        with open(result_file, "w") as fh:
            fh.write(repr(float(loss)))


def main(argv=None):
    args = parse_args(argv)
    validate_args(args)
    if args.max_restarts > 0 and not os.environ.get("_DDP_SUPERVISED"):
        # Supervised mode: run the trainer in a child gang under
        # runtime.launcher.spawn, which restarts it (up to the budget) on
        # any nonzero exit — chaos preemption, watchdog exit code 75, a
        # real crash.  The child argv gains --resume so every restart
        # continues from the newest intact checkpoint instead of epoch 0.
        from distributeddataparallel_tpu.runtime.launcher import spawn

        if args.compile_cache:
            # Export the cache through the environment BEFORE spawning:
            # gang members and respawns are fresh interpreters, and the
            # env (plus the child argv) is what makes every restart a
            # cache hit / AOT load instead of a cold compile.
            from distributeddataparallel_tpu.training.warm_start import (
                enable_compile_cache,
            )

            enable_compile_cache(args.compile_cache)
        child_argv = list(argv) if argv is not None else sys.argv[1:]
        if "--resume" not in child_argv:
            child_argv.append("--resume")
        child_env = {"_DDP_SUPERVISED": "1"}
        if args.elastic:
            # Same rendezvous root for every incarnation: the supervisor
            # reads it to tell a shrunk-roster death (resize-respawn)
            # from a plain crash (restart).
            child_env["DDP_ELASTIC_DIR"] = elastic_store_dir(args)
        spawn(
            _worker, args=(child_argv,), nprocs=1,
            max_restarts=args.max_restarts,
            env=child_env,
            # Supervisor-side observability: restart attempts land in
            # events-supervisor.jsonl and the per-worker logs merge into
            # one gang timeline.jsonl when supervision ends.
            events_dir=args.events_dir,
            # The supervisor writes the runs-store summary for supervised
            # runs — its view spans every incarnation + restart gaps.
            runs_dir=args.runs_dir,
            elastic_store=elastic_store_dir(args) if args.elastic else None,
            min_procs=args.min_procs,
        )
        return
    select_device(args)
    train(args)


if __name__ == "__main__":
    main()
