#!/usr/bin/env python
"""Llama-3 8B memory-fit analysis (BASELINE config 5 evidence).

Strategy: XLA's compile-time memory assignment is exact, but the full 8B
config exceeds this chip's HBM and XLA refuses to compile it (its OOM
message reports only a lower bound).  With ``scan_layers=True`` peak
memory is affine in the layer count L (scanned layers stack parameters;
remat keeps one layer's backward live at a time) and in the vocab size V
(embedding + lm_head params and the f32 logits buffer), with no L x V
cross term.  So the full config's peak is recovered by measuring configs
that compile on this chip — the REAL shapes (d_model 4096, d_ff 14336,
GQA 32/8, full 128256 vocab, seq as given; bf16 compute, f32 params,
remat + scan, donated state — the exact step ``dpp.py`` runs), just
fewer layers — and extrapolating only the layer direction:

    peak(32, mb) = peak_measured(L0, full_vocab, mb) + (32-L0)*dL(mb)

The grid runs with STATELESS sgd; optimizer state is then added
analytically (its exact bytes from ``tx.init``'s abstract shapes — the
donated update is elementwise, so opt state is purely additional
resident memory).  Three validation points are measured and reported:
the L midpoint (affinity in L), the full-vocab column (affinity in V),
and an sgd+momentum compile (the optimizer-bytes additivity).

Nothing is allocated at any point — compile-only on the real TPU
backend.  Run: ``python memfit.py [--seq-len 4096]``; output committed
as MEMFIT.md.
"""

from __future__ import annotations

import argparse

# Usable HBM reported by this environment's XLA when a program exceeds it
# ("Used ... of 15.75G hbm", v5e); memory_stats() is not exposed through
# the remote-compile tunnel, so the observed figure is the fallback.
V5E_HBM_BYTES = int(15.75 * (1 << 30))
V5P_HBM_BYTES = 95 * (1 << 30)  # BASELINE config 5's platform


def gb(x: float) -> float:
    return round(x / (1 << 30), 2)


def _abstract_state(model, tx):
    import jax
    import jax.numpy as jnp

    import distributeddataparallel_tpu as ddp

    def make():
        params = model.init(
            jax.random.PRNGKey(0), jnp.zeros((1, 16), jnp.int32)
        )["params"]
        return ddp.TrainState.create(apply_fn=model.apply, params=params, tx=tx)

    return jax.eval_shape(make)


def _tree_bytes(tree) -> int:
    import jax

    return sum(l.size * l.dtype.itemsize for l in jax.tree.leaves(tree))


def _peak_bytes(model, tx, mb: int, seq_len: int) -> int:
    """AOT-compile the DP train step; return XLA's peak memory figure."""
    import jax
    import jax.numpy as jnp

    import distributeddataparallel_tpu as ddp
    from distributeddataparallel_tpu.ops import lm_cross_entropy

    astate = _abstract_state(model, tx)

    def loss_fn(params, batch, rng):
        toks = batch["tokens"]
        logits = model.apply({"params": params}, toks[:, :-1])
        return lm_cross_entropy(logits, toks[:, 1:]), {}

    mesh = ddp.make_mesh(("data",), devices=jax.devices()[:1])
    step = ddp.make_train_step(loss_fn, mesh=mesh)
    akey = jax.eval_shape(lambda: jax.random.PRNGKey(0))
    abatch = {"tokens": jax.ShapeDtypeStruct((mb, seq_len + 1), jnp.int32)}
    ma = step.lower(astate, abatch, akey).compile().memory_analysis()
    return ma.peak_memory_in_bytes or (
        ma.argument_size_in_bytes
        + ma.temp_size_in_bytes
        + ma.output_size_in_bytes
        - ma.alias_size_in_bytes
    )


def analyze(seq_len: int, microbatches=(1, 2)) -> dict:
    import jax
    import optax

    from distributeddataparallel_tpu.models import TransformerLM, llama3_8b

    sgd = optax.sgd(1e-3)  # stateless: isolates model memory
    L0, L1, Lmid = 2, 4, 3
    V0 = 16032  # small vocab for the layer direction (keeps L=4 on-chip)

    full_cfg = llama3_8b(max_seq_len=seq_len)
    target_layers, target_vocab = full_cfg.num_layers, full_cfg.vocab_size

    def model_at(L, V):
        return TransformerLM(
            llama3_8b(max_seq_len=seq_len, num_layers=L, vocab_size=V)
        )

    def peak(L, V, mb, tx=sgd):
        return _peak_bytes(model_at(L, V), tx, mb, seq_len)

    # The FULL-vocab base is measured directly at L0 (it fits on-chip) —
    # no extrapolation in V at all (vocab-coupled memory is not quite
    # affine: XLA pads/lays out the big logits buffer differently at
    # 128256 than at small vocabs; measured 17% off in an earlier affine
    # attempt).  Only the layer direction, which IS affine under scan
    # (validated below), is extrapolated.
    peak_model, checks = {}, []
    for mb in microbatches:
        a = peak(L0, V0, mb)
        dL = (peak(L1, V0, mb) - a) / (L1 - L0)
        base_full = peak(L0, target_vocab, mb)
        peak_model[mb] = base_full + (target_layers - L0) * dL
        if mb == microbatches[0]:
            # Validation 1: affinity in L — the midpoint must sit on the line.
            mid_pred = a + (Lmid - L0) * dL
            mid_meas = peak(Lmid, V0, mb)
            checks.append({
                "what": f"L affinity (L={Lmid}, V={V0}, mb={mb})",
                "predicted_gb": gb(mid_pred), "measured_gb": gb(mid_meas),
                "rel_err": round(abs(mid_pred - mid_meas) / mid_meas, 4),
            })
            # Validation 2: dL is vocab-independent (no L x V cross term) —
            # the L2->L3 delta at FULL vocab must equal dL measured at V0.
            try:
                l3_pred = base_full + (Lmid - L0) * dL
                l3_meas = peak(Lmid, target_vocab, mb)
                checks.append({
                    "what": f"dL vocab-independence (L={Lmid}, "
                            f"V={target_vocab}, mb={mb})",
                    "predicted_gb": gb(l3_pred), "measured_gb": gb(l3_meas),
                    "rel_err": round(abs(l3_pred - l3_meas) / l3_meas, 4),
                })
            except Exception as e:  # noqa: BLE001 — validation point OOM
                checks.append({
                    "what": f"dL vocab-independence (L={Lmid}): "
                            f"did not fit on this chip ({type(e).__name__})",
                    "predicted_gb": None, "measured_gb": None,
                    "rel_err": None,
                })
            # Validation 3: optimizer state adds exactly its bytes.
            mom = optax.sgd(1e-3, momentum=0.9)
            mom_bytes = _tree_bytes(
                _abstract_state(model_at(L0, V0), mom).opt_state
            )
            mom_pred = a + mom_bytes
            mom_meas = peak(L0, V0, mb, tx=mom)
            checks.append({
                "what": f"opt-state additivity (sgd+momentum, L={L0}, V={V0})",
                "predicted_gb": gb(mom_pred), "measured_gb": gb(mom_meas),
                "rel_err": round(abs(mom_pred - mom_meas) / mom_meas, 4),
            })

    b0, b1 = microbatches
    slope = (peak_model[b1] - peak_model[b0]) / (b1 - b0)
    model_fixed = peak_model[b0] - b0 * slope

    dev = jax.local_devices()[0]
    hbm = (dev.memory_stats() or {}).get("bytes_limit") or V5E_HBM_BYTES

    full_model = TransformerLM(full_cfg)
    params_bytes = _tree_bytes(_abstract_state(full_model, sgd).params)

    def max_mb(limit, fixed_bytes):
        if slope <= 0:
            return None
        return max(0, int((limit - fixed_bytes) // slope))

    # TP-8: Megatron layout shards the layer params (q/k/v/o, MLP) over 8
    # chips — exact byte fractions from the spec tree; embeddings/norms
    # stay replicated.  Params AND grads shard; opt state mirrors params.
    # The activation slope is kept unsharded (a conservative upper bound:
    # TP also divides attention/MLP activations, which we cannot measure
    # on one chip).
    from distributeddataparallel_tpu.parallel.tensor_parallel import (
        tp_param_specs,
    )

    def sharded_bytes(tree) -> int:
        specs = tp_param_specs(tree)
        return sum(
            l.size * l.dtype.itemsize
            for l, s in zip(jax.tree.leaves(tree), jax.tree.leaves(specs))
            if any(s)
        )

    TPN = 8
    FSDPN = 8
    # FSDP byte math (optimizer-independent parts, parallel/fsdp.py):
    # stored = per-chip params shards; the gathered non-layer flat and
    # ~2 gathered layers (current + backward regather) live full.
    from distributeddataparallel_tpu.parallel.fsdp import _Meta

    meta = _Meta(full_cfg, FSDPN)
    layer_elems = sum(l.size for l in jax.tree.leaves(meta.layer_template))
    rest_elems = meta.rest_chunk * FSDPN
    # v2 gathers ride bf16 (gather_dtype) and the rest flat is
    # checkpointed around its two uses, so the transient is the LARGER
    # of (gathered rest) and (~2 gathered layers), not their sum.
    fsdp_transient = max(2 * rest_elems, 2 * 2 * layer_elems)
    fsdp_stored = 4 * (meta.L * meta.layer_chunk + meta.rest_chunk)
    FSDPN32 = 32
    fsdp32_stored = fsdp_stored * FSDPN / FSDPN32
    rows = []
    for name, tx in (
        ("sgd", sgd),
        ("sgd_momentum", optax.sgd(1e-3, momentum=0.9)),
        ("adamw", optax.adamw(3e-4)),
    ):
        ast = _abstract_state(full_model, tx)
        opt_bytes = _tree_bytes(ast.opt_state)
        fixed = model_fixed + opt_bytes
        # params + grads each drop their sharded fraction (N-1)/N; opt
        # state drops its own sharded fraction.
        sharded_opt = sharded_bytes(ast.opt_state)
        tp_saving = (
            2 * sharded_bytes(ast.params) + sharded_opt
        ) * (TPN - 1) / TPN
        tp_fixed = fixed - tp_saving
        # TP-8 x ZeRO-1x8 (a DP(8) x TP(8) pod slice): params/grads keep
        # the TP fractions; the flat opt state is built from each
        # position's LOCAL Megatron shard and then 1/8-sharded again over
        # the data axis (parallel/zero.py zero_state(tp_axis=...)).
        tp_local_opt = (opt_bytes - sharded_opt) + sharded_opt / TPN
        tp_zero_fixed = tp_fixed - tp_local_opt + tp_local_opt / 8
        # FSDP-8: params, grads, and opt state all 1/8 resident; plus the
        # full gathered non-layer flat, ~2 gathered layers, AND the same
        # measured non-param residual (model_fixed - params - grads, the
        # XLA/framework overhead ~10 GB) every other column inherits —
        # without it the FSDP column would not be comparable.
        opt_mult = opt_bytes / max(params_bytes, 1)  # 0 sgd, 1 mom, 2 adamw
        residual = max(model_fixed - 2 * params_bytes, 0)
        fsdp_fixed = (
            fsdp_stored * (2 + opt_mult) + fsdp_transient + residual
        )
        fsdp32_fixed = (
            fsdp32_stored * (2 + opt_mult) + fsdp_transient + residual
        )
        rows.append({
            "optimizer": name,
            "opt_state_gb": gb(opt_bytes),
            "peak8b_gb": {mb: gb(p + opt_bytes) for mb, p in peak_model.items()},
            "fixed_gb": gb(fixed),
            "max_mb_v5e": max_mb(hbm, fixed),
            "max_mb_v5p": max_mb(V5P_HBM_BYTES, fixed),
            # ZeRO-1 over N chips keeps 1/N of the opt state per chip
            # (parallel/zero.py); nothing else changes.
            "zero1x8_fixed_gb": gb(model_fixed + opt_bytes / 8),
            "zero1x8_max_mb_v5p": max_mb(
                V5P_HBM_BYTES, model_fixed + opt_bytes / 8
            ),
            "tp8_fixed_gb": gb(tp_fixed),
            "tp8_max_mb_v5p": max_mb(V5P_HBM_BYTES, tp_fixed),
            "tp8_max_mb_v5e": max_mb(hbm, tp_fixed),
            "tp8_zero8_fixed_gb": gb(tp_zero_fixed),
            "tp8_zero8_max_mb_v5p": max_mb(V5P_HBM_BYTES, tp_zero_fixed),
            "fsdp8_fixed_gb": gb(fsdp_fixed),
            "fsdp8_max_mb_v5p": max_mb(V5P_HBM_BYTES, fsdp_fixed),
            "fsdp8_max_mb_v5e": max_mb(hbm, fsdp_fixed),
            "fsdp32_fixed_gb": gb(fsdp32_fixed),
            "fsdp32_max_mb_v5e": max_mb(hbm, fsdp32_fixed),
        })

    # ZeRO-level ladder (sharded weight update, adamw, N=8 data shards):
    # per-chip PEAK coefficient on params bytes P and between-step STORED
    # state, from the parallel/zero.py byte model.  Peak counts params +
    # grads + opt-state residency; zero1 and zero2 share a peak line (opt
    # at 1/N) — zero2's win over zero1 is the scatter TRANSIENT (one
    # ~1 MiB bucket instead of a full P-byte flat f32 grad copy) and is
    # below the GB resolution of this table.  zero3 is peak-honest for
    # the implemented full-gather step: the gathered param tree is live
    # at peak, so peak EXCEEDS zero2 by P/N while stored drops to
    # (params + opt)/N — the stored column is what checkpoint/resident
    # HWM telemetry sees (bench zero_sharding, mesh_sim).
    ZN = 8
    P = params_bytes
    adamw_opt = _tree_bytes(
        _abstract_state(full_model, optax.adamw(3e-4)).opt_state
    )
    opt_coeff = adamw_opt / max(P, 1)  # 2.0 for adamw
    zero_levels = []
    for name, peak_coeff, stored in (
        ("dp", 2.0 + opt_coeff, P + adamw_opt),
        ("zero1", 2.0 + opt_coeff / ZN, P + adamw_opt / ZN),
        ("zero2", 2.0 + opt_coeff / ZN, P + adamw_opt / ZN),
        ("zero3", 2.0 + (1 + opt_coeff) / ZN, (P + adamw_opt) / ZN),
    ):
        fixed = peak_coeff * P + residual
        headroom = max(V5P_HBM_BYTES - residual - slope, 0)
        zero_levels.append({
            "level": name,
            "stored_gb": gb(stored),
            "fixed_gb": gb(fixed),
            "max_mb_v5e": max_mb(hbm, fixed),
            "max_mb_v5p": max_mb(V5P_HBM_BYTES, fixed),
            # largest f32 param count whose mb=1 step still fits a v5p
            # chip: invert fixed(P) = coeff*P + residual at one act row
            "max_params_b_v5p_mb1": round(
                headroom / peak_coeff / 4 / 1e9, 2
            ),
        })

    return {
        "device_kind": dev.device_kind,
        "seq_len": seq_len,
        "hbm_gb": gb(hbm),
        "params_gb": gb(params_bytes),
        "act_gb_per_row": gb(slope),
        "model_fixed_gb": gb(model_fixed),
        "validations": checks,
        "optimizers": rows,
        "zero_levels": zero_levels,
    }


def main() -> None:
    import os

    import jax

    # Persistent compile cache: reruns reuse the measured grid's binaries.
    jax.config.update(
        "jax_compilation_cache_dir",
        os.path.join(os.path.dirname(os.path.abspath(__file__)), ".jax_cache"),
    )
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)

    p = argparse.ArgumentParser()
    p.add_argument("--seq-len", type=int, default=4096)
    args = p.parse_args()

    r = analyze(args.seq_len)
    print(f"# Llama-3 8B memory fit — measured on {r['device_kind']} "
          f"({r['hbm_gb']} GB HBM), seq {r['seq_len']}, remat+scan, "
          f"bf16 compute / f32 params, donated state\n")
    print(f"Params: {r['params_gb']} GB f32; model fixed cost "
          f"{r['model_fixed_gb']} GB (params + grads + residue); "
          f"activations {r['act_gb_per_row']} GB per batch row.  Peaks "
          f"are XLA's exact compile-time memory assignment (AOT, nothing "
          f"allocated): the full-128256-vocab base is measured directly "
          f"at 2 layers, then extrapolated in the layer direction only "
          f"(affine under scan, validated below); optimizer state adds "
          f"its exact byte size.  v5p columns project onto a 95 GB chip "
          f"(BASELINE config 5's platform).\n")
    print("Regression validations (each predicted from the regression "
          "basis, then measured directly):\n")
    for c in r["validations"]:
        print(f"- {c['what']}: predicted {c['predicted_gb']} GB, measured "
              f"{c['measured_gb']} GB, rel err {c['rel_err']}")
    print()
    print("| optimizer | opt state | 8B peak @mb=1 | 8B peak @mb=2 | "
          "max mb (v5e 16G) | max mb (v5p 95G) | ZeRO-1x8 fixed | "
          "ZeRO-1x8 max mb (v5p) | TP-8 fixed | TP-8 max mb (v5p) | "
          "TP-8 x ZeRO-1x8 fixed | TP-8 x ZeRO max mb (v5p) | "
          "FSDP-8 fixed | FSDP-8 max mb (v5p) | FSDP-8 max mb (v5e 16G) | "
          "FSDP-32 fixed | FSDP-32 max mb (v5e 16G) |  "
          "(FSDP columns assume --fsdp-gather bf16; f32 gathers double "
          "the transient term)")
    print("|---|---|---|---|---|---|---|---|---|---|---|---|---|---|---|---|---|")
    for row in r["optimizers"]:
        mbs = sorted(row["peak8b_gb"])
        print(
            f"| {row['optimizer']} | {row['opt_state_gb']} GB "
            f"| {row['peak8b_gb'][mbs[0]]} GB | {row['peak8b_gb'][mbs[1]]} GB "
            f"| {row['max_mb_v5e']} | {row['max_mb_v5p']} "
            f"| {row['zero1x8_fixed_gb']} GB | {row['zero1x8_max_mb_v5p']} "
            f"| {row['tp8_fixed_gb']} GB | {row['tp8_max_mb_v5p']} "
            f"| {row['tp8_zero8_fixed_gb']} GB "
            f"| {row['tp8_zero8_max_mb_v5p']} "
            f"| {row['fsdp8_fixed_gb']} GB | {row['fsdp8_max_mb_v5p']} "
            f"| {row['fsdp8_max_mb_v5e']} "
            f"| {row['fsdp32_fixed_gb']} GB | {row['fsdp32_max_mb_v5e']} |"
        )
    print()
    print("## Sharded weight update (ZeRO ladder, adamw, N=8 data "
          "shards)\n")
    print("Per-chip byte model of the parallel/zero.py update path.  "
          "'Stored' is the between-step resident state (what HWM "
          "telemetry and checkpoints see); 'peak' adds the transient "
          "gradients (and for zero3 the gathered param tree, which the "
          "implemented full-gather step keeps live at peak — zero3 "
          "trades a slightly higher peak for 1/N stored params).  zero1 "
          "and zero2 share a peak line: zero2's win is the scatter "
          "transient (one ~1 MiB bucket, not a full flat f32 grad "
          "copy), below this table's GB resolution.\n")
    print("| level | stored / chip | peak fixed | max mb (v5e 16G) | "
          "max mb (v5p 95G) | max f32 params @mb=1 (v5p) |")
    print("|---|---|---|---|---|---|")
    for z in r["zero_levels"]:
        print(
            f"| {z['level']} | {z['stored_gb']} GB | {z['fixed_gb']} GB "
            f"| {z['max_mb_v5e']} | {z['max_mb_v5p']} "
            f"| {z['max_params_b_v5p_mb1']} B |"
        )
    import json
    print("\n```json")
    print(json.dumps(r))
    print("```")


if __name__ == "__main__":
    main()
