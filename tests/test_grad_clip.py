"""Axis-aware global-norm gradient clipping (VERDICT r3 item 4).

The contract: ``--grad-clip`` under ANY composition equals the
single-device clipped step on the same global batch — the torch
``clip_grad_norm_`` idiom (clip after averaging, one uniform scale),
with the global norm computed exactly despite model-axis sharding:
sharded leaves psum over their axes, replicated leaves count once
(de-duplication), flat layouts de-weight duplicated elements.

Every test asserts the clip actually BINDS (scale < 1) so a broken norm
can't pass by the clip being inactive.
"""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

import distributeddataparallel_tpu as ddp
from distributeddataparallel_tpu.data.loader import shard_batch
from distributeddataparallel_tpu.models import TransformerLM, tiny_lm
from distributeddataparallel_tpu.ops import lm_cross_entropy
from distributeddataparallel_tpu.parallel.data_parallel import clip_scale

CLIP = 0.05


def _tokens(b=4, s=17, vocab=256, seed=0):
    return np.random.default_rng(seed).integers(
        0, vocab, size=(b, s)
    ).astype(np.int32)


def _ref_clipped_step(model, params, tokens, tx, extra_loss=None):
    """Single-device: grads -> global-norm clip -> update."""

    def loss(p):
        logits = model.apply({"params": p}, jnp.asarray(tokens[:, :-1]))
        base = lm_cross_entropy(logits, jnp.asarray(tokens[:, 1:]))
        return base if extra_loss is None else base + extra_loss(p)

    loss_v, grads = jax.value_and_grad(loss)(params)
    gnorm = jnp.sqrt(
        sum(jnp.sum(l.astype(jnp.float32) ** 2) for l in jax.tree.leaves(grads))
    )
    scale = clip_scale(gnorm, CLIP)
    assert float(scale) < 1.0, "clip must bind for the test to mean anything"
    grads = jax.tree.map(lambda g: g * scale, grads)
    updates, _ = tx.update(grads, tx.init(params), params)
    return float(loss_v), optax.apply_updates(params, updates)


def _assert_tree_close(got, want, atol=3e-5):
    for (path, a), b in zip(
        jax.tree_util.tree_flatten_with_path(got)[0],
        jax.tree.leaves(want),
    ):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), atol=atol,
            err_msg="/".join(str(getattr(k, "key", k)) for k in path),
        )


def _lm_loss(model):
    def loss_fn(p, batch, rng):
        toks = batch["tokens"]
        logits = model.apply({"params": p}, toks[:, :-1])
        return lm_cross_entropy(logits, toks[:, 1:]), {}

    return loss_fn


def test_clip_dp_tp(devices):
    mesh = ddp.make_mesh(("data", "model"), shape=(2, 4))
    cfg = tiny_lm(num_heads=4, d_model=32, d_ff=64)
    cfg_tp = dataclasses.replace(cfg, tp_axis="model")
    model, model_tp = TransformerLM(cfg), TransformerLM(cfg_tp)
    tokens = _tokens()
    params = model.init(
        jax.random.PRNGKey(0), jnp.zeros((1, 16), jnp.int32)
    )["params"]
    tx = optax.adam(1e-2)
    loss_ref, params_ref = _ref_clipped_step(model, params, tokens, tx)

    state = ddp.TrainState.create(
        apply_fn=model_tp.apply, params=params, tx=tx
    )
    state = ddp.shard_state_tp(state, mesh)
    step = ddp.make_train_step(
        _lm_loss(model_tp), mesh=mesh, tp_axis="model", grad_clip=CLIP,
        donate=False,
    )
    state, metrics = step(
        state, shard_batch({"tokens": tokens}, mesh), jax.random.PRNGKey(0)
    )
    assert float(metrics["loss"]) == pytest.approx(loss_ref, rel=1e-5)
    _assert_tree_close(state.params, params_ref)


def test_clip_dp_ep(devices):
    mesh = ddp.make_mesh(("data", "expert"), shape=(2, 4))
    cfg = tiny_lm(num_heads=2, d_model=32, d_ff=64, moe_experts=4)
    cfg_ep = dataclasses.replace(cfg, ep_axis="expert")
    model, model_ep = TransformerLM(cfg), TransformerLM(cfg_ep)
    tokens = _tokens(seed=1)
    params = model.init(
        jax.random.PRNGKey(1), jnp.zeros((1, 16), jnp.int32)
    )["params"]
    tx = optax.adam(1e-2)
    loss_ref, params_ref = _ref_clipped_step(model, params, tokens, tx)

    state = ddp.TrainState.create(
        apply_fn=model_ep.apply, params=params, tx=tx
    )
    state = ddp.shard_state_ep(state, mesh)
    step = ddp.make_train_step(
        _lm_loss(model_ep), mesh=mesh, ep_axis="expert", grad_clip=CLIP,
        donate=False,
    )
    state, metrics = step(
        state, shard_batch({"tokens": tokens}, mesh), jax.random.PRNGKey(0)
    )
    assert float(metrics["loss"]) == pytest.approx(loss_ref, rel=1e-5)
    _assert_tree_close(state.params, params_ref)


@pytest.mark.parametrize("schedule", ["gpipe", "1f1b"])
def test_clip_dp_pp(devices, schedule):
    from distributeddataparallel_tpu.parallel import (
        make_pp_train_step,
        shard_state_pp,
    )

    mesh = ddp.make_mesh(("data", "pipe"), shape=(4, 2))
    cfg = tiny_lm(
        num_heads=2, d_model=32, d_ff=64, num_layers=4, scan_layers=True
    )
    model = TransformerLM(cfg)
    tokens = _tokens(b=8, seed=2)
    params = model.init(
        jax.random.PRNGKey(2), jnp.zeros((1, 16), jnp.int32)
    )["params"]
    tx = optax.adam(1e-2)
    loss_ref, params_ref = _ref_clipped_step(model, params, tokens, tx)

    state = ddp.TrainState.create(apply_fn=None, params=params, tx=tx)
    state = shard_state_pp(state, mesh)
    step = make_pp_train_step(
        cfg, mesh=mesh, microbatches=2, grad_clip=CLIP, donate=False,
        schedule=schedule,
    )
    state, metrics = step(
        state, shard_batch({"tokens": tokens}, mesh), jax.random.PRNGKey(0)
    )
    assert float(metrics["loss"]) == pytest.approx(loss_ref, rel=1e-5)
    _assert_tree_close(state.params, params_ref)


def test_clip_zero_tp(devices):
    mesh = ddp.make_mesh(("data", "model"), shape=(2, 4))
    cfg = tiny_lm(num_heads=4, d_model=32, d_ff=64)
    cfg_tp = dataclasses.replace(cfg, tp_axis="model")
    model, model_tp = TransformerLM(cfg), TransformerLM(cfg_tp)
    tokens = _tokens(seed=3)
    params = model.init(
        jax.random.PRNGKey(3), jnp.zeros((1, 16), jnp.int32)
    )["params"]
    tx = optax.adam(1e-2)
    loss_ref, params_ref = _ref_clipped_step(model, params, tokens, tx)

    state = ddp.zero_state(
        apply_fn=model_tp.apply, params=params, tx=tx, mesh=mesh,
        tp_axis="model",
    )
    step = ddp.make_train_step(
        _lm_loss(model_tp), mesh=mesh, tp_axis="model", zero=True,
        grad_clip=CLIP, donate=False,
    )
    state, metrics = step(
        state, shard_batch({"tokens": tokens}, mesh), jax.random.PRNGKey(0)
    )
    assert float(metrics["loss"]) == pytest.approx(loss_ref, rel=1e-5)
    _assert_tree_close(state.params, params_ref)


def test_clip_fsdp_tp(devices):
    from distributeddataparallel_tpu.parallel.fsdp import (
        fsdp_gather_params,
        fsdp_state,
        make_fsdp_train_step,
    )

    mesh = ddp.make_mesh(("data", "model"), shape=(4, 2))
    cfg = tiny_lm(
        num_heads=2, d_model=32, d_ff=64, num_layers=2, scan_layers=True,
        remat=True,
    )
    cfg_tp = dataclasses.replace(cfg, tp_axis="model")
    model = TransformerLM(cfg)
    tokens = _tokens(seed=4)
    params = model.init(
        jax.random.PRNGKey(4), jnp.zeros((1, 16), jnp.int32)
    )["params"]
    tx = optax.adam(1e-2)
    loss_ref, params_ref = _ref_clipped_step(model, params, tokens, tx)

    state = fsdp_state(cfg_tp, params, tx, mesh, tp_axis="model")
    step = make_fsdp_train_step(
        cfg_tp, mesh=mesh, tp_axis="model", grad_clip=CLIP, donate=False
    )
    state, metrics = step(
        state, shard_batch({"tokens": tokens}, mesh), jax.random.PRNGKey(0)
    )
    assert float(metrics["loss"]) == pytest.approx(loss_ref, rel=1e-5)
    got = fsdp_gather_params(cfg_tp, state, mesh, tp_axis="model", host=True)
    _assert_tree_close(got, params_ref)


def test_clip_pp_zero(devices):
    from distributeddataparallel_tpu.parallel import (
        make_pp_train_step,
        shard_state_pp,
    )

    mesh = ddp.make_mesh(("data", "pipe"), shape=(4, 2))
    cfg = tiny_lm(
        num_heads=2, d_model=32, d_ff=64, num_layers=4, scan_layers=True
    )
    model = TransformerLM(cfg)
    tokens = _tokens(b=8, seed=5)
    params = model.init(
        jax.random.PRNGKey(5), jnp.zeros((1, 16), jnp.int32)
    )["params"]
    tx = optax.adam(1e-2)
    loss_ref, params_ref = _ref_clipped_step(model, params, tokens, tx)

    state = ddp.zero_state(
        apply_fn=None, params=params, tx=tx, mesh=mesh, pp_axis="pipe"
    )
    step = make_pp_train_step(
        cfg, mesh=mesh, microbatches=2, zero=True, grad_clip=CLIP,
        donate=False,
    )
    state, metrics = step(
        state, shard_batch({"tokens": tokens}, mesh), jax.random.PRNGKey(0)
    )
    assert float(metrics["loss"]) == pytest.approx(loss_ref, rel=1e-5)
    _assert_tree_close(state.params, params_ref)
