"""The defining DDP invariant (SURVEY.md §4 'equivalence'): N-device DP with
per-replica batch B must produce the SAME loss curve as single-device
training with batch N×B — because averaged per-replica grads over equal
shards equal the full-batch gradient.  Plus grad-accumulation boundary
semantics (no_sync analog) and bucketed-psum equivalence at step level."""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest
from jax.sharding import PartitionSpec as P

from distributeddataparallel_tpu.models.simple_cnn import TinyMLP
from distributeddataparallel_tpu.ops.losses import cross_entropy_loss
from distributeddataparallel_tpu.parallel.data_parallel import broadcast_params
from distributeddataparallel_tpu.runtime.distributed import make_mesh
from distributeddataparallel_tpu.training.state import TrainState
from distributeddataparallel_tpu.training.train_step import make_train_step


def _setup(lr=0.1, seed=0):
    model = TinyMLP(features=(32,), num_classes=10)
    params = model.init(jax.random.PRNGKey(seed), jnp.zeros((1, 8, 8, 1)))["params"]

    def loss_fn(params, batch, rng):
        logits = model.apply({"params": params}, batch["image"])
        return cross_entropy_loss(logits, batch["label"]), {}

    tx = optax.sgd(lr)
    state = TrainState.create(apply_fn=model.apply, params=params, tx=tx)
    return model, state, loss_fn


def _fake_batches(num_steps, global_batch, seed=0):
    rng = np.random.default_rng(seed)
    # Class-conditional means so the task is learnable (loss can decrease).
    protos = rng.normal(size=(10, 8, 8, 1)).astype(np.float32)
    out = []
    for _ in range(num_steps):
        labels = rng.integers(0, 10, size=(global_batch,)).astype(np.int32)
        images = protos[labels] + 0.5 * rng.normal(
            size=(global_batch, 8, 8, 1)
        ).astype(np.float32)
        out.append({"image": images.astype(np.float32), "label": labels})
    return out


def _single_device_curve(state, loss_fn, batches):
    """Reference curve: plain jit on one device, full global batch."""

    @jax.jit
    def step(state, batch):
        (loss, _), grads = jax.value_and_grad(loss_fn, has_aux=True)(
            state.params, batch, jax.random.PRNGKey(0)
        )
        return state.apply_gradients(grads), loss

    losses = []
    for b in batches:
        state, loss = step(state, b)
        losses.append(float(loss))
    return losses, state


def test_dp_equals_single_device(devices):
    mesh = make_mesh(("data",))
    n = mesh.shape["data"]
    _, state, loss_fn = _setup()
    batches = _fake_batches(10, global_batch=8 * n)

    ref_losses, _ = _single_device_curve(state, loss_fn, batches)

    dp_state = broadcast_params(state, mesh)
    step_fn = make_train_step(loss_fn, mesh=mesh, donate=False)
    from distributeddataparallel_tpu.data.loader import shard_batch

    dp_losses = []
    rng = jax.random.PRNGKey(0)
    for b in batches:
        dp_state, metrics = step_fn(dp_state, shard_batch(b, mesh), rng)
        dp_losses.append(float(metrics["loss"]))

    np.testing.assert_allclose(dp_losses, ref_losses, rtol=2e-4, atol=1e-5)
    # loss actually decreased (training happened)
    assert dp_losses[-1] < dp_losses[0]


def test_grad_accum_matches_single_step(devices):
    """accum_steps=4 over batch 4B == one step over batch 4B (same global
    batch, sync only on the boundary — DDP no_sync semantics)."""
    mesh = make_mesh(("data",))
    n = mesh.shape["data"]
    _, state, loss_fn = _setup()
    batches = _fake_batches(6, global_batch=16 * n, seed=3)

    from distributeddataparallel_tpu.data.loader import shard_batch

    s1 = broadcast_params(state, mesh)
    step1 = make_train_step(loss_fn, mesh=mesh, donate=False)
    s4 = broadcast_params(state, mesh)
    step4 = make_train_step(loss_fn, mesh=mesh, accum_steps=4, donate=False)

    rng = jax.random.PRNGKey(0)
    l1s, l4s = [], []
    for b in batches:
        sb = shard_batch(b, mesh)
        s1, m1 = step1(s1, sb, rng)
        s4, m4 = step4(s4, sb, rng)
        l1s.append(float(m1["loss"]))
        l4s.append(float(m4["loss"]))
    np.testing.assert_allclose(l4s, l1s, rtol=2e-4, atol=1e-5)
    p1 = jax.tree.leaves(s1.params)
    p4 = jax.tree.leaves(s4.params)
    for a, b in zip(p1, p4):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=2e-4, atol=1e-5)


def test_bucketed_step_matches_plain(devices):
    mesh = make_mesh(("data",))
    n = mesh.shape["data"]
    _, state, loss_fn = _setup()
    batches = _fake_batches(5, global_batch=8 * n, seed=7)
    from distributeddataparallel_tpu.data.loader import shard_batch

    sp = broadcast_params(state, mesh)
    sb_ = broadcast_params(state, mesh)
    plain = make_train_step(loss_fn, mesh=mesh, donate=False)
    bucketed = make_train_step(loss_fn, mesh=mesh, bucket_bytes=4096, donate=False)
    rng = jax.random.PRNGKey(1)
    for b in batches:
        x = shard_batch(b, mesh)
        sp, mp = plain(sp, x, rng)
        sb_, mb = bucketed(sb_, x, rng)
        np.testing.assert_allclose(
            float(mb["loss"]), float(mp["loss"]), rtol=1e-4
        )


def test_metrics_are_replicated_and_aux_flows(devices):
    mesh = make_mesh(("data",))
    model = TinyMLP(features=(16,), num_classes=10)
    params = model.init(jax.random.PRNGKey(0), jnp.zeros((1, 8, 8, 1)))["params"]

    def loss_fn(params, batch, rng):
        logits = model.apply({"params": params}, batch["image"])
        from distributeddataparallel_tpu.ops.losses import accuracy

        return cross_entropy_loss(logits, batch["label"]), {
            "accuracy": accuracy(logits, batch["label"])
        }

    state = TrainState.create(
        apply_fn=model.apply, params=params, tx=optax.sgd(0.1)
    )
    state = broadcast_params(state, mesh)
    step = make_train_step(loss_fn, mesh=mesh, donate=False)
    from distributeddataparallel_tpu.data.loader import shard_batch

    b = _fake_batches(1, global_batch=8 * mesh.shape["data"])[0]
    state, metrics = step(state, shard_batch(b, mesh), jax.random.PRNGKey(0))
    assert set(metrics) == {"loss", "accuracy"}
    assert metrics["loss"].sharding.is_fully_replicated
    assert 0.0 <= float(metrics["accuracy"]) <= 1.0


def test_buffer_sync_mean_vs_broadcast(devices):
    """Model-state consistency modes: 'mean' averages per-replica stats
    (SyncBN-flavored); 'broadcast' adopts replica 0's exactly (DDP
    broadcast_buffers semantics)."""
    mesh = make_mesh(("data",))
    n = mesh.shape["data"]
    model, _, _ = _setup()
    params = model.init(jax.random.PRNGKey(0), jnp.zeros((1, 8, 8, 1)))["params"]

    def loss_fn(params, ms, batch, rng):
        logits = model.apply({"params": params}, batch["image"])
        # Fake buffer update: each replica records ITS batch's pixel mean
        # (distinct per replica, like BatchNorm running stats would be).
        new_ms = {"probe": batch["image"].mean()}
        return cross_entropy_loss(logits, batch["label"]), ({}, new_ms)

    B = 4
    batch = _fake_batches(1, B * n, seed=3)[0]
    from distributeddataparallel_tpu.data.loader import shard_batch

    sbatch = shard_batch(batch, mesh)
    per_replica_means = np.asarray([
        batch["image"][r * B : (r + 1) * B].mean() for r in range(n)
    ])

    def run(buffer_sync):
        state = TrainState.create(
            apply_fn=model.apply, params=params, tx=optax.sgd(0.1),
            model_state={"probe": jnp.zeros(())},
        )
        state = broadcast_params(state, mesh)
        step = make_train_step(
            loss_fn, mesh=mesh, with_model_state=True,
            buffer_sync=buffer_sync, donate=False,
        )
        state, _ = step(state, sbatch, jax.random.PRNGKey(0))
        return float(state.model_state["probe"])

    assert run("mean") == pytest.approx(float(per_replica_means.mean()), abs=1e-6)
    assert run("broadcast") == pytest.approx(float(per_replica_means[0]), abs=1e-6)
    with pytest.raises(ValueError, match="buffer_sync"):
        make_train_step(
            loss_fn, mesh=mesh, with_model_state=True, buffer_sync="local"
        )


def test_buffer_sync_broadcast_composes_with_cp(devices):
    """broadcast under DP×CP must deliver position (0,0)'s buffers to ALL
    replicas — regression for the re-masked double-psum that zeroed
    buffers on every data-rank != 0."""
    from distributeddataparallel_tpu.parallel.sampler import DistributedSampler  # noqa: F401 (layout parity with other tests)
    from jax.sharding import NamedSharding

    mesh = make_mesh(("data", "seq"), shape=(4, 2))

    def loss_fn(params, ms, batch, rng):
        # per-position "buffer": this position's input mean (distinct
        # everywhere); loss ties params in so grads exist.
        new_ms = {"probe": batch["x"].mean()}
        return (params["w"] * batch["x"].mean()).sum(), ({}, new_ms)

    rng = np.random.default_rng(0)
    x = rng.normal(size=(8, 8)).astype(np.float32)  # (rows, seq)
    batch = jax.device_put(
        {"x": x}, NamedSharding(mesh, jax.sharding.PartitionSpec("data", "seq"))
    )
    state = TrainState.create(
        apply_fn=None, params={"w": jnp.ones(())}, tx=optax.sgd(0.0),
        model_state={"probe": jnp.zeros(())},
    )
    state = broadcast_params(state, mesh)
    step = make_train_step(
        loss_fn, mesh=mesh, with_model_state=True, buffer_sync="broadcast",
        cp_axis="seq", donate=False,
    )
    state, _ = step(state, batch, jax.random.PRNGKey(0))
    # Position (0,0) holds rows 0-1 x seq cols 0-3.
    want = float(x[0:2, 0:4].mean())
    got = np.asarray(state.model_state["probe"])
    assert got.shape == () or got.size == 1
    assert float(got) == pytest.approx(want, abs=1e-6)
