"""Autotuner subsystem tests (distributeddataparallel_tpu.tuning):

- Typed search space: enumeration is seed-deterministic and every
  emitted trial passes the same validity gates dpp.py enforces.
- TunedConfig store round trip; key mismatch falls back LOUDLY to the
  untuned defaults (warning naming the differing fields, strict raises)
  — the same contract as the warm-start executable store.
- Autotuner core: analytic memory pruning, predicted-throughput
  ranking, baseline always measured and eligible to win, exact
  predicted-vs-measured drift accounting, crash-isolated candidates.
- Generalized BackgroundPrecompiler: arbitrary (name, key, build) jobs,
  wait/done, and the join-at-shutdown guard (submit after join raises).
- ExecutableStore capability record: ``_store.json`` carries a bool
  ``reserialize_ok`` verdict and never shows up as an entry.
- perf_gate metric directions: ``*_gain_frac`` gates higher-is-better
  and must not be shadowed by the ``*_frac`` lower-is-better rule.
- Acceptance: ``dpp.py --autotune search`` persists a winner and emits
  tune_trial events; a second run with ``--autotune apply`` reaches the
  first step with ZERO search trials.
"""

import json
import logging
import os
import sys

import pytest

sys.path.insert(0, "/root/repo")
sys.path.insert(0, os.path.join("/root/repo", "scripts"))

import dpp  # noqa: E402
import perf_gate  # noqa: E402
from distributeddataparallel_tpu.analysis.mesh_sim import (  # noqa: E402
    analytic_memory_fit,
)
from distributeddataparallel_tpu.training.warm_start import (  # noqa: E402
    WarmStartMismatch,
    _save_allowed,
)
from distributeddataparallel_tpu.tuning import (  # noqa: E402
    Autotuner,
    SearchSpace,
    TrialConfig,
    TuningStore,
)
from distributeddataparallel_tpu.utils.logging import get_logger  # noqa: E402


class _Capture(logging.Handler):
    """The repo logger has propagate=False, so caplog can't see it —
    capture by attaching directly."""

    def __init__(self):
        super().__init__()
        self.messages = []

    def emit(self, record):
        self.messages.append(record.getMessage())


class _capture_warnings:
    def __enter__(self):
        self._h = _Capture()
        get_logger().addHandler(self._h)
        return self._h.messages

    def __exit__(self, *exc):
        get_logger().removeHandler(self._h)


# ---------------------------------------------------------------- space


def test_space_enumeration_deterministic_and_valid():
    space = SearchSpace(
        batch_per_chip=(8, 16, 32), accum_steps=(1, 2, 3),
        remat=(False, True), zero=(0, 1, 2),
        moment_dtype=("f32", "bf16"),
    )
    a = space.enumerate(seed=7)
    b = space.enumerate(seed=7)
    assert a == b, "same seed must give the same trial order"
    assert a != space.enumerate(seed=8), "seed must actually shuffle"
    assert sorted(t.label for t in a) == sorted(
        t.label for t in space.enumerate(seed=8)
    ), "seeds reorder, never change the trial SET"
    for t in a:
        assert not t.problems(), t
    labels = {t.label for t in a}
    # the dpp gates: accum must divide batch; low-bit moments need zero
    assert not any(t.batch_per_chip % t.accum_steps for t in a)
    assert "b8-a1-r0-z0-mbf16-q2" not in labels
    assert "b8-a3-r0-z0-mf32-q2" not in labels


def test_trial_round_trip_and_cli_flags():
    t = TrialConfig(batch_per_chip=16, accum_steps=2, remat=True, zero=2,
                    moment_dtype="bf16", bucket_mb=4.0, dispatch_depth=3)
    assert TrialConfig.from_dict(t.as_dict()) == t
    flags = t.cli_flags()
    assert "--remat" in flags and "--moment-dtype" in flags
    assert "--zero" in flags and "--bucket-mb" in flags
    # mlp/cnn have no remat knob and dpp.py rejects the flag for them
    assert "--remat" not in t.cli_flags(lm=False)
    # a valid winner must replay through the dpp argument gates
    base = ["--model", "gpt2", "--dataset", "synthetic-lm"]
    dpp.validate_args(dpp.parse_args(base + flags))


# ---------------------------------------------------------------- store


def test_tuned_config_round_trip(devices, tmp_path):
    import distributeddataparallel_tpu as ddp
    from distributeddataparallel_tpu.tuning import tuned_key

    mesh = ddp.make_mesh(("data",))
    store = TuningStore(str(tmp_path / "tuned"))
    key = tuned_key(mesh=mesh, extra={"model": "mlp", "seq": 0})
    trial = TrialConfig(batch_per_chip=16, zero=1)
    path = store.save(
        "mlp@d8", key, config=trial.as_dict(), objective="model_flops/s",
        score=1.0, measured_step_s=0.01, gain_frac=0.25,
    )
    assert os.path.exists(path)
    rec = store.load("mlp@d8", key)
    assert rec is not None
    assert TrialConfig.from_dict(rec["config"]) == trial
    assert rec["gain_frac"] == 0.25
    assert store.index()["mlp@d8"]["score"] == 1.0


def test_tuned_config_key_mismatch_loud(devices, tmp_path):
    import distributeddataparallel_tpu as ddp
    from distributeddataparallel_tpu.tuning import tuned_key

    mesh = ddp.make_mesh(("data",))
    store = TuningStore(str(tmp_path / "tuned"))
    key = tuned_key(mesh=mesh, extra={"model": "mlp", "seq": 128})
    store.save("mlp@d8", key, config=TrialConfig().as_dict(),
               objective="model_flops/s", score=1.0)

    stale = tuned_key(mesh=mesh, extra={"model": "mlp", "seq": 256})
    with _capture_warnings() as messages:
        assert store.load("mlp@d8", stale) is None
    assert any(
        "key mismatch" in m and "extra.seq" in m
        and "falling back to untuned defaults" in m
        for m in messages
    ), messages
    with pytest.raises(WarmStartMismatch, match="key mismatch"):
        store.load("mlp@d8", stale, strict=True)
    # a cold store (nothing tuned yet) is silent — not a fault
    with _capture_warnings() as messages:
        assert store.load("other@d8", key) is None
    assert not messages


# ------------------------------------------------------------ perf_gate


def test_perf_gate_gain_frac_direction():
    """``*_gain_frac`` is a WIN share: higher is better, and it must
    not be shadowed by the ``_frac$`` lower-is-better waste-share rule
    (ISSUE 15 satellite f)."""
    assert perf_gate._bench_direction("tune_gain_frac") == "higher"
    assert perf_gate._bench_direction("gain_frac") == "higher"
    # the neighbors keep their directions
    assert perf_gate._bench_direction("tuned_step_s") == "lower"
    assert perf_gate._bench_direction("zb_bubble_frac") == "lower"
    assert perf_gate._bench_direction("integrity_overhead_frac") == "lower"
    assert perf_gate._bench_direction("restart_reclaimed_s") == "higher"
    assert perf_gate._bench_direction("serve_tok_s") == "higher"


# ------------------------------------------------------- memory pruning


def test_analytic_memory_fit_zero_ladder():
    kw = dict(
        params_bytes=4_000_000, params_count=1_000_000, n_devices=8,
        act_bytes=1_000_000, batch_bytes=500_000,
        budget_bytes=10_000_000,
    )
    req = {
        z: analytic_memory_fit(zero_level=z, **kw)["required_bytes"]
        for z in (0, 1, 2, 3)
    }
    # each ZeRO level shards one more term by N: strictly less memory
    assert req[0] > req[1] > req[2] > req[3]
    # zero1 shards the moments (8 B/param) across 8 devices
    assert req[0] - req[1] == 8_000_000 - 8_000_000 // 8
    fit = analytic_memory_fit(zero_level=0, **kw)
    assert not fit["fits"] and fit["analytic"]
    assert analytic_memory_fit(zero_level=3, **kw)["fits"]
    # low-bit moments shrink the optimizer term
    low = analytic_memory_fit(
        zero_level=1, moment_bytes_per_param=2.0, **kw
    )
    assert low["required_bytes"] < req[1]


def test_mesh_sim_no_compile_records_fit(devices):
    from distributeddataparallel_tpu.analysis.mesh_sim import simulate

    rec = simulate("cnn", "dp", batch_per_chip=2, do_compile=False)
    fit = rec.get("fit")
    assert fit is not None and fit.get("analytic") is True
    assert fit["required_bytes"] > 0 and fit["fits"] in (True, False)


# ------------------------------------------------------- autotuner core


def _fake_hooks(step_s_by_label, *, no_fit=(), fail=()):
    """Deterministic predict/measure pair: predicted step time is the
    table value, measured is exactly 2x it (drift_frac == +1.0)."""

    def predict(trial):
        return {
            "model_flops": 100.0,
            "step_s": step_s_by_label[trial.label],
            "fit": {
                "required_bytes": 1, "budget_bytes": 2,
                "fits": trial.label not in no_fit, "analytic": True,
            },
        }

    def measure(trial):
        if trial.label in fail:
            raise RuntimeError("XLA fell over")
        s = 2.0 * step_s_by_label[trial.label]
        return {"step_s": s, "score": 100.0 / s, "mfu": None,
                "warm_mode": "aot"}

    return predict, measure


def test_autotuner_prunes_ranks_and_accounts_drift():
    trials = [
        TrialConfig(batch_per_chip=b) for b in (8, 16, 32, 64, 128)
    ]
    by_label = {t.label: 0.01 * (i + 1)
                for i, t in enumerate(trials)}  # slower as batch grows
    predict, measure = _fake_hooks(
        by_label, no_fit={trials[4].label}, fail={trials[0].label},
    )
    prepared = []
    tuner = Autotuner(predict=predict, measure=measure,
                      prepare=prepared.append, top_k=2)
    baseline = TrialConfig(batch_per_chip=64)
    winner, records = tuner.search(trials, baseline=baseline)
    by = {r.trial.label: r for r in records}

    assert by[trials[4].label].status == "pruned-memory"
    # fastest predicted (b8) and next (b16) are the top-2 candidates;
    # b8's measurement crashes and that is a RESULT, not a failure
    assert by[trials[0].label].status.startswith("error:")
    assert by[trials[1].label].status == "measured"
    assert by[trials[2].label].status == "pruned-cost"
    assert by[baseline.label].status == "baseline"
    # measured = 2x predicted everywhere -> drift is exactly +100%
    assert by[trials[1].label].drift_frac == pytest.approx(1.0)
    assert by[baseline.label].drift_frac == pytest.approx(1.0)
    # b16 measured 0.04s vs baseline 0.08s -> b16 wins on model FLOP/s
    assert winner is by[trials[1].label]
    # prepare() was called for each measured candidate after the first
    assert prepared == [t.trial for t in
                        [by[trials[1].label], by[baseline.label]]]


def test_autotuner_baseline_can_win():
    trials = [TrialConfig(batch_per_chip=8)]
    base = TrialConfig(batch_per_chip=64)
    by_label = {trials[0].label: 0.08, base.label: 0.01}
    predict, measure = _fake_hooks(by_label)
    winner, _ = Autotuner(predict=predict, measure=measure,
                          top_k=1).search(trials, baseline=base)
    assert winner.trial == base and winner.status == "baseline"


def test_autotuner_seeded_search_is_deterministic():
    space = SearchSpace(batch_per_chip=(8, 16, 32, 64),
                        accum_steps=(1, 2), zero=(0, 1))
    by_label = {t.label: 0.01 + 0.001 * i
                for i, t in enumerate(space.enumerate())}
    predict, measure = _fake_hooks(by_label)

    def run():
        tuner = Autotuner(predict=predict, measure=measure, top_k=3)
        winner, records = tuner.search(space.enumerate(seed=3))
        return winner.trial.label, [
            (r.trial.label, r.status, r.measured_step_s) for r in records
        ]

    assert run() == run()


# ----------------------------------------------- background precompiler


def test_background_precompiler_generalized(devices, tmp_path):
    """Arbitrary (name, key, build) jobs run off-thread; results land in
    report; the join guard makes late submits raise instead of hanging
    interpreter teardown."""
    import jax
    import jax.numpy as jnp

    from distributeddataparallel_tpu.training.warm_start import (
        BackgroundPrecompiler,
        ExecutableStore,
    )

    store = ExecutableStore(str(tmp_path / "aot"), probe=False)

    def build_for(scale):
        def build():
            fn = jax.jit(lambda v: v * scale)
            args = (jax.ShapeDtypeStruct((8,), jnp.float32),)
            return fn, args
        return build

    pre = BackgroundPrecompiler(store).start()
    pre.submit("t2", {"scale": 2}, build_for(2.0))
    pre.submit("t3", {"scale": 3}, build_for(3.0))
    assert pre.wait(timeout=60), "worker never went idle"
    assert pre.done
    assert pre.report == {"t2": "saved", "t3": "saved"}
    # resubmitting an already-stored key is a cheap no-op
    pre.submit("t2", {"scale": 2}, build_for(2.0))
    assert pre.wait(timeout=60)
    assert pre.report["t2"] == "cached"
    # a crashing build is swallowed per-job, not fatal to the worker
    def bad_build():
        raise ValueError("no mesh for you")
    pre.submit("boom", {"x": 1}, bad_build)
    assert pre.wait(timeout=60)
    assert pre.report["boom"].startswith("error:")

    pre.join(timeout=60)
    with pytest.raises(RuntimeError, match="submit after join"):
        pre.submit("late", {"x": 2}, build_for(4.0))
    assert sorted(store.index()) == ["t2", "t3"]


def test_executable_store_capability_record(devices, tmp_path):
    from distributeddataparallel_tpu.training.warm_start import (
        ExecutableStore,
    )

    root = str(tmp_path / "aot")
    store = ExecutableStore(root)  # probe at open
    assert isinstance(store.reserialize_ok, bool)
    meta = store.store_meta()
    assert meta["reserialize_ok"] == store.reserialize_ok
    assert "versions" in meta
    assert os.path.exists(os.path.join(root, "_store.json"))
    # the reserved record is store metadata, never an entry
    assert "_store" not in store.index()

    # reopen trusts the persisted verdict instead of re-probing
    with open(os.path.join(root, "_store.json")) as fh:
        rec = json.load(fh)
    rec["reserialize_ok"] = not store.reserialize_ok
    with open(os.path.join(root, "_store.json"), "w") as fh:
        json.dump(rec, fh)
    assert ExecutableStore(root).reserialize_ok is rec["reserialize_ok"]

    # the save policy: fresh compiles always persist; cache-hit compiles
    # persist only where the probe said re-serialization round-trips
    store.reserialize_ok = False
    assert _save_allowed(store, 0, None)
    assert _save_allowed(store, 1, None)
    assert _save_allowed(store, 0, {"key": {}})
    assert not _save_allowed(store, 1, {"key": {}})
    store.reserialize_ok = True
    assert _save_allowed(store, 1, {"key": {}})


# ----------------------------------------------------------- acceptance


def _tune_args(tmp_path, mode, events_sub):
    return dpp.parse_args([
        "--device", "cpu",
        "--model", "mlp",
        "--dataset", "synthetic",
        "--num-examples", "128",
        "--batch-size", "8",
        "--epochs", "1",
        "--log-every", "1000",
        "--autotune", mode,
        "--tune-trials", "1",
        "--tune-steps", "1",
        "--tune-dir", str(tmp_path / "tuned"),
        "--events-dir", str(tmp_path / events_sub),
    ])


def _tune_kinds(tmp_path, events_sub):
    recs = []
    evdir = str(tmp_path / events_sub)
    for fname in os.listdir(evdir):
        if fname.startswith("events-") and fname.endswith(".jsonl"):
            with open(os.path.join(evdir, fname)) as fh:
                recs += [json.loads(line) for line in fh if line.strip()]
    return [r for r in recs if str(r.get("kind", "")).startswith("tune_")]


def test_dpp_autotune_search_then_apply(devices, tmp_path):
    """The PR's acceptance loop: a search run persists a winner and
    emits tune_trial events; the apply rerun reaches its first train
    step with ZERO search trials, replaying the stored config."""
    dpp.train(_tune_args(tmp_path, "search", "ev_search"))
    assert os.path.exists(str(tmp_path / "tuned" / "mlp@d8.tuned.json"))
    search_events = _tune_kinds(tmp_path, "ev_search")
    n_trials = sum(1 for r in search_events if r["kind"] == "tune_trial")
    results = [r for r in search_events if r["kind"] == "tune_result"]
    assert n_trials > 0
    assert [r["mode"] for r in results] == ["search"]
    assert results[0]["winner"]

    dpp.train(_tune_args(tmp_path, "apply", "ev_apply"))
    apply_events = _tune_kinds(tmp_path, "ev_apply")
    assert sum(
        1 for r in apply_events if r["kind"] == "tune_trial"
    ) == 0, "apply must not search"
    results = [r for r in apply_events if r["kind"] == "tune_result"]
    assert [r["mode"] for r in results] == ["apply"]
    assert results[0]["applied"] is True
    assert (
        results[0]["winner"]["batch_per_chip"]
        == json.load(
            open(str(tmp_path / "tuned" / "mlp@d8.tuned.json"))
        )["config"]["batch_per_chip"]
    )


def test_dpp_autotune_apply_cold_store_falls_back(devices, tmp_path):
    """apply on a never-tuned host: loud info, CLI defaults, run still
    trains (a tuned config is an optimization, not a requirement)."""
    args = _tune_args(tmp_path, "apply", "ev_cold")
    loss = dpp.train(args)
    assert loss == loss  # finite run completed
    results = [r for r in _tune_kinds(tmp_path, "ev_cold")
               if r["kind"] == "tune_result"]
    assert [r["mode"] for r in results] == ["apply"]
    assert results[0]["applied"] is False
    assert args.batch_size == 8  # defaults untouched


def test_dpp_autotune_arg_gates():
    with pytest.raises(SystemExit, match="autotune"):
        dpp.validate_args(dpp.parse_args(
            ["--model", "gpt2", "--dataset", "synthetic-lm",
             "--autotune", "search", "--fsdp"]
        ))
    with pytest.raises(SystemExit, match="remat"):
        dpp.validate_args(dpp.parse_args(
            ["--model", "mlp", "--dataset", "synthetic",
             "--remat", "on"]
        ))
