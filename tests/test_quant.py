"""Weight-only int8 decode quantization (ops.quant).

- round-trip error bounded by the per-channel quantization grid;
- leaf selection (matrices quantize; 1-D/tiny/int leaves pass through);
- decode-model logits with quantized weights track the full-precision
  logits; generate() runs end-to-end with quantize="int8";
- the byte ledger shows ~half the bf16 stream for matrix-heavy trees.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from distributeddataparallel_tpu.models import TransformerLM, tiny_lm
from distributeddataparallel_tpu.models.generate import generate
from distributeddataparallel_tpu.ops.quant import (
    MIN_QUANT_ELEMS,
    QuantLeaf,
    dequantize,
    quantize_int8,
    quantized_bytes,
)


def _lm(vocab=256, d_model=128, d_ff=512, layers=2):
    cfg = tiny_lm(
        vocab_size=vocab, d_model=d_model, d_ff=d_ff,
        num_layers=layers, num_heads=4, max_seq_len=64,
        dtype=jnp.bfloat16,
    )
    model = TransformerLM(cfg)
    params = model.init(
        jax.random.PRNGKey(0), jnp.zeros((1, 8), jnp.int32)
    )["params"]
    return model, params


def test_roundtrip_error_bounded(devices):
    rng = np.random.default_rng(0)
    w = jnp.asarray(
        rng.normal(size=(256, 128)).astype(np.float32) * 0.2
    )
    q = quantize_int8({"w": w})["w"]
    assert isinstance(q, QuantLeaf)
    assert q.q.dtype == jnp.int8 and q.q.shape == w.shape
    assert q.scale.shape == (1, 128)  # keepdims: broadcasts against q
    deq = dequantize({"w": q}, jnp.float32)["w"]
    # per-element error <= half a quantization bin per channel
    absmax = np.abs(np.asarray(w)).max(axis=0)
    err = np.abs(np.asarray(deq) - np.asarray(w))
    assert (err <= absmax / 127.0 * 0.5 + 1e-7).all()


def test_leaf_selection(devices):
    tree = {
        "mat": jnp.ones((256, 128)),          # quantized
        "bias": jnp.ones((4096,)),            # 1-D: pass
        "tiny": jnp.ones((16, 16)),           # under floor: pass
        "ids": jnp.ones((256, 128), jnp.int32),  # non-float: pass
    }
    q = quantize_int8(tree)
    assert isinstance(q["mat"], QuantLeaf)
    assert not isinstance(q["bias"], QuantLeaf)
    assert not isinstance(q["tiny"], QuantLeaf)
    assert not isinstance(q["ids"], QuantLeaf)
    assert tree["mat"].size >= MIN_QUANT_ELEMS
    led = quantized_bytes(q)
    assert led["n_quantized_leaves"] == 1
    assert led["n_passthrough_leaves"] == 3
    # matrix leaf: int8 payload + f32 scales (4x down from f32);
    # pass-through leaves keep their source bytes
    assert led["bytes"] == (
        256 * 128 + 128 * 4          # quantized matrix
        + 4096 * 4 + 16 * 16 * 4     # f32 pass-through
        + 256 * 128 * 4              # int32 ids
    )


def test_decode_logits_track_full_precision(devices):
    """Quantized decode-twin logits stay close to the bf16 logits —
    the end-to-end accuracy bar for 8-bit weight-only serving."""
    import dataclasses

    model, params = _lm()
    dcfg = dataclasses.replace(
        model.cfg, decode=True, remat=False, dropout_rate=0.0
    )
    dm = TransformerLM(dcfg)
    toks = jnp.asarray(
        np.random.default_rng(1).integers(0, 256, size=(2, 8)),
        jnp.int32,
    )
    cache = dm.init(
        jax.random.PRNGKey(0), toks[:, :1], positions=jnp.arange(1)
    )["cache"]
    full, _ = dm.apply(
        {"params": params, "cache": cache}, toks,
        positions=jnp.arange(8), mutable=["cache"],
    )
    qp = quantize_int8(params)
    deq = dequantize(qp, jnp.bfloat16)
    quant, _ = dm.apply(
        {"params": deq, "cache": cache}, toks,
        positions=jnp.arange(8), mutable=["cache"],
    )
    f = np.asarray(full, np.float32)
    g = np.asarray(quant, np.float32)
    # bf16 logits at random init are O(1); 8-bit weight error stays small
    assert np.abs(f - g).max() < 0.25, np.abs(f - g).max()
    # and well-correlated
    assert np.corrcoef(f.ravel(), g.ravel())[0, 1] > 0.999


def test_generate_int8_runs(devices):
    model, params = _lm()
    prompt = jnp.asarray([[1, 2, 3, 4]], jnp.int32)
    out = generate(model, params, prompt, 8, quantize="int8")
    assert out.shape == (1, 12)
    assert out.dtype == jnp.int32
    assert bool((out[:, :4] == prompt).all())
    with pytest.raises(ValueError, match="quantize"):
        generate(model, params, prompt, 4, quantize="fp4")


def test_scanned_stack_per_layer_scales(devices):
    """A stacked (L, in, out) kernel whose layers differ 100x in range
    quantizes each layer against ITS OWN absmax (round-5 review
    finding: a shared scale vector costs the quiet layer ~3 bits and
    its error bound)."""
    rng = np.random.default_rng(0)
    loud = rng.normal(size=(256, 128)).astype(np.float32)
    quiet = loud * 0.01
    w = jnp.asarray(np.stack([loud, quiet]))
    q = quantize_int8({"w": w})["w"]
    assert q.scale.shape[0] == 2  # per-layer scale slices
    deq = np.asarray(dequantize({"w": q}, jnp.float32)["w"])
    for layer in range(2):
        absmax = np.abs(np.asarray(w[layer])).max(axis=0)
        err = np.abs(deq[layer] - np.asarray(w[layer]))
        assert (err <= absmax / 127.0 * 0.5 + 1e-9).all(), layer


def test_scale_overhead_capped(devices):
    """Unscanned QKV-shaped (d, h, hd) kernels coarsen their scale
    groups so the f32 scales stay <= 1/16 of the int8 payload."""
    w = jnp.ones((768, 12, 64))
    q = quantize_int8({"w": w})["w"]
    assert q.scale.size * 4 <= w.size / 16
    # scanned 4D keeps the layer dim separate AND stays under the cap
    w4 = jnp.ones((4, 256, 8, 32))
    q4 = quantize_int8({"w": w4})["w"]
    assert q4.scale.shape[0] == 4
    assert q4.scale.size * 4 <= w4.size / 16


def test_generate_accepts_prequantized_tree(devices):
    """Serving loops quantize once: generate() detects a QuantLeaf tree
    and skips the per-call quantize pass; outputs match the
    quantize='int8' convenience path exactly."""
    model, params = _lm()
    prompt = jnp.asarray([[1, 2, 3, 4]], jnp.int32)
    qp = jax.jit(quantize_int8)(params)
    out_pre = generate(model, qp, prompt, 8)
    out_conv = generate(model, params, prompt, 8, quantize="int8")
    np.testing.assert_array_equal(np.asarray(out_pre), np.asarray(out_conv))


def test_stacked_mode_and_scanned_generate(devices):
    """stacked_first_dim keeps the layer dim on EVERY scale (norm-stack
    leaves included — nn.scan must slice scales alongside q); scanned
    generate() runs end-to-end and matches the unquantized-fixup path."""
    from distributeddataparallel_tpu.ops.quant import quantize_int8_jit

    # a stacked norm-like leaf exactly at the floor: (8, 2048)
    w = jnp.ones((8, 2048))
    q = quantize_int8_jit({"w": w}, stacked_first_dim=True)["w"]
    assert q.scale.shape[0] == 8  # per-layer, sliceable
    # non-stacked quantization of the same leaf loses the layer dim
    q_bad = quantize_int8_jit({"w": w})["w"]
    assert q_bad.scale.shape[0] == 1

    import dataclasses

    cfg = dataclasses.replace(
        tiny_lm(
            vocab_size=256, d_model=128, d_ff=512, num_layers=2,
            num_heads=4, max_seq_len=64, dtype=jnp.bfloat16,
        ),
        scan_layers=True,
    )
    model = TransformerLM(cfg)
    params = model.init(
        jax.random.PRNGKey(0), jnp.zeros((1, 8), jnp.int32)
    )["params"]
    prompt = jnp.asarray([[1, 2, 3, 4]], jnp.int32)
    out = generate(model, params, prompt, 8, quantize="int8")
    assert out.shape == (1, 12)
    # hand-quantized WITHOUT stacked mode: the fixup serves the
    # unsliceable leaves dequantized; still runs and agrees on shape
    from distributeddataparallel_tpu.ops.quant import quantize_int8

    qp = jax.jit(quantize_int8)(params)  # non-stacked on purpose
    out2 = generate(model, qp, prompt, 8)
    assert out2.shape == (1, 12)
