"""Attention op tests: RoPE properties, causal masking, GQA expansion, and
the Pallas flash kernel vs the XLA reference (interpret mode on CPU)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from distributeddataparallel_tpu.ops.attention import (
    apply_rope,
    causal_mask_bias,
    dot_product_attention,
    repeat_kv,
    rope_frequencies,
)
from distributeddataparallel_tpu.ops import pallas_attention


def _qkv(key, B=2, S=16, H=4, D=8, Hkv=None, dtype=jnp.float32):
    kq, kk, kv = jax.random.split(key, 3)
    Hkv = Hkv or H
    q = jax.random.normal(kq, (B, S, H, D), dtype)
    k = jax.random.normal(kk, (B, S, Hkv, D), dtype)
    v = jax.random.normal(kv, (B, S, Hkv, D), dtype)
    return q, k, v


def test_causal_masking_blocks_future():
    """Perturbing a future token must not change earlier outputs."""
    q, k, v = _qkv(jax.random.PRNGKey(0))
    out = dot_product_attention(q, k, v, causal=True)
    k2 = k.at[:, -1].add(100.0)
    v2 = v.at[:, -1].add(100.0)
    out2 = dot_product_attention(q, k2, v2, causal=True)
    np.testing.assert_allclose(out[:, :-1], out2[:, :-1], atol=1e-5)
    assert not np.allclose(out[:, -1], out2[:, -1])


def test_attention_matches_manual_softmax():
    q, k, v = _qkv(jax.random.PRNGKey(1), B=1, S=6, H=2, D=4)
    out = dot_product_attention(q, k, v, causal=False)
    logits = np.einsum("bqhd,bkhd->bhqk", q, k) / np.sqrt(4)
    w = jax.nn.softmax(jnp.asarray(logits), axis=-1)
    expected = np.einsum("bhqk,bkhd->bqhd", w, v)
    np.testing.assert_allclose(out, expected, atol=1e-5)


def test_causal_mask_bias_offsets():
    # Chunk at global q offset 4 attending to kv chunk at offset 0: all visible.
    bias = causal_mask_bias(4, 4, q_offset=4, kv_offset=0)
    assert (bias == 0).all()
    # kv chunk strictly in the future: all masked.
    bias = causal_mask_bias(4, 4, q_offset=0, kv_offset=4)
    assert (bias < -1e29).all()
    # Diagonal chunk: lower triangle visible.
    bias = causal_mask_bias(4, 4, q_offset=0, kv_offset=0)
    expected = np.where(np.tril(np.ones((4, 4))), 0, -1e30).astype(np.float32)
    assert (np.asarray(bias) == expected).all()


def test_rope_preserves_norm_and_relative_phase():
    cos, sin = rope_frequencies(8, 32)
    x = jax.random.normal(jax.random.PRNGKey(2), (1, 16, 2, 8))
    rx = apply_rope(x, cos, sin)
    np.testing.assert_allclose(
        np.linalg.norm(np.asarray(x), axis=-1),
        np.linalg.norm(np.asarray(rx), axis=-1),
        rtol=1e-5,
    )
    # Rotation at position 0 is the identity.
    np.testing.assert_allclose(rx[:, 0], x[:, 0], atol=1e-6)


def test_rope_relative_position_invariance():
    """q·k after RoPE depends only on relative distance."""
    cos, sin = rope_frequencies(8, 64)
    q = jax.random.normal(jax.random.PRNGKey(3), (1, 1, 1, 8))
    k = jax.random.normal(jax.random.PRNGKey(4), (1, 1, 1, 8))

    def dot_at(pq, pk):
        rq = apply_rope(q, cos, sin, positions=jnp.array([pq]))
        rk = apply_rope(k, cos, sin, positions=jnp.array([pk]))
        return float(jnp.sum(rq * rk))

    assert dot_at(5, 3) == pytest.approx(dot_at(12, 10), rel=1e-5)
    assert dot_at(5, 3) != pytest.approx(dot_at(5, 4), rel=1e-3)


def test_rope_explicit_positions_match_offset_slice():
    """RoPE on a shard with explicit positions == slice of full-seq RoPE
    (the property sequence-parallel shards rely on)."""
    cos, sin = rope_frequencies(8, 64)
    x = jax.random.normal(jax.random.PRNGKey(5), (1, 16, 2, 8))
    full = apply_rope(x, cos, sin)
    shard = apply_rope(x[:, 8:], cos, sin, positions=jnp.arange(8, 16))
    np.testing.assert_allclose(full[:, 8:], shard, atol=1e-6)


def test_repeat_kv_gqa():
    x = jax.random.normal(jax.random.PRNGKey(6), (2, 4, 2, 8))
    r = repeat_kv(x, 3)
    assert r.shape == (2, 4, 6, 8)
    np.testing.assert_allclose(r[:, :, 0], x[:, :, 0])
    np.testing.assert_allclose(r[:, :, 2], x[:, :, 0])
    np.testing.assert_allclose(r[:, :, 3], x[:, :, 1])


@pytest.mark.parametrize("causal", [True, False])
def test_flash_attention_matches_reference(causal):
    q, k, v = _qkv(jax.random.PRNGKey(7), B=2, S=256, H=2, D=16)
    ref = dot_product_attention(q, k, v, causal=causal)
    out = pallas_attention.flash_attention(q, k, v, causal, True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5)


def test_flash_attention_grads_match_reference():
    q, k, v = _qkv(jax.random.PRNGKey(8), B=1, S=128, H=2, D=16)

    def loss_flash(q, k, v):
        return jnp.sum(pallas_attention.flash_attention(q, k, v, True, True) ** 2)

    def loss_ref(q, k, v):
        return jnp.sum(dot_product_attention(q, k, v, causal=True) ** 2)

    gf = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
    gr = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(gf, gr):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=1e-4)


def test_causal_decode_shapes_see_full_context():
    """Sq != Skv: queries align to the END of the kv sequence, so a 1-token
    query attends over the whole cache (not just position 0)."""
    q, k, v = _qkv(jax.random.PRNGKey(9), B=1, S=8, H=2, D=4)
    full = dot_product_attention(q, k, v, causal=True)
    last = dot_product_attention(q[:, -1:], k, v, causal=True)
    np.testing.assert_allclose(np.asarray(last), np.asarray(full[:, -1:]), atol=1e-5)


def test_flash_causal_decode_shapes():
    q, k, v = _qkv(jax.random.PRNGKey(10), B=1, S=256, H=2, D=16)
    full = pallas_attention.flash_attention(q, k, v, True, True)
    half = pallas_attention.flash_attention(q[:, 128:], k, v, True, True)
    np.testing.assert_allclose(
        np.asarray(half), np.asarray(full[:, 128:]), atol=2e-5
    )


def test_flash_supported_gating():
    q = jnp.zeros((1, 256, 2, 16))
    # CPU backend in tests → native kernel not supported (interpret only).
    assert not pallas_attention.supported(q, q, q)
    assert pallas_attention._pick_block(256) == 256
    assert pallas_attention._pick_block(384) == 128
    assert pallas_attention._pick_block(100) is None


def test_flash_decode_shape_grads_match_reference():
    """Sq != Skv backward: the blockwise kernels' q_offset must align query
    rows to the END of the kv sequence, matching the XLA reference."""
    q, k, v = _qkv(jax.random.PRNGKey(11), B=1, S=256, H=2, D=16)
    qh = q[:, 128:]  # 128 queries against 256 kv positions

    def loss_flash(qh, k, v):
        return jnp.sum(pallas_attention.flash_attention(qh, k, v, True, True) ** 2)

    def loss_ref(qh, k, v):
        return jnp.sum(dot_product_attention(qh, k, v, causal=True) ** 2)

    gf = jax.grad(loss_flash, argnums=(0, 1, 2))(qh, k, v)
    gr = jax.grad(loss_ref, argnums=(0, 1, 2))(qh, k, v)
    for a, b in zip(gf, gr):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=1e-4)


def test_flash_backward_memory_is_linear_in_seq():
    """Long-context guarantee: backward peak temp memory must scale O(S),
    not O(S²) — the blockwise kernels never materialize the (S, S)
    probability matrix (an O(S²) backward at S=2048 needs >100 MB here;
    the blockwise one a few MB)."""

    def temp_bytes(S):
        def loss(q, k, v):
            return jnp.sum(
                pallas_attention.flash_attention(q, k, v, True, True) ** 2
            )

        args = [jax.ShapeDtypeStruct((1, S, 2, 16), jnp.float32)] * 3
        compiled = jax.jit(jax.grad(loss, argnums=(0, 1, 2))).lower(*args).compile()
        analysis = compiled.memory_analysis()
        if analysis is None:
            pytest.skip("backend exposes no memory analysis")
        return analysis.temp_size_in_bytes

    m512, m1024, m2048 = temp_bytes(512), temp_bytes(1024), temp_bytes(2048)
    # Linear growth: each doubling adds ~2x the previous increment.
    # Quadratic growth would multiply increments by ~4 and blow past this.
    assert m2048 - m1024 < 3 * (m1024 - m512) + (1 << 20), (m512, m1024, m2048)
    assert m2048 < 8 * m512, (m512, m2048)


def test_flash_rejects_causal_sq_gt_skv():
    """Causal Sq > Skv leaves query rows with no visible keys (undefined
    softmax) — must be rejected, not silently garbage."""
    q = jnp.zeros((1, 256, 2, 16))
    kv = jnp.zeros((1, 128, 2, 16))
    assert not pallas_attention.supported(q, kv, kv)
    with pytest.raises(ValueError, match="Sq <= Skv"):
        pallas_attention.flash_attention(q, kv, kv, True, True)


@pytest.mark.parametrize("causal", [True, False])
def test_flash_gqa_matches_repeated_reference(causal):
    """GQA-native flash (kv at Hkv < H, indexed per group in the kernel)
    must equal the reference on repeat_kv-expanded kv."""
    q, k, v = _qkv(jax.random.PRNGKey(12), B=2, S=256, H=4, D=16, Hkv=2)
    ref = dot_product_attention(
        q, repeat_kv(k, 2), repeat_kv(v, 2), causal=causal
    )
    out = pallas_attention.flash_attention(q, k, v, causal, True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5)


def test_flash_gqa_grads_match_repeated_reference():
    """GQA backward: dk/dv accumulate over the whole query-head group
    (the dkv grid walks every (q block, group member) pair per kv head)."""
    q, k, v = _qkv(jax.random.PRNGKey(13), B=1, S=128, H=4, D=16, Hkv=2)

    def loss_flash(q, k, v):
        return jnp.sum(pallas_attention.flash_attention(q, k, v, True, True) ** 2)

    def loss_ref(q, k, v):
        return jnp.sum(
            dot_product_attention(
                q, repeat_kv(k, 2), repeat_kv(v, 2), causal=True
            ) ** 2
        )

    gf = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
    gr = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for name, a, b in zip("qkv", gf, gr):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), atol=1e-4, err_msg=f"d{name}"
        )
