"""FSDP tests: the fully-sharded step must reproduce single-device
training exactly (the DDP invariant, with params/grads/opt state all
1/N-resident), the flat layout must round-trip, and the residency claim
must hold on the actual shardings."""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest
from jax.sharding import PartitionSpec as P

import distributeddataparallel_tpu as ddp
from distributeddataparallel_tpu.data.loader import shard_batch
from distributeddataparallel_tpu.models import TransformerLM, tiny_lm
from distributeddataparallel_tpu.ops import lm_cross_entropy
from distributeddataparallel_tpu.parallel.fsdp import (
    _Meta,
    fsdp_gather_params,
    fsdp_state,
    make_fsdp_train_step,
)


def _cfg(**over):
    base = dict(
        num_layers=3, num_heads=2, d_model=32, d_ff=64, max_seq_len=32,
        scan_layers=True,
    )
    base.update(over)
    return tiny_lm(**base)


def _init_params(cfg):
    return TransformerLM(cfg).init(
        jax.random.PRNGKey(0), jnp.zeros((1, 16), jnp.int32)
    )["params"]


def test_flat_roundtrip(devices):
    """flatten_full -> unflatten_full is the identity on the param tree."""
    cfg = _cfg()
    params = _init_params(cfg)
    meta = _Meta(cfg, 8)
    back = meta.unflatten_full(meta.flatten_full(params))
    for (pa, a), b in zip(
        jax.tree_util.tree_flatten_with_path(params)[0],
        jax.tree.leaves(back),
    ):
        np.testing.assert_array_equal(
            np.asarray(a), np.asarray(b),
            err_msg="/".join(str(getattr(k, "key", k)) for k in pa),
        )


@pytest.mark.parametrize("remat", [False, True], ids=["plain", "remat"])
def test_fsdp_matches_single_device(remat, devices):
    """One FSDP step over 8 ways == the single-device step on the same
    global batch: same loss, same (gathered) updated params."""
    cfg = _cfg(remat=remat)
    mesh = ddp.make_mesh(("data",))
    model = TransformerLM(cfg)
    rng = np.random.default_rng(0)
    tokens = rng.integers(0, 256, size=(8, 17)).astype(np.int32)
    params = _init_params(cfg)
    tx = optax.sgd(0.1)

    def ref_loss(p):
        logits = model.apply({"params": p}, jnp.asarray(tokens[:, :-1]))
        return lm_cross_entropy(logits, jnp.asarray(tokens[:, 1:]))

    loss_ref, grads_ref = jax.value_and_grad(ref_loss)(params)
    updates, _ = tx.update(grads_ref, tx.init(params), params)
    params_ref = optax.apply_updates(params, updates)

    state = fsdp_state(cfg, params, tx, mesh)
    step = make_fsdp_train_step(cfg, mesh=mesh, donate=False)
    state, metrics = step(
        state, shard_batch({"tokens": tokens}, mesh), jax.random.PRNGKey(0)
    )
    assert float(metrics["loss"]) == pytest.approx(float(loss_ref), rel=1e-5)

    got = fsdp_gather_params(cfg, state, mesh)
    for (pa, a), b in zip(
        jax.tree_util.tree_flatten_with_path(params_ref)[0],
        jax.tree.leaves(got),
    ):
        np.testing.assert_allclose(
            np.asarray(b), np.asarray(a), atol=1e-5,
            err_msg="/".join(str(getattr(k, "key", k)) for k in pa),
        )


def test_fsdp_adam_multi_step(devices):
    """Two adam steps: sharded mu/nu must evolve identically to the
    replicated single-device run (reduction order aside)."""
    cfg = _cfg()
    mesh = ddp.make_mesh(("data",))
    model = TransformerLM(cfg)
    rng = np.random.default_rng(1)
    batches = [
        rng.integers(0, 256, size=(8, 17)).astype(np.int32) for _ in range(2)
    ]
    params = _init_params(cfg)
    tx = optax.adam(1e-2)

    ref_p, ref_o = params, tx.init(params)
    for t in batches:
        def ref_loss(p, _t=t):
            logits = model.apply({"params": p}, jnp.asarray(_t[:, :-1]))
            return lm_cross_entropy(logits, jnp.asarray(_t[:, 1:]))

        g = jax.grad(ref_loss)(ref_p)
        up, ref_o = tx.update(g, ref_o, ref_p)
        ref_p = optax.apply_updates(ref_p, up)

    state = fsdp_state(cfg, params, tx, mesh)
    step = make_fsdp_train_step(cfg, mesh=mesh, donate=False)
    for t in batches:
        state, _ = step(
            state, shard_batch({"tokens": t}, mesh), jax.random.PRNGKey(0)
        )
    got = fsdp_gather_params(cfg, state, mesh)
    # atol 1e-4: adam's rsqrt amplifies the reduce-scatter's different
    # fp summation order over multiple steps.
    for a, b in zip(jax.tree.leaves(ref_p), jax.tree.leaves(got)):
        np.testing.assert_allclose(np.asarray(b), np.asarray(a), atol=1e-4)


def test_fsdp_residency(devices):
    """Params AND opt state live 1/N-sharded on device — nothing full is
    resident between steps."""
    cfg = _cfg()
    mesh = ddp.make_mesh(("data",))
    state = fsdp_state(cfg, _init_params(cfg), optax.adam(1e-3), mesh)
    assert state.params["layers"].sharding.spec == P(None, "data")
    assert state.params["rest"].sharding.spec == P("data")
    for l in jax.tree.leaves(state.opt_state):
        if l.ndim == 2:
            assert l.sharding.spec == P(None, "data"), l.sharding
        elif l.ndim == 1:
            assert l.sharding.spec == P("data"), l.sharding


def test_fsdp_guards(devices):
    with pytest.raises(ValueError, match="scan_layers"):
        _Meta(_cfg(scan_layers=False), 8)
    with pytest.raises(ValueError, match="TP only"):
        _Meta(dataclasses.replace(_cfg(), cp_axis="seq"), 8)
    # tp_axis must be given to BOTH the config and the factory.
    mesh = ddp.make_mesh(("data", "model"), shape=(4, 2))
    with pytest.raises(ValueError, match="BOTH"):
        make_fsdp_train_step(
            dataclasses.replace(_cfg(), tp_axis="model"), mesh=mesh
        )
    # grad_clip under FSDP x TP is SUPPORTED now (duplicate-de-weighted
    # flat norm) — equivalence pinned by test_grad_clip.test_clip_fsdp_tp.


def test_fsdp_accum_matches_single_big_batch(devices):
    """FSDP x gradient accumulation: 2 microbatches accumulated in the
    sharded layout == the single big-batch FSDP step (and therefore the
    single-device step, by test_fsdp_matches_single_device)."""
    cfg = _cfg()
    mesh = ddp.make_mesh(("data",))
    rng = np.random.default_rng(5)
    tokens = rng.integers(0, 256, size=(16, 17)).astype(np.int32)
    params = _init_params(cfg)
    tx = optax.sgd(0.1)
    batch = shard_batch({"tokens": tokens}, mesh)

    def run(accum):
        state = fsdp_state(cfg, params, tx, mesh)
        step = make_fsdp_train_step(
            cfg, mesh=mesh, accum_steps=accum, donate=False
        )
        state, metrics = step(state, batch, jax.random.PRNGKey(0))
        return float(metrics["loss"]), fsdp_gather_params(cfg, state, mesh)

    loss1, p1 = run(1)
    loss2, p2 = run(2)
    assert loss1 == pytest.approx(loss2, rel=1e-6)
    for a, b in zip(jax.tree.leaves(p1), jax.tree.leaves(p2)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=1e-6)


def test_entrypoint_fsdp_eval_generate(devices):
    """The dpp.py --fsdp --eval --generate path: per-epoch gather feeds
    the masked eval and the decode, and the run completes with finite
    metrics."""
    import sys

    sys.path.insert(0, "/root/repo")
    import dpp

    loss = dpp.train(dpp.parse_args(
        ["--device", "cpu", "--model", "gpt2", "--fsdp", "--eval",
         "--generate", "8", "--seq-len", "32", "--layers", "2",
         "--d-model", "32", "--vocab-size", "64", "--epochs", "1",
         "--num-examples", "64", "--batch-size", "4",
         "--log-every", "1000"]
    ))
    assert loss == loss  # finite: gather->eval->decode wiring intact


# --- FSDP v2: TP composition, bf16 gathers, streaming eval, host gather --


def test_fsdp_tp_matches_single_device(devices):
    """FSDP(4) x Megatron TP(2): flats store each model position's TP
    shard, gathers ride the data axis only — still equal to the
    single-device step, adam state included."""
    cfg = _cfg(num_heads=4, num_kv_heads=2)
    cfg_tp = dataclasses.replace(cfg, tp_axis="model")
    mesh = ddp.make_mesh(("data", "model"), shape=(4, 2))
    model = TransformerLM(cfg)
    rng = np.random.default_rng(7)
    tokens = rng.integers(0, 256, size=(8, 17)).astype(np.int32)
    params = _init_params(cfg)
    tx = optax.adam(1e-2)

    def ref_loss(p):
        logits = model.apply({"params": p}, jnp.asarray(tokens[:, :-1]))
        return lm_cross_entropy(logits, jnp.asarray(tokens[:, 1:]))

    loss_ref, grads_ref = jax.value_and_grad(ref_loss)(params)
    updates, _ = tx.update(grads_ref, tx.init(params), params)
    params_ref = optax.apply_updates(params, updates)

    state = fsdp_state(cfg_tp, params, tx, mesh, tp_axis="model")
    step = make_fsdp_train_step(
        cfg_tp, mesh=mesh, tp_axis="model", donate=False
    )
    state, metrics = step(
        state, shard_batch({"tokens": tokens}, mesh), jax.random.PRNGKey(0)
    )
    assert float(metrics["loss"]) == pytest.approx(float(loss_ref), rel=1e-5)
    got = fsdp_gather_params(cfg_tp, state, mesh, tp_axis="model")
    # atol 1e-4 as in test_fsdp_adam_multi_step: adam's rsqrt amplifies
    # the reduce-scatter's different fp summation order.
    for (path, a), b in zip(
        jax.tree_util.tree_flatten_with_path(got)[0],
        jax.tree.leaves(params_ref),
    ):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), atol=1e-4,
            err_msg="/".join(str(getattr(k, "key", k)) for k in path),
        )


def test_fsdp_tp_flat_roundtrip(devices):
    """flatten_full/unflatten_full with TP: Megatron shards laid out
    model-major round-trip exactly to the original tree."""
    cfg = dataclasses.replace(
        _cfg(num_heads=4, num_kv_heads=2), tp_axis="model"
    )
    params = _init_params(dataclasses.replace(cfg, tp_axis=None))
    meta = _Meta(cfg, n=4, tp_axis="model", n_tp=2)
    back = meta.unflatten_full(
        {k: jnp.asarray(v) for k, v in meta.flatten_full(params).items()}
    )
    for (path, a), b in zip(
        jax.tree_util.tree_flatten_with_path(params)[0],
        jax.tree.leaves(back),
    ):
        np.testing.assert_array_equal(
            np.asarray(a), np.asarray(b),
            err_msg="/".join(str(getattr(k, "key", k)) for k in path),
        )


def test_fsdp_gather_params_host(devices):
    """host=True assembles the full tree in host RAM (pure numpy leaves)
    and matches the device-side gather exactly."""
    cfg = _cfg()
    mesh = ddp.make_mesh(("data",))
    params = _init_params(cfg)
    state = fsdp_state(cfg, params, optax.sgd(0.1), mesh)
    dev = fsdp_gather_params(cfg, state, mesh)
    host = fsdp_gather_params(cfg, state, mesh, host=True)
    for (path, a), b in zip(
        jax.tree_util.tree_flatten_with_path(host)[0], jax.tree.leaves(dev)
    ):
        assert isinstance(a, np.ndarray)
        np.testing.assert_array_equal(a, np.asarray(b), err_msg=str(path))


def test_fsdp_streaming_eval_matches_direct(devices):
    """make_fsdp_eval_step (per-layer gathers, no full tree) reproduces
    the direct masked metrics, padded rows excluded."""
    from distributeddataparallel_tpu.ops import (
        per_example_accuracy,
        per_example_cross_entropy,
    )
    from distributeddataparallel_tpu.parallel.fsdp import make_fsdp_eval_step

    cfg = _cfg()
    mesh = ddp.make_mesh(("data",))
    model = TransformerLM(cfg)
    rng = np.random.default_rng(3)
    tokens = rng.integers(0, 256, size=(16, 17)).astype(np.int32)
    valid = np.array([1] * 13 + [0] * 3, np.int32)
    params = _init_params(cfg)

    logits = model.apply({"params": params}, jnp.asarray(tokens[:, :-1]))
    v = jnp.asarray(valid, jnp.float32)
    want_loss = float(
        jnp.sum(per_example_cross_entropy(logits, tokens[:, 1:]) * v) / v.sum()
    )
    want_acc = float(
        jnp.sum(per_example_accuracy(logits, tokens[:, 1:]) * v) / v.sum()
    )

    state = fsdp_state(cfg, params, optax.sgd(0.1), mesh)
    eval_step = make_fsdp_eval_step(cfg, mesh=mesh)
    metrics, cnt = eval_step(
        state.params, shard_batch({"tokens": tokens, "valid": valid}, mesh)
    )
    assert float(cnt) == 13.0
    assert float(metrics["loss"]) == pytest.approx(want_loss, rel=1e-5)
    assert float(metrics["accuracy"]) == pytest.approx(want_acc, abs=1e-6)


def test_fsdp_bf16_gather_runs_and_tracks_f32(devices):
    """gather_dtype=bfloat16: master flats stay f32, the step runs, and
    the loss tracks the exact f32 step within bf16 rounding."""
    cfg = _cfg()
    mesh = ddp.make_mesh(("data",))
    rng = np.random.default_rng(11)
    tokens = rng.integers(0, 256, size=(8, 17)).astype(np.int32)
    params = _init_params(cfg)
    batch = shard_batch({"tokens": tokens}, mesh)

    def run(gdt):
        state = fsdp_state(cfg, params, optax.sgd(0.1), mesh)
        step = make_fsdp_train_step(
            cfg, mesh=mesh, donate=False, gather_dtype=gdt
        )
        state, m = step(state, batch, jax.random.PRNGKey(0))
        assert state.params["layers"].dtype == jnp.float32
        return float(m["loss"]), state

    loss_f32, _ = run(None)
    loss_bf16, _ = run(jnp.bfloat16)
    assert loss_bf16 == pytest.approx(loss_f32, rel=2e-2)


def test_entrypoint_fsdp_tp_cli(devices):
    """dpp.py --fsdp --tp 2 end-to-end with streaming eval and host-
    gathered generation."""
    import sys

    sys.path.insert(0, "/root/repo")
    import dpp

    args = dpp.parse_args(
        [
            "--device", "cpu",
            "--model", "llama",
            "--layers", "2",
            "--d-model", "64",
            "--seq-len", "32",
            "--vocab-size", "64",
            "--fsdp",
            "--tp", "2",
            "--eval",
            "--generate", "8",
            "--epochs", "1",
            "--num-examples", "64",
            "--batch-size", "4",
            "--log-every", "1000",
        ]
    )
    loss = dpp.train(args)
    assert loss == loss


# --- multi-host host gather (VERDICT r3 item 3) ------------------------------


def _mp_fsdp_gather_worker(process_id: int, world: int, tmpdir: str):
    """2 OS processes x 2 CPU devices: FSDP train step, then the
    multi-host host=True gather — must equal the device-side (host=False)
    gather exactly, on every process."""
    import json
    import os

    import jax

    from distributeddataparallel_tpu.compat import configure_cpu_devices

    configure_cpu_devices(2)

    import jax.numpy as jnp
    import numpy as np
    import optax

    import distributeddataparallel_tpu as ddp
    from distributeddataparallel_tpu.models import TransformerLM, tiny_lm
    from distributeddataparallel_tpu.ops import lm_cross_entropy
    from distributeddataparallel_tpu.parallel.fsdp import (
        fsdp_gather_params,
        fsdp_state,
        make_fsdp_train_step,
    )

    ddp.init_process_group("cpu")
    assert jax.process_count() == world
    mesh = ddp.make_mesh(("data",))
    cfg = tiny_lm(
        num_layers=2, num_heads=2, d_model=32, d_ff=64, max_seq_len=32,
        vocab_size=64, scan_layers=True, dtype=jnp.float32, remat=True,
    )
    params = TransformerLM(cfg).init(
        jax.random.PRNGKey(0), jnp.zeros((1, 8), jnp.int32)
    )["params"]
    state = fsdp_state(cfg, params, optax.adam(1e-3), mesh)
    step = make_fsdp_train_step(cfg, mesh=mesh)
    rng = np.random.default_rng(0)
    toks = rng.integers(0, 64, size=(4 * mesh.shape["data"] // 4 * 4, 17))
    from distributeddataparallel_tpu.data.loader import shard_batch

    batch = shard_batch({"tokens": toks.astype(np.int32)}, mesh)
    state, metrics = step(state, batch, jax.random.PRNGKey(1))
    jax.block_until_ready(state.params)

    host_tree = fsdp_gather_params(cfg, state, mesh, host=True)
    dev_tree = fsdp_gather_params(cfg, state, mesh, host=False)
    mismatch = 0
    for h, d in zip(jax.tree.leaves(host_tree), jax.tree.leaves(dev_tree)):
        if not np.array_equal(np.asarray(h), np.asarray(d.addressable_data(0))):
            mismatch += 1
    checksum = float(
        sum(np.sum(np.asarray(l, np.float64)) for l in jax.tree.leaves(host_tree))
    )
    with open(os.path.join(tmpdir, f"g{process_id}.json"), "w") as f:
        json.dump(
            {"loss": float(metrics["loss"]), "mismatch": mismatch,
             "checksum": checksum},
            f,
        )
    ddp.destroy_process_group()


def test_multihost_fsdp_host_gather(tmp_path, devices):
    import functools
    import json

    from distributeddataparallel_tpu.runtime.launcher import (
        MULTIPROCESS_UNSUPPORTED_EXIT,
        guarded_worker,
        spawn,
    )

    procs = spawn(
        functools.partial(guarded_worker, _mp_fsdp_gather_worker),
        args=(2, str(tmp_path)), nprocs=2, join=False,
    )
    for p in procs:
        p.join(timeout=300)
    codes = [p.exitcode for p in procs]
    for p in procs:
        if p.is_alive():
            p.terminate()
    if MULTIPROCESS_UNSUPPORTED_EXIT in codes:
        pytest.skip(
            "this jaxlib's CPU backend cannot run multiprocess computations"
        )
    assert codes == [0, 0], f"child exit codes {codes}"
    r = [json.load(open(tmp_path / f"g{i}.json")) for i in range(2)]
    assert r[0]["mismatch"] == 0 and r[1]["mismatch"] == 0
    assert r[0]["checksum"] == pytest.approx(r[1]["checksum"], rel=1e-12)
    assert r[0]["loss"] == pytest.approx(r[1]["loss"], abs=1e-6)


def _mp_fsdp_generate_worker(process_id: int, tmpdir: str):
    """The end-to-end bar: dpp.py --fsdp --eval --generate across 2 real
    processes — exercises the multi-host host gather inside the CLI's
    full_params() path (generation) and the streaming masked eval."""
    import os

    import jax

    from distributeddataparallel_tpu.compat import configure_cpu_devices

    configure_cpu_devices(2)

    import sys

    sys.path.insert(0, "/root/repo")
    import dpp

    args = dpp.parse_args(
        [
            "--device", "cpu",
            "--model", "llama",
            "--layers", "2",
            "--d-model", "32",
            "--seq-len", "16",
            "--vocab-size", "64",
            "--fsdp",
            "--eval",
            "--generate", "8",
            "--epochs", "1",
            "--num-examples", "32",
            "--batch-size", "4",
            "--log-every", "1000",
        ]
    )
    loss = dpp.train(args)
    assert loss == loss
    with open(os.path.join(tmpdir, f"ok{process_id}"), "w") as f:
        f.write(str(loss))


def test_multihost_fsdp_generate_cli(tmp_path, devices):
    import functools

    from distributeddataparallel_tpu.runtime.launcher import (
        MULTIPROCESS_UNSUPPORTED_EXIT,
        guarded_worker,
        spawn,
    )

    procs = spawn(
        functools.partial(guarded_worker, _mp_fsdp_generate_worker),
        args=(str(tmp_path),), nprocs=2, join=False,
    )
    for p in procs:
        p.join(timeout=300)
    codes = [p.exitcode for p in procs]
    for p in procs:
        if p.is_alive():
            p.terminate()
    if MULTIPROCESS_UNSUPPORTED_EXIT in codes:
        pytest.skip(
            "this jaxlib's CPU backend cannot run multiprocess computations"
        )
    assert codes == [0, 0], f"child exit codes {codes}"
    assert (tmp_path / "ok0").exists() and (tmp_path / "ok1").exists()
