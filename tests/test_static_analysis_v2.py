"""ddplint v2: sharding-flow pass (SF2xx), schedule-as-data lint
(SL3xx), and the compile-only mesh simulator — mutation tests (each
seeded violation must fire its distinct rule id) plus the CLI/store
wiring.
"""

import json
import os
import subprocess
import sys

import jax
import jax.numpy as jnp
import optax
import pytest
from jax.sharding import PartitionSpec as P

import distributeddataparallel_tpu as ddp
from distributeddataparallel_tpu import compat
from distributeddataparallel_tpu.analysis import (
    mesh_sim,
    schedule_lint,
    shard_flow,
)
from distributeddataparallel_tpu.analysis.rules import RULES, Finding
from distributeddataparallel_tpu.analysis.schedule_lint import (
    grad_sync_schedule_ir,
    gpipe_schedule_ir,
    lint_schedule,
    one_f_one_b_schedule_ir,
    zb_schedule_ir,
)
from distributeddataparallel_tpu.observability import baseline as bl

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, os.path.join(REPO, "scripts"))
import ddplint  # noqa: E402
import perf_gate  # noqa: E402

# ---------------------------------------------------------------------
# sharding-flow pass (SF201-SF204)
# ---------------------------------------------------------------------

MAN_DP = {"mode": "dp", "grad_reduce": {"data": {"psum": (1, None)}}}
MAN_ZERO = {
    "mode": "zero",
    "grad_reduce": {"data": {"reduce_scatter": (1, None),
                             "psum": (0, None)}},
}
MAN_GATHER = {
    "mode": "fsdp",
    "grad_reduce": {"data": {"all_gather": (1, None),
                             "reduce_scatter": (1, None),
                             "psum": (0, None)}},
}


@pytest.fixture(scope="module")
def mesh(devices):
    return ddp.make_mesh(("data",))


def _lowered_text(fn, mesh, *args, in_specs, out_specs=P()):
    sm = compat.shard_map(
        fn, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
        check_vma=False,
    )
    return jax.jit(sm).lower(*args).as_text()


def test_sf201_replicated_gradient_anomaly(mesh):
    # a dp-style dense all_reduce linted under a ZeRO manifest: the
    # sharded-optimizer contract says gradient payloads reduce-scatter
    text = _lowered_text(
        lambda x: jax.lax.psum(x, "data"), mesh,
        jnp.ones((64,), jnp.float32), in_specs=(P("data"),),
    )
    rep = shard_flow.lint_flow(
        text, manifest=MAN_ZERO, grad_bytes_floor=16,
    )
    assert "SF201" in {f.rule for f in rep.findings}
    # the same program under its own dp manifest is clean
    assert shard_flow.lint_flow(text, manifest=MAN_DP).ok


def test_sf202_reshard_in_loop(mesh):
    # all_gather of a LOOP-INVARIANT value inside a fori_loop: the
    # gather hoists, paying wire bytes every iteration for nothing
    def body(w, x):
        def it(i, acc):
            full = jax.lax.all_gather(w, "data", tiled=True)
            return acc + jnp.sum(full) + x[0, 0]

        return jax.lax.fori_loop(0, 6, it, 0.0)

    text = _lowered_text(
        body, mesh,
        jnp.arange(64, dtype=jnp.float32), jnp.ones((8, 4), jnp.float32),
        in_specs=(P("data"), P("data")),
    )
    rep = shard_flow.lint_flow(text, manifest=MAN_DP)
    assert "SF202" in {f.rule for f in rep.findings}


def test_parse_module_recovers_loop_context(mesh):
    # XLA outlines fori_loop bodies into private functions called from
    # the while region — the parser must still see the gather as
    # in-loop with an invariant operand
    def body(w):
        def it(i, acc):
            return acc + jnp.sum(jax.lax.all_gather(w, "data", tiled=True))

        return jax.lax.fori_loop(0, 6, it, 0.0)

    text = _lowered_text(
        body, mesh, jnp.arange(64, dtype=jnp.float32),
        in_specs=(P("data"),),
    )
    _, colls = shard_flow.parse_module(text)
    gathers = [c for c in colls if c.op == "all_gather"]
    assert gathers, "lowering lost the all_gather"
    assert any(
        c.in_loop and any(c.loop_invariant_operands) for c in gathers
    )


def test_sf203_gather_exceeds_hbm_budget(mesh):
    text = _lowered_text(
        lambda x: jax.lax.all_gather(x, "data", tiled=True), mesh,
        jnp.ones((64,), jnp.float32), in_specs=(P("data"),),
        out_specs=P(),
    )
    # result is 64 x f32 = 256 bytes; a 100-byte "HBM" cannot hold it
    rep = shard_flow.lint_flow(
        text, manifest=MAN_GATHER, hbm_budget_bytes=100,
    )
    assert "SF203" in {f.rule for f in rep.findings}
    assert shard_flow.lint_flow(
        text, manifest=MAN_GATHER, hbm_budget_bytes=1 << 30,
    ).ok


def test_sf204_custom_vjp_hides_collective(mesh):
    @jax.custom_vjp
    def sneaky(x):
        return jax.lax.psum(x, "data")

    sneaky.defvjp(lambda x: (sneaky(x), None), lambda res, g: (g,))

    def prog(x):
        return jnp.sum(sneaky(x))

    sm = compat.shard_map(
        prog, mesh=mesh, in_specs=(P("data"),), out_specs=P(),
        check_vma=False,
    )
    jaxpr = jax.make_jaxpr(sm)(jnp.ones((64,), jnp.float32))
    found = shard_flow.lint_custom_vjp(
        jaxpr, manifest=MAN_DP, where="flow:test"
    )
    assert {f.rule for f in found} == {"SF204"}
    # the manifest waiver acknowledges an intentional in-vjp collective
    waived = shard_flow.lint_custom_vjp(
        jaxpr,
        manifest={**MAN_DP, "custom_vjp_collectives_ok": True},
        where="flow:test",
    )
    assert waived == []


def test_flow_clean_on_live_factories(mesh):
    from distributeddataparallel_tpu.training.train_step import (
        make_train_step,
    )

    params = {"w": jnp.ones((8, 4)), "b": jnp.ones((4,))}
    batch = {"x": jnp.ones((8, 8)), "y": jnp.ones((8, 4))}

    def loss_fn(p, b, _rng):
        pred = b["x"] @ p["w"] + p["b"]
        return jnp.mean((pred - b["y"]) ** 2), {}

    for kw in ({}, {"zero": True}):
        step = make_train_step(loss_fn, mesh=mesh, **kw)
        state = ddp.TrainState.create(
            apply_fn=None, params=params, tx=optax.sgd(0.1)
        )
        if kw.get("zero"):
            from distributeddataparallel_tpu.parallel.zero import (
                zero_state,
            )

            state = zero_state(
                apply_fn=None, params=params, tx=optax.sgd(0.1),
                mesh=mesh,
            )
        rep = shard_flow.analyze_step(
            step, state, batch, jax.random.PRNGKey(0)
        )
        assert rep.ok, [str(f) for f in rep.findings]
        assert rep.collectives


# ---------------------------------------------------------------------
# schedule-as-data lint (SL301-SL304)
# ---------------------------------------------------------------------


def test_1f1b_table_matches_factory_accounting():
    from distributeddataparallel_tpu.parallel.pipeline_parallel import (
        pp_bubble_fraction,
    )

    # independent derivations: table census vs the factory's closed form
    for n, m, v in [(2, 2, 1), (4, 8, 1), (4, 6, 1), (2, 4, 2),
                    (4, 8, 2), (3, 7, 1)]:
        ir = one_f_one_b_schedule_ir(n, m, v)
        acct = pp_bubble_fraction(n, m, v)
        assert abs(ir.bubble_fraction() - acct["bubble_fraction"]) < 5e-4, (
            (n, m, v)
        )
        assert lint_schedule(ir, bubble=acct) == []


def test_zb_table_matches_factory_accounting():
    from distributeddataparallel_tpu.parallel.pipeline_parallel import (
        pp_bubble_fraction,
    )

    # same cross-check for the zero-bubble table: the IR derives its
    # phase windows from its own unit extents, the factory from
    # _zb_segments — independent arithmetic that must agree exactly
    for n, m, v in [(2, 2, 1), (2, 4, 1), (4, 8, 1), (4, 16, 1),
                    (2, 4, 2), (4, 8, 2), (8, 32, 1), (3, 7, 1)]:
        ir = zb_schedule_ir(n, m, v)
        acct = pp_bubble_fraction(n, m, v, schedule="zb")
        assert abs(ir.bubble_fraction() - acct["bubble_fraction"]) < 5e-4, (
            (n, m, v)
        )
        assert lint_schedule(ir, bubble=acct) == [], (n, m, v)
        # zb keeps W work on the table: every (stage, chunk, microbatch)
        # triple contributes exactly one F, one B, and one W unit
        phases = [u.phase for u in ir.units]
        assert phases.count("F") == phases.count("B") == \
            phases.count("W") == n * m * v


def test_sl301_zb_w_before_b_fires():
    import dataclasses

    ir = zb_schedule_ir(4, 8)
    assert lint_schedule(ir) == []
    units = list(ir.units)
    # drag one W unit to before its B: weight grads need the incoming
    # cotangent, so a W ahead of its B is an impossible schedule
    for i, u in enumerate(units):
        if u.phase == "W" and u.tick > 0:
            units[i] = dataclasses.replace(u, tick=0)
            break
    broken = dataclasses.replace(ir, units=tuple(units))
    assert "SL301" in {f.rule for f in lint_schedule(broken)}


def test_sl302_zb_dropped_and_extra_hop_fire():
    ir = zb_schedule_ir(4, 8)
    manifest = {"grad_reduce": {ir.hop_axis: {"ppermute": (1, None)}}}
    assert ir.hops_total is not None
    ok = lint_schedule(ir, manifest=manifest, traced_hops=ir.hops_total)
    assert ok == [], [str(f) for f in ok]
    # dropped boundary hop (a ppermute optimized away / miscounted)
    assert "SL302" in {
        f.rule for f in lint_schedule(
            ir, manifest=manifest, traced_hops=ir.hops_total - 1
        )
    }
    # extra hop (double-send)
    assert "SL302" in {
        f.rule for f in lint_schedule(
            ir, manifest=manifest, traced_hops=ir.hops_total + 1
        )
    }


def test_sl304_zb_bubble_drift_fires():
    ir = zb_schedule_ir(4, 16)
    assert lint_schedule(ir, bubble=ir.bubble_fraction()) == []
    # seeded mutant: factory accounting that disagrees with the table
    assert "SL304" in {
        f.rule
        for f in lint_schedule(ir, bubble=ir.bubble_fraction() + 0.05)
    }


def test_sl301_missing_unit_fires():
    import dataclasses

    ir = gpipe_schedule_ir(4, 4)
    broken = dataclasses.replace(ir, units=ir.units[:-1])
    assert "SL301" in {f.rule for f in lint_schedule(broken)}


def test_sl301_backward_before_forward_fires():
    import dataclasses

    ir = one_f_one_b_schedule_ir(2, 2)
    units = list(ir.units)
    # find a B unit whose F is later in the warm-up and swap its tick
    # to before the matching forward
    for i, u in enumerate(units):
        if u.phase == "B" and u.tick > 0:
            units[i] = dataclasses.replace(u, tick=0)
            break
    broken = dataclasses.replace(ir, units=tuple(units))
    assert "SL301" in {f.rule for f in lint_schedule(broken)}


def test_sl302_undeclared_hop_and_count_mismatch():
    ir = grad_sync_schedule_ir(3)
    ok_manifest = {"grad_reduce": {"data": {"psum": (1, None)}}}
    assert lint_schedule(ir, manifest=ok_manifest, traced_hops=3) == []
    # hop primitive absent from the manifest's axis entry
    assert "SL302" in {
        f.rule for f in lint_schedule(ir, manifest={"grad_reduce": {}})
    }
    # exact-hop schedule traced with one extra collective (double sync)
    assert "SL302" in {
        f.rule
        for f in lint_schedule(ir, manifest=ok_manifest, traced_hops=4)
    }


def test_sl303_ring_too_small_fires():
    import dataclasses

    ir = one_f_one_b_schedule_ir(4, 8, virtual=2)
    assert lint_schedule(ir) == []
    broken = dataclasses.replace(
        ir, ring={"n_slots": 3, "modulus": ir.ring["modulus"]}
    )
    assert "SL303" in {f.rule for f in lint_schedule(broken)}


def test_sl304_bubble_drift_fires():
    ir = one_f_one_b_schedule_ir(4, 8)
    assert lint_schedule(ir, bubble=ir.bubble_fraction()) == []
    assert "SL304" in {
        f.rule
        for f in lint_schedule(ir, bubble=ir.bubble_fraction() + 0.05)
    }


def test_pp_factory_attaches_schedule_ir(devices):
    from distributeddataparallel_tpu.models import tiny_lm
    from distributeddataparallel_tpu.parallel import make_pp_train_step

    mesh2 = ddp.make_mesh(("data", "pipe"), shape=(2, 4))
    cfg = tiny_lm(
        num_layers=4, num_heads=2, d_model=32, d_ff=64,
        max_seq_len=32, scan_layers=True,
    )
    for schedule in ("gpipe", "1f1b", "zb"):
        step = make_pp_train_step(
            cfg, mesh=mesh2, microbatches=4, schedule=schedule,
        )
        ir = step.schedule_ir
        assert ir.kind == schedule
        assert ir.n_stages == 4 and ir.n_microbatches == 4
        findings = lint_schedule(
            ir,
            manifest=step.collective_manifest,
            bubble=step.bubble_accounting,
        )
        assert findings == [], [str(f) for f in findings]


def test_bucketed_step_attaches_comm_schedule(mesh):
    from distributeddataparallel_tpu.training.train_step import (
        make_train_step,
    )

    params = {"w": jnp.ones((8, 4)), "b": jnp.ones((4,))}

    def loss_fn(p, b, _rng):
        return jnp.mean((b["x"] @ p["w"] + p["b"]) ** 2), {}

    step = make_train_step(loss_fn, mesh=mesh, bucket_bytes=1 << 20)
    ir = step.comm_schedule(params)
    assert ir.kind == "grad-sync"
    assert ir.hop_prim == "psum" and ir.hop_axis == "data"
    assert lint_schedule(ir, manifest=step.collective_manifest) == []
    # unbucketed plain-dp steps carry no schedule IR
    plain = make_train_step(loss_fn, mesh=mesh)
    assert getattr(plain, "comm_schedule", None) is None


# ---------------------------------------------------------------------
# mesh simulation + baseline-store round trip
# ---------------------------------------------------------------------


def test_mesh_sim_record_roundtrips_store(devices, tmp_path):
    record = mesh_sim.simulate("cnn", "dp", batch_per_chip=2)
    assert record["record"] == "mesh_sim"
    assert record["devices"] == len(jax.devices())
    assert record["findings"] == []
    assert record["fit"]["fits"] is True
    assert record["headline"]["sim_required_bytes"] == \
        record["fit"]["required_bytes"]

    store = str(tmp_path / "runs")
    name = mesh_sim.fingerprint(record)
    bl.append_run(store, record, name=name, source="meshsim")
    runs = bl.read_runs(store)
    assert len(runs) == 1
    assert runs[0]["name"] == name
    assert runs[0]["headline"] == record["headline"]


def test_mesh_sim_record_gates_as_bench(devices, tmp_path):
    record = mesh_sim.simulate("cnn", "dp")
    path = tmp_path / "sim.json"
    path.write_text(json.dumps(record))
    flat, source = perf_gate.load_run(str(path))
    assert source == "bench"
    assert flat["sim_required_bytes"] == record["fit"]["required_bytes"]
    # every sim headline metric is bytes-suffixed -> lower-is-better
    metrics = perf_gate.gate_metrics_for(flat, source, 0.05)
    assert all(d == "lower" for d, _tol in metrics.values())


def test_mesh_sim_budget_miss_reported(devices):
    record = mesh_sim.simulate("cnn", "dp", hbm_budget_bytes=1024)
    assert record["fit"]["fits"] is False


@pytest.mark.slow
def test_meshsim_cli_worker_roundtrip(tmp_path):
    # one orchestrated case end to end in a fresh interpreter
    out = subprocess.run(
        [sys.executable, os.path.join(REPO, "scripts", "ddp_meshsim.py"),
         "--model", "cnn", "--mode", "dp", "--devices", "8", "--json",
         "--store", str(tmp_path / "runs")],
        capture_output=True, text=True, timeout=300,
    )
    assert out.returncode == 0, out.stderr[-2000:]
    record = json.loads(out.stdout.strip().splitlines()[-1])
    assert record["model"] == "cnn" and record["devices"] == 8
    assert bl.read_runs(str(tmp_path / "runs"))


# ---------------------------------------------------------------------
# ddplint CLI: --changed-only, --events-dir, rule-id registry gate
# ---------------------------------------------------------------------

_VIOLATION = "events.emit('sa2_ghost_kind', step=1)\n"


def _git(cwd, *argv):
    subprocess.run(
        ["git", "-c", "user.email=t@t", "-c", "user.name=t", *argv],
        cwd=cwd, check=True, capture_output=True,
    )


@pytest.fixture()
def lint_repo(tmp_path):
    """A tiny git repo shaped like the tree ddplint targets: dpp.py at
    the root plus a scripts/ dir, one committed violation in each."""
    (tmp_path / "scripts").mkdir()
    (tmp_path / "dpp.py").write_text("x = 1\n")
    (tmp_path / "scripts" / "util.py").write_text(_VIOLATION)
    (tmp_path / "README.md").write_text("hi\n")
    _git(tmp_path, "init", "-q")
    _git(tmp_path, "add", "-A")
    _git(tmp_path, "commit", "-qm", "seed")
    return tmp_path


def test_changed_only_dirty_tree_narrows_targets(lint_repo):
    # dirty file gains a violation; the committed violation in
    # scripts/util.py is untouched and must NOT be linted
    (lint_repo / "dpp.py").write_text(_VIOLATION)
    findings = ddplint.run_ast(True, root=lint_repo)
    assert findings and all(f.where.startswith("dpp.py") for f in findings)
    # the full run still sees both
    full = ddplint.run_ast(False, root=lint_repo)
    assert {f.where.split(":")[0] for f in full} == {
        "dpp.py", "scripts/util.py"
    }


def test_changed_only_renamed_file_lints_new_path(lint_repo):
    _git(lint_repo, "mv", "scripts/util.py", "scripts/renamed.py")
    (lint_repo / "scripts" / "renamed.py").write_text(_VIOLATION)
    findings = ddplint.run_ast(True, root=lint_repo)
    assert findings
    assert all(
        f.where.startswith("scripts/renamed.py") for f in findings
    )


def test_changed_only_no_python_changes(lint_repo, monkeypatch, capsys):
    (lint_repo / "README.md").write_text("only docs changed\n")
    assert ddplint.run_ast(True, root=lint_repo) == []
    # the graph layer is skipped outright: no step-defining paths moved
    monkeypatch.setattr(ddplint, "ROOT", lint_repo)
    assert ddplint.main(["--graph", "--changed-only"]) == 0
    out = capsys.readouterr().out
    assert "skipped (no step-defining changes)" in out


def test_events_dir_emits_schema_valid_lint_report(tmp_path, capsys):
    from distributeddataparallel_tpu.observability.schema import (
        validate_file,
    )

    assert ddplint.main(
        ["--ast", "--events-dir", str(tmp_path)]
    ) == 0
    path = tmp_path / "events-lint.jsonl"
    assert path.exists()
    assert validate_file(path) == []
    recs = [json.loads(l) for l in path.read_text().splitlines()]
    assert [r["kind"] for r in recs] == ["lint_report"]
    assert recs[0]["layer"] == "ast" and recs[0]["n_findings"] == 0


def test_unregistered_rule_id_is_operational_error(monkeypatch, capsys):
    monkeypatch.setattr(
        ddplint, "run_ast",
        lambda *a, **k: [Finding("ZZ999", "x.py:1", "made-up rule")],
    )
    assert ddplint.main(["--ast"]) == 2
    assert "ZZ999" in capsys.readouterr().err


def test_new_rules_registered():
    for rid in ("SF201", "SF202", "SF203", "SF204",
                "SL301", "SL302", "SL303", "SL304"):
        assert rid in RULES, rid
