"""ZeRO-2/3 sharded weight update: bitwise parity with replicated DP,
per-device memory reduction, flat-layout dtype policy, low-bit optimizer
moments, and the comm-schedule / manifest lint wiring."""

import math

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

import distributeddataparallel_tpu as ddp
from distributeddataparallel_tpu.data.loader import shard_batch
from distributeddataparallel_tpu.models import TinyMLP
from distributeddataparallel_tpu.ops import cross_entropy_loss
from distributeddataparallel_tpu.parallel import zero


def _setup(n_batches=5, seed=0):
    mesh = ddp.make_mesh(("data",))
    model = TinyMLP(num_classes=10)
    params = model.init(jax.random.PRNGKey(0), jnp.zeros((1, 32, 32, 3)))[
        "params"
    ]

    def loss_fn(params, batch, rng):
        logits = model.apply({"params": params}, batch["image"])
        return cross_entropy_loss(logits, batch["label"]), {}

    rng = np.random.default_rng(seed)
    batches = [
        shard_batch(
            {
                "image": rng.normal(size=(16, 32, 32, 3)).astype(np.float32),
                "label": rng.integers(0, 10, size=(16,)).astype(np.int32),
            },
            mesh,
        )
        for _ in range(n_batches)
    ]
    return mesh, model, params, loss_fn, batches


def _dp_state(model, params, mesh, tx):
    state = ddp.TrainState.create(apply_fn=model.apply, params=params, tx=tx)
    return ddp.broadcast_params(state, mesh)


@pytest.mark.parametrize(
    "tx_fn", [lambda: optax.adam(1e-2), lambda: optax.adamw(1e-2)],
    ids=["adam", "adamw"],
)
def test_zero23_bitwise_parity_with_dp(tx_fn, devices):
    """dp, zero2, and zero3 run the same math: after 5 steps the params
    are BITWISE equal (CPU psum/psum_scatter reduction orders agree; the
    bucketed layout only re-chunks the same flat reduction)."""
    mesh, model, params, loss_fn, batches = _setup()

    s_dp = _dp_state(model, params, mesh, tx_fn())
    step_dp = ddp.make_train_step(loss_fn, mesh=mesh, donate=False)

    params_r = ddp.broadcast_params(params, mesh)
    s_z2 = ddp.zero_state(
        apply_fn=model.apply, params=params_r, tx=tx_fn(), mesh=mesh, level=2
    )
    step_z2 = ddp.make_train_step(loss_fn, mesh=mesh, zero=2, donate=False)

    s_z3 = ddp.zero_state(
        apply_fn=model.apply, params=params_r, tx=tx_fn(), mesh=mesh, level=3
    )
    step_z3 = ddp.make_train_step(loss_fn, mesh=mesh, zero=3, donate=False)

    for b in batches:
        s_dp, m_dp = step_dp(s_dp, b, jax.random.PRNGKey(0))
        s_z2, m_z2 = step_z2(s_z2, b, jax.random.PRNGKey(0))
        s_z3, m_z3 = step_z3(s_z3, b, jax.random.PRNGKey(0))
        assert float(m_dp["loss"]) == pytest.approx(
            float(m_z2["loss"]), rel=1e-6
        )
        assert float(m_dp["loss"]) == pytest.approx(
            float(m_z3["loss"]), rel=1e-6
        )

    for a, b in zip(
        jax.tree.leaves(s_dp.params), jax.tree.leaves(s_z2.params)
    ):
        assert np.array_equal(np.asarray(a), np.asarray(b))

    z3_params = zero.zero3_gather_params(s_z3, mesh)
    for a, b in zip(
        jax.tree.leaves(s_dp.params), jax.tree.leaves(z3_params)
    ):
        assert np.array_equal(np.asarray(a), np.asarray(b))


def _perdevice_state_bytes(state) -> int:
    """Busiest device's resident bytes for (params, opt_state) — the
    live-array HWM arithmetic restricted to one state."""
    per: dict = {}
    for leaf in jax.tree.leaves((state.params, state.opt_state)):
        itemsize = leaf.dtype.itemsize
        for s in leaf.addressable_shards:
            per[s.device.id] = per.get(s.device.id, 0) + int(
                math.prod(s.data.shape) * itemsize
            )
    return max(per.values())


def test_zero23_perdevice_state_bytes_drop(devices):
    """The memory claim, measured on real shardings: adam state per
    device drops from ~3P (dp) to ~P + 2P/8 (zero2) to ~3P/8 (zero3)."""
    mesh, model, params, loss_fn, _ = _setup(n_batches=0)
    params_r = ddp.broadcast_params(params, mesh)

    dp = _perdevice_state_bytes(
        _dp_state(model, params, mesh, optax.adam(1e-2))
    )
    z2 = _perdevice_state_bytes(ddp.zero_state(
        apply_fn=model.apply, params=params_r, tx=optax.adam(1e-2),
        mesh=mesh, level=2,
    ))
    z3 = _perdevice_state_bytes(ddp.zero_state(
        apply_fn=model.apply, params=params_r, tx=optax.adam(1e-2),
        mesh=mesh, level=3,
    ))
    n = mesh.shape["data"]
    assert z2 < 0.6 * dp            # >=25% drop criterion, with margin
    assert z3 < 0.6 * z2            # params sharding wins again
    # ~3P/8 per device at zero3: within 20% of the analytic figure
    assert z3 < 3 * dp / 3 / n * 1.2


def test_flatten_cast_modes():
    f32_tree = {"a": jnp.ones((4,), jnp.float32),
                "b": jnp.ones((3,), jnp.float32)}
    bf16_tree = jax.tree.map(lambda x: x.astype(jnp.bfloat16), f32_tree)
    mixed = {"a": f32_tree["a"], "b": bf16_tree["b"]}
    padded = 8

    # default: explicit f32 master (upcast), back-compat positional call
    flat = zero.flatten_f32(bf16_tree, padded)
    assert flat.dtype == jnp.float32 and flat.shape == (padded,)

    # preserve: uniform non-f32 master keeps its dtype
    flat = zero.flatten_f32(bf16_tree, padded, cast="preserve")
    assert flat.dtype == jnp.bfloat16

    with pytest.raises(TypeError, match="mixes dtypes"):
        zero.flatten_f32(mixed, padded, cast="preserve")
    with pytest.raises(TypeError, match="non-f32"):
        zero.flatten_f32(bf16_tree, padded, cast="strict")
    assert zero.flatten_f32(f32_tree, padded, cast="strict").dtype \
        == jnp.float32
    with pytest.raises(ValueError, match="unknown cast"):
        zero.flatten_f32(f32_tree, padded, cast="bf16")


@pytest.mark.parametrize("moment_dtype", ["bf16", "int8"])
def test_low_bit_moments_convergence(moment_dtype, devices):
    """Stochastically-rounded low-bit moments track f32 training: after
    50 zero2 steps the loss stays within tolerance of the f32-moment
    run (the error-compensation claim — deterministic truncation would
    visibly stall adam's small-update tail)."""
    mesh, model, params, loss_fn, batches = _setup(n_batches=10, seed=1)
    params_r = ddp.broadcast_params(params, mesh)

    def run(md):
        # fresh step per run: the low-bit tx wrapper changes the state's
        # pytree metadata, so the cached spec tree can't be shared
        step = ddp.make_train_step(loss_fn, mesh=mesh, zero=2, donate=False)
        s = ddp.zero_state(
            apply_fn=model.apply, params=params_r, tx=optax.adam(1e-2),
            mesh=mesh, level=2, moment_dtype=md,
        )
        loss = None
        for i in range(50):
            s, m = step(s, batches[i % len(batches)], jax.random.PRNGKey(0))
            loss = float(m["loss"])
        return loss

    ref = run(None)
    low = run(moment_dtype)
    # both must have actually trained, and agree to ~10%
    first = float(
        ddp.make_train_step(loss_fn, mesh=mesh, donate=False)(
            _dp_state(model, params, mesh, optax.adam(1e-2)),
            batches[0], jax.random.PRNGKey(0),
        )[1]["loss"]
    )
    assert ref < 0.1 * first
    assert low < 0.1 * first
    # near-zero losses: tolerance needs an absolute floor (both runs
    # land at ~1e-4 where 10% relative would be noise-level)
    assert abs(low - ref) <= max(0.1 * ref, 0.01)


def test_low_bit_moments_state_is_compressed(devices):
    from distributeddataparallel_tpu.ops.quant import Q8Moment

    mesh, model, params, loss_fn, _ = _setup(n_batches=0)
    params_r = ddp.broadcast_params(params, mesh)
    for md, pred in (
        ("bf16", lambda l: getattr(l, "dtype", None) == jnp.bfloat16),
        ("int8", lambda l: isinstance(l, Q8Moment)),
    ):
        s = ddp.zero_state(
            apply_fn=model.apply, params=params_r, tx=optax.adam(1e-2),
            mesh=mesh, level=2, moment_dtype=md,
        )
        leaves = jax.tree.flatten(
            s.opt_state, is_leaf=lambda x: isinstance(x, Q8Moment)
        )[0]
        assert any(pred(l) for l in leaves), md
    with pytest.raises(ValueError, match="moment_dtype"):
        zero.low_bit_moments(optax.adam(1e-2), "fp8")


def _traced_hops(step, state, batch, rng, ir):
    from distributeddataparallel_tpu.analysis.graph_lint import (
        collect_collectives,
    )

    jaxpr = jax.make_jaxpr(step)(state, batch, rng)
    return sum(
        c.effective_count
        for c in collect_collectives(jaxpr)
        if c.prim == ir.hop_prim and ir.hop_axis in c.axes and c.nonscalar
    )


@pytest.mark.parametrize(
    "level,accum,prim",
    [(2, 1, "reduce_scatter"), (3, 1, "all_gather"), (3, 2, "all_gather")],
    ids=["zero2", "zero3", "zero3-accum2"],
)
def test_zero23_comm_schedule_matches_trace(level, accum, prim, devices):
    """The schedule-as-data contract: the attached IR's tick count
    equals the traced per-bucket hop count (trip-multiplied through the
    accum scan for zero3's in-loop gathers), and SL302 stays quiet."""
    from distributeddataparallel_tpu.analysis.schedule_lint import (
        lint_schedule,
    )

    mesh, model, params, loss_fn, batches = _setup(n_batches=1)
    params_r = ddp.broadcast_params(params, mesh)
    state = ddp.zero_state(
        apply_fn=model.apply, params=params_r, tx=optax.adam(1e-2),
        mesh=mesh, level=level,
    )
    step = ddp.make_train_step(
        loss_fn, mesh=mesh, zero=level, donate=False, accum_steps=accum
    )
    ir = step.comm_schedule(state.params)
    assert ir.hop_prim == prim

    n = mesh.shape["data"]
    nb = (
        state.params.meta.plan.n_buckets
        if level == 3 else zero.bucket_plan(params, n).n_buckets
    )
    assert ir.ticks == nb * (accum if level == 3 else 1)

    hops = _traced_hops(step, state, batches[0], jax.random.PRNGKey(0), ir)
    assert hops == ir.ticks
    clean = lint_schedule(
        ir, manifest=step.collective_manifest, traced_hops=hops
    )
    assert not clean, [str(f) for f in clean] if clean else None


@pytest.mark.parametrize(
    "level,prim", [(2, "reduce_scatter"), (3, "all_gather")],
    ids=["zero2", "zero3"],
)
def test_sl302_mutations_caught(level, prim, devices):
    """Mutation tests, one per SL302 rule path: (a) a manifest that
    dropped the hop prim; (b) a traced count one hop short (a dropped or
    reordered bucket collective)."""
    from distributeddataparallel_tpu.analysis.schedule_lint import (
        lint_schedule,
    )

    mesh, model, params, loss_fn, batches = _setup(n_batches=1)
    params_r = ddp.broadcast_params(params, mesh)
    state = ddp.zero_state(
        apply_fn=model.apply, params=params_r, tx=optax.adam(1e-2),
        mesh=mesh, level=level,
    )
    step = ddp.make_train_step(loss_fn, mesh=mesh, zero=level, donate=False)
    ir = step.comm_schedule(state.params)
    hops = _traced_hops(step, state, batches[0], jax.random.PRNGKey(0), ir)

    # (a) manifest mutation: the hop prim vanishes from the declaration
    import copy

    mutated = copy.deepcopy(step.collective_manifest)
    mutated["grad_reduce"]["data"].pop(prim)
    findings = lint_schedule(ir, manifest=mutated, traced_hops=hops)
    assert any(f.rule == "SL302" for f in findings)

    # (b) trace mutation: one bucket hop missing
    findings = lint_schedule(
        ir, manifest=step.collective_manifest, traced_hops=hops - 1
    )
    assert any(f.rule == "SL302" for f in findings)


def test_zero2_manifest_catches_dense_allreduce(devices):
    """The seeded acceptance mutation: a step that still dense-psums its
    gradients, linted against the zero2 manifest (which promises
    reduce_scatter and bounds psum at 0), trips GL001."""
    from distributeddataparallel_tpu.analysis.graph_lint import (
        lint_train_step,
    )

    mesh, model, params, loss_fn, batches = _setup(n_batches=1)
    s_dp = _dp_state(model, params, mesh, optax.adam(1e-2))
    step_dp = ddp.make_train_step(loss_fn, mesh=mesh, donate=False)
    step_z2 = ddp.make_train_step(loss_fn, mesh=mesh, zero=2, donate=False)

    report = lint_train_step(
        step_dp, s_dp, batches[0], jax.random.PRNGKey(0),
        manifest=step_z2.collective_manifest,
    )
    assert any(f.rule == "GL001" for f in report.findings)

    # and the real zero2 step is clean against its own manifest
    s_z2 = ddp.zero_state(
        apply_fn=model.apply,
        params=ddp.broadcast_params(params, mesh),
        tx=optax.adam(1e-2), mesh=mesh, level=2,
    )
    report = lint_train_step(
        step_z2, s_z2, batches[0], jax.random.PRNGKey(0)
    )
    assert not [f for f in report.findings if f.rule == "GL001"]


def test_zero23_level_and_axis_rejections(devices):
    mesh, model, params, loss_fn, _ = _setup(n_batches=0)
    with pytest.raises(ValueError, match="level"):
        ddp.zero_state(
            apply_fn=model.apply, params=params, tx=optax.adam(1e-2),
            mesh=mesh, level=4,
        )
    with pytest.raises(ValueError, match="data axis only"):
        ddp.zero_state(
            apply_fn=model.apply, params=params, tx=optax.adam(1e-2),
            mesh=mesh, level=2, tp_axis="model",
        )
    with pytest.raises(ValueError, match="data axis only"):
        ddp.make_train_step(loss_fn, mesh=mesh, zero=3, tp_axis="model")
    with pytest.raises(ValueError):
        ddp.make_train_step(loss_fn, mesh=mesh, zero=1, bucket_bytes=1 << 20)
    # levels 2/3 DO take bucket_bytes (granularity knob)
    ddp.make_train_step(loss_fn, mesh=mesh, zero=2, bucket_bytes=1 << 16)


@pytest.mark.parametrize("level", [1, 2, 3])
def test_zero_state_step_rides_the_mesh(level, devices):
    """The step counter must be COMMITTED replicated on the mesh at
    every level: checkpoint restore uses template shardings
    leaf-for-leaf, and an uncommitted scalar comes back committed to
    device 0 — unsteppable next to mesh-committed params (the
    --zero ... --resume crash)."""
    mesh, model, params, loss_fn, _ = _setup(n_batches=0)
    s = ddp.zero_state(
        apply_fn=model.apply,
        params=ddp.broadcast_params(params, mesh),
        tx=optax.adam(1e-2), mesh=mesh, level=level,
    )
    assert s.step.committed
    assert len(s.step.sharding.device_set) == len(mesh.devices.flat)


def test_zero3_shard_gather_roundtrip(devices):
    """zero_state(level=3) followed by zero3_gather_params is the
    identity on the param tree (exact slicing, bitwise)."""
    mesh, model, params, loss_fn, _ = _setup(n_batches=0)
    params_r = ddp.broadcast_params(params, mesh)
    s = ddp.zero_state(
        apply_fn=model.apply, params=params_r, tx=optax.adam(1e-2),
        mesh=mesh, level=3,
    )
    back = zero.zero3_gather_params(s, mesh)
    for a, b in zip(jax.tree.leaves(params), jax.tree.leaves(back)):
        assert np.array_equal(np.asarray(a), np.asarray(b))
