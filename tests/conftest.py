"""Test harness: force 8 fake CPU devices before JAX backends initialize.

This is the JAX-native analog of torch's fake process group (SURVEY.md §4):
every DP test — psum correctness, sampler semantics, grad-accum boundaries,
the DDP equivalence invariant — runs on an 8-device CPU mesh in one process,
no cluster needed.

Note: this environment pre-imports jax via sitecustomize (TPU plugin), so
env-var selection (JAX_PLATFORMS/XLA_FLAGS) is captured before pytest runs;
``jax.config.update`` still works because no backend is initialized yet.
"""

import jax
import pytest

jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_num_cpu_devices", 8)


@pytest.fixture(scope="session")
def devices():
    devs = jax.devices()
    assert len(devs) == 8, f"expected 8 fake CPU devices, got {len(devs)}"
    return devs
