"""Test harness: force 8 fake CPU devices before JAX backends initialize.

This is the JAX-native analog of torch's fake process group (SURVEY.md §4):
every DP test — psum correctness, sampler semantics, grad-accum boundaries,
the DDP equivalence invariant — runs on an 8-device CPU mesh in one process,
no cluster needed.

Note: this environment pre-imports jax via sitecustomize (TPU plugin), so
env-var selection (JAX_PLATFORMS/XLA_FLAGS) is captured before pytest runs;
``jax.config.update`` still works because no backend is initialized yet.
"""

import jax
import pytest

jax.config.update("jax_platforms", "cpu")
try:
    jax.config.update("jax_num_cpu_devices", 8)
except AttributeError:
    # Older jax has no jax_num_cpu_devices option; the pre-backend-init
    # XLA flag is the equivalent (read when the CPU client is created,
    # which hasn't happened yet at conftest import time).
    import os

    _flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in _flags:
        os.environ["XLA_FLAGS"] = (
            _flags + " --xla_force_host_platform_device_count=8"
        ).strip()


def pytest_configure(config):
    config.addinivalue_line(
        "markers",
        "slow: excluded from the tier-1 gate (-m 'not slow'); run "
        "explicitly or via the dedicated CI stage",
    )


@pytest.fixture(scope="session")
def devices():
    devs = jax.devices()
    assert len(devs) == 8, f"expected 8 fake CPU devices, got {len(devs)}"
    return devs


@pytest.fixture(scope="module", autouse=True)
def _bounded_jit_cache():
    """Drop prior modules' compiled executables at each module start.

    A ~280-test run accumulates hundreds of executables in one process;
    a full-suite run once hit an XLA:CPU runtime abort deep in the
    pipeline module that never reproduces standalone or in the module's
    own run.  Bounding the live cache to ~one module's worth keeps the
    suite's memory/runtime state shaped like the per-module runs that
    are known good, while preserving within-module cache reuse."""
    jax.clear_caches()
    yield
