"""ZeRO-1 weight-update sharding tests: exact equivalence with replicated
DP, sharded opt-state layout, and grad-accumulation composition."""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest
from jax.sharding import NamedSharding, PartitionSpec as P

import distributeddataparallel_tpu as ddp
from distributeddataparallel_tpu.data.loader import shard_batch
from distributeddataparallel_tpu.models import TinyMLP
from distributeddataparallel_tpu.ops import cross_entropy_loss
from distributeddataparallel_tpu.parallel import zero


def _setup(devices):
    mesh = ddp.make_mesh(("data",))
    model = TinyMLP(num_classes=10)
    params = model.init(jax.random.PRNGKey(0), jnp.zeros((1, 32, 32, 3)))[
        "params"
    ]

    def loss_fn(params, batch, rng):
        logits = model.apply({"params": params}, batch["image"])
        return cross_entropy_loss(logits, batch["label"]), {}

    rng = np.random.default_rng(0)
    batches = [
        shard_batch(
            {
                "image": rng.normal(size=(16, 32, 32, 3)).astype(np.float32),
                "label": rng.integers(0, 10, size=(16,)).astype(np.int32),
            },
            mesh,
        )
        for _ in range(5)
    ]
    return mesh, model, params, loss_fn, batches


@pytest.mark.parametrize(
    "tx_fn",
    [
        lambda: optax.sgd(0.1, momentum=0.9),
        lambda: optax.adam(1e-2),
        lambda: optax.adamw(1e-2, weight_decay=0.01),
    ],
    ids=["sgd-momentum", "adam", "adamw"],
)
def test_zero_matches_replicated_dp(tx_fn, devices):
    """The defining property: ZeRO sharding changes memory layout, not math.

    N-way ZeRO params after k steps == replicated-DP params after k steps.
    """
    mesh, model, params, loss_fn, batches = _setup(devices)

    state_dp = ddp.TrainState.create(
        apply_fn=model.apply, params=params, tx=tx_fn()
    )
    state_dp = ddp.broadcast_params(state_dp, mesh)
    step_dp = ddp.make_train_step(loss_fn, mesh=mesh, donate=False)

    params_z = ddp.broadcast_params(params, mesh)
    state_z = ddp.zero_state(
        apply_fn=model.apply, params=params_z, tx=tx_fn(), mesh=mesh
    )
    step_z = ddp.make_train_step(loss_fn, mesh=mesh, zero=True, donate=False)

    for b in batches:
        state_dp, m_dp = step_dp(state_dp, b, jax.random.PRNGKey(0))
        state_z, m_z = step_z(state_z, b, jax.random.PRNGKey(0))
        assert float(m_dp["loss"]) == pytest.approx(
            float(m_z["loss"]), rel=1e-6
        )
    for a, b in zip(
        jax.tree.leaves(state_dp.params), jax.tree.leaves(state_z.params)
    ):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=1e-5)


def test_zero_opt_state_is_sharded(devices):
    mesh = ddp.make_mesh(("data",))
    model = TinyMLP(num_classes=10)
    params = model.init(jax.random.PRNGKey(0), jnp.zeros((1, 32, 32, 3)))[
        "params"
    ]
    params = ddp.broadcast_params(params, mesh)
    state = ddp.zero_state(
        apply_fn=model.apply, params=params, tx=optax.adam(1e-3), mesh=mesh
    )
    n = mesh.shape["data"]
    padded, chunk = zero.flat_size(params, n)
    vec_leaves = [
        l for l in jax.tree.leaves(state.opt_state) if l.ndim >= 1
    ]
    assert len(vec_leaves) == 2  # adam mu, nu
    for leaf in vec_leaves:
        assert leaf.shape == (padded,)
        # each device holds only its 1/N chunk
        assert leaf.sharding.spec == P("data")
        shard_shapes = {s.data.shape for s in leaf.addressable_shards}
        assert shard_shapes == {(chunk,)}


def test_zero_with_grad_accumulation(devices):
    mesh, model, params, loss_fn, batches = _setup(devices)
    params = ddp.broadcast_params(params, mesh)
    state = ddp.zero_state(
        apply_fn=model.apply, params=params, tx=optax.sgd(0.1), mesh=mesh
    )
    step = ddp.make_train_step(loss_fn, mesh=mesh, zero=True, accum_steps=2)
    losses = []
    # Repeatedly fit ONE batch: with 5 distinct noise batches the
    # per-batch loss is not monotonic (nothing generalizes from noise),
    # so descending on a fixed batch is the property that actually
    # tests the accumulated-ZeRO step optimizes.
    for _ in batches:
        state, metrics = step(state, batches[0], jax.random.PRNGKey(0))
        losses.append(float(metrics["loss"]))
    assert losses[-1] < losses[0]


def test_flatten_roundtrip():
    tree = {
        "a": jnp.arange(5, dtype=jnp.float32),
        "b": jnp.ones((2, 3), jnp.bfloat16),
    }
    padded, chunk = zero.flat_size(tree, 8)
    assert padded == 16 and chunk == 2
    flat = zero.flatten_f32(tree, padded)
    assert flat.shape == (16,)
    back = zero.unflatten(flat, tree)
    for a, b in zip(jax.tree.leaves(back), jax.tree.leaves(tree)):
        assert a.dtype == b.dtype
        np.testing.assert_allclose(
            np.asarray(a, np.float32), np.asarray(b, np.float32)
        )
