"""Comm/compute overlap machinery (parallel/overlap.py; VERDICT r3 item 1).

Three layers of coverage, matched to what each fabric can prove:

- *Numerics* (CPU mesh): chained reverse-order buckets are bit-for-bit a
  gradient mean — chain ordering and the optimization barriers move no
  data; the overlapped train step matches the stock DP step.
- *Schedule parser*: ``schedule_report`` extracts windows/cycles from
  scheduled-HLO text (exercised on a canned snippet — no TPU needed).
- *TPU schedule evidence*: AOT-compile for a multi-chip TPU topology via
  ``jax.experimental.topologies`` and assert nonzero scheduled overlap.
  Skipped where no TPU compiler is importable (the CI CPU mesh) — the
  committed OVERLAP.md artifact carries the recorded result.
"""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest
from jax.sharding import PartitionSpec as P

import distributeddataparallel_tpu as ddp
from distributeddataparallel_tpu.parallel.data_parallel import (
    all_reduce_gradients,
    bucket_gradients,
)
from distributeddataparallel_tpu.parallel.overlap import (
    cpu_fabric_note,
    schedule_report,
)
from distributeddataparallel_tpu.runtime.distributed import make_mesh


def _grad_tree(key):
    sizes = ((64, 64), (7,), (33, 5), (256,), (2, 3, 4))
    keys = jax.random.split(key, len(sizes))
    return {
        f"p{i}": jax.random.normal(k, s)
        for i, (k, s) in enumerate(zip(keys, sizes))
    }


def test_chained_buckets_equal_plain_mean(devices):
    mesh = make_mesh(("data",))
    n = mesh.shape["data"]
    trees = [_grad_tree(jax.random.PRNGKey(40 + i)) for i in range(n)]
    stacked = jax.tree.map(lambda *xs: jnp.stack(xs), *trees)

    def f(shard):
        local = jax.tree.map(lambda x: x[0], shard)
        return bucket_gradients(
            local, "data", bucket_bytes=1024, chain=True
        )

    out = jax.jit(
        jax.shard_map(f, mesh=mesh, in_specs=(P("data"),), out_specs=P(),
                      check_vma=False)
    )(stacked)
    expected = jax.tree.map(lambda *xs: jnp.mean(jnp.stack(xs), 0), *trees)
    for k in expected:
        np.testing.assert_allclose(out[k], expected[k], rtol=1e-6, atol=1e-7)


def test_chained_buckets_mixed_dtypes(devices):
    """bf16 leaves reduce in native dtype, f32 in f32; chain still exact
    to a plain pmean done at matching precision."""
    mesh = make_mesh(("data",))
    n = mesh.shape["data"]
    trees = [
        {
            "a": jax.random.normal(jax.random.PRNGKey(50 + i), (64, 8)),
            "b": jax.random.normal(
                jax.random.PRNGKey(80 + i), (16, 16)
            ).astype(jnp.bfloat16),
        }
        for i in range(n)
    ]
    stacked = jax.tree.map(lambda *xs: jnp.stack(xs), *trees)

    def f(shard):
        local = jax.tree.map(lambda x: x[0], shard)
        return all_reduce_gradients(local, "data", chain=True)

    out = jax.jit(
        jax.shard_map(f, mesh=mesh, in_specs=(P("data"),), out_specs=P(),
                      check_vma=False)
    )(stacked)
    exp_a = jnp.mean(jnp.stack([t["a"] for t in trees]), 0)
    exp_b = (
        sum(t["b"].astype(jnp.float32) for t in trees) / n
    )
    np.testing.assert_allclose(out["a"], exp_a, rtol=1e-6, atol=1e-7)
    assert out["b"].dtype == jnp.bfloat16
    # bf16-accumulated sum: loose tolerance, but the value must be the mean
    np.testing.assert_allclose(
        out["b"].astype(jnp.float32), exp_b, rtol=0.05, atol=0.05
    )


def test_overlap_train_step_matches_stock(devices):
    """overlap=True is a pure schedule change: same loss, same params."""
    mesh = make_mesh(("data",))

    def loss_fn(params, batch, rng):
        pred = batch["x"] @ params["w"] + params["b"]
        return jnp.mean((pred - batch["y"]) ** 2), {}

    params = {
        "w": jax.random.normal(jax.random.PRNGKey(0), (16, 4)),
        "b": jnp.zeros((4,)),
    }
    batch = {
        "x": jax.random.normal(jax.random.PRNGKey(1), (32, 16)),
        "y": jax.random.normal(jax.random.PRNGKey(2), (32, 4)),
    }
    dp = ddp.DataParallel(mesh)
    sharded = dp.shard_batch(batch)

    outs = {}
    for name, kw in (
        ("stock", {}),
        ("overlap", {"overlap": True}),
        ("overlap_accum", {"overlap": True, "accum_steps": 2}),
        ("overlap_clip", {"overlap": True, "grad_clip": 0.5}),
    ):
        state = ddp.TrainState.create(
            apply_fn=None, params=jax.tree.map(jnp.copy, params),
            tx=optax.sgd(0.1),
        )
        state = ddp.broadcast_params(state, mesh)
        step = ddp.make_train_step(loss_fn, mesh=mesh, donate=False, **kw)
        new_state, metrics = step(state, sharded, jax.random.PRNGKey(3))
        outs[name] = (new_state.params, float(metrics["loss"]))

    np.testing.assert_allclose(
        outs["stock"][1], outs["overlap"][1], rtol=1e-6
    )
    for k in params:
        np.testing.assert_allclose(
            outs["stock"][0][k], outs["overlap"][0][k], rtol=1e-6, atol=1e-7
        )
    # accum/clip variants: different math (by design); loss finite + params sane
    for name in ("overlap_accum", "overlap_clip"):
        assert np.isfinite(outs[name][1])


def test_scan_body_grad_sync_matches_stock(devices):
    """grad_sync_axis (in-scan-body pmean via sync_grad_in_backward) +
    presynced skip-list in the step == the stock DP step, bit-for-bit in
    params and loss — the reduction moves INTO the backward while loop,
    the math doesn't change."""
    import jax.numpy as jnp

    from distributeddataparallel_tpu.data.loader import shard_batch
    from distributeddataparallel_tpu.models import TransformerLM, tiny_lm
    from distributeddataparallel_tpu.ops import lm_cross_entropy

    mesh = make_mesh(("data",))
    n = mesh.shape["data"]
    seq = 16

    def build(grad_sync_axis, remat):
        cfg = tiny_lm(
            max_seq_len=seq, scan_layers=True, remat=remat,
            grad_sync_axis=grad_sync_axis,
        )
        model = TransformerLM(cfg)

        def loss_fn(params, batch, rng):
            toks = batch["tokens"]
            logits = model.apply({"params": params}, toks[:, :-1])
            return lm_cross_entropy(logits, toks[:, 1:]), {}

        return model, loss_fn

    toks = np.asarray(
        jax.random.randint(
            jax.random.PRNGKey(7), (4 * n, seq + 1), 0, 256
        ),
        np.int32,
    )
    for remat in (False, True):
        model0, loss0 = build(None, remat)
        model1, loss1 = build("data", remat)
        params = model0.init(
            jax.random.PRNGKey(0), jnp.zeros((1, seq), jnp.int32)
        )["params"]
        # the map_variables wrap is identity at init: same param tree
        params1 = model1.init(
            jax.random.PRNGKey(0), jnp.zeros((1, seq), jnp.int32)
        )["params"]
        jax.tree.map(
            lambda a, b: np.testing.assert_array_equal(a, b),
            params, params1,
        )

        outs = []
        for loss_fn, kwargs in (
            (loss0, {}),
            (loss1, {"presynced": lambda p: p[0] == "layers",
                     "overlap": True}),
        ):
            state = ddp.TrainState.create(
                apply_fn=None, params=jax.tree.map(jnp.copy, params),
                tx=optax.sgd(0.1),
            )
            state = ddp.broadcast_params(state, mesh)
            step = ddp.make_train_step(
                loss_fn, mesh=mesh, donate=False, **kwargs
            )
            new_state, metrics = step(
                state, shard_batch({"tokens": toks}, mesh),
                jax.random.PRNGKey(3),
            )
            outs.append((new_state.params, float(metrics["loss"])))

        np.testing.assert_allclose(outs[0][1], outs[1][1], rtol=1e-6)
        jax.tree.map(
            lambda a, b: np.testing.assert_allclose(
                np.asarray(a), np.asarray(b), rtol=1e-6, atol=1e-7
            ),
            outs[0][0], outs[1][0],
        )


def test_grad_sync_axis_requires_scan(devices):
    import jax.numpy as jnp

    from distributeddataparallel_tpu.models import TransformerLM, tiny_lm

    cfg = tiny_lm(scan_layers=False, grad_sync_axis="data")
    with pytest.raises(ValueError, match="scan_layers"):
        TransformerLM(cfg).init(
            jax.random.PRNGKey(0), jnp.zeros((1, 8), jnp.int32)
        )


def test_presynced_rejects_zero_and_nosync(devices):
    mesh = make_mesh(("data",))

    def loss_fn(params, batch, rng):
        return jnp.sum(params["w"] * 0.0), {}

    with pytest.raises(ValueError, match="presynced"):
        ddp.make_train_step(
            loss_fn, mesh=mesh, zero=True, presynced=lambda p: False
        )
    with pytest.raises(ValueError, match="presynced"):
        ddp.make_train_step(
            loss_fn, mesh=mesh, grad_sync=False, presynced=lambda p: False
        )


def test_overlap_rejects_zero_and_nosync(devices):
    mesh = make_mesh(("data",))

    def loss_fn(params, batch, rng):
        return jnp.sum(params["w"] * 0.0), {}

    with pytest.raises(ValueError):
        ddp.make_train_step(loss_fn, mesh=mesh, zero=True, overlap=True)
    with pytest.raises(ValueError):
        ddp.make_train_step(
            loss_fn, mesh=mesh, grad_sync=False, overlap=True
        )


_CANNED_HLO = """\
HloModule m

%async_collective_fusion.1 (param_0.1: f32[8]) -> f32[8] {
  %param_0.1 = f32[8]{0} parameter(0)
  ROOT %ar = f32[8]{0} all-reduce(%param_0.1), replica_groups={}
}

%fused_computation.9 (p: f32[8]) -> f32[8] {
  %p = f32[8]{0} parameter(0)
  ROOT %ar2 = f32[8]{0} all-reduce(%p), replica_groups={}
}

ENTRY %main (a: f32[8]) -> f32[8] {
  %a = f32[8]{0} parameter(0)
  %f0 = f32[8]{0} fusion(%a), kind=kLoop, calls=%fc0, backend_config={"estimated_cycles":"100"}
  %async-collective-start = f32[8]{0} fusion(%f0), kind=kCustom, calls=%fused_computation.9
  %f1 = f32[8]{0} fusion(%f0), kind=kLoop, calls=%fc1, backend_config={"estimated_cycles":"250"}
  %f2 = f32[8]{0} fusion(%f1), kind=kOutput, calls=%async_collective_fusion.1, backend_config={"estimated_cycles":"50"}
  %async-collective-done = f32[8]{0} fusion(%async-collective-start), kind=kCustom, calls=%fused_computation.9
  %f3 = f32[8]{0} fusion(%f2), kind=kLoop, calls=%fc2, backend_config={"estimated_cycles":"400"}
  %ar9 = f32[8]{0} all-reduce(%f3), replica_groups={}
  ROOT %f4 = f32[8]{0} fusion(%ar9), kind=kLoop, calls=%fc3, backend_config={"estimated_cycles":"75"}
}
"""


def test_schedule_report_parser():
    rep = schedule_report(_CANNED_HLO)
    assert rep["n_async_windows"] == 1
    # window holds f1 (250) + f2 (50, collective-fused compute) = 300
    assert rep["windows"][0]["compute_cycles"] == 300
    assert rep["n_sync_collectives"] == 1  # %ar9
    assert rep["total_compute_cycles"] == 100 + 250 + 50 + 400 + 75
    assert 0 < rep["overlapped_frac_of_compute"] < 1


def test_schedule_parse_validation():
    """Live-compile guard (VERDICT r4 weak 2): a toolchain bump that
    renames the metadata the parsers read must raise, not record 0."""
    from distributeddataparallel_tpu.parallel.overlap import (
        ScheduleEvidenceError,
        validate_schedule_parse,
    )

    good = schedule_report(_CANNED_HLO)
    assert validate_schedule_parse(good, _CANNED_HLO, where="t") is good

    # estimated_cycles renamed -> zero parsed compute cycles -> loud.
    renamed = _CANNED_HLO.replace("estimated_cycles", "est_cyc_v2")
    with pytest.raises(ScheduleEvidenceError, match="estimated_cycles"):
        validate_schedule_parse(
            schedule_report(renamed), renamed, where="t"
        )

    # collective spelling drifted: text still contains all-reduce but the
    # parser classifies none (simulate by feeding a report parsed from a
    # collective-free program against collective-carrying text).
    no_coll = "\n".join(
        l for l in _CANNED_HLO.splitlines()
        if "all-reduce" not in l and "async-collective" not in l
        and "async_collective" not in l and "fused_computation.9" not in l
    )
    rep = schedule_report(no_coll)
    assert rep["n_async_windows"] == 0 and rep["n_sync_collectives"] == 0
    with pytest.raises(ScheduleEvidenceError, match="collectives"):
        validate_schedule_parse(rep, _CANNED_HLO, where="t")


def test_compiler_stamp():
    from distributeddataparallel_tpu.parallel.overlap import compiler_stamp

    stamp = compiler_stamp()
    assert stamp["jax"]  # at minimum the jax version is always present


def test_cycles_by_scope_strict():
    from distributeddataparallel_tpu.parallel.overlap import (
        ScheduleEvidenceError,
        cycles_by_scope,
    )

    with pytest.raises(ScheduleEvidenceError):
        cycles_by_scope("ENTRY %m () -> f32[] {}", {"a": "x"}, strict=True)
    # non-strict keeps the old degrade-to-zero behavior for diagnostics
    assert cycles_by_scope(
        "ENTRY %m () -> f32[] {}", {"a": "x"}
    )["total_cycles"] == 0


def test_cpu_fabric_note(devices):
    note = cpu_fabric_note()
    assert note["physical_cores"] >= 1
    # On the CI CPU mesh the live-compiler check must run and confirm
    # the synchronous-only lowering that makes overlap unmeasurable here.
    assert note.get("cpu_hlo_sync_allreduce") is True
    assert note.get("cpu_hlo_async_allreduce") is False


def test_tpu_schedule_evidence():
    """Nonzero scheduled overlap on an 8-chip TPU topology (AOT)."""
    pytest.importorskip("jax.experimental.topologies")
    from distributeddataparallel_tpu.parallel.overlap import (
        grad_sync_schedule_evidence,
    )

    try:
        rep = grad_sync_schedule_evidence(
            n_layers=4, d_model=512, batch_per_chip=8
        )
    except Exception as exc:  # no TPU compiler in this process
        pytest.skip(f"TPU topology compile unavailable: {exc!r}")
    assert rep["n_async_windows"] >= 1
    assert rep["overlapped_compute_cycles"] > 0
    assert rep["compiler"]["jax"]


def test_tpu_real_step_schedule_evidence_scanned():
    """The REAL scanned-Llama train step (remat + scan + in-body grad
    sync) schedules async all-reduce windows INSIDE the backward scan
    body on an 8-chip TPU topology — the model-scale evidence VERDICT r4
    item 1 demanded (size reduced from the bench config to keep the AOT
    compile test-budget-sized; same structure: scan, remat, GQA,
    grad_sync_axis, presynced step)."""
    pytest.importorskip("jax.experimental.topologies")
    from distributeddataparallel_tpu.parallel.overlap import (
        train_step_schedule_evidence,
    )

    try:
        rep = train_step_schedule_evidence(
            model="llama", per_chip_batch=2, seq_len=512
        )
    except Exception as exc:  # no TPU compiler in this process
        pytest.skip(f"TPU topology compile unavailable: {exc!r}")
    assert rep["config"]["scan_layers"] and rep["config"]["remat"]
    # the win: windows inside the backward while body, every scan trip
    body_windows = sum(
        b["n_async_windows_per_trip"] * b["trip_count"]
        for b in rep["while_bodies"]
    )
    assert body_windows >= rep["config"]["num_layers"]
    assert rep["overlapped_compute_cycles"] > 0
    # the bulk of the collective payload rides async (weight-sized
    # grads); at this reduced test size the sync residue (norm-scale
    # leaves) is a bigger share than at bench scale, hence > 0.5 here
    # and the real fraction recorded from the full config in BENCH_r{N}
    assert rep["async_bytes_frac"] > 0.5
