"""Augmentation tests: vectorized flip/crop semantics, determinism from
the (seed, epoch, step) derivation, and the loader hook."""

import numpy as np

import distributeddataparallel_tpu as ddp
from distributeddataparallel_tpu.data import (
    ArrayDataset,
    DataLoader,
    cifar_augment,
    random_crop,
    random_horizontal_flip,
)


def _imgs(n=8, h=8, w=8, c=3, seed=0):
    return np.random.default_rng(seed).normal(size=(n, h, w, c)).astype(
        np.float32
    )


def test_flip_extremes_and_determinism(devices):
    imgs = _imgs()
    none = random_horizontal_flip(imgs, np.random.default_rng(0), p=0.0)
    np.testing.assert_array_equal(none, imgs)
    allf = random_horizontal_flip(imgs, np.random.default_rng(0), p=1.0)
    np.testing.assert_array_equal(allf, imgs[:, :, ::-1])
    a = random_horizontal_flip(imgs, np.random.default_rng(7), p=0.5)
    b = random_horizontal_flip(imgs, np.random.default_rng(7), p=0.5)
    np.testing.assert_array_equal(a, b)
    assert not np.array_equal(a, imgs)  # 8 coin flips: all-heads ~0.4%


def test_crop_offsets_and_fill(devices):
    imgs = _imgs()
    assert random_crop(imgs, np.random.default_rng(0), padding=0) is imgs
    out = random_crop(imgs, np.random.default_rng(1), padding=2, fill=-1.0)
    assert out.shape == imgs.shape
    # Every output row is a contiguous window of the padded image: verify
    # against a manual reconstruction with the same generator draws.
    rng = np.random.default_rng(1)
    oy = rng.integers(0, 5, 8)
    ox = rng.integers(0, 5, 8)
    padded = np.pad(
        imgs, ((0, 0), (2, 2), (2, 2), (0, 0)), constant_values=-1.0
    )
    for i in range(8):
        np.testing.assert_array_equal(
            out[i], padded[i, oy[i] : oy[i] + 8, ox[i] : ox[i] + 8]
        )


def test_loader_augment_deterministic_and_epoch_varying(devices):
    mesh = ddp.make_mesh(("data",))
    ds = ArrayDataset(_imgs(64, seed=3), np.zeros(64, np.int32))

    def batches(epoch):
        loader = DataLoader(
            ds, per_replica_batch=2, mesh=mesh, shuffle=False, seed=5,
            augment=cifar_augment, device_feed=False,
        )
        loader.set_epoch(epoch)
        return [b["image"].copy() for b in loader]

    a0, b0 = batches(0), batches(0)
    for x, y in zip(a0, b0):
        np.testing.assert_array_equal(x, y)  # rerun-deterministic
    a1 = batches(1)
    assert any(not np.array_equal(x, y) for x, y in zip(a0, a1))

    # Without augment, the same loader config yields the raw rows.
    plain = DataLoader(
        ds, per_replica_batch=2, mesh=mesh, shuffle=False, seed=5,
        device_feed=False,
    )
    raw = next(iter(plain))["image"]
    assert not np.array_equal(raw, a0[0])


def test_fused_native_augment_matches_numpy(devices):
    """native.gather_augment_u8 == gather+normalize then crop+flip in
    NumPy, bit-for-bit up to the /255 reciprocal ULP."""
    from distributeddataparallel_tpu import native
    from distributeddataparallel_tpu.data.datasets import normalize_images
    from distributeddataparallel_tpu.data.transforms import _crop_at

    rng = np.random.default_rng(11)
    src = rng.integers(0, 256, size=(32, 8, 8, 3)).astype(np.uint8)
    idx = rng.integers(0, 32, size=10).astype(np.int64)
    oy = rng.integers(0, 5, size=10).astype(np.int64)
    ox = rng.integers(0, 5, size=10).astype(np.int64)
    flip = (rng.random(10) < 0.5)

    got = native.gather_augment_u8(src, idx, oy, ox, flip, padding=2)

    ref = normalize_images(src[idx])
    ref = _crop_at(ref, oy, ox, 2, -1.0)
    ref[flip] = ref[flip, :, ::-1]
    np.testing.assert_allclose(got, ref, atol=1e-6)
    assert native.available()  # the kernel actually ran natively


def test_loader_fused_u8_matches_f32_path(devices):
    """CifarAugment via the fused uint8 loader path == the generic f32
    path on the same data and (seed, epoch): rng consumption order is
    identical by construction."""
    mesh = ddp.make_mesh(("data",))
    rng = np.random.default_rng(13)
    u8 = rng.integers(0, 256, size=(64, 8, 8, 3)).astype(np.uint8)
    labels = np.zeros(64, np.int32)
    from distributeddataparallel_tpu.data import CifarAugment
    from distributeddataparallel_tpu.data.datasets import normalize_images

    ds_u8 = ArrayDataset(u8, labels, normalize_u8=True)
    ds_f32 = ArrayDataset(normalize_images(u8), labels)

    def batches(ds):
        loader = DataLoader(
            ds, per_replica_batch=2, mesh=mesh, shuffle=False, seed=5,
            augment=CifarAugment(), device_feed=False,
        )
        loader.set_epoch(1)
        return [b["image"] for b in loader]

    for a, b in zip(batches(ds_u8), batches(ds_f32)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=1e-6)
