"""Augmentation tests: vectorized flip/crop semantics, determinism from
the (seed, epoch, step) derivation, and the loader hook."""

import numpy as np

import distributeddataparallel_tpu as ddp
from distributeddataparallel_tpu.data import (
    ArrayDataset,
    DataLoader,
    cifar_augment,
    random_crop,
    random_horizontal_flip,
)


def _imgs(n=8, h=8, w=8, c=3, seed=0):
    return np.random.default_rng(seed).normal(size=(n, h, w, c)).astype(
        np.float32
    )


def test_flip_extremes_and_determinism(devices):
    imgs = _imgs()
    none = random_horizontal_flip(imgs, np.random.default_rng(0), p=0.0)
    np.testing.assert_array_equal(none, imgs)
    allf = random_horizontal_flip(imgs, np.random.default_rng(0), p=1.0)
    np.testing.assert_array_equal(allf, imgs[:, :, ::-1])
    a = random_horizontal_flip(imgs, np.random.default_rng(7), p=0.5)
    b = random_horizontal_flip(imgs, np.random.default_rng(7), p=0.5)
    np.testing.assert_array_equal(a, b)
    assert not np.array_equal(a, imgs)  # 8 coin flips: all-heads ~0.4%


def test_crop_offsets_and_fill(devices):
    imgs = _imgs()
    assert random_crop(imgs, np.random.default_rng(0), padding=0) is imgs
    out = random_crop(imgs, np.random.default_rng(1), padding=2, fill=-1.0)
    assert out.shape == imgs.shape
    # Every output row is a contiguous window of the padded image: verify
    # against a manual reconstruction with the same generator draws.
    rng = np.random.default_rng(1)
    oy = rng.integers(0, 5, 8)
    ox = rng.integers(0, 5, 8)
    padded = np.pad(
        imgs, ((0, 0), (2, 2), (2, 2), (0, 0)), constant_values=-1.0
    )
    for i in range(8):
        np.testing.assert_array_equal(
            out[i], padded[i, oy[i] : oy[i] + 8, ox[i] : ox[i] + 8]
        )


def test_loader_augment_deterministic_and_epoch_varying(devices):
    mesh = ddp.make_mesh(("data",))
    ds = ArrayDataset(_imgs(64, seed=3), np.zeros(64, np.int32))

    def batches(epoch):
        loader = DataLoader(
            ds, per_replica_batch=2, mesh=mesh, shuffle=False, seed=5,
            augment=cifar_augment, device_feed=False,
        )
        loader.set_epoch(epoch)
        return [b["image"].copy() for b in loader]

    a0, b0 = batches(0), batches(0)
    for x, y in zip(a0, b0):
        np.testing.assert_array_equal(x, y)  # rerun-deterministic
    a1 = batches(1)
    assert any(not np.array_equal(x, y) for x, y in zip(a0, a1))

    # Without augment, the same loader config yields the raw rows.
    plain = DataLoader(
        ds, per_replica_batch=2, mesh=mesh, shuffle=False, seed=5,
        device_feed=False,
    )
    raw = next(iter(plain))["image"]
    assert not np.array_equal(raw, a0[0])
