"""In-loop alerting: rule arithmetic (spike vs rolling median, floors,
storms, starvation, memory growth), the engine's rising-edge /
hysteresis / no-refire discipline, --alerts spec parsing, the live
monitor's exit-code contract, and the end-to-end dpp wiring (alert
events + registry counters + run_summary + runs-store append)."""

import json
import os
import sys

import pytest

sys.path.insert(0, "/root/repo")

import dpp  # noqa: E402
from distributeddataparallel_tpu.observability import (  # noqa: E402
    EventLog,
    MetricsRegistry,
    events_path,
    read_events,
    read_runs,
    validate_file,
)
from distributeddataparallel_tpu.observability.alerts import (  # noqa: E402
    AlertEngine,
    GoodputFloor,
    LoaderStarvation,
    MemoryGrowth,
    MfuFloor,
    RestartStorm,
    StepTimeSpike,
    parse_alert_spec,
)

sys.path.insert(0, os.path.join("/root/repo", "scripts"))
import ddp_monitor  # noqa: E402


def _engine(*rules, **kw):
    return AlertEngine(list(rules), **kw)


# ------------------------------------------------------ rule arithmetic


def test_step_spike_fires_on_spike_not_on_steady_state():
    eng = _engine(StepTimeSpike(factor=2.0, min_history=3))
    for _ in range(5):
        assert eng.observe(step=0, step_s=0.1) == []
    fired = eng.observe(step=6, step_s=0.25)  # 2.5x the 0.1 median
    assert [a["rule"] for a in fired] == ["step_spike"]
    assert fired[0]["value"] == pytest.approx(0.25)
    assert fired[0]["threshold"] == pytest.approx(0.2)


def test_step_spike_needs_history():
    eng = _engine(StepTimeSpike(factor=2.0, min_history=3))
    # Fewer than min_history windows: even a huge value cannot fire —
    # there is no median to compare against yet.
    assert eng.observe(step=0, step_s=0.1) == []
    assert eng.observe(step=1, step_s=99.0) == []


def test_step_spike_hysteresis_no_refire_then_rearm():
    eng = _engine(StepTimeSpike(factor=2.0, clear_factor=1.5))
    for s in range(4):
        eng.observe(step=s, step_s=0.1)
    assert len(eng.observe(step=4, step_s=0.3)) == 1
    # Still elevated (above the 1.5x clear bound): active, no re-fire.
    assert eng.observe(step=5, step_s=0.25) == []
    assert eng.firing == ["step_spike"]
    # Back under the clear bound: clears silently...
    assert eng.observe(step=6, step_s=0.1) == []
    assert eng.firing == []
    # ...and a NEW spike is a new rising edge.
    assert len(eng.observe(step=7, step_s=0.5)) == 1
    assert len(eng.fired) == 2


def test_step_spike_adapts_to_regime_change():
    # A sustained slowdown becomes the new normal: the spike window
    # itself enters the history, so the median catches up and the rule
    # clears instead of alerting forever.
    eng = _engine(StepTimeSpike(factor=2.0, history=4))
    for s in range(4):
        eng.observe(step=s, step_s=0.1)
    assert len(eng.observe(step=4, step_s=0.3)) == 1
    for s in range(5, 10):
        eng.observe(step=s, step_s=0.3)
    assert eng.firing == []  # median is now 0.3: condition cleared


def test_mfu_floor_skips_first_window_then_fires():
    eng = _engine(MfuFloor(floor=0.3))
    assert eng.observe(step=0, mfu=0.01) == []  # warm-up window
    fired = eng.observe(step=1, mfu=0.01)
    assert [a["rule"] for a in fired] == ["mfu_floor"]
    # Recovery above floor*1.1 clears; a later dip re-fires.
    eng.observe(step=2, mfu=0.5)
    assert len(eng.observe(step=3, mfu=0.1)) == 1


def test_mfu_floor_absent_signal_is_inert():
    eng = _engine(MfuFloor(floor=0.3))
    # No mfu key at all (run without --mfu): the rule must stay silent
    # AND not consume its warm-up budget.
    assert eng.observe(step=0) == []
    assert eng.observe(step=1, mfu=0.9) == []  # this is window 1: skip
    assert len(eng.observe(step=2, mfu=0.01)) == 1


def test_goodput_floor_waits_for_min_elapsed():
    eng = _engine(GoodputFloor(floor=0.5, min_elapsed_s=60.0))
    assert eng.observe(step=0, goodput=0.1, elapsed_s=10.0) == []
    fired = eng.observe(step=1, goodput=0.1, elapsed_s=61.0)
    assert [a["rule"] for a in fired] == ["goodput_floor"]


def test_restart_storm_fires_once_only():
    eng = _engine(RestartStorm(max_restarts=2))
    assert eng.observe(step=0, restarts=1) == []
    assert len(eng.observe(step=1, restarts=2)) == 1
    # Monotone: stays active forever, never re-fires.
    assert eng.observe(step=2, restarts=3) == []
    assert len(eng.fired) == 1


def test_loader_starvation_needs_consecutive_empty_windows():
    eng = _engine(LoaderStarvation(windows=3))
    assert eng.observe(step=0, prefetch_depth=0) == []
    assert eng.observe(step=1, prefetch_depth=2) == []  # streak reset
    assert eng.observe(step=2, prefetch_depth=0) == []
    assert eng.observe(step=3, prefetch_depth=0) == []
    assert len(eng.observe(step=4, prefetch_depth=0)) == 1


def test_memory_growth_fires_on_hwm_above_settled_baseline():
    eng = _engine(MemoryGrowth(frac=0.10, settle_windows=2))
    assert eng.observe(step=0, live_hwm_bytes=1000) == []  # settling
    assert eng.observe(step=1, live_hwm_bytes=1000) == []  # baseline set
    assert eng.observe(step=2, live_hwm_bytes=1050) == []  # +5%: under
    fired = eng.observe(step=3, live_hwm_bytes=1200)       # +20%
    assert [a["rule"] for a in fired] == ["mem_growth"]
    assert fired[0]["baseline_bytes"] == 1000
    # HWM is monotone: never clears, never re-fires.
    assert eng.observe(step=4, live_hwm_bytes=5000) == []


# --------------------------------------------------------- spec parsing


def test_parse_alert_spec_defaults_and_overrides():
    rules = {r.name: r for r in parse_alert_spec("")}
    assert set(rules) == {"step_spike", "mfu_floor", "goodput_floor",
                          "restart_storm", "loader_starved", "mem_growth",
                          "sdc_storm", "gang_suspect"}
    rules = {r.name: r for r in parse_alert_spec(
        "mfu_floor=0.3, step_spike=2.5, restart_storm=5"
    )}
    assert rules["mfu_floor"].floor == pytest.approx(0.3)
    assert rules["step_spike"].factor == pytest.approx(2.5)
    assert rules["restart_storm"].max_restarts == 5
    assert rules["goodput_floor"].floor == pytest.approx(0.5)  # default


def test_parse_alert_spec_rejects_unknown_and_malformed():
    with pytest.raises(ValueError, match="unknown alert rule"):
        parse_alert_spec("mfu=0.3")
    with pytest.raises(ValueError, match="needs a threshold"):
        parse_alert_spec("mfu_floor")
    with pytest.raises(ValueError, match="not a number"):
        parse_alert_spec("mfu_floor=lots")
    with pytest.raises(SystemExit):
        dpp.parse_args(["--alerts", "bogus=1"])


# ----------------------------------------------- engine event/registry


def test_engine_emits_events_and_counters(tmp_path):
    ev_dir = str(tmp_path)
    reg = MetricsRegistry()
    with EventLog(events_path(ev_dir, 0), 0) as events:
        eng = AlertEngine(
            [MfuFloor(floor=0.3), RestartStorm(max_restarts=1)],
            events=events, registry=reg,
        )
        eng.observe(step=0, mfu=0.9, restarts=0)
        eng.observe(step=1, mfu=0.01, restarts=1)  # both fire
    recs = [r for r in read_events(events_path(ev_dir, 0))
            if r["kind"] == "alert"]
    assert {r["rule"] for r in recs} == {"mfu_floor", "restart_storm"}
    assert validate_file(events_path(ev_dir, 0)) == []
    assert reg.counter("alerts_total").value == 2
    assert reg.counter("alerts_mfu_floor").value == 1
    assert eng.summary() == {
        "total": 2, "by_rule": {"mfu_floor": 1, "restart_storm": 1},
    }


# -------------------------------------------------------- live monitor


def _write_events(path, proc, recs):
    os.makedirs(os.path.dirname(path), exist_ok=True)
    with open(path, "w") as fh:
        for i, r in enumerate(recs):
            fh.write(json.dumps({
                "v": 1, "ts": 1000.0 + i, "seq": i, "proc": proc, **r,
            }) + "\n")


def test_monitor_one_shot_healthy_exits_zero(tmp_path, capsys):
    ev_dir = str(tmp_path)
    _write_events(events_path(ev_dir, 0), 0, [
        {"kind": "run_start", "argv": []},
        {"kind": "span", "name": "step", "dur_s": 0.1, "step": 7},
        {"kind": "mfu", "step": 7, "model_flops_per_s": 1e9, "mfu": 0.41},
    ])
    assert ddp_monitor.main([ev_dir]) == 0
    out = capsys.readouterr().out
    assert "0.41" in out and "running" in out


def test_monitor_one_shot_alert_exits_two(tmp_path, capsys):
    ev_dir = str(tmp_path)
    _write_events(events_path(ev_dir, 0), 0, [
        {"kind": "run_start", "argv": []},
        {"kind": "alert", "rule": "mfu_floor", "step": 20,
         "value": 0.01, "threshold": 0.3},
        {"kind": "nan_skip", "step": 21},
    ])
    _write_events(
        os.path.join(ev_dir, "events-supervisor.jsonl"), "supervisor",
        [{"kind": "restart_attempt", "attempt": 1}],
    )
    assert ddp_monitor.main([ev_dir]) == ddp_monitor.ALERT_EXIT
    out = capsys.readouterr().out
    assert "ALERT [mfu_floor]" in out
    assert "restart_attempt" in out


def test_monitor_empty_dir_exits_one(tmp_path):
    assert ddp_monitor.main([str(tmp_path)]) == 1


def test_monitor_tail_ignores_torn_partial_line(tmp_path):
    ev_dir = str(tmp_path)
    path = events_path(ev_dir, 0)
    _write_events(path, 0, [{"kind": "run_start", "argv": []}])
    with open(path, "a") as fh:
        fh.write('{"v": 1, "ts": 1002.0, "seq": 9, "proc": 0, "kin')
    tail = ddp_monitor._Tail(path)
    recs = tail.poll()
    assert [r["kind"] for r in recs] == ["run_start"]
    offset = tail.offset
    # The torn line is NOT consumed; completing it makes it readable.
    with open(path, "a") as fh:
        fh.write('d": "nan_skip", "step": 3}\n')
    assert tail.offset == offset
    assert [r["kind"] for r in tail.poll()] == ["nan_skip"]


def test_monitor_follow_mode_terminates_on_budget(tmp_path, capsys):
    ev_dir = str(tmp_path)
    _write_events(events_path(ev_dir, 0), 0, [
        {"kind": "run_start", "argv": []},
        {"kind": "alert", "rule": "step_spike", "step": 40,
         "value": 0.5, "threshold": 0.2},
    ])
    rc = ddp_monitor.main(
        [ev_dir, "--follow", "--interval", "0.05", "--max-seconds", "0.2"]
    )
    assert rc == ddp_monitor.ALERT_EXIT
    assert "ALERT [step_spike]" in capsys.readouterr().out


# ------------------------------------------- end-to-end: dpp wiring


def test_train_alerts_run_summary_and_runs_store(
    devices, tmp_path, monkeypatch,
):
    """In-process train with --alerts + --runs-dir: a restart_storm rule
    armed at threshold 1 fires off the env restart counter at the first
    window boundary, the run_summary event carries window stats, and the
    runs store gains one trainer-source line."""
    ev_dir = str(tmp_path / "events")
    runs_dir = str(tmp_path / "runs")
    # Pretend this incarnation is a respawn: restart_storm=1 must fire
    # at the first throughput-window boundary.
    monkeypatch.setenv("DDP_RESTART_ATTEMPT", "1")
    args = dpp.parse_args([
        "--device", "cpu", "--fake-devices", "8",
        "--model", "mlp", "--dataset", "synthetic",
        "--num-examples", "768", "--batch-size", "4",
        "--epochs", "1", "--log-every", "10",
        "--events-dir", ev_dir, "--metrics-every", "0",
        "--alerts", "restart_storm=1",
        "--runs-dir", runs_dir,
    ])
    dpp.train(args)

    recs = read_events(events_path(ev_dir, 0))
    assert validate_file(events_path(ev_dir, 0)) == []
    alerts = [r for r in recs if r["kind"] == "alert"]
    assert [a["rule"] for a in alerts] == ["restart_storm"]
    assert alerts[0]["value"] == 1

    summaries = [r for r in recs if r["kind"] == "run_summary"]
    assert len(summaries) == 1
    rs = summaries[0]
    # StepTimer window floor is 20: 24 steps - 1 compile step = 23
    # post-compile steps -> exactly one window reading.
    assert rs["windows"] == 1
    assert rs["step_s_p50"] is not None and rs["step_s_p50"] > 0
    assert rs["restarts"] == 1
    assert rs["alerts_total"] == 1
    assert rs["status"] == "ok"
    # run_summary precedes run_end in the same log.
    kinds = [r["kind"] for r in recs]
    assert kinds.index("run_summary") < kinds.index("run_end")

    runs = read_runs(runs_dir)
    assert len(runs) == 1
    assert runs[0]["source"] == "trainer"
    assert runs[0]["windows"] == 1 and runs[0]["alerts_total"] == 1

    # The live monitor sees the firing alert: non-zero for scripting.
    assert ddp_monitor.main([ev_dir]) == ddp_monitor.ALERT_EXIT
