"""Performance-attribution layer (PR 5): the analytic cost model vs
XLA's own cost analysis, goodput bucket arithmetic across a restart,
straggler skew attribution, the flop_signature handoff, and the
acceptance path — a supervised --mfu --memory-telemetry chaos run whose
events dir renders into a full ddp_report."""

import json
import os
import sys
import types

import jax
import jax.numpy as jnp
import pytest

sys.path.insert(0, "/root/repo")

import dpp  # noqa: E402
from distributeddataparallel_tpu.observability import (  # noqa: E402
    EventLog,
    GoodputLedger,
    MetricsRegistry,
    MFUMeter,
    goodput_from_timeline,
    mlp_fwd_flops,
    peak_flops_for,
    read_events,
    simple_cnn_fwd_flops,
    straggler_report,
    train_step_flops,
    transformer_fwd_flops,
    xla_cost_analysis,
)
from distributeddataparallel_tpu.observability.memory import (  # noqa: E402
    MemoryTelemetry,
    executable_memory_analysis,
    live_array_bytes,
)
from distributeddataparallel_tpu.runtime.launcher import spawn  # noqa: E402

sys.path.insert(0, os.path.join("/root/repo", "scripts"))
import ddp_report  # noqa: E402


# -------------------------------------------- cost model vs XLA


def test_transformer_flops_vs_xla_cost_analysis(devices):
    """The analytic forward count agrees with XLA's cost analysis on a
    small gpt2-shaped config within tolerance (the analytic model counts
    matmuls only; XLA adds elementwise/softmax work on top)."""
    from distributeddataparallel_tpu.models import transformer as tfm

    cfg = tfm.gpt2_124m(
        vocab_size=512, max_seq_len=64, num_layers=2, d_model=128,
        num_heads=4, d_ff=512,
    )
    model = tfm.TransformerLM(cfg)
    B, S = 4, 64
    tokens = jnp.zeros((B, S), jnp.int32)
    params = model.init(jax.random.PRNGKey(0), tokens)

    lowered = jax.jit(lambda p, t: model.apply(p, t)).lower(params, tokens)
    ca = xla_cost_analysis(lowered)
    assert ca is not None and ca["flops"] > 0

    analytic = transformer_fwd_flops(cfg, batch=B, seq_len=S)
    ratio = ca["flops"] / analytic
    assert 0.75 < ratio < 1.35, (ca["flops"], analytic, ratio)


def test_train_step_flops_vs_xla_and_accum_invariance(devices):
    """3x-forward matches XLA's count for the full train step, and
    accumulation does NOT change per-step FLOPs (it splits the batch)."""
    import optax

    from distributeddataparallel_tpu import models
    from distributeddataparallel_tpu.ops.losses import cross_entropy_loss
    from distributeddataparallel_tpu.runtime.distributed import make_mesh
    from distributeddataparallel_tpu.training.state import TrainState
    from distributeddataparallel_tpu.training.train_step import make_train_step

    model = models.TinyMLP(num_classes=10)
    B = 16
    x = jnp.zeros((B, 8, 8, 3), jnp.float32)
    y = jnp.zeros((B,), jnp.int32)
    params = model.init(jax.random.PRNGKey(0), x)["params"]
    mesh = make_mesh(("data",))
    state = TrainState.create(
        apply_fn=model.apply, params=params, tx=optax.sgd(0.1)
    )

    def loss_fn(params, batch, rng):
        logits = model.apply({"params": params}, batch["image"])
        return cross_entropy_loss(logits, batch["label"]), {}

    batch = {"image": x, "label": y}
    flops = {}
    for accum in (1, 2):
        step = make_train_step(
            loss_fn, mesh=mesh, accum_steps=accum, donate=False
        )
        sig = step.flop_signature
        assert sig["accum_steps"] == accum
        assert sig["microbatch_fraction"] == pytest.approx(1.0 / accum)
        ca = xla_cost_analysis(
            step.lower(state, batch, jax.random.PRNGKey(1))
        )
        assert ca is not None
        flops[accum] = ca["flops"]
        analytic = train_step_flops(
            mlp_fwd_flops(batch=B, in_features=8 * 8 * 3, num_classes=10),
            flop_signature=sig,
        )
        # The SPMD-lowered step shards the batch across the mesh, so
        # cost_analysis() reports PER-DEVICE flops; the analytic count
        # is the global batch — scale back up before comparing.
        ratio = ca["flops"] * len(jax.devices()) / analytic["model_flops"]
        assert 0.7 < ratio < 1.4, (accum, ca["flops"], analytic, ratio)
    # Accumulation splits the batch; XLA's count must not ~double.
    assert flops[2] / flops[1] < 1.5, flops


def test_cnn_flops_vs_xla_cost_analysis(devices):
    from distributeddataparallel_tpu import models

    model = models.SimpleCNN(num_classes=10)
    B, H, W, C = 8, 16, 16, 3
    x = jnp.zeros((B, H, W, C), jnp.float32)
    params = model.init(jax.random.PRNGKey(0), x)
    ca = xla_cost_analysis(
        jax.jit(lambda p, x: model.apply(p, x)).lower(params, x)
    )
    assert ca is not None and ca["flops"] > 0
    analytic = simple_cnn_fwd_flops(
        batch=B, image_shape=(H, W, C), num_classes=10
    )
    ratio = ca["flops"] / analytic
    assert 0.7 < ratio < 1.3, (ca["flops"], analytic, ratio)


def test_moe_flops_scale_with_dispatch_mode():
    """Dense dispatch scales with E; token-choice scales with top-k."""
    cfg = types.SimpleNamespace(
        d_model=64, num_heads=4, num_kv_heads=None, head_dim=16,
        d_ff=256, activation="gelu", vocab_size=128, num_layers=2,
        moe_experts=0, moe_top_k=2, moe_capacity_factor=0.0,
    )
    dense_mlp = transformer_fwd_flops(cfg, batch=2, seq_len=32)
    cfg.moe_experts = 4
    all_experts = transformer_fwd_flops(cfg, batch=2, seq_len=32)
    cfg.moe_capacity_factor = 1.25
    top_k = transformer_fwd_flops(cfg, batch=2, seq_len=32)
    assert all_experts > top_k > dense_mlp


def test_mfu_meter_reading_and_unknown_peak():
    registry = MetricsRegistry()
    meter = MFUMeter(
        {"model_flops": 1e9, "hardware_flops": 1.5e9},
        n_chips=8, peak_flops_per_chip=1e10, registry=registry,
    )
    out = meter.on_reading({"steps_per_s": 4.0}, step=10)
    assert out["model_flops_per_s"] == pytest.approx(4e9)
    assert out["mfu"] == pytest.approx(4e9 / 8e10)
    assert out["hfu"] == pytest.approx(6e9 / 8e10)
    assert registry.gauge("mfu").read() == pytest.approx(0.05)
    # Unknown hardware: no fraction, but the absolute rate still reports.
    blind = MFUMeter({"model_flops": 1e9}, n_chips=8,
                     peak_flops_per_chip=None)
    out = blind.on_reading({"steps_per_s": 4.0}, step=10)
    assert out["mfu"] is None and out["model_flops_per_s"] == 4e9


def test_peak_flops_for_device_kinds():
    v5e = types.SimpleNamespace(device_kind="TPU v5 lite")
    assert peak_flops_for(v5e) == pytest.approx(197e12)
    assert peak_flops_for(types.SimpleNamespace(device_kind="warp9")) is None


# -------------------------------------------- memory telemetry


def test_memory_telemetry_samples_without_device_stats(devices, tmp_path):
    """On CPU (no allocator stats) sampling degrades to the live-array
    view, tracks the HWM, and the exec_memory path reads a compiled
    executable's budget."""
    keep = jnp.ones((1024, 256), jnp.float32)  # 1 MiB held live
    total, count = live_array_bytes()
    assert total >= keep.nbytes and count >= 1

    ev = EventLog(str(tmp_path / "events-p0.jsonl"), 0)
    registry = MetricsRegistry()
    tel = MemoryTelemetry(registry=registry, events=ev,
                          devices=jax.local_devices())
    s1 = tel.sample(step=0)
    assert s1["live_bytes"] >= keep.nbytes
    assert s1["live_hwm_bytes"] == tel.live_hwm_bytes

    compiled = jax.jit(lambda x: x * 2 + 1).lower(keep).compile()
    analysis = executable_memory_analysis(compiled)
    if analysis is not None:  # backend-optional
        assert tel.note_executable(compiled, label="toy") is not None
    ev.close()
    kinds = [r["kind"] for r in read_events(ev.path)]
    assert "memory" in kinds


# -------------------------------------------- goodput


def _rec(kind, ts, proc=0, **fields):
    return {"v": 1, "ts": ts, "seq": int(ts * 10), "proc": proc,
            "kind": kind, **fields}


def test_goodput_ledger_buckets_and_remainder():
    led = GoodputLedger()
    led.add("compile", 2.0)
    led.add("checkpoint", 1.0)
    led.add("eval", None)  # tolerated no-op
    s = led.summary(total_s=10.0)
    assert s["productive_s"] == pytest.approx(7.0)
    assert s["goodput"] == pytest.approx(0.7)
    with pytest.raises(KeyError):
        led.add("coffee", 1.0)
    # Buckets exceeding total clamp at zero productive, not negative.
    led.add("restart", 100.0)
    s = led.summary(total_s=10.0)
    assert s["productive_s"] == 0.0 and s["goodput"] == 0.0


def test_goodput_from_timeline_with_restart():
    """Synthetic two-incarnation timeline: attempt 0 is preempted (no
    run_end, rebuilt from spans + warm_start), the gap to attempt 1 is
    the restart bucket, attempt 1 carries its own goodput event."""
    records = [
        _rec("run_start", 100.0, argv=[]),
        _rec("warm_start", 102.0, mode="cold", first_step_s=2.0),
        _rec("span", 104.0, name="ckpt_save", dur_s=1.0),
        _rec("span", 106.0, name="step", dur_s=0.1, step=5),  # killed here
        # supervisor respawns at 110: 4s restart gap
        _rec("run_start", 110.0, argv=[]),
        _rec("warm_start", 110.5, mode="aot", first_step_s=0.5),
        _rec("goodput", 119.0, total_s=9.0, goodput=0.8,
             buckets={"compile": 0.5, "checkpoint": 1.0, "eval": 0.0,
                      "restart": 0.0, "stall": 0.0}),
        _rec("run_end", 119.5, status="ok"),
    ]
    g = goodput_from_timeline(records)
    assert g is not None and g["restarts"] == 1
    assert len(g["incarnations"]) == 2
    assert g["incarnations"][0]["status"] == "killed"
    assert g["incarnations"][0]["buckets"]["compile"] == pytest.approx(2.0)
    assert g["incarnations"][0]["buckets"]["checkpoint"] == pytest.approx(1.0)
    assert g["incarnations"][1]["ended_clean"]
    # restart = gap between incarnation 0's last event and attempt 1.
    assert g["buckets"]["restart"] == pytest.approx(4.0)
    assert g["buckets"]["compile"] == pytest.approx(2.5)
    assert g["total_s"] == pytest.approx(19.5)
    spent = sum(g["buckets"].values())
    assert g["productive_s"] == pytest.approx(19.5 - spent)
    assert g["goodput"] == pytest.approx((19.5 - spent) / 19.5, abs=1e-3)


def test_goodput_from_timeline_empty_and_supervisor_only():
    assert goodput_from_timeline([]) is None
    sup = [_rec("restart_attempt", 5.0, proc="supervisor", attempt=1)]
    assert goodput_from_timeline(sup) is None


# -------------------------------------------- straggler


def test_straggler_attribution_and_histogram():
    """Rank 1 finishes every step last by 60ms — the report must say so."""
    records = []
    for step in range(10):
        t = 100.0 + step
        records.append(_rec("span", t, proc=0, name="step",
                            dur_s=0.1, step=step))
        records.append(_rec("span", t + 0.06, proc=1, name="step",
                            dur_s=0.16, step=step))
    s = straggler_report(records)
    assert s["n_ranks"] == 2 and s["steps_compared"] == 10
    assert s["slowest_rank"] == 1
    assert s["slowest_counts"] == {1: 10}
    assert s["skew_mean_s"] == pytest.approx(0.06)
    assert s["skew_histogram"]["0.01-0.05s"] == 0
    assert s["skew_histogram"]["0.05-0.1s"] == 10
    assert s["ranks"][1]["mean_step_s"] == pytest.approx(0.16)


def test_straggler_single_rank_degrades():
    recs = [_rec("span", 100.0 + i, name="step", dur_s=0.1, step=i)
            for i in range(3)]
    s = straggler_report(recs)
    assert s["n_ranks"] == 1 and s["slowest_rank"] is None
    assert s["ranks"][0]["steps"] == 3
    assert straggler_report([]) is None


# -------------------------------------------- acceptance: full report


def test_acceptance_mfu_memory_chaos_report(devices, tmp_path):
    """ISSUE acceptance: an 8-fake-device supervised run with --mfu,
    --memory-telemetry and a chaos preemption yields an events dir that
    ddp_report renders with non-trivial goodput, MFU, memory, and
    straggler sections (markdown AND --json).

    Step counts matter: StepTimer's window floor is 20, so each
    incarnation must run 21+ post-compile steps for an mfu/memory
    reading to land.  24 steps/epoch with preempt@30 gives attempt 0
    thirty steps (one window) and the resumed attempt 1 twenty-four
    (one window)."""
    ev_dir = str(tmp_path / "events")
    ck = str(tmp_path / "ck")
    base = [
        "--device", "cpu", "--fake-devices", "8",
        "--model", "mlp", "--dataset", "synthetic",
        "--num-examples", "1024", "--batch-size", "4",
        "--epochs", "2", "--steps-per-epoch", "24", "--log-every", "10",
        "--mfu", "--memory-telemetry", "--metrics-every", "8",
        "--checkpoint-dir", ck, "--resume",
    ]
    spawn(
        dpp._worker, args=(base,), nprocs=1, max_restarts=1,
        env={
            "_DDP_SUPERVISED": "1",
            # preempt@30 = epoch 1 batch 6: dies after epoch 0's
            # checkpoint, so the respawn resumes and finishes clean.
            "DDP_CHAOS": "preempt@30",
            "DDP_CHAOS_STATE": os.path.join(ck, ".chaos"),
        },
        events_dir=ev_dir,
    )
    out_md = str(tmp_path / "report.md")
    assert ddp_report.main([ev_dir, "-o", out_md]) == 0
    md = open(out_md).read()
    assert "## Goodput" in md and "restart |" in md
    assert "## MFU trend" in md and "model FLOP/s" in md
    assert "## Memory high-water marks" in md
    assert "## Stragglers" in md
    assert "was productive (1 restart(s))" in md

    analysis = json.loads(
        __import__("subprocess").run(
            [sys.executable, "scripts/ddp_report.py", ev_dir, "--json"],
            capture_output=True, text=True, cwd="/root/repo", check=True,
        ).stdout
    )
    g = analysis["goodput"]
    assert g["restarts"] == 1 and 0.0 < g["goodput"] < 1.0
    assert g["buckets"]["restart"] > 0
    assert analysis["mfu"] and analysis["mfu"][0]["mfu"] > 0
    assert analysis["memory"] and analysis["straggler"]


def test_report_tolerates_missing_and_supervisor_only(tmp_path):
    """Satellite: a gang that died before any worker wrote events still
    yields a (degraded) report, and an empty dir exits nonzero without
    crashing."""
    empty = tmp_path / "empty"
    empty.mkdir()
    assert ddp_report.main([str(empty)]) == 1

    sup_only = tmp_path / "suponly"
    sup_only.mkdir()
    ev = EventLog(str(sup_only / "events-supervisor.jsonl"), "supervisor")
    ev.emit("restart_exhausted", attempt=1, failed=[[0, 1]])
    ev.close()
    out = str(tmp_path / "r.md")
    assert ddp_report.main([str(sup_only), "-o", out]) == 0
    md = open(out).read()
    assert "supervisor-only" in md
    assert "goodput cannot be attributed" in md.lower()
