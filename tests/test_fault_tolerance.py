"""Fault-tolerance subsystem: retrying checkpoint IO, corrupt-checkpoint
fallback, the non-finite-grad guard, the step watchdog, chaos-spec
validation, and the acceptance path — a worker preempted mid-epoch under
launcher supervision resumes to loss parity with an uninterrupted run."""

import glob
import os
import sys
import time

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

sys.path.insert(0, "/root/repo")

import dpp  # noqa: E402
from distributeddataparallel_tpu.models.simple_cnn import TinyMLP  # noqa: E402
from distributeddataparallel_tpu.ops.losses import cross_entropy_loss  # noqa: E402
from distributeddataparallel_tpu.parallel.data_parallel import (  # noqa: E402
    broadcast_params,
)
from distributeddataparallel_tpu.runtime.distributed import make_mesh  # noqa: E402
from distributeddataparallel_tpu.runtime.launcher import spawn  # noqa: E402
from distributeddataparallel_tpu.training.fault_tolerance import (  # noqa: E402
    CheckpointUnrecoverable,
    NonFiniteBreaker,
    ResilientCheckpointer,
    RetryPolicy,
    StepWatchdog,
    TrainingDiverged,
)
from distributeddataparallel_tpu.training.state import TrainState  # noqa: E402
from distributeddataparallel_tpu.training.train_step import (  # noqa: E402
    make_train_step,
)
from distributeddataparallel_tpu.utils.chaos import (  # noqa: E402
    FaultInjector,
    SimulatedPreemption,
    parse_chaos_spec,
)
from distributeddataparallel_tpu.utils.metrics import FaultCounters  # noqa: E402


# ---------------------------------------------------------------- units


def test_retry_policy_backoff_grows_and_caps():
    p = RetryPolicy(2, backoff_s=0.001, max_backoff_s=0.004, jitter=0.0)
    assert p.sleep(0) == pytest.approx(0.001)
    assert p.sleep(1) == pytest.approx(0.002)
    assert p.sleep(10) == pytest.approx(0.004)  # capped
    with pytest.raises(ValueError, match="retries"):
        RetryPolicy(-1)


def test_nonfinite_breaker_counts_and_trips():
    b = NonFiniteBreaker(max_consecutive=3)
    assert b.observe(0.0) == 0
    assert b.observe(1.0) == 1
    assert b.observe(0.0) == 0  # a good step resets the run
    b.observe(1.0)
    b.observe(1.0)
    with pytest.raises(TrainingDiverged, match="3 consecutive"):
        b.observe(1.0)
    assert b.total == 4
    with pytest.raises(ValueError, match="max_consecutive"):
        NonFiniteBreaker(0)


def test_chaos_spec_parses_and_rejects():
    entries = parse_chaos_spec("ckpt-io@0:2, nan-grad@3,slow-step@5:2.5,preempt@12")
    assert [(e.kind, e.step, e.arg) for e in entries] == [
        ("ckpt-io", 0, "2"),
        ("nan-grad", 3, None),
        ("slow-step", 5, "2.5"),
        ("preempt", 12, None),
    ]
    assert parse_chaos_spec("") == []
    for bad in ("bogus@2", "nan-grad@", "preempt@3:4", "ckpt-io",
                "slow-step@-1", "slow-step@2:fast"):
        with pytest.raises(ValueError, match="bad chaos entry"):
            parse_chaos_spec(bad)


def test_chaos_cli_validation():
    base = ["--device", "cpu", "--fake-devices", "8"]
    with pytest.raises(SystemExit, match="--chaos"):
        dpp.validate_args(dpp.parse_args(base + ["--chaos", "bogus@2"]))
    with pytest.raises(SystemExit, match="--max-restarts requires"):
        dpp.validate_args(dpp.parse_args(base + ["--max-restarts", "2"]))
    with pytest.raises(SystemExit, match="--step-timeout"):
        dpp.validate_args(dpp.parse_args(base + ["--step-timeout", "0"]))
    with pytest.raises(SystemExit, match="--nan-guard"):
        dpp.validate_args(dpp.parse_args(
            base + ["--nan-guard", "--fsdp", "--model", "gpt2"]
        ))


def test_chaos_markers_fire_at_most_once_across_restarts(tmp_path):
    sd = str(tmp_path / "chaos")
    first = FaultInjector("preempt@4", state_dir=sd)
    with pytest.raises(SimulatedPreemption):
        first.before_step(4)
    # A restarted incarnation sees the marker and does not re-raise:
    second = FaultInjector("preempt@4", state_dir=sd)
    second.before_step(4)


def test_watchdog_fires_with_diagnostic_and_hook():
    hook = {}
    wd = StepWatchdog(
        0.25, on_timeout=hook.update, exit_process=False, poll_s=0.05
    )
    wd.start(epoch=1, batch=7)
    deadline = time.monotonic() + 5.0
    while wd.fired is None and time.monotonic() < deadline:
        time.sleep(0.05)
    wd.stop()
    assert wd.fired is not None
    assert hook["last_known_state"] == {"epoch": 1, "batch": 7}
    assert hook["seconds_since_heartbeat"] > 0.25
    assert hook["devices"]  # roster captured at start()


def test_watchdog_heartbeats_keep_it_quiet():
    wd = StepWatchdog(0.4, exit_process=False, poll_s=0.05)
    wd.start()
    assert wd.running
    for i in range(16):  # 0.8s of wall clock, beats well inside deadline
        time.sleep(0.05)
        wd.beat(i=i)
    wd.stop()
    assert wd.fired is None
    with pytest.raises(ValueError, match="timeout_s"):
        StepWatchdog(0.0)


# ------------------------------------------------- resilient checkpointer


def _toy_state(val, step=0):
    return {
        "params": {"w": np.full((4, 4), val, np.float32)},
        "step": np.full((), step, np.int32),
    }


def test_ckpt_io_retry_recovers(devices, tmp_path):
    counters = FaultCounters()
    ckpt = ResilientCheckpointer(
        str(tmp_path / "ck"),
        policy=RetryPolicy(3, backoff_s=0.01, jitter=0.0),
        injector=FaultInjector("ckpt-io@0:2"),
        counters=counters,
    )
    ckpt.save(_toy_state(1.5), 0)
    assert counters.io_retries == 2
    assert ckpt.latest_step() == 0
    restored, nxt = ckpt.restore_latest(_toy_state(0.0))
    assert nxt == 1
    np.testing.assert_array_equal(restored["params"]["w"], 1.5)


def test_ckpt_retry_budget_exhausts(devices, tmp_path):
    ckpt = ResilientCheckpointer(
        str(tmp_path / "ck"),
        policy=RetryPolicy(1, backoff_s=0.01, jitter=0.0),
        injector=FaultInjector("ckpt-io@0:99"),
    )
    with pytest.raises(CheckpointUnrecoverable, match="after 2 attempts"):
        ckpt.save(_toy_state(1.0), 0)


def test_corrupt_checkpoint_falls_back_to_previous(devices, tmp_path):
    d = str(tmp_path / "ck")
    counters = FaultCounters()
    ckpt = ResilientCheckpointer(d, counters=counters)
    ckpt.save(_toy_state(1.0, step=10), 0)
    ckpt.save(_toy_state(2.0, step=20), 1)
    assert ckpt.latest_step() == 1

    # Tear the newest step: overwrite every file in its dir with garbage
    # (the shape of a half-written checkpoint on a crashed host).
    step_dir = ckpt._step_dir(1)
    assert step_dir is not None
    for root, _, files in os.walk(step_dir):
        for f in files:
            with open(os.path.join(root, f), "wb") as fh:
                fh.write(b"\x00corrupt\x00")

    restored, nxt = ckpt.restore_latest(_toy_state(0.0))
    assert nxt == 1  # fell back to step 0 -> resume epoch 1
    np.testing.assert_array_equal(restored["params"]["w"], 1.0)
    assert counters.ckpt_fallbacks == 1
    # The bad step was quarantined for post-mortem, not destroyed:
    assert glob.glob(os.path.join(d, "*.corrupt*"))


def test_all_checkpoints_corrupt_means_fresh_start(devices, tmp_path):
    d = str(tmp_path / "ck")
    ckpt = ResilientCheckpointer(d, counters=FaultCounters())
    ckpt.save(_toy_state(3.0), 0)
    step_dir = ckpt._step_dir(0)
    for root, _, files in os.walk(step_dir):
        for f in files:
            with open(os.path.join(root, f), "wb") as fh:
                fh.write(b"garbage")
    fresh = _toy_state(7.0)
    restored, nxt = ckpt.restore_latest(fresh)
    assert nxt == 0  # nothing intact left: train from scratch
    np.testing.assert_array_equal(restored["params"]["w"], 7.0)


# ------------------------------------------------- non-finite grad guard


def test_nonfinite_guard_skips_step_and_reports(devices):
    mesh = make_mesh(("data",))
    n = mesh.shape["data"]
    model = TinyMLP(features=(16,), num_classes=10)
    params = model.init(jax.random.PRNGKey(0), jnp.zeros((1, 8, 8, 1)))["params"]

    def loss_fn(p, batch, rng):
        return cross_entropy_loss(
            model.apply({"params": p}, batch["image"]), batch["label"]
        ), {}

    state = broadcast_params(
        TrainState.create(
            apply_fn=model.apply, params=params, tx=optax.sgd(0.1)
        ),
        mesh,
    )
    step = make_train_step(loss_fn, mesh=mesh, nonfinite_guard=True, donate=False)
    from distributeddataparallel_tpu.data.loader import shard_batch

    rng = np.random.default_rng(0)
    good = {
        "image": rng.normal(size=(8 * n, 8, 8, 1)).astype(np.float32),
        "label": rng.integers(0, 10, size=(8 * n,)).astype(np.int32),
    }
    bad = {**good, "image": good["image"].copy()}
    bad["image"][0, 0, 0, 0] = np.nan

    s1, m1 = step(state, shard_batch(bad, mesh), jax.random.PRNGKey(0))
    assert float(m1["nonfinite_grad"]) == 1.0
    # Update skipped: params and opt state identical, only step advanced.
    for a, b in zip(jax.tree.leaves(state.params), jax.tree.leaves(s1.params)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    assert int(s1.step) == int(state.step) + 1

    s2, m2 = step(s1, shard_batch(good, mesh), jax.random.PRNGKey(0))
    assert float(m2["nonfinite_grad"]) == 0.0
    changed = any(
        not np.array_equal(np.asarray(a), np.asarray(b))
        for a, b in zip(jax.tree.leaves(s1.params), jax.tree.leaves(s2.params))
    )
    assert changed  # a finite step actually trains


def test_nan_guard_end_to_end_survives_poisoned_step(devices):
    args = dpp.parse_args(
        ["--device", "cpu", "--dataset", "synthetic", "--model", "mlp",
         "--num-examples", "128", "--batch-size", "4", "--epochs", "1",
         "--log-every", "1000", "--nan-guard", "--chaos", "nan-grad@1"]
    )
    # Without the guard the poisoned step-1 batch would NaN the params and
    # every loss after; a finite final loss IS the skip working end to end.
    loss = dpp.train(args)
    assert loss == loss and loss < 2.4


# ---------------------------------------------------- satellite guards


def test_powersgd_rejects_model_axes(devices):
    mesh = make_mesh(("data", "model"), shape=(4, 2))

    def loss_fn(p, b, r):
        return jnp.zeros(()), {}

    with pytest.raises(ValueError, match="powersgd"):
        make_train_step(
            loss_fn, mesh=mesh, grad_compress="powersgd", tp_axis="model"
        )


def test_bf16_compress_skips_mixed_dtype_buckets(devices):
    from jax.sharding import PartitionSpec as P

    from distributeddataparallel_tpu.parallel.data_parallel import (
        bucket_gradients,
    )

    mesh = make_mesh(("data",))
    grads = {
        "f32": np.linspace(0.0, 1.0, 64, dtype=np.float32),
        "bf16": np.linspace(0.0, 1.0, 64, dtype=np.float32).astype(
            jnp.bfloat16
        ),
    }
    stacked = jax.tree.map(lambda x: np.stack([x] * 8), grads)

    def f(shard):
        local = jax.tree.map(lambda x: x[0], shard)
        return bucket_gradients(
            local, "data", op="mean", bucket_bytes=1 << 30, compress="bf16"
        )

    out = jax.jit(
        jax.shard_map(f, mesh=mesh, in_specs=(P("data"),), out_specs=P())
    )(stacked)
    # The mixed f32/bf16 bucket must NOT round-trip through bf16: the f32
    # leaf keeps dtype and full precision.  A bf16 round-trip would show
    # ~4e-3 relative error (8-bit mantissa); allow only f32 psum ulps.
    assert out["f32"].dtype == jnp.float32
    np.testing.assert_allclose(
        np.asarray(out["f32"]), grads["f32"], rtol=1e-6, atol=1e-7
    )


def test_elastic_powersgd_restore_across_degrees(devices, tmp_path):
    """Cross-degree PowerSGD resume (8 -> 3, non-divisible): the warm Q
    factors transport, the residuals rebuild zeroed at the NEW degree —
    via a host-side numpy-template restore of the throwaway old-degree
    rows (no device materialization of the old residuals)."""
    from jax.sharding import Mesh

    from distributeddataparallel_tpu.parallel.powersgd import (
        _is_entry,
        powersgd_state,
    )
    from distributeddataparallel_tpu.training.elastic import (
        elastic_restore,
        topology_meta,
    )

    mesh8 = make_mesh(("data",))
    model = TinyMLP(features=(64,), num_classes=10)
    params = model.init(
        jax.random.PRNGKey(0), jnp.zeros((1, 16, 16, 1))
    )["params"]

    def loss_fn(p, batch, rng):
        return cross_entropy_loss(
            model.apply({"params": p}, batch["image"]), batch["label"]
        ), {}

    from distributeddataparallel_tpu.data.loader import shard_batch

    rng = np.random.default_rng(0)
    batch = {
        "image": rng.normal(size=(24, 16, 16, 1)).astype(np.float32),
        "label": rng.integers(0, 10, size=(24,)).astype(np.int32),
    }
    st8 = broadcast_params(
        TrainState.create(
            apply_fn=model.apply, params=params, tx=optax.sgd(0.1)
        ).replace(comm_state=powersgd_state(params, 8, rank=2)),
        mesh8,
    )
    step8 = make_train_step(
        loss_fn, mesh=mesh8, grad_compress="powersgd", donate=False
    )
    st8, _ = step8(st8, shard_batch(batch, mesh8), jax.random.PRNGKey(0))

    ckpt = ResilientCheckpointer(str(tmp_path / "ck"))
    ckpt.save(st8, 0, meta=topology_meta(mesh8, "replicated"))
    saved_qs = [
        np.asarray(e.q)
        for e in jax.tree.leaves(st8.comm_state, is_leaf=_is_entry)
        if e is not None
    ]

    mesh3 = Mesh(np.array(jax.devices()[:3]), ("data",))
    st3 = broadcast_params(
        TrainState.create(
            apply_fn=model.apply, params=params, tx=optax.sgd(0.1)
        ).replace(comm_state=powersgd_state(params, 3, rank=2)),
        mesh3,
    )
    st3, nxt = elastic_restore(ckpt, st3, mesh3, layout="replicated")
    assert nxt == 1
    got = [
        e for e in jax.tree.leaves(st3.comm_state, is_leaf=_is_entry)
        if e is not None
    ]
    assert len(got) == len(saved_qs) > 0
    for e, q in zip(got, saved_qs):
        np.testing.assert_allclose(np.asarray(e.q), q, rtol=1e-6)
        assert e.err.shape[0] == 3  # rebuilt at the NEW degree
        assert not np.any(np.asarray(e.err))
    for a, b in zip(jax.tree.leaves(st3.params), jax.tree.leaves(st8.params)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=1e-6)


# ----------------------------------------------- acceptance: chaos e2e


def test_preemption_and_io_fault_resume_to_loss_parity(devices, tmp_path):
    """ISSUE acceptance: a chaos run that kills the worker mid-epoch AND
    injects one checkpoint-IO fault still completes training, with a
    final loss matching the uninterrupted run (deterministic per-step
    RNG stream + elastic resume -> near-exact replay)."""
    base = [
        "--device", "cpu", "--fake-devices", "8",
        "--model", "mlp", "--dataset", "synthetic",
        "--num-examples", "128", "--batch-size", "4",
        "--epochs", "3", "--steps-per-epoch", "4", "--log-every", "1",
    ]
    ref = dpp.train(dpp.parse_args(base))  # uninterrupted reference

    ck = str(tmp_path / "ck")
    result = str(tmp_path / "loss.txt")
    # preempt@6 = epoch 1, batch 2: after epoch 0's checkpoint committed
    # (through its injected IO failure + retry), before epoch 1's.
    spawn(
        dpp._worker,
        args=(base + ["--checkpoint-dir", ck, "--resume"], result),
        nprocs=1,
        max_restarts=2,
        env={
            "_DDP_SUPERVISED": "1",
            "DDP_CHAOS": "ckpt-io@0,preempt@6",
            "DDP_CHAOS_STATE": os.path.join(ck, ".chaos"),
        },
    )
    chaotic = float(open(result).read())
    assert abs(chaotic - ref) < 5e-2, (chaotic, ref)
