"""Checkpoint/resume end-to-end: save -> restore -> bitwise-identical
continued training, for plain DP and ZeRO-sharded optimizer state, plus the
CLI --resume path.

The reference has no checkpointing at all (training state dies with the
process, ref dpp.py:44-57; SURVEY.md §5) — this is the beyond-parity
surface BASELINE configs 3-5 require.  The invariant pinned here is the
strongest one: an interrupted-and-resumed run must be indistinguishable
from an uninterrupted one, leaf for leaf.
"""

import sys

import jax
import jax.numpy as jnp
import numpy as np
import optax

sys.path.insert(0, "/root/repo")

import distributeddataparallel_tpu as ddp  # noqa: E402
from distributeddataparallel_tpu.data.loader import shard_batch  # noqa: E402
from distributeddataparallel_tpu.models import TinyMLP  # noqa: E402
from distributeddataparallel_tpu.ops import cross_entropy_loss  # noqa: E402
from distributeddataparallel_tpu.training.checkpoint import Checkpointer  # noqa: E402


def _snapshot(tree):
    """Host copy of every leaf (the step donates device buffers)."""
    return jax.tree.map(np.asarray, tree)


def _assert_trees_equal(a, b, what=""):
    la, lb = jax.tree.leaves(a), jax.tree.leaves(b)
    assert len(la) == len(lb), what
    for x, y in zip(la, lb):
        np.testing.assert_array_equal(np.asarray(x), np.asarray(y), err_msg=what)


def _make_batches(mesh, n, seed=0):
    rng = np.random.default_rng(seed)
    out = []
    for _ in range(n):
        out.append(
            shard_batch(
                {
                    "image": rng.normal(size=(16, 8, 8, 1)).astype(np.float32),
                    "label": rng.integers(0, 10, size=(16,)).astype(np.int32),
                },
                mesh,
            )
        )
    return out


def _setup(mesh, tx, *, zero=False, init_seed=0):
    model = TinyMLP(features=(32,))
    params = model.init(
        jax.random.PRNGKey(init_seed), jnp.zeros((1, 8, 8, 1))
    )["params"]

    def loss_fn(p, b, r):
        logits = model.apply({"params": p}, b["image"])
        return cross_entropy_loss(logits, b["label"]), {}

    if zero:
        state = ddp.zero_state(
            apply_fn=model.apply, params=params, tx=tx, mesh=mesh
        )
    else:
        state = ddp.TrainState.create(
            apply_fn=model.apply, params=params, tx=tx
        )
    state = ddp.broadcast_params(state, mesh)
    step = ddp.make_train_step(loss_fn, mesh=mesh, zero=zero)
    return state, step


def _run_split(tmp_path, devices, *, zero, tx_factory):
    """Train 2 steps, checkpoint, train 2 more (reference run); then restore
    into a differently-initialized state and replay the last 2 steps."""
    mesh = ddp.make_mesh(("data",))
    batches = _make_batches(mesh, 4)
    rngs = [jax.random.PRNGKey(100 + i) for i in range(4)]

    state, step = _setup(mesh, tx_factory(), zero=zero, init_seed=0)
    for i in range(2):
        state, _ = step(state, batches[i], rngs[i])
    ckpt = Checkpointer(str(tmp_path / "ckpt"))
    ckpt.save(state, 0)
    ckpt.wait()
    at_save = _snapshot({"params": state.params, "opt": state.opt_state,
                         "step": state.step})
    for i in range(2, 4):
        state, _ = step(state, batches[i], rngs[i])
    reference_final = _snapshot({"params": state.params, "opt": state.opt_state})

    # Fresh process-restart analog: DIFFERENT init seed proves restore
    # actually overwrites, not that both runs started identically.
    state2, step2 = _setup(mesh, tx_factory(), zero=zero, init_seed=7)
    ckpt2 = Checkpointer(str(tmp_path / "ckpt"))
    template_shardings = [
        leaf.sharding for leaf in jax.tree.leaves(state2.opt_state)
    ]
    state2, next_epoch = ckpt2.restore_latest(state2)
    assert next_epoch == 1
    _assert_trees_equal(
        {"params": state2.params, "opt": state2.opt_state, "step": state2.step},
        at_save,
        "restored state != state at save time",
    )
    # Restored leaves must keep the template's shardings (ZeRO: the flat
    # optimizer vectors stay 1/N-sharded along the data axis, zero.py:91-119).
    for leaf, want in zip(jax.tree.leaves(state2.opt_state), template_shardings):
        assert leaf.sharding.is_equivalent_to(want, leaf.ndim), (
            leaf.sharding, want)

    for i in range(2, 4):
        state2, _ = step2(state2, batches[i], rngs[i])
    # The restore itself is pinned bitwise above; the CONTINUED steps get
    # ulp slack — this XLA:CPU build's threaded reductions (ZeRO's
    # reduce-scatter especially) are not run-to-run deterministic.
    for x, y in zip(
        jax.tree.leaves({"params": state2.params, "opt": state2.opt_state}),
        jax.tree.leaves(reference_final),
    ):
        np.testing.assert_allclose(
            np.asarray(x), np.asarray(y), rtol=1e-4, atol=1e-8,
            err_msg="resumed training diverged from uninterrupted run",
        )


def test_dp_save_restore_bitwise(tmp_path, devices):
    # momentum: non-trivial optimizer state must round-trip too.
    _run_split(tmp_path, devices, zero=False,
               tx_factory=lambda: optax.sgd(0.05, momentum=0.9))


def test_zero_sharded_save_restore_bitwise(tmp_path, devices):
    # adam: mu/nu live ZeRO-sharded (1/8 per device) through the round-trip.
    _run_split(tmp_path, devices, zero=True,
               tx_factory=lambda: optax.adam(1e-3))


def test_restore_latest_empty_dir(tmp_path, devices):
    mesh = ddp.make_mesh(("data",))
    state, _ = _setup(mesh, optax.sgd(0.1))
    ckpt = Checkpointer(str(tmp_path / "empty"))
    restored, epoch = ckpt.restore_latest(state)
    assert epoch == 0
    _assert_trees_equal(restored.params, state.params)


def test_cli_resume_matches_uninterrupted(tmp_path, devices):
    """--checkpoint-dir/--resume (dpp.py:358-364): 1 epoch + resume-to-2
    must equal an uninterrupted 2-epoch run exactly (no dropout, fixed
    seeds -> deterministic)."""
    import dpp

    def run(ckpt_dir, epochs, resume):
        argv = [
            "--device", "cpu", "--dataset", "synthetic",
            "--num-examples", "256", "--batch-size", "8",
            "--model", "mlp", "--lr", "0.1", "--log-every", "1000",
            "--epochs", str(epochs), "--checkpoint-dir", str(ckpt_dir),
        ]
        if resume:
            argv.append("--resume")
        return dpp.train(dpp.parse_args(argv))

    loss_full = run(tmp_path / "full", 2, resume=False)

    run(tmp_path / "split", 1, resume=False)
    loss_resumed = run(tmp_path / "split", 2, resume=True)
    assert loss_resumed == loss_full, (loss_resumed, loss_full)


def _resume_matches_uninterrupted(
    tmp_path, name, step, fresh_state, batches, key, check_restored=None
):
    """Shared skeleton for the sharded-layout resume tests: 4-step
    uninterrupted reference vs 2 steps -> save -> restore into a fresh
    skeleton (-> optional layout check) -> 2 more steps; must match
    leaf-for-leaf."""
    ref = fresh_state()
    for b in batches:
        ref, _ = step(ref, b, key)

    st = fresh_state()
    for b in batches[:2]:
        st, _ = step(st, b, key)
    ckpt = Checkpointer(str(tmp_path / name))
    ckpt.save(st, epoch=0)
    ckpt.wait()
    restored, epoch = Checkpointer(str(tmp_path / name)).restore_latest(
        fresh_state()
    )
    assert epoch == 1  # next epoch to run
    if check_restored is not None:
        check_restored(restored)
    for b in batches[2:]:
        restored, _ = step(restored, b, key)

    _assert_trees_equal(restored.params, ref.params, "params after resume")
    _assert_trees_equal(
        restored.opt_state, ref.opt_state, "opt state after resume"
    )


def test_checkpoint_resume_tp_sharded(tmp_path, devices):
    """TP-sharded state survives save -> restore with its Megatron layout
    intact, and resumed training matches the uninterrupted run exactly."""
    import dataclasses

    from distributeddataparallel_tpu.models import TransformerLM, tiny_lm
    from distributeddataparallel_tpu.ops import lm_cross_entropy

    mesh = ddp.make_mesh(("data", "model"), shape=(4, 2))
    cfg = tiny_lm(num_heads=4, num_kv_heads=2, d_model=32, d_ff=64)
    cfg_tp = dataclasses.replace(cfg, tp_axis="model")
    model_tp = TransformerLM(cfg_tp)
    params = TransformerLM(cfg).init(
        jax.random.PRNGKey(0), jnp.zeros((1, 16), jnp.int32)
    )["params"]

    def loss_fn(p, batch, rng):
        toks = batch["tokens"]
        logits = model_tp.apply({"params": p}, toks[:, :-1])
        return lm_cross_entropy(logits, toks[:, 1:]), {}

    rng = np.random.default_rng(7)
    batches = [
        shard_batch(
            {"tokens": rng.integers(0, 256, size=(8, 17)).astype(np.int32)},
            mesh,
        )
        for _ in range(4)
    ]

    tx = optax.adam(1e-2)  # one instance: tx is static pytree metadata

    def fresh_state():
        state = ddp.TrainState.create(
            apply_fn=model_tp.apply, params=params, tx=tx
        )
        return ddp.shard_state_tp(state, mesh)

    step = ddp.make_train_step(
        loss_fn, mesh=mesh, tp_axis="model", donate=False
    )

    def check(restored):
        # Restored leaves keep the TP sharding (no silent replication).
        from distributeddataparallel_tpu.parallel import tp_param_specs

        for leaf, spec in zip(
            jax.tree.leaves(restored.params),
            jax.tree.leaves(tp_param_specs(params)),
        ):
            got = leaf.sharding.spec if hasattr(leaf.sharding, "spec") else None
            if any(spec):
                assert got == spec, (got, spec)

    _resume_matches_uninterrupted(
        tmp_path, "tp", step, fresh_state, batches, jax.random.PRNGKey(1),
        check_restored=check,
    )


def test_checkpoint_resume_pp_sharded(tmp_path, devices):
    """GPipe-sharded state (layer stack over the pipe axis) survives
    save -> restore with its sharding intact; resumed training matches
    the uninterrupted run exactly."""
    from distributeddataparallel_tpu.models import TransformerLM, tiny_lm
    from distributeddataparallel_tpu.parallel import (
        make_pp_train_step,
        shard_state_pp,
    )

    mesh = ddp.make_mesh(("data", "pipe"), shape=(2, 4))
    cfg = tiny_lm(
        num_layers=4, num_heads=2, d_model=32, d_ff=64, max_seq_len=32,
        scan_layers=True,
    )
    params = TransformerLM(cfg).init(
        jax.random.PRNGKey(0), jnp.zeros((1, 32), jnp.int32)
    )["params"]
    tx = optax.adam(1e-2)
    rng = np.random.default_rng(5)
    batches = [
        shard_batch(
            {"tokens": rng.integers(0, 256, size=(8, 33)).astype(np.int32)},
            mesh,
        )
        for _ in range(4)
    ]
    step = make_pp_train_step(cfg, mesh=mesh, microbatches=2, donate=False)

    def fresh():
        st = ddp.TrainState.create(apply_fn=None, params=params, tx=tx)
        return shard_state_pp(st, mesh)

    def check(restored):
        # Layer leaves keep their pipe sharding after restore.
        leaf = restored.params["layers"]["block"]["attn"]["q_proj"]["kernel"]
        assert leaf.sharding.spec[0] == "pipe", leaf.sharding

    _resume_matches_uninterrupted(
        tmp_path, "pp", step, fresh, batches, jax.random.PRNGKey(1),
        check_restored=check,
    )


def test_checkpoint_resume_zero_tp_sharded(tmp_path, devices):
    """ZeRO × TP state (Megatron params + flat opt chunks sharded over
    BOTH axes) survives save -> restore with its layout intact, and
    resumed training matches the uninterrupted run exactly."""
    import dataclasses

    from jax.sharding import PartitionSpec as P

    from distributeddataparallel_tpu.models import TransformerLM, tiny_lm
    from distributeddataparallel_tpu.ops import lm_cross_entropy

    mesh = ddp.make_mesh(("data", "model"), shape=(4, 2))
    cfg = tiny_lm(num_heads=4, num_kv_heads=2, d_model=32, d_ff=64)
    cfg_tp = dataclasses.replace(cfg, tp_axis="model")
    model_tp = TransformerLM(cfg_tp)
    params = TransformerLM(cfg).init(
        jax.random.PRNGKey(0), jnp.zeros((1, 16), jnp.int32)
    )["params"]

    def loss_fn(p, batch, rng):
        toks = batch["tokens"]
        logits = model_tp.apply({"params": p}, toks[:, :-1])
        return lm_cross_entropy(logits, toks[:, 1:]), {}

    rng = np.random.default_rng(9)
    batches = [
        shard_batch(
            {"tokens": rng.integers(0, 256, size=(8, 17)).astype(np.int32)},
            mesh,
        )
        for _ in range(4)
    ]

    tx = optax.adam(1e-2)

    def fresh_state():
        return ddp.zero_state(
            apply_fn=model_tp.apply, params=params, tx=tx, mesh=mesh,
            tp_axis="model",
        )

    step = ddp.make_train_step(
        loss_fn, mesh=mesh, tp_axis="model", zero=True, donate=False
    )

    def check(restored):
        # Flat opt vectors stay sharded over BOTH axes after restore.
        for leaf in jax.tree.leaves(restored.opt_state):
            if leaf.ndim >= 1:
                assert leaf.sharding.spec == P(("data", "model")), (
                    leaf.sharding
                )

    _resume_matches_uninterrupted(
        tmp_path, "zero_tp", step, fresh_state, batches,
        jax.random.PRNGKey(2), check_restored=check,
    )


def test_sigterm_preemption_checkpoint_and_resume(tmp_path, devices):
    """SIGTERM mid-training (the TPU-VM preemption signal) finishes the
    in-flight step, checkpoints, and exits cleanly; --resume continues
    from the NEXT epoch (the interrupted epoch's tail is skipped — the
    loader position is not part of the state)."""
    import os
    import pathlib
    import signal
    import subprocess
    import threading
    import time

    repo = str(pathlib.Path(__file__).resolve().parents[1])
    ckdir = str(tmp_path / "preempt")
    cmd = [
        sys.executable, "dpp.py", "--device", "cpu", "--fake-devices", "2",
        "--model", "mlp", "--epochs", "200", "--num-examples", "64",
        "--batch-size", "4", "--log-every", "1", "--lr", "0.05",
        "--checkpoint-dir", ckdir,
    ]
    env = dict(os.environ)
    proc = subprocess.Popen(
        cmd, cwd=repo, env=env,
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
    )
    # Watchdog: readline has no timeout of its own — kill a wedged child
    # so the test fails with diagnostics instead of hanging pytest.
    watchdog = threading.Timer(300, proc.kill)
    watchdog.start()
    saw_loss = False
    lines = []
    try:
        while True:
            line = proc.stdout.readline()
            if not line:
                break
            lines.append(line)
            if "Loss:" in line:
                saw_loss = True
                proc.send_signal(signal.SIGTERM)
                break
        assert saw_loss, "".join(lines[-20:])
        out, _ = proc.communicate(timeout=300)
    finally:
        watchdog.cancel()
    lines.append(out)
    all_out = "".join(lines)
    assert proc.returncode == 0, all_out[-2000:]
    # The child may have raced past epoch 0 before the signal landed —
    # read the ACTUAL preempted epoch from the log instead of assuming.
    import re

    m = re.search(r"checkpoint saved mid-epoch (\d+)", all_out)
    assert m, all_out[-2000:]
    saved_epoch = int(m.group(1))

    # Resume skips the interrupted epoch's tail and continues from the
    # NEXT epoch (epoch granularity: the loader position is not state).
    res = subprocess.run(
        cmd + ["--resume", "--epochs", str(saved_epoch + 3)],  # last-wins
        cwd=repo, env=env, capture_output=True, text=True,
        timeout=300,
    )
    logs = res.stdout + res.stderr  # log0 writes to stderr
    assert res.returncode == 0, logs
    assert f"Epoch {saved_epoch + 1}," in logs, logs
    assert f"Epoch {saved_epoch}," not in logs, logs


def test_checkpoint_resume_fsdp_sharded(tmp_path, devices):
    """FSDP state (per-layer flat chunks + sharded opt state) survives
    save -> restore with its 1/N layout intact, and resumed training
    matches the uninterrupted run exactly."""
    from jax.sharding import PartitionSpec as P

    from distributeddataparallel_tpu.models import TransformerLM, tiny_lm
    from distributeddataparallel_tpu.parallel.fsdp import (
        fsdp_state,
        make_fsdp_train_step,
    )

    cfg = tiny_lm(
        num_layers=2, num_heads=2, d_model=32, d_ff=64, max_seq_len=32,
        scan_layers=True,
    )
    mesh = ddp.make_mesh(("data",))
    params = TransformerLM(cfg).init(
        jax.random.PRNGKey(0), jnp.zeros((1, 16), jnp.int32)
    )["params"]
    rng = np.random.default_rng(23)
    batches = [
        shard_batch(
            {"tokens": rng.integers(0, 256, size=(8, 17)).astype(np.int32)},
            mesh,
        )
        for _ in range(4)
    ]
    tx = optax.adam(1e-2)

    def fresh_state():
        return fsdp_state(cfg, params, tx, mesh)

    step = make_fsdp_train_step(cfg, mesh=mesh, donate=False)

    def check(restored):
        assert restored.params["layers"].sharding.spec == P(None, "data")
        assert restored.params["rest"].sharding.spec == P("data")
        # Opt state keeps its 1/N layout too — a silently-replicated
        # restore would defeat the ZeRO-3 memory property while still
        # matching leaf values.
        for l in jax.tree.leaves(restored.opt_state):
            if l.ndim == 2:
                assert l.sharding.spec == P(None, "data"), l.sharding
            elif l.ndim == 1:
                assert l.sharding.spec == P("data"), l.sharding

    _resume_matches_uninterrupted(
        tmp_path, "fsdp", step, fresh_state, batches,
        jax.random.PRNGKey(3), check_restored=check,
    )
