"""bf16 gradient-compression comm hook (torch DDP ``bf16_compress_hook``
analog, the ``register_comm_hook`` surface behind ref dpp.py:52):

- numerics: a compressed DP step tracks the exact step to bf16 tolerance
  and the compression REALLY happens (wire dtype is bf16 in the compiled
  HLO; results differ bitwise from the exact step);
- composition: buckets, accumulation, grad-clip, the in-scan-body sync
  (scanned stacks), and the CLI flag;
- rejections: layouts that own their reductions (--zero/--fsdp/--pp).
"""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

import distributeddataparallel_tpu as ddp
from distributeddataparallel_tpu.data.loader import shard_batch
from distributeddataparallel_tpu.models import TransformerLM, tiny_lm
from distributeddataparallel_tpu.ops import lm_cross_entropy
from distributeddataparallel_tpu.parallel.data_parallel import (
    broadcast_params,
)
from distributeddataparallel_tpu.runtime.distributed import make_mesh
from distributeddataparallel_tpu.training.state import TrainState
from distributeddataparallel_tpu.training.train_step import make_train_step

from distributeddataparallel_tpu.models.simple_cnn import TinyMLP
from distributeddataparallel_tpu.ops.losses import cross_entropy_loss


def _setup(lr=0.1, seed=0):
    model = TinyMLP(features=(32,), num_classes=10)
    params = model.init(
        jax.random.PRNGKey(seed), jnp.zeros((1, 8, 8, 1))
    )["params"]

    def loss_fn(params, batch, rng):
        logits = model.apply({"params": params}, batch["image"])
        return cross_entropy_loss(logits, batch["label"]), {}

    state = TrainState.create(
        apply_fn=model.apply, params=params, tx=optax.sgd(lr)
    )
    return model, state, loss_fn


def _fake_batches(num_steps, global_batch, seed=0):
    rng = np.random.default_rng(seed)
    protos = rng.normal(size=(10, 8, 8, 1)).astype(np.float32)
    out = []
    for _ in range(num_steps):
        labels = rng.integers(0, 10, size=(global_batch,))
        imgs = protos[labels] + 0.1 * rng.normal(
            size=(global_batch, 8, 8, 1)
        ).astype(np.float32)
        out.append(
            {"image": imgs.astype(np.float32),
             "label": labels.astype(np.int32)}
        )
    return out


def _run_steps(state, loss_fn, mesh, batches, **kw):
    step = make_train_step(loss_fn, mesh=mesh, donate=False, **kw)
    state = broadcast_params(state, mesh)
    for b in batches:
        state, metrics = step(state, shard_batch(b, mesh), jax.random.PRNGKey(1))
    return state, metrics


def test_compress_tracks_exact_step(devices):
    """bf16-compressed DP == exact DP to bf16 tolerance over several
    steps — and not bitwise (the hook is live, not a no-op)."""
    mesh = make_mesh(("data",))
    batches = _fake_batches(4, 8 * len(jax.devices()))
    _, state, loss_fn = _setup()
    exact, _ = _run_steps(state, loss_fn, mesh, batches)
    comp, m = _run_steps(state, loss_fn, mesh, batches, grad_compress="bf16")
    exact_l, comp_l = jax.tree.leaves(exact.params), jax.tree.leaves(comp.params)
    for a, b in zip(exact_l, comp_l):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=0, atol=5e-3
        )
    assert float(m["loss"]) == float(m["loss"])
    assert any(
        not np.array_equal(np.asarray(a), np.asarray(b))
        for a, b in zip(exact_l, comp_l)
    ), "compression produced bitwise-identical params - hook not applied?"


def test_compress_wire_dtype_is_bf16(devices):
    """Every gradient psum in the traced step carries a bf16 payload
    (only the f32 loss-metric pmean stays f32).  Checked at jaxpr level:
    the CPU backend's float-normalization pass re-promotes bf16
    all-reduces to f32 in its compiled HLO, so the backend-independent
    trace is where the hook's wire dtype is visible on CPU; the TPU
    compiled wire dtype is pinned by the TPU-gated test below."""
    mesh = make_mesh(("data",))
    _, state, loss_fn = _setup()
    state = broadcast_params(state, mesh)
    batch = shard_batch(_fake_batches(1, 8 * len(jax.devices()))[0], mesh)
    step = make_train_step(
        loss_fn, mesh=mesh, donate=False, grad_compress="bf16"
    )
    jx = str(jax.make_jaxpr(step)(state, batch, jax.random.PRNGKey(0)))
    psums = [
        l.strip() for l in jx.splitlines()
        if "= psum" in l and "f32[]" not in l
    ]
    assert psums, "no gradient psums found in the traced step"
    assert all(
        ":bf16[" in l.split("=")[0] for l in psums
    ), f"non-bf16 gradient psum: {psums}"


def test_tpu_compress_wire_dtype(devices):
    """On the REAL TPU compiler the compressed all-reduce stays bf16 on
    the wire (no silent re-promotion), AOT-compiled for the 8-chip v5e
    topology."""
    pytest.importorskip("jax.experimental.topologies")
    from distributeddataparallel_tpu.parallel.overlap import (
        tpu_topology_mesh,
    )

    try:
        mesh = tpu_topology_mesh()
        _, state, loss_fn = _setup()
        state_sds = jax.eval_shape(lambda: state)
        batch = _fake_batches(1, 8 * mesh.devices.size)[0]
        batch_sds = {
            k: jax.ShapeDtypeStruct(v.shape, v.dtype)
            for k, v in batch.items()
        }
        step = make_train_step(
            loss_fn, mesh=mesh, donate=False, grad_compress="bf16"
        )
        txt = (
            step.lower(state_sds, batch_sds, jax.random.PRNGKey(0))
            .compile()
            .as_text()
        )
    except Exception as exc:  # no TPU compiler in this process
        pytest.skip(f"TPU topology compile unavailable: {exc!r}")
    assert any(
        "bf16[" in l.split("(")[0]
        for l in txt.splitlines()
        if "all-reduce" in l
    ), "no bf16 all-reduce in TPU HLO - wire compression lost"


def test_compress_composes_buckets_accum_clip(devices):
    """compress x {bucket_bytes, accum_steps, grad_clip} stays within
    bf16 tolerance of the exact composed step."""
    mesh = make_mesh(("data",))
    batches = _fake_batches(2, 8 * len(jax.devices()))
    _, state, loss_fn = _setup()
    kw = dict(bucket_bytes=1 << 10, accum_steps=2, grad_clip=1.0)
    exact, _ = _run_steps(state, loss_fn, mesh, batches, **kw)
    comp, _ = _run_steps(
        state, loss_fn, mesh, batches, grad_compress="bf16", **kw
    )
    for a, b in zip(
        jax.tree.leaves(exact.params), jax.tree.leaves(comp.params)
    ):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=0, atol=5e-3
        )


def test_compress_scan_body_sync(devices):
    """Scanned stack with grad_sync_axis + grad_sync_compress: the
    in-body bf16 reduction tracks the exact in-body reduction (presynced
    leaves excluded from the step's own sync in both runs)."""
    mesh = make_mesh(("data",))
    cfg = tiny_lm(
        num_layers=2, scan_layers=True, remat=True, grad_sync_axis="data"
    )
    cfg_c = dataclasses.replace(cfg, grad_sync_compress="bf16")
    rngs = np.random.default_rng(0)
    toks = rngs.integers(
        0, cfg.vocab_size, size=(2 * len(jax.devices()), 17)
    ).astype(np.int32)

    def make(cfg):
        model = TransformerLM(cfg)
        params = TransformerLM(
            dataclasses.replace(cfg, grad_sync_axis=None)
        ).init(jax.random.PRNGKey(0), jnp.zeros((1, 16), jnp.int32))["params"]

        def loss_fn(p, b, rng):
            logits = model.apply({"params": p}, b["tokens"][:, :-1])
            return lm_cross_entropy(logits, b["tokens"][:, 1:]), {}

        st = TrainState.create(
            apply_fn=None, params=params, tx=optax.sgd(0.05)
        )
        return st, loss_fn

    presync = lambda p: p[0] == "layers"  # noqa: E731
    st, lf = make(cfg)
    exact, _ = _run_steps(
        st, lf, mesh, [{"tokens": toks}], presynced=presync
    )
    st_c, lf_c = make(cfg_c)
    comp, _ = _run_steps(
        st_c, lf_c, mesh, [{"tokens": toks}],
        presynced=presync, grad_compress="bf16",
    )
    for a, b in zip(
        jax.tree.leaves(exact.params), jax.tree.leaves(comp.params)
    ):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=0, atol=5e-3
        )


def test_compress_rejections(devices):
    """Layouts that own their reductions reject the hook loudly."""
    mesh = make_mesh(("data",))
    _, state, loss_fn = _setup()
    with pytest.raises(ValueError, match="grad_compress"):
        make_train_step(
            loss_fn, mesh=mesh, zero=True, grad_compress="bf16"
        )
    with pytest.raises(ValueError, match="grad_compress"):
        make_train_step(
            loss_fn, mesh=mesh, grad_sync=False, grad_compress="bf16"
        )
    with pytest.raises(ValueError, match="compress"):
        ddp.all_reduce_gradients({}, compress="fp8")


def test_cli_grad_compress(devices):
    """dpp.py --grad-compress bf16 end-to-end; --zero rejects it."""
    import sys

    sys.path.insert(0, "/root/repo")
    import dpp

    args = dpp.parse_args(
        [
            "--device", "cpu", "--model", "mlp", "--epochs", "1",
            "--num-examples", "64", "--batch-size", "4",
            "--grad-compress", "bf16", "--log-every", "1000",
        ]
    )
    loss = dpp.train(args)
    assert loss == loss
    with pytest.raises(SystemExit, match="grad-compress"):
        dpp.validate_args(
            dpp.parse_args(
                ["--device", "cpu", "--model", "mlp", "--grad-compress",
                 "bf16", "--zero"]
            )
        )
