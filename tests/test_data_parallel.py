"""Gradient-sync primitive tests: psum/pmean correctness, bucket coalescing
equivalence, param replication (SURVEY.md §4 'multi-device without a cluster')."""

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import PartitionSpec as P

from distributeddataparallel_tpu.parallel.data_parallel import (
    DataParallel,
    all_reduce_gradients,
    broadcast_params,
    bucket_gradients,
)
from distributeddataparallel_tpu.runtime.distributed import make_mesh


def _grad_tree(key, sizes=((8, 16), (128,), (4, 4, 4), (1000,))):
    keys = jax.random.split(key, len(sizes))
    return {
        f"p{i}": jax.random.normal(k, s)
        for i, (k, s) in enumerate(zip(keys, sizes))
    }


def test_all_reduce_mean_matches_manual(devices):
    mesh = make_mesh(("data",))
    n = mesh.shape["data"]
    # per-replica distinct grads: shard a leading axis
    trees = [_grad_tree(jax.random.PRNGKey(i)) for i in range(n)]
    stacked = jax.tree.map(lambda *xs: jnp.stack(xs), *trees)

    def f(shard):
        # shard has leading dim 1 per replica
        local = jax.tree.map(lambda x: x[0], shard)
        return all_reduce_gradients(local, "data", op="mean")

    out = jax.jit(
        jax.shard_map(f, mesh=mesh, in_specs=(P("data"),), out_specs=P())
    )(stacked)
    expected = jax.tree.map(lambda *xs: jnp.mean(jnp.stack(xs), 0), *trees)
    for k in expected:
        # rtol/atol: XLA's psum may reduce in a different association
        # order than the host-side stack/mean — a few ulps of f32 slack
        # (atol covers near-zero elements where rtol alone is too sharp).
        np.testing.assert_allclose(out[k], expected[k], rtol=1e-5, atol=1e-7)


def test_bucketed_equals_unbucketed(devices):
    mesh = make_mesh(("data",))
    n = mesh.shape["data"]
    trees = [_grad_tree(jax.random.PRNGKey(100 + i)) for i in range(n)]
    stacked = jax.tree.map(lambda *xs: jnp.stack(xs), *trees)

    def f(shard):
        local = jax.tree.map(lambda x: x[0], shard)
        plain = all_reduce_gradients(local, "data", op="mean")
        # tiny bucket size forces multiple buckets; large forces one
        multi = bucket_gradients(local, "data", op="mean", bucket_bytes=2048)
        single = bucket_gradients(local, "data", op="mean", bucket_bytes=1 << 30)
        return plain, multi, single

    plain, multi, single = jax.jit(
        jax.shard_map(f, mesh=mesh, in_specs=(P("data"),), out_specs=(P(), P(), P()))
    )(stacked)
    for k in plain:
        np.testing.assert_allclose(multi[k], plain[k], rtol=1e-5, atol=1e-6)
        np.testing.assert_allclose(single[k], plain[k], rtol=1e-5, atol=1e-6)
        assert multi[k].dtype == plain[k].dtype


def test_bucket_sum_op(devices):
    mesh = make_mesh(("data",))

    def f(x):
        return bucket_gradients({"w": x}, "data", op="sum", bucket_bytes=64)["w"]

    xs = jnp.arange(8.0).reshape(8, 1)
    out = jax.jit(
        jax.shard_map(lambda x: f(x[0]), mesh=mesh, in_specs=(P("data"),), out_specs=P())
    )(xs)
    np.testing.assert_allclose(out, jnp.sum(xs))


def test_broadcast_params_replicates(devices):
    mesh = make_mesh(("data",))
    params = _grad_tree(jax.random.PRNGKey(0))
    rep = broadcast_params(params, mesh)
    for leaf in jax.tree.leaves(rep):
        assert leaf.sharding.is_fully_replicated
        assert len(leaf.sharding.device_set) == len(jax.devices())


def test_data_parallel_facade(devices):
    dp = DataParallel()
    assert dp.num_replicas == 8
    batch = {"image": np.ones((16, 4), np.float32), "label": np.zeros((16,), np.int32)}
    sharded = dp.shard_batch(batch)
    # leading dim split 8 ways -> 2 rows per device
    shard_shapes = {
        s.data.shape for s in sharded["image"].addressable_shards
    }
    assert shard_shapes == {(2, 4)}
    rep = dp.replicate({"w": np.ones((3, 3), np.float32)})
    assert jax.tree.leaves(rep)[0].sharding.is_fully_replicated
