"""Model zoo tests: shapes/dtypes, stem variants, BN state plumbing through
the DP train step (SURVEY.md §4 'unit': model forwards golden tests)."""

import jax
import jax.numpy as jnp
import numpy as np
import optax

from distributeddataparallel_tpu.models.resnet import ResNet18, ResNet50


def test_resnet18_cifar_stem_shapes():
    model = ResNet18(num_classes=10, stem="cifar")
    variables = model.init(jax.random.PRNGKey(0), jnp.zeros((2, 32, 32, 3)))
    logits = model.apply(variables, jnp.zeros((2, 32, 32, 3)), train=False)
    assert logits.shape == (2, 10)
    assert logits.dtype == jnp.float32
    assert "batch_stats" in variables


def test_resnet50_imagenet_stem_shapes():
    model = ResNet50(num_classes=1000, dtype=jnp.bfloat16)
    variables = model.init(jax.random.PRNGKey(0), jnp.zeros((1, 64, 64, 3)))
    logits = model.apply(variables, jnp.zeros((1, 64, 64, 3)), train=False)
    assert logits.shape == (1, 1000)
    # head forced to float32 even under bf16 compute
    assert logits.dtype == jnp.float32
    n_params = sum(x.size for x in jax.tree.leaves(variables["params"]))
    # torchvision resnet50 has 25.56M params; ours should match closely
    # (fc head 1000 classes). Allow small slack for impl details.
    assert abs(n_params - 25_557_032) / 25_557_032 < 0.02, n_params


def test_resnet18_param_count():
    model = ResNet18(num_classes=10, stem="cifar")
    variables = model.init(jax.random.PRNGKey(0), jnp.zeros((1, 32, 32, 3)))
    n_params = sum(x.size for x in jax.tree.leaves(variables["params"]))
    # torchvision resnet18 = 11.69M with 1000-class head (10-class head and
    # cifar stem shave the fc + conv1): sanity range
    assert 10_500_000 < n_params < 11_800_000, n_params


def test_resnet_train_step_with_bn(devices):
    """BN models run through the DP step; stats update and stay replicated."""
    import distributeddataparallel_tpu as ddp
    from distributeddataparallel_tpu.data.loader import shard_batch
    from distributeddataparallel_tpu.ops import cross_entropy_loss

    mesh = ddp.make_mesh(("data",))
    model = ResNet18(num_classes=10, stem="cifar", num_filters=8)
    variables = model.init(jax.random.PRNGKey(0), jnp.zeros((1, 16, 16, 3)))
    params = variables["params"]
    ms = {k: v for k, v in variables.items() if k != "params"}

    def loss_fn(params, ms, batch, rng):
        logits, new_vars = model.apply(
            {"params": params, **ms}, batch["image"], train=True,
            mutable=list(ms.keys()),
        )
        return cross_entropy_loss(logits, batch["label"]), ({}, new_vars)

    state = ddp.TrainState.create(
        apply_fn=model.apply, params=params, tx=optax.sgd(0.1), model_state=ms
    )
    state = ddp.broadcast_params(state, mesh)
    step = ddp.make_train_step(
        loss_fn, mesh=mesh, with_model_state=True, donate=False
    )

    rng = np.random.default_rng(0)
    B = 2 * mesh.shape["data"]
    batch = shard_batch(
        {
            "image": rng.normal(size=(B, 16, 16, 3)).astype(np.float32),
            "label": rng.integers(0, 10, size=(B,)).astype(np.int32),
        },
        mesh,
    )
    old_mean = np.asarray(
        jax.tree.leaves(state.model_state["batch_stats"])[0]
    ).copy()
    state2, metrics = step(state, batch, jax.random.PRNGKey(0))
    assert np.isfinite(float(metrics["loss"]))
    new_mean = np.asarray(jax.tree.leaves(state2.model_state["batch_stats"])[0])
    assert not np.allclose(old_mean, new_mean)  # stats updated
    # stats replicated across all devices
    leaf = jax.tree.leaves(state2.model_state["batch_stats"])[0]
    assert leaf.sharding.is_fully_replicated

    # accum path with BN state threads through the scan
    step_acc = ddp.make_train_step(
        loss_fn, mesh=mesh, with_model_state=True, accum_steps=2, donate=False
    )
    state3, metrics3 = step_acc(state, batch, jax.random.PRNGKey(0))
    assert np.isfinite(float(metrics3["loss"]))
