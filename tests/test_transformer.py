"""Transformer LM tests: shapes, causality, config families, param counts,
scan/remat equivalence, and a DP training smoke (loss decreases on the
synthetic Markov LM task)."""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

import distributeddataparallel_tpu as ddp
from distributeddataparallel_tpu.data import DataLoader, SyntheticLM
from distributeddataparallel_tpu.data.loader import shard_batch
from distributeddataparallel_tpu.models.transformer import (
    TransformerLM,
    gpt2_124m,
    llama3_8b,
    tiny_lm,
)
from distributeddataparallel_tpu.ops import lm_cross_entropy


def _init(cfg, B=2, S=16, seed=0):
    model = TransformerLM(cfg)
    toks = jnp.zeros((B, S), jnp.int32)
    params = model.init(jax.random.PRNGKey(seed), toks)["params"]
    return model, params


def test_lm_output_shapes_and_dtype():
    cfg = tiny_lm()
    model, params = _init(cfg)
    toks = jax.random.randint(jax.random.PRNGKey(1), (2, 16), 0, cfg.vocab_size)
    logits = model.apply({"params": params}, toks)
    assert logits.shape == (2, 16, cfg.vocab_size)
    assert logits.dtype == jnp.float32


def test_lm_is_causal():
    """Changing a later token must not change earlier logits."""
    cfg = tiny_lm()
    model, params = _init(cfg)
    toks = jax.random.randint(jax.random.PRNGKey(2), (1, 16), 0, cfg.vocab_size)
    out1 = model.apply({"params": params}, toks)
    toks2 = toks.at[0, -1].set((toks[0, -1] + 1) % cfg.vocab_size)
    out2 = model.apply({"params": params}, toks2)
    np.testing.assert_allclose(
        np.asarray(out1[:, :-1]), np.asarray(out2[:, :-1]), atol=1e-5
    )
    assert not np.allclose(out1[:, -1], out2[:, -1])


@pytest.mark.parametrize(
    "cfg_fn,kw", [(gpt2_124m, {}), (llama3_8b, {})], ids=["gpt2", "llama3"]
)
def test_family_configs_forward(cfg_fn, kw):
    """Both families run forward at test size (shrunk dims, family wiring)."""
    cfg = cfg_fn(
        num_layers=2, d_model=64, d_ff=128, num_heads=4,
        **({"num_kv_heads": 2} if cfg_fn is llama3_8b else {}),
        vocab_size=128, max_seq_len=64, dtype=jnp.float32, remat=False,
        scan_layers=False, **kw,
    )
    model, params = _init(cfg, S=32)
    toks = jax.random.randint(jax.random.PRNGKey(3), (2, 32), 0, 128)
    logits = model.apply({"params": params}, toks)
    assert logits.shape == (2, 32, 128)
    assert np.isfinite(np.asarray(logits)).all()


def test_gpt2_124m_param_count():
    """Full-size GPT-2 small must land on the published 124M total."""
    cfg = gpt2_124m()
    model = TransformerLM(cfg)
    shapes = jax.eval_shape(
        lambda k: model.init(k, jnp.zeros((1, 8), jnp.int32)),
        jax.random.PRNGKey(0),
    )["params"]
    n = sum(int(np.prod(s.shape)) for s in jax.tree.leaves(shapes))
    assert 124e6 < n < 125e6, f"got {n/1e6:.2f}M params"


def test_llama3_8b_param_count():
    cfg = llama3_8b()
    model = TransformerLM(cfg)
    shapes = jax.eval_shape(
        lambda k: model.init(k, jnp.zeros((1, 8), jnp.int32)),
        jax.random.PRNGKey(0),
    )["params"]
    n = sum(int(np.prod(s.shape)) for s in jax.tree.leaves(shapes))
    assert 8.0e9 < n < 8.1e9, f"got {n/1e9:.3f}B params"


def test_scan_and_loop_layers_agree():
    """scan_layers=True is a compile-time optimization, not a model change."""
    kw = dict(num_layers=3, seed=7)
    cfg_loop = tiny_lm(scan_layers=False, num_layers=3)
    cfg_scan = tiny_lm(scan_layers=True, num_layers=3)
    model_loop, params_loop = _init(cfg_loop, seed=7)
    model_scan = TransformerLM(cfg_scan)
    # Map loop params (layer_i/block subtrees) into the scan layout
    # (stacked along axis 0 under layers/block).
    stacked = {}
    layer_keys = [f"layer_{i}" for i in range(3)]

    def stack(trees):
        return jax.tree.map(lambda *xs: jnp.stack(xs), *trees)

    scan_params = {
        k: v for k, v in params_loop.items() if not k.startswith("layer_")
    }
    scan_params["layers"] = {"block": stack([params_loop[k] for k in layer_keys])}
    toks = jax.random.randint(jax.random.PRNGKey(8), (2, 16), 0, 256)
    out_loop = model_loop.apply({"params": params_loop}, toks)
    out_scan = model_scan.apply({"params": scan_params}, toks)
    np.testing.assert_allclose(
        np.asarray(out_loop), np.asarray(out_scan), atol=1e-5
    )


def test_remat_matches_plain():
    cfg_plain = tiny_lm(remat=False)
    cfg_remat = tiny_lm(remat=True)
    model_plain, params = _init(cfg_plain, seed=9)
    model_remat = TransformerLM(cfg_remat)
    toks = jax.random.randint(jax.random.PRNGKey(10), (2, 16), 0, 256)

    def loss(m, p):
        return lm_cross_entropy(
            m.apply({"params": p}, toks[:, :-1]), toks[:, 1:]
        )

    l1, g1 = jax.value_and_grad(lambda p: loss(model_plain, p))(params)
    l2, g2 = jax.value_and_grad(lambda p: loss(model_remat, p))(params)
    assert float(l1) == pytest.approx(float(l2), rel=1e-6)
    for a, b in zip(jax.tree.leaves(g1), jax.tree.leaves(g2)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=1e-5)


def test_scan_layers_respects_positions():
    """The scan path must forward explicit RoPE positions (sequence-parallel
    shards depend on this)."""
    cfg = tiny_lm(scan_layers=True)
    model, params = _init(cfg)
    toks = jax.random.randint(jax.random.PRNGKey(11), (1, 16), 0, 256)
    out_default = model.apply({"params": params}, toks)
    out_offset = model.apply(
        {"params": params}, toks, positions=jnp.arange(4, 20)
    )
    assert not np.allclose(out_default, out_offset)
    out_explicit = model.apply(
        {"params": params}, toks, positions=jnp.arange(16)
    )
    np.testing.assert_allclose(
        np.asarray(out_default), np.asarray(out_explicit), atol=1e-6
    )


def test_dropout_active_in_training_mode():
    cfg = tiny_lm(dropout_rate=0.5)
    model, params = _init(cfg)
    toks = jax.random.randint(jax.random.PRNGKey(12), (1, 16), 0, 256)
    out_det = model.apply({"params": params}, toks, deterministic=True)
    out_a = model.apply(
        {"params": params}, toks, deterministic=False,
        rngs={"dropout": jax.random.PRNGKey(1)},
    )
    out_b = model.apply(
        {"params": params}, toks, deterministic=False,
        rngs={"dropout": jax.random.PRNGKey(2)},
    )
    assert not np.allclose(out_a, out_b)
    assert not np.allclose(out_a, out_det)


def test_llama_has_no_biases():
    cfg = llama3_8b(
        num_layers=1, d_model=32, d_ff=64, num_heads=2, num_kv_heads=1,
        vocab_size=64, max_seq_len=32, dtype=jnp.float32, remat=False,
        scan_layers=False,
    )
    _, params = _init(cfg, S=8)
    names = [jax.tree_util.keystr(p) for p, _ in jax.tree.flatten_with_path(params)[0]]
    assert not any("bias" in n for n in names), names


def test_lm_cross_entropy_mask():
    logits = jnp.zeros((1, 4, 8))
    targets = jnp.zeros((1, 4), jnp.int32)
    full = lm_cross_entropy(logits, targets)
    half = lm_cross_entropy(logits, targets, mask=jnp.array([[1, 1, 0, 0]]))
    assert float(full) == pytest.approx(float(np.log(8)), rel=1e-5)
    assert float(half) == pytest.approx(float(np.log(8)), rel=1e-5)


def test_lm_dp_training_loss_decreases(devices):
    """End-to-end: tiny LM under the 8-way DP train step learns the
    synthetic Markov structure (BASELINE config-4 shape, test size)."""
    mesh = ddp.make_mesh(("data",))
    cfg = tiny_lm(num_layers=2, d_model=32)
    model = TransformerLM(cfg)
    ds = SyntheticLM(num_examples=512, seq_len=32, vocab_size=cfg.vocab_size)
    loader = DataLoader(ds, per_replica_batch=8, mesh=mesh, seed=0)
    params = model.init(
        jax.random.PRNGKey(0), jnp.zeros((1, 32), jnp.int32)
    )["params"]

    def loss_fn(params, batch, rng):
        toks = batch["tokens"]
        logits = model.apply({"params": params}, toks[:, :-1])
        return lm_cross_entropy(logits, toks[:, 1:]), {}

    state = ddp.TrainState.create(
        apply_fn=model.apply, params=params, tx=optax.adam(1e-2)
    )
    state = ddp.broadcast_params(state, mesh)
    step = ddp.make_train_step(loss_fn, mesh=mesh)

    losses = []
    for epoch in range(3):
        loader.set_epoch(epoch)
        for batch in loader:
            state, metrics = step(state, batch, jax.random.PRNGKey(epoch))
            losses.append(float(metrics["loss"]))
    assert losses[-1] < losses[0] - 0.5, (losses[0], losses[-1])
