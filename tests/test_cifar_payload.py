"""Real-format CIFAR-10 payload + pretrained fine-tune flow.

The reference's whole purpose is fine-tuning pretrained ResNet-18 on real
CIFAR-10 (ref dpp.py:14-15,33).  These tests synthesize a GENUINE
``cifar-10-python.tar.gz`` (python-pickle batches, CHW uint8 planes,
bytes keys — exactly the upstream layout) so the tar/extract/parse path
in ``data/datasets.py`` runs for real, and drive ``dpp.py --pretrained``
end-to-end for both converter families.
"""

import io
import os
import pickle
import tarfile

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from distributeddataparallel_tpu.data.datasets import (
    load_cifar10,
    normalize_images,
)
from distributeddataparallel_tpu.models import io as mio

N_PER_BATCH = 8  # tiny but genuine: 5 train batches + 1 test batch


def _raw_batches(seed=0):
    """The 6 pickle payloads, keyed like the upstream archive."""
    rng = np.random.default_rng(seed)
    out = {}
    for name in [f"data_batch_{i}" for i in range(1, 6)] + ["test_batch"]:
        out[name] = {
            b"data": rng.integers(
                0, 256, size=(N_PER_BATCH, 3072), dtype=np.uint8
            ),
            b"labels": [int(x) for x in rng.integers(0, 10, N_PER_BATCH)],
        }
    return out


def _write_cifar_tgz(root, batches):
    """A genuine cifar-10-python.tar.gz: pickle members under the
    standard cifar-10-batches-py/ prefix."""
    os.makedirs(root, exist_ok=True)
    tgz = os.path.join(root, "cifar-10-python.tar.gz")
    with tarfile.open(tgz, "w:gz") as tf:
        for name, payload in batches.items():
            blob = pickle.dumps(payload)
            info = tarfile.TarInfo(f"cifar-10-batches-py/{name}")
            info.size = len(blob)
            tf.addfile(info, io.BytesIO(blob))
    return tgz


@pytest.fixture()
def cifar_root(tmp_path):
    batches = _raw_batches()
    _write_cifar_tgz(str(tmp_path), batches)
    return str(tmp_path), batches


def test_load_cifar10_real_payload(cifar_root):
    """Extract-from-tar + pickle parse + CHW->HWC + normalize, checked
    value-for-value against the raw arrays that went into the archive."""
    root, batches = cifar_root
    ds = load_cifar10(root, train=True, synthetic_fallback=False)
    assert len(ds) == 5 * N_PER_BATCH
    # Extraction must have materialized the batch dir atomically.
    assert os.path.isdir(os.path.join(root, "cifar-10-batches-py"))

    want_u8 = np.concatenate(
        [
            batches[f"data_batch_{i}"][b"data"]
            .reshape(-1, 3, 32, 32)
            .transpose(0, 2, 3, 1)
            for i in range(1, 6)
        ]
    )
    np.testing.assert_allclose(ds.images, normalize_images(want_u8))
    assert ds.images.min() >= -1.0 and ds.images.max() <= 1.0
    want_labels = np.concatenate(
        [batches[f"data_batch_{i}"][b"labels"] for i in range(1, 6)]
    )
    np.testing.assert_array_equal(ds.labels, want_labels)

    test_ds = load_cifar10(root, train=False, synthetic_fallback=False)
    assert len(test_ds) == N_PER_BATCH
    np.testing.assert_array_equal(
        test_ds.labels, batches["test_batch"][b"labels"]
    )


def test_load_cifar10_real_payload_u8_mode(cifar_root):
    """keep_u8 stores raw uint8 and normalizes on access — __getitem__
    must agree exactly with the eager f32 pipeline."""
    root, _ = cifar_root
    eager = load_cifar10(root, train=True, synthetic_fallback=False)
    lazy = load_cifar10(
        root, train=True, synthetic_fallback=False, keep_u8=True
    )
    assert lazy.images.dtype == np.uint8 and lazy.normalize_u8
    img_lazy, lbl_lazy = lazy[3]
    img_eager, lbl_eager = eager[3]
    np.testing.assert_allclose(img_lazy, img_eager)
    assert lbl_lazy == lbl_eager


def test_cifar10_cli_trains_on_real_payload(cifar_root, devices):
    """dpp.py --dataset cifar10 against the real-format payload: loader,
    sharding, and a full epoch run off the parsed pickle batches."""
    import sys

    sys.path.insert(0, "/root/repo")
    import dpp

    root, _ = cifar_root
    args = dpp.parse_args(
        [
            "--device", "cpu",
            "--model", "cnn",
            "--dataset", "cifar10",
            "--data-root", root,
            "--epochs", "1",
            "--batch-size", "4",
            "--log-every", "1000",
        ]
    )
    loss = dpp.train(args)
    assert np.isfinite(loss)


def test_pretrained_resnet18_finetune_cli(cifar_root, devices):
    """The reference's end-to-end journey (ref dpp.py:14-15,33): a
    torchvision-layout ResNet-18 checkpoint + real-format CIFAR-10 ->
    ``--pretrained`` converts the state_dict into the initial params and
    training runs.  Also pins that the converted tree EQUALS the source
    (via load_pretrained directly)."""
    import sys

    from safetensors.numpy import save_file

    from distributeddataparallel_tpu.models.resnet import ResNet18

    sys.path.insert(0, "/root/repo")
    import dpp

    root, _ = cifar_root
    model = ResNet18(num_classes=10, stem="cifar")
    variables = model.init(
        jax.random.PRNGKey(7), jnp.zeros((1, 32, 32, 3), jnp.float32)
    )
    sd = mio.export_resnet_torch(
        variables, model.stage_sizes, bottleneck=False
    )
    ckpt = os.path.join(root, "resnet18.safetensors")
    save_file(sd, ckpt)

    # Direct conversion equality: torch layout -> our tree round-trips.
    fresh = model.init(
        jax.random.PRNGKey(8), jnp.zeros((1, 32, 32, 3), jnp.float32)
    )
    loaded = mio.load_pretrained(ckpt, model, fresh)
    for (path, a), b in zip(
        jax.tree_util.tree_flatten_with_path(loaded)[0],
        jax.tree.leaves(variables),
    ):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), atol=1e-6,
            err_msg="/".join(str(getattr(k, "key", k)) for k in path),
        )

    args = dpp.parse_args(
        [
            "--device", "cpu",
            "--model", "resnet18",
            "--dataset", "cifar10",
            "--data-root", root,
            "--pretrained", ckpt,
            "--epochs", "1",
            "--batch-size", "4",
            "--log-every", "1000",
        ]
    )
    loss = dpp.train(args)
    assert np.isfinite(loss)


def test_pretrained_gpt2_hf_cli(tmp_path, devices):
    """HF-layout GPT-2 tensors load through --pretrained: format sniffed,
    c_attn split, and the run trains (fine-tune flow for the LM family)."""
    import sys

    from safetensors.numpy import save_file

    sys.path.insert(0, "/root/repo")
    import dpp

    # Shapes for the CLI config below: d=32, heads=2, D=16, ff=128, V=64.
    d, V, S, L, ff = 32, 64, 32, 2, 128
    rng = np.random.default_rng(0)
    w = lambda *shape: (0.02 * rng.standard_normal(shape)).astype(np.float32)
    sd = {"wte.weight": w(V, d), "wpe.weight": w(S, d),
          "ln_f.weight": np.ones(d, np.float32), "ln_f.bias": w(d)}
    for i in range(L):
        p = f"h.{i}."
        sd.update({
            p + "ln_1.weight": np.ones(d, np.float32), p + "ln_1.bias": w(d),
            p + "attn.c_attn.weight": w(d, 3 * d),
            p + "attn.c_attn.bias": w(3 * d),
            p + "attn.c_proj.weight": w(d, d), p + "attn.c_proj.bias": w(d),
            p + "ln_2.weight": np.ones(d, np.float32), p + "ln_2.bias": w(d),
            p + "mlp.c_fc.weight": w(d, ff), p + "mlp.c_fc.bias": w(ff),
            p + "mlp.c_proj.weight": w(ff, d), p + "mlp.c_proj.bias": w(d),
        })
    ckpt = str(tmp_path / "gpt2.safetensors")
    save_file(sd, ckpt)

    args = dpp.parse_args(
        [
            "--device", "cpu",
            "--model", "gpt2",
            "--layers", str(L),
            "--d-model", str(d),
            "--seq-len", str(S),
            "--vocab-size", str(V),
            "--pretrained", ckpt,
            "--epochs", "1",
            "--num-examples", "64",
            "--batch-size", "4",
            "--log-every", "1000",
        ]
    )
    loss = dpp.train(args)
    assert np.isfinite(loss)


def test_pretrained_native_safetensors(devices):
    """The framework's own save_params output loads through the
    --pretrained sniffing path (no conversion, strict shape check)."""
    from distributeddataparallel_tpu.models import TransformerLM, tiny_lm

    import tempfile

    cfg = tiny_lm()
    model = TransformerLM(cfg)
    toks = jnp.zeros((1, 16), jnp.int32)
    variables = model.init(jax.random.PRNGKey(0), toks)
    with tempfile.TemporaryDirectory() as td:
        path = os.path.join(td, "params.safetensors")
        mio.save_params(variables["params"], path)
        fresh = model.init(jax.random.PRNGKey(1), toks)
        loaded = mio.load_pretrained(path, model, fresh)
    for a, b in zip(
        jax.tree.leaves(loaded["params"]), jax.tree.leaves(variables["params"])
    ):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b))
