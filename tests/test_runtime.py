"""Runtime tests: process-group lifecycle, mesh construction, launcher."""

import jax
import numpy as np
import pytest

from distributeddataparallel_tpu.runtime import distributed as dist
from distributeddataparallel_tpu.runtime.launcher import spawn


def test_init_destroy_lifecycle():
    assert not dist.is_initialized()
    dist.init_process_group("cpu")
    assert dist.is_initialized()
    with pytest.raises(RuntimeError):
        dist.init_process_group("cpu")
    assert dist.get_rank() == 0
    assert dist.get_world_size() == 1
    assert dist.local_device_count() == 8
    assert dist.global_device_count() == 8
    dist.destroy_process_group()
    assert not dist.is_initialized()
    # re-init after destroy works
    dist.init_process_group("cpu")
    dist.destroy_process_group()


def test_make_mesh_default(devices):
    mesh = dist.make_mesh(("data",))
    assert mesh.axis_names == ("data",)
    assert mesh.shape["data"] == 8


def test_make_mesh_2d(devices):
    mesh = dist.make_mesh(("data", "model"), shape=(4, 2))
    assert mesh.shape == {"data": 4, "model": 2}
    with pytest.raises(ValueError):
        dist.make_mesh(("data", "model"), shape=(3, 2))


def test_spawn_single_inprocess():
    out = []
    spawn(lambda i, x: out.append((i, x)), args=(42,), nprocs=1)
    assert out == [(0, 42)]


def test_spawn_validates():
    with pytest.raises(ValueError):
        spawn(lambda i: None, nprocs=0)


def test_barrier_single_process(devices):
    dist.barrier()  # must not deadlock or raise in single-process mode
