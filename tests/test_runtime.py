"""Runtime tests: process-group lifecycle, mesh construction, launcher."""

import functools

import jax
import numpy as np
import pytest

from distributeddataparallel_tpu.runtime import distributed as dist
from distributeddataparallel_tpu.runtime.launcher import (
    MULTIPROCESS_UNSUPPORTED_EXIT,
    guarded_worker,
    spawn,
)


def _skip_if_mp_unsupported(codes):
    if MULTIPROCESS_UNSUPPORTED_EXIT in codes:
        pytest.skip(
            "this jaxlib's CPU backend cannot run multiprocess computations"
        )


def test_init_destroy_lifecycle():
    assert not dist.is_initialized()
    dist.init_process_group("cpu")
    assert dist.is_initialized()
    with pytest.raises(RuntimeError):
        dist.init_process_group("cpu")
    assert dist.get_rank() == 0
    assert dist.get_world_size() == 1
    assert dist.local_device_count() == 8
    assert dist.global_device_count() == 8
    dist.destroy_process_group()
    assert not dist.is_initialized()
    # re-init after destroy works
    dist.init_process_group("cpu")
    dist.destroy_process_group()


def test_make_mesh_default(devices):
    mesh = dist.make_mesh(("data",))
    assert mesh.axis_names == ("data",)
    assert mesh.shape["data"] == 8


def test_make_mesh_2d(devices):
    mesh = dist.make_mesh(("data", "model"), shape=(4, 2))
    assert mesh.shape == {"data": 4, "model": 2}
    with pytest.raises(ValueError):
        dist.make_mesh(("data", "model"), shape=(3, 2))


def test_spawn_single_inprocess():
    out = []
    spawn(lambda i, x: out.append((i, x)), args=(42,), nprocs=1)
    assert out == [(0, 42)]


def test_spawn_validates():
    with pytest.raises(ValueError):
        spawn(lambda i: None, nprocs=0)


def test_barrier_single_process(devices):
    dist.barrier()  # must not deadlock or raise in single-process mode


def _mp_dp_worker(process_id, tmpdir):
    """Child of test_spawn_two_process_dp_step — fresh interpreter, so the
    JAX platform must be configured before any device query (the launcher's
    env contract supplies the rendezvous: JAX_COORDINATOR_ADDRESS etc.)."""
    import json
    import os

    import jax

    from distributeddataparallel_tpu.compat import configure_cpu_devices

    configure_cpu_devices(2)

    import jax.numpy as jnp
    import optax

    import distributeddataparallel_tpu as ddp
    from distributeddataparallel_tpu.data import DataLoader
    from distributeddataparallel_tpu.data.datasets import SyntheticClassification
    from distributeddataparallel_tpu.models import TinyMLP
    from distributeddataparallel_tpu.ops import cross_entropy_loss

    ddp.init_process_group("cpu")  # rendezvous via the spawned env vars
    assert jax.process_count() == 2, jax.process_count()
    assert jax.process_index() == process_id
    assert len(jax.devices()) == 4  # 2 hosts x 2 local devices

    mesh = ddp.make_mesh(("data",))  # global 4-way DP mesh
    ds = SyntheticClassification(num_examples=32, shape=(4, 4, 1), seed=0)
    # Multi-host loader: this process gathers rows for ITS 2 replicas only;
    # the global batch is assembled via make_array_from_process_local_data.
    loader = DataLoader(
        ds, per_replica_batch=4, mesh=mesh, shuffle=False, drop_last=True
    )

    model = TinyMLP(features=(16,))
    params = model.init(
        jax.random.PRNGKey(0), jnp.zeros((1, 4, 4, 1))
    )["params"]

    def loss_fn(p, batch, rng):
        logits = model.apply({"params": p}, batch["image"])
        return cross_entropy_loss(logits, batch["label"]), {}

    state = ddp.TrainState.create(
        apply_fn=model.apply, params=params, tx=optax.sgd(0.1)
    )
    state = ddp.broadcast_params(state, mesh)
    step = ddp.make_train_step(loss_fn, mesh=mesh)
    batch = next(iter(loader))
    state, metrics = step(state, batch, jax.random.PRNGKey(1))

    checksum = sum(
        float(jnp.sum(l.astype(jnp.float32))) for l in jax.tree.leaves(state.params)
    )
    with open(os.path.join(tmpdir, f"rank{process_id}.json"), "w") as f:
        json.dump({"loss": float(metrics["loss"]), "checksum": checksum}, f)
    ddp.destroy_process_group()


def test_spawn_two_process_dp_step(tmp_path, devices):
    """The true L1 path (analog of ref dpp.py:20-24,62): two OS processes
    rendezvous over a localhost coordinator, build one global mesh, feed a
    batch through make_array_from_process_local_data, and take one DP step
    whose loss/params must equal the single-process computation on the same
    global batch (the DDP equivalence invariant, across real processes)."""
    import json

    import jax.numpy as jnp
    import optax

    from distributeddataparallel_tpu.data.datasets import SyntheticClassification
    from distributeddataparallel_tpu.models import TinyMLP
    from distributeddataparallel_tpu.ops import cross_entropy_loss
    from distributeddataparallel_tpu.parallel.sampler import DistributedSampler

    procs = spawn(
        functools.partial(guarded_worker, _mp_dp_worker),
        args=(str(tmp_path),), nprocs=2, join=False,
    )
    for p in procs:
        p.join(timeout=240)
    codes = [p.exitcode for p in procs]
    for p in procs:
        if p.is_alive():
            p.terminate()
    _skip_if_mp_unsupported(codes)
    assert codes == [0, 0], f"child exit codes {codes}"

    results = [
        json.load(open(tmp_path / f"rank{i}.json")) for i in range(2)
    ]
    # Both processes observe the same replicated loss and params.
    assert results[0]["loss"] == pytest.approx(results[1]["loss"], abs=1e-6)
    assert results[0]["checksum"] == pytest.approx(
        results[1]["checksum"], abs=1e-5
    )

    # Single-process reference on the same global batch (replica-major rows
    # from the same sampler striding the children's loader used).
    ds = SyntheticClassification(num_examples=32, shape=(4, 4, 1), seed=0)
    rows = np.concatenate([
        DistributedSampler(len(ds), num_replicas=4, rank=r, shuffle=False)
        .local_indices()[:4]
        for r in range(4)
    ])
    images = jnp.asarray(ds.images[rows])
    labels = jnp.asarray(ds.labels[rows])
    model = TinyMLP(features=(16,))
    params = model.init(jax.random.PRNGKey(0), jnp.zeros((1, 4, 4, 1)))["params"]

    def loss_fn(p):
        return cross_entropy_loss(model.apply({"params": p}, images), labels)

    loss, grads = jax.value_and_grad(loss_fn)(params)
    tx = optax.sgd(0.1)
    updates, _ = tx.update(grads, tx.init(params), params)
    new_params = optax.apply_updates(params, updates)
    checksum = sum(
        float(jnp.sum(l.astype(jnp.float32))) for l in jax.tree.leaves(new_params)
    )
    assert results[0]["loss"] == pytest.approx(float(loss), abs=1e-5)
    assert results[0]["checksum"] == pytest.approx(checksum, rel=1e-5)


def _mp_tp_worker(process_id, tmpdir):
    """Child of test_spawn_two_process_dp_tp_step: DP(2) x TP(2) in the
    standard multi-host topology — the TP axis pairs each process's own
    devices (fastest interconnect) while the DP gradient sync crosses
    the process boundary over the collective backend."""
    import json
    import os

    import jax

    from distributeddataparallel_tpu.compat import configure_cpu_devices

    configure_cpu_devices(2)

    import dataclasses

    import jax.numpy as jnp
    import numpy as np
    import optax

    import distributeddataparallel_tpu as ddp
    from distributeddataparallel_tpu.data.loader import shard_batch
    from distributeddataparallel_tpu.models import TransformerLM, tiny_lm
    from distributeddataparallel_tpu.ops import lm_cross_entropy

    ddp.init_process_group("cpu")
    assert jax.process_count() == 2

    # 4 global devices as (data=2, model=2), row-major over
    # [p0d0, p0d1, p1d0, p1d1]: each process is one data row and its two
    # local devices form the model (TP) pair — TP stays intra-process,
    # DP crosses processes (the standard deployment layout).
    mesh = ddp.make_mesh(("data", "model"), shape=(2, 2))
    cfg = tiny_lm(num_heads=4, num_kv_heads=2, d_model=32, d_ff=64)
    model_tp = TransformerLM(dataclasses.replace(cfg, tp_axis="model"))
    params = TransformerLM(cfg).init(
        jax.random.PRNGKey(0), jnp.zeros((1, 16), jnp.int32)
    )["params"]

    def loss_fn(p, batch, rng):
        toks = batch["tokens"]
        logits = model_tp.apply({"params": p}, toks[:, :-1])
        return lm_cross_entropy(logits, toks[:, 1:]), {}

    state = ddp.TrainState.create(
        apply_fn=model_tp.apply, params=params, tx=optax.sgd(0.1)
    )
    state = ddp.shard_state_tp(state, mesh)
    step = ddp.make_train_step(loss_fn, mesh=mesh, tp_axis="model")
    rng = np.random.default_rng(11)
    tokens = rng.integers(0, 256, size=(4, 17)).astype(np.int32)
    batch = shard_batch({"tokens": tokens}, mesh)
    state, metrics = step(state, batch, jax.random.PRNGKey(0))

    with open(os.path.join(tmpdir, f"tp_rank{process_id}.json"), "w") as f:
        json.dump({"loss": float(metrics["loss"])}, f)
    ddp.destroy_process_group()


def test_spawn_two_process_dp_tp_step(tmp_path, devices):
    """Multi-process Megatron: two OS processes hold a (data=2, model=2)
    mesh (TP intra-process, DP across processes); the step's loss must
    match the single-process single-device computation."""
    import json

    import jax.numpy as jnp

    from distributeddataparallel_tpu.models import TransformerLM, tiny_lm
    from distributeddataparallel_tpu.ops import lm_cross_entropy

    procs = spawn(
        functools.partial(guarded_worker, _mp_tp_worker),
        args=(str(tmp_path),), nprocs=2, join=False,
    )
    for p in procs:
        p.join(timeout=240)
    codes = [p.exitcode for p in procs]
    for p in procs:
        if p.is_alive():
            p.terminate()
    _skip_if_mp_unsupported(codes)
    assert codes == [0, 0], f"child exit codes {codes}"

    results = [
        json.load(open(tmp_path / f"tp_rank{i}.json")) for i in range(2)
    ]
    assert results[0]["loss"] == pytest.approx(results[1]["loss"], abs=1e-6)

    # Single-device reference on the same global batch.
    cfg = tiny_lm(num_heads=4, num_kv_heads=2, d_model=32, d_ff=64)
    model = TransformerLM(cfg)
    rng = np.random.default_rng(11)
    tokens = rng.integers(0, 256, size=(4, 17)).astype(np.int32)
    params = model.init(
        jax.random.PRNGKey(0), jnp.zeros((1, 16), jnp.int32)
    )["params"]
    logits = model.apply({"params": params}, jnp.asarray(tokens[:, :-1]))
    ref = float(lm_cross_entropy(logits, jnp.asarray(tokens[:, 1:])))
    assert results[0]["loss"] == pytest.approx(ref, rel=1e-5)


def _mp_fsdp_worker(process_id, tmpdir):
    """Child of test_spawn_two_process_fsdp_step: FSDP state built over a
    GLOBAL 2-host mesh (device_put with a cross-process NamedSharding),
    one step, gathered-param checksum written per rank."""
    import json
    import os

    import jax

    from distributeddataparallel_tpu.compat import configure_cpu_devices

    configure_cpu_devices(2)

    import jax.numpy as jnp
    import numpy as np
    import optax

    import distributeddataparallel_tpu as ddp
    from distributeddataparallel_tpu.data.loader import shard_batch
    from distributeddataparallel_tpu.models import TransformerLM, tiny_lm

    ddp.init_process_group("cpu")
    mesh = ddp.make_mesh(("data",))  # global 4-way
    cfg = tiny_lm(
        num_layers=2, num_heads=2, d_model=32, d_ff=64, max_seq_len=32,
        scan_layers=True,
    )
    params = TransformerLM(cfg).init(
        jax.random.PRNGKey(0), jnp.zeros((1, 16), jnp.int32)
    )["params"]
    tokens = np.random.default_rng(0).integers(
        0, 256, size=(8, 17)
    ).astype(np.int32)

    state = ddp.fsdp_state(cfg, params, optax.sgd(0.1), mesh)
    step = ddp.make_fsdp_train_step(cfg, mesh=mesh, donate=False)
    state, metrics = step(
        state, shard_batch({"tokens": tokens}, mesh), jax.random.PRNGKey(1)
    )
    got = ddp.fsdp_gather_params(cfg, state, mesh)
    checksum = sum(
        float(jnp.sum(l.astype(jnp.float32))) for l in jax.tree.leaves(got)
    )
    with open(os.path.join(tmpdir, f"fsdp{process_id}.json"), "w") as f:
        json.dump({"loss": float(metrics["loss"]), "checksum": checksum}, f)
    ddp.destroy_process_group()


def test_spawn_two_process_fsdp_step(tmp_path, devices):
    """FSDP across real OS processes: the 1/N flats span BOTH hosts'
    devices; one step must equal the single-device reference on the same
    global batch (loss and gathered-params checksum, both ranks agreeing)."""
    import json

    import jax
    import jax.numpy as jnp
    import numpy as np
    import optax

    from distributeddataparallel_tpu.models import TransformerLM, tiny_lm
    from distributeddataparallel_tpu.ops import lm_cross_entropy

    procs = spawn(
        functools.partial(guarded_worker, _mp_fsdp_worker),
        args=(str(tmp_path),), nprocs=2, join=False,
    )
    for p in procs:
        p.join(timeout=240)
    codes = [p.exitcode for p in procs]
    for p in procs:
        if p.is_alive():
            p.terminate()  # don't let a hung rank wedge the pytest exit
    _skip_if_mp_unsupported(codes)
    assert codes == [0, 0], f"child exit codes {codes}"

    results = [
        json.load(open(tmp_path / f"fsdp{r}.json")) for r in range(2)
    ]
    assert results[0] == results[1], results

    cfg = tiny_lm(
        num_layers=2, num_heads=2, d_model=32, d_ff=64, max_seq_len=32,
        scan_layers=True,
    )
    model = TransformerLM(cfg)
    params = model.init(
        jax.random.PRNGKey(0), jnp.zeros((1, 16), jnp.int32)
    )["params"]
    tokens = np.random.default_rng(0).integers(
        0, 256, size=(8, 17)
    ).astype(np.int32)

    def ref_loss(p):
        logits = model.apply({"params": p}, jnp.asarray(tokens[:, :-1]))
        return lm_cross_entropy(logits, jnp.asarray(tokens[:, 1:]))

    loss_ref, grads = jax.value_and_grad(ref_loss)(params)
    tx = optax.sgd(0.1)
    updates, _ = tx.update(grads, tx.init(params), params)
    ref_params = optax.apply_updates(params, updates)
    ref_checksum = sum(
        float(jnp.sum(l.astype(jnp.float32)))
        for l in jax.tree.leaves(ref_params)
    )
    assert results[0]["loss"] == pytest.approx(float(loss_ref), rel=1e-5)
    assert results[0]["checksum"] == pytest.approx(ref_checksum, rel=1e-5)
