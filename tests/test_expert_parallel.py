"""Mixture-of-experts + expert-parallelism tests: the EP-sharded MoE step
(experts over an 'expert' mesh axis, dense einsum dispatch, psum combine)
must reproduce the single-device MoE computation exactly."""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest
from jax.sharding import PartitionSpec as P

import distributeddataparallel_tpu as ddp
from distributeddataparallel_tpu.data.loader import shard_batch
from distributeddataparallel_tpu.models import TransformerLM, tiny_lm
from distributeddataparallel_tpu.ops import lm_cross_entropy
from distributeddataparallel_tpu.parallel.expert_parallel import (
    ep_param_specs,
)


def _moe_cfg(**over):
    base = dict(
        num_layers=2, num_heads=2, d_model=32, d_ff=64, max_seq_len=32,
        moe_experts=4,
    )
    base.update(over)
    return tiny_lm(**base)


def test_moe_dense_trains(devices):
    """MoE without EP: forward shape, loss finite, grads nonzero on every
    expert that received tokens AND on the router."""
    cfg = _moe_cfg()
    model = TransformerLM(cfg)
    toks = jax.random.randint(jax.random.PRNGKey(1), (4, 33), 0, 256)
    params = model.init(jax.random.PRNGKey(0), toks[:, :-1])["params"]

    def loss(p):
        logits = model.apply({"params": p}, toks[:, :-1])
        return lm_cross_entropy(logits, toks[:, 1:])

    l, g = jax.value_and_grad(loss)(params)
    assert np.isfinite(float(l))
    router_g = g["layer_0"]["mlp"]["router"]["kernel"]
    assert float(jnp.abs(router_g).max()) > 0.0
    assert float(jnp.abs(g["layer_0"]["mlp"]["experts_up"]).max()) > 0.0


def test_ep_param_specs_rules(devices):
    cfg = _moe_cfg(scan_layers=True)
    params = TransformerLM(cfg).init(
        jax.random.PRNGKey(0), jnp.zeros((1, 16), jnp.int32)
    )["params"]
    specs = ep_param_specs(params)
    flat = {
        "/".join(str(getattr(k, "key", k)) for k in path): s
        for path, s in jax.tree_util.tree_flatten_with_path(specs)[0]
    }
    # Scanned: leading layer dim unsharded, expert dim sharded.
    assert flat["layers/block/mlp/experts_up"] == P(None, "expert", None, None)
    assert flat["layers/block/mlp/router/kernel"] == P()


def test_dp_ep_matches_single_device(devices):
    """DP(2) x EP(4): expert-sharded MoE train step == single-device step
    on the same global batch (adam state shards with its experts)."""
    cfg = _moe_cfg()
    cfg_ep = dataclasses.replace(cfg, ep_axis="expert")
    mesh = ddp.make_mesh(("data", "expert"), shape=(2, 4))
    model, model_ep = TransformerLM(cfg), TransformerLM(cfg_ep)
    rng = np.random.default_rng(0)
    tokens = rng.integers(0, 256, size=(4, 33)).astype(np.int32)
    params = model.init(
        jax.random.PRNGKey(0), jnp.zeros((1, 32), jnp.int32)
    )["params"]
    tx = optax.adam(1e-2)

    def ref_loss(p):
        logits = model.apply({"params": p}, jnp.asarray(tokens[:, :-1]))
        return lm_cross_entropy(logits, jnp.asarray(tokens[:, 1:]))

    loss_ref, grads_ref = jax.value_and_grad(ref_loss)(params)
    updates, _ = tx.update(grads_ref, tx.init(params), params)
    params_ref = optax.apply_updates(params, updates)

    def loss_fn(p, batch, rng):
        toks = batch["tokens"]
        logits = model_ep.apply({"params": p}, toks[:, :-1])
        return lm_cross_entropy(logits, toks[:, 1:]), {}

    state = ddp.TrainState.create(apply_fn=model_ep.apply, params=params, tx=tx)
    state = ddp.shard_state_ep(state, mesh)
    step = ddp.make_train_step(
        loss_fn, mesh=mesh, ep_axis="expert", donate=False
    )
    state, metrics = step(
        state, shard_batch({"tokens": tokens}, mesh), jax.random.PRNGKey(0)
    )
    assert float(metrics["loss"]) == pytest.approx(float(loss_ref), rel=1e-5)
    for (path, a), b in zip(
        jax.tree_util.tree_flatten_with_path(state.params)[0],
        jax.tree.leaves(params_ref),
    ):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), atol=2e-5,
            err_msg="/".join(str(getattr(k, "key", k)) for k in path),
        )


def test_dp_ep_tp_matches_single_device(devices):
    """DP(2) x EP(2) x TP(2): expert sharding and Megatron attention
    sharding on separate axes of one 3-D mesh, both completed by the
    conjugate-operator pair — still equal to the single-device step."""
    from distributeddataparallel_tpu.parallel import tp_param_specs
    from jax.sharding import NamedSharding

    cfg = _moe_cfg(num_heads=4, num_kv_heads=2)
    cfg_x = dataclasses.replace(cfg, ep_axis="expert", tp_axis="model")
    mesh = ddp.make_mesh(("data", "expert", "model"), shape=(2, 2, 2))
    model, model_x = TransformerLM(cfg), TransformerLM(cfg_x)
    rng = np.random.default_rng(3)
    tokens = rng.integers(0, 256, size=(4, 33)).astype(np.int32)
    params = model.init(
        jax.random.PRNGKey(0), jnp.zeros((1, 32), jnp.int32)
    )["params"]
    tx = optax.sgd(0.1)

    def ref_loss(p):
        logits = model.apply({"params": p}, jnp.asarray(tokens[:, :-1]))
        return lm_cross_entropy(logits, jnp.asarray(tokens[:, 1:]))

    loss_ref, grads_ref = jax.value_and_grad(ref_loss)(params)
    updates, _ = tx.update(grads_ref, tx.init(params), params)
    params_ref = optax.apply_updates(params, updates)

    def loss_fn(p, batch, rng):
        toks = batch["tokens"]
        logits = model_x.apply({"params": p}, toks[:, :-1])
        return lm_cross_entropy(logits, toks[:, 1:]), {}

    # Combined placement: TP specs where they bite, EP specs elsewhere.
    tspecs = tp_param_specs(params, "model")
    especs = ep_param_specs(params, "expert")
    combined = jax.tree.map(
        lambda t, e: e if any(e) else t, tspecs, especs
    )
    state = ddp.TrainState.create(apply_fn=model_x.apply, params=params, tx=tx)
    state = jax.tree.map(
        lambda x, s: jax.device_put(x, NamedSharding(mesh, s)),
        state,
        state.replace(
            step=jax.sharding.PartitionSpec(),
            params=combined,
            opt_state=jax.tree.map(lambda _: P(), state.opt_state),
            model_state={},
        ),
    )
    step = ddp.make_train_step(
        loss_fn, mesh=mesh, tp_axis="model", ep_axis="expert", donate=False
    )
    state, metrics = step(
        state, shard_batch({"tokens": tokens}, mesh), jax.random.PRNGKey(0)
    )
    assert float(metrics["loss"]) == pytest.approx(float(loss_ref), rel=1e-5)
    for a, b in zip(
        jax.tree.leaves(state.params), jax.tree.leaves(params_ref)
    ):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=2e-5)


def test_moe_aux_loss_sown_and_differentiable(devices):
    """The switch load-balance aux is exposed via sow (per layer, scan
    included), is minimized at uniform routing, and pushes router grads."""
    cfg = _moe_cfg(scan_layers=True)
    model = TransformerLM(cfg)
    toks = jax.random.randint(jax.random.PRNGKey(1), (4, 33), 0, 256)
    params = model.init(jax.random.PRNGKey(0), toks[:, :-1])["params"]

    def loss(p):
        logits, col = model.apply(
            {"params": p}, toks[:, :-1], mutable=["intermediates"]
        )
        terms = jax.tree.leaves(col)
        aux = sum(jnp.mean(t) for t in terms) / max(len(terms), 1)
        return lm_cross_entropy(logits, toks[:, 1:]) + 0.01 * aux, aux

    (l, aux), g = jax.value_and_grad(loss, has_aux=True)(params)
    assert np.isfinite(float(l)) and np.isfinite(float(aux))
    # E * sum f_e P_e >= 1 with equality at perfect balance.
    assert float(aux) >= 1.0 - 1e-4
    router_g = g["layers"]["block"]["mlp"]["router"]["kernel"]
    assert float(jnp.abs(router_g).max()) > 0.0


def test_ep_accum_matches_plain_ep(devices):
    """EP x gradient accumulation: 2 microbatches == single EP step on
    the same global batch."""
    cfg = _moe_cfg()
    cfg_ep = dataclasses.replace(cfg, ep_axis="expert")
    mesh = ddp.make_mesh(("data", "expert"), shape=(2, 4))
    model_ep = TransformerLM(cfg_ep)
    rng = np.random.default_rng(9)
    tokens = rng.integers(0, 256, size=(4, 33)).astype(np.int32)
    params = TransformerLM(cfg).init(
        jax.random.PRNGKey(0), jnp.zeros((1, 32), jnp.int32)
    )["params"]

    def loss_fn(p, batch, rng):
        toks = batch["tokens"]
        logits = model_ep.apply({"params": p}, toks[:, :-1])
        return lm_cross_entropy(logits, toks[:, 1:]), {}

    def run(accum):
        state = ddp.TrainState.create(
            apply_fn=model_ep.apply, params=params, tx=optax.sgd(0.1)
        )
        state = ddp.shard_state_ep(state, mesh)
        step = ddp.make_train_step(
            loss_fn, mesh=mesh, ep_axis="expert", accum_steps=accum,
            donate=False,
        )
        state, m = step(
            state, shard_batch({"tokens": tokens}, mesh), jax.random.PRNGKey(0)
        )
        return float(m["loss"]), state.params

    l1, p1 = run(1)
    l2, p2 = run(2)
    assert l1 == pytest.approx(l2, rel=1e-6)
    for a, b in zip(jax.tree.leaves(p1), jax.tree.leaves(p2)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=1e-6)


def test_dp_cp_ep_matches_single_device(devices):
    """DP(2) x CP(2) x EP(2): sequence sharding (ring attention) and
    expert sharding on separate axes — must equal the single-device MoE
    step (router runs per local seq chunk; grads complete via the cp
    pmean plus the expert-axis operators)."""
    from distributeddataparallel_tpu.data import shard_lm_batch

    cfg = _moe_cfg()
    cfg_x = dataclasses.replace(cfg, cp_axis="seq", ep_axis="expert")
    mesh = ddp.make_mesh(("data", "seq", "expert"), shape=(2, 2, 2))
    model, model_x = TransformerLM(cfg), TransformerLM(cfg_x)
    rng = np.random.default_rng(11)
    tokens = rng.integers(0, 256, size=(4, 33)).astype(np.int32)
    params = model.init(
        jax.random.PRNGKey(0), jnp.zeros((1, 32), jnp.int32)
    )["params"]
    tx = optax.sgd(0.1)

    def ref_loss(p):
        logits = model.apply({"params": p}, jnp.asarray(tokens[:, :-1]))
        return lm_cross_entropy(logits, jnp.asarray(tokens[:, 1:]))

    loss_ref, grads_ref = jax.value_and_grad(ref_loss)(params)
    updates, _ = tx.update(grads_ref, tx.init(params), params)
    params_ref = optax.apply_updates(params, updates)

    def loss_fn(p, batch, rng):
        logits = model_x.apply({"params": p}, batch["inputs"])
        return lm_cross_entropy(logits, batch["targets"]), {}

    state = ddp.TrainState.create(apply_fn=model_x.apply, params=params, tx=tx)
    state = ddp.shard_state_ep(state, mesh)
    step = ddp.make_train_step(
        loss_fn, mesh=mesh, cp_axis="seq", ep_axis="expert", donate=False
    )
    state, metrics = step(
        state, shard_lm_batch(tokens, mesh), jax.random.PRNGKey(0)
    )
    assert float(metrics["loss"]) == pytest.approx(float(loss_ref), rel=1e-5)
    for (path, a), b in zip(
        jax.tree_util.tree_flatten_with_path(state.params)[0],
        jax.tree.leaves(params_ref),
    ):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), atol=2e-5,
            err_msg="/".join(str(getattr(k, "key", k)) for k in path),
        )


def test_entrypoint_cp_ep_moe_aux(devices):
    """The dpp.py CLI path for --cp with --moe-experts/--ep and a nonzero
    aux weight: the CP-branch loss_fn applies with mutable intermediates
    under seq sharding and adds the load-balance aux.  Covers the wiring
    no equivalence test touches (they use plain losses)."""
    import sys

    sys.path.insert(0, "/root/repo")
    import dpp

    args = dpp.parse_args(
        [
            "--device", "cpu",
            "--model", "gpt2",
            "--layers", "2",
            "--d-model", "32",
            "--seq-len", "32",
            "--vocab-size", "64",
            "--cp", "2",
            "--moe-experts", "4",
            "--ep", "2",
            "--moe-aux-weight", "0.01",
            "--epochs", "1",
            "--num-examples", "64",
            "--batch-size", "4",
            "--log-every", "1000",
        ]
    )
    loss = dpp.train(args)
    assert loss == loss  # not NaN: aux plumbing intact under CP x EP


@pytest.mark.parametrize(
    "moe_kwargs,seed,atol",
    [
        ({}, 11, 2e-6),
        # Token-choice dispatch: the all_to_all token exchange composes
        # with the flat-chunk updates exactly as the dense path does
        # (5e-6: adam amplifies the dispatch paths' different fp
        # summation order over two steps).
        ({"moe_top_k": 2, "moe_capacity_factor": 4.0}, 23, 5e-6),
    ],
    ids=["dense", "token-choice"],
)
def test_ep_zero_matches_plain_ep(moe_kwargs, seed, atol, devices):
    """EP × ZeRO-1: the flat-chunk sharded update on each position's
    LOCAL expert shard must reproduce the replicated-optimizer DP×EP
    step exactly over two adam steps (expert stacks are uniform across
    the expert axis, so flat offsets are position-invariant and the
    replicated leaves — router included — stay in lockstep) — for both
    dispatch modes."""
    mesh = ddp.make_mesh(("data", "expert"), shape=(4, 2))
    cfg_x = _moe_cfg(ep_axis="expert", **moe_kwargs)
    model_x = TransformerLM(cfg_x)
    rng = np.random.default_rng(seed)
    batches = [
        shard_batch(
            {"tokens": rng.integers(0, 256, size=(8, 17)).astype(np.int32)},
            mesh,
        )
        for _ in range(2)
    ]
    params = TransformerLM(_moe_cfg(**{
        k: v for k, v in moe_kwargs.items() if k != "moe_capacity_factor"
    })).init(
        jax.random.PRNGKey(0), jnp.zeros((1, 16), jnp.int32)
    )["params"]
    tx = optax.adam(1e-2)

    def loss_fn(p, batch, rng):
        toks = batch["tokens"]
        logits = model_x.apply({"params": p}, toks[:, :-1])
        return lm_cross_entropy(logits, toks[:, 1:]), {}

    state = ddp.TrainState.create(apply_fn=model_x.apply, params=params, tx=tx)
    state = ddp.shard_state_ep(state, mesh)
    step = ddp.make_train_step(
        loss_fn, mesh=mesh, ep_axis="expert", donate=False
    )
    for t in batches:
        state, _ = step(state, t, jax.random.PRNGKey(0))

    zstate = ddp.zero_state(
        apply_fn=model_x.apply, params=params, tx=tx, mesh=mesh,
        ep_axis="expert",
    )
    zstep = ddp.make_train_step(
        loss_fn, mesh=mesh, ep_axis="expert", zero=True, donate=False
    )
    for t in batches:
        zstate, _ = zstep(zstate, t, jax.random.PRNGKey(0))

    # Flat opt vectors sharded over BOTH axes.
    assert any(
        l.sharding.spec == P(("data", "expert"))
        for l in jax.tree.leaves(zstate.opt_state) if l.ndim >= 1
    )
    for (path, a), b in zip(
        jax.tree_util.tree_flatten_with_path(state.params)[0],
        jax.tree.leaves(zstate.params),
    ):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), atol=atol,
            err_msg="/".join(str(getattr(k, "key", k)) for k in path),
        )


def test_ep_tp_zero_matches_replicated(devices):
    """DP(2) x TP(2) x EP(2) with ZeRO-1: flat chunks of the combined
    Megatron+expert local shard (opt vectors P(('data','model','expert')))
    must reproduce the replicated-optimizer 3-axis step exactly."""
    mesh = ddp.make_mesh(("data", "model", "expert"), shape=(2, 2, 2))
    cfg_x = _moe_cfg(num_heads=4, num_kv_heads=2, tp_axis="model",
                     ep_axis="expert")
    model_x = TransformerLM(cfg_x)
    rng = np.random.default_rng(13)
    batches = [
        shard_batch(
            {"tokens": rng.integers(0, 256, size=(8, 17)).astype(np.int32)},
            mesh,
        )
        for _ in range(2)
    ]
    params = TransformerLM(
        _moe_cfg(num_heads=4, num_kv_heads=2)
    ).init(jax.random.PRNGKey(0), jnp.zeros((1, 16), jnp.int32))["params"]
    tx = optax.adam(1e-2)

    def loss_fn(p, batch, rng):
        toks = batch["tokens"]
        logits = model_x.apply({"params": p}, toks[:, :-1])
        return lm_cross_entropy(logits, toks[:, 1:]), {}

    from distributeddataparallel_tpu.parallel.expert_parallel import (
        shard_state_model_axes,
    )

    state = ddp.TrainState.create(apply_fn=model_x.apply, params=params, tx=tx)
    state = shard_state_model_axes(
        state, mesh, tp_axis="model", ep_axis="expert"
    )
    step = ddp.make_train_step(
        loss_fn, mesh=mesh, tp_axis="model", ep_axis="expert", donate=False
    )
    for t in batches:
        state, _ = step(state, t, jax.random.PRNGKey(0))

    zstate = ddp.zero_state(
        apply_fn=model_x.apply, params=params, tx=tx, mesh=mesh,
        tp_axis="model", ep_axis="expert",
    )
    zstep = ddp.make_train_step(
        loss_fn, mesh=mesh, tp_axis="model", ep_axis="expert", zero=True,
        donate=False,
    )
    for t in batches:
        zstate, _ = zstep(zstate, t, jax.random.PRNGKey(0))

    assert any(
        l.sharding.spec == P(("data", "model", "expert"))
        for l in jax.tree.leaves(zstate.opt_state) if l.ndim >= 1
    )
    for (path, a), b in zip(
        jax.tree_util.tree_flatten_with_path(state.params)[0],
        jax.tree.leaves(zstate.params),
    ):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), atol=2e-6,
            err_msg="/".join(str(getattr(k, "key", k)) for k in path),
        )


def test_cp_ep_zero_matches_replicated(devices):
    """DP(2) x CP(2) x EP(2) with ZeRO-1 == the replicated-optimizer
    sequence-sharded MoE step (the CP pmean completes gradients before
    the data-axis reduce_scatter)."""
    from distributeddataparallel_tpu.data import shard_lm_batch

    mesh = ddp.make_mesh(("data", "seq", "expert"), shape=(2, 2, 2))
    cfg_x = _moe_cfg(cp_axis="seq", ep_axis="expert")
    model_x = TransformerLM(cfg_x)
    rng = np.random.default_rng(17)
    batches = [
        shard_lm_batch(
            rng.integers(0, 256, size=(4, 33)).astype(np.int32), mesh
        )
        for _ in range(2)
    ]
    params = TransformerLM(_moe_cfg(max_seq_len=32)).init(
        jax.random.PRNGKey(0), jnp.zeros((1, 32), jnp.int32)
    )["params"]
    tx = optax.adam(1e-2)

    def loss_fn(p, batch, rng):
        logits = model_x.apply({"params": p}, batch["inputs"])
        return lm_cross_entropy(logits, batch["targets"]), {}

    state = ddp.TrainState.create(apply_fn=model_x.apply, params=params, tx=tx)
    state = ddp.shard_state_ep(state, mesh)
    step = ddp.make_train_step(
        loss_fn, mesh=mesh, cp_axis="seq", ep_axis="expert", donate=False
    )
    for t in batches:
        state, _ = step(state, t, jax.random.PRNGKey(0))

    zstate = ddp.zero_state(
        apply_fn=model_x.apply, params=params, tx=tx, mesh=mesh,
        ep_axis="expert",
    )
    zstep = ddp.make_train_step(
        loss_fn, mesh=mesh, cp_axis="seq", ep_axis="expert", zero=True,
        donate=False,
    )
    for t in batches:
        zstate, _ = zstep(zstate, t, jax.random.PRNGKey(0))

    for a, b in zip(
        jax.tree.leaves(state.params), jax.tree.leaves(zstate.params)
    ):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=2e-6)


def test_dp_ep_top2_matches_single_device(devices):
    """Mixtral-style top-2 routing (renormalized gates) under DP(2) x
    EP(4) == the single-device top-2 computation, adam state included."""
    cfg = _moe_cfg(moe_top_k=2)
    cfg_ep = dataclasses.replace(cfg, ep_axis="expert")
    mesh = ddp.make_mesh(("data", "expert"), shape=(2, 4))
    model, model_ep = TransformerLM(cfg), TransformerLM(cfg_ep)
    rng = np.random.default_rng(29)
    tokens = rng.integers(0, 256, size=(4, 33)).astype(np.int32)
    params = model.init(
        jax.random.PRNGKey(0), jnp.zeros((1, 32), jnp.int32)
    )["params"]
    tx = optax.adam(1e-2)

    def ref_loss(p):
        logits = model.apply({"params": p}, jnp.asarray(tokens[:, :-1]))
        return lm_cross_entropy(logits, jnp.asarray(tokens[:, 1:]))

    loss_ref, grads_ref = jax.value_and_grad(ref_loss)(params)
    updates, _ = tx.update(grads_ref, tx.init(params), params)
    params_ref = optax.apply_updates(params, updates)

    def loss_fn(p, batch, rng):
        toks = batch["tokens"]
        logits = model_ep.apply({"params": p}, toks[:, :-1])
        return lm_cross_entropy(logits, toks[:, 1:]), {}

    state = ddp.TrainState.create(apply_fn=model_ep.apply, params=params, tx=tx)
    state = ddp.shard_state_ep(state, mesh)
    step = ddp.make_train_step(
        loss_fn, mesh=mesh, ep_axis="expert", donate=False
    )
    state, metrics = step(
        state, shard_batch({"tokens": tokens}, mesh), jax.random.PRNGKey(0)
    )
    assert float(metrics["loss"]) == pytest.approx(float(loss_ref), rel=1e-5)
    for (path, a), b in zip(
        jax.tree_util.tree_flatten_with_path(state.params)[0],
        jax.tree.leaves(params_ref),
    ):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), atol=2e-5,
            err_msg="/".join(str(getattr(k, "key", k)) for k in path),
        )


def test_top2_output_is_renormalized_blend(devices):
    """The module's OUTPUT equals the renormalized-top-2 blend of the
    per-expert MLP outputs, computed independently from the raw params
    (a K regression — e.g. silently reverting to top-1 or skipping the
    renormalization — fails this)."""
    import flax.linen as nn_

    from distributeddataparallel_tpu.models.transformer import MoEMLP

    cfg = _moe_cfg(moe_top_k=2, num_layers=1)
    params = TransformerLM(cfg).init(
        jax.random.PRNGKey(0),
        jax.random.randint(jax.random.PRNGKey(3), (2, 8), 0, 256),
    )["params"]
    mp = params["layer_0"]["mlp"]
    x = jax.random.normal(jax.random.PRNGKey(4), (2, 8, cfg.d_model))
    got = MoEMLP(cfg).apply({"params": mp}, x)

    # Independent reconstruction (tiny_lm default activation: swiglu).
    logits = x.astype(jnp.float32) @ mp["router"]["kernel"]
    probs = jax.nn.softmax(logits, axis=-1)
    vals, idx = jax.lax.top_k(probs, 2)
    vals = vals / vals.sum(-1, keepdims=True)
    w = jnp.sum(
        jax.nn.one_hot(idx, cfg.moe_experts) * vals[..., None], axis=2
    )
    h = jnp.einsum("bsd,edf->ebsf", x, mp["experts_up"])
    g = jnp.einsum("bsd,edf->ebsf", x, mp["experts_gate"])
    y = jnp.einsum("ebsf,efd->ebsd", nn_.silu(g) * h, mp["experts_down"])
    want = jnp.einsum("ebsd,bse->bsd", y, w)
    np.testing.assert_allclose(
        np.asarray(got), np.asarray(want), atol=1e-5
    )
    np.testing.assert_allclose(np.asarray(w.sum(-1)), 1.0, rtol=1e-6)
    # Exactly two experts carry weight per token.
    assert int((np.asarray(w) > 0).sum(-1).max()) == 2


# --- Token-choice dispatch (ops.moe, GShard capacity convention) --------


def test_token_choice_slots_priority_and_drop(devices):
    """Slot assignment unit test: earlier tokens win slots (stable-sort
    priority), overflow entries vanish (gate 0), kept gates land in
    their expert's slots."""
    from distributeddataparallel_tpu.ops.moe import token_choice_slots

    # 4 tokens, top-1; tokens 0,1,3 -> expert 2; token 2 -> expert 0.
    idx = jnp.array([[2], [2], [0], [2]], jnp.int32)
    gates = jnp.array([[0.9], [0.8], [0.7], [0.6]], jnp.float32)
    tok, gate = token_choice_slots(idx, gates, num_experts=4, capacity=2)
    tok = np.asarray(tok).reshape(4, 2)
    gate = np.asarray(gate).reshape(4, 2)
    # Expert 0 got token 2; expert 2 got tokens 0 and 1; token 3 dropped.
    assert tok[0, 0] == 2 and gate[0, 0] == pytest.approx(0.7)
    assert list(tok[2]) == [0, 1]
    np.testing.assert_allclose(gate[2], [0.9, 0.8])
    assert gate[1].sum() == 0 and gate[3].sum() == 0  # untouched experts
    assert not np.isclose(gate, 0.6).any()            # token 3's gate gone


def test_token_choice_matches_dense_single_device(devices):
    """At drop-free capacity the token-choice forward AND gradients equal
    the dense-dispatch path exactly (same routing, same params)."""
    from distributeddataparallel_tpu.ops import lm_cross_entropy as xent

    cfg = _moe_cfg(moe_top_k=2)
    cfg_tc = dataclasses.replace(
        cfg, moe_capacity_factor=float(cfg.moe_experts)
    )
    model, model_tc = TransformerLM(cfg), TransformerLM(cfg_tc)
    toks = jax.random.randint(jax.random.PRNGKey(1), (4, 33), 0, 256)
    params = model.init(jax.random.PRNGKey(0), toks[:, :-1])["params"]

    def lg(m):
        def f(p):
            return xent(m.apply({"params": p}, toks[:, :-1]), toks[:, 1:])
        return jax.value_and_grad(f)(params)

    l_d, g_d = lg(model)
    l_t, g_t = lg(model_tc)
    assert float(l_t) == pytest.approx(float(l_d), rel=1e-6)
    for (path, a), b in zip(
        jax.tree_util.tree_flatten_with_path(g_d)[0], jax.tree.leaves(g_t)
    ):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), atol=1e-5,
            err_msg="/".join(str(getattr(k, "key", k)) for k in path),
        )


def test_dp_ep_token_choice_matches_single_device(devices):
    """DP(2) x EP(4) token-choice (real all_to_all token exchange over
    the expert axis) == the single-device dense step, adam state
    included — the dispatch rewrite changes the dataflow, not the math."""
    cfg = _moe_cfg(moe_top_k=2)
    cfg_ep = dataclasses.replace(
        cfg, ep_axis="expert", moe_capacity_factor=float(cfg.moe_experts)
    )
    mesh = ddp.make_mesh(("data", "expert"), shape=(2, 4))
    model, model_ep = TransformerLM(cfg), TransformerLM(cfg_ep)
    rng = np.random.default_rng(0)
    tokens = rng.integers(0, 256, size=(4, 33)).astype(np.int32)
    params = model.init(
        jax.random.PRNGKey(0), jnp.zeros((1, 32), jnp.int32)
    )["params"]
    tx = optax.adam(1e-2)

    def ref_loss(p):
        logits = model.apply({"params": p}, jnp.asarray(tokens[:, :-1]))
        return lm_cross_entropy(logits, jnp.asarray(tokens[:, 1:]))

    loss_ref, grads_ref = jax.value_and_grad(ref_loss)(params)
    updates, _ = tx.update(grads_ref, tx.init(params), params)
    params_ref = optax.apply_updates(params, updates)

    def loss_fn(p, batch, rng):
        toks = batch["tokens"]
        logits = model_ep.apply({"params": p}, toks[:, :-1])
        return lm_cross_entropy(logits, toks[:, 1:]), {}

    state = ddp.TrainState.create(apply_fn=model_ep.apply, params=params, tx=tx)
    state = ddp.shard_state_ep(state, mesh)
    step = ddp.make_train_step(
        loss_fn, mesh=mesh, ep_axis="expert", donate=False
    )
    state, metrics = step(
        state, shard_batch({"tokens": tokens}, mesh), jax.random.PRNGKey(0)
    )
    assert float(metrics["loss"]) == pytest.approx(float(loss_ref), rel=1e-5)
    for (path, a), b in zip(
        jax.tree_util.tree_flatten_with_path(state.params)[0],
        jax.tree.leaves(params_ref),
    ):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), atol=2e-5,
            err_msg="/".join(str(getattr(k, "key", k)) for k in path),
        )


def test_ep_tp_token_choice_matches_single_device(devices):
    """DP(2) x EP(2) x TP(2) with token-choice dispatch: the all_to_all
    token exchange rides the expert axis while Megatron shards attention
    on the model axis — still equal to the single-device step."""
    from distributeddataparallel_tpu.parallel.expert_parallel import (
        shard_state_model_axes,
    )

    cfg = _moe_cfg(num_heads=4, num_kv_heads=2)
    cfg_x = dataclasses.replace(
        cfg, ep_axis="expert", tp_axis="model",
        moe_capacity_factor=float(cfg.moe_experts),
    )
    mesh = ddp.make_mesh(("data", "expert", "model"), shape=(2, 2, 2))
    model, model_x = TransformerLM(cfg), TransformerLM(cfg_x)
    rng = np.random.default_rng(3)
    tokens = rng.integers(0, 256, size=(4, 33)).astype(np.int32)
    params = model.init(
        jax.random.PRNGKey(0), jnp.zeros((1, 32), jnp.int32)
    )["params"]
    tx = optax.sgd(0.1)

    def ref_loss(p):
        logits = model.apply({"params": p}, jnp.asarray(tokens[:, :-1]))
        return lm_cross_entropy(logits, jnp.asarray(tokens[:, 1:]))

    loss_ref, grads_ref = jax.value_and_grad(ref_loss)(params)
    updates, _ = tx.update(grads_ref, tx.init(params), params)
    params_ref = optax.apply_updates(params, updates)

    def loss_fn(p, batch, rng):
        toks = batch["tokens"]
        logits = model_x.apply({"params": p}, toks[:, :-1])
        return lm_cross_entropy(logits, toks[:, 1:]), {}

    state = ddp.TrainState.create(apply_fn=model_x.apply, params=params, tx=tx)
    state = shard_state_model_axes(
        state, mesh, tp_axis="model", ep_axis="expert"
    )
    step = ddp.make_train_step(
        loss_fn, mesh=mesh, tp_axis="model", ep_axis="expert", donate=False
    )
    state, metrics = step(
        state, shard_batch({"tokens": tokens}, mesh), jax.random.PRNGKey(0)
    )
    assert float(metrics["loss"]) == pytest.approx(float(loss_ref), rel=1e-5)
    for (path, a), b in zip(
        jax.tree_util.tree_flatten_with_path(state.params)[0],
        jax.tree.leaves(params_ref),
    ):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), atol=2e-5,
            err_msg="/".join(str(getattr(k, "key", k)) for k in path),
        )


def test_token_choice_drops_through_residual(devices):
    """With capacity squeezed below the offered load, MoEMLP's output for
    dropped tokens is exactly zero (the residual carries them) while
    kept tokens match the unconstrained computation."""
    from distributeddataparallel_tpu.models.transformer import MoEMLP
    from distributeddataparallel_tpu.ops.moe import (
        moe_capacity,
        token_choice_slots,
    )

    cfg = _moe_cfg(num_layers=1, moe_top_k=1)
    params = TransformerLM(cfg).init(
        jax.random.PRNGKey(0),
        jax.random.randint(jax.random.PRNGKey(3), (2, 8), 0, 256),
    )["params"]
    mp = params["layer_0"]["mlp"]
    x = jax.random.normal(jax.random.PRNGKey(4), (1, 16, cfg.d_model))

    tight = dataclasses.replace(cfg, moe_capacity_factor=0.5)
    loose = dataclasses.replace(cfg, moe_capacity_factor=float(cfg.moe_experts))
    got_t = np.asarray(MoEMLP(tight).apply({"params": mp}, x))
    got_l = np.asarray(MoEMLP(loose).apply({"params": mp}, x))

    # Recompute which tokens survive the tight capacity from the raw
    # router, independent of the module.
    logits = x.astype(jnp.float32) @ mp["router"]["kernel"]
    probs = jax.nn.softmax(logits, axis=-1)
    vals, idx = jax.lax.top_k(probs, 1)
    C = moe_capacity(16, cfg.moe_experts, 1, 0.5)
    tok, gate = token_choice_slots(
        idx.reshape(16, 1), vals.reshape(16, 1), cfg.moe_experts, C
    )
    kept = np.zeros(16, bool)
    kept[np.asarray(tok)[np.asarray(gate) > 0]] = True
    assert kept.sum() < 16, "fixture must actually overflow"
    np.testing.assert_allclose(got_t[0, ~kept], 0.0, atol=1e-7)
    np.testing.assert_allclose(
        got_t[0, kept], got_l[0, kept], atol=1e-5
    )


def test_entrypoint_token_choice_cli(devices):
    """dpp.py --moe-capacity-factor path end-to-end (EP + aux weight)."""
    import sys

    sys.path.insert(0, "/root/repo")
    import dpp

    args = dpp.parse_args(
        [
            "--device", "cpu",
            "--model", "gpt2",
            "--layers", "2",
            "--d-model", "32",
            "--seq-len", "32",
            "--vocab-size", "64",
            "--moe-experts", "4",
            "--moe-top-k", "2",
            "--ep", "2",
            "--moe-capacity-factor", "1.25",
            "--moe-aux-weight", "0.01",
            "--epochs", "1",
            "--num-examples", "64",
            "--batch-size", "4",
            "--log-every", "1000",
        ]
    )
    loss = dpp.train(args)
    assert loss == loss  # not NaN


def test_pp_ep_token_choice_matches_single_device(devices):
    """DP(2) × PP(2) × EP(2) with token-choice dispatch: the MoE
    all_to_all runs inside pipeline stage bodies — still equal to the
    single-device step (aux weight 0: 1F1B-style restriction does not
    apply, this is GPipe with AD)."""
    from distributeddataparallel_tpu.parallel.pipeline_parallel import (
        make_pp_train_step,
        shard_state_pp,
    )

    cfg = _moe_cfg(num_layers=2, scan_layers=True, moe_top_k=2)
    cfg_x = dataclasses.replace(
        cfg, ep_axis="expert", moe_capacity_factor=4.0
    )
    mesh = ddp.make_mesh(("data", "pipe", "expert"), shape=(2, 2, 2))
    model = TransformerLM(cfg)
    rng = np.random.default_rng(31)
    tokens = rng.integers(0, 256, size=(4, 33)).astype(np.int32)
    params = model.init(
        jax.random.PRNGKey(0), jnp.zeros((1, 32), jnp.int32)
    )["params"]
    tx = optax.sgd(0.1)

    def ref_loss(p):
        logits = model.apply({"params": p}, jnp.asarray(tokens[:, :-1]))
        return lm_cross_entropy(logits, jnp.asarray(tokens[:, 1:]))

    loss_ref, grads_ref = jax.value_and_grad(ref_loss)(params)
    updates, _ = tx.update(grads_ref, tx.init(params), params)
    params_ref = optax.apply_updates(params, updates)

    state = ddp.TrainState.create(apply_fn=None, params=params, tx=tx)
    state = shard_state_pp(state, mesh, ep_axis="expert")
    step = make_pp_train_step(
        cfg_x, mesh=mesh, microbatches=2, donate=False, moe_aux_weight=0.0
    )
    state, metrics = step(
        state, shard_batch({"tokens": tokens}, mesh), jax.random.PRNGKey(0)
    )
    assert float(metrics["loss"]) == pytest.approx(float(loss_ref), rel=1e-5)
    for (path, a), b in zip(
        jax.tree_util.tree_flatten_with_path(state.params)[0],
        jax.tree.leaves(params_ref),
    ):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), atol=2e-5,
            err_msg="/".join(str(getattr(k, "key", k)) for k in path),
        )


def test_tpu_ep_memory_evidence():
    """AOT per-chip memory analysis of the REAL EP train step (VERDICT r4
    weak 6): EP-8 at E=16 strips 7/8 of the expert stack from every
    chip's arguments — measured from the compiled executable, matching
    the analytic split from the production spec rule (size reduced from
    the bench config to keep the compile test-budget-sized)."""
    pytest.importorskip("jax.experimental.topologies")
    from distributeddataparallel_tpu.parallel.expert_parallel import (
        ep_memory_evidence,
    )

    try:
        rep = ep_memory_evidence(
            experts=16, num_layers=2, d_model=256, d_ff=512, seq_len=128
        )
    except Exception as exc:  # no TPU compiler in this process
        pytest.skip(f"TPU topology compile unavailable: {exc!r}")
    assert rep["ep_degree"] == 8 and rep["experts_per_chip"] == 2
    assert rep["measured_expert_shard_frac"] == pytest.approx(
        rep["expected_expert_shard_frac"], abs=0.02
    )
    assert rep["ep_sharded"]["match_err"] < 0.02
    assert rep["dp_replicated"]["match_err"] < 0.02
    assert (
        rep["per_chip_expert_bytes_ep"]
        == rep["expert_param_bytes_total"] // 8
    )
