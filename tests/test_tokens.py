"""Real-token LM data path (data/tokens.py; VERDICT r3 item 6).

Contracts under test: flat-stream windowing (the +1 next-token overlap),
pre-chunked rows, memmapped access, DistributedSampler semantics through
the DataLoader (identical batches to an in-RAM dataset of the same
windows), the masked-eval mask riding along, and the end-to-end bar —
dpp.py fine-tuning ``--pretrained`` GPT-2 weights on a real-text corpus
via ``--dataset tokens:FILE``.
"""

import numpy as np
import pytest

from distributeddataparallel_tpu.data import (
    DataLoader,
    TokenFileDataset,
    encode_bytes,
    write_token_file,
)

CORPUS = (
    "It is a truth universally acknowledged, that a single model in "
    "possession of a good optimizer, must be in want of data. "
    "We hold these truths to be self-evident, that all gradients are "
    "created equal, that they are endowed by their loss with certain "
    "unalienable parameters, that among these are weights, biases and "
    "the pursuit of convergence. "
) * 12


def test_flat_stream_windowing(tmp_path):
    toks = np.arange(101, dtype=np.int32)
    path = write_token_file(str(tmp_path / "t.npy"), toks)
    ds = TokenFileDataset(path, seq_len=10)
    assert len(ds) == 10  # (101-1)//10
    row = ds[3]["tokens"]
    np.testing.assert_array_equal(row, np.arange(30, 41))
    batch = ds.gather([0, 9])
    np.testing.assert_array_equal(batch["tokens"][0], np.arange(0, 11))
    np.testing.assert_array_equal(batch["tokens"][1], np.arange(90, 101))
    assert batch["tokens"].dtype == np.int32


def test_overlapping_stride_windows(tmp_path):
    """stride < seq_len overlaps windows; counts and contents are exact."""
    toks = np.arange(101, dtype=np.int32)
    path = write_token_file(str(tmp_path / "t.npy"), toks)
    ds = TokenFileDataset(path, seq_len=10, stride=5)
    # starts 0,5,...,90: last window covers [90, 101) -> 19 windows
    assert len(ds) == 19
    np.testing.assert_array_equal(ds[1]["tokens"], np.arange(5, 16))
    batch = ds.gather(np.array([0, 18]))
    np.testing.assert_array_equal(batch["tokens"][1], np.arange(90, 101))
    # default stride reproduces the non-overlapping layout exactly
    base = TokenFileDataset(path, seq_len=10)
    strided = TokenFileDataset(path, seq_len=10, stride=10)
    assert len(base) == len(strided)
    np.testing.assert_array_equal(
        base.gather(range(len(base)))["tokens"],
        strided.gather(range(len(strided)))["tokens"],
    )
    with pytest.raises(ValueError, match="stride must be >= 1"):
        TokenFileDataset(path, seq_len=10, stride=0)


def test_flat_gather_rejects_out_of_range(tmp_path):
    """Negative/overflow window indices fail loudly — the sliding-window
    view would otherwise wrap them to off-grid starts (wrong text)."""
    toks = np.arange(101, dtype=np.int32)
    path = write_token_file(str(tmp_path / "t.npy"), toks)
    ds = TokenFileDataset(path, seq_len=10)
    with pytest.raises(IndexError):
        ds.gather([-1])
    with pytest.raises(IndexError):
        ds.gather([len(ds)])


def test_stride_rejected_on_row_files(tmp_path):
    rows = np.arange(60, dtype=np.int64).reshape(6, 10)
    path = write_token_file(str(tmp_path / "rows.npy"), rows)
    with pytest.raises(ValueError, match="flat streams"):
        TokenFileDataset(path, seq_len=9, stride=4)
    # explicit stride == seq_len is the default layout: allowed
    assert len(TokenFileDataset(path, seq_len=9, stride=9)) == 6


def test_prechunked_rows_and_sidecar(tmp_path):
    rows = np.arange(60, dtype=np.int64).reshape(6, 10)
    path = write_token_file(
        str(tmp_path / "rows.npy"), rows, vocab_size=60
    )
    ds = TokenFileDataset(path, seq_len=9)
    assert len(ds) == 6 and ds.vocab_size == 60
    np.testing.assert_array_equal(ds.gather([5])["tokens"][0], rows[5])
    with pytest.raises(ValueError, match="rows are 10 wide"):
        TokenFileDataset(path, seq_len=20)


def test_validation(tmp_path):
    with pytest.raises(FileNotFoundError):
        TokenFileDataset(str(tmp_path / "nope.npy"), seq_len=4)
    p = str(tmp_path / "f.npy")
    np.save(p, np.zeros((8,), np.float32))
    with pytest.raises(ValueError, match="integers"):
        TokenFileDataset(p, seq_len=4)
    with pytest.raises(ValueError, match="shorter than one window"):
        toks = np.arange(5, dtype=np.int32)
        TokenFileDataset(
            write_token_file(str(tmp_path / "s.npy"), toks), seq_len=10
        )
    with pytest.raises(ValueError, match="negative"):
        write_token_file(str(tmp_path / "n.npy"), np.asarray([-1, 2]))


def test_loader_matches_in_ram_windows(devices, tmp_path):
    """Sampler semantics: the memmapped dataset yields the exact batches
    an in-RAM dataset of the same windows does — shuffle, epoch
    reshuffle, pad mask included."""
    import distributeddataparallel_tpu as ddp

    toks = encode_bytes(CORPUS)
    S = 16
    path = write_token_file(str(tmp_path / "c.npy"), toks)
    ds = TokenFileDataset(path, seq_len=S)
    n = len(ds)
    assert n > 40

    class InRam:
        def __init__(self):
            self.rows = np.stack(
                [toks[i * S : i * S + S + 1] for i in range(n)]
            )

        def __len__(self):
            return n

        def arrays(self):
            return {"tokens": self.rows}

    mesh = ddp.make_mesh(("data",))
    for epoch in (0, 1):
        outs = []
        for dataset in (ds, InRam()):
            loader = DataLoader(
                dataset, per_replica_batch=2, mesh=mesh, seed=7,
                drop_last=False, with_mask=True, device_feed=False,
            )
            loader.set_epoch(epoch)
            outs.append(list(loader))
        assert len(outs[0]) == len(outs[1]) > 0
        for a, b in zip(*outs):
            np.testing.assert_array_equal(a["tokens"], b["tokens"])
            np.testing.assert_array_equal(a["valid"], b["valid"])


def test_memmapped_not_loaded(tmp_path):
    toks = np.arange(100_000, dtype=np.int32)
    path = write_token_file(str(tmp_path / "m.npy"), toks)
    ds = TokenFileDataset(path, seq_len=64)
    assert isinstance(ds._arr, np.memmap)


def test_cli_finetunes_pretrained_gpt2_on_real_corpus(devices, tmp_path):
    """The end-to-end bar: --pretrained GPT-2-family weights fine-tuned
    on a real-text byte-level corpus via --dataset tokens:FILE, with
    masked eval on the val split.  Loss must improve over training."""
    import sys

    sys.path.insert(0, "/root/repo")
    import jax
    import jax.numpy as jnp

    import dpp
    from distributeddataparallel_tpu.models import TransformerLM
    from distributeddataparallel_tpu.models.io import save_params
    from distributeddataparallel_tpu.models.transformer import gpt2_124m

    S, V = 32, 256
    # "Pretrained" checkpoint: a tiny GPT-2-family model saved in the
    # framework's safetensors interchange (the --pretrained flow;
    # HF-format conversion parity is pinned in test_io).
    # geometry matches the CLI's --d-model 32 derivation (heads =
    # d_model//16, d_ff = 4*d_model)
    cfg = gpt2_124m(
        num_layers=2, d_model=32, d_ff=128, num_heads=2,
        vocab_size=V, max_seq_len=S, dtype=jnp.float32,
    )
    params = TransformerLM(cfg).init(
        jax.random.PRNGKey(7), jnp.zeros((1, 8), jnp.int32)
    )["params"]
    ckpt = str(tmp_path / "w.safetensors")
    save_params(params, ckpt)

    toks = encode_bytes(CORPUS)
    cut = int(len(toks) * 0.85)
    train_path = write_token_file(
        str(tmp_path / "corpus.npy"), toks[:cut], vocab_size=V
    )
    write_token_file(str(tmp_path / "corpus.val.npy"), toks[cut:])

    args = dpp.parse_args(
        [
            "--device", "cpu",
            "--model", "gpt2",
            "--layers", "2", "--d-model", "32",
            "--seq-len", str(S), "--vocab-size", str(V),
            "--dataset", f"tokens:{train_path}",
            "--pretrained", ckpt,
            "--epochs", "4", "--batch-size", "2", "--lr", "0.01",
            "--optimizer", "adamw",
            "--log-every", "1000", "--eval",
        ]
    )
    final_loss = dpp.train(args)
    # byte-level chance is ln(256) ~ 5.55; real text must beat it.
    assert final_loss < 5.0, final_loss
