"""KV-cache decoding tests: incremental (prefill + 1-token) logits must
reproduce the full-sequence forward exactly, and greedy generate() must
match argmax decoding done with full forwards (no cache).  Covers both
LM families (learned-positional MHA, RoPE GQA) and scanned layers."""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from distributeddataparallel_tpu.models import TransformerLM, generate, tiny_lm
from distributeddataparallel_tpu.models.generate import decode_model


def _gpt2ish(**over):
    base = dict(
        vocab_size=97, num_layers=2, num_heads=2, d_model=32, d_ff=64,
        max_seq_len=48, norm="layernorm", activation="gelu",
        positional="learned", tie_embeddings=True,
    )
    base.update(over)
    return tiny_lm(**base)


def _llamaish(**over):
    # tiny_lm defaults: rmsnorm, swiglu, rope; add GQA.
    return tiny_lm(
        vocab_size=97, num_heads=4, num_kv_heads=2, d_model=32, d_ff=64,
        max_seq_len=48, tie_embeddings=False, **over,
    )


@pytest.mark.parametrize(
    "cfg_fn", [_gpt2ish, _llamaish], ids=["gpt2ish", "llamaish-gqa"]
)
def test_incremental_decode_matches_full_forward(cfg_fn, devices):
    """Prefill P tokens, then feed the rest one at a time: every decode
    step's logits must equal the full forward's logits at that position."""
    cfg = cfg_fn()
    model = TransformerLM(cfg)
    toks = jax.random.randint(jax.random.PRNGKey(1), (2, 12), 0, 97)
    params = model.init(jax.random.PRNGKey(0), toks)["params"]
    full = model.apply({"params": params}, toks)  # (B, 12, V)

    dm = decode_model(model)
    P = 5
    cache = dm.init(
        jax.random.PRNGKey(0), toks[:, :1], positions=jnp.arange(1)
    )["cache"]
    logits, upd = dm.apply(
        {"params": params, "cache": cache}, toks[:, :P],
        positions=jnp.arange(P), mutable=["cache"],
    )
    np.testing.assert_allclose(
        np.asarray(logits), np.asarray(full[:, :P]), atol=2e-5
    )
    cache = upd["cache"]
    for t in range(P, 12):
        logits, upd = dm.apply(
            {"params": params, "cache": cache}, toks[:, t : t + 1],
            positions=jnp.asarray([t]), mutable=["cache"],
        )
        cache = upd["cache"]
        np.testing.assert_allclose(
            np.asarray(logits[:, 0]), np.asarray(full[:, t]), atol=2e-5,
            err_msg=f"decode position {t}",
        )


def test_decode_scanned_layers(devices):
    """Scanned-layer configs decode too (per-layer caches stack along the
    scan dim)."""
    cfg = _llamaish(scan_layers=True)
    model = TransformerLM(cfg)
    toks = jax.random.randint(jax.random.PRNGKey(2), (2, 8), 0, 97)
    params = model.init(jax.random.PRNGKey(0), toks)["params"]

    out = generate(model, params, toks[:, :4], 4)
    # Greedy reference: iteratively extend with full forwards.
    ref = np.asarray(toks[:, :4])
    for _ in range(4):
        logits = model.apply({"params": params}, jnp.asarray(ref))
        nxt = np.argmax(np.asarray(logits[:, -1], np.float32), axis=-1)
        ref = np.concatenate([ref, nxt[:, None].astype(np.int32)], axis=1)
    np.testing.assert_array_equal(np.asarray(out), ref)
    assert out.shape == (2, 8)


@pytest.mark.parametrize(
    "cfg_fn", [_gpt2ish, _llamaish], ids=["gpt2ish", "llamaish-gqa"]
)
def test_greedy_generate_matches_full_forward_argmax(cfg_fn, devices):
    cfg = cfg_fn()
    model = TransformerLM(cfg)
    prompt = jax.random.randint(jax.random.PRNGKey(3), (3, 6), 0, 97)
    params = model.init(jax.random.PRNGKey(0), prompt)["params"]

    out = generate(model, params, prompt, 6)
    assert out.shape == (3, 12)
    np.testing.assert_array_equal(np.asarray(out[:, :6]), np.asarray(prompt))

    ref = np.asarray(prompt)
    for _ in range(6):
        logits = model.apply({"params": params}, jnp.asarray(ref))
        nxt = np.argmax(np.asarray(logits[:, -1], np.float32), axis=-1)
        ref = np.concatenate([ref, nxt[:, None].astype(np.int32)], axis=1)
    np.testing.assert_array_equal(np.asarray(out), ref)


def test_sampling_modes(devices):
    """Temperature sampling is rng-deterministic, top-k constrains to the
    top-k support, and the guards fire."""
    cfg = _llamaish()
    model = TransformerLM(cfg)
    prompt = jax.random.randint(jax.random.PRNGKey(4), (2, 4), 0, 97)
    params = model.init(jax.random.PRNGKey(0), prompt)["params"]

    a = generate(
        model, params, prompt, 5, rng=jax.random.PRNGKey(7), temperature=1.0
    )
    b = generate(
        model, params, prompt, 5, rng=jax.random.PRNGKey(7), temperature=1.0
    )
    np.testing.assert_array_equal(np.asarray(a), np.asarray(b))

    # top_k=1 == greedy regardless of temperature.
    g = generate(model, params, prompt, 5)
    k1 = generate(
        model, params, prompt, 5, rng=jax.random.PRNGKey(9),
        temperature=0.7, top_k=1,
    )
    np.testing.assert_array_equal(np.asarray(k1), np.asarray(g))

    with pytest.raises(ValueError, match="requires rng"):
        generate(model, params, prompt, 2, temperature=0.5)
    with pytest.raises(ValueError, match="max_seq_len"):
        generate(model, params, prompt, cfg.max_seq_len)


def test_generate_rejects_sharded_layouts(devices):
    """TP/EP configs hold sharded param layouts the decode apply cannot
    consume: a clear error, not a deep ScopeParamShapeError."""
    cfg = dataclasses.replace(_llamaish(), tp_axis="model")
    model = TransformerLM(cfg)
    toks = jnp.zeros((1, 4), jnp.int32)
    with pytest.raises(ValueError, match="replicated params"):
        generate(model, {}, toks, 2)
