"""Tensor-parallelism tests: Megatron column/row sharding over a 'model'
mesh axis must reproduce the unsharded model exactly — forward logits,
and a full DP×TP train step against the single-device reference (the DDP
invariant, extended to a 2-D mesh)."""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest
from jax.sharding import NamedSharding, PartitionSpec as P

import distributeddataparallel_tpu as ddp
from distributeddataparallel_tpu.data.loader import shard_batch
from distributeddataparallel_tpu.models import TransformerLM, tiny_lm
from distributeddataparallel_tpu.ops import lm_cross_entropy
from distributeddataparallel_tpu.parallel.tensor_parallel import (
    tp_param_specs,
)


def _cfgs(tp_axis="model", num_kv_heads=None, **over):
    """MHA by default (4 heads shard 4 ways); pass num_kv_heads=2 for the
    GQA variant (shards at most 2 ways)."""
    base = tiny_lm(
        num_heads=4, num_kv_heads=num_kv_heads, d_model=32, d_ff=64, **over
    )
    return base, dataclasses.replace(base, tp_axis=tp_axis)


def test_tp_param_specs_rules(devices):
    cfg, _ = _cfgs()
    model = TransformerLM(cfg)
    params = model.init(
        jax.random.PRNGKey(0), jnp.zeros((1, 16), jnp.int32)
    )["params"]
    specs = tp_param_specs(params)
    flat = dict(
        ("/".join(str(getattr(k, "key", k)) for k in path), s)
        for path, s in jax.tree_util.tree_flatten_with_path(specs)[0]
    )
    assert flat["layer_0/attn/q_proj/kernel"] == P(None, "model", None)
    assert flat["layer_0/attn/o_proj/kernel"] == P("model", None, None)
    assert flat["layer_0/mlp/up_proj/kernel"] == P(None, "model")
    assert flat["layer_0/mlp/down_proj/kernel"] == P("model", None)
    assert flat["token_embed/embedding"] == P()


def test_tp_forward_matches_single_device(devices):
    """4-way TP forward == unsharded logits, same params."""
    mesh = ddp.make_mesh(("model",), devices=jax.devices()[:4])
    cfg, cfg_tp = _cfgs()
    model, model_tp = TransformerLM(cfg), TransformerLM(cfg_tp)
    toks = jax.random.randint(jax.random.PRNGKey(1), (2, 16), 0, 256)
    params = model.init(jax.random.PRNGKey(0), toks)["params"]
    ref = model.apply({"params": params}, toks)

    specs = tp_param_specs(params)
    sharded_params = jax.tree.map(
        lambda x, s: jax.device_put(x, NamedSharding(mesh, s)), params, specs
    )
    fn = jax.shard_map(
        lambda p, t: model_tp.apply({"params": p}, t),
        mesh=mesh,
        in_specs=(specs, P()),
        out_specs=P(),
        check_vma=False,
    )
    out = jax.jit(fn)(sharded_params, toks)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5)


def test_dp_tp_train_step_matches_single_device(devices):
    """DP(2) × TP(4) one train step == single-device step on the same
    global batch: same loss, same updated params (gathered)."""
    mesh = ddp.make_mesh(("data", "model"), shape=(2, 4))
    cfg, cfg_tp = _cfgs()
    model, model_tp = TransformerLM(cfg), TransformerLM(cfg_tp)
    rng = np.random.default_rng(0)
    tokens = rng.integers(0, 256, size=(4, 17)).astype(np.int32)
    params = model.init(
        jax.random.PRNGKey(0), jnp.zeros((1, 16), jnp.int32)
    )["params"]
    tx = optax.adam(1e-2)

    # Single-device reference.
    def ref_loss(p):
        logits = model.apply({"params": p}, jnp.asarray(tokens[:, :-1]))
        return lm_cross_entropy(logits, jnp.asarray(tokens[:, 1:]))

    loss_ref, grads_ref = jax.value_and_grad(ref_loss)(params)
    updates, _ = tx.update(grads_ref, tx.init(params), params)
    params_ref = optax.apply_updates(params, updates)

    # DP×TP step.
    def loss_fn(p, batch, rng):
        toks = batch["tokens"]
        logits = model_tp.apply({"params": p}, toks[:, :-1])
        return lm_cross_entropy(logits, toks[:, 1:]), {}

    state = ddp.TrainState.create(apply_fn=model_tp.apply, params=params, tx=tx)
    state = ddp.shard_state_tp(state, mesh)
    step = ddp.make_train_step(
        loss_fn, mesh=mesh, tp_axis="model", donate=False
    )
    batch = shard_batch({"tokens": tokens}, mesh)
    state, metrics = step(state, batch, jax.random.PRNGKey(0))

    assert float(metrics["loss"]) == pytest.approx(float(loss_ref), rel=1e-5)
    for (path, a), b in zip(
        jax.tree_util.tree_flatten_with_path(state.params)[0],
        jax.tree.leaves(params_ref),
    ):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), atol=2e-5,
            err_msg="/".join(str(getattr(k, "key", k)) for k in path),
        )


def test_dp_tp_scan_remat_gqa(devices):
    """The Llama-shaped variant: scanned+remat'd layers with GQA under
    DP(2) × TP(2) still matches the unsharded step."""
    mesh = ddp.make_mesh(("data", "model"), shape=(4, 2))
    cfg, cfg_tp = _cfgs(num_kv_heads=2, scan_layers=True, remat=True)
    model, model_tp = TransformerLM(cfg), TransformerLM(cfg_tp)
    rng = np.random.default_rng(3)
    tokens = rng.integers(0, 256, size=(8, 17)).astype(np.int32)
    params = model.init(
        jax.random.PRNGKey(0), jnp.zeros((1, 16), jnp.int32)
    )["params"]
    tx = optax.sgd(0.1)

    def ref_loss(p):
        logits = model.apply({"params": p}, jnp.asarray(tokens[:, :-1]))
        return lm_cross_entropy(logits, jnp.asarray(tokens[:, 1:]))

    loss_ref, grads_ref = jax.value_and_grad(ref_loss)(params)
    updates, _ = tx.update(grads_ref, tx.init(params), params)
    params_ref = optax.apply_updates(params, updates)

    def loss_fn(p, batch, rng):
        toks = batch["tokens"]
        logits = model_tp.apply({"params": p}, toks[:, :-1])
        return lm_cross_entropy(logits, toks[:, 1:]), {}

    state = ddp.TrainState.create(apply_fn=model_tp.apply, params=params, tx=tx)
    state = ddp.shard_state_tp(state, mesh)
    step = ddp.make_train_step(
        loss_fn, mesh=mesh, tp_axis="model", donate=False
    )
    state, metrics = step(
        state, shard_batch({"tokens": tokens}, mesh), jax.random.PRNGKey(0)
    )
    assert float(metrics["loss"]) == pytest.approx(float(loss_ref), rel=1e-5)
    for a, b in zip(
        jax.tree.leaves(state.params), jax.tree.leaves(params_ref)
    ):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=2e-5)


def test_dp_cp_tp_train_step_matches_single_device(devices):
    """The full 3-D composition: DP(2) x CP(2) x TP(2) on 8 devices must
    reproduce the single-device step — data rows sharded over 'data',
    sequence over 'seq' (ring attention), heads/hidden over 'model'
    (Megatron), all at once."""
    from distributeddataparallel_tpu.data import shard_lm_batch

    mesh = ddp.make_mesh(("data", "seq", "model"), shape=(2, 2, 2))
    cfg, _ = _cfgs(num_kv_heads=2)
    cfg_xp = dataclasses.replace(cfg, cp_axis="seq", tp_axis="model")
    model, model_xp = TransformerLM(cfg), TransformerLM(cfg_xp)
    rng = np.random.default_rng(5)
    tokens = rng.integers(0, 256, size=(4, 33)).astype(np.int32)
    params = model.init(
        jax.random.PRNGKey(0), jnp.zeros((1, 32), jnp.int32)
    )["params"]
    tx = optax.sgd(0.1)

    def ref_loss(p):
        logits = model.apply({"params": p}, jnp.asarray(tokens[:, :-1]))
        return lm_cross_entropy(logits, jnp.asarray(tokens[:, 1:]))

    loss_ref, grads_ref = jax.value_and_grad(ref_loss)(params)
    updates, _ = tx.update(grads_ref, tx.init(params), params)
    params_ref = optax.apply_updates(params, updates)

    def loss_fn(p, batch, rng):
        logits = model_xp.apply({"params": p}, batch["inputs"])
        return lm_cross_entropy(logits, batch["targets"]), {}

    state = ddp.TrainState.create(apply_fn=model_xp.apply, params=params, tx=tx)
    state = ddp.shard_state_tp(state, mesh)
    step = ddp.make_train_step(
        loss_fn, mesh=mesh, cp_axis="seq", tp_axis="model", donate=False
    )
    state, metrics = step(
        state, shard_lm_batch(tokens, mesh), jax.random.PRNGKey(0)
    )
    assert float(metrics["loss"]) == pytest.approx(float(loss_ref), rel=1e-5)
    for a, b in zip(
        jax.tree.leaves(state.params), jax.tree.leaves(params_ref)
    ):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=2e-5)


def test_tp_accum_matches_plain_tp(devices):
    """TP x gradient accumulation: 2 microbatches == single TP step on
    the same global batch."""
    mesh = ddp.make_mesh(("data", "model"), shape=(2, 4))
    cfg, cfg_tp = _cfgs()
    model_tp = TransformerLM(cfg_tp)
    rng = np.random.default_rng(7)
    tokens = rng.integers(0, 256, size=(4, 17)).astype(np.int32)
    params = TransformerLM(cfg).init(
        jax.random.PRNGKey(0), jnp.zeros((1, 16), jnp.int32)
    )["params"]

    def loss_fn(p, batch, rng):
        toks = batch["tokens"]
        logits = model_tp.apply({"params": p}, toks[:, :-1])
        return lm_cross_entropy(logits, toks[:, 1:]), {}

    def run(accum):
        tx = optax.sgd(0.1)
        state = ddp.TrainState.create(
            apply_fn=model_tp.apply, params=params, tx=tx
        )
        state = ddp.shard_state_tp(state, mesh)
        step = ddp.make_train_step(
            loss_fn, mesh=mesh, tp_axis="model", accum_steps=accum,
            donate=False,
        )
        state, m = step(
            state, shard_batch({"tokens": tokens}, mesh), jax.random.PRNGKey(0)
        )
        return float(m["loss"]), state.params

    l1, p1 = run(1)
    l2, p2 = run(2)
    assert l1 == pytest.approx(l2, rel=1e-6)
    for a, b in zip(jax.tree.leaves(p1), jax.tree.leaves(p2)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=1e-6)


def test_tp_zero_matches_plain_tp(devices):
    """TP × ZeRO-1: the flat-chunk sharded update on each position's
    LOCAL Megatron shard must reproduce the replicated-optimizer DP×TP
    step exactly over two adam steps (params stay in lockstep because
    flat offsets are identical across model positions)."""
    mesh = ddp.make_mesh(("data", "model"), shape=(4, 2))
    cfg, cfg_tp = _cfgs(num_kv_heads=2)
    model_tp = TransformerLM(cfg_tp)
    rng = np.random.default_rng(3)
    batches = [
        {"tokens": rng.integers(0, 256, size=(8, 17)).astype(np.int32)}
        for _ in range(2)
    ]
    params = TransformerLM(cfg).init(
        jax.random.PRNGKey(0), jnp.zeros((1, 16), jnp.int32)
    )["params"]
    tx = optax.adam(1e-2)

    def loss_fn(p, batch, rng):
        toks = batch["tokens"]
        logits = model_tp.apply({"params": p}, toks[:, :-1])
        return lm_cross_entropy(logits, toks[:, 1:]), {}

    # Replicated-optimizer DP×TP baseline, two steps.
    state = ddp.TrainState.create(apply_fn=model_tp.apply, params=params, tx=tx)
    state = ddp.shard_state_tp(state, mesh)
    step = ddp.make_train_step(
        loss_fn, mesh=mesh, tp_axis="model", donate=False
    )
    for b in batches:
        state, _ = step(state, shard_batch(b, mesh), jax.random.PRNGKey(0))

    # ZeRO-1 × TP, same two steps.
    zstate = ddp.zero_state(
        apply_fn=model_tp.apply, params=params, tx=tx, mesh=mesh,
        tp_axis="model",
    )
    zstep = ddp.make_train_step(
        loss_fn, mesh=mesh, tp_axis="model", zero=True, donate=False
    )
    for b in batches:
        zstate, _ = zstep(zstate, shard_batch(b, mesh), jax.random.PRNGKey(0))

    # Flat opt state is sharded over BOTH axes: 8 positions × distinct
    # chunks, none replicated.
    mu = jax.tree.leaves(zstate.opt_state)
    assert any(
        l.sharding.spec == P(("data", "model")) for l in mu if l.ndim >= 1
    ), [getattr(l, "sharding", None) for l in mu]

    for (path, a), b in zip(
        jax.tree_util.tree_flatten_with_path(state.params)[0],
        jax.tree.leaves(zstate.params),
    ):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), atol=2e-6,
            err_msg="/".join(str(getattr(k, "key", k)) for k in path),
        )


def test_cp_tp_zero_matches_replicated(devices):
    """DP(2) x CP(2) x TP(2) with ZeRO-1: the flat-chunk update on local
    Megatron shards under sequence sharding must reproduce the
    replicated-optimizer 3-D step exactly (adam, two steps)."""
    from distributeddataparallel_tpu.data import shard_lm_batch
    from distributeddataparallel_tpu.parallel import make_cp_train_step

    mesh = ddp.make_mesh(("data", "seq", "model"), shape=(2, 2, 2))
    cfg, _ = _cfgs(num_kv_heads=2)
    cfg_xp = dataclasses.replace(cfg, cp_axis="seq", tp_axis="model")
    model_xp = TransformerLM(cfg_xp)
    rng = np.random.default_rng(7)
    batches = [
        rng.integers(0, 256, size=(4, 33)).astype(np.int32) for _ in range(2)
    ]
    params = TransformerLM(cfg).init(
        jax.random.PRNGKey(0), jnp.zeros((1, 32), jnp.int32)
    )["params"]
    tx = optax.adam(1e-2)

    def loss_fn(p, batch, rng):
        logits = model_xp.apply({"params": p}, batch["inputs"])
        return lm_cross_entropy(logits, batch["targets"]), {}

    state = ddp.TrainState.create(apply_fn=model_xp.apply, params=params, tx=tx)
    state = ddp.shard_state_tp(state, mesh)
    step = make_cp_train_step(
        loss_fn, mesh=mesh, tp_axis="model", donate=False
    )
    for t in batches:
        state, _ = step(state, shard_lm_batch(t, mesh), jax.random.PRNGKey(0))

    zstate = ddp.zero_state(
        apply_fn=model_xp.apply, params=params, tx=tx, mesh=mesh,
        tp_axis="model",
    )
    zstep = make_cp_train_step(
        loss_fn, mesh=mesh, tp_axis="model", zero=True, donate=False
    )
    for t in batches:
        zstate, _ = zstep(
            zstate, shard_lm_batch(t, mesh), jax.random.PRNGKey(0)
        )

    for a, b in zip(
        jax.tree.leaves(state.params), jax.tree.leaves(zstate.params)
    ):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=2e-6)
