"""Zero-bubble (ZB-H1-style) pipeline schedule: the B/W backward split
must be a pure re-bracketing of AD — bitwise loss/param parity with
1f1b — while the three-scan rendering reports its own useful-slot
counters and the shared tick arithmetic stays one source of truth
across the compiled schedule, the bubble accounting, and the zb
schedule IR.  Plus the dpp CLI's loud zb-constraint rejections and the
events-side measured-bubble reconstruction."""

import numpy as np
import optax
import pytest

import jax
import jax.numpy as jnp

import distributeddataparallel_tpu as ddp
import dpp
from distributeddataparallel_tpu.data.loader import shard_batch
from distributeddataparallel_tpu.models import TransformerLM, tiny_lm
from distributeddataparallel_tpu.parallel.pipeline_parallel import (
    _1f1b_ticks,
    _zb_segments,
    interleave_layer_perm,
    make_pp_train_step,
    pp_bubble_fraction,
    shard_state_pp,
)


def _scan_cfg(**over):
    base = dict(
        num_layers=4, num_heads=2, d_model=32, d_ff=64, scan_layers=True,
        max_seq_len=32,
    )
    base.update(over)
    return tiny_lm(**base)


def _run_schedule(cfg, params, token_batches, mesh, microbatches,
                  schedule, virtual=1):
    """Run one schedule over len(token_batches) steps; returns the
    per-step losses, the final params, and the last step's metrics."""
    step = make_pp_train_step(
        cfg, mesh=mesh, microbatches=microbatches, donate=False,
        schedule=schedule, virtual=virtual,
    )
    state = shard_state_pp(
        ddp.TrainState.create(apply_fn=None, params=params,
                              tx=optax.adam(1e-2)),
        mesh,
    )
    losses, metrics = [], None
    for i, tokens in enumerate(token_batches):
        batch = shard_batch({"tokens": tokens}, mesh)
        state, metrics = step(state, batch, jax.random.PRNGKey(i))
        losses.append(np.asarray(metrics["loss"]))
    return losses, state.params, metrics


@pytest.mark.parametrize(
    "microbatches,virtual",
    [(8, 1),   # accum-style: M > n, the pp microbatch loop IS --accum
     (4, 1),   # M = n edge: steady state is exactly one group
     (8, 2)],  # interleaved: v > 1 composes with the B/W split
)
def test_zb_bitwise_parity_with_1f1b(devices, microbatches, virtual):
    """DP(2) x PP(4), 3 steps: zb must produce BITWISE-identical losses
    and params to 1f1b (atol=0, f32) — the split backward runs the same
    per-primitive transposes as the joint vjp, in the same order, and
    the DP grad psum sees identical addends."""
    cfg = _scan_cfg(num_layers=4 * virtual)
    mesh = ddp.make_mesh(("data", "pipe"), shape=(2, 4))
    params = TransformerLM(cfg).init(
        jax.random.PRNGKey(0), jnp.zeros((1, 32), jnp.int32)
    )["params"]
    rng = np.random.default_rng(7)
    batches = [
        rng.integers(0, 256, size=(microbatches * 2, 33)).astype(np.int32)
        for _ in range(3)
    ]

    ref_losses, ref_params, ref_m = _run_schedule(
        cfg, params, batches, mesh, microbatches, "1f1b", virtual
    )
    zb_losses, zb_params, zb_m = _run_schedule(
        cfg, params, batches, mesh, microbatches, "zb", virtual
    )

    for a, b in zip(ref_losses, zb_losses):
        np.testing.assert_array_equal(a, b)
    for (path, a), b in zip(
        jax.tree_util.tree_flatten_with_path(zb_params)[0],
        jax.tree.leaves(ref_params),
    ):
        np.testing.assert_array_equal(
            np.asarray(a), np.asarray(b),
            err_msg="/".join(str(getattr(k, "key", k)) for k in path),
        )

    # The phase counters are the measured-schedule contract: every
    # stage executed M*v valid F and B slots under both schedules, and
    # M*v separate W slots under zb (W is fused into B under 1f1b).
    M = microbatches
    ref_counts = np.asarray(ref_m["pp_phase_counts"])
    zb_counts = np.asarray(zb_m["pp_phase_counts"])
    assert ref_counts.shape == zb_counts.shape == (4, 3)
    np.testing.assert_array_equal(
        ref_counts, np.tile([M * virtual, M * virtual, 0], (4, 1))
    )
    np.testing.assert_array_equal(
        zb_counts, np.tile([M * virtual] * 3, (4, 1))
    )


# ------------------------------------------------ tick arithmetic edges


def test_1f1b_ticks_edge_cases():
    # n=2, M=n: two groups of nothing — last unit is j=1, T covers
    # warm-up + steady + drain exactly
    assert _1f1b_ticks(2, 2, 1) == (1, 4)
    # M = n at larger n
    assert _1f1b_ticks(4, 4, 1) == (3, 10)
    # M not a multiple of n: the tail group still schedules
    assert _1f1b_ticks(3, 7, 1) == (6, 11)
    # v > 1: groups advance by n*v units
    assert _1f1b_ticks(2, 4, 2) == (7, 12)
    assert _1f1b_ticks(4, 8, 2) == (15, 26)


def test_zb_segments_partition_the_1f1b_scan():
    for n, M, v in [(2, 2, 1), (2, 4, 1), (4, 4, 1), (4, 16, 1),
                    (3, 7, 1), (2, 4, 2), (4, 8, 2), (8, 32, 1)]:
        j_last, T = _1f1b_ticks(n, M, v)
        warm, steady, drain, f_end = _zb_segments(n, M, v)
        # the three segments tile [0, T): zb re-brackets capacity, it
        # never lengthens the critical path
        assert warm + steady + drain == T, (n, M, v)
        assert warm == v * n - 1
        assert f_end == warm + steady == j_last + n
        assert drain == T - f_end >= 0


def test_zb_bubble_accounting_fields():
    for n, M, v in [(4, 16, 1), (8, 32, 1), (2, 4, 2)]:
        acct = pp_bubble_fraction(n, M, v, schedule="zb")
        _, _, _, f_end = _zb_segments(n, M, v)
        assert acct["schedule"] == "zb"
        assert acct["useful_slots"] == 3 * M * v
        assert acct["slot_capacity"] == 3 * f_end
        # the accounting rounds to 4 decimals for telemetry
        assert acct["bubble_fraction"] == pytest.approx(
            1.0 - M * v / f_end, abs=5e-5
        )
        # zb strictly beats 1f1b at the same geometry
        v1 = pp_bubble_fraction(n, M, v)["bubble_fraction"]
        assert acct["bubble_fraction"] < v1


def test_zb_beats_1f1b_v4_roofline_at_bench_geometry():
    # the ISSUE's done bar, as arithmetic: zb v=1 under the analytic
    # 1F1B interleave-v4 fractions the bubble study recorded
    for n, M in [(4, 16), (8, 32)]:
        zb = pp_bubble_fraction(n, M, 1, schedule="zb")["bubble_fraction"]
        v4 = pp_bubble_fraction(n, M, 4)["bubble_fraction"]
        assert zb < v4, (n, M, zb, v4)


def test_interleave_layer_perm_roundtrip():
    for L, n, v in [(8, 4, 2), (8, 2, 2), (12, 2, 3), (16, 4, 2),
                    (8, 4, 1), (6, 3, 2)]:
        perm = interleave_layer_perm(L, n, v)
        assert sorted(perm.tolist()) == list(range(L)), (L, n, v)
        logical = np.arange(L)
        stored = logical[perm]
        # invert with argsort: stored[argsort(perm)] == logical
        np.testing.assert_array_equal(stored[np.argsort(perm)], logical)
        # stage s's contiguous block is its v round-robin chunks in
        # chunk-major order
        Lc = L // (n * v)
        block = stored[: v * Lc]
        expect = np.concatenate(
            [np.arange(c * n * Lc, c * n * Lc + Lc) for c in range(v)]
        )
        np.testing.assert_array_equal(block, expect)


# ------------------------------------------------ loud rejections


def test_factory_rejects_bad_zb_compositions(devices):
    mesh = ddp.make_mesh(("data", "pipe"), shape=(2, 4))
    with pytest.raises(ValueError, match="cp_axis"):
        make_pp_train_step(
            _scan_cfg(cp_axis="seq"), mesh=mesh, microbatches=4,
            schedule="zb",
        )
    with pytest.raises(ValueError, match="aux"):
        make_pp_train_step(
            _scan_cfg(moe_experts=2), mesh=mesh, microbatches=4,
            schedule="zb", moe_aux_weight=0.01,
        )
    with pytest.raises(ValueError, match="schedule"):
        make_pp_train_step(
            _scan_cfg(), mesh=mesh, microbatches=4, schedule="zb2",
        )
    # gpipe still rejects virtual; 1f1b/zb accept it
    with pytest.raises(ValueError, match="virtual"):
        make_pp_train_step(
            _scan_cfg(num_layers=8), mesh=mesh, microbatches=4,
            schedule="gpipe", virtual=2,
        )


def test_dpp_cli_zb_validation():
    base = ["--device", "cpu", "--fake-devices", "8", "--model", "gpt2",
            "--dataset", "synthetic-lm", "--pp", "4"]
    # microbatch minimum: fewer microbatches than stages has no steady
    # state for W to fill
    with pytest.raises(SystemExit, match="--pp-microbatches >= --pp"):
        dpp.validate_args(dpp.parse_args(
            base + ["--pp-schedule", "zb", "--pp-microbatches", "2"]
        ))
    # unsupported composition: context parallel
    with pytest.raises(SystemExit, match="does not compose with --cp"):
        dpp.validate_args(dpp.parse_args(
            base + ["--pp-schedule", "zb", "--cp", "2"]
        ))
    # unsupported composition: MoE aux loss (default aux weight is on)
    with pytest.raises(SystemExit, match="MoE aux loss"):
        dpp.validate_args(dpp.parse_args(
            base + ["--pp-schedule", "zb", "--moe-experts", "4"]
        ))
    # layer divisibility extends to pp x virtual
    with pytest.raises(SystemExit, match="divisible by --pp"):
        dpp.validate_args(dpp.parse_args(
            base + ["--pp-schedule", "zb", "--layers", "6"]
        ))
    # virtual now composes with zb (and still rejects gpipe)
    dpp.validate_args(dpp.parse_args(
        base + ["--pp-schedule", "zb", "--pp-virtual", "2",
                "--layers", "8"]
    ))
    with pytest.raises(SystemExit, match="--pp-schedule 1f1b or zb"):
        dpp.validate_args(dpp.parse_args(
            base + ["--pp-schedule", "gpipe", "--pp-virtual", "2"]
        ))
    # the happy path validates clean
    dpp.validate_args(dpp.parse_args(
        base + ["--pp-schedule", "zb", "--pp-microbatches", "8"]
    ))


# ------------------------------------------------ measured reconstruction


def test_measured_bubble_roundtrip_through_events(devices, tmp_path):
    """Close the loop the way a real run does: compiled zb step ->
    phase counters -> pp_phase event -> merged timeline ->
    measured_bubble_fraction; measured must equal the factory's
    analytic number exactly (same schedule, zero drift)."""
    from distributeddataparallel_tpu.observability.events import (
        EventLog,
        events_path,
        load_timeline,
    )
    from distributeddataparallel_tpu.observability.pipeline import (
        measured_bubble_fraction,
        phase_counts_payload,
    )
    from distributeddataparallel_tpu.observability.schema import (
        validate_file,
    )

    cfg = _scan_cfg()
    mesh = ddp.make_mesh(("data", "pipe"), shape=(2, 4))
    params = TransformerLM(cfg).init(
        jax.random.PRNGKey(0), jnp.zeros((1, 32), jnp.int32)
    )["params"]
    tokens = np.random.default_rng(0).integers(
        0, 256, size=(8, 33)
    ).astype(np.int32)
    step = make_pp_train_step(
        cfg, mesh=mesh, microbatches=4, donate=False, schedule="zb"
    )
    state = shard_state_pp(
        ddp.TrainState.create(apply_fn=None, params=params,
                              tx=optax.sgd(0.1)),
        mesh,
    )
    _, metrics = step(state, shard_batch({"tokens": tokens}, mesh),
                      jax.random.PRNGKey(0))

    edir = str(tmp_path / "events")
    with EventLog(events_path(edir, 0), proc=0) as log:
        log.emit("pp_phase", **phase_counts_payload(
            jax.device_get(metrics["pp_phase_counts"]),
            schedule="zb", n_stages=4, virtual=1, microbatches=4,
            accounting=step.bubble_accounting,
        ))
    assert validate_file(events_path(edir, 0)) == []

    rec = measured_bubble_fraction(load_timeline(edir))
    assert rec is not None
    acct = step.bubble_accounting
    assert rec["schedule"] == "zb" and rec["n_stages"] == 4
    assert rec["measured_bubble_fraction"] == pytest.approx(
        acct["bubble_fraction"], abs=1e-4
    )
    assert rec["analytic_bubble_fraction"] == acct["bubble_fraction"]
    assert [s["useful_slots"] for s in rec["per_stage"]] == [12, 12, 12, 12]

    # degrade path: a timeline with no pp_phase records reconstructs
    # to None (the report's "not a pipeline run" line)
    assert measured_bubble_fraction([{"kind": "span"}]) is None
    assert measured_bubble_fraction([]) is None
