"""Fleet-wide distributed tracing tests: context, spans, decomposition.

The load-bearing contracts:

- trace/span ids are **derived, never drawn** — same name parts, same
  ids — so a ``VirtualClock`` replay of the same (seed, config) fleet
  run emits byte-identical trace ids (the property that makes traces
  diffable across replays);
- every completed request's span tree decomposes its measured TTFT
  into queue/prefill/handoff/decode within 5% (``err_frac``), even
  with an engine killed mid-run — a disconnected tree shows up as
  queue time leaking into the error, not as a silent gap;
- ``check_lineage`` is structural: exactly one root per trace, every
  parent edge lands in the same trace, orphans and cross-trace edges
  produce distinct diagnostics;
- schema v2 admits the trace fields (hex-shape-checked) and still
  validates v1 records without them;
- the Perfetto export stitches one flow per multi-span trace and
  ``validate_trace`` catches a dangling flow id;
- the rendezvous RPC transport echoes trace fields in replies without
  them ever reaching store-method dispatch;
- the /metrics plane round-trips: registry → Prometheus text → scrape
  → parsed floats, and malformed payloads raise instead of zero-fill.
"""

import json
import os
import socket
import sys

import jax
import jax.numpy as jnp
import numpy as np
import pytest

sys.path.insert(0, "/root/repo")

from distributeddataparallel_tpu.models import TransformerLM, tiny_lm
from distributeddataparallel_tpu.observability import (
    MetricsHTTPServer,
    MetricsRegistry,
    check_lineage,
    critical_path_of,
    parse_prometheus_text,
    prometheus_text,
    request_decompositions,
    root_context,
    scrape,
    tier_rollups,
    to_trace_events,
    ttft_rollup,
    validate_record,
    validate_trace,
)
from distributeddataparallel_tpu.observability.events import (
    EventLog,
    read_events,
)
from distributeddataparallel_tpu.observability.tracecontext import (
    SpanContext,
    derive_span_id,
    derive_trace_id,
    from_fields,
    from_traceparent,
)
from distributeddataparallel_tpu.serving import (
    EngineConfig,
    FleetConfig,
    LoadConfig,
    ServingFleet,
    VirtualClock,
    make_trace,
    run_load,
)


def _model():
    cfg = tiny_lm(
        vocab_size=97, num_layers=2, num_heads=2, d_model=32, d_ff=64,
        max_seq_len=64, positional="learned", norm="layernorm",
        activation="gelu", tie_embeddings=True,
    )
    model = TransformerLM(cfg)
    params = model.init(
        jax.random.PRNGKey(0), jnp.zeros((1, 4), jnp.int32)
    )["params"]
    return cfg, model, params


def _ecfg(**over):
    base = dict(num_slots=4, num_blocks=48, block_size=8, prefill_chunk=8)
    base.update(over)
    return EngineConfig(**base)


def _drive(fleet, clock, max_steps=800):
    steps = 0
    while fleet.has_work():
        fleet.step()
        clock.tick()
        steps += 1
        assert steps < max_steps, "fleet failed to drain"


# ---------------------------------------------------- context algebra


def test_trace_ids_deterministic_and_scoped():
    assert derive_trace_id(3, "req-0") == derive_trace_id(3, "req-0")
    assert derive_trace_id(3, "req-0") != derive_trace_id(3, "req-1")
    # unit separator: concatenation cannot collide across part splits
    assert derive_trace_id("ab", "c") != derive_trace_id("a", "bc")
    tid = derive_trace_id("x")
    assert len(tid) == 32 and int(tid, 16) >= 0
    # span ids are scoped to their trace: same parts, different trace
    other = derive_trace_id("y")
    assert derive_span_id(tid, "root") != derive_span_id(other, "root")
    with pytest.raises(ValueError):
        derive_trace_id()


def test_root_and_child_contexts():
    root = root_context("req", "f-7")
    assert root.parent_id is None
    child = root.child("prefill", "prefill-0", 4)
    assert child.trace_id == root.trace_id
    assert child.parent_id == root.span_id
    # deterministic: re-deriving the same child gives the same id
    assert child == root.child("prefill", "prefill-0", 4)
    # serialization round-trips through plain fields
    assert from_fields(child.to_fields()) == child
    assert from_fields(root.to_fields()) == root
    assert "parent" not in root.to_fields()
    # W3C interop shape
    tp = child.traceparent()
    assert tp == f"00-{child.trace_id}-{child.span_id}-01"
    parsed = from_traceparent(tp)
    assert (parsed.trace_id, parsed.span_id) == \
        (child.trace_id, child.span_id)


def test_malformed_contexts_rejected():
    with pytest.raises(ValueError):
        SpanContext(trace_id="zz" * 16, span_id="ab" * 8)
    with pytest.raises(ValueError):
        SpanContext(trace_id="ab" * 16, span_id="ab" * 7)  # 14 hex
    assert from_fields({"trace": "nope", "span": "ab" * 8}) is None
    assert from_fields({"trace": "ab" * 16}) is None
    assert from_fields(None) is None
    assert from_traceparent("01-xx-yy-zz") is None


# ------------------------------------------------- schema v2 envelope


def _rec(**over):
    base = dict(v=2, ts=1.0, seq=0, proc="w0", kind="span",
                name="x", dur_s=0.1)
    base.update(over)
    return base


def test_schema_v2_trace_fields():
    ctx = root_context("req", "f-0").child("serve", "decode-0", 1)
    assert validate_record(_rec(**ctx.to_fields())) == []
    # v1 records without trace fields still validate
    assert validate_record(_rec(v=1)) == []
    # the Tracer's legacy nesting-scope names in span/parent (no
    # ``trace`` field) predate v2 and must keep validating
    assert validate_record(_rec(v=1, parent="epoch")) == []
    assert validate_record(_rec(parent="epoch")) == []
    # hex-shape enforcement once ``trace`` opts the record in
    assert any(
        "not 32-hex" in p
        for p in validate_record(_rec(trace="abc", span="ab" * 8))
    )
    assert any(
        "not 16-hex" in p
        for p in validate_record(_rec(trace="ab" * 16, span="xyz"))
    )
    # a parent edge with no span of its own is meaningless
    assert any(
        "parent without span" in p
        for p in validate_record(
            _rec(trace="ab" * 16, parent="ab" * 8)
        )
    )


# ---------------------------------------------- lineage + decomposition


def _span(trace, span, name, start, end, parent=None, **extra):
    rec = dict(
        v=2, ts=end, seq=0, proc="w0", kind="span", name=name,
        dur_s=end - start, start_s=start, end_s=end,
        trace=trace, span=span,
    )
    if parent is not None:
        rec["parent"] = parent
    rec.update(extra)
    return rec


def _clean_tree(fid="f-0", ttft=1.0):
    root = root_context("req", fid)
    pre = root.child("prefill", "prefill-0", 1)
    dec = root.child("decode", "decode-0", 1)
    return [
        _span(root.trace_id, root.span_id, f"req:{fid}", 0.0, 2.0,
              ttft_s=ttft, req=fid),
        _span(pre.trace_id, pre.span_id, f"prefill:{fid}", 0.2, 1.0,
              parent=pre.parent_id),
        _span(dec.trace_id, dec.span_id, f"decode:{fid}", 1.0, 2.0,
              parent=dec.parent_id),
    ]


def test_check_lineage_clean_and_broken():
    assert check_lineage(_clean_tree()) == []
    # orphan: parent id never emitted anywhere
    recs = _clean_tree()
    recs[1]["parent"] = "ab" * 8
    assert any("orphan" in p for p in check_lineage(recs))
    # two roots in one trace
    recs = _clean_tree()
    del recs[1]["parent"]
    probs = check_lineage(recs)
    assert any("2 root spans" in p for p in probs)
    # cross-trace edge: parent exists, but in a different trace
    recs = _clean_tree("f-0") + _clean_tree("f-1")
    recs[4]["parent"] = recs[0]["span"]  # f-1's prefill -> f-0's root
    assert any("cross-trace edge" in p for p in check_lineage(recs))


def test_decomposition_clips_merges_and_balances():
    recs = _clean_tree(ttft=1.0)  # window [0, 1]: 0.2 q, 0.8 prefill
    (d,) = request_decompositions(recs)
    assert d["req"] == "f-0" and d["ttft_s"] == 1.0
    assert d["prefill_s"] == pytest.approx(0.8)
    assert d["queue_s"] == pytest.approx(0.2)
    # decode span [1.0, 2.0] is entirely outside the TTFT window
    assert d["decode_s"] == 0.0 and d["handoff_s"] == 0.0
    assert d["err_frac"] == pytest.approx(0.0)
    roll = ttft_rollup([d])
    assert roll["ttft_queue_share_frac"] == pytest.approx(0.2)
    assert roll["ttft_prefill_share_frac"] == pytest.approx(0.8)
    assert roll["ttft_decomp_err_frac"] == pytest.approx(0.0)
    tiers = tier_rollups([d])
    assert tiers["decode"]["requests"] == 1  # no handoff -> decode tier
    assert tiers["prefill"]["requests"] == 0
    path = critical_path_of(recs, d["trace"])
    assert [s["name"] for s in path] == \
        ["req:f-0", "prefill:f-0", "decode:f-0"]


# --------------------------------------------- fleet end-to-end tracing


def _traced_fleet_run(tmp_path, tag, kill=None, n_req=6, n_new=8):
    cfg, model, params = _model()
    log = EventLog(str(tmp_path / f"events-{tag}.jsonl"), f"fleet-{tag}")
    clock = VirtualClock()
    fleet = ServingFleet(
        model, params, _ecfg(), FleetConfig(prefill=1, decode=2),
        time_fn=clock, events=log, check_invariants=True,
    )
    rng = np.random.default_rng(11)
    fids = [
        fleet.submit(rng.integers(1, cfg.vocab_size, 12 + i).tolist(),
                     n_new)
        for i in range(n_req)
    ]
    if kill:
        for _ in range(3):          # get requests in flight first
            fleet.step()
            clock.tick()
        fleet.kill_engine(kill)
    _drive(fleet, clock)
    summary = fleet.summary()   # emits tier_summary while the log is open
    log.close()
    return fleet, fids, summary, \
        read_events(str(tmp_path / f"events-{tag}.jsonl"))


def test_fleet_kill_decomposition_within_5pct(tmp_path):
    fleet, fids, s, records = _traced_fleet_run(
        tmp_path, "kill", kill="decode-0"
    )
    assert sorted(fleet.completed) == sorted(fids)
    assert s["dropped_req_total"] == 0 and s["kills"] == 1
    # zero orphan spans even though one engine died mid-request
    assert check_lineage(records) == []
    decomps = request_decompositions(records)
    assert sorted(d["req"] for d in decomps) == sorted(fids)
    for d in decomps:
        # per-request: segments must re-derive the measured TTFT
        assert d["err_frac"] <= 0.05, d
        assert d["spans"] >= 2, d  # root + at least one engine child
    # time lost to the killed engine surfaces as queue wait, not error
    roll = ttft_rollup(decomps)
    assert 0.0 <= roll["ttft_queue_share_frac"] <= 1.0
    assert roll["ttft_decomp_err_frac"] <= 0.05
    # handed-off requests classify into the prefill (disaggregated)
    # tier even though handoff rides after the first token
    tiers = tier_rollups(decomps)
    assert tiers["prefill"]["requests"] >= 1
    assert tiers["prefill"]["requests"] + tiers["decode"]["requests"] \
        == len(decomps)


def test_fleet_replay_trace_ids_byte_identical(tmp_path):
    cfg, model, params = _model()
    lcfg = LoadConfig(
        rate_rps=40.0, duration_s=0.3, prompt_len=(10, 20),
        output_len=(4, 8), vocab_size=cfg.vocab_size, seed=3,
        turns=2, turn_gap_s=0.05,
    )
    trace = make_trace(lcfg)

    def one_run(tag):
        log = EventLog(str(tmp_path / f"ev-{tag}.jsonl"), f"run-{tag}")
        clock = VirtualClock()
        fleet = ServingFleet(
            model, params, _ecfg(), FleetConfig(prefill=1, decode=2),
            time_fn=clock, events=log,
        )
        out = run_load(fleet, trace, clock=clock)
        log.close()
        assert out["dropped_req_total"] == 0
        spans = [
            (r["trace"], r["span"], r.get("parent"), r["name"])
            for r in read_events(str(tmp_path / f"ev-{tag}.jsonl"))
            if r.get("kind") == "span"
        ]
        return sorted(spans)

    spans_a, spans_b = one_run("a"), one_run("b")
    assert spans_a and spans_a == spans_b
    # and the trees those ids form are structurally clean
    assert check_lineage(
        read_events(str(tmp_path / "ev-a.jsonl"))
    ) == []


# --------------------------------------------------- Perfetto flows


def test_trace_export_flow_events_and_validation():
    recs = _clean_tree()
    trace = to_trace_events(recs)
    assert validate_trace(trace) == []
    flows = [e for e in trace["traceEvents"]
             if e.get("ph") in ("s", "t", "f")]
    assert flows, "multi-span trace produced no flow events"
    tid16 = recs[0]["trace"][:16]
    assert {e["id"] for e in flows} == {tid16}
    assert sorted(e["ph"] for e in flows) == sorted("stf")
    # per-trace track naming: thread_name metadata carries req:<trace8>
    names = [
        e for e in trace["traceEvents"]
        if e.get("ph") == "M" and e.get("name") == "thread_name"
        and e["args"]["name"].startswith("req:")
    ]
    assert names and names[0]["args"]["name"] == f"req:{recs[0]['trace'][:8]}"
    # a dangling flow (start without finish) is a validation failure
    broken = dict(trace)
    broken["traceEvents"] = [
        e for e in trace["traceEvents"] if e.get("ph") != "f"
    ]
    assert any("dangling flow" in p for p in validate_trace(broken))


# ----------------------------------------------- rendezvous RPC echo


def test_rendezvous_rpc_echoes_trace_fields(tmp_path):
    from distributeddataparallel_tpu.runtime.rendezvous import (
        RendezvousStore,
        TCPRendezvousClient,
        TCPRendezvousServer,
    )

    store = RendezvousStore(str(tmp_path / "rdzv"))
    ctx = root_context("hostgang", "gang", "w0")
    with TCPRendezvousServer(store) as srv:
        # the high-level client stamps every RPC with its context
        with TCPRendezvousClient(
            srv.address, trace=ctx.to_fields()
        ) as c:
            c.join("w0")
            assert "w0" in c.alive()
        # raw-wire check: trace fields ride the payload, are echoed in
        # the reply, and never reach store-method dispatch as kwargs
        host, port = srv.address.rsplit(":", 1)
        with socket.create_connection((host, int(port)), timeout=5) as sk:
            msg = {"op": "roster", **ctx.to_fields()}
            sk.sendall((json.dumps(msg) + "\n").encode())
            reply = json.loads(sk.makefile().readline())
            assert reply["ok"] is True
            assert reply["trace"] == ctx.trace_id
            assert reply["span"] == ctx.span_id
            # error replies echo them too (correlatable failures)
            bad = {"op": "no_such_op", **ctx.to_fields()}
            sk.sendall((json.dumps(bad) + "\n").encode())
            reply = json.loads(sk.makefile().readline())
            assert reply["ok"] is False
            assert reply["trace"] == ctx.trace_id
    assert store.roster() == ["w0"] or store.alive() == ["w0"]


# ------------------------------------------------- /metrics plane


def test_httpmetrics_roundtrip():
    reg = MetricsRegistry()
    reg.counter("serve_tok_s")  # pre-initialized gauge-style series
    reg.gauge("router_queue_depth").set(3)
    reg.counter("requests_total").inc(7)
    srv = MetricsHTTPServer(reg)
    try:
        got = scrape(srv.address)
    finally:
        srv.close()
    assert got["router_queue_depth"] == 3.0
    assert got["requests_total"] == 7.0
    assert "serve_tok_s" in got  # present even while still zero
    # text rendering is the parseable subset by construction
    assert parse_prometheus_text(prometheus_text(reg)) == got


def test_parse_prometheus_text_rejects_malformed():
    with pytest.raises(ValueError):
        parse_prometheus_text("this is not a sample line\n")
    with pytest.raises(ValueError):
        parse_prometheus_text("name 1.0 extra\n")
    # comments and blanks are fine
    assert parse_prometheus_text("# TYPE x gauge\n\nx 2\n") == {"x": 2.0}
