"""PowerSGD low-rank comm hook (torch DDP ``powerSGD_hook`` analog,
Vogels et al. 2019 — the register_comm_hook surface behind ref
dpp.py:52).

- exactness pin: with rank >= min(n, m) the projector spans the full
  column space, so the hook reproduces dense DP up to float error;
- error feedback: the per-replica residual satisfies the conservation
  invariant  sum_t applied_t + err_T == sum_t local_grad_t  exactly;
- training: low rank still learns (loss drops), replicas in lockstep;
- state: checkpoints round-trip (typed PowerSGDLeaf nodes + None
  entries survive orbax);
- rejections: zero/presynced/uninitialized comm_state.
"""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest
from jax.sharding import PartitionSpec as P

import distributeddataparallel_tpu as ddp
from distributeddataparallel_tpu.data.loader import shard_batch
from distributeddataparallel_tpu.models.simple_cnn import TinyMLP
from distributeddataparallel_tpu.ops.losses import cross_entropy_loss
from distributeddataparallel_tpu.parallel.data_parallel import (
    broadcast_params,
)
from distributeddataparallel_tpu.parallel.powersgd import (
    MIN_COMPRESS_ELEMS,
    powersgd_state,
    powersgd_state_specs,
    powersgd_sync,
    powersgd_wire_bytes,
)
from distributeddataparallel_tpu.runtime.distributed import make_mesh
from distributeddataparallel_tpu.training.state import TrainState
from distributeddataparallel_tpu.training.train_step import make_train_step


def _setup(lr=0.1, seed=0, hidden=128):
    # 16x16 images -> input matrix (256, hidden): compressed for
    # hidden >= 64; the hidden x 10 head and biases stay dense — the
    # mixed compressed/dense tree the hook must handle.
    model = TinyMLP(features=(hidden,), num_classes=10)
    params = model.init(
        jax.random.PRNGKey(seed), jnp.zeros((1, 16, 16, 1))
    )["params"]

    def loss_fn(params, batch, rng):
        logits = model.apply({"params": params}, batch["image"])
        return cross_entropy_loss(logits, batch["label"]), {}

    state = TrainState.create(
        apply_fn=model.apply, params=params, tx=optax.sgd(lr)
    )
    return model, state, loss_fn


def _fake_batches(num_steps, global_batch, seed=0):
    rng = np.random.default_rng(seed)
    protos = rng.normal(size=(10, 16, 16, 1)).astype(np.float32)
    out = []
    for _ in range(num_steps):
        labels = rng.integers(0, 10, size=(global_batch,))
        imgs = protos[labels] + 0.1 * rng.normal(
            size=(global_batch, 16, 16, 1)
        ).astype(np.float32)
        out.append(
            {"image": imgs.astype(np.float32),
             "label": labels.astype(np.int32)}
        )
    return out


def _run(state, loss_fn, mesh, batches, **kw):
    step = make_train_step(loss_fn, mesh=mesh, donate=False, **kw)
    state = broadcast_params(state, mesh)
    losses = []
    for b in batches:
        state, m = step(state, shard_batch(b, mesh), jax.random.PRNGKey(1))
        losses.append(float(m["loss"]))
    return state, losses


def test_full_rank_matches_dense(devices):
    """rank >= min(n, m): P spans col(M), M_hat == mean(M) up to float —
    the hook's exactness pin against plain DP over several steps."""
    mesh = make_mesh(("data",))
    n = len(jax.devices())
    batches = _fake_batches(3, 8 * n)
    _, state, loss_fn = _setup(hidden=64)  # input matrix 256x64, full rank
    dense, _ = _run(state, loss_fn, mesh, batches)
    comm = powersgd_state(state.params, n, rank=64)
    hooked, _ = _run(
        state.replace(comm_state=comm), loss_fn, mesh, batches,
        grad_compress="powersgd",
    )
    for a, b in zip(
        jax.tree.leaves(dense.params), jax.tree.leaves(hooked.params)[:4]
    ):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=0, atol=2e-4
        )


def test_low_rank_learns_in_lockstep(devices):
    """rank-2 compression still trains (loss drops well below init) and
    the applied params stay replicated bit-identically."""
    mesh = make_mesh(("data",))
    n = len(jax.devices())
    batches = _fake_batches(30, 8 * n)
    _, state, loss_fn = _setup()
    comm = powersgd_state(state.params, n, rank=2)
    hooked, losses = _run(
        state.replace(comm_state=comm), loss_fn, mesh, batches,
        grad_compress="powersgd",
    )
    assert losses[-1] < 0.5 * losses[0], losses
    for leaf in jax.tree.leaves(hooked.params):
        assert bool(jnp.all(jnp.isfinite(leaf)))
        shards = [np.asarray(s.data) for s in leaf.addressable_shards]
        for s in shards[1:]:
            np.testing.assert_array_equal(shards[0], s)


def test_error_feedback_conservation(devices):
    """Per replica: sum_t applied + err_T == sum_t local_grad_t exactly
    (float-exact up to accumulation rounding) — the hook never silently
    drops gradient signal, it defers it."""
    mesh = make_mesh(("data",))
    n = len(jax.devices())
    n_mat, m_mat = 256, 128
    rng = np.random.default_rng(0)
    # deterministic per-replica "gradients" for 3 rounds
    gs = [
        rng.normal(size=(n, n_mat, m_mat)).astype(np.float32)
        for _ in range(3)
    ]
    comm = {"w": powersgd_state({"w": gs[0][0]}, n, rank=2)["w"]}

    def one_round(g_local, st):
        synced, new_st = powersgd_sync({"w": g_local}, st, "data")
        return synced["w"], new_st

    import functools

    @functools.partial(
        jax.jit,
        static_argnames=(),
    )
    def run(gs_stacked, comm):
        def body(g_all, st):
            # g_all: (n, n_mat, m_mat) sharded; inside shard_map each
            # position sees (1, n_mat, m_mat)
            return one_round(g_all[0], st)

        sm = jax.shard_map(
            body,
            mesh=mesh,
            in_specs=(P("data"), powersgd_state_specs(comm)),
            out_specs=(P(), powersgd_state_specs(comm)),
            check_vma=False,
        )
        applied = []
        st = comm
        for i in range(3):
            a, st = sm(gs_stacked[i], st)
            applied.append(a)
        return applied, st

    applied, st = run(jnp.asarray(np.stack(gs)), comm)
    # replica r: sum of its local grads == sum of applied + its residual
    for r in range(n):
        local_sum = sum(g[r] for g in gs)
        applied_sum = sum(np.asarray(a) for a in applied)
        err_r = np.asarray(st["w"].err)[r]
        np.testing.assert_allclose(
            applied_sum + err_r, local_sum, rtol=0, atol=1e-4
        )


def test_wire_bytes_and_leaf_selection(devices):
    """Ledger: 2-D+ leaves above the size floor compress; 1-D and tiny
    leaves stay dense; ratio matches shapes exactly."""
    params = {
        "emb": jnp.zeros((1000, 64)),     # compressed
        "conv": jnp.zeros((3, 3, 32, 64)),  # compressed (folded 288x64)
        "bias": jnp.zeros((4096,)),       # 1-D: dense
        "tiny": jnp.zeros((16, 16)),      # under floor: dense
    }
    st = powersgd_state(params, 4, rank=2)
    assert st["emb"] is not None and st["conv"] is not None
    assert st["bias"] is None and st["tiny"] is None
    assert st["emb"].q.shape == (64, 2)
    assert st["emb"].err.shape == (4, 1000, 64)
    led = powersgd_wire_bytes(params, rank=2)
    assert led["n_compressed_leaves"] == 2 and led["n_dense_leaves"] == 2
    exp_comp = (
        4 * 2 * (1000 + 64)        # emb factors
        + 4 * 2 * (288 + 64)       # conv factors
        + 4096 * 4 + 16 * 16 * 4   # dense leaves
    )
    assert led["powersgd_wire_bytes"] == exp_comp
    assert params["emb"].size >= MIN_COMPRESS_ELEMS


def test_comm_state_checkpoints(tmp_path, devices):
    """TrainState.comm_state (typed nodes + None entries) survives an
    orbax save/restore round-trip."""
    from distributeddataparallel_tpu.training.checkpoint import (
        Checkpointer,
    )

    mesh = make_mesh(("data",))
    n = len(jax.devices())
    _, state, loss_fn = _setup()
    state = state.replace(
        comm_state=powersgd_state(state.params, n, rank=2)
    )
    state = broadcast_params(state, mesh)
    batches = _fake_batches(1, 8 * n)
    state, _ = _run(state, loss_fn, mesh, batches, grad_compress="powersgd")
    ckpt = Checkpointer(str(tmp_path))
    ckpt.save(state, 0)
    ckpt.wait()
    template = state.replace()  # same structure
    restored, nxt = ckpt.restore_latest(template)
    assert nxt == 1
    for a, b in zip(
        jax.tree.leaves(state.comm_state),
        jax.tree.leaves(restored.comm_state),
    ):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_rejections(devices):
    mesh = make_mesh(("data",))
    _, state, loss_fn = _setup()
    with pytest.raises(ValueError, match="grad_compress"):
        make_train_step(
            loss_fn, mesh=mesh, zero=True, grad_compress="powersgd"
        )
    with pytest.raises(ValueError, match="presynced"):
        make_train_step(
            loss_fn, mesh=mesh, grad_compress="powersgd",
            presynced=lambda p: False,
        )
    with pytest.raises(ValueError, match="comm_state"):
        step = make_train_step(
            loss_fn, mesh=mesh, grad_compress="powersgd"
        )
        b = _fake_batches(1, 8 * len(jax.devices()))[0]
        step(
            broadcast_params(state, mesh),
            shard_batch(b, mesh),
            jax.random.PRNGKey(0),
        )
    with pytest.raises(ValueError, match="rank"):
        powersgd_state(state.params, 4, rank=0)


def test_elastic_resume_resets_residuals_keeps_q(tmp_path, devices):
    """Data-degree change (8 -> 4): everything restores against the
    template, the warm Q transports, the residuals rebuild as zeros at
    the new degree (rows have no replica mapping across topologies)."""
    from distributeddataparallel_tpu.training.checkpoint import (
        Checkpointer,
    )
    from distributeddataparallel_tpu.training.elastic import (
        elastic_restore,
        topology_meta,
    )
    from jax.sharding import Mesh

    devs = np.array(jax.devices())
    mesh8 = Mesh(devs.reshape(8), ("data",))
    mesh4 = Mesh(devs[:4].reshape(4), ("data",))
    _, state, loss_fn = _setup()
    st8 = state.replace(comm_state=powersgd_state(state.params, 8, rank=2))
    st8 = broadcast_params(st8, mesh8)
    st8, _ = _run(st8, loss_fn, mesh8, _fake_batches(2, 16),
                  grad_compress="powersgd")
    ckpt = Checkpointer(str(tmp_path))
    ckpt.save(st8, 0, meta=topology_meta(mesh8, "replicated"))
    ckpt.wait()

    st4 = state.replace(comm_state=powersgd_state(state.params, 4, rank=2))
    st4 = broadcast_params(st4, mesh4)
    restored, nxt = elastic_restore(ckpt, st4, mesh4, layout="replicated")
    assert nxt == 1
    # params transported exactly
    for a, b in zip(
        jax.tree.leaves(st8.params), jax.tree.leaves(restored.params)
    ):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    # warm Q transported; residuals fresh zeros at the new degree
    from distributeddataparallel_tpu.parallel.powersgd import _is_entry

    e8 = [
        e for e in jax.tree.flatten(
            st8.comm_state, is_leaf=_is_entry
        )[0] if e is not None
    ]
    er = [
        e for e in jax.tree.flatten(
            restored.comm_state, is_leaf=_is_entry
        )[0] if e is not None
    ]
    assert e8 and len(e8) == len(er)
    for a, b in zip(e8, er):
        np.testing.assert_array_equal(np.asarray(a.q), np.asarray(b.q))
        assert b.err.shape[0] == 4
        assert float(jnp.abs(b.err).max()) == 0.0


def test_rank_clamped_to_leaf_dims(devices):
    """Oversized rank clamps to min(n, m) per leaf — q keeps a stable
    shape through sync (donated-buffer + checkpoint-template safety)."""
    mesh = make_mesh(("data",))
    n = len(jax.devices())
    _, state, loss_fn = _setup(hidden=64)  # input matrix 256x64
    comm = powersgd_state(state.params, n, rank=512)
    from distributeddataparallel_tpu.parallel.powersgd import _is_entry

    entries = [
        e for e in jax.tree.flatten(comm, is_leaf=_is_entry)[0]
        if e is not None
    ]
    assert entries and all(e.q.shape[1] == 64 for e in entries)
    st = state.replace(comm_state=comm)
    st, _ = _run(st, loss_fn, mesh, _fake_batches(1, 8 * n),
                 grad_compress="powersgd")
    after = [
        e for e in jax.tree.flatten(st.comm_state, is_leaf=_is_entry)[0]
        if e is not None
    ]
    for a, b in zip(entries, after):
        assert a.q.shape == b.q.shape
    led = powersgd_wire_bytes(state.params, rank=512)
    assert led["powersgd_wire_bytes"] < led["dense_wire_bytes"] * 2


def test_legacy_checkpoint_without_comm_state_restores(
    tmp_path, devices
):
    """Checkpoints written before TrainState grew comm_state restore
    into the new template (comm_state stays empty) — the round-5 review
    regression: StandardRestore rejects the extra empty node, the
    Checkpointer falls back to a partial restore."""
    from typing import Any, Callable

    import flax.struct

    from distributeddataparallel_tpu.training.checkpoint import (
        Checkpointer,
    )

    @flax.struct.dataclass
    class LegacyTrainState:  # the pre-comm_state field set
        step: jax.Array
        params: Any
        opt_state: Any
        model_state: Any
        apply_fn: Callable = flax.struct.field(pytree_node=False)
        tx: Any = flax.struct.field(pytree_node=False)

    _, state, _ = _setup()
    legacy = LegacyTrainState(
        step=jnp.asarray(0, jnp.int32),
        params=state.params,
        opt_state=state.opt_state,
        model_state={},
        apply_fn=None,
        tx=state.tx,
    )
    ckpt = Checkpointer(str(tmp_path))
    ckpt.save(legacy, 2)
    ckpt.wait()
    restored, nxt = ckpt.restore_latest(state)
    assert nxt == 3
    assert restored.comm_state == {}
    for a, b in zip(
        jax.tree.leaves(state.params), jax.tree.leaves(restored.params)
    ):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    # a template that EXPECTS hook state stays a loud error
    with_hook = state.replace(
        comm_state=powersgd_state(state.params, len(jax.devices()), rank=2)
    )
    with pytest.raises(ValueError):
        Checkpointer(str(tmp_path)).restore_latest(with_hook)
