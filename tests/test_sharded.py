"""Streaming file-sharded dataset (data/sharded.py; VERDICT r3 item 2).

The contract under test: (1) the streaming gather is byte-identical to
the in-RAM u8 path, (2) DistributedSampler semantics are preserved
batch-for-batch through the DataLoader, (3) a corpus far larger than the
RAM budget streams with only batch-sized anonymous allocations — image
bytes stay file-backed (memmap), and (4) the dpp.py CLI trains on
``--dataset shards:DIR`` end to end.
"""

import os

import numpy as np
import pytest

from distributeddataparallel_tpu.data import (
    ArrayDataset,
    DataLoader,
    ShardedImageDataset,
    shard_indices_for_hosts,
    write_image_shards,
    write_synthetic_image_shards,
)


def _toy_corpus(n=300, shape=(16, 16, 3), seed=0):
    rng = np.random.default_rng(seed)
    images = rng.integers(0, 256, size=(n,) + shape, dtype=np.uint8)
    labels = rng.integers(0, 10, size=(n,), dtype=np.int32)
    return images, labels


def test_roundtrip_gather_matches_in_ram(tmp_path):
    images, labels = _toy_corpus()
    root = write_image_shards(
        str(tmp_path / "shards"), images, labels, shard_rows=64
    )
    ds = ShardedImageDataset(root)
    assert len(ds) == len(images)
    assert ds.image_shape == images.shape[1:]

    ram = ArrayDataset(images, labels, normalize_u8=True)
    idx = np.asarray([0, 5, 63, 64, 65, 127, 128, 299, 7])  # shard borders
    got = ds.gather(idx)
    want_img = np.stack([ram[int(i)][0] for i in idx])
    # native fused kernel vs NumPy normalize: identical up to 1 ulp
    np.testing.assert_allclose(
        got["image"], want_img.astype(np.float32), atol=1e-6
    )
    np.testing.assert_array_equal(got["label"], labels[idx])

    img0, lab0 = ds[42]
    np.testing.assert_allclose(img0, ram[42][0], atol=1e-6)
    assert lab0 == labels[42]


def test_shard_indices_for_hosts():
    offsets = np.asarray([0, 64, 128, 150])
    sid, local = shard_indices_for_hosts(offsets, [0, 63, 64, 149, 100])
    np.testing.assert_array_equal(sid, [0, 0, 1, 2, 1])
    np.testing.assert_array_equal(local, [0, 63, 0, 21, 36])


def test_loader_batches_match_in_ram_dataset(devices, tmp_path):
    """Sampler semantics preserved: the streaming dataset yields the
    exact batches the in-RAM dataset does — shuffle, epoch reshuffle,
    pad masking and all."""
    import distributeddataparallel_tpu as ddp

    images, labels = _toy_corpus(n=275)  # non-multiple of replicas: pads
    mesh = ddp.make_mesh(("data",))

    def batches(dataset, epoch):
        loader = DataLoader(
            dataset, per_replica_batch=4, mesh=mesh, seed=3,
            drop_last=False, with_mask=True, device_feed=False,
        )
        loader.set_epoch(epoch)
        return list(loader)

    for epoch in (0, 1):
        for sharded_root_rows in (64,):
            root = write_image_shards(
                str(tmp_path / f"eq_{epoch}"), images, labels,
                shard_rows=sharded_root_rows,
            )
            a = batches(ShardedImageDataset(root), epoch)
            b = batches(ArrayDataset(images, labels, normalize_u8=True), epoch)
            assert len(a) == len(b) and len(a) > 0
            for x, y in zip(a, b):
                np.testing.assert_array_equal(x["image"], y["image"])
                np.testing.assert_array_equal(x["label"], y["label"])
                np.testing.assert_array_equal(x["valid"], y["valid"])


def _rss_anon_kb() -> int:
    with open("/proc/self/status") as fh:
        for line in fh:
            if line.startswith("RssAnon"):
                return int(line.split()[1])
    raise RuntimeError("no RssAnon in /proc/self/status")


def test_streams_larger_than_ram_budget(tmp_path, devices):
    """A 4 GB corpus (sparse shard files: real .npy layout, hole-backed
    pages) streams with anonymous-RSS growth bounded by batch buffers —
    nothing resembling the corpus is ever materialized in RAM."""
    import distributeddataparallel_tpu as ddp

    shape = (224, 224, 3)  # ImageNet geometry: ~150 KB/row
    n = 28000              # ~4.2 GB of image bytes
    root = write_synthetic_image_shards(
        str(tmp_path / "big"), n, shape, 1000, shard_rows=4096, sparse=True,
    )
    ds = ShardedImageDataset(root)
    assert len(ds) == n

    mesh = ddp.make_mesh(("data",))
    loader = DataLoader(
        ds, per_replica_batch=16, mesh=mesh, seed=0, device_feed=False,
    )
    base = _rss_anon_kb()
    it = iter(loader)
    seen = 0
    for _ in range(12):  # 12 × 128-row batches ≈ 230 MB of corpus touched
        batch = next(it)
        assert batch["image"].shape == (128,) + shape
        seen += batch["image"].shape[0]
    grown_mb = (_rss_anon_kb() - base) / 1024
    touched_mb = seen * int(np.prod(shape)) / 1e6
    # Anonymous growth must be batch-scale (float32 batch ≈ 77 MB plus
    # allocator slack), nowhere near the ~1.7 GB of (normalized f32)
    # corpus already consumed, let alone the 4 GB corpus.
    assert grown_mb < 500, (grown_mb, touched_mb)


def test_cli_trains_on_shards(tmp_path, devices):
    import sys

    sys.path.insert(0, "/root/repo")
    import dpp

    rng = np.random.default_rng(0)
    # small learnable corpus: class-conditional synthetic, real bytes
    root = write_synthetic_image_shards(
        str(tmp_path / "cli"), 256, (16, 16, 3), 10, shard_rows=100,
        sparse=False,
    )
    # train split layout: bare directory (no train/ subdir)
    args = dpp.parse_args(
        [
            "--device", "cpu",
            "--model", "cnn",
            "--dataset", f"shards:{root}",
            "--epochs", "2",
            "--batch-size", "4",
            "--lr", "0.05",
            "--log-every", "1000",
        ]
    )
    final_loss = dpp.train(args)
    assert final_loss == final_loss and final_loss < 2.5  # finite, learning


def test_device_normalize_path(tmp_path, devices):
    """device_normalize=True ships raw u8; in-graph normalize matches the
    host-side fused kernel to 1 ulp."""
    import jax

    from distributeddataparallel_tpu.ops import normalize_u8_images

    images, labels = _toy_corpus(n=64)
    root = write_image_shards(str(tmp_path / "u8"), images, labels,
                              shard_rows=32)
    dev = ShardedImageDataset(root, device_normalize=True)
    host = ShardedImageDataset(root)
    idx = np.arange(0, 64, 3)
    raw = dev.gather(idx)
    assert raw["image"].dtype == np.uint8
    np.testing.assert_array_equal(raw["image"], images[idx])
    normed = jax.jit(normalize_u8_images)(raw["image"])
    np.testing.assert_allclose(
        np.asarray(normed), host.gather(idx)["image"], atol=1e-6
    )


def test_cli_trains_on_shards_with_eval(tmp_path, devices):
    """shards:DIR with train/val split layout + --eval end to end."""
    import sys

    sys.path.insert(0, "/root/repo")
    import dpp

    base = tmp_path / "split"
    write_synthetic_image_shards(
        str(base / "train"), 256, (16, 16, 3), 10, shard_rows=100
    )
    write_synthetic_image_shards(
        str(base / "val"), 64, (16, 16, 3), 10, shard_rows=100, seed=9
    )
    args = dpp.parse_args(
        [
            "--device", "cpu", "--model", "cnn",
            "--dataset", f"shards:{base}",
            "--epochs", "1", "--batch-size", "4", "--lr", "0.05",
            "--log-every", "1000", "--eval",
        ]
    )
    final_loss = dpp.train(args)
    assert final_loss == final_loss


def test_u8_augment_fill_matches_float_path():
    """random_crop on uint8 (device-normalize streaming path) pads with
    u8 black (0), agreeing with the float path's normalized -1.0 fill
    after in-graph normalize — not wrapping -1.0 to white 255."""
    from distributeddataparallel_tpu.data import random_crop
    from distributeddataparallel_tpu.data.datasets import normalize_images

    rng_img = np.random.default_rng(0)
    u8 = rng_img.integers(0, 256, size=(4, 8, 8, 3), dtype=np.uint8)
    f32 = normalize_images(u8)
    out_u8 = random_crop(u8, np.random.default_rng(7), padding=4)
    out_f32 = random_crop(f32, np.random.default_rng(7), padding=4)
    np.testing.assert_allclose(
        normalize_images(out_u8), out_f32, atol=1e-6
    )


def test_write_image_shards_infers_num_classes(tmp_path):
    images, labels = _toy_corpus(n=40)
    root = write_image_shards(str(tmp_path / "nc"), images, labels)
    assert ShardedImageDataset(root).num_classes == int(labels.max()) + 1


def test_dataset_arg_rejected_at_parse_time():
    import sys

    sys.path.insert(0, "/root/repo")
    import dpp

    with pytest.raises(SystemExit):
        dpp.parse_args(["--dataset", "cifar"])  # typo: parse-time error


def test_manifest_validation(tmp_path):
    with pytest.raises(FileNotFoundError):
        ShardedImageDataset(str(tmp_path / "nope"))
    images, labels = _toy_corpus(n=10)
    with pytest.raises(ValueError):
        write_image_shards(
            str(tmp_path / "f32"), images.astype(np.float32), labels
        )
