"""Pipeline-parallelism tests: the GPipe schedule over the scanned layer
stack must reproduce the single-device step exactly — forward loss and
updated params (layer slices sharded over the pipe axis), with the
backward pipeline arising purely from AD through the forward loop."""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest
from jax.sharding import PartitionSpec as P

import distributeddataparallel_tpu as ddp
from distributeddataparallel_tpu.data.loader import shard_batch
from distributeddataparallel_tpu.models import TransformerLM, tiny_lm
from distributeddataparallel_tpu.ops import lm_cross_entropy
from distributeddataparallel_tpu.parallel.pipeline_parallel import (
    make_pp_train_step,
    pp_param_specs,
    shard_state_pp,
)


def _scan_cfg(**over):
    base = dict(
        num_layers=4, num_heads=2, d_model=32, d_ff=64, scan_layers=True,
        max_seq_len=32,
    )
    base.update(over)
    return tiny_lm(**base)


def _reference_step(cfg, params, tokens, tx):
    model = TransformerLM(cfg)

    def loss_fn(p):
        logits = model.apply({"params": p}, jnp.asarray(tokens[:, :-1]))
        return lm_cross_entropy(logits, jnp.asarray(tokens[:, 1:]))

    loss, grads = jax.value_and_grad(loss_fn)(params)
    updates, _ = tx.update(grads, tx.init(params), params)
    return float(loss), optax.apply_updates(params, updates)


def _run_pp(cfg, params, tokens, tx, mesh, microbatches, schedule="gpipe"):
    step = make_pp_train_step(cfg, mesh=mesh, microbatches=microbatches,
                              donate=False, schedule=schedule)
    state = ddp.TrainState.create(apply_fn=None, params=params, tx=tx)
    state = shard_state_pp(state, mesh)
    batch = shard_batch({"tokens": tokens}, mesh)
    state, metrics = step(state, batch, jax.random.PRNGKey(0))
    return float(metrics["loss"]), state


def test_pp_param_specs(devices):
    cfg = _scan_cfg()
    params = TransformerLM(cfg).init(
        jax.random.PRNGKey(0), jnp.zeros((1, 16), jnp.int32)
    )["params"]
    specs = pp_param_specs(params)
    flat = {
        "/".join(str(getattr(k, "key", k)) for k in path): s
        for path, s in jax.tree_util.tree_flatten_with_path(specs)[0]
    }
    # Every stacked layer leaf shards its leading (layer) dim.
    assert all(
        s[0] == "pipe" for k, s in flat.items() if k.startswith("layers/")
    )
    assert flat["token_embed/embedding"] == P()


@pytest.mark.parametrize("family", ["llama_style", "gpt2_style"])
def test_dp_pp_matches_single_device(family, devices):
    """DP(2) x PP(4) GPipe step == single-device step: same loss, same
    updated params (layer slices gathered back by the output sharding)."""
    if family == "llama_style":
        cfg = _scan_cfg()  # rope + rmsnorm + swiglu + tied
    else:
        cfg = _scan_cfg(
            norm="layernorm", activation="gelu", positional="learned",
            use_bias=True,
        )
    mesh = ddp.make_mesh(("data", "pipe"), shape=(2, 4))
    params = TransformerLM(cfg).init(
        jax.random.PRNGKey(0), jnp.zeros((1, 32), jnp.int32)
    )["params"]
    tx = optax.sgd(0.1)
    rng = np.random.default_rng(0)
    tokens = rng.integers(0, 256, size=(8, 33)).astype(np.int32)

    ref_loss, ref_params = _reference_step(cfg, params, tokens, tx)
    pp_loss, state = _run_pp(cfg, params, tokens, tx, mesh, microbatches=4)

    assert pp_loss == pytest.approx(ref_loss, rel=1e-5)
    for (path, a), b in zip(
        jax.tree_util.tree_flatten_with_path(state.params)[0],
        jax.tree.leaves(ref_params),
    ):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), atol=2e-5,
            err_msg="/".join(str(getattr(k, "key", k)) for k in path),
        )


def test_pp_remat_and_adam(devices):
    """PP composes with remat'd blocks and stateful optimizers (adam's
    mu/nu shard with their layer slices)."""
    cfg = _scan_cfg(remat=True)
    mesh = ddp.make_mesh(("data", "pipe"), shape=(2, 4))
    params = TransformerLM(cfg).init(
        jax.random.PRNGKey(0), jnp.zeros((1, 32), jnp.int32)
    )["params"]
    tx = optax.adam(1e-2)
    rng = np.random.default_rng(1)
    tokens = rng.integers(0, 256, size=(8, 33)).astype(np.int32)

    ref_loss, ref_params = _reference_step(cfg, params, tokens, tx)
    pp_loss, state = _run_pp(cfg, params, tokens, tx, mesh, microbatches=2)

    assert pp_loss == pytest.approx(ref_loss, rel=1e-5)
    for a, b in zip(
        jax.tree.leaves(state.params), jax.tree.leaves(ref_params)
    ):
        # 2e-4, not 2e-5: with remat the PP backward reassociates the
        # float32 reductions and adam's rsqrt amplifies the drift to a
        # few 1e-5 on ~1-scale params (max observed ~6e-5).
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=2e-4)


def test_pp_rejects_unscanned(devices):
    mesh = ddp.make_mesh(("data", "pipe"), shape=(2, 4))
    cfg = dataclasses.replace(_scan_cfg(), scan_layers=False)
    with pytest.raises(ValueError, match="scan_layers"):
        make_pp_train_step(cfg, mesh=mesh, microbatches=2)


def test_dp_pp_tp_matches_single_device(devices):
    """Three axes at once: DP(2) x PP(2) x TP(2) — stages over 'pipe',
    Megatron head/hidden sharding over 'model' inside each stage — must
    still reproduce the single-device step."""
    cfg = _scan_cfg()
    cfg_x = dataclasses.replace(cfg, tp_axis="model")
    mesh = ddp.make_mesh(("data", "pipe", "model"), shape=(2, 2, 2))
    params = TransformerLM(cfg).init(
        jax.random.PRNGKey(0), jnp.zeros((1, 32), jnp.int32)
    )["params"]
    tx = optax.sgd(0.1)
    rng = np.random.default_rng(2)
    tokens = rng.integers(0, 256, size=(8, 33)).astype(np.int32)

    ref_loss, ref_params = _reference_step(cfg, params, tokens, tx)

    step = make_pp_train_step(cfg_x, mesh=mesh, microbatches=2, donate=False)
    state = ddp.TrainState.create(apply_fn=None, params=params, tx=tx)
    state = shard_state_pp(state, mesh, tp_axis="model")
    batch = shard_batch({"tokens": tokens}, mesh)
    state, metrics = step(state, batch, jax.random.PRNGKey(0))

    assert float(metrics["loss"]) == pytest.approx(ref_loss, rel=1e-5)
    for (path, a), b in zip(
        jax.tree_util.tree_flatten_with_path(state.params)[0],
        jax.tree.leaves(ref_params),
    ):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), atol=2e-5,
            err_msg="/".join(str(getattr(k, "key", k)) for k in path),
        )


def test_dp_cp_pp_matches_single_device(devices):
    """DP(2) x CP(2) x PP(2): sequence-sharded microbatches flow through
    the GPipe schedule with ring attention inside each stage — must equal
    the single-device step."""
    from distributeddataparallel_tpu.data import shard_lm_batch

    cfg = _scan_cfg()
    cfg_x = dataclasses.replace(cfg, cp_axis="seq")
    mesh = ddp.make_mesh(("data", "seq", "pipe"), shape=(2, 2, 2))
    params = TransformerLM(cfg).init(
        jax.random.PRNGKey(0), jnp.zeros((1, 32), jnp.int32)
    )["params"]
    tx = optax.sgd(0.1)
    rng = np.random.default_rng(4)
    tokens = rng.integers(0, 256, size=(8, 33)).astype(np.int32)

    ref_loss, ref_params = _reference_step(cfg, params, tokens, tx)

    step = make_pp_train_step(cfg_x, mesh=mesh, microbatches=2, donate=False)
    state = ddp.TrainState.create(apply_fn=None, params=params, tx=tx)
    state = shard_state_pp(state, mesh)
    batch = shard_lm_batch(tokens, mesh)
    state, metrics = step(state, batch, jax.random.PRNGKey(0))

    assert float(metrics["loss"]) == pytest.approx(ref_loss, rel=1e-5)
    for (path, a), b in zip(
        jax.tree_util.tree_flatten_with_path(state.params)[0],
        jax.tree.leaves(ref_params),
    ):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), atol=2e-5,
            err_msg="/".join(str(getattr(k, "key", k)) for k in path),
        )


def test_cp_pp_tp_four_axis_mesh(devices):
    """The full stack on one mesh: CP(2) x PP(2) x TP(2) (data axis of 1)
    — ring attention + GPipe stages + Megatron sharding simultaneously."""
    from distributeddataparallel_tpu.data import shard_lm_batch

    cfg = _scan_cfg(num_kv_heads=2)
    cfg_x = dataclasses.replace(cfg, cp_axis="seq", tp_axis="model")
    mesh = ddp.make_mesh(
        ("data", "seq", "pipe", "model"), shape=(1, 2, 2, 2)
    )
    params = TransformerLM(cfg).init(
        jax.random.PRNGKey(0), jnp.zeros((1, 32), jnp.int32)
    )["params"]
    tx = optax.sgd(0.1)
    rng = np.random.default_rng(6)
    tokens = rng.integers(0, 256, size=(4, 33)).astype(np.int32)

    ref_loss, ref_params = _reference_step(cfg, params, tokens, tx)

    step = make_pp_train_step(cfg_x, mesh=mesh, microbatches=2, donate=False)
    state = ddp.TrainState.create(apply_fn=None, params=params, tx=tx)
    state = shard_state_pp(state, mesh, tp_axis="model")
    batch = shard_lm_batch(tokens, mesh)
    state, metrics = step(state, batch, jax.random.PRNGKey(0))

    assert float(metrics["loss"]) == pytest.approx(ref_loss, rel=1e-5)
    for a, b in zip(
        jax.tree.leaves(state.params), jax.tree.leaves(ref_params)
    ):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=3e-5)


def test_dp_pp_ep_moe_matches_single_device(devices):
    """DP(2) x PP(2) x EP(2): MoE blocks inside pipeline stages with the
    expert dim sharded over its own axis — equal to single-device."""
    cfg = _scan_cfg(moe_experts=4)
    cfg_x = dataclasses.replace(cfg, ep_axis="expert")
    mesh = ddp.make_mesh(("data", "pipe", "expert"), shape=(2, 2, 2))
    params = TransformerLM(cfg).init(
        jax.random.PRNGKey(0), jnp.zeros((1, 32), jnp.int32)
    )["params"]
    tx = optax.sgd(0.1)
    rng = np.random.default_rng(8)
    tokens = rng.integers(0, 256, size=(8, 33)).astype(np.int32)

    ref_loss, ref_params = _reference_step(cfg, params, tokens, tx)

    # aux weight 0: the reference is pure CE (aux equivalence is pinned
    # separately below and in test_expert_parallel).
    step = make_pp_train_step(
        cfg_x, mesh=mesh, microbatches=2, donate=False, moe_aux_weight=0.0
    )
    state = ddp.TrainState.create(apply_fn=None, params=params, tx=tx)
    state = shard_state_pp(state, mesh, ep_axis="expert")
    batch = shard_batch({"tokens": tokens}, mesh)
    state, metrics = step(state, batch, jax.random.PRNGKey(0))

    assert float(metrics["loss"]) == pytest.approx(ref_loss, rel=1e-5)
    for (path, a), b in zip(
        jax.tree_util.tree_flatten_with_path(state.params)[0],
        jax.tree.leaves(ref_params),
    ):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), atol=2e-5,
            err_msg="/".join(str(getattr(k, "key", k)) for k in path),
        )

    # With the aux ON, the loss gains a positive load-balance term (the
    # switch aux is >= 1 at any routing) and still trains.
    step_aux = make_pp_train_step(
        cfg_x, mesh=mesh, microbatches=2, donate=False, moe_aux_weight=0.01
    )
    state2 = ddp.TrainState.create(apply_fn=None, params=params, tx=tx)
    state2 = shard_state_pp(state2, mesh, ep_axis="expert")
    state2, m2 = step_aux(state2, batch, jax.random.PRNGKey(0))
    assert float(m2["loss"]) > ref_loss
    assert float(m2["loss"]) == pytest.approx(ref_loss + 0.01 * 1.0, abs=0.05)


def test_pp_eval_matches_unsharded(devices):
    """Pipelined masked eval == valid-weighted per-row metrics computed on
    the unsharded model, padded duplicate rows contributing nothing."""
    from distributeddataparallel_tpu.ops.losses import (
        per_example_accuracy,
        per_example_cross_entropy,
    )
    from distributeddataparallel_tpu.parallel.pipeline_parallel import (
        make_pp_eval_step,
    )

    cfg = _scan_cfg()
    mesh = ddp.make_mesh(("data", "pipe"), shape=(2, 4))
    model = TransformerLM(cfg)
    rng = np.random.default_rng(19)
    tokens = rng.integers(0, 256, size=(8, 17)).astype(np.int32)
    valid = np.array([1, 1, 1, 0, 1, 0, 1, 1], np.float32)  # padded rows
    params = model.init(
        jax.random.PRNGKey(0), jnp.zeros((1, 16), jnp.int32)
    )["params"]

    logits = model.apply({"params": params}, jnp.asarray(tokens[:, :-1]))
    ce = np.asarray(per_example_cross_entropy(logits, tokens[:, 1:]))
    hit = np.asarray(per_example_accuracy(logits, tokens[:, 1:]))
    want_loss = (ce * valid).sum() / valid.sum()
    want_acc = (hit * valid).sum() / valid.sum()

    # Params placed in the PP layout (layer stack over the pipe axis).
    from jax.sharding import NamedSharding

    placed = jax.tree.map(
        lambda x, s: jax.device_put(x, NamedSharding(mesh, s)),
        params,
        pp_param_specs(params),
    )
    eval_step = make_pp_eval_step(cfg, mesh=mesh, microbatches=2)
    batch = shard_batch(
        {"tokens": tokens, "valid": valid.astype(np.int32)}, mesh
    )
    metrics, cnt = eval_step(placed, batch)
    assert float(cnt) == valid.sum()
    np.testing.assert_allclose(float(metrics["loss"]), want_loss, rtol=1e-5)
    np.testing.assert_allclose(float(metrics["accuracy"]), want_acc, rtol=1e-5)


def test_pp_eval_seq_bound_guard(devices):
    """Eval enforces the same max_seq_len bound as training (XLA would
    silently clamp positional gathers past it)."""
    from distributeddataparallel_tpu.parallel.pipeline_parallel import (
        make_pp_eval_step,
    )

    cfg = _scan_cfg(max_seq_len=16)
    mesh = ddp.make_mesh(("data", "pipe"), shape=(2, 4))
    params = TransformerLM(_scan_cfg(max_seq_len=32)).init(
        jax.random.PRNGKey(0), jnp.zeros((1, 16), jnp.int32)
    )["params"]
    eval_step = make_pp_eval_step(cfg, mesh=mesh, microbatches=2)
    batch = shard_batch(
        {
            "tokens": np.zeros((8, 33), np.int32),  # S=32 > max_seq_len=16
            "valid": np.ones((8,), np.int32),
        },
        mesh,
    )
    with pytest.raises(ValueError, match="max_seq_len"):
        eval_step(params, batch)


def test_dp_ulysses_pp_matches_single_device(devices):
    """DP(2) x CP(2, ulysses) x PP(2): the all_to_all sequence-parallel
    attention composes with the pipeline exactly as the ring does (same
    block dispatch, same global positions) — must equal the
    single-device step."""
    from distributeddataparallel_tpu.data import shard_lm_batch

    cfg = _scan_cfg()
    cfg_x = dataclasses.replace(cfg, cp_axis="seq", cp_impl="ulysses")
    mesh = ddp.make_mesh(("data", "seq", "pipe"), shape=(2, 2, 2))
    params = TransformerLM(cfg).init(
        jax.random.PRNGKey(0), jnp.zeros((1, 32), jnp.int32)
    )["params"]
    tx = optax.sgd(0.1)
    rng = np.random.default_rng(6)
    tokens = rng.integers(0, 256, size=(8, 33)).astype(np.int32)

    ref_loss, ref_params = _reference_step(cfg, params, tokens, tx)

    step = make_pp_train_step(cfg_x, mesh=mesh, microbatches=2, donate=False)
    state = ddp.TrainState.create(apply_fn=None, params=params, tx=tx)
    state = shard_state_pp(state, mesh)
    state, metrics = step(state, shard_lm_batch(tokens, mesh),
                          jax.random.PRNGKey(0))

    assert float(metrics["loss"]) == pytest.approx(ref_loss, rel=1e-5)
    for a, b in zip(
        jax.tree.leaves(state.params), jax.tree.leaves(ref_params)
    ):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=2e-5)


def test_pp_zero_matches_plain_pp(devices):
    """PP × ZeRO-1: the flat-chunk sharded update on each position's
    pipe-local tree must reproduce the replicated-optimizer DP×PP step
    exactly over two adam steps (flat opt vectors sharded over BOTH
    axes)."""
    cfg = _scan_cfg()
    mesh = ddp.make_mesh(("data", "pipe"), shape=(2, 4))
    rng = np.random.default_rng(21)
    batches = [
        shard_batch(
            {"tokens": rng.integers(0, 256, size=(8, 17)).astype(np.int32)},
            mesh,
        )
        for _ in range(2)
    ]
    params = TransformerLM(cfg).init(
        jax.random.PRNGKey(0), jnp.zeros((1, 16), jnp.int32)
    )["params"]
    tx = optax.adam(1e-2)

    state = ddp.TrainState.create(apply_fn=None, params=params, tx=tx)
    state = shard_state_pp(state, mesh)
    step = make_pp_train_step(cfg, mesh=mesh, microbatches=2, donate=False)
    for b in batches:
        state, _ = step(state, b, jax.random.PRNGKey(0))

    zstate = ddp.zero_state(
        apply_fn=None, params=params, tx=tx, mesh=mesh, pp_axis="pipe"
    )
    zstep = make_pp_train_step(
        cfg, mesh=mesh, microbatches=2, donate=False, zero=True
    )
    for b in batches:
        zstate, _ = zstep(zstate, b, jax.random.PRNGKey(0))

    # Flat opt vectors sharded over BOTH axes.
    assert any(
        l.sharding.spec == P(("data", "pipe"))
        for l in jax.tree.leaves(zstate.opt_state) if l.ndim >= 1
    )
    for (path, a), b in zip(
        jax.tree_util.tree_flatten_with_path(state.params)[0],
        jax.tree.leaves(zstate.params),
    ):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), atol=2e-6,
            err_msg="/".join(str(getattr(k, "key", k)) for k in path),
        )


def test_cp_pp_zero_matches_replicated(devices):
    """DP(2) x CP(2) x PP(2) with ZeRO-1 == the replicated-optimizer
    sequence-sharded pipeline step (the ZeRO reduce_scatter runs after
    the pipe psum AND the cp pmean complete the gradients)."""
    from distributeddataparallel_tpu.data import shard_lm_batch

    cfg = _scan_cfg()
    cfg_x = dataclasses.replace(cfg, cp_axis="seq")
    mesh = ddp.make_mesh(("data", "seq", "pipe"), shape=(2, 2, 2))
    params = TransformerLM(cfg).init(
        jax.random.PRNGKey(0), jnp.zeros((1, 32), jnp.int32)
    )["params"]
    tx = optax.adam(1e-2)
    rng = np.random.default_rng(31)
    batches = [
        shard_lm_batch(
            rng.integers(0, 256, size=(8, 33)).astype(np.int32), mesh
        )
        for _ in range(2)
    ]

    state = ddp.TrainState.create(apply_fn=None, params=params, tx=tx)
    state = shard_state_pp(state, mesh)
    step = make_pp_train_step(cfg_x, mesh=mesh, microbatches=2, donate=False)
    for b in batches:
        state, _ = step(state, b, jax.random.PRNGKey(0))

    zstate = ddp.zero_state(
        apply_fn=None, params=params, tx=tx, mesh=mesh, pp_axis="pipe"
    )
    zstep = make_pp_train_step(
        cfg_x, mesh=mesh, microbatches=2, donate=False, zero=True
    )
    for b in batches:
        zstate, _ = zstep(zstate, b, jax.random.PRNGKey(0))

    # The flat opt vectors really are sharded over (data, pipe) on the
    # 3-axis mesh — without this, replicated opt state would still pass
    # the value comparison below.
    assert any(
        l.sharding.spec == P(("data", "pipe"))
        for l in jax.tree.leaves(zstate.opt_state) if l.ndim >= 1
    )
    for a, b in zip(
        jax.tree.leaves(state.params), jax.tree.leaves(zstate.params)
    ):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=2e-6)


# --- 1F1B schedule (interleaved manual backward) ------------------------


def test_1f1b_matches_gpipe_and_single_device(devices):
    """The 1F1B schedule is a different EXECUTION ORDER of the same math:
    loss equals GPipe's exactly and params match the single-device step
    (manual vjp backward vs AD — tolerance covers recompute rounding)."""
    cfg = _scan_cfg()
    mesh = ddp.make_mesh(("data", "pipe"), shape=(2, 4))
    rng = np.random.default_rng(0)
    tokens = rng.integers(0, 256, size=(8, 33)).astype(np.int32)
    params = TransformerLM(cfg).init(
        jax.random.PRNGKey(0), jnp.zeros((1, 32), jnp.int32)
    )["params"]
    tx = optax.adam(1e-2)
    loss_ref, params_ref = _reference_step(cfg, params, tokens, tx)
    loss_g, _ = _run_pp(cfg, params, tokens, tx, mesh, 4, schedule="gpipe")
    loss_1, state = _run_pp(cfg, params, tokens, tx, mesh, 4, schedule="1f1b")
    assert loss_1 == pytest.approx(loss_g, rel=1e-6)
    assert loss_1 == pytest.approx(loss_ref, rel=1e-5)
    for (path, a), b in zip(
        jax.tree_util.tree_flatten_with_path(state.params)[0],
        jax.tree.leaves(params_ref),
    ):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), atol=5e-5,
            err_msg="/".join(str(getattr(k, "key", k)) for k in path),
        )


def test_1f1b_tp_matches_single_device(devices):
    """1F1B x Megatron TP: the stage body's collectives transpose inside
    the manual jax.vjp exactly as under AD."""
    cfg = _scan_cfg(num_heads=4, num_kv_heads=2, tp_axis="model")
    cfg_ref = dataclasses.replace(cfg, tp_axis=None)
    mesh = ddp.make_mesh(("data", "pipe", "model"), shape=(2, 2, 2))
    rng = np.random.default_rng(5)
    tokens = rng.integers(0, 256, size=(8, 33)).astype(np.int32)
    params = TransformerLM(cfg_ref).init(
        jax.random.PRNGKey(0), jnp.zeros((1, 32), jnp.int32)
    )["params"]
    tx = optax.sgd(0.1)
    loss_ref, params_ref = _reference_step(cfg_ref, params, tokens, tx)
    step = make_pp_train_step(
        cfg, mesh=mesh, microbatches=4, donate=False, schedule="1f1b"
    )
    state = ddp.TrainState.create(apply_fn=None, params=params, tx=tx)
    state = shard_state_pp(state, mesh, tp_axis="model")
    state, metrics = step(
        state, shard_batch({"tokens": tokens}, mesh), jax.random.PRNGKey(0)
    )
    assert float(metrics["loss"]) == pytest.approx(loss_ref, rel=1e-5)
    for (path, a), b in zip(
        jax.tree_util.tree_flatten_with_path(state.params)[0],
        jax.tree.leaves(params_ref),
    ):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), atol=5e-5,
            err_msg="/".join(str(getattr(k, "key", k)) for k in path),
        )


def test_1f1b_activation_memory_flat_in_microbatches(devices):
    """THE point of 1F1B: compiled temp memory is ~constant in the
    microbatch count (a 2n-slot stage-input ring + per-tick transients)
    while GPipe's grows linearly (AD keeps every microbatch's stage
    activations until the reverse sweep)."""
    cfg = _scan_cfg(
        num_layers=8, d_model=128, d_ff=512, num_heads=4, max_seq_len=256
    )
    mesh = ddp.make_mesh(("data", "pipe"), shape=(2, 4))
    params = TransformerLM(cfg).init(
        jax.random.PRNGKey(0), jnp.zeros((1, 256), jnp.int32)
    )["params"]
    rng = np.random.default_rng(0)

    def temp_mib(schedule, M):
        tokens = rng.integers(0, 256, size=(4 * M, 257)).astype(np.int32)
        step = make_pp_train_step(
            cfg, mesh=mesh, microbatches=M, donate=False, schedule=schedule
        )
        state = ddp.TrainState.create(
            apply_fn=None, params=params, tx=optax.sgd(0.1)
        )
        state = shard_state_pp(state, mesh)
        batch = shard_batch({"tokens": tokens}, mesh)
        state, _ = step(state, batch, jax.random.PRNGKey(0))
        analysis = (
            step.jitted.lower(state, batch, jax.random.PRNGKey(0))
            .compile().memory_analysis()
        )
        if analysis is None:
            pytest.skip("backend exposes no memory analysis")
        return analysis.temp_size_in_bytes / 2**20

    g4, g16 = temp_mib("gpipe", 4), temp_mib("gpipe", 16)
    f4, f16 = temp_mib("1f1b", 4), temp_mib("1f1b", 16)
    # GPipe grows with M; 1F1B stays flat and beats GPipe at M=16.
    assert g16 > 1.5 * g4, (g4, g16)
    assert f16 < 1.2 * f4, (f4, f16)
    assert f16 < g16 / 2, (f16, g16)


def test_1f1b_zero_matches_gpipe_zero(devices):
    """ZeRO-1 under the 1F1B schedule: the manual-vjp grads feed the same
    reduce_scatter/sharded-update path as GPipe's AD grads — identical
    loss and params."""
    cfg = _scan_cfg()
    mesh = ddp.make_mesh(("data", "pipe"), shape=(2, 4))
    rng = np.random.default_rng(29)
    tokens = rng.integers(0, 256, size=(8, 33)).astype(np.int32)
    params = TransformerLM(cfg).init(
        jax.random.PRNGKey(0), jnp.zeros((1, 32), jnp.int32)
    )["params"]
    tx = optax.adam(1e-2)

    def run(schedule):
        st = ddp.zero_state(
            apply_fn=None, params=params, tx=tx, mesh=mesh, pp_axis="pipe"
        )
        step = make_pp_train_step(
            cfg, mesh=mesh, microbatches=4, donate=False, zero=True,
            schedule=schedule,
        )
        st, metrics = step(
            st, shard_batch({"tokens": tokens}, mesh), jax.random.PRNGKey(0)
        )
        return float(metrics["loss"]), st.params

    loss_g, params_g = run("gpipe")
    loss_1, params_1 = run("1f1b")
    assert loss_1 == pytest.approx(loss_g, rel=1e-6)
    for (path, a), b in zip(
        jax.tree_util.tree_flatten_with_path(params_1)[0],
        jax.tree.leaves(params_g),
    ):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), atol=5e-5,
            err_msg="/".join(str(getattr(k, "key", k)) for k in path),
        )


def test_1f1b_cp_matches_gpipe_and_single_device(devices):
    """DP x CP x PP under the 1F1B schedule: ring collectives transpose
    inside the manual jax.vjp, the outer cp pmean completes the
    seq-sharded gradient — equal to GPipe and the single-device step."""
    from distributeddataparallel_tpu.data import shard_lm_batch

    cfg = _scan_cfg(cp_axis="seq")
    cfg_ref = dataclasses.replace(cfg, cp_axis=None)
    mesh = ddp.make_mesh(("data", "seq", "pipe"), shape=(2, 2, 2))
    rng = np.random.default_rng(23)
    tokens = rng.integers(0, 256, size=(8, 33)).astype(np.int32)
    params = TransformerLM(cfg_ref).init(
        jax.random.PRNGKey(0), jnp.zeros((1, 32), jnp.int32)
    )["params"]
    tx = optax.adam(1e-2)
    loss_ref, params_ref = _reference_step(cfg_ref, params, tokens, tx)

    def run(schedule):
        step = make_pp_train_step(
            cfg, mesh=mesh, microbatches=2, donate=False, schedule=schedule
        )
        state = ddp.TrainState.create(apply_fn=None, params=params, tx=tx)
        state = shard_state_pp(state, mesh)
        batch = shard_lm_batch(tokens, mesh, data_axis="data",
                               seq_axis="seq")
        state, metrics = step(state, batch, jax.random.PRNGKey(0))
        return float(metrics["loss"]), state.params

    loss_g, params_g = run("gpipe")
    loss_1, params_1 = run("1f1b")
    assert loss_1 == pytest.approx(loss_g, rel=1e-5)
    assert loss_1 == pytest.approx(loss_ref, rel=1e-5)
    for (path, a), b in zip(
        jax.tree_util.tree_flatten_with_path(params_1)[0],
        jax.tree.leaves(params_ref),
    ):
        # 2e-4, not 5e-5: CP splits the sequence reduction on top of
        # the PP microbatch split, so adam integrates doubly-
        # reassociated float32 grads (max observed drift ~7e-5).
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), atol=2e-4,
            err_msg="/".join(str(getattr(k, "key", k)) for k in path),
        )


def test_1f1b_moe_aux_matches_gpipe(devices):
    """The MoE aux loss under 1F1B (aux value + cotangent riding the
    B-tick's stage recompute) equals GPipe's mutable-intermediates path:
    same loss, same updated params."""
    cfg = _scan_cfg(moe_experts=4)
    mesh = ddp.make_mesh(("data", "pipe"), shape=(2, 4))
    rng = np.random.default_rng(21)
    tokens = rng.integers(0, 256, size=(8, 33)).astype(np.int32)
    params = TransformerLM(cfg).init(
        jax.random.PRNGKey(0), jnp.zeros((1, 32), jnp.int32)
    )["params"]
    tx = optax.adam(1e-2)

    def run(schedule):
        step = make_pp_train_step(
            cfg, mesh=mesh, microbatches=4, donate=False,
            schedule=schedule, moe_aux_weight=0.01,
        )
        state = ddp.TrainState.create(apply_fn=None, params=params, tx=tx)
        state = shard_state_pp(state, mesh)
        state, metrics = step(
            state, shard_batch({"tokens": tokens}, mesh),
            jax.random.PRNGKey(0),
        )
        return float(metrics["loss"]), state.params

    loss_g, params_g = run("gpipe")
    loss_1, params_1 = run("1f1b")
    assert loss_1 == pytest.approx(loss_g, rel=1e-5)
    # aux actually contributes (switch aux >= 1 at any routing)
    assert loss_1 > 0.0
    for (path, a), b in zip(
        jax.tree_util.tree_flatten_with_path(params_1)[0],
        jax.tree.leaves(params_g),
    ):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), atol=5e-5,
            err_msg="/".join(str(getattr(k, "key", k)) for k in path),
        )


def test_pp_eval_pads_tail_to_microbatch_multiple(devices):
    """A tail batch whose per-position row count does not divide the
    microbatch count must evaluate (padded with valid=0 rows), matching
    the unsharded masked metrics on the valid rows."""
    from distributeddataparallel_tpu.parallel import make_pp_eval_step
    from distributeddataparallel_tpu.ops import (
        per_example_accuracy,
        per_example_cross_entropy,
    )

    cfg = _scan_cfg()
    mesh = ddp.make_mesh(("data", "pipe"), shape=(2, 4))
    model = TransformerLM(cfg)
    rng = np.random.default_rng(2)
    # 6 rows over data=2 -> 3 rows/position, not divisible by M=4.
    tokens = rng.integers(0, 256, size=(6, 33)).astype(np.int32)
    valid = np.array([1, 1, 1, 1, 1, 0], np.int32)
    params = model.init(
        jax.random.PRNGKey(0), jnp.zeros((1, 32), jnp.int32)
    )["params"]

    logits = model.apply({"params": params}, jnp.asarray(tokens[:, :-1]))
    v = jnp.asarray(valid, jnp.float32)
    want_loss = float(
        jnp.sum(per_example_cross_entropy(logits, tokens[:, 1:]) * v)
        / v.sum()
    )
    want_acc = float(
        jnp.sum(per_example_accuracy(logits, tokens[:, 1:]) * v) / v.sum()
    )

    eval_step = make_pp_eval_step(cfg, mesh=mesh, microbatches=4)
    batch = shard_batch({"tokens": tokens, "valid": valid}, mesh)
    metrics, cnt = eval_step(params, batch)
    assert float(cnt) == 5.0
    assert float(metrics["loss"]) == pytest.approx(want_loss, rel=1e-5)
    assert float(metrics["accuracy"]) == pytest.approx(want_acc, abs=1e-6)


def test_entrypoint_pp_1f1b_cli(devices):
    """dpp.py --pp --pp-schedule 1f1b end-to-end (with eval)."""
    import sys

    sys.path.insert(0, "/root/repo")
    import dpp

    args = dpp.parse_args(
        [
            "--device", "cpu",
            "--model", "gpt2",
            "--layers", "4",
            "--d-model", "32",
            "--seq-len", "32",
            "--vocab-size", "64",
            "--pp", "2",
            "--pp-microbatches", "4",
            "--pp-schedule", "1f1b",
            "--eval",
            "--epochs", "1",
            "--num-examples", "64",
            "--batch-size", "8",
            "--log-every", "1000",
        ]
    )
    loss = dpp.train(args)
    assert loss == loss


def test_interleaved_1f1b_matches_single_device(devices):
    """Interleaved 1F1B (virtual=2): same loss and params as the
    single-device reference step — the round-robin chunk schedule and
    the layer-permutation placement are pure schedule/layout changes
    (VERDICT r4 item 5)."""
    import numpy as _np

    from distributeddataparallel_tpu.parallel.pipeline_parallel import (
        interleave_layer_perm,
    )

    cfg = _scan_cfg(num_layers=8)
    n, v = 2, 2
    mesh = ddp.make_mesh(("data", "pipe"), shape=(4, n))
    params = TransformerLM(cfg).init(
        jax.random.PRNGKey(0), jnp.zeros((1, 32), jnp.int32)
    )["params"]
    tx = optax.sgd(0.1)
    rng = _np.random.default_rng(0)
    tokens = rng.integers(0, 256, size=(16, 33)).astype(_np.int32)

    ref_loss, ref_params = _reference_step(cfg, params, tokens, tx)

    state = ddp.TrainState.create(apply_fn=None, params=params, tx=tx)
    state = shard_state_pp(state, mesh, virtual=v)
    step = make_pp_train_step(
        cfg, mesh=mesh, microbatches=4, donate=False, schedule="1f1b",
        virtual=v,
    )
    batch = shard_batch({"tokens": tokens}, mesh)
    state, metrics = step(state, batch, jax.random.PRNGKey(0))

    assert float(metrics["loss"]) == pytest.approx(ref_loss, rel=1e-5)
    inv = _np.argsort(interleave_layer_perm(cfg.num_layers, n, v))
    for (path, a), b in zip(
        jax.tree_util.tree_flatten_with_path(state.params)[0],
        jax.tree.leaves(ref_params),
    ):
        names = tuple(str(getattr(k, "key", k)) for k in path)
        a = _np.asarray(a)
        if "layers" in names:
            a = a[inv]  # storage (interleaved) -> logical layer order
        _np.testing.assert_allclose(
            a, _np.asarray(b), atol=2e-5,
            err_msg="/".join(names),
        )


def test_interleaved_1f1b_multi_step_matches_gpipe(devices):
    """3 training steps of interleaved 1F1B track GPipe's loss curve
    (same logical model, different schedule + storage layout)."""
    import numpy as _np

    cfg = _scan_cfg(num_layers=4)
    n, v = 2, 2
    mesh = ddp.make_mesh(("data", "pipe"), shape=(4, n))
    params = TransformerLM(cfg).init(
        jax.random.PRNGKey(0), jnp.zeros((1, 32), jnp.int32)
    )["params"]
    tx = optax.adam(1e-2)
    rng = _np.random.default_rng(1)
    batches = [
        rng.integers(0, 256, size=(16, 33)).astype(_np.int32)
        for _ in range(3)
    ]

    g_state = ddp.TrainState.create(apply_fn=None, params=params, tx=tx)
    g_state = shard_state_pp(g_state, mesh)
    g_step = make_pp_train_step(cfg, mesh=mesh, microbatches=4,
                                donate=False)

    i_state = ddp.TrainState.create(apply_fn=None, params=params, tx=tx)
    i_state = shard_state_pp(i_state, mesh, virtual=v)
    i_step = make_pp_train_step(
        cfg, mesh=mesh, microbatches=4, donate=False, schedule="1f1b",
        virtual=v,
    )

    for t in batches:
        b = shard_batch({"tokens": t}, mesh)
        g_state, gm = g_step(g_state, b, jax.random.PRNGKey(0))
        i_state, im = i_step(i_state, b, jax.random.PRNGKey(0))
        assert float(im["loss"]) == pytest.approx(
            float(gm["loss"]), rel=2e-5
        )


def test_interleaved_requires_1f1b_and_divisibility(devices):
    from distributeddataparallel_tpu.parallel.pipeline_parallel import (
        pp_bubble_fraction,
    )

    cfg = _scan_cfg(num_layers=4)
    mesh = ddp.make_mesh(("data", "pipe"), shape=(4, 2))
    with pytest.raises(ValueError, match="1f1b"):
        make_pp_train_step(cfg, mesh=mesh, microbatches=2, virtual=2)
    # 4 layers cannot split into 2 stages x 4 chunks
    with pytest.raises(ValueError, match="divisible"):
        make_pp_train_step(
            cfg, mesh=mesh, microbatches=2, schedule="1f1b", virtual=4
        )
    # bubble accounting: v=1 reproduces the classic 2(n-1) idle units,
    # higher v strictly shrinks it
    b1 = pp_bubble_fraction(4, 8, 1)
    b2 = pp_bubble_fraction(4, 8, 2)
    b4 = pp_bubble_fraction(4, 8, 4)
    assert b1["bubble_stage_units"] == 2 * (4 - 1)
    assert (
        b4["bubble_stage_units"] < b2["bubble_stage_units"]
        < b1["bubble_stage_units"]
    )
