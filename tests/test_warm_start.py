"""Warm-start + dispatch subsystem tests (training.warm_start):

- AOT executable store round trip on CPU: serialize a compiled train
  step, load it back through a FRESH wrapper, same first-step numerics.
- Key-mismatch / corruption paths fall back LOUDLY to JIT (warning
  logged, strict mode raises) — a stale binary must never run silently.
- Persistent compile cache shared across two real spawned processes:
  the second process's compile is a cache HIT (counted via the
  monitoring events, not timing — deterministic in CI).
- Bounded async dispatch: the --dispatch-depth loop is numerically
  inert (bitwise-identical final params vs the blocking loop) and the
  nan-guard breaker still trips within max_bad_steps + depth steps.
"""

import logging
import multiprocessing as mp
import sys

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

sys.path.insert(0, "/root/repo")

import dpp  # noqa: E402
import distributeddataparallel_tpu as ddp  # noqa: E402
from distributeddataparallel_tpu.data.loader import shard_batch  # noqa: E402
from distributeddataparallel_tpu.models import TinyMLP  # noqa: E402
from distributeddataparallel_tpu.ops import cross_entropy_loss  # noqa: E402
from distributeddataparallel_tpu.training.warm_start import (  # noqa: E402
    BoundedDispatch,
    ExecutableStore,
    WarmStartMismatch,
    executable_key,
    warm_train_step,
)
from distributeddataparallel_tpu.utils.logging import get_logger  # noqa: E402


class _Capture(logging.Handler):
    """The repo logger has propagate=False, so caplog can't see it —
    capture by attaching directly."""

    def __init__(self):
        super().__init__()
        self.messages = []

    def emit(self, record):
        self.messages.append(record.getMessage())


class _capture_warnings:
    def __enter__(self):
        self._h = _Capture()
        get_logger().addHandler(self._h)
        return self._h.messages

    def __exit__(self, *exc):
        get_logger().removeHandler(self._h)


def _setup(mesh):
    model = TinyMLP(features=(16,))
    params = model.init(
        jax.random.PRNGKey(0), jnp.zeros((1, 4, 4, 1))
    )["params"]

    def loss_fn(p, b, r):
        logits = model.apply({"params": p}, b["image"])
        return cross_entropy_loss(logits, b["label"]), {}

    state = ddp.TrainState.create(
        apply_fn=model.apply, params=params, tx=optax.sgd(0.1)
    )
    state = ddp.broadcast_params(state, mesh)
    # donate=False: the test reuses `state` across acquisition modes.
    step = ddp.make_train_step(loss_fn, mesh=mesh, donate=False)

    rng = np.random.default_rng(0)
    batch = shard_batch(
        {
            "image": rng.normal(size=(16, 4, 4, 1)).astype(np.float32),
            "label": rng.integers(0, 10, size=(16,)).astype(np.int32),
        },
        mesh,
    )
    return state, step, batch


def test_store_round_trip_smoke(devices, tmp_path):
    """Tier-1 smoke: compile -> save -> load through a fresh wrapper;
    the loaded executable must produce the cold path's step bitwise."""
    mesh = ddp.make_mesh(("data",))
    state, step, batch = _setup(mesh)
    store = ExecutableStore(str(tmp_path / "aot"))
    key = executable_key(
        mesh=mesh, step_signature=getattr(step, "aot_signature", None)
    )

    cold = warm_train_step(step, store=store, key=key)
    s1, m1 = cold(state, batch, jax.random.PRNGKey(1))
    assert cold.report["mode"] in ("cold", "cache-hit")
    meta = store.meta("train_step")
    assert meta is not None and meta["key"] == key
    assert "loss" in meta["metric_keys"]

    warm = warm_train_step(step, store=store, key=key)
    s2, m2 = warm(state, batch, jax.random.PRNGKey(1))
    assert warm.report["mode"] == "aot"
    assert float(m2["loss"]) == float(m1["loss"])
    for a, b in zip(jax.tree.leaves(s1.params), jax.tree.leaves(s2.params)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_key_mismatch_falls_back_loudly(devices, tmp_path):
    """A stored executable whose key differs from the live run must not
    load: warning naming the differing fields + None (strict: raise)."""
    mesh = ddp.make_mesh(("data",))
    state, step, batch = _setup(mesh)
    store = ExecutableStore(str(tmp_path / "aot"))
    key = executable_key(
        mesh=mesh, step_signature=getattr(step, "aot_signature", None),
        extra={"lr": 0.1},
    )
    first = warm_train_step(step, store=store, key=key)
    first(state, batch, jax.random.PRNGKey(1))
    assert store.meta("train_step") is not None

    stale = executable_key(
        mesh=mesh, step_signature=getattr(step, "aot_signature", None),
        extra={"lr": 0.2},  # optax bakes hyperparams into the binary
    )
    args = (state, batch, jax.random.PRNGKey(1))
    with _capture_warnings() as messages:
        loaded = store.load(
            "train_step", stale, example_args=args, state=state
        )
    assert loaded is None
    assert any("key mismatch" in m and "extra" in m for m in messages)

    with pytest.raises(WarmStartMismatch, match="key mismatch"):
        store.load(
            "train_step", stale, example_args=args, state=state, strict=True
        )

    # The wrapper path: mismatch degrades to a working compile, loudly.
    with _capture_warnings() as messages:
        wrapped = warm_train_step(step, store=store, key=stale)
        _, m = wrapped(state, batch, jax.random.PRNGKey(1))
    assert wrapped.report["mode"] in ("cold", "cache-hit")
    assert float(m["loss"]) == float(m["loss"])  # finite step ran
    assert any("key mismatch" in m for m in messages)


def test_corrupt_artifact_falls_back_loudly(devices, tmp_path):
    """Truncated payload (killed writer, disk fault): load warns and
    returns None instead of raising into the train loop."""
    mesh = ddp.make_mesh(("data",))
    state, step, batch = _setup(mesh)
    store = ExecutableStore(str(tmp_path / "aot"))
    key = executable_key(mesh=mesh)
    warm_train_step(step, store=store, key=key)(
        state, batch, jax.random.PRNGKey(1)
    )
    aot_path, _ = store._paths("train_step")
    with open(aot_path, "wb") as fh:
        fh.write(b"not a pickled executable")
    with _capture_warnings() as messages:
        loaded = store.load(
            "train_step", key,
            example_args=(state, batch, jax.random.PRNGKey(1)), state=state,
        )
    assert loaded is None
    assert any("failed to load" in m for m in messages)


def _cache_probe_worker(cache_dir, out_path):
    """Spawn child: compile one jit function with the persistent cache
    rooted at ``cache_dir`` and record the hit/miss event counts."""
    import json

    from distributeddataparallel_tpu.compat import configure_cpu_devices

    configure_cpu_devices(2)

    import jax
    import jax.numpy as jnp

    from distributeddataparallel_tpu.training.warm_start import (
        CompileCacheStats,
        enable_compile_cache,
    )

    enable_compile_cache(cache_dir)
    stats = CompileCacheStats()

    @jax.jit
    def f(x):
        return jnp.tanh(x) @ x + jnp.sum(x, axis=0)

    jax.block_until_ready(f(jnp.arange(64.0).reshape(8, 8)))
    stats.close()
    with open(out_path, "w") as fh:
        json.dump({"hits": stats.hits, "misses": stats.misses}, fh)


def test_compile_cache_hit_across_processes(tmp_path):
    """Two REAL processes, same cache dir: the first compiles (miss),
    the second must hit — the event counters make this deterministic
    instead of a timing assertion."""
    import json

    cache = str(tmp_path / "cache")
    ctx = mp.get_context("spawn")
    results = []
    for run in range(2):
        out = tmp_path / f"probe{run}.json"
        p = ctx.Process(
            target=_cache_probe_worker, args=(cache, str(out))
        )
        p.start()
        p.join(timeout=240)
        if p.is_alive():
            p.terminate()
            p.join()
            pytest.fail(f"cache probe child {run} timed out")
        assert p.exitcode == 0, f"child {run} exit {p.exitcode}"
        results.append(json.load(open(out)))
    assert results[0]["misses"] >= 1 and results[0]["hits"] == 0, results
    assert results[1]["hits"] >= 1, results


def test_bounded_dispatch_window_semantics():
    d = BoundedDispatch(2)
    assert d.push("a", 0) == []
    assert d.push("b", 1) == []
    assert d.push("c", 2) == [("a", 0)]  # oldest falls out of the window
    assert len(d) == 2
    assert d.drain() == [("b", 1), ("c", 2)]
    assert len(d) == 0
    # depth 0 degenerates to the synchronous per-step pattern.
    sync = BoundedDispatch(0)
    assert sync.push("a", 0) == [("a", 0)]
    with pytest.raises(ValueError, match="depth"):
        BoundedDispatch(-1)


def _final_checkpoint(ckpt_dir):
    import orbax.checkpoint as ocp

    mgr = ocp.CheckpointManager(ckpt_dir)
    step = mgr.latest_step()
    assert step is not None, "no checkpoint written"
    # Template-free raw read: both runs' trees get the same treatment,
    # so a bitwise compare needs no TrainState reconstruction.
    tree = mgr.restore(step, args=ocp.args.StandardRestore())
    mgr.close()
    return step, tree


def test_async_dispatch_bitwise_matches_blocking_loop(devices, tmp_path):
    """--dispatch-depth 4 vs 0 on a fixed seed: same final loss AND
    bitwise-identical final checkpointed state — the dispatch window
    reorders host syncs, never the computation."""

    def run(depth):
        d = str(tmp_path / f"ckpt_depth{depth}")
        args = dpp.parse_args(
            ["--device", "cpu", "--dataset", "synthetic", "--model", "mlp",
             "--num-examples", "64", "--batch-size", "8", "--epochs", "2",
             "--log-every", "3", "--seed", "3",
             "--dispatch-depth", str(depth), "--checkpoint-dir", d]
        )
        loss = dpp.train(args)
        return loss, _final_checkpoint(d)

    loss0, (step0, tree0) = run(0)
    loss4, (step4, tree4) = run(4)
    assert loss0 == loss4  # bitwise: both are float(np.float32)
    assert step0 == step4
    l0, l4 = jax.tree.leaves(tree0), jax.tree.leaves(tree4)
    assert len(l0) == len(l4) and len(l0) > 0
    for a, b in zip(l0, l4):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_nan_guard_trips_under_deep_dispatch(devices):
    """With a K-deep dispatch window the breaker observes each step's
    flag at most K steps late — a sustained NaN burst must still abort
    within max_bad_steps + K steps instead of training through it."""
    from distributeddataparallel_tpu.training.fault_tolerance import (
        TrainingDiverged,
    )

    # 512 examples / (4 x 8-device) global batch = 16 steps: the burst
    # at steps 2-6 settles mid-loop (step S leaves the 4-deep window at
    # step S+4), tripping the breaker before the epoch-edge drain.
    args = dpp.parse_args(
        ["--device", "cpu", "--dataset", "synthetic", "--model", "mlp",
         "--num-examples", "512", "--batch-size", "4", "--epochs", "1",
         "--log-every", "1000", "--nan-guard", "--max-bad-steps", "3",
         "--dispatch-depth", "4",
         "--chaos",
         "nan-grad@2,nan-grad@3,nan-grad@4,nan-grad@5,nan-grad@6"]
    )
    with pytest.raises(TrainingDiverged, match="3 consecutive"):
        dpp.train(args)


def test_nan_guard_survives_isolated_nan_under_dispatch(devices):
    """One poisoned step inside the dispatch window is skipped in-graph;
    the run finishes finite exactly like the blocking loop's guard."""
    args = dpp.parse_args(
        ["--device", "cpu", "--dataset", "synthetic", "--model", "mlp",
         "--num-examples", "128", "--batch-size", "4", "--epochs", "1",
         "--log-every", "1000", "--nan-guard", "--dispatch-depth", "4",
         "--chaos", "nan-grad@1"]
    )
    loss = dpp.train(args)
    assert loss == loss and loss < 2.4
