"""Observability tests: StepTimer windows, BW probe sanity, trace no-op."""

import jax
import jax.numpy as jnp
import numpy as np

import distributeddataparallel_tpu as ddp
from distributeddataparallel_tpu.utils import (
    StepTimer,
    allreduce_bandwidth,
    profile_trace,
)


def test_step_timer_windows():
    t = StepTimer(window=3, n_chips=4)
    # First tick is the compile step: timed separately, never a reading.
    assert t.tick(8) is None
    assert t.compile_s is not None and t.compile_s >= 0
    assert t.tick(8) is None
    assert t.tick(8) is None
    r = t.tick(8)
    assert r is not None and not r["warmup"]
    assert r["items_per_s"] > 0
    assert abs(r["items_per_s_per_chip"] - r["items_per_s"] / 4) < 1e-6
    # compile_s rides along exactly once, on the first reading.
    assert r["compile_s"] == round(t.compile_s, 3)
    for _ in range(2):
        assert t.tick(8) is None
    r2 = t.tick(8)
    assert r2 is not None and not r2["warmup"]
    assert "compile_s" not in r2


def test_allreduce_bandwidth_probe(devices):
    mesh = ddp.make_mesh(("data",))
    r = allreduce_bandwidth(mesh, size_mb=1.0, iters=2)
    assert r["devices"] == 8
    assert r["bus_bw_gb_s"] > 0
    assert 0 <= r["utilization"]
    assert r["payload_mb"] == 1.0


def test_profile_trace_noop(tmp_path):
    with profile_trace(None):
        pass  # no-op path must not start the profiler
    x = jnp.ones((8,))
    with profile_trace(str(tmp_path / "trace"), sync=x):
        jax.block_until_ready(x * 2)
    assert any((tmp_path / "trace").rglob("*")), "trace not written"


def test_overlap_probe(devices):
    """The comm/compute overlap probe: all three timings positive, comm
    measured over a real 8-way axis, overlap fraction bounded."""
    import optax

    import distributeddataparallel_tpu as ddp
    from distributeddataparallel_tpu.data.loader import shard_batch
    from distributeddataparallel_tpu.models import TinyMLP
    from distributeddataparallel_tpu.ops import cross_entropy_loss
    from distributeddataparallel_tpu.utils import overlap_probe

    mesh = ddp.make_mesh(("data",))
    model = TinyMLP(features=(32,))
    params = model.init(jax.random.PRNGKey(0), jnp.zeros((1, 8, 8, 1)))["params"]

    def loss_fn(p, batch, rng):
        return cross_entropy_loss(
            model.apply({"params": p}, batch["image"]), batch["label"]
        ), {}

    state = ddp.TrainState.create(
        apply_fn=model.apply, params=params, tx=optax.sgd(0.1)
    )
    state = ddp.broadcast_params(state, mesh)
    rng = np.random.default_rng(0)
    batch = shard_batch(
        {
            "image": rng.normal(size=(16, 8, 8, 1)).astype(np.float32),
            "label": rng.integers(0, 10, size=(16,)).astype(np.int32),
        },
        mesh,
    )
    probe = overlap_probe(
        loss_fn, state, batch, jax.random.PRNGKey(1), mesh=mesh, iters=3
    )
    assert probe["devices"] == 8
    assert probe["step_ms"] > 0 and probe["compute_ms"] > 0
    assert probe["comm_ms"] > 0
    assert probe["grad_mb"] > 0
    assert probe["overlap_frac"] is None or 0.0 <= probe["overlap_frac"] <= 1.0


def test_grad_sync_false_skips_the_allreduce(devices):
    """grad_sync=False (the DDP.no_sync analog) must leave per-replica
    grads unaveraged: with different shards per replica, params diverge
    from the synced step's result."""
    import optax

    import distributeddataparallel_tpu as ddp
    from distributeddataparallel_tpu.data.loader import shard_batch
    from distributeddataparallel_tpu.models import TinyMLP
    from distributeddataparallel_tpu.ops import cross_entropy_loss

    mesh = ddp.make_mesh(("data",))
    model = TinyMLP(features=(16,))
    params = model.init(jax.random.PRNGKey(0), jnp.zeros((1, 4, 4, 1)))["params"]

    def loss_fn(p, batch, rng):
        return cross_entropy_loss(
            model.apply({"params": p}, batch["image"]), batch["label"]
        ), {}

    rng = np.random.default_rng(1)
    batch = shard_batch(
        {
            "image": rng.normal(size=(16, 4, 4, 1)).astype(np.float32),
            "label": rng.integers(0, 10, size=(16,)).astype(np.int32),
        },
        mesh,
    )

    def run(grad_sync):
        state = ddp.TrainState.create(
            apply_fn=model.apply, params=params, tx=optax.sgd(0.1)
        )
        state = ddp.broadcast_params(state, mesh)
        step = ddp.make_train_step(
            loss_fn, mesh=mesh, donate=False, grad_sync=grad_sync
        )
        state, _ = step(state, batch, jax.random.PRNGKey(0))
        return state.params

    synced = run(True)
    local = run(False)
    diffs = [
        float(np.abs(np.asarray(a) - np.asarray(b)).max())
        for a, b in zip(jax.tree.leaves(synced), jax.tree.leaves(local))
    ]
    assert max(diffs) > 1e-6, "no_sync step unexpectedly matched synced step"
