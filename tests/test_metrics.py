"""Observability tests: StepTimer windows, BW probe sanity, trace no-op."""

import jax
import jax.numpy as jnp

import distributeddataparallel_tpu as ddp
from distributeddataparallel_tpu.utils import (
    StepTimer,
    allreduce_bandwidth,
    profile_trace,
)


def test_step_timer_windows():
    t = StepTimer(window=3, n_chips=4)
    assert t.tick(8) is None
    assert t.tick(8) is None
    r = t.tick(8)
    assert r is not None and r["warmup"]
    assert r["items_per_s"] > 0
    assert abs(r["items_per_s_per_chip"] - r["items_per_s"] / 4) < 1e-6
    for _ in range(2):
        assert t.tick(8) is None
    r2 = t.tick(8)
    assert r2 is not None and not r2["warmup"]


def test_allreduce_bandwidth_probe(devices):
    mesh = ddp.make_mesh(("data",))
    r = allreduce_bandwidth(mesh, size_mb=1.0, iters=2)
    assert r["devices"] == 8
    assert r["bus_bw_gb_s"] > 0
    assert 0 <= r["utilization"]
    assert r["payload_mb"] == 1.0


def test_profile_trace_noop(tmp_path):
    with profile_trace(None):
        pass  # no-op path must not start the profiler
    x = jnp.ones((8,))
    with profile_trace(str(tmp_path / "trace"), sync=x):
        jax.block_until_ready(x * 2)
    assert any((tmp_path / "trace").rglob("*")), "trace not written"
