"""Observability subsystem: span nesting + the no-sync hot-path rule,
JSONL schema round-trip and validation, metrics registry + exporters,
gang-timeline merge ordering, capture-on-anomaly, and the acceptance
path — a supervised chaos run whose merged timeline shows injection,
skip-step, and restart attempt in causal order."""

import json
import logging as pylogging
import os
import sys
import time

import jax
import jax.numpy as jnp
import numpy as np
import pytest

sys.path.insert(0, "/root/repo")

import dpp  # noqa: E402
from distributeddataparallel_tpu.observability import (  # noqa: E402
    SCHEMA_VERSION,
    EventLog,
    JsonlExporter,
    MetricsRegistry,
    ProfilerOrchestrator,
    TextExporter,
    Tracer,
    events_path,
    json_safe,
    merge_timeline,
    parse_profile_steps,
    read_events,
    validate_file,
    validate_record,
)
from distributeddataparallel_tpu.runtime.launcher import spawn  # noqa: E402
from distributeddataparallel_tpu.utils import logging as ddp_logging  # noqa: E402
from distributeddataparallel_tpu.utils.metrics import FaultCounters  # noqa: E402

sys.path.insert(0, os.path.join("/root/repo", "scripts"))
import check_events  # noqa: E402


# ------------------------------------------------------- schema basics


def test_json_safe_coercion():
    out = json_safe({
        "nan": float("nan"),
        "inf": float("inf"),
        "ninf": float("-inf"),
        "np_f": np.float32(1.5),
        "np_i": np.int64(7),
        "np_0d": np.array(2.25),
        "np_bool": np.bool_(True),
        "bool": True,
        "tup": (1, 2.0, "x"),
        "nested": {"a": [np.float64("nan")]},
    })
    text = json.dumps(out)  # must not raise
    back = json.loads(text)
    assert back["nan"] == "nan" and back["inf"] == "inf"
    assert back["ninf"] == "-inf"
    assert back["np_f"] == 1.5 and back["np_i"] == 7
    assert back["np_0d"] == 2.25
    assert back["np_bool"] is True and back["bool"] is True
    assert back["tup"] == [1, 2.0, "x"]
    assert back["nested"]["a"] == ["nan"]


def test_fault_counters_summary_json_safe():
    """Satellite regression: warm-start timing can land as a numpy
    scalar or nan; summary() must stay serializable for the event log."""
    c = FaultCounters()
    c.warm_start_mode = "aot"
    c.compile_s = np.float32("nan")
    s = c.summary()
    text = json.dumps(s)  # the event log does exactly this
    assert json.loads(text)["first_step_s"] == "nan"
    c.compile_s = np.float64(1.23456)
    assert json.loads(json.dumps(c.summary()))["first_step_s"] == 1.235


def test_event_log_roundtrip_schema_version(tmp_path):
    path = str(tmp_path / "events-p0.jsonl")
    with EventLog(path, 0) as ev:
        ev.emit("run_start", argv=["--x"])
        ev.emit("nan_skip", step=3, extra=np.float32(0.5))
        ev.emit("run_end", status="ok")
    recs = read_events(path)
    assert [r["kind"] for r in recs] == ["run_start", "nan_skip", "run_end"]
    assert all(r["v"] == SCHEMA_VERSION for r in recs)
    assert [r["seq"] for r in recs] == [0, 1, 2]  # per-writer monotonic
    assert recs[1]["extra"] == 0.5  # json_safe applied at emit
    assert validate_file(path) == []


def test_event_log_append_survives_restart(tmp_path):
    """A respawned incarnation reuses the same path: records append
    rather than erase the previous incarnation's history."""
    path = str(tmp_path / "events-p0.jsonl")
    with EventLog(path, 0) as ev:
        ev.emit("run_start", argv=[])
    with EventLog(path, 0) as ev:
        ev.emit("run_start", argv=[])
    assert len(read_events(path)) == 2


def test_validator_rejects_bad_records(tmp_path):
    assert validate_record({"v": 1}) != []  # missing envelope fields
    assert any(
        "version" in p
        for p in validate_record(
            {"v": 99, "ts": 0.0, "seq": 0, "proc": 0, "kind": "run_end",
             "status": "ok"}
        )
    )
    assert any(
        "unknown kind" in p
        for p in validate_record(
            {"v": 1, "ts": 0.0, "seq": 0, "proc": 0, "kind": "nope"}
        )
    )
    assert any(
        "missing required" in p
        for p in validate_record(
            {"v": 1, "ts": 0.0, "seq": 0, "proc": 0, "kind": "span"}
        )
    )
    bad = tmp_path / "bad.jsonl"
    bad.write_text('{"v": 1}\nnot json\n')
    assert check_events.main([str(bad)]) == 1
    good = tmp_path / "good.jsonl"
    with EventLog(str(good), 0) as ev:
        ev.emit("run_end", status="ok")
    assert check_events.main([str(good)]) == 0
    # --expect-order: present vs violated
    with EventLog(str(good), 0) as ev:
        ev.emit("run_start", argv=[])
    assert check_events.main(
        [str(good), "--expect-order", "run_end,run_start"]
    ) == 0
    assert check_events.main(
        [str(good), "--expect-order", "run_start,run_end"]
    ) == 1


# ----------------------------------------------------- tracer / spans


def test_span_nesting_depth_and_parent(tmp_path):
    path = str(tmp_path / "events-p0.jsonl")
    with EventLog(path, 0) as ev:
        tr = Tracer(ev)
        with tr.span("epoch", epoch=0):
            with tr.span("step", step=0):
                pass
            with tr.span("ckpt_save", epoch=0):
                pass
    spans = {r["name"]: r for r in read_events(path)}
    assert spans["step"]["depth"] == 1 and spans["step"]["parent"] == "epoch"
    assert spans["ckpt_save"]["parent"] == "epoch"
    assert spans["epoch"]["depth"] == 0 and spans["epoch"]["parent"] is None
    # children closed before the parent -> parent duration covers them
    assert spans["epoch"]["dur_s"] >= spans["step"]["dur_s"]
    assert validate_file(path) == []


def test_hot_path_never_syncs(tmp_path, monkeypatch, devices):
    """The no-sync rule, enforced: emitting spans, events, and metrics
    snapshots with an ASYNC jax computation in flight must not call
    block_until_ready (nor read a device value any other way)."""
    calls = {"n": 0}
    real = jax.block_until_ready

    def counting(x):
        calls["n"] += 1
        return real(x)

    monkeypatch.setattr(jax, "block_until_ready", counting)
    f = jax.jit(lambda x: (x * 2.0).sum())
    path = str(tmp_path / "events-p0.jsonl")
    with EventLog(path, 0) as ev:
        reg = MetricsRegistry()
        reg.add_exporter(JsonlExporter(ev))
        reg.bind("gauge", lambda: 1.25)
        tr = Tracer(ev, reg)
        out = None
        for i in range(5):
            with tr.span("step", step=i):
                out = f(jnp.ones((256,)) * i)  # dispatched, NOT read
            ev.emit("nan_skip", step=i)
            reg.export(step=i)
    assert calls["n"] == 0, "observability hot path forced a device sync"
    real(out)  # drain before leaving the test
    assert validate_file(path) == []


# -------------------------------------------------- metrics registry


def test_registry_instruments_and_snapshot():
    reg = MetricsRegistry()
    reg.counter("faults").inc()
    reg.counter("faults").inc(2)
    reg.gauge("depth").set(3)
    reg.bind("lazy", lambda: 7)
    h = reg.histogram("step_s")
    for v in (0.1, 0.2, 0.3):
        h.observe(v)
    snap = reg.snapshot()
    assert snap["faults"] == 3
    assert snap["depth"] == 3 and snap["lazy"] == 7
    assert snap["step_s"]["count"] == 3
    assert abs(snap["step_s"]["mean"] - 0.2) < 1e-9
    assert snap["step_s"]["min"] == 0.1 and snap["step_s"]["max"] == 0.3
    with pytest.raises(TypeError):
        reg.gauge("faults")  # name already taken by a Counter


def test_registry_exporters(tmp_path):
    path = str(tmp_path / "events-p0.jsonl")
    txt = str(tmp_path / "metrics.txt")
    with EventLog(path, 0) as ev:
        reg = MetricsRegistry()
        reg.add_exporter(JsonlExporter(ev))
        reg.add_exporter(TextExporter(txt))
        reg.counter("nan_skips").inc(4)
        reg.histogram("span_step_s").observe(0.5)
        snap = reg.export(step=10)
    assert snap["nan_skips"] == 4
    recs = read_events(path)
    assert recs[0]["kind"] == "metrics" and recs[0]["step"] == 10
    assert recs[0]["snapshot"]["nan_skips"] == 4
    content = open(txt).read()
    assert "nan_skips 4" in content
    assert "span_step_s_count 1" in content  # dict metrics flattened
    assert validate_file(path) == []


# -------------------------------------------------- timeline merging


def test_merge_timeline_ordering(tmp_path):
    """Records from 3 writers interleave strictly by (ts, seq) in the
    merged gang timeline, whatever order the files listed in."""
    t0 = time.time()
    for proc, offsets in ((0, (0.0, 0.2)), (1, (0.1, 0.3)), (2, (0.05,))):
        with EventLog(events_path(str(tmp_path), proc), proc) as ev:
            for off in offsets:
                ev.emit("nan_skip", step=int(off * 100))
        # Rewrite with controlled timestamps (emit stamps real time).
        recs = read_events(events_path(str(tmp_path), proc))
        for r, off in zip(recs, offsets):
            r["ts"] = t0 + off
        with open(events_path(str(tmp_path), proc), "w") as fh:
            for r in recs:
                fh.write(json.dumps(r) + "\n")
    out = merge_timeline(str(tmp_path))
    assert out and out.endswith("timeline.jsonl")
    merged = read_events(out)
    assert [r["proc"] for r in merged] == [0, 2, 1, 0, 1]
    assert [r["ts"] for r in merged] == sorted(r["ts"] for r in merged)
    assert validate_file(out) == []
    # Torn trailing line (SIGKILLed writer) is dropped, not fatal.
    with open(events_path(str(tmp_path), 0), "a") as fh:
        fh.write('{"v": 1, "ts":')
    assert len(read_events(merge_timeline(str(tmp_path)))) == 5


def test_merge_timeline_empty_dir(tmp_path):
    assert merge_timeline(str(tmp_path)) is None


# ------------------------------------------------ profiler orchestration


def test_parse_profile_steps():
    assert parse_profile_steps(None) is None
    assert parse_profile_steps("") is None
    assert parse_profile_steps("10:20") == (10, 20)
    for bad in ("10", "20:10", "5:5", "-1:3", "a:b"):
        with pytest.raises(ValueError):
            parse_profile_steps(bad)


def test_profiler_window_capture(tmp_path, devices):
    path = str(tmp_path / "events-p0.jsonl")
    with EventLog(path, 0) as ev:
        prof = ProfilerOrchestrator(
            str(tmp_path / "xprof"), window=(1, 3), events=ev
        )
        x = jnp.ones((64,))
        for i in range(5):
            prof.on_step_start(i)
            x = x * 1.5
            prof.on_step_end(i, sync=x)
        assert not prof.active
        prof.close()
    kinds = [(r["kind"], r.get("step")) for r in read_events(path)]
    assert ("profile_start", 1) in kinds and ("profile_stop", 2) in kinds
    assert os.path.isdir(str(tmp_path / "xprof"))


def test_profiler_anomaly_is_first_only(tmp_path, devices):
    path = str(tmp_path / "events-p0.jsonl")
    with EventLog(path, 0) as ev:
        prof = ProfilerOrchestrator(str(tmp_path / "xprof"), events=ev)
        prof.trigger_anomaly("nan_grad", 7, immediate=True)
        prof.trigger_anomaly("nan_grad", 9, immediate=True)  # ignored
        prof.close()
    starts = [r for r in read_events(path) if r["kind"] == "profile_start"]
    assert len(starts) == 1
    assert starts[0]["reason"] == "anomaly:nan_grad"
    assert starts[0]["step"] == 7


def test_disabled_profiler_is_inert():
    prof = ProfilerOrchestrator(None, window=(0, 2))
    for i in range(3):
        prof.on_step_start(i)
        prof.on_step_end(i)
    prof.trigger_anomaly("nan_grad", 0)
    prof.close()
    assert not prof.active


# ------------------------------------------------------- loader gauge


def test_loader_prefetch_depth_and_starvation(devices, monkeypatch):
    from distributeddataparallel_tpu.data import DataLoader
    from distributeddataparallel_tpu.runtime.distributed import make_mesh

    class SlowDataset:
        def __init__(self, n):
            self.images = np.zeros((n, 4), np.float32)
            self.labels = np.zeros((n,), np.int64)

        def __len__(self):
            return len(self.images)

        def arrays(self):
            time.sleep(0.02)  # slow producer: consumer always outruns it
            return {"image": self.images, "label": self.labels}

    warned = []
    monkeypatch.setattr(
        ddp_logging, "warn_all", lambda msg, *a: warned.append(msg % a)
    )
    mesh = make_mesh(("data",))
    loader = DataLoader(
        SlowDataset(64), per_replica_batch=1, mesh=mesh, shuffle=False,
        workers=1, starvation_window=2,
    )
    assert loader.prefetch_depth == 0  # no iteration active
    depths = []
    for _ in loader:
        depths.append(loader.prefetch_depth)
    assert all(isinstance(d, int) and d >= 0 for d in depths)
    assert loader.prefetch_depth == 0  # reset after the epoch
    assert len(warned) == 1, warned  # one-time, not per-step
    assert "starving" in warned[0]


# ------------------------------------------------- logging satellites


def test_log_level_env_and_debug0(monkeypatch):
    monkeypatch.setenv("DDP_LOG_LEVEL", "DEBUG")
    monkeypatch.setattr(ddp_logging, "_LOGGER", None)
    logger = ddp_logging.get_logger()
    assert logger.level == pylogging.DEBUG
    ddp_logging.debug0("debug message %d", 1)  # must not raise
    monkeypatch.setenv("DDP_LOG_LEVEL", "nonsense")
    monkeypatch.setattr(ddp_logging, "_LOGGER", None)
    assert ddp_logging.get_logger().level == pylogging.INFO  # safe fallback
    monkeypatch.setenv("DDP_LOG_LEVEL", "15")
    monkeypatch.setattr(ddp_logging, "_LOGGER", None)
    assert ddp_logging.get_logger().level == 15
    monkeypatch.delenv("DDP_LOG_LEVEL")
    monkeypatch.setattr(ddp_logging, "_LOGGER", None)
    assert ddp_logging.get_logger().level == pylogging.INFO


def test_profile_trace_compat_reexport():
    from distributeddataparallel_tpu.observability.profiler import (
        profile_trace as canonical,
    )
    from distributeddataparallel_tpu.utils import profile_trace as via_pkg
    from distributeddataparallel_tpu.utils.metrics import (
        profile_trace as via_metrics,
    )

    assert via_metrics is canonical and via_pkg is canonical


# ------------------------------------------- end-to-end: train wiring


def test_train_events_and_capture_on_anomaly(devices, tmp_path):
    """In-process train with --events-dir: the event log carries the
    run envelope, spans, metrics snapshots, the chaos injection and the
    nan-guard skip, and the anomaly grabs an XLA trace."""
    ev_dir = str(tmp_path / "events")
    args = dpp.parse_args([
        "--device", "cpu", "--fake-devices", "8",
        "--model", "mlp", "--dataset", "synthetic",
        "--num-examples", "64", "--batch-size", "4",
        "--epochs", "1", "--steps-per-epoch", "3", "--log-every", "10",
        "--nan-guard", "--chaos", "nan-grad@1",
        "--events-dir", ev_dir, "--metrics-every", "1",
    ])
    dpp.train(args)
    recs = read_events(events_path(ev_dir, 0))
    kinds = [r["kind"] for r in recs]
    assert kinds[0] == "run_start" and kinds[-1] == "run_end"
    for want in ("span", "metrics", "chaos_inject", "nan_skip",
                 "warm_start", "profile_start"):
        assert want in kinds, (want, kinds)
    names = {r["name"] for r in recs if r["kind"] == "span"}
    assert {"epoch", "step"} <= names
    snaps = [r for r in recs if r["kind"] == "metrics"]
    assert any("faults" in s["snapshot"] for s in snaps)
    assert any(
        s["snapshot"].get("faults", {}).get("nonfinite_steps", 0) == 1
        for s in snaps
    ) or recs[-1]["faults"]["nonfinite_steps"] == 1
    assert validate_file(events_path(ev_dir, 0)) == []
    # Unsupervised single-process run merges its own timeline on exit.
    assert os.path.exists(os.path.join(ev_dir, "timeline.jsonl"))
    assert os.path.exists(os.path.join(ev_dir, "metrics.txt"))


def test_acceptance_chaos_timeline_causal_order(devices, tmp_path):
    """ISSUE acceptance: a supervised chaos run (nan injection + a
    preemption, --max-restarts 1) produces a merged gang timeline with
    injection -> skip-step -> restart attempt in causal order, and
    scripts/check_events.py validates it."""
    ev_dir = str(tmp_path / "events")
    ck = str(tmp_path / "ck")
    base = [
        "--device", "cpu", "--fake-devices", "8",
        "--model", "mlp", "--dataset", "synthetic",
        "--num-examples", "128", "--batch-size", "4",
        "--epochs", "3", "--steps-per-epoch", "4", "--log-every", "1",
        "--nan-guard",
        "--checkpoint-dir", ck, "--resume",
    ]
    spawn(
        dpp._worker,
        args=(base,),
        nprocs=1,
        max_restarts=1,
        env={
            "_DDP_SUPERVISED": "1",
            # nan-grad@2: epoch 0 -> chaos_inject + nan_skip.
            # preempt@6 (epoch 1, batch 2): dies AFTER epoch 0's
            # checkpoint -> supervisor logs restart_attempt.
            "DDP_CHAOS": "nan-grad@2,preempt@6",
            "DDP_CHAOS_STATE": os.path.join(ck, ".chaos"),
        },
        events_dir=ev_dir,
    )
    timeline = os.path.join(ev_dir, "timeline.jsonl")
    assert os.path.exists(timeline)
    # Schema-valid AND the causal chain is in order.
    assert check_events.main([
        timeline,
        "--expect-order", "chaos_inject,nan_skip,restart_attempt,run_end",
    ]) == 0
    recs = read_events(timeline)
    by_kind = {}
    for r in recs:
        by_kind.setdefault(r["kind"], []).append(r)
    # Both incarnations wrote run_start into the SAME per-proc file.
    assert len(by_kind["run_start"]) == 2
    assert by_kind["run_start"][1]["attempt"] == 1
    assert by_kind["restart_attempt"][0]["proc"] == "supervisor"
    # The injected preemption is on the timeline before the restart.
    inj = [r for r in by_kind["chaos_inject"] if "preempt" in r["entry"]]
    assert inj and inj[0]["ts"] <= by_kind["restart_attempt"][0]["ts"]


# ------------------------------------- satellite: dead-gang exit merge


def test_supervisor_merge_tolerates_gang_dead_before_events(
    devices, tmp_path,
):
    """A gang that dies before ANY worker writes events (here: argv that
    fails validation in parse_args) must still surface the restart-
    exhausted RuntimeError, and the exit-time merge must produce a
    supervisor-only timeline instead of crashing."""
    ev_dir = str(tmp_path / "events")
    # --mfu has no resnet cost model: SystemExit in parse_args, before
    # the worker ever opens its events file.
    bad = ["--device", "cpu", "--fake-devices", "8",
           "--model", "resnet18", "--mfu"]
    with pytest.raises(RuntimeError, match="restart budget"):
        spawn(
            dpp._worker, args=(bad,), nprocs=1, max_restarts=1,
            restart_backoff_s=0.05,
            env={"_DDP_SUPERVISED": "1"}, events_dir=ev_dir,
        )
    assert not os.path.exists(events_path(ev_dir, 0))
    timeline = os.path.join(ev_dir, "timeline.jsonl")
    assert os.path.exists(timeline)
    recs = read_events(timeline)
    assert recs and all(r["proc"] == "supervisor" for r in recs)
    assert {"restart_attempt", "restart_exhausted"} <= {
        r["kind"] for r in recs
    }


def test_supervisor_merge_failure_does_not_mask_run_error(
    devices, tmp_path, monkeypatch,
):
    """If the exit-time merge itself fails (unwritable dir, disk full),
    the run's real exception must still be the one that propagates."""
    from distributeddataparallel_tpu.runtime import launcher as launcher_mod
    from distributeddataparallel_tpu.observability import events as ev_mod

    def broken_merge(events_dir, out_name="timeline.jsonl"):
        raise OSError("disk full")

    monkeypatch.setattr(ev_mod, "merge_timeline", broken_merge)
    ev_dir = str(tmp_path / "events")
    bad = ["--device", "cpu", "--fake-devices", "8",
           "--model", "resnet18", "--mfu"]
    with pytest.raises(RuntimeError, match="restart budget"):
        launcher_mod.spawn(
            dpp._worker, args=(bad,), nprocs=1, max_restarts=1,
            restart_backoff_s=0.05,
            env={"_DDP_SUPERVISED": "1"}, events_dir=ev_dir,
        )
