"""Disaggregated serving fleet tests: handoff, router, end-to-end.

The load-bearing contracts:

- a prefill→decode KV-block handoff is INVISIBLE to outputs: a fleet
  (1 prefill + 2 decode engines behind the session-affinity router)
  generates bitwise the tokens a single monolithic engine would, for
  block-exact and mid-block prompts, the unrolled and scanned layer
  layouts, int8 KV, and the prefix-cache + speculative fast path —
  with ``BlockAllocator.check()`` holding every scheduler step on
  every engine of both tiers;
- a corrupted handoff frame is a RETRY, never silent divergence: the
  per-block digest NAKs exactly the bad blocks, the sender re-ships
  only those, and the decoded stream stays bitwise correct; a link
  that corrupts every attempt exhausts the redelivery budget and
  raises instead of injecting garbage;
- the router spreads fresh requests least-outstanding-tokens, pins
  multi-turn sessions to the decode engine holding their prefix
  blocks (skipping the prefill tier on a hit), walks the heartbeat
  hysteresis ladder (``gang_suspect`` → tombstone) on an injected
  clock, and records ``engine_verdict`` rungs (``drain`` with tier
  survivors, ``fail`` without) exactly like PR 16's ``gang_verdict``;
- killing a decode engine mid-run drains-and-requeues every
  outstanding request onto the survivor: zero dropped;
- a fleet run under a ``VirtualClock`` is a pure function of
  (seed, config) — replayed, it produces identical tokens and
  identical route/handoff counters;
- multi-turn loadgen traces extend each session's prompt strictly
  (turn t is a prefix of turn t+1) from an rng independent of the
  base draws, so ``turns=1`` traces stay bitwise pinned;
- perf_gate infers the fleet headline directions (speedup higher,
  latency lower) and hard-fails any nonzero ``dropped_*_total`` even
  against an equally lossy baseline, unless ``--allow-drops``;
- the fleet event kinds export to Perfetto: ``kv_handoff`` doubles as
  the ``handoff_bytes`` counter track and ``route_admit`` as the
  ``router_queue`` depth track.
"""

import json
import os
import sys

import jax
import jax.numpy as jnp
import numpy as np
import pytest

sys.path.insert(0, "/root/repo")
sys.path.insert(0, os.path.join("/root/repo", "scripts"))

from distributeddataparallel_tpu.models import TransformerLM, tiny_lm
from distributeddataparallel_tpu.serving import (
    EngineConfig,
    FleetConfig,
    HandoffError,
    HandoffReceiver,
    HandoffSender,
    InferenceEngine,
    LoadConfig,
    PipeChannel,
    Router,
    RouterError,
    ServingFleet,
    VirtualClock,
    block_nbytes,
    make_trace,
    run_load,
)
from distributeddataparallel_tpu.serving.handoff import MAX_ATTEMPTS


def _unrolled(**over):
    base = dict(
        vocab_size=97, num_layers=2, num_heads=2, d_model=32, d_ff=64,
        max_seq_len=64, positional="learned", norm="layernorm",
        activation="gelu", tie_embeddings=True,
    )
    base.update(over)
    return tiny_lm(**base)


def _scanned(**over):
    base = dict(
        vocab_size=97, num_layers=2, num_heads=4, num_kv_heads=2,
        d_model=32, d_ff=64, max_seq_len=64, scan_layers=True,
        tie_embeddings=False,
    )
    base.update(over)
    return tiny_lm(**base)


def _model(cfg_fn):
    cfg = cfg_fn()
    model = TransformerLM(cfg)
    params = model.init(
        jax.random.PRNGKey(0), jnp.zeros((1, 4), jnp.int32)
    )["params"]
    return cfg, model, params


def _ecfg(**over):
    base = dict(
        num_slots=4, num_blocks=48, block_size=8, prefill_chunk=8
    )
    base.update(over)
    return EngineConfig(**base)


#: prompt lengths that cross the interesting boundaries at block_size
#: 8: one exactly block-aligned (16), one mid-block (13), one longer
#: multi-block (21)
_PROMPT_LENS = (16, 13, 21)


def _prompts(vocab, lens=_PROMPT_LENS, seed=0):
    rng = np.random.default_rng(seed)
    return [rng.integers(1, vocab, size=n).tolist() for n in lens]


def _ref_outputs(model, params, ecfg, prompts, n_new):
    """What a single monolithic engine generates for ``prompts``."""
    eng = InferenceEngine(model, params, ecfg, time_fn=VirtualClock())
    rids = [eng.submit(p, n_new) for p in prompts]
    while eng.has_work():
        eng.step()
    return [list(eng.completed[r].generated) for r in rids]


def _drive(fleet, clock, max_steps=800):
    steps = 0
    while fleet.has_work():
        fleet.step()
        clock.tick()
        steps += 1
        assert steps < max_steps, "fleet failed to drain"
    return steps


def _fleet_case(cfg_fn, n_new=10, **ecfg_over):
    """Run the 3-prompt parity scenario on a 1:2 fleet and return
    (fleet, outputs, reference outputs)."""
    cfg, model, params = _model(cfg_fn)
    ecfg = _ecfg(**ecfg_over)
    clock = VirtualClock()
    fleet = ServingFleet(
        model, params, ecfg, FleetConfig(prefill=1, decode=2),
        time_fn=clock, check_invariants=True,
    )
    prompts = _prompts(cfg.vocab_size)
    fids = [fleet.submit(p, n_new) for p in prompts]
    _drive(fleet, clock)
    outs = [list(fleet.completed[f].generated) for f in fids]
    refs = _ref_outputs(model, params, ecfg, prompts, n_new)
    return fleet, outs, refs


# ------------------------------------------------------- handoff parity


def test_fleet_parity_plain_unrolled():
    fleet, outs, refs = _fleet_case(_unrolled)
    assert outs == refs
    s = fleet.summary()
    # every fresh prompt went prefill-tier → handoff → decode-tier
    assert s["handoffs"] == len(_PROMPT_LENS)
    assert s["dropped_req_total"] == 0 and s["re_handoff_blocks"] == 0


def test_fleet_parity_scanned():
    # exercises the (L, N, bs, H, D) pool layout end to end, including
    # the layer-major moveaxis in extract and the batched landing
    fleet, outs, refs = _fleet_case(_scanned)
    assert outs == refs
    assert fleet.summary()["handoffs"] == len(_PROMPT_LENS)


def test_fleet_parity_quantized_kv():
    # int8 KV ships q/scale leaves raw — never re-quantized in transit
    _, outs, refs = _fleet_case(_unrolled, quantized_kv=True)
    assert outs == refs


def test_fleet_parity_fastpath():
    # prefix cache + speculative decoding on the decode tier must not
    # change what a handed-off sequence generates
    _, outs, refs = _fleet_case(_unrolled, prefix_cache=True, spec_k=2)
    assert outs == refs


# -------------------------------------------- corruption & redelivery


class _FlipOnce:
    """Channel wrapper that flips one byte of the Nth send, once."""

    def __init__(self, chan, nth):
        self._chan = chan
        self._nth = nth
        self._sends = 0

    def send(self, frame):
        self._sends += 1
        if self._sends == self._nth:
            frame = bytearray(frame)
            frame[len(frame) // 2] ^= 0xFF
            frame = bytes(frame)
        self._chan.send(frame)

    def __getattr__(self, name):
        return getattr(self._chan, name)


def test_handoff_corrupted_block_redelivered():
    cfg, model, params = _model(_unrolled)
    ecfg = _ecfg()
    clock = VirtualClock()
    prefill = InferenceEngine(model, params, ecfg, time_fn=clock)
    decode = InferenceEngine(model, params, ecfg, time_fn=clock)
    prompt = _prompts(cfg.vocab_size, lens=(16,))[0]
    rid = prefill.submit(prompt, 1)
    while prefill.has_work():
        prefill.step()
    payload = prefill.extract_handoff(rid, max_new_tokens=8)
    assert all(len(b) == block_nbytes(prefill.pool) for b in payload.blocks)

    a, b = PipeChannel.pair()
    # frame 1 is the header; frame 2 is block 0 — corrupt it once
    sender = HandoffSender(_FlipOnce(a, 2), time_fn=clock)
    receiver = HandoffReceiver(b)
    sender.offer(payload)
    got = receiver.poll()          # digest mismatch on block 0 → NAK
    assert got == [] and receiver.rejected_blocks == 1
    done = sender.poll()           # consumes NAK, re-ships block 0
    assert done == [] and sender.redelivered_blocks == 1
    got = receiver.poll()
    assert len(got) == 1 and got[0].blocks == payload.blocks
    (rec,) = sender.poll()
    assert rec["attempts"] == 2 and sender.in_flight == 0

    # the redelivered payload still injects and decodes bitwise right
    new_rid = decode.inject_handoff(got[0])
    while decode.has_work():
        decode.step()
    ref = _ref_outputs(model, params, ecfg, [prompt], 8)[0]
    assert list(decode.completed[new_rid].generated) == ref


def test_handoff_gives_up_after_redelivery_budget():
    class _FlipAlways(_FlipOnce):
        def send(self, frame):
            # corrupt every block frame (anything not JSON-parseable
            # as a control frame — cheap heuristic: big frames)
            if len(frame) > 512:
                frame = bytearray(frame)
                frame[0] ^= 0xFF
                frame = bytes(frame)
            self._chan.send(frame)

    cfg, model, params = _model(_unrolled)
    clock = VirtualClock()
    eng = InferenceEngine(model, params, _ecfg(), time_fn=clock)
    rid = eng.submit(_prompts(cfg.vocab_size, lens=(16,))[0], 1)
    while eng.has_work():
        eng.step()
    payload = eng.extract_handoff(rid, max_new_tokens=4)
    assert len(payload.blocks[0]) > 512  # the heuristic must trigger

    a, b = PipeChannel.pair()
    sender = HandoffSender(_FlipAlways(a, 0), time_fn=clock)
    receiver = HandoffReceiver(b)
    sender.offer(payload)
    with pytest.raises(HandoffError, match="still corrupt"):
        for _ in range(MAX_ATTEMPTS + 1):
            assert receiver.poll() == []  # every delivery rejected
            sender.poll()


def test_fleet_corrupted_frame_no_divergence():
    """End-to-end: one flipped byte inside the fleet's handoff channel
    costs a re-handoff, not a wrong token."""
    cfg, model, params = _model(_unrolled)
    ecfg = _ecfg()
    clock = VirtualClock()
    fleet = ServingFleet(
        model, params, ecfg, FleetConfig(prefill=1, decode=2),
        time_fn=clock, check_invariants=True,
    )
    for sender in fleet._senders.values():
        sender._chan = _FlipOnce(sender._chan, 2)
    prompts = _prompts(cfg.vocab_size)
    fids = [fleet.submit(p, 10) for p in prompts]
    _drive(fleet, clock)
    outs = [list(fleet.completed[f].generated) for f in fids]
    assert outs == _ref_outputs(model, params, ecfg, prompts, 10)
    s = fleet.summary()
    assert s["re_handoff_blocks"] >= 1
    assert s["dropped_req_total"] == 0


# ----------------------------------------------------------- router


class _Events:
    def __init__(self):
        self.records = []

    def emit(self, kind, **fields):
        self.records.append({"kind": kind, **fields})

    def kinds(self):
        return [r["kind"] for r in self.records]


class _Clock:
    def __init__(self):
        self.t = 0.0

    def __call__(self):
        return self.t


def _router(clock=None, events=None, decode=2, prefill=1):
    r = Router(
        block_size=8, heartbeat_timeout_s=2.0,
        events=events, time_fn=clock or _Clock(),
    )
    for i in range(prefill):
        r.register_engine(f"prefill-{i}", "prefill")
    for i in range(decode):
        r.register_engine(f"decode-{i}", "decode")
    return r


def test_router_least_outstanding_tokens():
    r = _router(decode=3, prefill=0)
    # 3 fresh requests (distinct prompts — identical ones would share
    # an affinity key and stick on purpose) spread over all engines
    r0 = r.route(0, list(range(0, 10)), 20)
    r1 = r.route(1, list(range(10, 20)), 5)
    r2 = r.route(2, list(range(20, 30)), 5)
    assert {r0["decode"], r1["decode"], r2["decode"]} == {
        "decode-0", "decode-1", "decode-2"
    }
    # next request goes to whichever engine holds the fewest tokens —
    # NOT round-robin: r0's engine (30 tokens) must lose to the 15s
    r3 = r.route(3, list(range(30, 40)), 5)
    assert r3["decode"] != r0["decode"]


def test_router_affinity_skips_prefill_and_sticks():
    ev = _Events()
    r = _router(events=ev)
    base = list(range(20))           # >= block_size: hashable prefix
    first = r.route(0, base, 8, session="s0")
    assert first["prefill"] == "prefill-0"
    home = first["decode"]
    # the follow-up extends the prompt; same first block → same key
    follow = r.route(1, base + [55, 56], 8, session="s0")
    assert follow["decode"] == home and follow["prefill"] is None
    assert r.affinity_hits == 1
    admits = [x for x in ev.records if x["kind"] == "route_admit"]
    assert [a["affinity"] for a in admits] == [False, True]
    # a prompt shorter than one block keys on the raw token tuple —
    # extending it CHANGES the key, so no (false) affinity hit
    r.route(2, [1, 2, 3], 4, session="tiny")
    r.route(3, [1, 2, 3, 4], 4, session="tiny")
    assert r.affinity_hits == 1


def test_router_heartbeat_hysteresis_and_drain():
    clock = _Clock()
    ev = _Events()
    r = _router(clock=clock, events=ev)
    rec = r.route(0, list(range(20)), 8)
    owner = rec["prefill"]
    clock.t = 1.2                    # past suspect (1.0), not timeout
    assert r.check() == []
    suspects = [x for x in ev.records if x["kind"] == "gang_suspect"]
    assert len(suspects) == 3        # every silent engine suspected once
    assert r.check() == [] and len(
        [x for x in ev.records if x["kind"] == "gang_suspect"]
    ) == 3                           # hysteresis: no re-fire
    r.heartbeat(owner)               # owner recovers...
    clock.t = 2.5                    # ...the others cross the timeout
    drained = r.check()
    assert drained == []             # dead engines held no requests
    assert r.alive_engines("prefill") == [owner] if owner else True
    verdicts = {
        x["engine"]: x for x in ev.records if x["kind"] == "engine_verdict"
    }
    assert len(verdicts) == 2 and all(
        v["reason"] == "heartbeat" for v in verdicts.values()
    )


def test_router_mark_dead_drains_and_purges_affinity():
    ev = _Events()
    r = _router(events=ev)
    base = list(range(20))
    rec = r.route(0, base, 8, session="s0")
    r.handoff_done(0)                # decode engine now owns fid 0
    home = rec["decode"]
    drained = r.mark_dead(home, reason="kill")
    assert [d["fid"] for d in drained] == [0]
    assert r.mark_dead(home) == []   # idempotent tombstone
    verdict = next(
        x for x in ev.records if x["kind"] == "engine_verdict"
    )
    assert verdict["rung"] == "drain" and verdict["requeued"] == 1
    # affinity purged: the re-route must pick the surviving engine
    rec2 = r.route(1, base + [9], 8, session="s0")
    assert rec2["decode"] != home and rec2["prefill"] is not None


def test_router_fail_rung_and_no_engine_error():
    ev = _Events()
    r = _router(events=ev, decode=1)
    r.mark_dead("decode-0")
    verdict = next(
        x for x in ev.records if x["kind"] == "engine_verdict"
    )
    assert verdict["rung"] == "fail"  # no survivor left in the tier
    with pytest.raises(RouterError):
        r.route(0, list(range(20)), 4)


# -------------------------------------------------- kill-drain, replay


def test_fleet_kill_drain_zero_dropped():
    cfg, model, params = _model(_unrolled)
    clock = VirtualClock()
    fleet = ServingFleet(
        model, params, _ecfg(), FleetConfig(prefill=1, decode=2),
        time_fn=clock, check_invariants=True,
    )
    rng = np.random.default_rng(7)
    fids = [
        fleet.submit(rng.integers(1, cfg.vocab_size, 16 + i).tolist(), 8)
        for i in range(6)
    ]
    for _ in range(3):               # get requests in flight
        fleet.step()
        clock.tick()
    fleet.kill_engine("decode-0")
    _drive(fleet, clock)
    assert sorted(fleet.completed) == sorted(fids)
    s = fleet.summary()
    assert s["dropped_req_total"] == 0 and s["kills"] == 1
    # the survivor's allocator still satisfies the partition invariant
    fleet.engines["decode-1"].allocator.check()


def test_fleet_virtual_clock_replay_deterministic():
    cfg, model, params = _model(_unrolled)
    lcfg = LoadConfig(
        rate_rps=40.0, duration_s=0.4, prompt_len=(10, 20),
        output_len=(4, 8), vocab_size=cfg.vocab_size, seed=3,
        turns=2, turn_gap_s=0.05,
    )
    trace = make_trace(lcfg)

    def one_run():
        clock = VirtualClock()
        fleet = ServingFleet(
            model, params, _ecfg(prefix_cache=True),
            FleetConfig(prefill=1, decode=2), time_fn=clock,
        )
        out = run_load(fleet, trace, clock=clock)
        toks = [
            list(fleet.completed[f].generated)
            for f in sorted(fleet.completed)
        ]
        keys = ("completed", "handoffs", "routed", "affinity_hits",
                "requeued", "dropped_req_total", "tokens_out")
        return toks, {k: out[k] for k in keys}

    toks_a, sum_a = one_run()
    toks_b, sum_b = one_run()
    assert toks_a == toks_b and sum_a == sum_b
    assert sum_a["completed"] == len(trace)
    assert sum_a["handoffs"] >= 1 and sum_a["affinity_hits"] >= 1


# -------------------------------------------------- loadgen multi-turn


def test_make_trace_multiturn_extends_sessions():
    cfg = LoadConfig(
        rate_rps=20.0, duration_s=0.5, prompt_len=(8, 16),
        vocab_size=101, seed=5, turns=3, turn_gap_s=0.1,
    )
    trace = make_trace(cfg)
    arrivals = [r["arrival_s"] for r in trace]
    assert arrivals == sorted(arrivals)
    sessions = {}
    for r in trace:
        sessions.setdefault(r["session"], []).append(r)
    assert sessions and all(len(v) == 3 for v in sessions.values())
    for turns in sessions.values():
        turns.sort(key=lambda r: r["turn"])
        for prev, nxt in zip(turns, turns[1:]):
            p, n = list(prev["prompt"]), list(nxt["prompt"])
            assert len(n) > len(p) and n[: len(p)] == p
            assert nxt["arrival_s"] > prev["arrival_s"]


def test_make_trace_turns1_bitwise_pinned():
    """The follow-up rng is independent of the base draws: a turns=2
    trace's turn-0 records are exactly the turns=1 trace."""
    kw = dict(
        rate_rps=25.0, duration_s=0.6, prompt_len=(6, 12),
        output_len=(3, 6), vocab_size=89, seed=11,
    )
    base = make_trace(LoadConfig(**kw))           # turns defaults to 1
    multi = make_trace(LoadConfig(**kw, turns=2))
    turn0 = [r for r in multi if r["turn"] == 0]
    assert len(turn0) == len(base) == len(multi) // 2
    for a, b in zip(base, turn0):
        assert a["arrival_s"] == b["arrival_s"]
        assert list(a["prompt"]) == list(b["prompt"])
        assert a["max_new_tokens"] == b["max_new_tokens"]


# ------------------------------------------------- perf_gate directions


def test_perf_gate_fleet_headline_directions():
    import perf_gate

    assert perf_gate._bench_direction("fleet_tok_s_speedup") == "higher"
    assert perf_gate._bench_direction("fleet_p99_ttft_improvement") == "higher"
    assert perf_gate._bench_direction("fleet_p99_ttft_s") == "lower"
    assert perf_gate._bench_direction("handoff_s") == "lower"
    # loss counters now classify as their own hard-zero direction (one
    # ordered table row); gate_metrics_for maps them back to a
    # lower-better pairwise compare
    assert perf_gate._bench_direction("dropped_req_total") == "hard-zero"
    # the neighbors keep their directions
    assert perf_gate._bench_direction("serve_tok_s") == "higher"
    assert perf_gate._bench_direction("tune_gain_frac") == "higher"


def _gate(tmp_path, headline, argv_extra=(), name="flt"):
    import perf_gate

    run = tmp_path / "BENCH_fleet.json"
    run.write_text(json.dumps({"parsed": {"headline": headline}}))
    store = str(tmp_path / "runs")
    base_args = [str(run), "--store", store, "--baseline", name]
    assert perf_gate.main(base_args + ["--update-baseline"]) == 0
    return perf_gate.main(base_args + list(argv_extra))


def test_perf_gate_hard_zero_dropped(tmp_path):
    import perf_gate

    # identical run and baseline, but dropped_req_total is nonzero —
    # "no worse than a lossy baseline" must still FAIL
    lossy = {"fleet_tok_s_speedup": 1.4, "dropped_req_total": 2.0}
    assert _gate(tmp_path, lossy) == perf_gate.REGRESS_EXIT
    # --allow-drops downgrades to the ordinary lower-better compare,
    # which passes against the equal baseline
    assert _gate(
        tmp_path, lossy, ["--allow-drops"], name="flt2"
    ) == 0
    # a clean run (zero drops) passes without the flag
    clean = {"fleet_tok_s_speedup": 1.4, "dropped_req_total": 0.0}
    assert _gate(tmp_path, clean, name="flt3") == 0


# --------------------------------------------------- perfetto export


def test_trace_export_fleet_tracks():
    from distributeddataparallel_tpu.observability.trace_export import (
        to_trace_events,
        validate_trace,
    )

    records = [
        {"kind": "run_start", "ts": 0.0, "proc": "supervisor"},
        {"kind": "route_admit", "ts": 0.1, "proc": "supervisor",
         "req": 0, "engine": "decode-0", "prefill": "prefill-0",
         "affinity": False, "session": "s0", "queue_depth": 1},
        {"kind": "kv_handoff", "ts": 0.2, "proc": 0, "req": 5,
         "blocks": 3, "bytes": 12288, "attempts": 1,
         "handoff_s": 0.01, "src": "prefill-0", "dst": "decode-0"},
        {"kind": "engine_verdict", "ts": 0.3, "proc": "supervisor",
         "engine": "decode-1", "rung": "drain", "tier": "decode",
         "requeued": 2, "reason": "kill"},
    ]
    trace = to_trace_events(records)
    assert validate_trace(trace) == []
    by = {}
    for e in trace["traceEvents"]:
        by.setdefault((e["ph"], e["name"]), []).append(e)
    # route_admit: instant + router queue-depth counter sample
    assert ("i", "route_admit") in by
    (queue,) = by[("C", "router_queue")]
    assert queue["args"] == {"router_queue": 1.0}
    # kv_handoff: handoff-bytes counter track
    (hand,) = by[("C", "handoff_bytes")]
    assert hand["args"] == {"handoff_bytes": 12288.0}
    # engine_verdict: a global instant carrying the rung
    (verdict,) = by[("i", "engine_verdict")]
    assert verdict["args"]["rung"] == "drain"
