"""Multi-host elastic runtime: fault-matrix chaos through real OS
processes, rendezvous hardening, and the degradation ladder.

Every test here runs gang members as SEPARATE interpreters (the
``runtime.hostgang`` driver) against a TCP rendezvous store, supervised
by ``launcher.spawn`` — the topology a real fleet runs, not the
single-process CPU simulation the rest of the suite uses.  The matrix
tests assert the one invariant the ladder promises: every injected
fault ends in exactly one rung — resize, checkpoint restart, or loud
fail — named by a supervisor ``gang_verdict`` event that attributes the
triggering fault.
"""

import json
import multiprocessing as mp
import os
import threading
import time

import pytest

from distributeddataparallel_tpu.runtime.hostgang import (
    EVICTED_EXIT,
    hostgang_worker,
    step_state,
)
from distributeddataparallel_tpu.runtime.launcher import spawn
from distributeddataparallel_tpu.runtime.rendezvous import (
    AddressBook,
    RendezvousStore,
    RetryPolicy,
    TCPRendezvousClient,
    TCPRendezvousServer,
    rehost_store,
    retry_call,
)
from distributeddataparallel_tpu.utils.chaos import HOST_KILLED_EXIT

pytestmark = pytest.mark.skipif(
    os.environ.get("DDP_SKIP_MULTIPROC") == "1",
    reason="multi-process gang tests disabled",
)


# ---------------------------------------------------------------------
# rendezvous hardening units (satellite: retry / re-host / self-heal)
# ---------------------------------------------------------------------


def test_retry_policy_backoff_and_jitter_bounds():
    p = RetryPolicy(attempts=5, base_s=0.1, max_s=0.8, jitter=0.25)
    delays = list(p.delays())
    assert len(delays) == 4  # attempts - 1 sleeps between attempts
    # Exponential envelope, capped, never negative, jitter-bounded.
    for i, d in enumerate(delays):
        nominal = min(0.1 * (2 ** i), 0.8)
        assert nominal * 0.75 - 1e-9 <= d <= nominal * 1.25 + 1e-9


def test_retry_call_recovers_after_transient_refusals():
    calls = {"n": 0}

    def flaky():
        calls["n"] += 1
        if calls["n"] < 3:
            raise ConnectionRefusedError("not up yet")
        return "ok"

    out = retry_call(
        flaky, policy=RetryPolicy(attempts=5, base_s=0.01, max_s=0.02)
    )
    assert out == "ok" and calls["n"] == 3


def test_address_book_generation_fence(tmp_path):
    book = AddressBook(str(tmp_path / "book.json"))
    assert book.lookup() is None
    assert book.publish("127.0.0.1:1000", 1)
    assert book.publish("127.0.0.1:2000", 2)
    # A stale (pre-re-host) server may try to re-publish: fenced.
    assert not book.publish("127.0.0.1:1000", 1)
    assert book.lookup() == ("127.0.0.1:2000", 2)


def test_store_self_heals_torn_epoch_json(tmp_path):
    store = RendezvousStore(str(tmp_path))
    for m in ("a", "b"):
        store.join(m)
    store.propose(["a", "b"], epoch=0)
    # Tear epoch.json the way a host dying mid-write does.
    with open(os.path.join(str(tmp_path), "epoch.json"), "w") as fh:
        fh.write('{"epoch": ')
    rec = store.epoch()  # must re-promote the last valid log record
    assert rec["epoch"] == 0 and rec["roster"] == ["a", "b"]


def test_tcp_client_survives_server_kill_and_rehost_mid_barrier(tmp_path):
    """The satellite's named scenario: a client blocked in ``barrier()``
    while the server is killed and re-hosted must complete the barrier
    against the new server via address-book re-resolution — no error
    reaches the membership protocol."""
    book = AddressBook(str(tmp_path / "book.json"))
    store = RendezvousStore(str(tmp_path / "s0"))
    for m in ("a", "b"):
        store.join(m)
    store.propose(["a", "b"], epoch=0)
    srv = TCPRendezvousServer(store, generation=0, address_book=book)

    cli = TCPRendezvousClient(
        address_book=book,
        retry=RetryPolicy(attempts=8, base_s=0.05, max_s=0.4),
    )
    cli.epoch()  # warm the epoch cache (re-host replay material)
    done = {}

    def in_barrier():
        done["rec"] = cli.barrier(1, "a", ["a", "b"], timeout_s=20.0)

    t = threading.Thread(target=in_barrier)
    t.start()
    time.sleep(0.2)  # let the barrier RPC get in flight
    srv.kill()
    new_srv = rehost_store(
        str(tmp_path / "s1"),
        cli.cached_history(),
        generation=1,
        members=["a", "b"],
        address_book=book,
    )
    try:
        # The other participant acks on the NEW server; the blocked
        # client's retry must land there too.
        with TCPRendezvousClient(address_book=book) as other:
            other.ack(1, "b")
            other.ack(1, "a")
        t.join(timeout=20.0)
        assert not t.is_alive(), "barrier never completed after re-host"
        assert done["rec"] is True
        assert cli.generation_seen == 1
    finally:
        new_srv.close()
        cli.close()


# ---------------------------------------------------------------------
# fault matrix -> degradation ladder (one test per cell)
# ---------------------------------------------------------------------


def _run_gang(tmp_path, chaos, *, world=3, steps=8, step_s=0.05,
              max_restarts=2, min_procs=1, expect_raise=False):
    """One supervised hostgang run; returns (events, verdicts, error)."""
    root = str(tmp_path / "gang")
    events_dir = os.path.join(root, "events")
    os.makedirs(events_dir)
    cfg = {
        "store_root": root,
        "world_size": world,
        "steps": steps,
        "step_s": step_s,
        "transport": "tcp",
        "min_size": min_procs,
        "heartbeat_timeout_s": 2.5,
        "suspect_after_s": 1.0,
    }
    env = {"DDP_CHAOS": chaos, "JAX_PLATFORMS": "cpu"}
    err = None
    try:
        spawn(
            hostgang_worker, args=(cfg,), nprocs=world,
            max_restarts=max_restarts, restart_backoff_s=0.1,
            env=env, events_dir=events_dir,
            elastic_store=os.path.join(root, "store"),
            min_procs=min_procs,
        )
    except RuntimeError as exc:
        if not expect_raise:
            raise
        err = exc
    recs = []
    for fn in sorted(os.listdir(events_dir)):
        if not fn.endswith(".jsonl") or fn == "timeline.jsonl":
            continue
        with open(os.path.join(events_dir, fn)) as fh:
            for line in fh:
                if line.strip():
                    recs.append(json.loads(line))
    verdicts = [r for r in recs if r.get("kind") == "gang_verdict"]
    return recs, verdicts, err


def _assert_single_verdict(verdicts, rung, fault_kind):
    assert len(verdicts) == 1, verdicts
    v = verdicts[0]
    assert v["rung"] == rung, v
    assert v["fault_kind"] == fault_kind, v
    assert v["fault"] and v["fault"].startswith(fault_kind), v
    assert v["proc"] == "supervisor", v
    return v


def test_matrix_host_kill_resize(tmp_path):
    """host-kill: the victim dies abruptly (os._exit, no unwind);
    survivors tombstone it and absorb the loss in place — resize rung,
    zero respawns, the dead rank's HOST_KILLED_EXIT in the verdict."""
    recs, verdicts, _ = _run_gang(tmp_path, "host-kill@3:1")
    v = _assert_single_verdict(verdicts, "resize", "host-kill")
    assert v["failed"] == [[1, HOST_KILLED_EXIT]]
    assert v["respawns"] == 0
    resizes = [r for r in recs if r.get("kind") == "gang_resize"]
    assert resizes and all("host1" in r["left"] for r in resizes)


def test_matrix_proposer_kill_resize(tmp_path):
    """proposer-kill: tombstones the would-be proposer (smallest live
    member); the promoted second-smallest must complete the transition
    the kill forced — resize rung, victim exits EVICTED_EXIT."""
    recs, verdicts, _ = _run_gang(tmp_path, "proposer-kill@3")
    v = _assert_single_verdict(verdicts, "resize", "proposer-kill")
    assert v["failed"] == [[0, EVICTED_EXIT]]
    epochs = [r for r in recs if r.get("kind") == "membership_epoch"]
    final = max(epochs, key=lambda r: r["epoch"])
    assert "host0" not in final["roster"]


def test_matrix_rdzv_kill_rehost_resize(tmp_path):
    """rdzv-kill: the TCP store dies mid-run; the deterministic
    smallest-name survivor re-hosts it at a higher generation and the
    run finishes with the roster intact — resize rung (nothing
    respawned, nothing restarted), with the re-host on the timeline."""
    recs, verdicts, _ = _run_gang(tmp_path, "rdzv-kill@3")
    _assert_single_verdict(verdicts, "resize", "rdzv-kill")
    rehosts = [r for r in recs if r.get("kind") == "rdzv_rehost"]
    assert rehosts and rehosts[0]["owner"] == "host0"
    assert rehosts[0]["generation"] >= 1
    assert not any(r.get("kind") == "restart_attempt" for r in recs)


def test_matrix_slow_heartbeat_suspect_then_resize(tmp_path):
    """slow-heartbeat: the victim's beat is suppressed past the full
    timeout.  The hysteresis window must fire FIRST (gang_suspect —
    straggler alarm, not yet tombstoned), then the failure detector
    promotes the expiry to a tombstone — resize rung."""
    recs, verdicts, _ = _run_gang(
        tmp_path, "slow-heartbeat@3:10.0:1", steps=40, step_s=0.15,
    )
    v = _assert_single_verdict(verdicts, "resize", "slow-heartbeat")
    assert v["failed"] == [[1, EVICTED_EXIT]]
    sus = [r for r in recs if r.get("kind") == "gang_suspect"]
    assert sus and {r["member"] for r in sus} == {"host1"}
    t_suspect = min(r["ts"] for r in sus)
    t_evict = max(
        r["ts"] for r in recs if r.get("kind") == "membership_epoch"
    )
    assert t_suspect <= t_evict, "suspect must precede the tombstone"


def test_matrix_partition_resize(tmp_path):
    """partition (asymmetric): the victim's writes are dropped while its
    reads still work — peers expire its heartbeat and shed it; the
    victim discovers its own eviction from the surviving side's epoch
    and exits EVICTED_EXIT — resize rung."""
    # Long enough for the victim's last write to age past the full
    # heartbeat timeout (2.5s) while peers keep stepping.
    recs, verdicts, _ = _run_gang(
        tmp_path, "partition@3:1", steps=40, step_s=0.15,
    )
    _assert_single_verdict(verdicts, "resize", "partition")
    epochs = [r for r in recs if r.get("kind") == "membership_epoch"]
    final = max(epochs, key=lambda r: r["epoch"])
    assert "host1" not in final["roster"]


def test_matrix_torn_epoch_restart(tmp_path):
    """torn-epoch: a host dies mid-``epoch.json`` write.  With the whole
    (single-member) gang gone there are no survivors to resize around:
    the supervisor restarts from the top — checkpoint-restart rung,
    budget consumed, fault named."""
    recs, verdicts, _ = _run_gang(tmp_path, "torn-epoch@3", world=1)
    v = _assert_single_verdict(verdicts, "restart", "torn-epoch")
    assert v["attempts"] == 1
    assert any(r.get("kind") == "restart_attempt" for r in recs)
    assert not any(r.get("kind") == "gang_resize" for r in recs)


def test_matrix_loud_fail_rung(tmp_path):
    """The ladder's last rung: resize impossible (single member — no
    survivors to absorb into) and the fault recurs past the restart
    budget — the supervisor must fail LOUDLY with a fail-rung verdict
    naming the fault, and raise."""
    recs, verdicts, err = _run_gang(
        tmp_path, "host-kill@3:0,host-kill@5:0", world=1, max_restarts=1,
        expect_raise=True,
    )
    assert err is not None and "restart budget" in str(err)
    v = _assert_single_verdict(verdicts, "fail", "host-kill")
    assert v["max_restarts"] == 1
    (rank, code), = v["failed"]
    assert rank == 0 and code == HOST_KILLED_EXIT
    # The first death consumed the one restart before the budget died.
    assert any(r.get("kind") == "restart_attempt" for r in recs)


# ---------------------------------------------------------------------
# shrink AND grow with bitwise live-state parity vs checkpoint restore
# ---------------------------------------------------------------------


def _reference_acc(steps: int) -> float:
    """Checkpoint-restore replay: what a member restoring from step 0
    and replaying every step computes — the parity baseline."""
    acc = 0.0
    for step in range(steps):
        acc = step_state(acc, step)
    return acc


def _done_states(store_root: str) -> dict:
    store = RendezvousStore(store_root)
    out = {}
    for name in ("host0", "host1", "host2", "host3"):
        blob = store.get_blob(f"done:{name}")
        if blob:
            out[name] = json.loads(blob)
    return out


def test_multihost_shrink_bitwise_parity(tmp_path):
    """Shrink: a 3-process TCP gang loses one host mid-run and absorbs
    it in place.  The survivors' live state must be BITWISE equal to
    the checkpoint-restore replay — the resize path corrupted nothing
    and skipped nothing."""
    steps = 8
    recs, verdicts, _ = _run_gang(tmp_path, "host-kill@3:1", steps=steps)
    _assert_single_verdict(verdicts, "resize", "host-kill")
    states = _done_states(str(tmp_path / "gang" / "store"))
    assert set(states) == {"host0", "host2"}  # host1 died, no done blob
    ref = _reference_acc(steps)
    for name, st in states.items():
        assert st["step"] == steps
        assert st["acc"] == ref, (name, st["acc"].hex(), ref.hex())


def test_multihost_grow_bitwise_parity(tmp_path):
    """Grow (ROADMAP 3c): a 4th process joins an established 3-process
    gang mid-run, catches up from the survivors' PUBLISHED live state
    (the blob board, not a checkpoint file), and finishes in lockstep:
    its final state is bitwise-identical to both the incumbents' and
    the checkpoint-restore replay."""
    root = str(tmp_path / "gang")
    os.makedirs(root)
    steps = 16
    cfg = {
        "store_root": root,
        "world_size": 3,
        "steps": steps,
        "step_s": 0.1,
        "transport": "tcp",
        "min_size": 1,
        "heartbeat_timeout_s": 2.5,
        "suspect_after_s": 1.0,
    }
    os.environ.pop("DDP_CHAOS", None)
    ctx = mp.get_context("spawn")
    procs = [
        ctx.Process(target=hostgang_worker, args=(i, cfg))
        for i in range(3)
    ]
    for p in procs:
        p.start()
    # Let the gang establish an epoch and make progress, then grow.
    store_root = os.path.join(root, "store")
    deadline = time.monotonic() + 30.0
    while time.monotonic() < deadline:
        try:
            blob = RendezvousStore(store_root).get_blob("state")
            if blob and json.loads(blob).get("step", 0) >= 3:
                break
        except OSError:
            pass
        time.sleep(0.05)
    late_cfg = dict(cfg, world_size=4)
    joiner = ctx.Process(target=hostgang_worker, args=(3, late_cfg))
    joiner.start()
    for p in procs + [joiner]:
        p.join(timeout=90.0)
    assert [p.exitcode for p in procs + [joiner]] == [0, 0, 0, 0]

    states = _done_states(store_root)
    assert set(states) == {"host0", "host1", "host2", "host3"}
    ref = _reference_acc(steps)
    for name, st in states.items():
        assert st["acc"] == ref, (name, st["acc"], ref)
    # The joiner really did catch up (adopted a step > 0), and the gang
    # agreed on a grown epoch containing it.
    hist = RendezvousStore(store_root).history()
    assert any("host3" in rec["roster"] for rec in hist)
