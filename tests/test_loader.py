"""Input-pipeline tests: loader row placement matches per-rank sampler
shards (each mesh position sees exactly what its DDP-rank counterpart
would), epoch reshuffle, sharded device placement, normalization parity."""

import jax
import numpy as np

from distributeddataparallel_tpu.data.datasets import (
    SyntheticClassification,
    normalize_images,
)
from distributeddataparallel_tpu.data.loader import DataLoader
from distributeddataparallel_tpu.parallel.sampler import DistributedSampler
from distributeddataparallel_tpu.runtime.distributed import make_mesh


def test_loader_rows_match_sampler_shards(devices):
    mesh = make_mesh(("data",))
    n = mesh.shape["data"]
    ds = SyntheticClassification(num_examples=257, shape=(4, 4, 1), seed=0)
    B = 4
    loader = DataLoader(
        ds, per_replica_batch=B, mesh=mesh, shuffle=True, seed=9, device_feed=False
    )
    loader.set_epoch(2)

    shards = []
    for r in range(n):
        s = DistributedSampler(len(ds), num_replicas=n, rank=r, seed=9)
        s.set_epoch(2)
        shards.append(s.local_indices())

    batches = list(loader)
    assert len(batches) == len(loader) == shards[0].shape[0] // B
    for step, batch in enumerate(batches):
        assert batch["image"].shape == (B * n, 4, 4, 1)
        for r in range(n):
            idx = shards[r][step * B : (step + 1) * B]
            np.testing.assert_array_equal(
                batch["image"][r * B : (r + 1) * B], ds.images[idx]
            )
            np.testing.assert_array_equal(
                batch["label"][r * B : (r + 1) * B], ds.labels[idx]
            )


def test_device_feed_sharding(devices):
    mesh = make_mesh(("data",))
    ds = SyntheticClassification(num_examples=128, shape=(4, 4, 1))
    loader = DataLoader(ds, per_replica_batch=2, mesh=mesh, prefetch=2)
    batch = next(iter(loader))
    img = batch["image"]
    assert isinstance(img, jax.Array)
    assert img.shape[0] == 2 * mesh.shape["data"]
    assert {s.data.shape[0] for s in img.addressable_shards} == {2}


def test_epoch_reshuffle_changes_order(devices):
    mesh = make_mesh(("data",))
    ds = SyntheticClassification(num_examples=256, shape=(2, 2, 1))
    loader = DataLoader(ds, per_replica_batch=4, mesh=mesh, device_feed=False)
    loader.set_epoch(0)
    b0 = next(iter(loader))
    loader.set_epoch(1)
    b1 = next(iter(loader))
    assert not np.array_equal(b0["image"], b1["image"])
    loader.set_epoch(0)
    b0_again = next(iter(loader))
    np.testing.assert_array_equal(b0["image"], b0_again["image"])


def test_normalize_matches_torch_transform():
    """ToTensor + Normalize((0.5,),(0.5,)) parity (ref dpp.py:32).

    torchvision isn't in this image, so reproduce its exact math with bare
    torch ops: ToTensor = uint8 HWC -> float CHW / 255; Normalize = (x-m)/s
    with scalar mean/std broadcast over channels.
    """
    torch = __import__("pytest").importorskip("torch")

    rng = np.random.default_rng(0)
    img_u8 = rng.integers(0, 256, size=(8, 8, 3), dtype=np.uint8)
    t = torch.from_numpy(img_u8).permute(2, 0, 1).to(torch.float32) / 255.0
    theirs = ((t - 0.5) / 0.5).numpy().transpose(1, 2, 0)  # CHW -> HWC
    ours = normalize_images(img_u8)
    np.testing.assert_allclose(ours, theirs, rtol=1e-6, atol=1e-6)
    assert ours.min() >= -1.0 and ours.max() <= 1.0
