"""Input-pipeline tests: loader row placement matches per-rank sampler
shards (each mesh position sees exactly what its DDP-rank counterpart
would), epoch reshuffle, sharded device placement, normalization parity."""

import jax
import numpy as np

from distributeddataparallel_tpu.data.datasets import (
    SyntheticClassification,
    normalize_images,
)
from distributeddataparallel_tpu.data.loader import DataLoader
from distributeddataparallel_tpu.parallel.sampler import DistributedSampler
from distributeddataparallel_tpu.runtime.distributed import make_mesh


def test_loader_rows_match_sampler_shards(devices):
    mesh = make_mesh(("data",))
    n = mesh.shape["data"]
    ds = SyntheticClassification(num_examples=257, shape=(4, 4, 1), seed=0)
    B = 4
    loader = DataLoader(
        ds, per_replica_batch=B, mesh=mesh, shuffle=True, seed=9, device_feed=False
    )
    loader.set_epoch(2)

    shards = []
    for r in range(n):
        s = DistributedSampler(len(ds), num_replicas=n, rank=r, seed=9)
        s.set_epoch(2)
        shards.append(s.local_indices())

    batches = list(loader)
    assert len(batches) == len(loader) == shards[0].shape[0] // B
    for step, batch in enumerate(batches):
        assert batch["image"].shape == (B * n, 4, 4, 1)
        for r in range(n):
            idx = shards[r][step * B : (step + 1) * B]
            np.testing.assert_array_equal(
                batch["image"][r * B : (r + 1) * B], ds.images[idx]
            )
            np.testing.assert_array_equal(
                batch["label"][r * B : (r + 1) * B], ds.labels[idx]
            )


def test_device_feed_sharding(devices):
    mesh = make_mesh(("data",))
    ds = SyntheticClassification(num_examples=128, shape=(4, 4, 1))
    loader = DataLoader(ds, per_replica_batch=2, mesh=mesh, prefetch=2)
    batch = next(iter(loader))
    img = batch["image"]
    assert isinstance(img, jax.Array)
    assert img.shape[0] == 2 * mesh.shape["data"]
    assert {s.data.shape[0] for s in img.addressable_shards} == {2}


def test_epoch_reshuffle_changes_order(devices):
    mesh = make_mesh(("data",))
    ds = SyntheticClassification(num_examples=256, shape=(2, 2, 1))
    loader = DataLoader(ds, per_replica_batch=4, mesh=mesh, device_feed=False)
    loader.set_epoch(0)
    b0 = next(iter(loader))
    loader.set_epoch(1)
    b1 = next(iter(loader))
    assert not np.array_equal(b0["image"], b1["image"])
    loader.set_epoch(0)
    b0_again = next(iter(loader))
    np.testing.assert_array_equal(b0["image"], b0_again["image"])


def test_normalize_matches_torch_transform():
    """ToTensor + Normalize((0.5,),(0.5,)) parity (ref dpp.py:32).

    torchvision isn't in this image, so reproduce its exact math with bare
    torch ops: ToTensor = uint8 HWC -> float CHW / 255; Normalize = (x-m)/s
    with scalar mean/std broadcast over channels.
    """
    torch = __import__("pytest").importorskip("torch")

    rng = np.random.default_rng(0)
    img_u8 = rng.integers(0, 256, size=(8, 8, 3), dtype=np.uint8)
    t = torch.from_numpy(img_u8).permute(2, 0, 1).to(torch.float32) / 255.0
    theirs = ((t - 0.5) / 0.5).numpy().transpose(1, 2, 0)  # CHW -> HWC
    ours = normalize_images(img_u8)
    np.testing.assert_allclose(ours, theirs, rtol=1e-6, atol=1e-6)
    assert ours.min() >= -1.0 and ours.max() <= 1.0


def test_with_mask_marks_padded_rows(devices):
    """The "valid" mask is 0 exactly on sampler-padded duplicate rows —
    positional (pad slots are global positions >= N), so it holds under
    shuffle too."""
    mesh = make_mesh(("data",))
    n = mesh.shape["data"]  # 8
    ds = SyntheticClassification(num_examples=9, shape=(2, 2, 1), seed=0)
    loader = DataLoader(
        ds, per_replica_batch=1, mesh=mesh, shuffle=True, seed=3,
        drop_last=False, device_feed=False, with_mask=True,
    )
    loader.set_epoch(1)
    batches = list(loader)
    assert len(batches) == 2  # ceil(9/8) per replica
    np.testing.assert_array_equal(batches[0]["valid"], np.ones(n))
    # Second step: only replica 0 (global position 8 < 9) holds a real row.
    expect = np.zeros(n)
    expect[0] = 1.0
    np.testing.assert_array_equal(batches[1]["valid"], expect)
    assert sum(b["valid"].sum() for b in batches) == len(ds)


def test_masked_eval_exact_over_padded_tail(devices):
    """End-to-end exactness (the DistributedSampler eval-padding trap):
    9 samples on 8 replicas pad the final batch with 7 duplicates; the
    masked eval mean must equal the plain mean over the 9 unique rows —
    duplicates must contribute to NEITHER numerator NOR denominator."""
    from distributeddataparallel_tpu.training.train_step import make_eval_step

    mesh = make_mesh(("data",))
    ds = SyntheticClassification(num_examples=9, shape=(2, 2, 1), seed=0)
    # Distinct per-row "metric": the sample's own mean pixel value.
    truth = ds.images.reshape(9, -1).mean(axis=1)

    def metric_fn(params, batch):
        return {"m": batch["image"].reshape(batch["image"].shape[0], -1).mean(axis=1)}

    step = make_eval_step(metric_fn, mesh=mesh, masked=True)
    loader = DataLoader(
        ds, per_replica_batch=1, mesh=mesh, shuffle=False, drop_last=False,
        with_mask=True,
    )
    vals = []
    for b in loader:
        m, cnt = step({}, b)
        vals.append((float(m["m"]), float(cnt)))

    assert sum(c for _, c in vals) == len(ds)  # counts = unique rows
    got = sum(v * c for v, c in vals) / sum(c for _, c in vals)
    np.testing.assert_allclose(got, truth.mean(), rtol=1e-6)


def test_masked_cp_eval_exact(devices):
    """DP×CP masked eval: per-row metrics pmean'd over the seq axis then
    masked-mean'd over data must equal the host-side mean over unique rows."""
    from distributeddataparallel_tpu.data.loader import shard_lm_batch
    from distributeddataparallel_tpu.parallel import make_cp_eval_step

    mesh = make_mesh(("data", "seq"), shape=(4, 2))
    rng = np.random.default_rng(0)
    tokens = rng.integers(0, 100, size=(6, 9)).astype(np.int32)  # 6 rows
    valid = np.array([1, 1, 1, 1, 1, 0], np.float32)  # row 5 is a pad dup

    def metric_fn(params, batch):
        # per-row mean target value over the LOCAL seq chunk
        return {"m": batch["targets"].astype(np.float32).mean(axis=1)}

    step = make_cp_eval_step(metric_fn, mesh=mesh, masked=True)
    # 6 rows don't split 4-way: pad to 8 with dups (mask 0) like the sampler.
    tokens8 = np.concatenate([tokens, tokens[:2]])
    valid8 = np.concatenate([valid, np.zeros(2, np.float32)])
    batch = shard_lm_batch(tokens8, mesh, valid=valid8)
    m, cnt = step({}, batch)
    assert float(cnt) == 5.0

    want = tokens[:5, 1:].astype(np.float32).mean()  # unique real rows only
    np.testing.assert_allclose(float(m["m"]), want, rtol=1e-6)


def test_synthetic_u8_mode_consistent(devices):
    """keep_u8 synthetic data: both access paths (itemwise __getitem__ and
    the loader's columnar gather, native kernel when built) must yield the
    same normalized float32 values."""
    ds = SyntheticClassification(num_examples=16, shape=(4, 4, 3), seed=0,
                                 keep_u8=True)
    assert ds.images.dtype == np.uint8 and ds.normalize_u8
    img0, label0 = ds[3]
    assert img0.dtype == np.float32
    assert img0.min() >= -1.0 and img0.max() <= 1.0

    mesh = make_mesh(("data",))
    loader = DataLoader(
        ds, per_replica_batch=2, mesh=mesh, shuffle=False, device_feed=False
    )
    batch = next(iter(loader))
    assert batch["image"].dtype == np.float32
    # Row 3 of the first batch: replica-major order puts sampler rank r's
    # first 2 indices at rows [2r, 2r+1]; with shuffle=False rank 1's
    # first index is 1 -> row 2 is sample 1, so recover sample 3 directly.
    idx = np.concatenate([
        DistributedSampler(len(ds), num_replicas=8, rank=r, shuffle=False)
        .local_indices()[:2]
        for r in range(8)
    ])
    row = int(np.where(idx == 3)[0][0])
    np.testing.assert_allclose(batch["image"][row], img0, atol=1e-6)
