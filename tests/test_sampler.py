"""DistributedSampler semantics tests (SURVEY.md §4 'unit').

The contract (SURVEY.md §2b): pad to ceil(N/W)*W by repeating indices,
stride indices[rank::W], reseed shuffle with seed+epoch.  Where behavior is
deterministic (shuffle=False) we check *exact* equality against torch's
DistributedSampler — the reference's actual dependency — using the baked-in
CPU torch.
"""

import numpy as np
import pytest

from distributeddataparallel_tpu.parallel.sampler import (
    DistributedSampler,
    shard_indices_for_hosts,
)


def test_partition_exact_cover_no_shuffle():
    N, W = 103, 8  # non-divisible on purpose
    shards = [
        list(DistributedSampler(N, num_replicas=W, rank=r, shuffle=False))
        for r in range(W)
    ]
    lens = {len(s) for s in shards}
    assert lens == {13}  # ceil(103/8)
    flat = sorted(i for s in shards for i in s)
    # covers all of range(N); padding repeats head indices
    assert set(flat) == set(range(N))
    assert len(flat) == 13 * 8


@pytest.mark.parametrize("N,W", [(100, 4), (103, 8), (7, 8), (64, 8)])
def test_matches_torch_no_shuffle(N, W):
    torch = pytest.importorskip("torch")
    from torch.utils.data import DistributedSampler as TorchSampler

    class _DS(torch.utils.data.Dataset):
        def __len__(self):
            return N

        def __getitem__(self, i):
            return i

    for rank in range(W):
        ours = list(DistributedSampler(N, num_replicas=W, rank=rank, shuffle=False))
        theirs = list(TorchSampler(_DS(), num_replicas=W, rank=rank, shuffle=False))
        assert ours == theirs, f"rank {rank}: {ours} != {theirs}"


@pytest.mark.parametrize("N,W", [(100, 4), (103, 8)])
def test_matches_torch_drop_last(N, W):
    torch = pytest.importorskip("torch")
    from torch.utils.data import DistributedSampler as TorchSampler

    class _DS(torch.utils.data.Dataset):
        def __len__(self):
            return N

        def __getitem__(self, i):
            return i

    for rank in range(W):
        ours = list(
            DistributedSampler(N, num_replicas=W, rank=rank, shuffle=False, drop_last=True)
        )
        theirs = list(
            TorchSampler(_DS(), num_replicas=W, rank=rank, shuffle=False, drop_last=True)
        )
        assert ours == theirs


def test_shuffle_is_epoch_deterministic_partition():
    N, W = 1000, 8
    samplers = [DistributedSampler(N, num_replicas=W, rank=r, seed=42) for r in range(W)]
    for epoch in (0, 1, 5):
        for s in samplers:
            s.set_epoch(epoch)
        shards = [s.local_indices() for s in samplers]
        # all shards equal length; union covers the dataset
        assert all(len(sh) == 125 for sh in shards)
        assert set(np.concatenate(shards).tolist()) == set(range(N))
        # same epoch twice -> identical
        again = [s.local_indices() for s in samplers]
        for a, b in zip(shards, again):
            np.testing.assert_array_equal(a, b)
    # different epochs -> different order
    samplers[0].set_epoch(0)
    e0 = samplers[0].local_indices()
    samplers[0].set_epoch(1)
    e1 = samplers[0].local_indices()
    assert not np.array_equal(e0, e1)


def test_host_sharding_matches_per_replica_sampler():
    N, hosts, per_host = 256, 2, 4
    W = hosts * per_host
    for h in range(hosts):
        rows = shard_indices_for_hosts(
            N, num_hosts=hosts, host_id=h, replicas_per_host=per_host,
            epoch=3, seed=7,
        )
        for r in range(per_host):
            s = DistributedSampler(N, num_replicas=W, rank=h * per_host + r, seed=7)
            s.set_epoch(3)
            np.testing.assert_array_equal(rows[r], s.local_indices())


def test_small_dataset_wraps():
    # dataset smaller than world size: every rank still gets 1 sample
    shards = [
        list(DistributedSampler(3, num_replicas=8, rank=r, shuffle=False))
        for r in range(8)
    ]
    assert all(len(s) == 1 for s in shards)
    # wrap order matches torch: [0,1,2] padded to [0,1,2,0,1,2,0,1]
    assert [s[0] for s in shards] == [0, 1, 2, 0, 1, 2, 0, 1]
