"""End-to-end: the dpp.py entrypoint trains on 8 fake devices and the loss
goes down (BASELINE config 1 acceptance: 'runs end-to-end; loss decreases')."""

import sys

sys.path.insert(0, "/root/repo")

import dpp  # noqa: E402


def _run(extra):
    args = dpp.parse_args(
        [
            "--device", "cpu",
            "--dataset", "synthetic",
            "--num-examples", "512",
            "--batch-size", "8",
            "--log-every", "1000",
        ]
        + extra
    )
    return dpp.train(args)


def test_toy_mlp_loss_decreases(devices):
    import jax
    import jax.numpy as jnp
    import numpy as np
    import optax

    import distributeddataparallel_tpu as ddp
    from distributeddataparallel_tpu.data import DataLoader, SyntheticClassification
    from distributeddataparallel_tpu.models import TinyMLP
    from distributeddataparallel_tpu.ops import cross_entropy_loss

    mesh = ddp.make_mesh(("data",))
    ds = SyntheticClassification(num_examples=512, shape=(8, 8, 1), seed=0)
    loader = DataLoader(ds, per_replica_batch=8, mesh=mesh, seed=0)
    model = TinyMLP(features=(64,))
    params = model.init(jax.random.PRNGKey(0), jnp.zeros((1, 8, 8, 1)))["params"]
    state = ddp.TrainState.create(
        apply_fn=model.apply, params=params, tx=optax.sgd(0.05)
    )
    state = ddp.broadcast_params(state, mesh)

    def loss_fn(p, b, r):
        return cross_entropy_loss(model.apply({"params": p}, b["image"]), b["label"]), {}

    step = ddp.make_train_step(loss_fn, mesh=mesh)
    first = last = None
    for epoch in range(3):
        loader.set_epoch(epoch)
        for batch in loader:
            state, m = step(state, batch, jax.random.PRNGKey(epoch))
            if first is None:
                first = float(m["loss"])
            last = float(m["loss"])
    assert last < first * 0.7, (first, last)


def test_entrypoint_cnn_synthetic(devices):
    loss = _run(["--model", "cnn", "--epochs", "3", "--lr", "0.1"])
    assert loss == loss  # not NaN
    assert loss < 2.3  # below random-chance CE for 10 classes


def test_entrypoint_accum(devices):
    loss = _run(
        ["--model", "mlp", "--epochs", "1", "--accum-steps", "2",
         "--batch-size", "16"]
    )
    assert loss == loss


def test_entrypoint_bucketed(devices):
    loss = _run(["--model", "mlp", "--epochs", "1", "--bucket-mb", "0.01"])
    assert loss == loss


def _lm_run(extra):
    args = dpp.parse_args(
        [
            "--device", "cpu",
            "--dataset", "synthetic-lm",
            "--layers", "2",
            "--d-model", "32",
            "--seq-len", "32",
            "--vocab-size", "64",
            "--num-examples", "128",
            "--batch-size", "8",
            "--epochs", "2",
            "--log-every", "1000",
        ]
        + extra
    )
    return dpp.train(args)


def test_dropout_trains_and_is_deterministic(devices):
    """--dropout (VERDICT r4 item 7): GPT-2-style dropout trains under
    DP and under the scanned+remat llama stack x ZeRO, and the rng
    stream is deterministic (two identical runs, identical loss)."""
    a = _lm_run(["--model", "gpt2", "--dropout", "0.1"])
    b = _lm_run(["--model", "gpt2", "--dropout", "0.1"])
    assert a == b and a < 4.2  # deterministic + finite/learning
    z = _lm_run(["--model", "llama", "--dropout", "0.1", "--zero"])
    assert z < 4.2


def test_dropout_single_rejection_message(devices):
    import pytest

    for bad in (["--model", "gpt2", "--dropout", "0.1", "--fsdp"],
                ["--model", "gpt2", "--dropout", "0.1", "--pp", "2",
                 "--layers", "2"]):
        with pytest.raises(SystemExit, match="do not support it"):
            _lm_run(bad)
