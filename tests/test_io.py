"""Weight-interchange tests: safetensors round trip, GPT-2 parity against
the HuggingFace torch implementation (built offline, random weights), and
the torchvision-ResNet converter round trip."""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from distributeddataparallel_tpu.models import TransformerLM, gpt2_124m
from distributeddataparallel_tpu.models import io as mio
from distributeddataparallel_tpu.models.resnet import ResNet18, ResNet50


def test_safetensors_roundtrip(tmp_path):
    tree = {
        "a": {"w": np.arange(6, dtype=np.float32).reshape(2, 3)},
        "b": np.ones(4, np.float32),
    }
    path = str(tmp_path / "p.safetensors")
    mio.save_params(tree, path)
    flat = mio.load_params(path)
    assert set(flat) == {"a/w", "b"}
    back = mio.load_params(path, like=tree)
    for x, y in zip(jax.tree.leaves(back), jax.tree.leaves(tree)):
        np.testing.assert_array_equal(x, y)


def test_unflatten_shape_check():
    tree = {"w": np.zeros((2, 3), np.float32)}
    with pytest.raises(ValueError, match="shape"):
        mio.unflatten_into(tree, {"w": np.zeros((3, 2), np.float32)})
    with pytest.raises(KeyError):
        mio.unflatten_into(tree, {})


def test_unflatten_rejects_superset_checkpoint():
    tree = {"w": np.zeros((2,), np.float32)}
    flat = {"w": np.ones((2,), np.float32), "stale": np.ones(3, np.float32)}
    with pytest.raises(ValueError, match="unconsumed"):
        mio.unflatten_into(tree, flat)
    back = mio.unflatten_into(tree, flat, strict=False)
    np.testing.assert_array_equal(back["w"], np.ones(2))


def test_native_gather_oob_falls_back():
    from distributeddataparallel_tpu import native

    src = np.arange(12, dtype=np.float32).reshape(4, 3)
    np.testing.assert_array_equal(
        native.gather_rows(src, np.array([-1])), src[[-1]]
    )
    with pytest.raises(IndexError):
        native.gather_rows(src, np.array([99]))


def test_gpt2_matches_huggingface():
    """Load an (offline, randomly initialized) HF GPT-2 into TransformerLM
    and require logit-level agreement with the torch forward pass — the
    strongest parity statement we can make without network access."""
    torch = pytest.importorskip("torch")
    transformers = pytest.importorskip("transformers")

    hf_cfg = transformers.GPT2Config(
        vocab_size=512, n_positions=64, n_embd=64, n_layer=2, n_head=4,
        resid_pdrop=0.0, embd_pdrop=0.0, attn_pdrop=0.0,
    )
    hf = transformers.GPT2LMHeadModel(hf_cfg).eval()
    sd = {k: v.detach().numpy() for k, v in hf.state_dict().items()}

    cfg = gpt2_124m(
        vocab_size=512, max_seq_len=64, d_model=64, num_layers=2,
        num_heads=4, d_ff=256,
    )
    model = TransformerLM(cfg)
    params = mio.convert_gpt2_hf(sd, cfg)
    # Structure check against a fresh init.
    init = model.init(
        jax.random.PRNGKey(0), jnp.zeros((1, 8), jnp.int32)
    )["params"]
    got = set(mio.flatten_tree(params))
    want = set(mio.flatten_tree(init))
    assert got == want, (sorted(want - got)[:5], sorted(got - want)[:5])

    rng = np.random.default_rng(0)
    toks = rng.integers(0, 512, size=(2, 16))
    ours = model.apply({"params": params}, jnp.asarray(toks, jnp.int32))
    with torch.no_grad():
        theirs = hf(torch.tensor(toks)).logits.numpy()
    np.testing.assert_allclose(np.asarray(ours), theirs, atol=2e-4)


@pytest.mark.parametrize(
    "model_fn,stages,bottleneck",
    [
        (ResNet18, (2, 2, 2, 2), False),
        (ResNet50, (3, 4, 6, 3), True),
    ],
    ids=["resnet18", "resnet50"],
)
def test_resnet_torch_roundtrip(model_fn, stages, bottleneck):
    """export -> torchvision state_dict layout -> convert back == identity,
    and the state_dict names match torchvision's scheme."""
    model = model_fn(num_classes=10)
    variables = model.init(jax.random.PRNGKey(0), jnp.zeros((1, 32, 32, 3)))
    sd = mio.export_resnet_torch(variables, stages, bottleneck=bottleneck)
    assert "conv1.weight" in sd and "fc.bias" in sd
    assert f"layer1.0.conv1.weight" in sd
    assert sd["conv1.weight"].shape[2:] == (7, 7)  # OIHW
    back = mio.convert_resnet_torch(
        sd, variables, stages, bottleneck=bottleneck
    )
    for x, y in zip(jax.tree.leaves(back), jax.tree.leaves(variables)):
        np.testing.assert_array_equal(np.asarray(x), np.asarray(y))


def test_llama_matches_huggingface():
    """Load an (offline, randomly initialized) HF Llama into TransformerLM
    and require logit-level agreement with the torch forward pass — the
    GQA q/kv mapping, gate/up/down split, RMSNorm naming, and untied
    head all verified at once (the GPT-2 parity test's Llama analog)."""
    torch = pytest.importorskip("torch")
    transformers = pytest.importorskip("transformers")

    hf_cfg = transformers.LlamaConfig(
        vocab_size=512, hidden_size=64, intermediate_size=128,
        num_hidden_layers=2, num_attention_heads=4, num_key_value_heads=2,
        max_position_embeddings=64, rope_theta=10000.0,
        tie_word_embeddings=False, attention_bias=False, mlp_bias=False,
        rms_norm_eps=1e-5,  # match models.transformer.RMSNorm
    )
    hf = transformers.LlamaForCausalLM(hf_cfg).eval()
    sd = {k: v.detach().numpy() for k, v in hf.state_dict().items()}

    from distributeddataparallel_tpu.models.transformer import llama3_8b

    cfg = llama3_8b(
        vocab_size=512, max_seq_len=64, d_model=64, num_layers=2,
        num_heads=4, num_kv_heads=2, d_ff=128, rope_theta=10000.0,
        dtype=jnp.float32, remat=False, scan_layers=False,
    )
    model = TransformerLM(cfg)
    params = mio.convert_llama_hf(sd, cfg)
    init = model.init(
        jax.random.PRNGKey(0), jnp.zeros((1, 8), jnp.int32)
    )["params"]
    got = set(mio.flatten_tree(params))
    want = set(mio.flatten_tree(init))
    assert got == want, (sorted(want - got)[:5], sorted(got - want)[:5])

    rng = np.random.default_rng(0)
    toks = rng.integers(0, 512, size=(2, 16))
    ours = model.apply({"params": params}, jnp.asarray(toks, jnp.int32))
    with torch.no_grad():
        theirs = hf(torch.tensor(toks)).logits.numpy()
    np.testing.assert_allclose(np.asarray(ours), theirs, atol=2e-4)


def test_llama_export_roundtrip():
    """export_llama_hf inverts convert_llama_hf exactly."""
    from distributeddataparallel_tpu.models.transformer import llama3_8b

    cfg = llama3_8b(
        vocab_size=128, max_seq_len=32, d_model=32, num_layers=2,
        num_heads=4, num_kv_heads=2, d_ff=64, dtype=jnp.float32,
        remat=False, scan_layers=False,
    )
    model = TransformerLM(cfg)
    params = model.init(
        jax.random.PRNGKey(3), jnp.zeros((1, 8), jnp.int32)
    )["params"]
    back = mio.convert_llama_hf(mio.export_llama_hf(params, cfg), cfg)
    for (path, a), b in zip(
        jax.tree_util.tree_flatten_with_path(
            mio.flatten_tree(params))[0],
        jax.tree.leaves(mio.flatten_tree(back)),
    ):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), atol=0,
            err_msg=str(path),
        )


def test_stack_scanned_layers_matches_scan_init():
    """stack_scanned_layers turns converter output (layer_i subtrees)
    into the exact scan_layers param structure (pretrained + FSDP/PP)."""
    from distributeddataparallel_tpu.models.transformer import tiny_lm

    cfg = tiny_lm(num_layers=3)
    cfg_s = dataclasses.replace(cfg, scan_layers=True)
    toks = jnp.zeros((1, 16), jnp.int32)
    p_flat = TransformerLM(cfg).init(jax.random.PRNGKey(0), toks)["params"]
    p_scan = TransformerLM(cfg_s).init(jax.random.PRNGKey(0), toks)["params"]
    stacked = mio.stack_scanned_layers(p_flat, 3)
    got = {k: v.shape for k, v in mio.flatten_tree(stacked).items()}
    want = {k: v.shape for k, v in mio.flatten_tree(p_scan).items()}
    assert got == want
