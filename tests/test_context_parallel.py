"""Context-parallelism tests: ring attention numerics vs full attention,
global positions, and an end-to-end DP×CP LM train step equivalence."""

import functools

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest
from jax.sharding import NamedSharding, PartitionSpec as P

import distributeddataparallel_tpu as ddp
from distributeddataparallel_tpu.data import shard_lm_batch
from distributeddataparallel_tpu.models import TransformerLM, tiny_lm
from distributeddataparallel_tpu.ops import lm_cross_entropy
from distributeddataparallel_tpu.ops.attention import dot_product_attention
from distributeddataparallel_tpu.parallel import (
    cp_positions,
    make_cp_train_step,
    ring_attention,
)


def _ring_on_mesh(q, k, v, mesh, causal):
    fn = jax.shard_map(
        functools.partial(ring_attention, axis_name="seq", causal=causal),
        mesh=mesh,
        in_specs=(P(None, "seq"), P(None, "seq"), P(None, "seq")),
        out_specs=P(None, "seq"),
        check_vma=False,
    )
    return jax.jit(fn)(q, k, v)


@pytest.mark.parametrize("causal", [True, False])
def test_ring_attention_matches_full(causal, devices):
    mesh = ddp.make_mesh(("seq",))
    B, S, H, D = 2, 64, 2, 8  # S sharded 8-way -> 8 tokens per device
    key = jax.random.PRNGKey(0)
    q, k, v = (
        jax.random.normal(kk, (B, S, H, D))
        for kk in jax.random.split(key, 3)
    )
    ref = dot_product_attention(q, k, v, causal=causal)
    out = _ring_on_mesh(q, k, v, mesh, causal)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=1e-5)


def test_cp_positions(devices):
    mesh = ddp.make_mesh(("seq",))
    fn = jax.shard_map(
        lambda: cp_positions(4, "seq").reshape(1, 4),
        mesh=mesh,
        in_specs=(),
        out_specs=P("seq"),
        check_vma=False,
    )
    got = np.asarray(jax.jit(fn)()).reshape(-1)
    np.testing.assert_array_equal(got, np.arange(32))


def test_cp_lm_forward_matches_single_device(devices):
    """Sequence-sharded forward (ring attention + global RoPE positions)
    must reproduce the unsharded model's logits."""
    mesh = ddp.make_mesh(("seq",))
    cfg = tiny_lm(max_seq_len=64)
    cfg_cp = tiny_lm(max_seq_len=64, cp_axis="seq")
    model = TransformerLM(cfg)
    model_cp = TransformerLM(cfg_cp)
    toks = jax.random.randint(jax.random.PRNGKey(1), (2, 64), 0, 256)
    params = model.init(jax.random.PRNGKey(0), toks)["params"]

    ref = model.apply({"params": params}, toks)

    fn = jax.shard_map(
        lambda p, t: model_cp.apply({"params": p}, t),
        mesh=mesh,
        in_specs=(P(), P(None, "seq")),
        out_specs=P(None, "seq"),
        check_vma=False,
    )
    out = jax.jit(fn)(params, toks)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-4)


def test_cp_train_step_matches_dp(devices):
    """DP×CP (4 data × 2 seq) one train step == single-device step on the
    same global batch: same loss, same updated params."""
    mesh = ddp.make_mesh(("data", "seq"), shape=(4, 2))
    cfg = tiny_lm(max_seq_len=32)
    cfg_cp = tiny_lm(max_seq_len=32, cp_axis="seq")
    model = TransformerLM(cfg)
    model_cp = TransformerLM(cfg_cp)
    rng = np.random.default_rng(0)
    tokens = rng.integers(0, 256, size=(8, 33)).astype(np.int32)
    params = model.init(
        jax.random.PRNGKey(0), jnp.zeros((1, 32), jnp.int32)
    )["params"]
    tx = optax.sgd(0.1)

    # Reference: single-device full-batch step.
    def ref_loss(p):
        logits = model.apply({"params": p}, jnp.asarray(tokens[:, :-1]))
        return lm_cross_entropy(logits, jnp.asarray(tokens[:, 1:]))

    loss_ref, grads_ref = jax.value_and_grad(ref_loss)(params)
    updates, _ = tx.update(grads_ref, tx.init(params), params)
    params_ref = optax.apply_updates(params, updates)

    # DP×CP step.
    def loss_fn(p, batch, rng):
        logits = model_cp.apply({"params": p}, batch["inputs"])
        return lm_cross_entropy(logits, batch["targets"]), {}

    state = ddp.TrainState.create(apply_fn=model_cp.apply, params=params, tx=tx)
    state = ddp.broadcast_params(state, mesh)
    step = make_cp_train_step(loss_fn, mesh=mesh)
    batch = shard_lm_batch(tokens, mesh)
    state, metrics = step(state, batch, jax.random.PRNGKey(0))

    assert float(metrics["loss"]) == pytest.approx(float(loss_ref), rel=1e-5)
    for a, b in zip(
        jax.tree.leaves(state.params), jax.tree.leaves(params_ref)
    ):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=1e-5)


def test_cp_global_seq_len_guard(devices):
    """The max_seq_len bound must be checked against the GLOBAL length
    under CP: 16 local x 8 shards = 128 > 64 must raise instead of
    letting XLA clamp out-of-range RoPE/pos_embed lookups silently."""
    mesh = ddp.make_mesh(("seq",))
    cfg_cp = tiny_lm(max_seq_len=64, cp_axis="seq")
    model_cp = TransformerLM(cfg_cp)
    toks = jnp.zeros((1, 64), jnp.int32)  # 8 tokens/shard: global 64, fits
    params = TransformerLM(tiny_lm(max_seq_len=64)).init(
        jax.random.PRNGKey(0), toks
    )["params"]

    def apply_sharded(t):
        fn = jax.shard_map(
            lambda p, x: model_cp.apply({"params": p}, x),
            mesh=mesh,
            in_specs=(P(), P(None, "seq")),
            out_specs=P(None, "seq"),
            check_vma=False,
        )
        return jax.jit(fn)(params, t)

    apply_sharded(toks)  # global 64 == max_seq_len: fine
    with pytest.raises(ValueError, match="global seq len 128"):
        apply_sharded(jnp.zeros((1, 128), jnp.int32))  # 16/shard: global 128


def test_cp_accum_matches_plain_cp(devices):
    """CP × gradient accumulation: accumulating 2 microbatches must equal
    the single-step CP run on the same global batch (no_sync boundary
    semantics compose with sequence sharding)."""
    mesh = ddp.make_mesh(("data", "seq"), shape=(4, 2))
    cfg_cp = tiny_lm(max_seq_len=32, cp_axis="seq")
    model_cp = TransformerLM(cfg_cp)
    rng = np.random.default_rng(1)
    tokens = rng.integers(0, 256, size=(8, 33)).astype(np.int32)
    params = TransformerLM(tiny_lm(max_seq_len=32)).init(
        jax.random.PRNGKey(0), jnp.zeros((1, 32), jnp.int32)
    )["params"]

    def loss_fn(p, batch, rng):
        logits = model_cp.apply({"params": p}, batch["inputs"])
        return lm_cross_entropy(logits, batch["targets"]), {}

    def run(accum):
        state = ddp.TrainState.create(
            apply_fn=model_cp.apply, params=params, tx=optax.sgd(0.1)
        )
        state = ddp.broadcast_params(state, mesh)
        step = make_cp_train_step(
            loss_fn, mesh=mesh, accum_steps=accum, donate=False
        )
        state, metrics = step(
            state, shard_lm_batch(tokens, mesh), jax.random.PRNGKey(0)
        )
        return float(metrics["loss"]), state.params

    loss1, p1 = run(1)
    loss2, p2 = run(2)
    assert loss1 == pytest.approx(loss2, rel=1e-6)
    for a, b in zip(jax.tree.leaves(p1), jax.tree.leaves(p2)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=1e-6)


def test_cp_zero_matches_plain_cp(devices):
    """CP × ZeRO-1: the sharded-optimizer update under sequence sharding
    must reproduce the replicated CP step exactly (adam state included)."""
    mesh = ddp.make_mesh(("data", "seq"), shape=(4, 2))
    cfg_cp = tiny_lm(max_seq_len=32, cp_axis="seq")
    model_cp = TransformerLM(cfg_cp)
    rng = np.random.default_rng(2)
    tokens = [
        rng.integers(0, 256, size=(8, 33)).astype(np.int32) for _ in range(2)
    ]
    params = TransformerLM(tiny_lm(max_seq_len=32)).init(
        jax.random.PRNGKey(0), jnp.zeros((1, 32), jnp.int32)
    )["params"]
    tx = optax.adam(1e-2)

    def loss_fn(p, batch, rng):
        logits = model_cp.apply({"params": p}, batch["inputs"])
        return lm_cross_entropy(logits, batch["targets"]), {}

    # Replicated CP baseline, two steps.
    state = ddp.TrainState.create(
        apply_fn=model_cp.apply, params=params, tx=tx
    )
    state = ddp.broadcast_params(state, mesh)
    step = make_cp_train_step(loss_fn, mesh=mesh, donate=False)
    for t in tokens:
        state, _ = step(state, shard_lm_batch(t, mesh), jax.random.PRNGKey(0))

    # ZeRO-1 CP, same two steps.
    zstate = ddp.zero_state(
        apply_fn=model_cp.apply, params=ddp.broadcast_params(params, mesh),
        tx=tx, mesh=mesh,
    )
    zstep = make_cp_train_step(loss_fn, mesh=mesh, zero=True, donate=False)
    for t in tokens:
        zstate, _ = zstep(
            zstate, shard_lm_batch(t, mesh), jax.random.PRNGKey(0)
        )

    for a, b in zip(
        jax.tree.leaves(state.params), jax.tree.leaves(zstate.params)
    ):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), atol=2e-6
        )


# --- Flash-kernel ring (Pallas per kv-hop) ------------------------------


@pytest.mark.parametrize("n_ring", [2, 4])
def test_flash_ring_matches_xla_ring(n_ring, devices):
    """flash_ring_attention (Pallas kernel per kv-hop, logsumexp merge,
    ring-flash manual backward) == the XLA-einsum ring, forward AND
    gradients, across wrap-masked hops.  Interpret mode: the kernel math
    runs as plain jax on CPU."""
    from jax.sharding import Mesh

    from distributeddataparallel_tpu.parallel.context_parallel import (
        flash_ring_attention,
    )

    mesh = Mesh(np.array(jax.devices()[:n_ring]), ("seq",))
    B, S, H, D = 1, 128 * n_ring, 2, 32
    rng = np.random.default_rng(0)
    q = jnp.asarray(rng.standard_normal((B, S, H, D)), jnp.float32)
    k = jnp.asarray(rng.standard_normal((B, S, H, D)), jnp.float32)
    v = jnp.asarray(rng.standard_normal((B, S, H, D)), jnp.float32)
    weight = 1 + jnp.arange(q.size, dtype=jnp.float32).reshape(q.shape) % 7

    def run(fn):
        def loss(q, k, v, w):
            return jnp.sum(fn(q, k, v) * w)

        sharded = jax.shard_map(
            jax.value_and_grad(loss, argnums=(0, 1, 2)),
            mesh=mesh,
            in_specs=(P(None, "seq"),) * 4,
            out_specs=(P(), (P(None, "seq"),) * 3),
            check_vma=False,
        )
        return jax.jit(sharded)(q, k, v, weight)

    l_x, g_x = run(
        lambda q, k, v: ring_attention(q, k, v, axis_name="seq", impl="xla")
    )
    l_f, g_f = run(
        lambda q, k, v: flash_ring_attention(q, k, v, "seq", True)
    )
    assert float(l_f) == pytest.approx(float(l_x), rel=1e-5)
    for name, a, b in zip("qkv", g_x, g_f):
        np.testing.assert_allclose(
            np.asarray(b), np.asarray(a), atol=2e-4, err_msg=name
        )


def test_ring_impl_dispatch(devices):
    """impl='pallas' off-TPU/odd shapes raises; impl='xla' never touches
    the kernel; 'auto' silently stays on the XLA path on CPU."""
    from jax.sharding import Mesh

    mesh = Mesh(np.array(jax.devices()[:2]), ("seq",))
    q = jnp.zeros((1, 64, 2, 16))  # 32-per-shard: below any flash block

    def call(impl):
        f = jax.shard_map(
            lambda q: ring_attention(q, q, q, axis_name="seq", impl=impl),
            mesh=mesh, in_specs=P(None, "seq"), out_specs=P(None, "seq"),
            check_vma=False,
        )
        return jax.jit(f)(q)

    call("xla")
    call("auto")  # CPU -> supported() False -> XLA fallback
    with pytest.raises(ValueError, match="pallas ring"):
        call("pallas")
