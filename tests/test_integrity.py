"""SDC defense (training.integrity + the --integrity-every plumbing):
bit-pattern digests, majority-vote attribution, the 2-rank replay
tiebreak, chaos ``bitflip`` injection, checkpoint content-hash sidecars,
the torn-epoch rendezvous reader, and the closed-loop acceptance run —
a bit flip on rank 2 must be detected, voted out, evicted via elastic
resize (no restart budget, no checkpoint read), with the survivors'
final state bitwise-equal to an uncorrupted reference run."""

import json
import os
import pathlib
import subprocess
import sys
import threading

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest
from jax.sharding import NamedSharding, PartitionSpec as P

import distributeddataparallel_tpu as ddp
from distributeddataparallel_tpu.observability.alerts import (
    SdcStorm,
    parse_alert_spec,
)
from distributeddataparallel_tpu.observability.events import (
    EventLog,
    events_path,
    load_timeline,
)
from distributeddataparallel_tpu.runtime.elastic_gang import (
    reshard_live_state,
)
from distributeddataparallel_tpu.runtime.rendezvous import RendezvousStore
from distributeddataparallel_tpu.training import integrity as integ
from distributeddataparallel_tpu.training.checkpoint import (
    Checkpointer,
    state_content_hash,
)
from distributeddataparallel_tpu.training.state import TrainState
from distributeddataparallel_tpu.training.train_step import make_train_step
from distributeddataparallel_tpu.utils import chaos
from distributeddataparallel_tpu.utils.metrics import FaultCounters

REPO = pathlib.Path(__file__).resolve().parents[1]


def _loss(params, batch, rng):
    pred = batch["x"] @ params["w"] + params["b"]
    return jnp.mean((pred - batch["y"]) ** 2), {}


def _mk_state(mesh):
    params = {
        "w": jnp.arange(6, dtype=jnp.float32).reshape(3, 2) / 7.0,
        "b": jnp.zeros((2,), jnp.float32),
    }
    state = TrainState.create(
        apply_fn=None, params=params, tx=optax.adam(1e-2)
    )
    rep = NamedSharding(mesh, P())
    return jax.tree.map(lambda x: jax.device_put(x, rep), state)


def _mk_batch(mesh, rows=8):
    batch = {
        "x": jnp.ones((rows, 3), jnp.float32),
        "y": jnp.ones((rows, 2), jnp.float32),
    }
    sh = NamedSharding(mesh, P("data"))
    return jax.tree.map(lambda x: jax.device_put(x, sh), batch)


# -- digests -------------------------------------------------------------


def test_leaf_digest_bit_pattern_semantics():
    """The digest fingerprints BITS, not values: a single low-mantissa
    flip, a sign-of-zero change, or a different NaN payload all change
    it — exactly the corruptions value-level comparison would hide."""
    x = jnp.arange(16, dtype=jnp.float32) / 3.0
    d = integ.leaf_digest(x)
    assert d.dtype == jnp.uint32

    u = np.asarray(x).view(np.uint32).copy()
    u[5] ^= 1  # lowest mantissa bit
    flipped = jnp.asarray(u.view(np.float32))
    assert int(integ.leaf_digest(flipped)) != int(d)
    assert bool(jnp.all(jnp.isfinite(flipped)))  # invisible to nan-guard

    # odd count: an even number of sign bits cancels mod 2**32
    zeros = jnp.zeros((3,), jnp.float32)
    negzeros = -zeros
    assert np.array_equal(np.asarray(zeros), np.asarray(negzeros))
    assert int(integ.leaf_digest(zeros)) != int(integ.leaf_digest(negzeros))

    # bf16 and int leaves digest too (the opt-state count leaf is int).
    assert integ.leaf_digest(x.astype(jnp.bfloat16)).dtype == jnp.uint32
    assert int(integ.leaf_digest(jnp.asarray(7, jnp.int32))) == 7


def test_digest_parts_zero_levels():
    """ZeRO-1 shards the optimizer flats, so only params stay in the
    digest domain there; plain DP digests opt state too."""
    state = _mk_state(jax.make_mesh((2,), ("data",),
                                    devices=jax.devices()[:2]))
    full = integ.digest_parts(state, 0)
    z1 = integ.digest_parts(state, 1)
    assert "opt_state" in full and "opt_state" not in z1
    names = integ.digest_leaf_names(full)
    assert len(names) == len(jax.tree.leaves(full))
    assert any(n.startswith("params/") for n in names)


# -- attribution ---------------------------------------------------------


def test_vote_majority_and_ties():
    m = np.asarray([[1, 2], [1, 2], [1, 2], [1, 2]], np.uint32)
    assert integ.vote(m).ok

    bad = m.copy()
    bad[2, 1] = 99
    v = integ.vote(bad, ["params/w", "params/b"])
    assert (v.ok, v.corrupt, v.leaves, v.tie) == (
        False, (2,), ("params/b",), False
    )

    # rank 0 corrupt: the majority is rows 1..3, not "whatever row 0 says"
    bad0 = m.copy()
    bad0[0, 0] = 99
    assert integ.vote(bad0).corrupt == (0,)

    # 2-rank split and all-rows-distinct: no strict majority
    assert integ.vote(np.asarray([[1], [2]], np.uint32)).tie
    assert integ.vote(
        np.asarray([[1], [2], [3], [4]], np.uint32)
    ).tie


def test_apply_bitflip_diverges_exactly_one_rank():
    mesh = jax.make_mesh((4,), ("data",), devices=jax.devices()[:4])
    state = _mk_state(mesh)
    digest = integ.make_digest_fn(mesh)

    clean = np.asarray(jax.device_get(digest(state)))
    assert (clean == clean[0:1]).all()

    flipped = integ.apply_bitflip(state, rank=2, mesh=mesh, leaf="w")
    mat = np.asarray(jax.device_get(digest(flipped)))
    names = integ.digest_leaf_names(integ.digest_parts(state, 0))
    v = integ.vote(mat, names)
    assert v.corrupt == (2,)
    assert v.leaves == ("params/w",)
    # the flip is value-preservingly finite AND invisible off-rank
    others = [r for r in range(4) if r != 2]
    assert (mat[others] == clean[0]).all()

    with pytest.raises(ValueError, match="out of range"):
        integ.apply_bitflip(state, rank=9, mesh=mesh)
    with pytest.raises(ValueError, match="no param leaf"):
        integ.apply_bitflip(state, rank=1, mesh=mesh, leaf="nope")


def test_copy_tree_preserves_per_rank_divergence():
    """The arbiter's snapshots ride through ``copy_tree``; a copy that
    collapsed a divergent "replicated" buffer to shard 0 would make the
    replay tiebreak vacuous."""
    mesh = jax.make_mesh((4,), ("data",), devices=jax.devices()[:4])
    state = integ.apply_bitflip(_mk_state(mesh), rank=3, mesh=mesh)
    digest = integ.make_digest_fn(mesh)
    a = np.asarray(jax.device_get(digest(state)))
    b = np.asarray(jax.device_get(digest(integ.copy_tree(state))))
    assert np.array_equal(a, b)
    assert integ.vote(a).corrupt == (3,)


# -- the in-step digest + skip plumbing ----------------------------------


def test_train_step_detects_on_cadence_and_skips_update():
    mesh = jax.make_mesh((4,), ("data",), devices=jax.devices()[:4])
    state = _mk_state(mesh)
    batch = _mk_batch(mesh)
    rng = jax.random.PRNGKey(0)
    step = make_train_step(_loss, mesh=mesh, integrity_every=2)
    assert step.aot_signature["integrity_every"] == 2

    state, m = step(state, batch, rng)  # step 0: on cadence, clean
    assert float(m["sdc_mismatch"]) == 0.0
    state, m = step(state, batch, rng)  # step 1: off cadence
    assert float(m["sdc_mismatch"]) == 0.0
    assert not np.asarray(jax.device_get(m["sdc_digest"])).any()

    state = integ.apply_bitflip(state, rank=3, mesh=mesh, leaf="w")
    before = jax.device_get(state.params)
    state, m = step(state, batch, rng)  # step 2: on cadence, corrupt
    assert float(m["sdc_mismatch"]) == 1.0
    mat = np.asarray(jax.device_get(m["sdc_digest"]))
    names = integ.digest_leaf_names(integ.digest_parts(state, 0))
    assert integ.vote(mat, names).corrupt == (3,)
    # containment: the polluted update is discarded wholesale, only the
    # step counter advances (nonfinite-guard skip semantics)
    after = jax.device_get(state.params)
    assert all(
        np.array_equal(x, y)
        for x, y in zip(jax.tree.leaves(before), jax.tree.leaves(after))
    )
    assert int(jax.device_get(state.step)) == 3


def test_integrity_step_lints_clean():
    """GL001 stays exact: the digest all_gather is declared in the step's
    collective manifest, so the graph linter finds nothing."""
    from distributeddataparallel_tpu.analysis.graph_lint import (
        lint_train_step,
    )

    mesh = jax.make_mesh((4,), ("data",), devices=jax.devices()[:4])
    state = _mk_state(mesh)
    step = make_train_step(_loss, mesh=mesh, integrity_every=2)
    rep = lint_train_step(
        state=state,
        batch={"x": jnp.ones((8, 3)), "y": jnp.ones((8, 2))},
        rng=jax.random.PRNGKey(0),
        step=step,
    )
    assert rep.ok, rep.findings
    assert rep.collective_counts.get("data:all_gather") == 1


def test_train_step_rejects_bad_integrity_configs():
    mesh = jax.make_mesh((4,), ("data",), devices=jax.devices()[:4])
    with pytest.raises(ValueError, match="integrity"):
        make_train_step(_loss, mesh=mesh, integrity_every=0)
    with pytest.raises(ValueError, match="integrity"):
        make_train_step(_loss, mesh=mesh, integrity_every=2,
                        grad_sync=False)
    with pytest.raises(ValueError, match="integrity"):
        make_train_step(_loss, mesh=mesh, integrity_every=2, zero=2)


# -- 2-rank replay tiebreak ----------------------------------------------


def test_shadow_arbiter_breaks_two_rank_tie():
    mesh = jax.make_mesh((2,), ("data",), devices=jax.devices()[:2])
    state = _mk_state(mesh)
    batch = _mk_batch(mesh, rows=4)
    rng = jax.random.PRNGKey(1)
    step = make_train_step(_loss, mesh=mesh, donate=False)
    digest = integ.make_digest_fn(mesh)

    arb = integ.ShadowArbiter(step, digest)
    arb.commit(integ.copy_tree(state))
    arb.hold(batch, rng)

    live, _ = step(state, batch, rng)
    live = integ.apply_bitflip(live, rank=1, mesh=mesh, leaf="b")
    mat = np.asarray(jax.device_get(digest(live)))
    assert integ.vote(mat).tie  # 2 ranks: voting alone cannot attribute

    v = arb.resolve(mat)
    assert (v.ok, v.corrupt, v.method) == (False, (1,), "replay")

    # no snapshot committed yet -> stays an unresolved tie
    assert integ.ShadowArbiter(step, digest).resolve(mat).tie


def test_integrity_checker_events_and_counters(tmp_path):
    counters = FaultCounters()
    events = EventLog(events_path(str(tmp_path), 0), proc=0)
    chk = integ.IntegrityChecker(
        every=2, leaf_names=["params/w"], events=events, counters=counters
    )
    assert chk.due(0) and not chk.due(1) and chk.due(4)
    with pytest.raises(ValueError, match="cadence"):
        integ.IntegrityChecker(every=0)

    clean = np.asarray([[1], [1], [1]], np.uint32)
    assert chk.check(clean, step=0).ok
    bad = np.asarray([[1], [9], [1]], np.uint32)
    v = chk.check(bad, step=2)
    assert v.corrupt == (1,)
    chk.note_eviction(1, step=2)
    chk.note_shadow_mismatch(step=4)
    events.close()

    assert (counters.sdc_checks, counters.sdc_detects,
            counters.sdc_evictions) == (2, 2, 1)
    s = counters.summary()
    assert s["sdc_detects"] == 2 and s["sdc_evictions"] == 1

    recs = load_timeline(str(tmp_path))
    kinds = [r["kind"] for r in recs]
    assert kinds.count("sdc_check") == 2
    detects = [r for r in recs if r["kind"] == "sdc_detect"]
    assert [d["rank"] for d in detects] == [1, -1]
    assert detects[0]["leaves"] == ["params/w"]
    assert detects[1]["method"] == "shadow"
    evict = next(r for r in recs if r["kind"] == "sdc_evict")
    assert (evict["rank"], evict["step"]) == (1, 2)


# -- eviction repair path ------------------------------------------------


def test_reshard_live_state_source_avoids_corrupt_device():
    """``source=`` is the repair guarantee: after evicting rank 0, the
    survivors must re-replicate from a device voted healthy — the
    default (device 0) would copy the corruption forward."""
    old = jax.make_mesh((4,), ("data",), devices=jax.devices()[:4])
    new = jax.make_mesh((3,), ("data",),
                        devices=jax.devices()[1:4])
    clean = _mk_state(old)
    ref = np.asarray(jax.device_get(clean.params["w"]))
    corrupt = integ.apply_bitflip(clean, rank=0, mesh=old, leaf="w")

    healed = reshard_live_state(corrupt, old, new, source=2)
    assert np.array_equal(
        np.asarray(jax.device_get(healed.params["w"])), ref
    )
    # without source, device_get reads device 0 — the corrupt bytes
    poisoned = reshard_live_state(corrupt, old, new)
    assert not np.array_equal(
        np.asarray(jax.device_get(poisoned.params["w"])), ref
    )
    with pytest.raises(ValueError, match="source"):
        reshard_live_state(corrupt, old, new, source=7)


# -- chaos grammar (satellite: doc table + parse-time rejection) ---------


def test_chaos_bitflip_parse_accept():
    for spec, arg in (
        ("bitflip@6", None),
        ("bitflip@6:2", "2"),
        ("bitflip@6:2:Dense_0/kernel", "2:Dense_0/kernel"),
    ):
        (e,) = chaos.parse_chaos_spec(spec)
        assert (e.kind, e.step, e.arg) == ("bitflip", 6, arg)


@pytest.mark.parametrize("bad", [
    "bitflip@6:-1",     # negative rank
    "bitflip@6:r2",     # non-integer rank
    "bitflip@-1",       # negative step
    "bitflip@",         # missing step
    "bitflips@6",       # unknown kind
])
def test_chaos_bitflip_parse_reject_names_grammar(bad):
    """Every rejection must print the FULL grammar, bitflip row
    included — the error message is the spec's discoverability."""
    with pytest.raises(ValueError) as ei:
        chaos.parse_chaos_spec(bad)
    msg = str(ei.value)
    assert "bitflip@S[:R][:leaf]" in msg
    for kind in chaos.KINDS:
        assert kind in msg


def test_chaos_doc_table_lists_every_kind():
    """The module docstring's grammar table and the README chaos spec
    both enumerate KINDS exactly — a kind added to the parser but not
    the docs (or vice versa) fails here, not in a user's terminal."""
    doc = chaos.__doc__
    readme = (REPO / "README.md").read_text()
    for kind in chaos.KINDS:
        assert f"{kind}@" in doc, f"{kind} missing from chaos docstring"
        assert f"{kind}@" in readme, f"{kind} missing from README"
    assert "bitflip@S[:R][:leaf]" in doc


def test_chaos_corrupt_state_without_mesh_warns_not_crashes():
    inj = chaos.FaultInjector("bitflip@0")
    state = object()
    assert inj.corrupt_state(state, 0, mesh=None) is state


# -- alerting ------------------------------------------------------------


def test_sdc_storm_rule():
    rule = SdcStorm(max_detects=2)
    assert rule.evaluate({}) is None  # integrity not wired: no signal
    fired, _, detail = rule.evaluate({"sdc_detects": 1})
    assert not fired
    fired, refires, detail = rule.evaluate({"sdc_detects": 2})
    assert fired and not refires and detail["threshold"] == 2
    with pytest.raises(ValueError, match=">= 1"):
        SdcStorm(0)
    rules = parse_alert_spec("sdc_storm=3")
    storm = next(r for r in rules if r.name == "sdc_storm")
    assert storm.max_detects == 3


# -- checkpoint content-hash sidecar (satellite) -------------------------


def test_checkpoint_hash_sidecar_roundtrip_and_corruption(tmp_path):
    mesh = jax.make_mesh((2,), ("data",), devices=jax.devices()[:2])
    state = _mk_state(mesh)

    ck = Checkpointer(str(tmp_path))
    ck.save(state, 0)
    ck.wait()
    assert (tmp_path / "hash_0.json").exists()
    saved = ck.read_hash(0)
    assert saved == state_content_hash(state)

    # clean roundtrip verifies
    restored, nxt = ck.restore_latest(state)
    assert nxt == 1

    # corrupted-but-parseable bytes: flip the recorded hash (equivalent
    # to flipping array bytes — the comparison is symmetric) and the
    # same restore becomes a loud ValueError
    with open(tmp_path / "hash_0.json", "w") as fh:
        json.dump({"sha256": "0" * 64}, fh)
    with pytest.raises(ValueError, match="content-hash"):
        ck.restore_latest(state)

    # legacy checkpoint (no sidecar): restores unverified
    os.remove(tmp_path / "hash_0.json")
    assert ck.read_hash(0) is None
    _, nxt = ck.restore_latest(state)
    assert nxt == 1


def test_resilient_restore_quarantines_hash_mismatch(tmp_path):
    """A hash-mismatched step behaves like any corrupt checkpoint:
    quarantined, and the next older verified step wins."""
    from distributeddataparallel_tpu.training.fault_tolerance import (
        ResilientCheckpointer,
    )

    mesh = jax.make_mesh((2,), ("data",), devices=jax.devices()[:2])
    s0 = _mk_state(mesh)
    s1 = s0.replace(params=jax.tree.map(lambda x: x + 1.0, s0.params))

    ck = ResilientCheckpointer(str(tmp_path))
    ck.save(s0, 0)
    ck.save(s1, 1)
    ck.wait()
    with open(tmp_path / "hash_1.json", "w") as fh:
        json.dump({"sha256": "f" * 64}, fh)

    restored, nxt = ck.restore_latest(s0)
    assert nxt == 1  # fell back to step 0
    assert np.array_equal(
        np.asarray(jax.device_get(restored.params["b"])),
        np.asarray(jax.device_get(s0.params["b"])),
    )
    assert any(p.name.endswith(".corrupt") for p in tmp_path.iterdir())


# -- rendezvous torn-write reader (satellite) ----------------------------


def test_rendezvous_epoch_missing_vs_torn(tmp_path):
    store = RendezvousStore(str(tmp_path))
    # missing file genuinely means "no transition yet"
    assert store.epoch() == {"epoch": -1, "roster": []}

    # transiently torn record: a concurrent atomic replace lands while
    # the reader is retrying — the reader must return the fixed record
    path = tmp_path / "epoch.json"
    path.write_text('{"epoch": 3, "roster": ["w0"')  # truncated write

    def fix():
        rec = {"epoch": 3, "roster": ["w0"]}
        tmp = tmp_path / ".epoch.tmp"
        tmp.write_text(json.dumps(rec))
        os.replace(tmp, path)

    t = threading.Timer(0.08, fix)
    t.start()
    try:
        assert store.epoch()["epoch"] == 3
    finally:
        t.join()

    # persistently torn: a bounded retry, then a LOUD error — never a
    # silent reset to epoch -1 (that forks membership history)
    path.write_text('{"epoch": 4, "roster": ["w0"')
    with pytest.raises(RuntimeError, match="torn or corrupt"):
        store.epoch()


# -- CLI validation ------------------------------------------------------


def _run_dpp(args, timeout=300):
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    env.pop("_DDP_SUPERVISED", None)
    env.pop("DDP_ELASTIC_WORLD", None)
    return subprocess.run(
        [sys.executable, str(REPO / "dpp.py"), *args],
        capture_output=True, text=True, timeout=timeout, env=env,
        cwd=str(REPO),
    )


def test_cli_shadow_requires_cadence():
    r = _run_dpp(["--model", "mlp", "--integrity-shadow"])
    assert r.returncode != 0
    assert "--integrity-every" in (r.stdout + r.stderr)


def test_cli_integrity_rejects_sharded_state():
    r = _run_dpp(["--model", "mlp", "--integrity-every", "2",
                  "--zero", "2"])
    assert r.returncode != 0
    assert "--zero 1" in (r.stdout + r.stderr)


# -- the closed-loop acceptance run --------------------------------------


def test_bitflip_detect_evict_matches_clean_run(tmp_path):
    """ISSUE 14's acceptance bar, end to end: run A takes a silent bit
    flip on rank 2 at step 6; the digest (cadence 2) catches it at the
    very next check, the vote names rank 2, the gang resizes 8 -> 7 with
    no restart budget and no checkpoint read, and training finishes.

    Run B is the uncorrupted control at the same shrunk size: the SAME
    program (same flags, so identical compiled step) skips step 6 via
    the nan-guard and loses rank 2 to a plain worker-kill at the same
    poll.  Both runs therefore execute identical updates on identical
    data — so their final checkpoints must be BITWISE equal, which the
    content-hash sidecars prove without touching an array file.
    """
    common = [
        "--model", "mlp", "--fake-devices", "8", "--batch-size", "4",
        "--epochs", "1", "--steps-per-epoch", "10",
        "--elastic", "--integrity-every", "2", "--nan-guard",
    ]
    out = {}
    for name, spec in (
        ("flip", "bitflip@6:2"),
        ("clean", "nan-grad@6,worker-kill@6:2"),
    ):
        ev = tmp_path / f"ev_{name}"
        ck = tmp_path / f"ck_{name}"
        r = _run_dpp(common + [
            "--chaos", spec,
            "--events-dir", str(ev), "--checkpoint-dir", str(ck),
        ])
        assert r.returncode == 0, (r.stdout + r.stderr)[-2000:]
        out[name] = (r.stdout + r.stderr, load_timeline(str(ev)), ck)

    log, recs, _ = out["flip"]
    kinds = [r.get("kind") for r in recs]
    # detection within one cadence window, attribution names rank 2
    detect = next(r for r in recs if r.get("kind") == "sdc_detect")
    assert detect["rank"] == 2 and detect["step"] == 6
    assert detect["method"] == "vote" and not detect["tie"]
    evict = next(r for r in recs if r.get("kind") == "sdc_evict")
    assert evict["rank"] == 2
    # repair is an elastic resize, not a restart — and no checkpoint
    # was read before it landed
    assert kinds.count("gang_resize") == 1, kinds
    assert "restart_attempt" not in kinds, kinds
    resize = next(r for r in recs if r.get("kind") == "gang_resize")
    assert (resize["old_size"], resize["new_size"]) == (8, 7)
    assert resize["left"] == ["proc2"]
    t_resize = resize["ts"]
    assert not any(
        r.get("kind") == "span" and "ckpt" in str(r.get("name"))
        and r["ts"] <= t_resize for r in recs
    )
    assert "no checkpoint read" in log

    # bitwise parity with the uncorrupted control at the shrunk size
    def final_hash(ck):
        steps = sorted(
            int(p.name[len("hash_"):-5])
            for p in ck.iterdir() if p.name.startswith("hash_")
        )
        assert steps, f"no hash sidecar in {ck}"
        with open(ck / f"hash_{steps[-1]}.json") as fh:
            return json.load(fh)["sha256"]

    assert final_hash(out["flip"][2]) == final_hash(out["clean"][2])
    # the control really did shrink the same way (same survivors)
    clean_recs = out["clean"][1]
    c_resize = next(
        r for r in clean_recs if r.get("kind") == "gang_resize"
    )
    assert c_resize["left"] == ["proc2"]
    assert not any(
        r.get("kind") == "sdc_detect" for r in clean_recs
    )
