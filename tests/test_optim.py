"""Optimizer/schedule surface: build_optimizer's warmup + decay math,
and the CLI paths (adamw + warmup-cosine, schedules under ZeRO)."""

import sys

import numpy as np

sys.path.insert(0, "/root/repo")

import dpp  # noqa: E402


def _args(extra):
    return dpp.parse_args(
        ["--device", "cpu", "--num-examples", "64", "--batch-size", "4",
         "--log-every", "1000"] + extra
    )


def _lr_trace(tx, steps, lr0=1.0):
    """Realized per-step LR of a transformation: apply to a unit gradient
    and read back the (negated) update."""
    import jax.numpy as jnp

    params = {"w": jnp.ones(())}
    state = tx.init(params)
    out = []
    for _ in range(steps):
        updates, state = tx.update({"w": jnp.ones(())}, state, params)
        out.append(-float(updates["w"]))
    return np.asarray(out)


def test_warmup_cosine_shape(devices):
    args = _args(
        ["--optimizer", "sgd", "--lr", "0.1", "--lr-schedule", "cosine",
         "--warmup-steps", "4", "--min-lr", "0.01"]
    )
    tx = dpp.build_optimizer(args, total_steps=12)
    lr = _lr_trace(tx, 13)
    # Linear warmup 0 -> peak over 4 steps, then cosine down to min_lr
    # (reached at total_steps, i.e. step index 12).
    assert lr[0] == 0.0
    np.testing.assert_allclose(lr[4], 0.1, rtol=1e-6)
    assert all(np.diff(lr[:5]) > 0)
    assert all(np.diff(lr[4:]) < 0)
    np.testing.assert_allclose(lr[12], 0.01, rtol=1e-5)


def test_linear_decay_floor(devices):
    args = _args(
        ["--lr", "0.2", "--lr-schedule", "linear", "--min-lr", "0.05"]
    )
    tx = dpp.build_optimizer(args, total_steps=10)
    lr = _lr_trace(tx, 12)
    np.testing.assert_allclose(lr[0], 0.2, rtol=1e-6)
    np.testing.assert_allclose(lr[10], 0.05, rtol=1e-6)
    np.testing.assert_allclose(lr[11], 0.05, rtol=1e-6)  # clamped past end


def test_constant_default_matches_reference(devices):
    # ref dpp.py:41: plain SGD, fixed lr.
    args = _args(["--lr", "0.01"])
    tx = dpp.build_optimizer(args, total_steps=100)
    lr = _lr_trace(tx, 3)
    np.testing.assert_allclose(lr, 0.01, rtol=1e-6)


def test_entrypoint_adamw_warmup_cosine(devices):
    loss = dpp.train(_args(
        ["--model", "mlp", "--epochs", "1", "--optimizer", "adamw",
         "--weight-decay", "0.01", "--lr", "0.003",
         "--lr-schedule", "cosine", "--warmup-steps", "4",
         "--fake-devices", "8"]
    ))
    assert loss == loss  # not NaN


def test_entrypoint_zero_with_schedule(devices):
    """Schedule state (a scalar count) rides the ZeRO flat-chunk update."""
    loss = dpp.train(_args(
        ["--model", "mlp", "--epochs", "1", "--optimizer", "adam",
         "--lr", "0.003", "--lr-schedule", "cosine", "--warmup-steps", "2",
         "--zero", "--fake-devices", "8"]
    ))
    assert loss == loss
