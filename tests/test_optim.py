"""Optimizer/schedule surface: build_optimizer's warmup + decay math,
and the CLI paths (adamw + warmup-cosine, schedules under ZeRO)."""

import sys

import numpy as np

sys.path.insert(0, "/root/repo")

import dpp  # noqa: E402


def _args(extra):
    return dpp.parse_args(
        ["--device", "cpu", "--num-examples", "64", "--batch-size", "4",
         "--log-every", "1000"] + extra
    )


def _lr_trace(tx, steps, lr0=1.0):
    """Realized per-step LR of a transformation: apply to a unit gradient
    and read back the (negated) update."""
    import jax.numpy as jnp

    params = {"w": jnp.ones(())}
    state = tx.init(params)
    out = []
    for _ in range(steps):
        updates, state = tx.update({"w": jnp.ones(())}, state, params)
        out.append(-float(updates["w"]))
    return np.asarray(out)


def test_warmup_cosine_shape(devices):
    args = _args(
        ["--optimizer", "sgd", "--lr", "0.1", "--lr-schedule", "cosine",
         "--warmup-steps", "4", "--min-lr", "0.01"]
    )
    tx = dpp.build_optimizer(args, total_steps=12)
    lr = _lr_trace(tx, 13)
    # Linear warmup 0 -> peak over 4 steps, then cosine down to min_lr
    # (reached at total_steps, i.e. step index 12).
    assert lr[0] == 0.0
    np.testing.assert_allclose(lr[4], 0.1, rtol=1e-6)
    assert all(np.diff(lr[:5]) > 0)
    assert all(np.diff(lr[4:]) < 0)
    np.testing.assert_allclose(lr[12], 0.01, rtol=1e-5)


def test_linear_decay_floor(devices):
    args = _args(
        ["--lr", "0.2", "--lr-schedule", "linear", "--min-lr", "0.05"]
    )
    tx = dpp.build_optimizer(args, total_steps=10)
    lr = _lr_trace(tx, 12)
    np.testing.assert_allclose(lr[0], 0.2, rtol=1e-6)
    np.testing.assert_allclose(lr[10], 0.05, rtol=1e-6)
    np.testing.assert_allclose(lr[11], 0.05, rtol=1e-6)  # clamped past end


def test_constant_default_matches_reference(devices):
    # ref dpp.py:41: plain SGD, fixed lr.
    args = _args(["--lr", "0.01"])
    tx = dpp.build_optimizer(args, total_steps=100)
    lr = _lr_trace(tx, 3)
    np.testing.assert_allclose(lr, 0.01, rtol=1e-6)


def test_entrypoint_adamw_warmup_cosine(devices):
    loss = dpp.train(_args(
        ["--model", "mlp", "--epochs", "1", "--optimizer", "adamw",
         "--weight-decay", "0.01", "--lr", "0.003",
         "--lr-schedule", "cosine", "--warmup-steps", "4",
         "--fake-devices", "8"]
    ))
    assert loss == loss  # not NaN


def test_entrypoint_zero_with_schedule(devices):
    """Schedule state (a scalar count) rides the ZeRO flat-chunk update."""
    loss = dpp.train(_args(
        ["--model", "mlp", "--epochs", "1", "--optimizer", "adam",
         "--lr", "0.003", "--lr-schedule", "cosine", "--warmup-steps", "2",
         "--zero", "--fake-devices", "8"]
    ))
    assert loss == loss


def test_grad_clip_matches_manual(devices):
    """DP grad_clip == manually clipping the full-batch gradient before
    the update (torch clip_grad_norm_ semantics)."""
    import jax
    import jax.numpy as jnp
    import optax

    import distributeddataparallel_tpu as ddp
    from distributeddataparallel_tpu.data.loader import shard_batch
    from distributeddataparallel_tpu.models import TinyMLP
    from distributeddataparallel_tpu.ops import cross_entropy_loss

    mesh = ddp.make_mesh(("data",))
    model = TinyMLP(features=(16,))
    x = np.random.default_rng(0).normal(size=(8, 8, 8, 1)).astype(np.float32)
    y = np.random.default_rng(1).integers(0, 10, size=(8,)).astype(np.int32)
    params = model.init(jax.random.PRNGKey(0), jnp.zeros((1, 8, 8, 1)))["params"]
    tx = optax.sgd(0.5)
    CLIP = 0.05  # far below the actual norm so clipping certainly bites

    def ref_loss(p):
        return cross_entropy_loss(
            model.apply({"params": p}, jnp.asarray(x)), jnp.asarray(y)
        )

    g = jax.grad(ref_loss)(params)
    gnorm = float(optax.global_norm(g))
    assert gnorm > CLIP
    g = jax.tree.map(lambda t: t * CLIP / gnorm, g)
    up, _ = tx.update(g, tx.init(params), params)
    ref_p = optax.apply_updates(params, up)

    def loss_fn(p, b, r):
        return cross_entropy_loss(
            model.apply({"params": p}, b["image"]), b["label"]
        ), {}

    state = ddp.TrainState.create(apply_fn=model.apply, params=params, tx=tx)
    state = ddp.broadcast_params(state, mesh)
    step = ddp.make_train_step(loss_fn, mesh=mesh, grad_clip=CLIP)
    state, _ = step(
        state, shard_batch({"image": x, "label": y}, mesh),
        jax.random.PRNGKey(0),
    )
    for a, b in zip(jax.tree.leaves(state.params), jax.tree.leaves(ref_p)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=1e-6)


def test_grad_clip_zero_matches_replicated(devices):
    """ZeRO's psum-exact chunk-norm clip == the replicated-path clip."""
    import jax
    import jax.numpy as jnp
    import optax

    import distributeddataparallel_tpu as ddp
    from distributeddataparallel_tpu.data.loader import shard_batch
    from distributeddataparallel_tpu.models import TinyMLP
    from distributeddataparallel_tpu.ops import cross_entropy_loss

    mesh = ddp.make_mesh(("data",))
    model = TinyMLP(features=(16,))
    x = np.random.default_rng(2).normal(size=(8, 8, 8, 1)).astype(np.float32)
    y = np.random.default_rng(3).integers(0, 10, size=(8,)).astype(np.int32)
    params = model.init(jax.random.PRNGKey(0), jnp.zeros((1, 8, 8, 1)))["params"]
    tx = optax.adam(1e-2)
    batch = shard_batch({"image": x, "label": y}, mesh)

    def loss_fn(p, b, r):
        return cross_entropy_loss(
            model.apply({"params": p}, b["image"]), b["label"]
        ), {}

    state = ddp.TrainState.create(apply_fn=model.apply, params=params, tx=tx)
    state = ddp.broadcast_params(state, mesh)
    step = ddp.make_train_step(
        loss_fn, mesh=mesh, grad_clip=0.05, donate=False
    )
    state, _ = step(state, batch, jax.random.PRNGKey(0))

    zstate = ddp.zero_state(
        apply_fn=model.apply,
        params=ddp.broadcast_params(params, mesh), tx=tx, mesh=mesh,
    )
    zstep = ddp.make_train_step(
        loss_fn, mesh=mesh, zero=True, grad_clip=0.05, donate=False
    )
    zstate, _ = zstep(zstate, batch, jax.random.PRNGKey(0))

    for a, b in zip(
        jax.tree.leaves(state.params), jax.tree.leaves(zstate.params)
    ):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=2e-6)


def test_grad_clip_fsdp_matches_replicated(devices):
    """FSDP's sharded-flat clip == the replicated-path clip."""
    import jax
    import jax.numpy as jnp
    import optax

    import distributeddataparallel_tpu as ddp
    from distributeddataparallel_tpu.data.loader import shard_batch
    from distributeddataparallel_tpu.models import TransformerLM, tiny_lm
    from distributeddataparallel_tpu.ops import lm_cross_entropy

    cfg = tiny_lm(
        num_layers=2, num_heads=2, d_model=32, d_ff=64, max_seq_len=32,
        scan_layers=True,
    )
    mesh = ddp.make_mesh(("data",))
    model = TransformerLM(cfg)
    tokens = np.random.default_rng(4).integers(
        0, 256, size=(8, 17)
    ).astype(np.int32)
    params = model.init(
        jax.random.PRNGKey(0), jnp.zeros((1, 16), jnp.int32)
    )["params"]
    tx = optax.sgd(0.5)
    batch = shard_batch({"tokens": tokens}, mesh)

    def loss_fn(p, b, r):
        toks = b["tokens"]
        logits = model.apply({"params": p}, toks[:, :-1])
        return lm_cross_entropy(logits, toks[:, 1:]), {}

    state = ddp.TrainState.create(apply_fn=model.apply, params=params, tx=tx)
    state = ddp.broadcast_params(state, mesh)
    step = ddp.make_train_step(
        loss_fn, mesh=mesh, grad_clip=0.01, donate=False
    )
    state, _ = step(state, batch, jax.random.PRNGKey(0))

    fstate = ddp.fsdp_state(cfg, params, tx, mesh)
    fstep = ddp.make_fsdp_train_step(
        cfg, mesh=mesh, grad_clip=0.01, donate=False
    )
    fstate, _ = fstep(fstate, batch, jax.random.PRNGKey(0))
    got = ddp.fsdp_gather_params(cfg, fstate, mesh)

    for a, b in zip(jax.tree.leaves(state.params), jax.tree.leaves(got)):
        np.testing.assert_allclose(np.asarray(b), np.asarray(a), atol=1e-5)
