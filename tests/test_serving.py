"""Serving subsystem tests: paged KV cache, continuous batching, replay.

The load-bearing contracts:

- ``BlockAllocator`` keeps the partition invariant (every non-scratch
  block in exactly one of free / live / retired) through alloc, extend,
  release, retire and LRU reclaim;
- the paged pool round-trip (gather -> decode twin -> scatter) is
  BITWISE identical to dense-cache greedy ``generate()`` for both the
  unrolled and scanned layer layouts — including a prompt whose length
  is an exact multiple of ``block_size`` (the ctx_len+1 admission
  case) and under pool pressure (preemption + LRU eviction);
- the scheduler bounds prefill per step without starving running
  decodes, and preemption requeues at the FRONT of the waiting queue;
- a seeded loadgen trace under a ``VirtualClock`` replays to an
  identical run (tokens, events, summary) — serving runs are a pure
  function of (seed, config);
- a serving events dir yields a schema-valid timeline, a structurally
  valid Perfetto trace, and a populated ddp_report Serving section.
"""

import os
import sys

import jax
import jax.numpy as jnp
import numpy as np
import pytest

sys.path.insert(0, "/root/repo")
sys.path.insert(0, os.path.join("/root/repo", "scripts"))

from distributeddataparallel_tpu.models import TransformerLM, generate, tiny_lm
from distributeddataparallel_tpu.serving import (
    SCRATCH_BLOCK,
    BlockAllocator,
    EngineConfig,
    InferenceEngine,
    LoadConfig,
    Request,
    Scheduler,
    VirtualClock,
    gather_block_cache,
    kv_pool_bytes,
    make_pool,
    make_trace,
    run_load,
)


def _unrolled(**over):
    base = dict(
        vocab_size=97, num_layers=2, num_heads=2, d_model=32, d_ff=64,
        max_seq_len=32, positional="learned", norm="layernorm",
        activation="gelu", tie_embeddings=True,
    )
    base.update(over)
    return tiny_lm(**base)


def _scanned(**over):
    base = dict(
        vocab_size=97, num_layers=2, num_heads=4, num_kv_heads=2,
        d_model=32, d_ff=64, max_seq_len=32, scan_layers=True,
        tie_embeddings=False,
    )
    base.update(over)
    return tiny_lm(**base)


def _model(cfg_fn, seed=0):
    cfg = cfg_fn()
    model = TransformerLM(cfg)
    params = model.init(
        jax.random.PRNGKey(seed), jnp.zeros((1, 4), jnp.int32)
    )["params"]
    return model, params


def _prompt(rng, n, vocab=97):
    return rng.integers(0, vocab, n, dtype=np.int32)


# ---------------------------------------------------------------------
# BlockAllocator
# ---------------------------------------------------------------------

def test_allocator_partition_invariant_through_lifecycle():
    a = BlockAllocator(num_blocks=8, block_size=4)
    a.check()
    assert a.free_blocks == 7  # block 0 is reserved scratch

    a.alloc("a", 7)   # 2 blocks
    a.alloc("b", 9)   # 3 blocks
    a.check()
    assert a.live_blocks == 5 and a.free_blocks == 2
    assert a.blocks_for(1) == 1 and a.blocks_for(4) == 1
    assert a.blocks_for(5) == 2

    a.extend("a", 12)  # 2 -> 3 blocks
    a.check()
    assert len(a.table_of("a")) == 3 and a.free_blocks == 1

    # Preemption path: immediate return to the free list.
    assert a.release("a") == 3
    a.check()
    assert a.free_blocks == 4 and "a" not in a._tables

    # Completion path: retired blocks are evictable, not free.
    assert a.retire("b") == 3
    a.check()
    assert a.free_blocks == 4 and a.evictable_blocks == 3
    assert a.evictions == 0  # parking is not evicting


def test_allocator_exhaustion_and_lru_reclaim_order():
    a = BlockAllocator(num_blocks=6, block_size=4)  # 5 allocatable
    a.alloc("r0", 8)   # 2 blocks
    a.alloc("r1", 8)   # 2 blocks
    assert not a.can_alloc(8)  # only 1 free
    with pytest.raises(RuntimeError, match="pool exhausted"):
        a.alloc("r2", 8)
    a.check()

    # Retire r0 first, then r1: LRU reclaim must hit r0 first.
    a.retire("r0")
    a.retire("r1")
    assert a.can_alloc(8)
    evicted = a.alloc("r2", 8)
    assert [rid for rid, _ in evicted] == ["r0"]
    assert a.evictions == 1 and a.evicted_blocks == 2
    a.check()

    # A bigger ask sweeps the remaining retiree too.
    a.retire("r2")
    evicted = a.alloc("r3", 17)  # 5 blocks: needs everything
    assert [rid for rid, _ in evicted] == ["r1", "r2"]
    a.check()


def test_allocator_table_array_pads_with_scratch():
    a = BlockAllocator(num_blocks=8, block_size=4)
    a.alloc("a", 6)  # 2 blocks
    t = a.table_array("a", blocks_per_seq=4)
    assert t.dtype == np.int32 and t.shape == (4,)
    assert tuple(t[:2]) == a.table_of("a")
    assert (t[2:] == SCRATCH_BLOCK).all()
    with pytest.raises(ValueError, match="exceeds"):
        a.table_array("a", blocks_per_seq=1)


# ---------------------------------------------------------------------
# Pool gather/scatter layout
# ---------------------------------------------------------------------

@pytest.mark.parametrize("cfg_fn", [_unrolled, _scanned],
                         ids=["unrolled", "scanned"])
def test_gather_block_cache_reassembles_pool_rows(cfg_fn, devices):
    """gather through a block table must lay pool rows out contiguously
    in sequence order, for both the 4-d and 5-d (scanned) pool leaves."""
    model, _ = _model(cfg_fn)
    pool = make_pool(model, num_blocks=6, block_size=4)
    # Fill every pool row with a distinct fingerprint value.
    pool = jax.tree.map(
        lambda leaf: jnp.arange(leaf.size, dtype=leaf.dtype).reshape(
            leaf.shape
        ),
        pool,
    )
    tables = jnp.asarray([[3, 1, 0, 0], [2, 4, 5, 0]], jnp.int32)
    dense = gather_block_cache(pool, tables, dtype=model.cfg.dtype)

    def expect(leaf):
        if leaf.ndim == 4:  # (N, bs, H, D) -> (B, S, H, D)
            g = leaf[tables]
            return g.reshape(2, 4 * 4, *leaf.shape[2:])
        g = jnp.take(leaf, tables, axis=1)
        return g.reshape(leaf.shape[0], 2, 4 * 4, *leaf.shape[3:])

    for got, want in zip(jax.tree.leaves(dense),
                         jax.tree.leaves(jax.tree.map(expect, pool))):
        np.testing.assert_array_equal(np.asarray(got), np.asarray(want))


# ---------------------------------------------------------------------
# Engine vs generate(): bitwise greedy parity
# ---------------------------------------------------------------------

@pytest.mark.parametrize("cfg_fn", [_unrolled, _scanned],
                         ids=["unrolled", "scanned"])
def test_engine_matches_generate_greedy(cfg_fn, devices):
    """Continuous batching must be invisible: every request's greedy
    continuation is bit-identical to static-batch generate().  Prompt
    lengths include exact block-size multiples (8, 16 with block_size
    8) — the case where admission must allocate ctx_len + 1 or the
    first decode row spills to scratch."""
    model, params = _model(cfg_fn)
    engine = InferenceEngine(
        model, params,
        EngineConfig(num_slots=4, num_blocks=16, block_size=8,
                     prefill_chunk=8),
    )
    rng = np.random.default_rng(3)
    # Each DISTINCT (plen, n_new) pair compiles its own generate()
    # reference — keep the list short but include both block-exact
    # prompt lengths (8, 16) and a repeated shape (cache hit).
    cases = [(3, 6), (8, 7), (16, 9), (8, 7)]
    rids = {}
    for plen, n_new in cases:
        p = _prompt(rng, plen)
        rids[engine.submit(p, n_new)] = (p, n_new)
    engine.run()
    assert len(engine.completed) == len(cases)
    for rid, (p, n_new) in rids.items():
        want = np.asarray(
            generate(model, params, jnp.asarray(p)[None], n_new)
        )[0]
        np.testing.assert_array_equal(engine.output_tokens(rid), want)


def test_engine_parity_under_pool_pressure(devices):
    """A pool too small to hold every sequence forces LRU evictions and
    recompute preemptions mid-flight; outputs must STILL be bit-exact
    vs generate() — preemption re-prefills prompt + generated-so-far
    and resumes, it never corrupts a continuation."""
    model, params = _model(_unrolled)
    engine = InferenceEngine(
        model, params,
        EngineConfig(num_slots=4, num_blocks=8, block_size=4,
                     prefill_chunk=8),
    )
    rng = np.random.default_rng(11)
    # Repeated shapes: only two generate() reference compiles, but six
    # in-flight sequences against a 7-block pool — guaranteed pressure.
    cases = [(4, 12), (7, 11), (4, 12), (7, 11), (4, 12), (7, 11)]
    rids = {}
    for plen, n_new in cases:
        p = _prompt(rng, plen)
        rids[engine.submit(p, n_new)] = (p, n_new)
    while engine.has_work():
        engine.step()
        engine.allocator.check()  # partition invariant every step
    stats = {
        "evictions": engine.allocator.evictions,
        "preemptions": sum(
            r.preemptions for r in engine.completed.values()
        ),
    }
    # The point of the test is pressure: something must have given.
    assert stats["evictions"] + stats["preemptions"] > 0, stats
    for rid, (p, n_new) in rids.items():
        want = np.asarray(
            generate(model, params, jnp.asarray(p)[None], n_new)
        )[0]
        np.testing.assert_array_equal(engine.output_tokens(rid), want)


def test_engine_int8_kv_completes(devices):
    """int8-KV pool: engine drains, outputs have the right shape and
    stay in-vocab (parity is approximate by construction — the exact
    per-row quantization contract lives in the kv_cache unit tests)."""
    model, params = _model(_unrolled)
    engine = InferenceEngine(
        model, params,
        EngineConfig(num_slots=2, num_blocks=16, block_size=8,
                     prefill_chunk=8, quantized_kv=True),
    )
    rng = np.random.default_rng(5)
    p = _prompt(rng, 6)
    rid = engine.submit(p, 8)
    engine.run()
    out = engine.output_tokens(rid)
    assert out.shape == (14,)
    assert (out[:6] == p).all()
    assert ((0 <= out) & (out < 97)).all()


# ---------------------------------------------------------------------
# Scheduler
# ---------------------------------------------------------------------

def test_scheduler_submit_rejects_impossible_requests():
    s = Scheduler(BlockAllocator(8, 4), num_slots=2, prefill_chunk=8,
                  max_seq_len=32)
    with pytest.raises(ValueError, match="exceeds max_seq_len"):
        s.submit(Request(rid=0, prompt=np.zeros(30, np.int32),
                         max_new_tokens=8))
    s2 = Scheduler(BlockAllocator(4, 4), num_slots=2, prefill_chunk=8,
                   max_seq_len=64)
    with pytest.raises(ValueError, match="never be admitted"):
        s2.submit(Request(rid=1, prompt=np.zeros(20, np.int32),
                          max_new_tokens=8))


def test_scheduler_chunked_prefill_does_not_starve_decodes():
    """With max_prefill_chunks_per_step=1, a long prompt prefills one
    chunk per plan while the already-running slot decodes EVERY plan."""
    alloc = BlockAllocator(num_blocks=16, block_size=4)
    s = Scheduler(alloc, num_slots=2, prefill_chunk=8, max_seq_len=64,
                  max_prefill_chunks_per_step=1)
    short = Request(rid=0, prompt=np.zeros(4, np.int32),
                    max_new_tokens=32)
    s.submit(short)
    plan = s.plan_step()
    assert plan.admitted == [short]
    assert plan.prefill_chunks == [(short, 0, 4)]
    assert plan.decode == []
    assert s.advance_prefill(short, 4)  # prefill done -> running
    short.generated.append(1)  # engine would append the first token

    long = Request(rid=1, prompt=np.zeros(32, np.int32),
                   max_new_tokens=8)
    s.submit(long)
    for step in range(4):  # 32 tokens / 8-token chunk = 4 plans
        plan = s.plan_step()
        assert plan.decode == [short], f"decode starved at step {step}"
        assert plan.prefill_chunks == [(long, 8 * step, 8)]
        assert not s.advance_prefill(long, 8) or step == 3
        short.generated.append(1)
    assert s.running[long.slot] is long  # prefill -> running transition


def test_scheduler_preemption_requeues_at_front():
    """When extend cannot be covered, the sequence is preempted: blocks
    released, slot freed, request at the FRONT of waiting (so it
    re-admits before anything that queued after it)."""
    alloc = BlockAllocator(num_blocks=4, block_size=4)  # 3 allocatable
    s = Scheduler(alloc, num_slots=2, prefill_chunk=8, max_seq_len=16)
    a = Request(rid=0, prompt=np.zeros(3, np.int32), max_new_tokens=8)
    b = Request(rid=1, prompt=np.zeros(3, np.int32), max_new_tokens=8)
    s.submit(a)
    s.submit(b)
    plan = s.plan_step()
    assert plan.admitted == [a, b]  # ctx_len+1 = 4 tokens = 1 block each
    s.advance_prefill(a, 3)
    s.advance_prefill(b, 3)
    # Walk both across their block boundary: at 2 generated tokens
    # next_pos is 4, so growth needs 5 tokens = 2 blocks each — but the
    # pool has 3 total.  Slot-order growth gives the free block to a
    # and preempts b.
    for _ in range(2):
        for r in (a, b):
            r.generated.append(1)
    plan = s.plan_step()
    assert [r.rid for r, _ in plan.preempted] == [1]
    assert s.waiting[0] is b and b.slot == -1 and b.prefilled == 0
    assert b.preemptions == 1
    assert plan.decode == [a]  # the survivor still decodes this step
    alloc.check()
    # b's recompute context is prompt + generated-so-far minus the
    # pending token; the pending token itself re-decodes after.
    assert b.ctx_len == 3 + len(b.generated) - 1


# ---------------------------------------------------------------------
# Loadgen: deterministic replay
# ---------------------------------------------------------------------

def test_make_trace_is_seed_deterministic():
    cfg = LoadConfig(rate_rps=40.0, duration_s=0.5, seed=7)
    t1, t2 = make_trace(cfg), make_trace(cfg)
    assert len(t1) == len(t2) and len(t1) > 0
    for r1, r2 in zip(t1, t2):
        assert r1["arrival_s"] == r2["arrival_s"]
        assert r1["max_new_tokens"] == r2["max_new_tokens"]
        np.testing.assert_array_equal(r1["prompt"], r2["prompt"])
    assert make_trace(LoadConfig(rate_rps=40.0, duration_s=0.5,
                                 seed=8)) != t1


def _replay_once(model, params, trace):
    clock = VirtualClock(0.01)
    engine = InferenceEngine(
        model, params,
        EngineConfig(num_slots=2, num_blocks=12, block_size=8,
                     prefill_chunk=8),
        time_fn=clock,
    )
    out = run_load(engine, trace, clock=clock)
    tokens = {
        rid: list(r.generated) for rid, r in engine.completed.items()
    }
    timing = {
        rid: (r.admit_s, r.first_token_s, r.done_s, r.preemptions)
        for rid, r in engine.completed.items()
    }
    return out, tokens, timing


def test_virtual_clock_replay_is_identical(devices):
    """Same seed + VirtualClock => the ENTIRE run is reproduced: every
    generated token, every admission/TTFT/done timestamp, and the
    summary dict (the property that makes serving bugs bisectable)."""
    model, params = _model(_unrolled)
    trace = make_trace(LoadConfig(
        rate_rps=60.0, duration_s=0.4, prompt_len=(2, 10),
        output_len=(2, 8), vocab_size=97, seed=5,
    ))
    assert len(trace) >= 4  # enough overlap to exercise batching
    out1, toks1, tm1 = _replay_once(model, params, trace)
    out2, toks2, tm2 = _replay_once(model, params, trace)
    assert out1["completed"] == len(trace)
    assert toks1 == toks2
    assert tm1 == tm2
    assert out1 == out2
    assert out1["serve_tok_s"] > 0
    assert out1["serve_p50_ttft_s"] <= out1["serve_p99_ttft_s"]


# ---------------------------------------------------------------------
# Observability: events -> report Serving section + Perfetto trace
# ---------------------------------------------------------------------

def test_serving_events_report_and_trace(tmp_path, devices):
    from distributeddataparallel_tpu.observability.events import (
        EventLog,
        events_path,
        load_timeline,
        merge_timeline,
    )
    from distributeddataparallel_tpu.observability.registry import (
        MetricsRegistry,
    )
    from distributeddataparallel_tpu.observability.schema import (
        validate_file,
    )
    from distributeddataparallel_tpu.observability.trace_export import (
        to_trace_events,
        validate_trace,
    )
    import ddp_report

    d = str(tmp_path)
    events = EventLog(events_path(d, 0), 0)
    events.emit("run_start", argv=[], role="serve")
    model, params = _model(_unrolled)
    clock = VirtualClock(0.005)
    engine = InferenceEngine(
        model, params,
        EngineConfig(num_slots=2, num_blocks=12, block_size=8,
                     prefill_chunk=8),
        events=events, registry=MetricsRegistry(), time_fn=clock,
    )
    trace = make_trace(LoadConfig(
        rate_rps=40.0, duration_s=0.3, prompt_len=(2, 8),
        output_len=(2, 6), vocab_size=97, seed=2,
    ))
    out = run_load(engine, trace, clock=clock)
    events.emit("metrics", snapshot=engine.registry.snapshot())
    events.emit("run_end", status="ok")
    events.close()
    merge_timeline(d)

    assert validate_file(os.path.join(d, "timeline.jsonl")) == []
    records = load_timeline(d)
    assert validate_trace(to_trace_events(records)) == []

    a = ddp_report.analyze(records)
    s = a["serving"]
    assert s is not None
    assert s["completed"] == out["completed"] == len(trace)
    assert s["tokens_out"] == out["tokens_out"]
    assert s["decode_steps"] > 0 and s["tok_s"] > 0
    assert s["ttft_p50_s"] is not None
    md = ddp_report.render_markdown(a, d)
    assert "## Serving" in md
    assert f"**{len(trace)}/{len(trace)} requests completed**" in md


def test_report_degrades_without_serving_events():
    import ddp_report

    a = ddp_report.analyze([
        {"kind": "run_start", "ts": 0.0, "proc": 0, "argv": []},
        {"kind": "run_end", "ts": 1.0, "proc": 0, "status": "ok"},
    ])
    assert a["serving"] is None
    assert "No serving events" in ddp_report.render_markdown(a, ".")


# ---------------------------------------------------------------------
# Sizing helper
# ---------------------------------------------------------------------

def test_kv_pool_bytes_formula():
    cfg = _unrolled()  # 2 layers, 2 heads, d_model 32 -> head_dim 16
    rows = 2 * 2 * 64 * 16 * 2  # k+v x layers x blocks x bs x heads
    assert kv_pool_bytes(cfg, 64, 16) == rows * 16 * 4  # f32
    # int8: 1 byte/element + one f32 scale per (row, head).
    assert kv_pool_bytes(cfg, 64, 16, quantized_kv=True) == (
        rows * 16 + rows * 4
    )
    # The actual pool allocation agrees with the estimator.
    model = TransformerLM(cfg)
    pool = make_pool(model, 64, 16)
    assert sum(
        leaf.size * leaf.dtype.itemsize
        for leaf in jax.tree.leaves(pool)
    ) == kv_pool_bytes(cfg, 64, 16)
