"""Serving subsystem tests: paged KV cache, continuous batching, replay.

The load-bearing contracts:

- ``BlockAllocator`` keeps the partition invariant (every non-scratch
  block in exactly one of free / live / retired) through alloc, extend,
  release, retire and LRU reclaim;
- the paged pool round-trip (gather -> decode twin -> scatter) is
  BITWISE identical to dense-cache greedy ``generate()`` for both the
  unrolled and scanned layer layouts — including a prompt whose length
  is an exact multiple of ``block_size`` (the ctx_len+1 admission
  case) and under pool pressure (preemption + LRU eviction);
- the scheduler bounds prefill per step without starving running
  decodes, and preemption requeues at the FRONT of the waiting queue;
- a seeded loadgen trace under a ``VirtualClock`` replays to an
  identical run (tokens, events, summary) — serving runs are a pure
  function of (seed, config), with the prefix cache and speculative
  decoding on as well as off;
- the serving fast path is invisible to outputs: generation after a
  radix prefix-cache hit is bit-identical to a cold prefill, and
  speculative decoding through the (num_slots, k+1) verify program is
  bit-identical to one-token decode — both pinned against
  ``generate()``, including under pool pressure with ``check()`` run
  every scheduler step;
- a serving events dir yields a schema-valid timeline, a structurally
  valid Perfetto trace, and a populated ddp_report Serving section.
"""

import os
import sys

import jax
import jax.numpy as jnp
import numpy as np
import pytest

sys.path.insert(0, "/root/repo")
sys.path.insert(0, os.path.join("/root/repo", "scripts"))

from distributeddataparallel_tpu.models import TransformerLM, generate, tiny_lm
from distributeddataparallel_tpu.serving import (
    SCRATCH_BLOCK,
    BlockAllocator,
    EngineConfig,
    InferenceEngine,
    LoadConfig,
    Request,
    Scheduler,
    VirtualClock,
    gather_block_cache,
    kv_pool_bytes,
    make_pool,
    make_trace,
    run_load,
)


def _unrolled(**over):
    base = dict(
        vocab_size=97, num_layers=2, num_heads=2, d_model=32, d_ff=64,
        max_seq_len=32, positional="learned", norm="layernorm",
        activation="gelu", tie_embeddings=True,
    )
    base.update(over)
    return tiny_lm(**base)


def _scanned(**over):
    base = dict(
        vocab_size=97, num_layers=2, num_heads=4, num_kv_heads=2,
        d_model=32, d_ff=64, max_seq_len=32, scan_layers=True,
        tie_embeddings=False,
    )
    base.update(over)
    return tiny_lm(**base)


def _model(cfg_fn, seed=0):
    cfg = cfg_fn()
    model = TransformerLM(cfg)
    params = model.init(
        jax.random.PRNGKey(seed), jnp.zeros((1, 4), jnp.int32)
    )["params"]
    return model, params


def _prompt(rng, n, vocab=97):
    return rng.integers(0, vocab, n, dtype=np.int32)


# ---------------------------------------------------------------------
# BlockAllocator
# ---------------------------------------------------------------------

def test_allocator_partition_invariant_through_lifecycle():
    a = BlockAllocator(num_blocks=8, block_size=4)
    a.check()
    assert a.free_blocks == 7  # block 0 is reserved scratch

    a.alloc("a", 7)   # 2 blocks
    a.alloc("b", 9)   # 3 blocks
    a.check()
    assert a.live_blocks == 5 and a.free_blocks == 2
    assert a.blocks_for(1) == 1 and a.blocks_for(4) == 1
    assert a.blocks_for(5) == 2

    a.extend("a", 12)  # 2 -> 3 blocks
    a.check()
    assert len(a.table_of("a")) == 3 and a.free_blocks == 1

    # Preemption path: immediate return to the free list.
    assert a.release("a") == 3
    a.check()
    assert a.free_blocks == 4 and "a" not in a._tables

    # Completion path: retired blocks are evictable, not free.
    assert a.retire("b") == 3
    a.check()
    assert a.free_blocks == 4 and a.evictable_blocks == 3
    assert a.evictions == 0  # parking is not evicting


def test_allocator_exhaustion_and_lru_reclaim_order():
    a = BlockAllocator(num_blocks=6, block_size=4)  # 5 allocatable
    a.alloc("r0", 8)   # 2 blocks
    a.alloc("r1", 8)   # 2 blocks
    assert not a.can_alloc(8)  # only 1 free
    with pytest.raises(RuntimeError, match="pool exhausted"):
        a.alloc("r2", 8)
    a.check()

    # Retire r0 first, then r1: LRU reclaim must hit r0 first.
    a.retire("r0")
    a.retire("r1")
    assert a.can_alloc(8)
    evicted = a.alloc("r2", 8)
    assert [rid for rid, _ in evicted] == ["r0"]
    assert a.evictions == 1 and a.evicted_blocks == 2
    a.check()

    # A bigger ask sweeps the remaining retiree too.
    a.retire("r2")
    evicted = a.alloc("r3", 17)  # 5 blocks: needs everything
    assert [rid for rid, _ in evicted] == ["r1", "r2"]
    a.check()


def test_allocator_table_array_pads_with_scratch():
    a = BlockAllocator(num_blocks=8, block_size=4)
    a.alloc("a", 6)  # 2 blocks
    t = a.table_array("a", blocks_per_seq=4)
    assert t.dtype == np.int32 and t.shape == (4,)
    assert tuple(t[:2]) == a.table_of("a")
    assert (t[2:] == SCRATCH_BLOCK).all()
    with pytest.raises(ValueError, match="exceeds"):
        a.table_array("a", blocks_per_seq=1)


# ---------------------------------------------------------------------
# Pool gather/scatter layout
# ---------------------------------------------------------------------

@pytest.mark.parametrize("cfg_fn", [_unrolled, _scanned],
                         ids=["unrolled", "scanned"])
def test_gather_block_cache_reassembles_pool_rows(cfg_fn, devices):
    """gather through a block table must lay pool rows out contiguously
    in sequence order, for both the 4-d and 5-d (scanned) pool leaves."""
    model, _ = _model(cfg_fn)
    pool = make_pool(model, num_blocks=6, block_size=4)
    # Fill every pool row with a distinct fingerprint value.
    pool = jax.tree.map(
        lambda leaf: jnp.arange(leaf.size, dtype=leaf.dtype).reshape(
            leaf.shape
        ),
        pool,
    )
    tables = jnp.asarray([[3, 1, 0, 0], [2, 4, 5, 0]], jnp.int32)
    dense = gather_block_cache(pool, tables, dtype=model.cfg.dtype)

    def expect(leaf):
        if leaf.ndim == 4:  # (N, bs, H, D) -> (B, S, H, D)
            g = leaf[tables]
            return g.reshape(2, 4 * 4, *leaf.shape[2:])
        g = jnp.take(leaf, tables, axis=1)
        return g.reshape(leaf.shape[0], 2, 4 * 4, *leaf.shape[3:])

    for got, want in zip(jax.tree.leaves(dense),
                         jax.tree.leaves(jax.tree.map(expect, pool))):
        np.testing.assert_array_equal(np.asarray(got), np.asarray(want))


# ---------------------------------------------------------------------
# Engine vs generate(): bitwise greedy parity
# ---------------------------------------------------------------------

@pytest.mark.parametrize("cfg_fn", [_unrolled, _scanned],
                         ids=["unrolled", "scanned"])
def test_engine_matches_generate_greedy(cfg_fn, devices):
    """Continuous batching must be invisible: every request's greedy
    continuation is bit-identical to static-batch generate().  Prompt
    lengths include exact block-size multiples (8, 16 with block_size
    8) — the case where admission must allocate ctx_len + 1 or the
    first decode row spills to scratch."""
    model, params = _model(cfg_fn)
    engine = InferenceEngine(
        model, params,
        EngineConfig(num_slots=4, num_blocks=16, block_size=8,
                     prefill_chunk=8),
    )
    rng = np.random.default_rng(3)
    # Each DISTINCT (plen, n_new) pair compiles its own generate()
    # reference — keep the list short but include both block-exact
    # prompt lengths (8, 16) and a repeated shape (cache hit).
    cases = [(3, 6), (8, 7), (16, 9), (8, 7)]
    rids = {}
    for plen, n_new in cases:
        p = _prompt(rng, plen)
        rids[engine.submit(p, n_new)] = (p, n_new)
    engine.run()
    assert len(engine.completed) == len(cases)
    for rid, (p, n_new) in rids.items():
        want = np.asarray(
            generate(model, params, jnp.asarray(p)[None], n_new)
        )[0]
        np.testing.assert_array_equal(engine.output_tokens(rid), want)


@pytest.mark.parametrize("prefix_cache,spec_k", [(False, 0), (True, 3)],
                         ids=["plain", "fastpath"])
def test_engine_parity_under_pool_pressure(prefix_cache, spec_k, devices):
    """A pool too small to hold every sequence forces LRU evictions and
    recompute preemptions mid-flight; outputs must STILL be bit-exact
    vs generate() — preemption re-prefills prompt + generated-so-far
    and resumes, it never corrupts a continuation.

    The fastpath variant turns on the radix prefix cache AND
    speculative decoding under the same pressure: shared prompt
    prefixes mean refcounted blocks, CoW on divergence, cached-subtree
    evictions, and the verify program's multi-token appends all run
    against ``check()`` every single step."""
    model, params = _model(_unrolled)
    engine = InferenceEngine(
        model, params,
        EngineConfig(num_slots=4, num_blocks=8, block_size=4,
                     prefill_chunk=8, prefix_cache=prefix_cache,
                     spec_k=spec_k),
    )
    rng = np.random.default_rng(11)
    # Repeated shapes: only two generate() reference compiles, but six
    # in-flight sequences against a 7-block pool — guaranteed pressure.
    # The 7-token prompts share a full-block 4-token prefix, so the
    # fastpath variant exercises sharing + CoW, not just eviction.
    shared = _prompt(rng, 4)
    cases = [(4, 12), (7, 11), (4, 12), (7, 11), (4, 12), (7, 11)]
    rids = {}
    for plen, n_new in cases:
        if plen == 7:
            p = np.concatenate([shared, _prompt(rng, 3)])
        else:
            p = _prompt(rng, plen)
        rids[engine.submit(p, n_new)] = (p, n_new)
    while engine.has_work():
        engine.step()
        engine.allocator.check()  # partition invariant every step
    stats = {
        "evictions": engine.allocator.evictions,
        "preemptions": sum(
            r.preemptions for r in engine.completed.values()
        ),
    }
    # The point of the test is pressure: something must have given.
    assert stats["evictions"] + stats["preemptions"] > 0, stats
    if spec_k:
        assert engine.spec_rows > 0
    for rid, (p, n_new) in rids.items():
        want = np.asarray(
            generate(model, params, jnp.asarray(p)[None], n_new)
        )[0]
        np.testing.assert_array_equal(engine.output_tokens(rid), want)


# ---------------------------------------------------------------------
# Serving fast path: refcounted radix prefix cache + spec decoding
# ---------------------------------------------------------------------

def test_allocator_prefix_sharing_refcount_lifecycle():
    """Refcount/CoW contract: a block shared by two sequences survives
    one owner's release, copy-on-write gives the writer a private copy,
    and a block is only reclaimable once every reference is gone."""
    a = BlockAllocator(num_blocks=16, block_size=4)
    ids = np.arange(12, dtype=np.int32)  # 3 full blocks of context

    # Cold path: nothing registered yet, so no match.
    ev, matched = a.alloc_shared("a", 13, ids)
    assert ev == [] and matched == 0
    a.check()
    # Registration publishes a's first 3 blocks into the radix trie.
    assert a.register_progress("a", ids, upto=12) == 3
    a.check()

    # Second sequence with the same context maps the shared blocks.
    ev, matched = a.alloc_shared("b", 13, ids)
    assert ev == [] and matched >= 8  # >= 2 full blocks shared
    a.check()
    n_shared = (matched + 3) // 4  # full + the partially matched tail
    ta, tb = list(a.table_of("a")), list(a.table_of("b"))
    shared_blocks = tb[:n_shared]
    assert shared_blocks == ta[:n_shared]
    for blk in shared_blocks:
        assert a.refcount(blk) == 2

    # Shared + registered blocks need CoW before any in-place write.
    assert a.needs_cow("b", 0)
    src, dst, ev = a.cow("b", 0)
    assert src == shared_blocks[0] and dst != src and ev == []
    assert a.refcount(src) == 1 and a.refcount(dst) == 1
    assert a.table_of("b")[0] == dst
    a.check()

    # Releasing one owner must NOT free blocks the other still maps.
    before = set(a.table_of("a"))
    assert a.release("b") > 0
    a.check()
    assert set(a.table_of("a")) == before
    # Registered blocks still referenced by "a" are not evictable.
    assert a.evictable_blocks == 0

    # Last reference gone: registered blocks become revivable cache...
    a.release("a")
    a.check()
    assert a.cached_blocks == 3 and a.evictable_blocks == 3
    # ...and a big enough demand reclaims them (refcount 0 only).
    evs, m = a.alloc_shared("c", 57, _prompt(np.random.default_rng(0), 57))
    a.check()
    assert sum(n for _, n in evs) >= 1  # forced cache eviction
    assert a.cached_blocks < 3
    a.release("c")
    a.check()


def test_allocator_match_prefix_is_collision_checked():
    """The radix walk verifies chunk CONTENT, not just the rolling
    hash: a different token run never matches a cached block."""
    a = BlockAllocator(num_blocks=8, block_size=4)
    ids = np.arange(8, dtype=np.int32)
    a.alloc_shared("a", 9, ids)
    a.register_progress("a", ids, upto=8)
    other = ids + 1
    blocks, matched = a.match_prefix(other, limit=8)
    assert blocks == [] and matched == 0
    blocks, matched = a.match_prefix(ids, limit=8)
    assert matched == 8 and len(blocks) == 2
    a.release("a")
    a.check()


def test_engine_prefix_hit_parity_vs_cold_prefill(devices):
    """A warm radix cache must be invisible: generation after a prefix
    hit is bit-identical to a cold prefill of the same prompt (which is
    itself pinned to generate())."""
    model, params = _model(_unrolled)

    def run(prefix_cache):
        engine = InferenceEngine(
            model, params,
            EngineConfig(num_slots=4, num_blocks=24, block_size=4,
                         prefill_chunk=8, prefix_cache=prefix_cache),
        )
        rng = np.random.default_rng(17)
        shared = _prompt(rng, 12)  # 3 full blocks
        prompts = [
            np.concatenate([shared, _prompt(rng, 5)]),
            np.concatenate([shared, _prompt(rng, 7)]),
            shared.copy(),                 # prompt == cached prefix
            np.concatenate([shared[:6], _prompt(rng, 4)]),  # diverges
        ]
        outs = []
        for p in prompts:  # sequential: each run registers its blocks
            rid = engine.submit(p, 8)
            engine.run()
            outs.append(engine.output_tokens(rid))
        return engine, outs

    warm_engine, warm = run(True)
    _, cold = run(False)
    assert warm_engine.prefix_hits >= 2
    assert warm_engine.prefix_hit_tokens >= 12
    warm_engine.allocator.check()
    for w, c in zip(warm, cold):
        np.testing.assert_array_equal(w, c)


@pytest.mark.parametrize("cfg_fn", [_unrolled, _scanned],
                         ids=["unrolled", "scanned"])
@pytest.mark.parametrize("spec_k", [2, 5])
def test_engine_spec_decode_greedy_parity(cfg_fn, spec_k, devices):
    """Speculative decoding must be invisible: greedy outputs through
    the (num_slots, k+1) verify program are bit-identical to
    generate().  Early decodes reject most drafts (k > accepted, the
    partial-accept path) while the looping tail accepts full windows
    that cross block boundaries (block_size 4 < k+1 appends)."""
    model, params = _model(cfg_fn)
    engine = InferenceEngine(
        model, params,
        EngineConfig(num_slots=4, num_blocks=24, block_size=4,
                     prefill_chunk=8, spec_k=spec_k),
    )
    rng = np.random.default_rng(23)
    cases = [(3, 6), (8, 7), (16, 9), (4, 12)]
    rids = {}
    for plen, n_new in cases:
        p = _prompt(rng, plen)
        rids[engine.submit(p, n_new)] = (p, n_new)
    while engine.has_work():
        engine.step()
        engine.allocator.check()
    assert engine.spec_rows > 0
    # Both regimes happened: some rejected drafts, some full accepts.
    assert engine.spec_accepted < engine.spec_drafted + engine.spec_rows
    for rid, (p, n_new) in rids.items():
        want = np.asarray(
            generate(model, params, jnp.asarray(p)[None], n_new)
        )[0]
        np.testing.assert_array_equal(engine.output_tokens(rid), want)


def test_engine_int8_kv_completes(devices):
    """int8-KV pool: engine drains, outputs have the right shape and
    stay in-vocab (parity is approximate by construction — the exact
    per-row quantization contract lives in the kv_cache unit tests)."""
    model, params = _model(_unrolled)
    engine = InferenceEngine(
        model, params,
        EngineConfig(num_slots=2, num_blocks=16, block_size=8,
                     prefill_chunk=8, quantized_kv=True),
    )
    rng = np.random.default_rng(5)
    p = _prompt(rng, 6)
    rid = engine.submit(p, 8)
    engine.run()
    out = engine.output_tokens(rid)
    assert out.shape == (14,)
    assert (out[:6] == p).all()
    assert ((0 <= out) & (out < 97)).all()


# ---------------------------------------------------------------------
# Scheduler
# ---------------------------------------------------------------------

def test_scheduler_submit_rejects_impossible_requests():
    s = Scheduler(BlockAllocator(8, 4), num_slots=2, prefill_chunk=8,
                  max_seq_len=32)
    with pytest.raises(ValueError, match="exceeds max_seq_len"):
        s.submit(Request(rid=0, prompt=np.zeros(30, np.int32),
                         max_new_tokens=8))
    s2 = Scheduler(BlockAllocator(4, 4), num_slots=2, prefill_chunk=8,
                   max_seq_len=64)
    with pytest.raises(ValueError, match="never be admitted"):
        s2.submit(Request(rid=1, prompt=np.zeros(20, np.int32),
                          max_new_tokens=8))


def test_scheduler_chunked_prefill_does_not_starve_decodes():
    """With max_prefill_chunks_per_step=1, a long prompt prefills one
    chunk per plan while the already-running slot decodes EVERY plan."""
    alloc = BlockAllocator(num_blocks=16, block_size=4)
    s = Scheduler(alloc, num_slots=2, prefill_chunk=8, max_seq_len=64,
                  max_prefill_chunks_per_step=1)
    short = Request(rid=0, prompt=np.zeros(4, np.int32),
                    max_new_tokens=32)
    s.submit(short)
    plan = s.plan_step()
    assert plan.admitted == [short]
    assert plan.prefill_chunks == [(short, 0, 4)]
    assert plan.decode == []
    assert s.advance_prefill(short, 4)  # prefill done -> running
    short.generated.append(1)  # engine would append the first token

    long = Request(rid=1, prompt=np.zeros(32, np.int32),
                   max_new_tokens=8)
    s.submit(long)
    for step in range(4):  # 32 tokens / 8-token chunk = 4 plans
        plan = s.plan_step()
        assert plan.decode == [short], f"decode starved at step {step}"
        assert plan.prefill_chunks == [(long, 8 * step, 8)]
        assert not s.advance_prefill(long, 8) or step == 3
        short.generated.append(1)
    assert s.running[long.slot] is long  # prefill -> running transition


def test_scheduler_preemption_requeues_at_front():
    """When extend cannot be covered, the sequence is preempted: blocks
    released, slot freed, request at the FRONT of waiting (so it
    re-admits before anything that queued after it)."""
    alloc = BlockAllocator(num_blocks=4, block_size=4)  # 3 allocatable
    s = Scheduler(alloc, num_slots=2, prefill_chunk=8, max_seq_len=16)
    a = Request(rid=0, prompt=np.zeros(3, np.int32), max_new_tokens=8)
    b = Request(rid=1, prompt=np.zeros(3, np.int32), max_new_tokens=8)
    s.submit(a)
    s.submit(b)
    plan = s.plan_step()
    assert plan.admitted == [a, b]  # ctx_len+1 = 4 tokens = 1 block each
    s.advance_prefill(a, 3)
    s.advance_prefill(b, 3)
    # Walk both across their block boundary: at 2 generated tokens
    # next_pos is 4, so growth needs 5 tokens = 2 blocks each — but the
    # pool has 3 total.  Slot-order growth gives the free block to a
    # and preempts b.
    for _ in range(2):
        for r in (a, b):
            r.generated.append(1)
    plan = s.plan_step()
    assert [r.rid for r, _ in plan.preempted] == [1]
    assert s.waiting[0] is b and b.slot == -1 and b.prefilled == 0
    assert b.preemptions == 1
    assert plan.decode == [a]  # the survivor still decodes this step
    alloc.check()
    # b's recompute context is prompt + generated-so-far minus the
    # pending token; the pending token itself re-decodes after.
    assert b.ctx_len == 3 + len(b.generated) - 1


# ---------------------------------------------------------------------
# Loadgen: deterministic replay
# ---------------------------------------------------------------------

def test_make_trace_is_seed_deterministic():
    cfg = LoadConfig(rate_rps=40.0, duration_s=0.5, seed=7)
    t1, t2 = make_trace(cfg), make_trace(cfg)
    assert len(t1) == len(t2) and len(t1) > 0
    for r1, r2 in zip(t1, t2):
        assert r1["arrival_s"] == r2["arrival_s"]
        assert r1["max_new_tokens"] == r2["max_new_tokens"]
        np.testing.assert_array_equal(r1["prompt"], r2["prompt"])
    assert make_trace(LoadConfig(rate_rps=40.0, duration_s=0.5,
                                 seed=8)) != t1


def _replay_once(model, params, trace):
    clock = VirtualClock(0.01)
    engine = InferenceEngine(
        model, params,
        EngineConfig(num_slots=2, num_blocks=12, block_size=8,
                     prefill_chunk=8),
        time_fn=clock,
    )
    out = run_load(engine, trace, clock=clock)
    tokens = {
        rid: list(r.generated) for rid, r in engine.completed.items()
    }
    timing = {
        rid: (r.admit_s, r.first_token_s, r.done_s, r.preemptions)
        for rid, r in engine.completed.items()
    }
    return out, tokens, timing


def test_virtual_clock_replay_is_identical(devices):
    """Same seed + VirtualClock => the ENTIRE run is reproduced: every
    generated token, every admission/TTFT/done timestamp, and the
    summary dict (the property that makes serving bugs bisectable)."""
    model, params = _model(_unrolled)
    trace = make_trace(LoadConfig(
        rate_rps=60.0, duration_s=0.4, prompt_len=(2, 10),
        output_len=(2, 8), vocab_size=97, seed=5,
    ))
    assert len(trace) >= 4  # enough overlap to exercise batching
    out1, toks1, tm1 = _replay_once(model, params, trace)
    out2, toks2, tm2 = _replay_once(model, params, trace)
    assert out1["completed"] == len(trace)
    assert toks1 == toks2
    assert tm1 == tm2
    assert out1 == out2
    assert out1["serve_tok_s"] > 0
    assert out1["serve_p50_ttft_s"] <= out1["serve_p99_ttft_s"]


def test_make_trace_zipf_shared_prefix():
    """Shared-prefix mode: every prompt starts with one of the pooled
    prefixes, hot ranks dominate per the Zipf weights, and the whole
    trace stays a pure function of the seed."""
    cfg = LoadConfig(
        rate_rps=80.0, duration_s=1.0, prompt_len=(10, 16),
        output_len=(2, 4), vocab_size=97, seed=7,
        prefix_pool=3, prefix_len=8, zipf_alpha=1.2,
    )
    t1, t2 = make_trace(cfg), make_trace(cfg)
    assert len(t1) == len(t2) > 10
    for r1, r2 in zip(t1, t2):
        assert r1["arrival_s"] == r2["arrival_s"]
        np.testing.assert_array_equal(r1["prompt"], r2["prompt"])
    heads = {tuple(int(t) for t in r["prompt"][:8]) for r in t1}
    assert 1 < len(heads) <= 3  # drawn from the 3-prefix pool
    counts = sorted(
        (sum(1 for r in t1
             if tuple(int(t) for t in r["prompt"][:8]) == h)
         for h in heads),
        reverse=True,
    )
    assert counts[0] > counts[-1]  # Zipf skew: a hot prefix dominates
    for r in t1:
        assert len(r["prompt"]) >= 9  # prefix + >=1 suffix token
    with pytest.raises(ValueError, match="prefix_len"):
        make_trace(LoadConfig(prefix_pool=2))


def test_virtual_clock_replay_is_identical_fastpath(devices):
    """The fast path stays a pure function of (seed, config): with the
    prefix cache AND speculation on, the same Zipf trace under a
    VirtualClock reproduces every token, timestamp, and the summary —
    including the prefix-hit and accept-length stats."""
    model, params = _model(_unrolled)
    trace = make_trace(LoadConfig(
        rate_rps=60.0, duration_s=0.4, prompt_len=(10, 14),
        output_len=(2, 8), vocab_size=97, seed=5,
        prefix_pool=2, prefix_len=8, zipf_alpha=1.1,
    ))
    assert len(trace) >= 4

    def once():
        clock = VirtualClock(0.01)
        engine = InferenceEngine(
            model, params,
            EngineConfig(num_slots=2, num_blocks=16, block_size=4,
                         prefill_chunk=8, prefix_cache=True, spec_k=3),
            time_fn=clock,
        )
        out = run_load(engine, trace, clock=clock)
        tokens = {
            rid: list(r.generated) for rid, r in engine.completed.items()
        }
        timing = {
            rid: (r.admit_s, r.first_token_s, r.done_s, r.preemptions)
            for rid, r in engine.completed.items()
        }
        engine.allocator.check()
        return out, tokens, timing

    out1, toks1, tm1 = once()
    out2, toks2, tm2 = once()
    assert out1["completed"] == len(trace)
    assert toks1 == toks2 and tm1 == tm2 and out1 == out2
    assert out1["prefix_hit_frac"] > 0
    assert out1["spec_accept_mean"] > 0


# ---------------------------------------------------------------------
# Observability: events -> report Serving section + Perfetto trace
# ---------------------------------------------------------------------

def test_serving_events_report_and_trace(tmp_path, devices):
    from distributeddataparallel_tpu.observability.events import (
        EventLog,
        events_path,
        load_timeline,
        merge_timeline,
    )
    from distributeddataparallel_tpu.observability.registry import (
        MetricsRegistry,
    )
    from distributeddataparallel_tpu.observability.schema import (
        validate_file,
    )
    from distributeddataparallel_tpu.observability.trace_export import (
        to_trace_events,
        validate_trace,
    )
    import ddp_report

    d = str(tmp_path)
    events = EventLog(events_path(d, 0), 0)
    events.emit("run_start", argv=[], role="serve")
    model, params = _model(_unrolled)
    clock = VirtualClock(0.005)
    engine = InferenceEngine(
        model, params,
        EngineConfig(num_slots=2, num_blocks=12, block_size=8,
                     prefill_chunk=8),
        events=events, registry=MetricsRegistry(), time_fn=clock,
    )
    trace = make_trace(LoadConfig(
        rate_rps=40.0, duration_s=0.3, prompt_len=(2, 8),
        output_len=(2, 6), vocab_size=97, seed=2,
    ))
    out = run_load(engine, trace, clock=clock)
    events.emit("metrics", snapshot=engine.registry.snapshot())
    events.emit("run_end", status="ok")
    events.close()
    merge_timeline(d)

    assert validate_file(os.path.join(d, "timeline.jsonl")) == []
    records = load_timeline(d)
    assert validate_trace(to_trace_events(records)) == []

    a = ddp_report.analyze(records)
    s = a["serving"]
    assert s is not None
    assert s["completed"] == out["completed"] == len(trace)
    assert s["tokens_out"] == out["tokens_out"]
    assert s["decode_steps"] > 0 and s["tok_s"] > 0
    assert s["ttft_p50_s"] is not None
    md = ddp_report.render_markdown(a, d)
    assert "## Serving" in md
    assert f"**{len(trace)}/{len(trace)} requests completed**" in md


def test_report_degrades_without_serving_events():
    import ddp_report

    a = ddp_report.analyze([
        {"kind": "run_start", "ts": 0.0, "proc": 0, "argv": []},
        {"kind": "run_end", "ts": 1.0, "proc": 0, "status": "ok"},
    ])
    assert a["serving"] is None
    assert "No serving events" in ddp_report.render_markdown(a, ".")


# ---------------------------------------------------------------------
# Sizing helper
# ---------------------------------------------------------------------

def test_kv_pool_bytes_formula():
    cfg = _unrolled()  # 2 layers, 2 heads, d_model 32 -> head_dim 16
    rows = 2 * 2 * 64 * 16 * 2  # k+v x layers x blocks x bs x heads
    assert kv_pool_bytes(cfg, 64, 16) == rows * 16 * 4  # f32
    # int8: 1 byte/element + one f32 scale per (row, head).
    assert kv_pool_bytes(cfg, 64, 16, quantized_kv=True) == (
        rows * 16 + rows * 4
    )
    # The actual pool allocation agrees with the estimator.
    model = TransformerLM(cfg)
    pool = make_pool(model, 64, 16)
    assert sum(
        leaf.size * leaf.dtype.itemsize
        for leaf in jax.tree.leaves(pool)
    ) == kv_pool_bytes(cfg, 64, 16)
