"""ddplint (distributeddataparallel_tpu.analysis): both layers over the
live repo, plus mutation tests — each seeded violation must be flagged
with its distinct rule id.

This file IS the CI wiring for the static-analysis subsystem: the
tier-1 pytest command runs it, and it runs ``scripts/ddplint.py`` (in
process) over the real tree, so a lint regression fails the suite the
same as any other test.
"""

import os
import sys
import textwrap

import jax
import jax.numpy as jnp
import optax
import pytest
from jax import lax
from jax.sharding import PartitionSpec as P

import distributeddataparallel_tpu as ddp
from distributeddataparallel_tpu.analysis import ast_rules, graph_lint
from distributeddataparallel_tpu.analysis.rules import (
    RULES,
    collective_manifest,
)
from distributeddataparallel_tpu.training.state import TrainState
from distributeddataparallel_tpu.training.train_step import make_train_step

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, os.path.join(REPO, "scripts"))
import check_events  # noqa: E402
import ddplint  # noqa: E402

# ---------------------------------------------------------------------
# shared tiny-step scaffolding for the graph-layer mutation tests
# ---------------------------------------------------------------------


@pytest.fixture(scope="module")
def mesh(devices):
    return ddp.make_mesh(("data",))


@pytest.fixture(scope="module")
def tiny(mesh):
    params = {"w": jnp.ones((8, 4)), "b": jnp.ones((4,))}
    state = TrainState.create(
        apply_fn=None, params=params, tx=optax.sgd(0.1)
    )
    batch = {"x": jnp.ones((8, 8)), "y": jnp.ones((8, 4))}
    return state, batch, jax.random.PRNGKey(0)


def _grads_of(state, batch):
    def loss(p):
        pred = batch["x"] @ p["w"] + p["b"]
        return jnp.mean((pred - batch["y"]) ** 2)

    return jax.value_and_grad(loss)(state.params)


def _jit_step(mesh, body, *, donate=True):
    fn = jax.shard_map(
        body, mesh=mesh, in_specs=(P(), P("data"), P()),
        out_specs=(P(), P()), check_vma=False,
    )
    kw = {"donate_argnums": (0,)} if donate else {}
    return jax.jit(fn, **kw)


MAN = collective_manifest(
    "dp", grad_reduce={"data": {"psum": (1, None)}},
    donate=True, per_leaf_axes=("data",),
)


def _good_body(state, batch, rng):
    loss, g = _grads_of(state, batch)
    g = jax.tree.map(lambda x: lax.pmean(x, "data"), g)
    return state.apply_gradients(g), {"loss": lax.pmean(loss, "data")}


# ---------------------------------------------------------------------
# graph layer: live factories are clean; mutations are caught
# ---------------------------------------------------------------------


def test_graph_clean_on_live_dp_factory(mesh, tiny):
    state, batch, rng = tiny

    def loss_fn(params, batch, _rng):
        pred = batch["x"] @ params["w"] + params["b"]
        return jnp.mean((pred - batch["y"]) ** 2), {}

    step = make_train_step(loss_fn, mesh=mesh)
    rep = graph_lint.lint_train_step(step, state, batch, rng)
    assert rep.ok, rep.findings
    # unbucketed DP: exactly one psum per param leaf over the data axis
    assert rep.collective_counts["data:psum"] == len(
        jax.tree.leaves(state.params)
    )
    assert rep.donated_args >= rep.donation_expected


def test_graph_clean_on_correct_handwritten_step(mesh, tiny):
    state, batch, rng = tiny
    rep = graph_lint.lint_train_step(
        _jit_step(mesh, _good_body), state, batch, rng, manifest=MAN
    )
    assert rep.ok, rep.findings


def test_mutation_dropped_psum_flagged_gl001(mesh, tiny):
    state, batch, rng = tiny

    def body(state, batch, rng):  # trains on per-replica grads!
        loss, g = _grads_of(state, batch)
        return state.apply_gradients(g), {"loss": lax.pmean(loss, "data")}

    rep = graph_lint.lint_train_step(
        _jit_step(mesh, body), state, batch, rng, manifest=MAN
    )
    assert {f.rule for f in rep.findings} == {"GL001"}
    assert any("dropped" in f.message for f in rep.findings)


def test_mutation_double_sync_flagged_gl001(mesh, tiny):
    state, batch, rng = tiny

    def body(state, batch, rng):  # pays the wire twice
        loss, g = _grads_of(state, batch)
        g = jax.tree.map(lambda x: lax.pmean(x, "data"), g)
        g = jax.tree.map(lambda x: lax.pmean(x, "data"), g)
        return state.apply_gradients(g), {"loss": lax.pmean(loss, "data")}

    rep = graph_lint.lint_train_step(
        _jit_step(mesh, body), state, batch, rng, manifest=MAN
    )
    assert {f.rule for f in rep.findings} == {"GL001"}


def test_mutation_removed_donation_flagged_gl003(mesh, tiny):
    state, batch, rng = tiny
    step = _jit_step(mesh, _good_body, donate=False)  # lost donate_argnums
    rep = graph_lint.lint_train_step(step, state, batch, rng, manifest=MAN)
    assert {f.rule for f in rep.findings} == {"GL003"}


def test_mutation_host_callback_flagged_gl005(mesh, tiny):
    state, batch, rng = tiny

    def body(state, batch, rng):
        loss, g = _grads_of(state, batch)
        g = jax.tree.map(lambda x: lax.pmean(x, "data"), g)
        jax.debug.print("loss {l}", l=loss)  # host round-trip per step
        return state.apply_gradients(g), {"loss": lax.pmean(loss, "data")}

    rep = graph_lint.lint_train_step(
        _jit_step(mesh, body), state, batch, rng, manifest=MAN
    )
    assert "GL005" in {f.rule for f in rep.findings}


def test_mutation_bf16_promotion_flagged_gl004(mesh, tiny):
    state, batch, rng = tiny
    bf16 = TrainState.create(
        apply_fn=None,
        params=jax.tree.map(
            lambda x: x.astype(jnp.bfloat16), state.params
        ),
        tx=optax.sgd(0.1),
    )

    def body(state, batch, rng):  # reduces f32 under bf16 params
        loss, g = _grads_of(state, batch)
        g = jax.tree.map(
            lambda x: lax.pmean(
                x.astype(jnp.float32), "data"
            ).astype(x.dtype),
            g,
        )
        return state.apply_gradients(g), {"loss": lax.pmean(loss, "data")}

    man = collective_manifest(
        "dp", grad_reduce={"data": {"psum": (1, None)}}, donate=False
    )
    rep = graph_lint.lint_train_step(
        _jit_step(mesh, body, donate=False), bf16, batch, rng, manifest=man
    )
    assert {f.rule for f in rep.findings} == {"GL004"}
    # the same step under an allow_f32_reduce manifest is clean: the
    # waiver is the factory's to grant, not the linter's to assume
    man2 = collective_manifest(
        "dp", grad_reduce={"data": {"psum": (1, None)}},
        donate=False, allow_f32_reduce=True,
    )
    rep2 = graph_lint.lint_train_step(
        _jit_step(mesh, body, donate=False), bf16, batch, rng,
        manifest=man2,
    )
    assert rep2.ok, rep2.findings


def test_collective_fingerprint_deterministic_gl002(mesh, tiny):
    state, batch, rng = tiny
    reps = [
        graph_lint.lint_train_step(
            _jit_step(mesh, _good_body, donate=False), state, batch, rng,
            manifest=collective_manifest(
                "dp", grad_reduce={"data": {"psum": (1, None)}},
                donate=False,
            ),
        )
        for _ in range(2)
    ]
    # stable across independent factory instances AND across the double
    # trace inside each lint run (which is the GL002 check itself)
    assert reps[0].fingerprint == reps[1].fingerprint
    assert not any(f.rule == "GL002" for r in reps for f in r.findings)
    # and sensitive to the collective sequence actually changing
    def reordered(state, batch, rng):
        # issue the leaf pmeans in the opposite order (w before b —
        # tree order is alphabetical), changing the collective sequence
        loss, g = _grads_of(state, batch)
        gw = lax.pmean(g["w"], "data")
        gb = lax.pmean(g["b"], "data")
        return state.apply_gradients({"b": gb, "w": gw}), {
            "loss": lax.pmean(loss, "data")
        }

    rep3 = graph_lint.lint_train_step(
        _jit_step(mesh, reordered, donate=False), state, batch, rng,
        manifest=collective_manifest(
            "dp", grad_reduce={"data": {"psum": (1, None)}}, donate=False
        ),
    )
    assert rep3.fingerprint != reps[0].fingerprint


# ---------------------------------------------------------------------
# donation regression (satellite): dp + fsdp lowered steps report
# params+opt-state aliasing; donate=False detected as no-aliasing
# ---------------------------------------------------------------------


def test_donation_regression_dp(mesh, tiny):
    state, batch, rng = tiny

    def loss_fn(params, batch, _rng):
        pred = batch["x"] @ params["w"] + params["b"]
        return jnp.mean((pred - batch["y"]) ** 2), {}

    donated, expected = graph_lint.donation_report(
        make_train_step(loss_fn, mesh=mesh, donate=True),
        state, batch, rng,
    )
    assert expected == len(
        jax.tree.leaves((state.params, state.opt_state))
    )
    assert donated >= expected, (donated, expected)

    donated_off, _ = graph_lint.donation_report(
        make_train_step(loss_fn, mesh=mesh, donate=False),
        state, batch, rng,
    )
    assert donated_off == 0


def test_donation_regression_fsdp(mesh, devices):
    import numpy as np

    from distributeddataparallel_tpu.data.loader import shard_batch
    from distributeddataparallel_tpu.models import TransformerLM, tiny_lm
    from distributeddataparallel_tpu.parallel.fsdp import (
        fsdp_state,
        make_fsdp_train_step,
    )

    cfg = tiny_lm(
        num_layers=2, num_heads=2, d_model=32, d_ff=64, max_seq_len=32,
        scan_layers=True,
    )
    params = TransformerLM(cfg).init(
        jax.random.PRNGKey(0), jnp.zeros((1, 16), jnp.int32)
    )["params"]
    state = fsdp_state(cfg, params, optax.adam(1e-2), mesh)
    batch = shard_batch(
        {"tokens": np.random.default_rng(0).integers(
            0, 256, size=(8, 17)).astype(np.int32)},
        mesh,
    )
    rng = jax.random.PRNGKey(1)

    for donate, check in ((True, lambda d, e: d >= e),
                          (False, lambda d, e: d == 0)):
        step = make_fsdp_train_step(cfg, mesh=mesh, donate=donate)
        jax.make_jaxpr(step)(state, batch, rng)  # populates step.jitted
        donated, expected = graph_lint.donation_report(
            step, state, batch, rng
        )
        assert check(donated, expected), (donate, donated, expected)


# ---------------------------------------------------------------------
# AST layer: clean on the live tree; synthetic mutations per rule
# ---------------------------------------------------------------------

HOT = "distributeddataparallel_tpu/training/train_step.py"


def test_ast_clean_on_repo():
    findings = ast_rules.lint_paths(
        ast_rules.default_targets(REPO), REPO
    )
    assert not findings, "\n" + "\n".join(str(f) for f in findings)


def test_ast_host_sync_flagged_al101():
    src = textwrap.dedent("""
        import jax
        import numpy as np
        def dispatch(state, out):
            jax.block_until_ready(out)
            x = out.item()
            y = float(jax.device_get(out))
            z = np.asarray(out)
            return x, y, z
    """)
    rules = {f.rule for f in ast_rules.lint_source(src, HOT)}
    assert rules == {"AL101"}
    assert len(ast_rules.lint_source(src, HOT)) == 4
    # same source outside the hot path: no findings
    assert not ast_rules.lint_source(src, "scripts/tooling.py")


def test_ast_host_sync_pragma_waives():
    src = textwrap.dedent("""
        import jax
        def probe(out):
            # ddplint: allow[host-sync] — measurement fence
            jax.block_until_ready(out)
    """)
    assert not ast_rules.lint_source(src, HOT)


def test_ast_time_in_jit_flagged_al102():
    src = textwrap.dedent("""
        import time
        import jax
        @jax.jit
        def step(x):
            return x * time.time()
        def make_cool_step():
            def inner(x):
                return x + time.perf_counter()
            return inner
        def host_side():
            return time.time()  # fine: not traced scope
    """)
    findings = ast_rules.lint_source(src, "anywhere.py")
    assert [f.rule for f in findings] == ["AL102", "AL102"]


def test_ast_broad_except_flagged_al103():
    src = "try:\n    pass\nexcept Exception:\n    pass\n"
    assert [f.rule for f in ast_rules.lint_source(src, "m.py")] \
        == ["AL103"]
    waived = (
        "try:\n    pass\n"
        "# ddplint: allow[broad-except] — supervision boundary\n"
        "except Exception:\n    pass\n"
    )
    assert not ast_rules.lint_source(waived, "m.py")


def test_ast_unregistered_event_kind_flagged_al104():
    src = "events.emit('totally_new_kind', step=1)\n"
    findings = ast_rules.lint_source(src, "m.py")
    assert [f.rule for f in findings] == ["AL104"]
    assert "totally_new_kind" in findings[0].message
    # registered kinds pass, kwarg form included
    ok = "events.emit('run_start')\nevents.emit(kind='nan_skip')\n"
    assert not ast_rules.lint_source(ok, "m.py")


def test_every_finding_carries_registered_rule_id():
    bad = "try:\n    pass\nexcept Exception:\n    events.emit('nope')\n"
    for f in ast_rules.lint_source(bad, HOT):
        assert f.rule in RULES
        assert f.name == RULES[f.rule][1]


# ---------------------------------------------------------------------
# wiring: the CLI and the schema-sync cross-check run clean in-process
# ---------------------------------------------------------------------


def test_ddplint_cli_graph_ast_clean(devices, capsys):
    # the acceptance-criteria invocation, in-process
    assert ddplint.main(["--graph", "--ast"]) == 0
    out = capsys.readouterr().out
    assert "ddplint: clean" in out
    assert "graph [dp] ok" in out


def test_ddplint_cli_list_rules(capsys):
    assert ddplint.main(["--list-rules"]) == 0
    out = capsys.readouterr().out
    for rid in RULES:
        assert rid in out


def test_ddplint_cli_changed_only_runs(devices):
    # smoke: must not crash whatever the current diff is (it shells out
    # to git); result is 0 because the tree is lint-clean either way
    assert ddplint.main(["--ast", "--changed-only"]) == 0


def test_check_events_schema_sync_clean():
    assert check_events.check_schema_sync(REPO) == []
    assert check_events.main(["--schema-sync"]) == 0


def test_check_events_schema_sync_catches_both_directions(tmp_path):
    # direction 1: emitted-but-unregistered is an AL104 finding AND a
    # schema-sync problem
    tree = tmp_path / "pkg.py"
    tree.write_text("events.emit('ghost_kind')\n")
    emitted = ast_rules.collect_emitted_kinds(tmp_path, [tree])
    assert "ghost_kind" in emitted
    # direction 2: registered-but-never-emitted — simulate by collecting
    # from a tree that emits nothing
    tree.write_text("x = 1\n")
    emitted = ast_rules.collect_emitted_kinds(tmp_path, [tree])
    from distributeddataparallel_tpu.observability.schema import (
        EVENT_KINDS,
    )

    assert set(EVENT_KINDS) - set(emitted) == set(EVENT_KINDS)


def test_loader_starved_is_emitted_and_registered():
    """The pre-existing drift this PR closes: 'loader_starved' was
    registered but nothing emitted it.  Pin both directions so it can't
    silently regress."""
    emitted = ast_rules.collect_emitted_kinds(REPO)
    assert "loader_starved" in emitted
    assert any("loader.py" in site for site in emitted["loader_starved"])
