"""Native (C++) kernel tests: build, gather/normalize/layout parity with
NumPy, bucket-planner parity between native and Python fallback, and the
threaded loader path."""

import numpy as np
import pytest

from distributeddataparallel_tpu import native


def test_native_builds_and_loads():
    # The toolchain is part of this environment; the library must build.
    assert native.available(), "libddp_native.so failed to build/load"


def test_native_builds_from_clean_tree(monkeypatch):
    """Round-1 regression: the lazy build must work with no prebuilt .so.

    `make SO=.dot.tmp` used to fall through to the `clean` rule (GNU make
    skips dot-prefixed targets when picking a default goal), silently
    producing nothing and disabling every native kernel forever.
    """
    import os

    if os.path.exists(native._SO):
        os.unlink(native._SO)
    monkeypatch.setattr(native, "_tried", False)
    monkeypatch.setattr(native, "_lib", None)
    assert native._build(), "fresh build produced no .so"
    assert os.path.exists(native._SO)
    assert native.available(), "freshly built .so failed to load"


def test_gather_rows_matches_numpy():
    rng = np.random.default_rng(0)
    src = rng.normal(size=(100, 7, 3)).astype(np.float32)
    idx = rng.integers(0, 100, size=33)
    np.testing.assert_array_equal(native.gather_rows(src, idx), src[idx])
    # non-f32 falls back, same result
    src16 = src.astype(np.float16)
    np.testing.assert_array_equal(native.gather_rows(src16, idx), src16[idx])


def test_gather_normalize_u8_matches_reference_transform():
    rng = np.random.default_rng(1)
    src = rng.integers(0, 256, size=(50, 8, 8, 3), dtype=np.uint8)
    idx = rng.integers(0, 50, size=20)
    got = native.gather_normalize_u8(src, idx)
    want = ((src[idx].astype(np.float32) / 255.0) - 0.5) / 0.5
    np.testing.assert_allclose(got, want, atol=1e-6)


def test_chw_to_hwc():
    rng = np.random.default_rng(2)
    src = rng.normal(size=(4, 3, 5, 6)).astype(np.float32)
    got = native.chw_to_hwc(src)
    np.testing.assert_array_equal(got, src.transpose(0, 2, 3, 1))


def test_plan_buckets_native_matches_python(monkeypatch):
    rng = np.random.default_rng(3)
    sizes = [int(s) for s in rng.integers(1, 2000, size=40)]
    got = native.plan_buckets(sizes, 4096)

    # Force the pure-Python fallback and compare exactly.
    monkeypatch.setattr(native, "_load", lambda: None)
    want = native.plan_buckets(sizes, 4096)
    assert got == want
    # structural invariants: every leaf exactly once, reverse-ordered
    flat = [i for b in got for i in b]
    assert sorted(flat) == list(range(40))
    assert flat == list(range(39, -1, -1))
    # no bucket except singletons exceeds the cap
    for b in got:
        if len(b) > 1:
            assert sum(sizes[i] for i in b) <= 4096


def test_plan_buckets_oversize_leaf():
    assert native.plan_buckets([10_000], 4096) == [[0]]
    assert native.plan_buckets([], 4096) == []


def test_threaded_loader_matches_sync(devices):
    import jax

    import distributeddataparallel_tpu as ddp
    from distributeddataparallel_tpu.data import DataLoader, SyntheticClassification

    mesh = ddp.make_mesh(("data",))
    ds = SyntheticClassification(num_examples=256)
    a = DataLoader(ds, per_replica_batch=4, mesh=mesh, seed=0)
    b = DataLoader(ds, per_replica_batch=4, mesh=mesh, seed=0, workers=1)
    a.set_epoch(1)
    b.set_epoch(1)
    batches_a = [jax.device_get(x) for x in a]
    batches_b = [jax.device_get(x) for x in b]
    assert len(batches_a) == len(batches_b) > 0
    for x, y in zip(batches_a, batches_b):
        np.testing.assert_array_equal(x["image"], y["image"])
        np.testing.assert_array_equal(x["label"], y["label"])


def test_u8_dataset_matches_f32_through_loader(devices):
    """keep_u8 + fused normalize-on-gather == pre-normalized f32 path."""
    import jax

    import distributeddataparallel_tpu as ddp
    from distributeddataparallel_tpu.data import DataLoader
    from distributeddataparallel_tpu.data.datasets import (
        ArrayDataset,
        normalize_images,
    )

    rng = np.random.default_rng(0)
    u8 = rng.integers(0, 256, size=(128, 32, 32, 3), dtype=np.uint8)
    labels = rng.integers(0, 10, size=128).astype(np.int32)
    ds_u8 = ArrayDataset(u8, labels, normalize_u8=True)
    ds_f32 = ArrayDataset(normalize_images(u8), labels)

    mesh = ddp.make_mesh(("data",))
    a = DataLoader(ds_u8, per_replica_batch=4, mesh=mesh, seed=0)
    b = DataLoader(ds_f32, per_replica_batch=4, mesh=mesh, seed=0)
    for x, y in zip(a, b):
        np.testing.assert_allclose(
            jax.device_get(x["image"]), jax.device_get(y["image"]), atol=1e-6
        )
        assert jax.device_get(x["image"]).dtype == np.float32


def test_threaded_loader_early_exit_no_stall(devices):
    """Breaking out of a threaded loader must not stall or leak."""
    import threading
    import time

    import distributeddataparallel_tpu as ddp
    from distributeddataparallel_tpu.data import DataLoader, SyntheticClassification

    mesh = ddp.make_mesh(("data",))
    ds = SyntheticClassification(num_examples=512)
    loader = DataLoader(ds, per_replica_batch=4, mesh=mesh, workers=1)
    n_before = threading.active_count()
    t0 = time.perf_counter()
    for i, _ in enumerate(loader):
        if i >= 2:
            break
    dt = time.perf_counter() - t0
    assert dt < 3.0, f"early exit stalled {dt:.1f}s"
    deadline = time.time() + 3.0
    while threading.active_count() > n_before and time.time() < deadline:
        time.sleep(0.05)
    assert threading.active_count() <= n_before, "producer thread leaked"


def test_u8_dataset_getitem_normalized():
    from distributeddataparallel_tpu.data.datasets import ArrayDataset

    u8 = np.full((4, 2, 2, 3), 255, dtype=np.uint8)
    ds = ArrayDataset(u8, np.zeros(4, np.int32), normalize_u8=True)
    img, _ = ds[0]
    assert img.dtype == np.float32
    np.testing.assert_allclose(img, 1.0)


def test_threaded_loader_propagates_errors(devices):
    import distributeddataparallel_tpu as ddp
    from distributeddataparallel_tpu.data import DataLoader, SyntheticClassification

    mesh = ddp.make_mesh(("data",))
    ds = SyntheticClassification(num_examples=256)
    loader = DataLoader(
        ds, per_replica_batch=4, mesh=mesh, workers=1,
        place_fn=lambda b: (_ for _ in ()).throw(RuntimeError("boom")),
    )
    with pytest.raises(RuntimeError, match="boom"):
        list(loader)
