"""ddplint v3: protocol-as-data model checker (PL4xx), timeline
conformance (PL405), the sync_lint concurrency AST rules (AL105-AL108),
and the consolidated perf_gate direction table.

The load-bearing contracts:

- every healthy shipped spec explores EXHAUSTIVELY (complete=True) and
  clean at CI scope (>=2 actors, >=1 fault) in seconds, so the protocol
  gate can run on every commit;
- every seeded mutant — one per rule id — is caught by exactly the
  intended rule, with a minimal counterexample trace on PL401;
- the conformance replay accepts the timeline an actual in-process
  fleet run (including an engine kill and drain-requeue) records, and
  rejects each hand-corrupted variant;
- the live modules and the checked specs share their constants
  (handoff.MAX_ATTEMPTS, the verdict ladder, the re-host election), so
  the plan the checker explores is the plan the runtime executes;
- perf_gate's ordered direction table classifies every metric name the
  bench headline actually emits the way the bench scripts document.
"""

import dataclasses
import json
import os
import subprocess
import sys
import time

import pytest

sys.path.insert(0, "/root/repo")
sys.path.insert(0, os.path.join("/root/repo", "scripts"))

from distributeddataparallel_tpu.analysis import (  # noqa: E402
    ast_rules,
    conformance,
    protocol,
    sync_lint,
)
from distributeddataparallel_tpu.analysis.protocol import (  # noqa: E402
    HANDOFF_MAX_ATTEMPTS,
    Transition,
    allocator_spec,
    elect_rehost_owner,
    handoff_spec,
    rendezvous_spec,
    router_spec,
    verdict_rung,
)
from distributeddataparallel_tpu.analysis.rules import (  # noqa: E402
    RULES,
    rule_table,
)

import check_events  # noqa: E402
import perf_gate  # noqa: E402

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

NEW_RULES = (
    "AL105", "AL106", "AL107", "AL108",
    "PL401", "PL402", "PL403", "PL404", "PL405", "PL406",
)


# ------------------------------------------------------- registration


def test_new_rules_registered():
    for rid in NEW_RULES:
        assert rid in RULES, rid
    table = rule_table()
    for rid in NEW_RULES:
        assert rid in table, rid


def test_live_modules_share_spec_constants():
    from distributeddataparallel_tpu.runtime.rendezvous import elect_rehost
    from distributeddataparallel_tpu.serving.handoff import MAX_ATTEMPTS

    assert MAX_ATTEMPTS == HANDOFF_MAX_ATTEMPTS
    assert elect_rehost(["h2", "h0", "h1"]) == "h0"
    assert elect_rehost_owner(["h2", "h0", "h1"]) == "h0"
    with pytest.raises(ValueError):
        elect_rehost_owner([])
    assert verdict_rung(True) == "drain"
    assert verdict_rung(False) == "fail"


# --------------------------------------------- healthy specs explore


def test_healthy_specs_exhaustive_and_clean():
    t0 = time.monotonic()
    reports = protocol.explore_all()
    elapsed = time.monotonic() - t0
    assert len(reports) == 4
    for rep in reports:
        assert rep.ok, (rep.spec.name, [str(f) for f in rep.findings])
        assert rep.complete, rep.spec.name
        assert rep.n_states > 0
    # CI budget: the acceptance bound is 30s; the suite is ~100x under
    assert elapsed < 30.0, f"exploration took {elapsed:.1f}s"


def test_spec_scope_has_actors_and_faults():
    # >=2 actors and >=1 fault action per distributed spec — the
    # small-scope hypothesis needs both to mean anything
    rdzv = rendezvous_spec()
    rout = router_spec()
    hand = handoff_spec()
    names = lambda s: {t.name for t in s.transitions}  # noqa: E731
    assert len({m for m, _st in rdzv.init()[0]}) >= 2
    assert "tombstone" in names(rdzv)
    assert "engine_die" in names(rout)
    assert "corrupt" in names(hand)


# ------------------------------------------------------ seeded mutants


def _rules_of(spec):
    rep = protocol.explore(spec)
    return {f.rule for f in rep.findings}, rep


@pytest.mark.parametrize("spec_fn,rule,needle", [
    (lambda: rendezvous_spec(fence=False), "PL401", "epoch-unique"),
    (lambda: rendezvous_spec(elect=lambda s: sorted(s)[-1]),
     "PL401", "rehost-owner"),
    (lambda: rendezvous_spec(barrier_guard=False),
     "PL401", "tombstone-barrier"),
    (lambda: router_spec(affinity_uses_prefill=True),
     "PL401", "affinity-tier"),
    (lambda: router_spec(complete_purges=False),
     "PL401", "drop-vs-complete"),
    (lambda: handoff_spec(dedup=False), "PL401", "at-most-once"),
    (lambda: allocator_spec(cow=False), "PL401", "cow-before-write"),
    (lambda: allocator_spec(conserve=False),
     "PL401", "refcount-conservation"),
])
def test_mutant_trips_invariant(spec_fn, rule, needle):
    rules, rep = _rules_of(spec_fn())
    assert rule in rules, (rep.spec.name, rules)
    msgs = [f.message for f in rep.findings if f.rule == rule]
    assert any(needle in m for m in msgs), msgs
    # PL401 counterexamples carry the minimal trace from the initial
    # state (BFS order): always present, bounded, starts at init
    for m in msgs:
        assert "init" in m, m


def test_mutant_escalate_missing_deadlocks():
    rules, rep = _rules_of(handoff_spec(escalate=False))
    assert "PL402" in rules, rules


def test_mutant_unreachable_state_pl403():
    spec = handoff_spec()
    spec = dataclasses.replace(spec, states=spec.states + ("limbo",))
    rules, rep = _rules_of(spec)
    assert "PL403" in rules, rules
    assert any("limbo" in f.message for f in rep.findings)


def test_mutant_dead_transition_pl404():
    spec = handoff_spec()
    spec = dataclasses.replace(
        spec,
        transitions=spec.transitions
        + (Transition("never_fires", "unsent", "failed"),),
    )
    rules, rep = _rules_of(spec)
    assert "PL404" in rules, rules
    assert any("never_fires" in f.message for f in rep.findings)


def test_mutant_malformed_spec_pl406():
    spec = dataclasses.replace(handoff_spec(), initial="bogus")
    rules, _rep = _rules_of(spec)
    assert "PL406" in rules, rules


# ------------------------------------------------------ sync_lint (AL)


def _lint(src, rel="distributeddataparallel_tpu/runtime/x.py"):
    return sync_lint.lint_source(src, rel)


def test_al105_blocking_socket():
    src = (
        "import socket\n"
        "def dial(h, p):\n"
        "    return socket.create_connection((h, p))\n"
    )
    assert [f.rule for f in _lint(src)] == ["AL105"]


def test_al105_waived_by_pragma():
    src = (
        "import socket\n"
        "def dial(h, p):\n"
        "    # ddplint: allow[blocking-socket] — caller retries\n"
        "    return socket.create_connection((h, p))\n"
    )
    assert _lint(src) == []


def test_al105_retry_call_covers_even_later_in_file():
    # the retry_call wrapper may appear AFTER the dial helper in file
    # order; the pre-pass must still credit it
    src = (
        "import socket\n"
        "def _dial(h, p):\n"
        "    return retry_call(lambda: socket.create_connection((h, p)))\n"
    )
    assert _lint(src) == []


def test_al106_wallclock_only_in_virtual_modules():
    src = (
        "import time\n"
        "def pump(self):\n"
        "    return time.monotonic()\n"
    )
    rel = "distributeddataparallel_tpu/serving/router.py"
    assert [f.rule for f in _lint(src, rel)] == ["AL106"]
    # same source outside the VirtualClock-replayable set: clean
    assert _lint(src, "distributeddataparallel_tpu/training/x.py") == []


def test_al107_host_sync_in_serve_loop():
    src = (
        "import numpy as np\n"
        "def step(self, x):\n"
        "    return np.asarray(x)\n"
        "def build(self, x):\n"
        "    return np.asarray(x)\n"
    )
    rel = "distributeddataparallel_tpu/serving/engine.py"
    found = _lint(src, rel)
    # only the serve-loop-shaped function (step) is flagged, not build
    assert [f.rule for f in found] == ["AL107"]
    assert "step()" in found[0].message


def test_al108_lock_discipline():
    src = (
        "import threading\n"
        "class Box:\n"
        "    def __init__(self):\n"
        "        self._lock = threading.Lock()\n"
        "        self._items = []\n"
        "    def put(self, x):\n"
        "        with self._lock:\n"
        "            self._items.append(x)\n"
        "    def drop(self):\n"
        "        self._items.pop()\n"
    )
    found = _lint(src)
    assert [f.rule for f in found] == ["AL108"]
    assert "drop()" in found[0].message


def test_tree_is_sync_lint_clean():
    # the shipped tree carries justified pragmas at every intentional
    # site; anything new must justify itself the same way
    targets = ast_rules.default_targets(REPO)
    assert sync_lint.lint_paths(targets, REPO) == []


# -------------------------------------------------- conformance (PL405)


def _clean_timeline():
    return [
        {"kind": "membership_epoch", "epoch": 1,
         "roster": ["h0", "h1", "h2"], "proc": 0},
        {"kind": "rdzv_rehost", "owner": "h0", "generation": 1},
        {"kind": "gang_verdict", "rung": "resize", "fault": "host-kill"},
        {"kind": "route_admit", "req": 0, "engine": "d0",
         "prefill": "p0", "affinity": False},
        {"kind": "kv_handoff", "req": 0, "attempts": 2},
        {"kind": "engine_verdict", "engine": "d0", "rung": "drain"},
        {"kind": "route_admit", "req": 0, "engine": "d1",
         "prefill": None, "affinity": False},
        {"kind": "route_admit", "req": 1, "engine": "d1",
         "prefill": None, "affinity": True},
    ]


def test_conformance_clean_timeline_passes():
    assert conformance.check_timeline(_clean_timeline()) == []


@pytest.mark.parametrize("corrupt,needle", [
    # affinity hit that still owns a prefill engine
    (lambda t: t.__setitem__(7, {
        "kind": "route_admit", "req": 1, "engine": "d1",
        "prefill": "p0", "affinity": True}), "affinity"),
    # same epoch committed with a different roster
    (lambda t: t.insert(1, {
        "kind": "membership_epoch", "epoch": 1,
        "roster": ["h0", "h1"], "proc": 1}), "forked membership"),
    # per-writer epoch going backwards
    (lambda t: t.insert(1, {
        "kind": "membership_epoch", "epoch": 0,
        "roster": ["h0", "h1", "h2"], "proc": 0}), "backwards"),
    # re-host onto a host outside the committed roster
    (lambda t: t.__setitem__(1, {
        "kind": "rdzv_rehost", "owner": "zz", "generation": 1}),
     "rehost-owner"),
    # store generation not fencing its predecessor
    (lambda t: t.insert(2, {
        "kind": "rdzv_rehost", "owner": "h1", "generation": 1}),
     "fence"),
    # rung off the declared gang ladder
    (lambda t: t.__setitem__(2, {
        "kind": "gang_verdict", "rung": "shrug"}), "ladder"),
    # handoff attempts past the NAK budget
    (lambda t: t.__setitem__(4, {
        "kind": "kv_handoff", "req": 0,
        "attempts": HANDOFF_MAX_ATTEMPTS + 1}), "NAK budget"),
    # handoff for a request never admitted through prefill
    (lambda t: t.append({
        "kind": "kv_handoff", "req": 99, "attempts": 1}), "nowhere"),
    # routing onto a tombstoned engine
    (lambda t: t.append({
        "kind": "route_admit", "req": 2, "engine": "d0",
        "prefill": None, "affinity": False}), "tombstone"),
    # re-admission with no engine_verdict in between (double-own)
    (lambda t: t.insert(5, {
        "kind": "route_admit", "req": 0, "engine": "d1",
        "prefill": None, "affinity": False}), "double-own"),
    # an engine dying twice
    (lambda t: t.append({
        "kind": "engine_verdict", "engine": "d0", "rung": "drain"}),
     "at most once"),
    # rung off the declared engine ladder
    (lambda t: t.append({
        "kind": "engine_verdict", "engine": "d1", "rung": "explode"}),
     "declared"),
])
def test_conformance_catches_corruption(corrupt, needle):
    timeline = _clean_timeline()
    corrupt(timeline)
    found = conformance.check_timeline(timeline)
    assert found, needle
    assert any(f.rule == "PL405" for f in found)
    assert any(needle in f.message for f in found), (
        needle, [str(f) for f in found],
    )


def test_conformance_ignores_foreign_kinds():
    # kinds outside the protocol vocabulary never trip the replay —
    # one checker serves training chaos AND serving fleet timelines
    records = [{"kind": "step", "step": 1}, {"kind": "mfu", "mfu": 0.1}]
    assert conformance.check_timeline(records) == []


# -------------------------------- conformance on a real fleet timeline


@pytest.fixture(scope="module")
def fleet_events_dir(tmp_path_factory):
    """One in-process fleet run — engine kill included — recorded to an
    events dir, shared by the conformance/CLI tests below."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from distributeddataparallel_tpu.models import TransformerLM, tiny_lm
    from distributeddataparallel_tpu.observability.events import (
        EventLog,
        events_path,
    )
    from distributeddataparallel_tpu.serving import (
        EngineConfig,
        FleetConfig,
        ServingFleet,
        VirtualClock,
    )

    out = tmp_path_factory.mktemp("fleet_events")
    cfg = tiny_lm(
        vocab_size=97, num_layers=2, num_heads=2, d_model=32, d_ff=64,
        max_seq_len=64, positional="learned", norm="layernorm",
        activation="gelu", tie_embeddings=True,
    )
    model = TransformerLM(cfg)
    params = model.init(
        jax.random.PRNGKey(0), jnp.zeros((1, 4), jnp.int32)
    )["params"]
    events = EventLog(events_path(str(out), 0), 0)
    clock = VirtualClock()
    fleet = ServingFleet(
        model, params,
        EngineConfig(num_slots=4, num_blocks=48, block_size=8,
                     prefill_chunk=8),
        FleetConfig(prefill=1, decode=2),
        events=events, time_fn=clock, check_invariants=True,
    )
    rng = np.random.default_rng(7)
    fids = [
        fleet.submit(rng.integers(1, cfg.vocab_size, 16 + i).tolist(), 6)
        for i in range(5)
    ]
    for _ in range(3):
        fleet.step()
        clock.tick()
    fleet.kill_engine("decode-0")
    steps = 0
    while fleet.has_work():
        fleet.step()
        clock.tick()
        steps += 1
        assert steps < 800, "fleet failed to drain"
    assert sorted(fleet.completed) == sorted(fids)
    return str(out)


def test_fleet_recorded_timeline_is_conformant(fleet_events_dir):
    from distributeddataparallel_tpu.observability.events import (
        load_timeline,
    )

    records = load_timeline(fleet_events_dir)
    assert records, "fleet run recorded no events"
    kinds = {r["kind"] for r in records}
    # the run exercised the protocol vocabulary, not just run_start
    assert {"route_admit", "kv_handoff", "engine_verdict"} <= kinds
    assert conformance.check_timeline(records) == []


def test_check_events_cli_conformance(fleet_events_dir, tmp_path):
    # events DIR: merged on the fly, conformant
    assert check_events.main(["--conformance", fleet_events_dir]) == 0
    # hand-corrupt the merged timeline: duplicate the engine_verdict
    # (schema-valid record, protocol-invalid history) -> exit 1
    src = os.path.join(fleet_events_dir, "timeline.jsonl")
    lines = open(src).read().splitlines()
    verdict = next(
        ln for ln in lines if json.loads(ln)["kind"] == "engine_verdict"
    )
    bad = tmp_path / "timeline.jsonl"
    bad.write_text("\n".join(lines + [verdict]) + "\n")
    assert check_events.main(["--conformance", str(bad)]) == 1


# ------------------------------------------------- ddplint CLI (PL4xx)


def test_ddplint_protocol_cli_clean():
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, "scripts", "ddplint.py"),
         "--protocol"],
        capture_output=True, text=True, cwd=REPO,
    )
    assert proc.returncode == 0, proc.stdout + proc.stderr
    for spec in ("rendezvous", "router", "handoff", "allocator"):
        assert f"proto [{spec}] ok" in proc.stdout, proc.stdout


def test_ddplint_list_rules_covers_new_layers():
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, "scripts", "ddplint.py"),
         "--list-rules"],
        capture_output=True, text=True, cwd=REPO,
    )
    assert proc.returncode == 0, proc.stdout + proc.stderr
    for rid in ("PL401", "PL405", "AL105", "AL108"):
        assert rid in proc.stdout, rid


# --------------------------------------- perf_gate direction table


#: every numeric metric the bench headline actually emits (bench.py
#: ``parsed.headline``), with its documented gate direction — the
#: whole contract the ordered _DIRECTION_TABLE must reproduce
BENCH_HEADLINE_DIRECTIONS = {
    "resnet50_img_s_chip": "higher",
    "resnet50_mfu": "higher",
    "gpt2_tok_s_chip": "higher",
    "gpt2_mfu": "higher",
    "llama_tok_s_chip": "higher",
    "llama_mfu": "higher",
    "decode_tok_s_chip_b256": "higher",
    "decode_hbm_util_b8": "higher",
    "decode_int8_llama_step_speedup": "higher",
    "decode_int8_gpt2_b8_step_speedup": "higher",
    "moe_e16_over_e4": "higher",
    "moe_roofline": "higher",
    "moe_ep_shard_frac_measured": "higher",
    "flash_vs_xla_block_speedup": "higher",
    "pp_interleaved_bubble_v4_over_v1": "lower",
    "zb_bubble_frac": "lower",
    "zb_step_s": "lower",
    "input_host_gather_img_s": "higher",
    "input_host_over_device": "higher",
    "token_gather_tok_s": "higher",
    "token_host_over_device": "higher",
    "resize_downtime_s": "lower",
    "restart_reclaimed_s": "higher",
    "integrity_overhead_frac": "lower",
    "z2_hwm_bytes": "lower",
    "z3_hwm_bytes": "lower",
    "z2_step_s": "lower",
    "z2_hwm_drop": "higher",
    "serve_tok_s": "higher",
    "serve_p99_ttft_s": "lower",
    "serve_cb_speedup": "higher",
    "spec_tok_s_speedup": "higher",
    "prefix_hit_frac": "higher",
    "prefill_flops_avoided_frac": "higher",
    "fastpath_p99_ttft_s": "lower",
    "fleet_tok_s_speedup": "higher",
    "fleet_p99_ttft_s": "lower",
    "handoff_s": "lower",
    "dropped_req_total": "hard-zero",
    "tuned_step_s": "lower",
    "tune_gain_frac": "higher",
    "ttft_queue_share_frac": "lower",
    "ttft_handoff_share_frac": "lower",
    "ttft_decomp_err_frac": "lower",
}


def test_bench_headline_directions_exhaustive():
    for name, want in BENCH_HEADLINE_DIRECTIONS.items():
        assert perf_gate._bench_direction(name) == want, name


def test_direction_table_order_carries_semantics():
    # row 1 (win suffixes) must beat row 4's broad cost patterns:
    # "step_speedup" CONTAINS "step_s", "_hit_frac" ends in "_frac",
    # "reclaimed_s" ends in "_s" and sits next to "restart"
    assert perf_gate._bench_direction("step_speedup") == "higher"
    assert perf_gate._bench_direction("restart_reclaimed_s") == "higher"
    # row 2 pins the TTFT-decomposition shares lower-better explicitly
    # — even a future "..._share_frac"-shaped win suffix in row 1 must
    # not flip them (and decomp error is never a win)
    assert perf_gate._bench_direction("ttft_queue_share_frac") == "lower"
    assert perf_gate._bench_direction("ttft_decomp_err_frac") == "lower"
    # row 3 (hard-zero) must beat row 4's plain "dropped"
    assert perf_gate._bench_direction("dropped_req_total") == "hard-zero"
    assert perf_gate._bench_direction("dropped_frames") == "lower"
    # unmatched names default higher
    assert perf_gate._bench_direction("goodput") == "higher"


def test_gate_metrics_for_maps_hard_zero_to_pairwise_lower():
    metrics = perf_gate.gate_metrics_for(
        {"dropped_req_total": 1.0, "serve_tok_s": 5.0, "handoff_s": 0.2},
        "bench", 0.05,
    )
    assert metrics["dropped_req_total"] == ("lower", 0.05)
    assert metrics["serve_tok_s"] == ("higher", 0.05)
    assert metrics["handoff_s"] == ("lower", 0.05)
