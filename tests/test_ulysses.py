"""Ulysses (all-to-all head-sharded) sequence parallelism tests: numerics
vs full attention (MHA + both GQA paths), LM forward parity, the DP×CP
train-step equivalence with cp_impl="ulysses", and the head-divisibility
guard.  Mirrors tests/test_context_parallel.py for the ring path."""

import functools

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest
from jax.sharding import PartitionSpec as P

import distributeddataparallel_tpu as ddp
from distributeddataparallel_tpu.data import shard_lm_batch
from distributeddataparallel_tpu.models import TransformerLM, tiny_lm
from distributeddataparallel_tpu.ops import lm_cross_entropy
from distributeddataparallel_tpu.ops.attention import (
    dot_product_attention,
    repeat_kv,
)
from distributeddataparallel_tpu.parallel import (
    make_cp_train_step,
    ulysses_attention,
)


def _ulysses_on_mesh(q, k, v, mesh, causal):
    fn = jax.shard_map(
        functools.partial(
            ulysses_attention, axis_name="seq", causal=causal, impl="xla"
        ),
        mesh=mesh,
        in_specs=(P(None, "seq"), P(None, "seq"), P(None, "seq")),
        out_specs=P(None, "seq"),
        check_vma=False,
    )
    return jax.jit(fn)(q, k, v)


@pytest.mark.parametrize("causal", [True, False])
def test_ulysses_matches_full(causal, devices):
    mesh = ddp.make_mesh(("seq",))  # 8-way: needs H % 8 == 0
    B, S, H, D = 2, 64, 8, 8
    key = jax.random.PRNGKey(0)
    q, k, v = (
        jax.random.normal(kk, (B, S, H, D))
        for kk in jax.random.split(key, 3)
    )
    ref = dot_product_attention(q, k, v, causal=causal)
    out = _ulysses_on_mesh(q, k, v, mesh, causal)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=1e-5)


@pytest.mark.parametrize("n_seq", [8, 4])
def test_ulysses_gqa_expand_path(n_seq, devices):
    """Hkv=2 does not divide the axis: kv heads are expanded to
    lcm(Hkv, n) before the all_to_all — full expansion to H at n=8,
    PARTIAL expansion (4 of 8 heads) at n=4."""
    mesh = ddp.make_mesh(
        ("seq",), shape=(n_seq,), devices=jax.devices()[:n_seq]
    )
    B, S, H, Hkv, D = 2, 64, 8, 2, 8
    key = jax.random.PRNGKey(1)
    kq, kk, kv = jax.random.split(key, 3)
    q = jax.random.normal(kq, (B, S, H, D))
    k = jax.random.normal(kk, (B, S, Hkv, D))
    v = jax.random.normal(kv, (B, S, Hkv, D))
    ref = dot_product_attention(
        q, repeat_kv(k, H // Hkv), repeat_kv(v, H // Hkv), causal=True
    )
    out = _ulysses_on_mesh(q, k, v, mesh, True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=1e-5)


def test_ulysses_gqa_native_path(devices):
    """Hkv=2 divides the 2-way axis: kv travels at its own head count and
    the local attention consumes GQA natively."""
    mesh = ddp.make_mesh(("seq",), shape=(2,), devices=jax.devices()[:2])
    B, S, H, Hkv, D = 2, 32, 4, 2, 8
    key = jax.random.PRNGKey(2)
    kq, kk, kv = jax.random.split(key, 3)
    q = jax.random.normal(kq, (B, S, H, D))
    k = jax.random.normal(kk, (B, S, Hkv, D))
    v = jax.random.normal(kv, (B, S, Hkv, D))
    ref = dot_product_attention(
        q, repeat_kv(k, H // Hkv), repeat_kv(v, H // Hkv), causal=True
    )
    out = _ulysses_on_mesh(q, k, v, mesh, True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=1e-5)


def test_ulysses_head_divisibility_guard(devices):
    """num_heads % axis size != 0 must raise at trace time, not silently
    misshard."""
    mesh = ddp.make_mesh(("seq",))  # 8-way
    B, S, H, D = 1, 64, 6, 8
    x = jnp.zeros((B, S, H, D))
    with pytest.raises(ValueError, match="num_heads"):
        _ulysses_on_mesh(x, x, x, mesh, True)


def test_ulysses_lm_forward_matches_single_device(devices):
    """Sequence-sharded forward with cp_impl='ulysses' (all_to_all + global
    RoPE positions) must reproduce the unsharded model's logits."""
    mesh = ddp.make_mesh(("seq",), shape=(2,), devices=jax.devices()[:2])
    cfg = tiny_lm(max_seq_len=64)
    cfg_u = tiny_lm(max_seq_len=64, cp_axis="seq", cp_impl="ulysses")
    model = TransformerLM(cfg)
    model_u = TransformerLM(cfg_u)
    toks = jax.random.randint(jax.random.PRNGKey(1), (2, 64), 0, 256)
    params = model.init(jax.random.PRNGKey(0), toks)["params"]

    ref = model.apply({"params": params}, toks)

    fn = jax.shard_map(
        lambda p, t: model_u.apply({"params": p}, t),
        mesh=mesh,
        in_specs=(P(), P(None, "seq")),
        out_specs=P(None, "seq"),
        check_vma=False,
    )
    out = jax.jit(fn)(params, toks)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-4)


def test_ulysses_train_step_matches_single_device(devices):
    """DP×CP(ulysses) (4 data × 2 seq) one train step == single-device
    step on the same global batch: same loss, same updated params."""
    mesh = ddp.make_mesh(("data", "seq"), shape=(4, 2))
    cfg = tiny_lm(max_seq_len=32)
    cfg_u = tiny_lm(max_seq_len=32, cp_axis="seq", cp_impl="ulysses")
    model = TransformerLM(cfg)
    model_u = TransformerLM(cfg_u)
    rng = np.random.default_rng(0)
    tokens = rng.integers(0, 256, size=(8, 33)).astype(np.int32)
    params = model.init(
        jax.random.PRNGKey(0), jnp.zeros((1, 32), jnp.int32)
    )["params"]
    tx = optax.sgd(0.1)

    def ref_loss(p):
        logits = model.apply({"params": p}, jnp.asarray(tokens[:, :-1]))
        return lm_cross_entropy(logits, jnp.asarray(tokens[:, 1:]))

    loss_ref, grads_ref = jax.value_and_grad(ref_loss)(params)
    updates, _ = tx.update(grads_ref, tx.init(params), params)
    params_ref = optax.apply_updates(params, updates)

    def loss_fn(p, batch, rng):
        logits = model_u.apply({"params": p}, batch["inputs"])
        return lm_cross_entropy(logits, batch["targets"]), {}

    state = ddp.TrainState.create(apply_fn=model_u.apply, params=params, tx=tx)
    state = ddp.broadcast_params(state, mesh)
    step = make_cp_train_step(loss_fn, mesh=mesh)
    batch = shard_lm_batch(tokens, mesh)
    state, metrics = step(state, batch, jax.random.PRNGKey(0))

    assert float(metrics["loss"]) == pytest.approx(float(loss_ref), rel=1e-5)
    for a, b in zip(
        jax.tree.leaves(state.params), jax.tree.leaves(params_ref)
    ):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=1e-5)


def test_dp_ulysses_tp_matches_single_device(devices):
    """DP(2) x CP(2, ulysses) x TP(2): the all_to_all operates on the
    TP-local head shard (H/tp % n_seq must hold) — must equal the
    single-device step."""
    import dataclasses

    mesh = ddp.make_mesh(("data", "seq", "model"), shape=(2, 2, 2))
    cfg = tiny_lm(num_heads=4, num_kv_heads=2, d_model=32, d_ff=64,
                  max_seq_len=32)
    cfg_x = dataclasses.replace(
        cfg, cp_axis="seq", cp_impl="ulysses", tp_axis="model"
    )
    model, model_x = TransformerLM(cfg), TransformerLM(cfg_x)
    rng = np.random.default_rng(8)
    tokens = rng.integers(0, 256, size=(4, 33)).astype(np.int32)
    params = model.init(
        jax.random.PRNGKey(0), jnp.zeros((1, 32), jnp.int32)
    )["params"]
    tx = optax.sgd(0.1)

    def ref_loss(p):
        logits = model.apply({"params": p}, jnp.asarray(tokens[:, :-1]))
        return lm_cross_entropy(logits, jnp.asarray(tokens[:, 1:]))

    loss_ref, grads_ref = jax.value_and_grad(ref_loss)(params)
    updates, _ = tx.update(grads_ref, tx.init(params), params)
    params_ref = optax.apply_updates(params, updates)

    def loss_fn(p, batch, rng):
        logits = model_x.apply({"params": p}, batch["inputs"])
        return lm_cross_entropy(logits, batch["targets"]), {}

    state = ddp.TrainState.create(
        apply_fn=model_x.apply, params=params, tx=tx
    )
    state = ddp.shard_state_tp(state, mesh)
    step = ddp.make_train_step(
        loss_fn, mesh=mesh, cp_axis="seq", tp_axis="model", donate=False
    )
    state, metrics = step(
        state, shard_lm_batch(tokens, mesh), jax.random.PRNGKey(0)
    )
    assert float(metrics["loss"]) == pytest.approx(float(loss_ref), rel=1e-5)
    for a, b in zip(
        jax.tree.leaves(state.params), jax.tree.leaves(params_ref)
    ):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=2e-5)
