"""Elastic checkpoint reshard: save at N devices, restore and continue at
M != N.  The invariant in every case: with the same GLOBAL batches, the
resharded continuation reproduces the uninterrupted N-device run's losses
and parameters exactly (the flat layouts' padding is mechanical, not
semantic)."""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest
from jax.sharding import Mesh

import distributeddataparallel_tpu as ddp
from distributeddataparallel_tpu.data.loader import shard_batch
from distributeddataparallel_tpu.models import TransformerLM, tiny_lm
from distributeddataparallel_tpu.ops import lm_cross_entropy
from distributeddataparallel_tpu.training.checkpoint import Checkpointer
from distributeddataparallel_tpu.training.elastic import (
    elastic_restore,
    topology_meta,
)


def _cfg(**over):
    base = dict(
        num_layers=2, num_heads=2, d_model=32, d_ff=64, max_seq_len=32,
    )
    base.update(over)
    return tiny_lm(**base)


def _mesh(n):
    return Mesh(np.array(jax.devices()[:n]), ("data",))


def _batches(k=4, rows=8, vocab=256):
    rng = np.random.default_rng(0)
    return [
        rng.integers(0, vocab, size=(rows, 17)).astype(np.int32)
        for _ in range(k)
    ]


def _loss_fn(model):
    def loss_fn(p, batch, rng):
        toks = batch["tokens"]
        logits = model.apply({"params": p}, toks[:, :-1])
        return lm_cross_entropy(logits, toks[:, 1:]), {}

    return loss_fn


def test_elastic_replicated_8_to_4(tmp_path, devices):
    """Plain DP: train 2 steps @8, save, restore @4, continue 2 steps —
    losses and params match the uninterrupted 8-device run (same global
    batches throughout)."""
    cfg = _cfg()
    model = TransformerLM(cfg)
    params = model.init(
        jax.random.PRNGKey(0), jnp.zeros((1, 16), jnp.int32)
    )["params"]
    tx = optax.adam(1e-2)
    batches = _batches()
    loss_fn = _loss_fn(model)

    def fresh(mesh):
        st = ddp.TrainState.create(
            apply_fn=model.apply, params=params, tx=tx
        )
        st = ddp.broadcast_params(st, mesh)
        step = ddp.make_train_step(loss_fn, mesh=mesh, donate=False)
        return st, step

    # Uninterrupted @8.
    mesh8 = _mesh(8)
    st, step = fresh(mesh8)
    ref_losses = []
    for t in batches:
        st, m = step(st, shard_batch({"tokens": t}, mesh8), jax.random.PRNGKey(0))
        ref_losses.append(float(m["loss"]))
    ref_params = jax.tree.map(np.asarray, st.params)

    # Interrupted: 2 steps @8, save, reshard to @4, finish.
    st, step = fresh(mesh8)
    for t in batches[:2]:
        st, m = step(st, shard_batch({"tokens": t}, mesh8), jax.random.PRNGKey(0))
    ckpt = Checkpointer(str(tmp_path))
    ckpt.save(st, 0, meta=topology_meta(mesh8, "replicated"))
    ckpt.wait()

    mesh4 = _mesh(4)
    st4, step4 = fresh(mesh4)
    st4, next_epoch = elastic_restore(
        ckpt, st4, mesh4, layout="replicated"
    )
    assert next_epoch == 1
    losses = ref_losses[:2]
    for t in batches[2:]:
        st4, m = step4(
            st4, shard_batch({"tokens": t}, mesh4), jax.random.PRNGKey(0)
        )
        losses.append(float(m["loss"]))
    np.testing.assert_allclose(losses, ref_losses, rtol=1e-6)
    for a, b in zip(jax.tree.leaves(st4.params), jax.tree.leaves(ref_params)):
        # atol 1e-5: pmean over 8 vs 4 devices reduces in a
        # different fp order
        np.testing.assert_allclose(np.asarray(a), b, atol=1e-5)


def test_elastic_zero1_8_to_4(tmp_path, devices):
    """ZeRO-1: the flat opt vectors bake in N (padded to 8 chunks); the
    reshard truncates the tail padding and re-pads for 4 — adam moments
    continue exactly."""
    # d_model 28 / vocab 251: park the total param count off the
    # 8-chunk alignment so the padded flat sizes actually differ
    cfg = _cfg(vocab_size=251, d_model=28, d_ff=52, num_layers=3)
    model = TransformerLM(cfg)
    params = model.init(
        jax.random.PRNGKey(0), jnp.zeros((1, 16), jnp.int32)
    )["params"]
    tx = optax.adam(1e-2)
    batches = _batches(vocab=251)
    loss_fn = _loss_fn(model)

    def fresh(mesh):
        st = ddp.zero_state(
            apply_fn=model.apply, params=params, tx=tx, mesh=mesh
        )
        step = ddp.make_train_step(
            loss_fn, mesh=mesh, zero=True, donate=False
        )
        return st, step

    mesh8 = _mesh(8)
    st, step = fresh(mesh8)
    ref_losses = []
    for t in batches:
        st, m = step(st, shard_batch({"tokens": t}, mesh8), jax.random.PRNGKey(0))
        ref_losses.append(float(m["loss"]))
    ref_params = jax.tree.map(np.asarray, st.params)

    st, step = fresh(mesh8)
    for t in batches[:2]:
        st, _ = step(st, shard_batch({"tokens": t}, mesh8), jax.random.PRNGKey(0))
    ckpt = Checkpointer(str(tmp_path))
    ckpt.save(st, 0, meta=topology_meta(mesh8, "zero1"))
    ckpt.wait()

    mesh4 = _mesh(4)
    st4, step4 = fresh(mesh4)
    # The flat opt shapes REALLY differ across topologies (the bug this
    # feature fixes): assert the precondition so the test can't pass
    # vacuously.
    olds = {l.shape for l in jax.tree.leaves(st.opt_state) if l.ndim == 1}
    news = {l.shape for l in jax.tree.leaves(st4.opt_state) if l.ndim == 1}
    assert olds != news, (olds, news)
    st4, _ = elastic_restore(ckpt, st4, mesh4, layout="zero1")
    losses = ref_losses[:2]
    for t in batches[2:]:
        st4, m = step4(
            st4, shard_batch({"tokens": t}, mesh4), jax.random.PRNGKey(0)
        )
        losses.append(float(m["loss"]))
    np.testing.assert_allclose(losses, ref_losses, rtol=1e-6)
    for a, b in zip(jax.tree.leaves(st4.params), jax.tree.leaves(ref_params)):
        # atol 1e-5: pmean over 8 vs 4 devices reduces in a
        # different fp order
        np.testing.assert_allclose(np.asarray(a), b, atol=1e-5)


def test_elastic_fsdp_8_to_4(tmp_path, devices):
    """FSDP: params AND opt state are flats whose chunk sizes bake in N;
    both reshard and the run continues exactly."""
    from distributeddataparallel_tpu.parallel.fsdp import (
        fsdp_gather_params,
        fsdp_state,
        make_fsdp_train_step,
    )

    cfg = _cfg(scan_layers=True, vocab_size=251, d_model=28, d_ff=52,
               num_layers=3)
    model = TransformerLM(cfg)
    params = model.init(
        jax.random.PRNGKey(0), jnp.zeros((1, 16), jnp.int32)
    )["params"]
    tx = optax.adam(1e-2)
    batches = _batches(vocab=251)

    def fresh(mesh):
        st = fsdp_state(cfg, params, tx, mesh, apply_fn=model.apply)
        step = make_fsdp_train_step(cfg, mesh=mesh, donate=False)
        return st, step

    mesh8 = _mesh(8)
    st, step = fresh(mesh8)
    ref_losses = []
    for t in batches:
        st, m = step(st, shard_batch({"tokens": t}, mesh8), jax.random.PRNGKey(0))
        ref_losses.append(float(m["loss"]))
    ref_params = jax.tree.map(
        np.asarray, fsdp_gather_params(cfg, st, mesh8)
    )

    st, step = fresh(mesh8)
    for t in batches[:2]:
        st, _ = step(st, shard_batch({"tokens": t}, mesh8), jax.random.PRNGKey(0))
    ckpt = Checkpointer(str(tmp_path))
    ckpt.save(st, 0, meta=topology_meta(mesh8, "fsdp"))
    ckpt.wait()

    mesh4 = _mesh(4)
    st4, step4 = fresh(mesh4)
    assert st4.params["layers"].shape != st.params["layers"].shape
    st4, _ = elastic_restore(ckpt, st4, mesh4, layout="fsdp", cfg=cfg)
    losses = ref_losses[:2]
    for t in batches[2:]:
        st4, m = step4(
            st4, shard_batch({"tokens": t}, mesh4), jax.random.PRNGKey(0)
        )
        losses.append(float(m["loss"]))
    np.testing.assert_allclose(losses, ref_losses, rtol=1e-6)
    got = fsdp_gather_params(cfg, st4, mesh4)
    for a, b in zip(jax.tree.leaves(got), jax.tree.leaves(ref_params)):
        # atol 1e-5: pmean over 8 vs 4 devices reduces in a
        # different fp order
        np.testing.assert_allclose(np.asarray(a), b, atol=1e-5)


def test_elastic_layout_mismatch_rejected(tmp_path, devices):
    cfg = _cfg()
    model = TransformerLM(cfg)
    params = model.init(
        jax.random.PRNGKey(0), jnp.zeros((1, 16), jnp.int32)
    )["params"]
    mesh8 = _mesh(8)
    st = ddp.TrainState.create(
        apply_fn=model.apply, params=params, tx=optax.sgd(0.1)
    )
    st = ddp.broadcast_params(st, mesh8)
    ckpt = Checkpointer(str(tmp_path))
    ckpt.save(st, 0, meta=topology_meta(mesh8, "replicated"))
    ckpt.wait()
    with pytest.raises(ValueError, match="layout"):
        elastic_restore(ckpt, st, _mesh(4), layout="zero1")


def test_elastic_cli_resume_at_different_device_count(tmp_path, devices):
    """dpp.py end-to-end: checkpoint @8 fake devices, --resume @4 — the
    run continues from the saved epoch instead of crashing on the
    resharded state.  Subprocesses: the CPU device count is fixed at
    backend init, so each topology needs its own process."""
    import pathlib
    import subprocess
    import sys

    repo = str(pathlib.Path(__file__).resolve().parents[1])
    common = [
        sys.executable, str(pathlib.Path(repo) / "dpp.py"),
        "--device", "cpu",
        "--model", "gpt2",
        "--layers", "2",
        "--d-model", "32",
        "--seq-len", "32",
        "--vocab-size", "64",
        "--zero",
        "--optimizer", "adam",
        "--num-examples", "64",
        "--log-every", "4",
        "--checkpoint-dir", str(tmp_path),
    ]
    r1 = subprocess.run(
        common + ["--fake-devices", "8", "--batch-size", "4",
                  "--epochs", "1"],
        capture_output=True, text=True, timeout=240,
    )
    assert r1.returncode == 0, r1.stderr[-2000:]
    r2 = subprocess.run(
        common + ["--fake-devices", "4", "--batch-size", "8",
                  "--epochs", "2", "--resume"],
        capture_output=True, text=True, timeout=240,
    )
    assert r2.returncode == 0, r2.stderr[-2000:]
    # Resumed at epoch 1, not 0 (log lines go to stderr).
    log = r2.stdout + r2.stderr
    assert "Epoch 1," in log and "Epoch 0," not in log, log[-2000:]


def test_elastic_fsdp_tp_reshape(tmp_path, devices):
    """FSDP x TP reshard (VERDICT r3 weak 6): save at (data=4, tp=2),
    restore at (data=2, tp=4) AND at pure-DP (data=8, tp=1) — the
    segmented flats round-trip through the full tree, Adam moments
    included, and the continuation reproduces the uninterrupted run."""
    import dataclasses

    from distributeddataparallel_tpu.parallel.fsdp import (
        fsdp_gather_params,
        fsdp_state,
        make_fsdp_train_step,
    )

    cfg = _cfg(
        scan_layers=True, vocab_size=251, d_model=64, d_ff=128,
        num_layers=2, num_heads=4,
    )
    model = TransformerLM(cfg)
    params = model.init(
        jax.random.PRNGKey(0), jnp.zeros((1, 16), jnp.int32)
    )["params"]
    tx = optax.adam(1e-2)
    batches = _batches(vocab=251)

    def fresh(mesh, tp):
        c = dataclasses.replace(cfg, tp_axis="model" if tp > 1 else None)
        st = fsdp_state(
            c, params, tx, mesh, tp_axis="model" if tp > 1 else None
        )
        step = make_fsdp_train_step(
            c, mesh=mesh, tp_axis="model" if tp > 1 else None, donate=False
        )
        return c, st, step

    def mesh_of(n_data, n_tp):
        if n_tp == 1:
            return _mesh(n_data)
        return Mesh(
            np.array(jax.devices()[: n_data * n_tp]).reshape(n_data, n_tp),
            ("data", "model"),
        )

    # Uninterrupted reference at (4, 2).
    mesh42 = mesh_of(4, 2)
    c42, st, step = fresh(mesh42, 2)
    ref_losses = []
    for t in batches:
        st, m = step(
            st, shard_batch({"tokens": t}, mesh42), jax.random.PRNGKey(0)
        )
        ref_losses.append(float(m["loss"]))
    ref_params = jax.tree.map(
        np.asarray,
        fsdp_gather_params(c42, st, mesh42, tp_axis="model", host=True),
    )

    # Interrupted: 2 steps at (4, 2), save with tp topology metadata.
    c42, st, step = fresh(mesh42, 2)
    for t in batches[:2]:
        st, _ = step(
            st, shard_batch({"tokens": t}, mesh42), jax.random.PRNGKey(0)
        )
    ckpt = Checkpointer(str(tmp_path))
    ckpt.save(st, 0, meta=topology_meta(mesh42, "fsdp", tp_axis="model"))
    ckpt.wait()

    for n_data, n_tp in ((2, 4), (8, 1)):
        mesh_n = mesh_of(n_data, n_tp)
        c_n, st_n, step_n = fresh(mesh_n, n_tp)
        st_n, _ = elastic_restore(
            ckpt, st_n, mesh_n, layout="fsdp", cfg=c_n,
            tp_axis="model" if n_tp > 1 else None,
        )
        losses = ref_losses[:2]
        for t in batches[2:]:
            st_n, m = step_n(
                st_n, shard_batch({"tokens": t}, mesh_n),
                jax.random.PRNGKey(0),
            )
            losses.append(float(m["loss"]))
        np.testing.assert_allclose(
            losses, ref_losses, rtol=2e-6,
            err_msg=f"(data={n_data}, tp={n_tp})",
        )
        got = fsdp_gather_params(
            c_n, st_n, mesh_n,
            tp_axis="model" if n_tp > 1 else None, host=True,
        )
        for a, b in zip(jax.tree.leaves(got), jax.tree.leaves(ref_params)):
            np.testing.assert_allclose(
                np.asarray(a), b, atol=2e-5,
                err_msg=f"(data={n_data}, tp={n_tp})",
            )


def test_elastic_zero1_tp_reshape(tmp_path, devices):
    """ZeRO-1 x TP reshard: params carry N-independent global shapes
    (orbax re-slices), and the (data, tp)-interleaved opt flats round-
    trip through full leaves — save at (4,2), resume at (2,4) and (8,1),
    Adam moments included."""
    import dataclasses

    cfg = _cfg(num_heads=4, d_model=64, d_ff=128, vocab_size=251)
    cfg_tp = dataclasses.replace(cfg, tp_axis="model")
    model_plain = TransformerLM(cfg)
    params = model_plain.init(
        jax.random.PRNGKey(0), jnp.zeros((1, 16), jnp.int32)
    )["params"]
    tx = optax.adam(1e-2)
    batches = _batches(vocab=251)

    def mesh_of(n_data, n_tp):
        if n_tp == 1:
            return _mesh(n_data)
        return Mesh(
            np.array(jax.devices()[: n_data * n_tp]).reshape(n_data, n_tp),
            ("data", "model"),
        )

    def fresh(mesh, tp):
        m = TransformerLM(cfg_tp if tp > 1 else cfg)
        st = ddp.zero_state(
            apply_fn=m.apply, params=params, tx=tx, mesh=mesh,
            tp_axis="model" if tp > 1 else None,
        )
        step = ddp.make_train_step(
            _loss_fn(m), mesh=mesh, zero=True,
            tp_axis="model" if tp > 1 else None, donate=False,
        )
        return st, step

    # Uninterrupted reference at (4, 2).
    mesh42 = mesh_of(4, 2)
    st, step = fresh(mesh42, 2)
    ref_losses = []
    for t in batches:
        st, m = step(
            st, shard_batch({"tokens": t}, mesh42), jax.random.PRNGKey(0)
        )
        ref_losses.append(float(m["loss"]))
    ref_params = jax.tree.map(np.asarray, st.params)

    # Interrupted: 2 steps, save with tp metadata.
    st, step = fresh(mesh42, 2)
    for t in batches[:2]:
        st, _ = step(
            st, shard_batch({"tokens": t}, mesh42), jax.random.PRNGKey(0)
        )
    ckpt = Checkpointer(str(tmp_path))
    ckpt.save(st, 0, meta=topology_meta(mesh42, "zero1", tp_axis="model"))
    ckpt.wait()

    for n_data, n_tp in ((2, 4), (8, 1)):
        mesh_n = mesh_of(n_data, n_tp)
        st_n, step_n = fresh(mesh_n, n_tp)
        st_n, _ = elastic_restore(
            ckpt, st_n, mesh_n, layout="zero1",
            tp_axis="model" if n_tp > 1 else None,
        )
        losses = ref_losses[:2]
        for t in batches[2:]:
            st_n, m = step_n(
                st_n, shard_batch({"tokens": t}, mesh_n),
                jax.random.PRNGKey(0),
            )
            losses.append(float(m["loss"]))
        np.testing.assert_allclose(
            losses, ref_losses, rtol=2e-6,
            err_msg=f"(data={n_data}, tp={n_tp})",
        )
        for (path, a), b in zip(
            jax.tree_util.tree_flatten_with_path(st_n.params)[0],
            jax.tree.leaves(ref_params),
        ):
            np.testing.assert_allclose(
                np.asarray(a), b, atol=2e-5,
                err_msg=f"(data={n_data}, tp={n_tp}) "
                + "/".join(str(getattr(k, "key", k)) for k in path),
            )


def test_elastic_zero1_ep_reshape(tmp_path, devices):
    """ZeRO-1 x EP reshard (VERDICT r4 missing 4): the (data, expert)-
    interleaved opt flats round-trip through full leaves — save at
    (data=4, ep=2), resume at (2, 4) and at pure-DP (8, 1), Adam
    moments included."""
    import dataclasses

    cfg = _cfg(moe_experts=4, moe_top_k=1, d_model=32, d_ff=64,
               vocab_size=251)
    model_plain = TransformerLM(cfg)
    params = model_plain.init(
        jax.random.PRNGKey(0), jnp.zeros((1, 16), jnp.int32)
    )["params"]
    tx = optax.adam(1e-2)
    batches = _batches(vocab=251)

    def mesh_of(n_data, n_ep):
        if n_ep == 1:
            return _mesh(n_data)
        return Mesh(
            np.array(jax.devices()[: n_data * n_ep]).reshape(n_data, n_ep),
            ("data", "expert"),
        )

    def fresh(mesh, ep):
        m = TransformerLM(
            dataclasses.replace(cfg, ep_axis="expert" if ep > 1 else None)
        )
        st = ddp.zero_state(
            apply_fn=m.apply, params=params, tx=tx, mesh=mesh,
            ep_axis="expert" if ep > 1 else None,
        )
        step = ddp.make_train_step(
            _loss_fn(m), mesh=mesh, zero=True,
            ep_axis="expert" if ep > 1 else None, donate=False,
        )
        return st, step

    mesh42 = mesh_of(4, 2)
    st, step = fresh(mesh42, 2)
    ref_losses = []
    for t in batches:
        st, m = step(
            st, shard_batch({"tokens": t}, mesh42), jax.random.PRNGKey(0)
        )
        ref_losses.append(float(m["loss"]))
    ref_params = jax.tree.map(np.asarray, st.params)

    st, step = fresh(mesh42, 2)
    for t in batches[:2]:
        st, _ = step(
            st, shard_batch({"tokens": t}, mesh42), jax.random.PRNGKey(0)
        )
    ckpt = Checkpointer(str(tmp_path))
    ckpt.save(st, 0, meta=topology_meta(mesh42, "zero1", ep_axis="expert"))
    ckpt.wait()

    for n_data, n_ep in ((2, 4), (8, 1)):
        mesh_n = mesh_of(n_data, n_ep)
        st_n, step_n = fresh(mesh_n, n_ep)
        st_n, _ = elastic_restore(
            ckpt, st_n, mesh_n, layout="zero1",
            ep_axis="expert" if n_ep > 1 else None,
        )
        losses = ref_losses[:2]
        for t in batches[2:]:
            st_n, m = step_n(
                st_n, shard_batch({"tokens": t}, mesh_n),
                jax.random.PRNGKey(0),
            )
            losses.append(float(m["loss"]))
        np.testing.assert_allclose(
            losses, ref_losses, rtol=2e-6,
            err_msg=f"(data={n_data}, ep={n_ep})",
        )
        for (path, a), b in zip(
            jax.tree_util.tree_flatten_with_path(st_n.params)[0],
            jax.tree.leaves(ref_params),
        ):
            np.testing.assert_allclose(
                np.asarray(a), b, atol=2e-5,
                err_msg=f"(data={n_data}, ep={n_ep}) "
                + "/".join(str(getattr(k, "key", k)) for k in path),
            )


def test_elastic_zero1_pp_reshape(tmp_path, devices):
    """ZeRO-1 x PP reshard incl. STAGE-COUNT changes (VERDICT r4 missing
    4): save at (data=2, pp=4), resume at (data=4, pp=2) and at pure-DP
    (8, 1) — the stacked-layer stage shards reassemble through full
    leaves, Adam moments exact."""
    from distributeddataparallel_tpu.parallel.pipeline_parallel import (
        make_pp_train_step,
    )

    cfg = _cfg(num_layers=4, scan_layers=True, d_model=32, d_ff=64,
               vocab_size=251)
    model = TransformerLM(cfg)
    params = model.init(
        jax.random.PRNGKey(0), jnp.zeros((1, 16), jnp.int32)
    )["params"]
    tx = optax.adam(1e-2)
    batches = _batches(vocab=251)

    def mesh_of(n_data, n_pp):
        if n_pp == 1:
            return _mesh(n_data)
        return Mesh(
            np.array(jax.devices()[: n_data * n_pp]).reshape(n_data, n_pp),
            ("data", "pipe"),
        )

    def fresh(mesh, pp):
        st = ddp.zero_state(
            apply_fn=None, params=params, tx=tx, mesh=mesh,
            pp_axis="pipe" if pp > 1 else None,
        )
        if pp > 1:
            step = make_pp_train_step(
                cfg, mesh=mesh, microbatches=2, donate=False, zero=True
            )
        else:
            step = ddp.make_train_step(
                _loss_fn(model), mesh=mesh, zero=True, donate=False
            )
        return st, step

    mesh24 = mesh_of(2, 4)
    st, step = fresh(mesh24, 4)
    ref_losses = []
    for t in batches:
        st, m = step(
            st, shard_batch({"tokens": t}, mesh24), jax.random.PRNGKey(0)
        )
        ref_losses.append(float(m["loss"]))
    ref_params = jax.tree.map(np.asarray, st.params)

    st, step = fresh(mesh24, 4)
    for t in batches[:2]:
        st, _ = step(
            st, shard_batch({"tokens": t}, mesh24), jax.random.PRNGKey(0)
        )
    ckpt = Checkpointer(str(tmp_path))
    ckpt.save(st, 0, meta=topology_meta(mesh24, "zero1", pp_axis="pipe"))
    ckpt.wait()

    for n_data, n_pp in ((4, 2), (8, 1)):
        mesh_n = mesh_of(n_data, n_pp)
        st_n, step_n = fresh(mesh_n, n_pp)
        st_n, _ = elastic_restore(
            ckpt, st_n, mesh_n, layout="zero1",
            pp_axis="pipe" if n_pp > 1 else None,
        )
        losses = ref_losses[:2]
        for t in batches[2:]:
            st_n, m = step_n(
                st_n, shard_batch({"tokens": t}, mesh_n),
                jax.random.PRNGKey(0),
            )
            losses.append(float(m["loss"]))
        # PP microbatching changes the reduction ORDER of the loss mean
        # (2 microbatches vs 1) but not the gradients/params at these
        # sizes; losses match to fp tolerance.
        np.testing.assert_allclose(
            losses, ref_losses, rtol=2e-5,
            err_msg=f"(data={n_data}, pp={n_pp})",
        )
        for (path, a), b in zip(
            jax.tree_util.tree_flatten_with_path(st_n.params)[0],
            jax.tree.leaves(ref_params),
        ):
            np.testing.assert_allclose(
                np.asarray(a), b, atol=3e-5,
                err_msg=f"(data={n_data}, pp={n_pp}) "
                + "/".join(str(getattr(k, "key", k)) for k in path),
            )


def test_elastic_replicated_pp_stage_change(tmp_path, devices):
    """Plain (non-ZeRO) PP: params are globally-shaped stacked leaves, so
    a stage-count change (pp=4 -> pp=2) is an exact-topology restore —
    orbax re-slices to the new mesh's shardings."""
    from distributeddataparallel_tpu.parallel.pipeline_parallel import (
        make_pp_train_step,
        shard_state_pp,
    )

    cfg = _cfg(num_layers=4, scan_layers=True, vocab_size=251)
    model = TransformerLM(cfg)
    params = model.init(
        jax.random.PRNGKey(0), jnp.zeros((1, 16), jnp.int32)
    )["params"]
    tx = optax.adam(1e-2)
    batches = _batches(vocab=251)

    def fresh(n_data, n_pp):
        mesh = Mesh(
            np.array(jax.devices()[: n_data * n_pp]).reshape(n_data, n_pp),
            ("data", "pipe"),
        )
        st = ddp.TrainState.create(apply_fn=None, params=params, tx=tx)
        st = shard_state_pp(st, mesh)
        step = make_pp_train_step(cfg, mesh=mesh, microbatches=2,
                                  donate=False)
        return st, step, mesh

    st, step, mesh24 = fresh(2, 4)
    ref_losses = []
    for t in batches:
        st, m = step(
            st, shard_batch({"tokens": t}, mesh24), jax.random.PRNGKey(0)
        )
        ref_losses.append(float(m["loss"]))
    ref_params = jax.tree.map(np.asarray, st.params)

    st, step, _ = fresh(2, 4)
    for t in batches[:2]:
        st, _ = step(
            st, shard_batch({"tokens": t}, mesh24), jax.random.PRNGKey(0)
        )
    ckpt = Checkpointer(str(tmp_path))
    ckpt.save(st, 0, meta=topology_meta(mesh24, "replicated"))
    ckpt.wait()

    st_n, step_n, mesh42 = fresh(4, 2)
    st_n, _ = elastic_restore(ckpt, st_n, mesh42, layout="replicated")
    losses = ref_losses[:2]
    for t in batches[2:]:
        st_n, m = step_n(
            st_n, shard_batch({"tokens": t}, mesh42), jax.random.PRNGKey(0)
        )
        losses.append(float(m["loss"]))
    np.testing.assert_allclose(losses, ref_losses, rtol=2e-5)
    for (path, a), b in zip(
        jax.tree_util.tree_flatten_with_path(st_n.params)[0],
        jax.tree.leaves(ref_params),
    ):
        np.testing.assert_allclose(
            np.asarray(a), b, atol=2e-5,
            err_msg="/".join(str(getattr(k, "key", k)) for k in path),
        )


def test_elastic_rejects_interleaved_geometry_change(tmp_path, devices):
    """--pp-virtual layer storage bakes (pp, virtual) into the row order;
    resuming at a different geometry must fail loudly, replicated layout
    included."""
    from distributeddataparallel_tpu.parallel.pipeline_parallel import (
        shard_state_pp,
    )

    cfg = _cfg(num_layers=4, scan_layers=True)
    model = TransformerLM(cfg)
    params = model.init(
        jax.random.PRNGKey(0), jnp.zeros((1, 16), jnp.int32)
    )["params"]
    mesh = Mesh(
        np.array(jax.devices()).reshape(4, 2), ("data", "pipe")
    )
    st = ddp.TrainState.create(
        apply_fn=None, params=params, tx=optax.sgd(0.1)
    )
    st = shard_state_pp(st, mesh, virtual=2)
    ckpt = Checkpointer(str(tmp_path))
    ckpt.save(
        st, 0,
        meta=topology_meta(mesh, "replicated", pp_axis="pipe",
                           pp_virtual=2),
    )
    ckpt.wait()
    # same geometry: restores fine
    st2, _ = elastic_restore(
        ckpt, st, mesh, layout="replicated", pp_axis="pipe", pp_virtual=2
    )
    # different virtual degree: rejected
    with pytest.raises(ValueError, match="interleaved"):
        elastic_restore(
            ckpt, st, mesh, layout="replicated", pp_axis="pipe",
            pp_virtual=1,
        )
    # same virtual, different pipe degree: rejected
    mesh24 = Mesh(
        np.array(jax.devices()).reshape(2, 4), ("data", "pipe")
    )
    with pytest.raises(ValueError, match="interleaved"):
        elastic_restore(
            ckpt, st, mesh24, layout="replicated", pp_axis="pipe",
            pp_virtual=2,
        )


def test_elastic_legacy_sidecar_rejected_into_interleaved_run(
    tmp_path, devices
):
    """A sidecar WITHOUT the n_virtual key predates interleaving, so its
    layer rows are contiguous (virtual=1): resuming it into a
    --pp-virtual>1 run must be rejected, not silently row-permuted
    (round-5 review finding: the legacy default was the CURRENT run's
    degree, which let exactly this slip through)."""
    import json

    from distributeddataparallel_tpu.parallel.pipeline_parallel import (
        shard_state_pp,
    )

    cfg = _cfg(num_layers=4, scan_layers=True)
    model = TransformerLM(cfg)
    params = model.init(
        jax.random.PRNGKey(0), jnp.zeros((1, 16), jnp.int32)
    )["params"]
    mesh = Mesh(np.array(jax.devices()).reshape(4, 2), ("data", "pipe"))
    st = ddp.TrainState.create(
        apply_fn=None, params=params, tx=optax.sgd(0.1)
    )
    st = shard_state_pp(st, mesh)  # contiguous (virtual=1) layout
    ckpt = Checkpointer(str(tmp_path))
    meta = topology_meta(mesh, "replicated", pp_axis="pipe")
    del meta["n_virtual"]  # simulate the pre-interleaving sidecar
    ckpt.save(st, 0, meta=meta)
    ckpt.wait()
    with pytest.raises(ValueError, match="interleaved"):
        elastic_restore(
            ckpt, st, mesh, layout="replicated", pp_axis="pipe",
            pp_virtual=2,
        )
    # and at virtual=1 the legacy sidecar restores exactly as before
    st2, _ = elastic_restore(
        ckpt, st, mesh, layout="replicated", pp_axis="pipe", pp_virtual=1
    )
    assert json.dumps(meta)  # meta untouched by the restore
