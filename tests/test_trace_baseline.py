"""Trace export + longitudinal baseline store: trace_event JSON
round-trip (per-track monotonic timestamps, rank->pid mapping, counter
samples, instant incidents), the streaming heap-merge, run-summary
extraction, perf-gate threshold logic (pass / regress / missing-metric
degrade / --update-baseline), and the acceptance path — a supervised
chaos run whose timeline exports to a schema-valid Perfetto trace with
the restart visible as an instant event."""

import json
import os
import sys

import pytest

sys.path.insert(0, "/root/repo")

import dpp  # noqa: E402
from distributeddataparallel_tpu.observability import (  # noqa: E402
    events_path,
    load_timeline,
    merge_timeline,
    read_events,
    read_runs,
)
from distributeddataparallel_tpu.observability.baseline import (  # noqa: E402
    RunSummaryBuilder,
    compare_to_baseline,
    run_summary_from_timeline,
)
from distributeddataparallel_tpu.observability.trace_export import (  # noqa: E402
    to_trace_events,
    validate_trace,
)
from distributeddataparallel_tpu.runtime.launcher import spawn  # noqa: E402

sys.path.insert(0, os.path.join("/root/repo", "scripts"))
import ddp_report  # noqa: E402
import ddp_trace  # noqa: E402
import perf_gate  # noqa: E402


def _rec(kind, ts, proc=0, seq=0, **fields):
    return {"v": 1, "ts": ts, "seq": seq, "proc": proc, "kind": kind,
            **fields}


def _synthetic_timeline():
    """Two ranks + supervisor: spans, mfu/memory gauges, a nan skip, a
    restart, and an alert — every mapping the exporter implements."""
    return [
        _rec("run_start", 100.0, proc=0, argv=[]),
        _rec("run_start", 100.0, proc=1, argv=[]),
        _rec("span", 101.0, proc=0, seq=1, name="step", dur_s=0.5, step=0),
        _rec("span", 101.1, proc=1, seq=1, name="step", dur_s=0.6, step=0),
        _rec("mfu", 101.2, proc=0, seq=2, step=0,
             model_flops_per_s=1e9, mfu=0.41, hfu=0.45),
        _rec("memory", 101.3, proc=0, seq=3, step=0,
             live_bytes=1_000_000, live_hwm_bytes=1_200_000),
        _rec("nan_skip", 101.4, proc=1, seq=2, step=1),
        _rec("alert", 101.5, proc=0, seq=4, rule="mfu_floor", step=1,
             value=0.01, threshold=0.3),
        _rec("restart_attempt", 102.0, proc="supervisor", attempt=1),
        _rec("span", 103.0, proc=0, seq=5, name="step", dur_s=0.4, step=1),
        _rec("run_end", 104.0, proc=0, seq=6, status="ok"),
    ]


# -------------------------------------------------------- trace export


def test_trace_export_round_trip_valid():
    trace = to_trace_events(_synthetic_timeline())
    assert validate_trace(trace) == []
    # Round-trips through JSON (what ddp_trace.py writes).
    assert validate_trace(json.loads(json.dumps(trace))) == []
    assert trace["displayTimeUnit"] == "ms"


def test_trace_export_rank_to_pid_mapping_and_metadata():
    trace = to_trace_events(_synthetic_timeline())
    names = {
        e["pid"]: e["args"]["name"]
        for e in trace["traceEvents"]
        if e["ph"] == "M" and e["name"] == "process_name"
    }
    assert names == {0: "supervisor", 1: "rank 0", 2: "rank 1"}


def test_trace_export_spans_counters_instants():
    trace = to_trace_events(_synthetic_timeline())
    evs = trace["traceEvents"]
    spans = [e for e in evs if e["ph"] == "X"]
    assert {e["name"] for e in spans} == {"step"}
    # Span start is ts - dur_s, converted to relative microseconds.
    first = min(spans, key=lambda e: e["ts"])
    assert first["ts"] == pytest.approx((101.0 - 0.5 - 100.0) * 1e6)
    assert first["dur"] == pytest.approx(0.5 * 1e6)

    counters = {e["name"] for e in evs if e["ph"] == "C"}
    assert {"step_s", "mfu", "memory_bytes"} <= counters
    mfu_samples = [e for e in evs if e["ph"] == "C" and e["name"] == "mfu"]
    assert mfu_samples[0]["args"]["mfu"] == pytest.approx(0.41)

    instants = {e["name"]: e for e in evs if e["ph"] == "i"}
    assert {"nan_skip", "alert", "restart_attempt"} <= set(instants)
    # The restart lands on the supervisor track with gang-wide scope.
    assert instants["restart_attempt"]["pid"] == 0
    assert instants["restart_attempt"]["s"] == "g"
    assert instants["alert"]["args"]["rule"] == "mfu_floor"


def test_trace_export_per_track_monotonic_timestamps():
    trace = to_trace_events(_synthetic_timeline())
    last = {}
    for e in trace["traceEvents"]:
        if e["ph"] == "M":
            continue
        key = (e["pid"], e["tid"])
        assert e["ts"] >= last.get(key, -1.0)
        last[key] = e["ts"]


def test_trace_export_empty_and_foreign_kinds():
    assert to_trace_events([]) == {"traceEvents": [],
                                   "displayTimeUnit": "ms"}
    # Unmapped kinds are skipped, not fatal.
    trace = to_trace_events([_rec("metrics", 100.0, snapshot={})])
    assert validate_trace(trace) == []


def test_validate_trace_catches_breakage():
    assert validate_trace({"nope": 1})
    bad = {"traceEvents": [
        {"ph": "X", "name": "a", "pid": 1, "tid": 0, "ts": 5.0, "dur": 1.0},
        {"ph": "X", "name": "b", "pid": 1, "tid": 0, "ts": 2.0, "dur": 1.0},
    ]}
    assert any("regresses" in p for p in validate_trace(bad))
    assert any("without dur" in p for p in validate_trace(
        {"traceEvents": [{"ph": "X", "name": "a", "pid": 1, "tid": 0,
                          "ts": 0.0}]}
    ))


# ------------------------------------------------- streaming heap-merge


def test_merge_timeline_streams_sorted_with_torn_tail(tmp_path):
    ev_dir = str(tmp_path)
    with open(events_path(ev_dir, 0), "w") as fh:
        for seq, ts in enumerate((100.0, 101.0, 103.0)):
            fh.write(json.dumps(_rec("span", ts, proc=0, seq=seq,
                                     name="step", dur_s=0.1)) + "\n")
    with open(events_path(ev_dir, 1), "w") as fh:
        for seq, ts in enumerate((100.5, 102.0)):
            fh.write(json.dumps(_rec("span", ts, proc=1, seq=seq,
                                     name="step", dur_s=0.1)) + "\n")
        fh.write('{"v": 1, "ts": 104.0, "seq": 2, "proc"')  # torn tail
    out = merge_timeline(ev_dir)
    recs = read_events(out)
    assert [r["ts"] for r in recs] == [100.0, 100.5, 101.0, 102.0, 103.0]
    # Ties on ts order by (seq, proc) — same key as the old full sort.
    assert merge_timeline(ev_dir) == out  # idempotent over its own output


def test_load_timeline_merges_on_demand(tmp_path):
    ev_dir = str(tmp_path)
    assert load_timeline(ev_dir) == []
    with open(events_path(ev_dir, 0), "w") as fh:
        fh.write(json.dumps(_rec("run_start", 100.0, argv=[])) + "\n")
    recs = load_timeline(ev_dir)
    assert [r["kind"] for r in recs] == ["run_start"]
    assert os.path.exists(os.path.join(ev_dir, "timeline.jsonl"))


# ------------------------------------------------ run-summary extraction


def test_run_summary_builder_percentiles():
    b = RunSummaryBuilder()
    for i in range(10):
        b.sample(step_s=0.1 + 0.01 * i, mfu=0.4, live_hwm_bytes=1000 + i)
    s = b.build(goodput={"goodput": 0.9, "buckets": {}}, restarts=2,
                alerts_total=1)
    assert s["windows"] == 10
    assert s["step_s_p50"] == pytest.approx(0.15, abs=0.01)
    assert s["step_s_p99"] == pytest.approx(0.19, abs=0.01)
    assert s["mfu_mean"] == pytest.approx(0.4)
    assert s["live_hwm_bytes"] == 1009
    assert s["goodput"] == 0.9 and s["restarts"] == 2


def test_run_summary_from_timeline_synthetic():
    s = run_summary_from_timeline(_synthetic_timeline())
    assert s["windows"] == 2  # two rank-0 step spans
    assert s["steps_total"] == 2
    assert s["mfu_mean"] == pytest.approx(0.41)
    assert s["live_hwm_bytes"] == 1_200_000
    assert s["alerts_total"] == 1
    assert s["status"] == "ok"


# ----------------------------------------------------------- perf gate


def _summary(**over):
    base = {"windows": 5, "steps_total": 100, "mfu_mean": 0.40,
            "step_s_p50": 0.10, "step_s_p99": 0.14,
            "live_hwm_bytes": 1_000_000, "goodput": 0.92, "restarts": 0}
    base.update(over)
    return base


def test_perf_gate_update_then_pass_then_regress(tmp_path, capsys):
    store = str(tmp_path / "runs")
    good = tmp_path / "good.json"
    good.write_text(json.dumps(_summary()))

    assert perf_gate.main([str(good), "--store", store,
                           "--baseline", "main",
                           "--update-baseline"]) == 0
    assert perf_gate.main([str(good), "--store", store,
                           "--baseline", "main"]) == 0

    # Synthetic 10% MFU regression against the stored baseline: the
    # gate must fail with its distinct non-zero exit.
    bad = tmp_path / "bad.json"
    bad.write_text(json.dumps(_summary(mfu_mean=0.36)))
    capsys.readouterr()
    assert perf_gate.main([str(bad), "--store", store,
                           "--baseline", "main"]) == perf_gate.REGRESS_EXIT
    err = capsys.readouterr().err
    assert "REGRESSION" in err and "mfu_mean" in err

    # ...and passes after the baseline is deliberately moved.
    assert perf_gate.main([str(bad), "--store", store,
                           "--baseline", "main",
                           "--update-baseline"]) == 0
    assert perf_gate.main([str(bad), "--store", store,
                           "--baseline", "main"]) == 0

    # Every gating attempt accreted into the history store.
    assert len(read_runs(store)) == 5


def test_perf_gate_missing_metric_degrades_not_fails(tmp_path, capsys):
    store = str(tmp_path / "runs")
    base = tmp_path / "base.json"
    base.write_text(json.dumps(_summary()))
    assert perf_gate.main([str(base), "--store", store,
                           "--baseline", "m", "--update-baseline"]) == 0
    # A run without --mfu: mfu_mean absent -> reported missing, exit 0.
    nomfu = tmp_path / "nomfu.json"
    s = _summary()
    del s["mfu_mean"]
    nomfu.write_text(json.dumps(s))
    capsys.readouterr()
    assert perf_gate.main([str(nomfu), "--store", store,
                           "--baseline", "m"]) == 0
    out = capsys.readouterr().out
    assert "missing" in out


def test_perf_gate_threshold_override_and_counts(tmp_path):
    store = str(tmp_path / "runs")
    base = tmp_path / "base.json"
    base.write_text(json.dumps(_summary()))
    assert perf_gate.main([str(base), "--store", store,
                           "--baseline", "m", "--update-baseline"]) == 0
    drop = tmp_path / "drop.json"
    drop.write_text(json.dumps(_summary(mfu_mean=0.37)))
    # 7.5% drop: fails the default 5% tolerance...
    assert perf_gate.main([str(drop), "--store", store,
                           "--baseline", "m"]) == perf_gate.REGRESS_EXIT
    # ...passes with the tolerance widened for that metric.
    assert perf_gate.main([str(drop), "--store", store,
                           "--baseline", "m",
                           "--threshold", "mfu_mean=0.10"]) == 0
    # New restarts are a regression at the default absolute 0.
    crashy = tmp_path / "crashy.json"
    crashy.write_text(json.dumps(_summary(restarts=2)))
    assert perf_gate.main([str(crashy), "--store", store,
                           "--baseline", "m"]) == perf_gate.REGRESS_EXIT


def test_perf_gate_bench_headline_mode(tmp_path):
    store = str(tmp_path / "runs")
    bench = tmp_path / "BENCH_x.json"
    bench.write_text(json.dumps({"parsed": {"headline": {
        "gpt2_mfu": 0.40, "pipeline_1f1b_bubble": 0.25,
    }}}))
    assert perf_gate.main([str(bench), "--store", store,
                           "--baseline", "bench",
                           "--update-baseline"]) == 0
    # Direction inference: mfu higher-better, bubble lower-better.
    worse = tmp_path / "BENCH_y.json"
    worse.write_text(json.dumps({"parsed": {"headline": {
        "gpt2_mfu": 0.40, "pipeline_1f1b_bubble": 0.30,
    }}}))
    assert perf_gate.main([str(worse), "--store", store,
                           "--baseline", "bench"]) == perf_gate.REGRESS_EXIT
    better = tmp_path / "BENCH_z.json"
    better.write_text(json.dumps({"parsed": {"headline": {
        "gpt2_mfu": 0.44, "pipeline_1f1b_bubble": 0.25,
    }}}))
    assert perf_gate.main([str(better), "--store", store,
                           "--baseline", "bench"]) == 0


def test_bench_direction_suffix_inference():
    # *_frac / *_fraction are waste shares -> lower is better; the zb
    # bubble headline must never gate backwards.
    assert perf_gate._bench_direction("zb_bubble_frac") == "lower"
    assert perf_gate._bench_direction("measured_bubble_fraction") == "lower"
    assert perf_gate._bench_direction("zb_step_s") == "lower"
    assert perf_gate._bench_direction("pipeline_1f1b_bubble") == "lower"
    # ...while rate suffixes stay higher-better (the PR 9 fix shape)
    assert perf_gate._bench_direction("serve_tok_s") == "higher"
    assert perf_gate._bench_direction("host_gather_img_s") == "higher"
    assert perf_gate._bench_direction("tokens_per_s") == "higher"
    assert perf_gate._bench_direction("gpt2_mfu") == "higher"
    # Serving fast-path WIN shares: hit rate, avoided prefill FLOPs,
    # and speedup ratio must beat _LOWER_BETTER's _frac$ / plain-name
    # fallthrough — a cache that hits MORE must never gate as worse.
    assert perf_gate._bench_direction("prefix_hit_frac") == "higher"
    assert perf_gate._bench_direction(
        "prefill_flops_avoided_frac") == "higher"
    assert perf_gate._bench_direction("spec_tok_s_speedup") == "higher"
    # ...without disturbing the waste-share neighbors
    assert perf_gate._bench_direction("preempt_frac") == "lower"
    assert perf_gate._bench_direction("serve_p99_ttft_s") == "lower"


def test_perf_gate_fastpath_win_shares_gate_higher_better(tmp_path):
    store = str(tmp_path / "runs")
    base = tmp_path / "BENCH_fp_a.json"
    base.write_text(json.dumps({"parsed": {"headline": {
        "prefix_hit_frac": 0.60, "spec_tok_s_speedup": 1.8,
    }}}))
    assert perf_gate.main([str(base), "--store", store,
                           "--baseline", "fp", "--update-baseline"]) == 0
    # hit rate / speedup dropped -> regression; improved -> pass
    worse = tmp_path / "BENCH_fp_b.json"
    worse.write_text(json.dumps({"parsed": {"headline": {
        "prefix_hit_frac": 0.30, "spec_tok_s_speedup": 1.8,
    }}}))
    assert perf_gate.main([str(worse), "--store", store,
                           "--baseline", "fp"]) == perf_gate.REGRESS_EXIT
    better = tmp_path / "BENCH_fp_c.json"
    better.write_text(json.dumps({"parsed": {"headline": {
        "prefix_hit_frac": 0.75, "spec_tok_s_speedup": 2.1,
    }}}))
    assert perf_gate.main([str(better), "--store", store,
                           "--baseline", "fp"]) == 0


def test_perf_gate_zb_bubble_gates_lower_better(tmp_path):
    store = str(tmp_path / "runs")
    base = tmp_path / "BENCH_a.json"
    base.write_text(json.dumps({"parsed": {"headline": {
        "zb_bubble_frac": 0.16, "zb_step_s": 0.10,
    }}}))
    assert perf_gate.main([str(base), "--store", store,
                           "--baseline", "zb", "--update-baseline"]) == 0
    # bubble grew -> regression; shrank -> pass
    worse = tmp_path / "BENCH_b.json"
    worse.write_text(json.dumps({"parsed": {"headline": {
        "zb_bubble_frac": 0.20, "zb_step_s": 0.10,
    }}}))
    assert perf_gate.main([str(worse), "--store", store,
                           "--baseline", "zb"]) == perf_gate.REGRESS_EXIT
    better = tmp_path / "BENCH_c.json"
    better.write_text(json.dumps({"parsed": {"headline": {
        "zb_bubble_frac": 0.12, "zb_step_s": 0.09,
    }}}))
    assert perf_gate.main([str(better), "--store", store,
                           "--baseline", "zb"]) == 0


def test_compare_to_baseline_direction_arithmetic():
    summary = _summary(step_s_p50=0.104, live_hwm_bytes=1_200_000)
    res = compare_to_baseline(summary, _summary())
    # +4% p50 is inside the 5% lower-better tolerance; +20% memory not.
    by = {c["metric"]: c["status"] for c in res["checks"]}
    assert by["step_s_p50"] == "pass"
    assert by["live_hwm_bytes"] == "regress"
    assert res["ok"] is False and res["regressed"] == ["live_hwm_bytes"]


# ------------------------------------------------- acceptance: chaos run


def test_acceptance_chaos_run_trace_and_store(devices, tmp_path):
    """ISSUE acceptance: a supervised chaos run (nan injection + a
    preemption-driven restart) exports a schema-valid Perfetto trace
    with per-rank tracks, a counter track, and the restart as an
    instant event; the supervisor appends a cross-incarnation
    run_summary to the runs store; ddp_report grows an Alerts section
    and the trace invocation hint."""
    ev_dir = str(tmp_path / "events")
    runs_dir = str(tmp_path / "runs")
    ck = str(tmp_path / "ck")
    base = [
        "--device", "cpu", "--fake-devices", "8",
        "--model", "mlp", "--dataset", "synthetic",
        "--num-examples", "128", "--batch-size", "4",
        "--epochs", "3", "--steps-per-epoch", "4", "--log-every", "1",
        "--nan-guard",
        "--checkpoint-dir", ck, "--resume",
    ]
    spawn(
        dpp._worker,
        args=(base,),
        nprocs=1,
        max_restarts=1,
        env={
            "_DDP_SUPERVISED": "1",
            # nan-grad@2: epoch 0 -> nan_skip.  preempt@6: dies after
            # epoch 0's checkpoint -> supervisor restart_attempt.
            "DDP_CHAOS": "nan-grad@2,preempt@6",
            "DDP_CHAOS_STATE": os.path.join(ck, ".chaos"),
        },
        events_dir=ev_dir,
        runs_dir=runs_dir,
    )

    # -- trace export ------------------------------------------------
    out = str(tmp_path / "trace.json")
    assert ddp_trace.main([ev_dir, "-o", out]) == 0
    with open(out) as fh:
        trace = json.load(fh)
    assert validate_trace(trace) == []
    evs = trace["traceEvents"]
    pids = {e["pid"] for e in evs}
    assert 0 in pids and 1 in pids  # supervisor track + rank-0 track
    assert any(e["ph"] == "C" and e["name"] == "step_s" for e in evs)
    restart_marks = [e for e in evs if e["ph"] == "i"
                     and e["name"] == "restart_attempt"]
    assert restart_marks and restart_marks[0]["pid"] == 0
    assert any(e["ph"] == "i" and e["name"] == "nan_skip" for e in evs)

    # -- runs store (supervisor summary spans both incarnations) ------
    runs = read_runs(runs_dir)
    sup = [r for r in runs if r.get("source") == "supervisor"]
    assert len(sup) == 1
    assert sup[0]["restarts"] == 1
    assert sup[0]["windows"] > 0  # step spans from both incarnations

    # -- report degrade/alert surfacing -------------------------------
    md = ddp_report.render_markdown(
        ddp_report.analyze(load_timeline(ev_dir)), ev_dir
    )
    assert "## Alerts" in md
    # Run had --runs-dir (so a run_summary) but no --alerts: the section
    # degrades to the explicit no-alerts line, not the predates-alerting
    # one.
    assert "No alerts fired." in md
    assert "## Run summary" in md
    assert "ddp_trace.py" in md  # the trace invocation hint
