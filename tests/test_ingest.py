"""ImageFolder-tree ingestion (data/ingest.py; VERDICT r4 missing 2).

The contract: a directory tree of ENCODED images (JPEG/PNG) in the
torchvision ImageFolder layout converts — streamed, thread-pooled —
into the streaming shard format, and the result trains end-to-end via
``--dataset shards:DIR`` with ImageFolder's exact class-id assignment.
"""

import os

import numpy as np
import pytest

from distributeddataparallel_tpu.data import (
    ShardedImageDataset,
    ingest_image_tree,
    scan_image_tree,
)

PIL = pytest.importorskip("PIL")
from PIL import Image  # noqa: E402


def _write_tree(root, *, classes=("cat", "dog", "eel"), per_class=7,
                size=(20, 24), fmt="JPEG", seed=0):
    """Synthetic encoded-image tree: per-class base color + noise so the
    ingested corpus is learnable, mixed sizes to exercise resize."""
    rng = np.random.default_rng(seed)
    os.makedirs(root, exist_ok=True)
    for cid, cname in enumerate(classes):
        cdir = os.path.join(root, cname)
        os.makedirs(cdir, exist_ok=True)
        base = rng.integers(40, 216, size=(3,))
        for i in range(per_class):
            w, h = size[0] + (i % 3) * 8, size[1] + (i % 2) * 6
            arr = np.clip(
                base + rng.integers(-30, 31, size=(h, w, 3)), 0, 255
            ).astype(np.uint8)
            ext = {"JPEG": ".jpg", "PNG": ".png"}[fmt]
            Image.fromarray(arr).save(
                os.path.join(cdir, f"img_{i:03d}{ext}"), format=fmt
            )
    return root


def test_scan_is_imagefolder_enumeration(tmp_path):
    root = _write_tree(str(tmp_path / "tree"))
    paths, labels, class_names = scan_image_tree(root)
    # sorted class dirs -> ids; files sorted within class
    assert class_names == ["cat", "dog", "eel"]
    assert len(paths) == 21
    np.testing.assert_array_equal(labels, np.repeat([0, 1, 2], 7))
    assert paths == sorted(paths)
    # non-image files are skipped
    open(os.path.join(root, "cat", "notes.txt"), "w").write("x")
    paths2, _, _ = scan_image_tree(root)
    assert len(paths2) == 21


def test_scan_rejects_flat_and_empty(tmp_path):
    with pytest.raises(FileNotFoundError):
        scan_image_tree(str(tmp_path / "missing"))
    flat = tmp_path / "flat"
    flat.mkdir()
    (flat / "img.jpg").write_bytes(b"")
    with pytest.raises(ValueError, match="class subdirectories"):
        scan_image_tree(str(flat))
    empty = tmp_path / "empty"
    (empty / "classA").mkdir(parents=True)
    with pytest.raises(ValueError, match="no decodable images"):
        scan_image_tree(str(empty))


def test_ingest_roundtrip(tmp_path):
    root = _write_tree(str(tmp_path / "tree"), fmt="PNG")
    dst = ingest_image_tree(
        root, str(tmp_path / "shards"), size=16, shard_rows=8, workers=4
    )
    ds = ShardedImageDataset(dst, device_normalize=True)
    assert len(ds) == 21
    assert ds.image_shape == (16, 16, 3)
    assert ds.num_classes == 3
    batch = ds.gather(np.arange(21))
    assert batch["image"].dtype == np.uint8
    np.testing.assert_array_equal(
        batch["label"], np.repeat([0, 1, 2], 7)
    )
    # PNG is lossless and _write_tree colors are class-separated by
    # construction: per-class mean colors must stay distinguishable
    # through decode+resize (the pixels are real, not placeholder).
    means = [
        batch["image"][batch["label"] == c].astype(np.float32).mean(axis=(0, 1, 2, 3))
        for c in range(3)
    ]
    assert np.ptp(means) > 10.0


def test_ingest_crop_vs_stretch(tmp_path):
    root = _write_tree(str(tmp_path / "tree"), per_class=2)
    crop = ingest_image_tree(root, str(tmp_path / "c"), size=12,
                             policy="crop", workers=2)
    stretch = ingest_image_tree(root, str(tmp_path / "s"), size=12,
                                policy="stretch", workers=2)
    a = ShardedImageDataset(crop, device_normalize=True).gather([0])
    b = ShardedImageDataset(stretch, device_normalize=True).gather([0])
    assert a["image"].shape == b["image"].shape == (1, 12, 12, 3)


def test_manifest_carries_class_names(tmp_path):
    import json

    root = _write_tree(str(tmp_path / "tree"), per_class=1)
    dst = ingest_image_tree(root, str(tmp_path / "m"), size=8, workers=1)
    with open(os.path.join(dst, "index.json")) as fh:
        m = json.load(fh)
    assert m["class_names"] == ["cat", "dog", "eel"]
    assert m["num_classes"] == 3


def test_cli_trains_on_ingested_tree(tmp_path, devices):
    """JPEG tree -> ingest -> shards:DIR -> dpp.py CLI training, end to
    end (the VERDICT done-bar)."""
    import sys

    sys.path.insert(0, "/root/repo")
    import dpp

    root = _write_tree(
        str(tmp_path / "tree"), classes=("a", "b", "c", "d"),
        per_class=40, size=(16, 16), seed=3,
    )
    dst = ingest_image_tree(root, str(tmp_path / "shards"), size=16,
                            shard_rows=64, workers=4)
    args = dpp.parse_args(
        [
            "--device", "cpu",
            "--model", "cnn",
            "--dataset", f"shards:{dst}",
            "--epochs", "2",
            "--batch-size", "4",
            "--lr", "0.05",
            "--log-every", "1000",
        ]
    )
    final_loss = dpp.train(args)
    assert final_loss == final_loss and final_loss < 1.4  # 4-class chance
