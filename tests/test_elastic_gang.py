"""Elastic gang runtime (runtime.elastic_gang + runtime.rendezvous):
membership-epoch transitions over the file and TCP transports, bitwise
parity of the checkpoint-free in-memory shrink against a real
``elastic_restore``, exactly-once data coverage across a mid-epoch
resize, and the supervised chaos-kill acceptance run whose timeline must
show a ``gang_resize`` and no ``restart_attempt``."""

import os
import pathlib
import subprocess
import sys
import threading

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest
from jax.sharding import Mesh

import distributeddataparallel_tpu as ddp
from distributeddataparallel_tpu.data.loader import shard_batch
from distributeddataparallel_tpu.data.sharded import resize_index_plan
from distributeddataparallel_tpu.models import TransformerLM, tiny_lm
from distributeddataparallel_tpu.ops import lm_cross_entropy
from distributeddataparallel_tpu.runtime.elastic_gang import (
    ElasticGangCoordinator,
    reshard_live_state,
)
from distributeddataparallel_tpu.runtime.rendezvous import (
    RendezvousStore,
    TCPRendezvousClient,
    TCPRendezvousServer,
)
from distributeddataparallel_tpu.training.checkpoint import Checkpointer
from distributeddataparallel_tpu.training.elastic import (
    elastic_restore,
    topology_meta,
)


# -- rendezvous: epoch transitions ---------------------------------------


def test_rendezvous_join_leave_epochs(tmp_path):
    """Joins and leaves move ``alive()``; each agreed roster is one epoch;
    the transition log stays monotonic."""
    store = RendezvousStore(str(tmp_path))
    for m in ("w0", "w1", "w2"):
        store.join(m)
    assert store.alive() == ["w0", "w1", "w2"]
    assert store.epoch() == {"epoch": -1, "roster": []}

    rec0 = store.propose(store.alive(), epoch=0)
    assert rec0["epoch"] == 0 and rec0["roster"] == ["w0", "w1", "w2"]

    store.leave("w1")
    assert store.alive() == ["w0", "w2"]
    assert "w1" in store.dead()
    store.ack(1, "w2")  # the other survivor's barrier ack (single caller)
    rec1 = store.transition("w0")
    assert rec1["epoch"] == 1 and rec1["roster"] == ["w0", "w2"]
    assert rec1["prev_roster"] == ["w0", "w1", "w2"]

    # A rejoin under the old name clears the tombstone.
    store.join("w1")
    assert store.alive() == ["w0", "w1", "w2"]
    epochs = [r["epoch"] for r in store.history()]
    assert epochs == sorted(epochs) == [0, 1]


def test_rendezvous_simultaneous_death_single_transition(tmp_path):
    """Two members tombstoned at once: the survivors run ONE transition
    (epoch k+1 with both gone), not one per death — and every survivor
    returns the identical record."""
    store = RendezvousStore(str(tmp_path))
    world = ["w0", "w1", "w2", "w3"]
    for m in world:
        store.join(m)
    store.propose(world, epoch=0)
    store.mark_dead("w1")
    store.mark_dead("w3")

    results = {}

    def run(name):
        results[name] = store.transition(name, timeout_s=10.0)

    threads = [threading.Thread(target=run, args=(m,)) for m in ("w0", "w2")]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=15.0)
    assert results["w0"] == results["w2"]
    assert results["w0"]["epoch"] == 1
    assert results["w0"]["roster"] == ["w0", "w2"]


def test_rendezvous_join_transition(tmp_path):
    """A grow: a new member joins, every member (incumbents + joiner)
    transitions concurrently, and epoch k+1 includes the joiner."""
    store = RendezvousStore(str(tmp_path))
    for m in ("w0", "w1"):
        store.join(m)
    store.propose(["w0", "w1"], epoch=0)
    store.join("w2")

    results = {}

    def run(name):
        results[name] = store.transition(name, timeout_s=10.0)

    threads = [
        threading.Thread(target=run, args=(m,))
        for m in ("w0", "w1", "w2")
    ]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=15.0)
    assert len({r["epoch"] for r in results.values()}) == 1
    assert results["w0"]["epoch"] == 1
    assert results["w0"]["roster"] == ["w0", "w1", "w2"]


def test_rendezvous_tcp_transport(tmp_path):
    """The socket front-end is duck-typed with the store: members that
    share no filesystem run the same join/kill/transition protocol, and
    concurrent client transitions agree."""
    store = RendezvousStore(str(tmp_path))
    with TCPRendezvousServer(store) as srv:
        with TCPRendezvousClient(srv.address) as c:
            c.join("w0")
            c.join("w1")
            c.join("w2")
            assert c.alive() == ["w0", "w1", "w2"]
            c.propose(["w0", "w1", "w2"])
            assert c.epoch()["epoch"] == 0
            c.mark_dead("w2")
            assert c.dead() == ["w2"]

        results = {}

        def run(name):
            with TCPRendezvousClient(srv.address) as cli:
                results[name] = cli.transition(name)

        threads = [
            threading.Thread(target=run, args=(m,)) for m in ("w0", "w1")
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=15.0)
        assert results["w0"] == results["w1"]
        assert results["w0"]["epoch"] == 1
        assert results["w0"]["roster"] == ["w0", "w1"]

        # Errors cross the wire as structured replies, not dead sockets.
        with TCPRendezvousClient(srv.address) as c:
            with pytest.raises(RuntimeError, match="surviving"):
                c.transition("w2")


def test_coordinator_kill_poll_decision(tmp_path):
    """The single-process gang: chaos kills a rank index, the next poll
    agrees on the shrunk roster and reports who left."""
    world = [f"proc{i}" for i in range(4)]
    gang = ElasticGangCoordinator(str(tmp_path), world=world, min_size=1)
    rec = gang.start()
    assert rec["epoch"] == 0 and rec["roster"] == sorted(world)
    assert gang.poll() is None  # stable membership: cheap no-op

    gang.kill("2")  # chaos rank-index form, maps to proc2
    decision = gang.poll()
    assert decision is not None
    assert decision.epoch == 1
    assert decision.left == ("proc2",)
    assert decision.joined == ()
    assert decision.old_size == 4 and decision.new_size == 3
    assert gang.poll() is None  # agreed: nothing further to do

    gang.kill("proc0")  # direct-name form
    with pytest.raises(RuntimeError, match="below --min-procs"):
        ElasticGangCoordinator(
            str(tmp_path), world=["proc1", "proc3"], min_size=3
        ).poll()


# -- checkpoint-free shrink: bitwise parity vs elastic_restore -----------


def _cfg(**over):
    base = dict(
        num_layers=2, num_heads=2, d_model=32, d_ff=64, max_seq_len=32,
    )
    base.update(over)
    return tiny_lm(**base)


def _mesh(n):
    return Mesh(np.array(jax.devices()[:n]), ("data",))


def _batches(k=3, rows=56, vocab=256):
    # 56 rows: divisible by BOTH 8 and 7, so the same global batch shards
    # cleanly before and after the shrink.
    rng = np.random.default_rng(0)
    return [
        rng.integers(0, vocab, size=(rows, 17)).astype(np.int32)
        for _ in range(k)
    ]


def _loss_fn(model):
    def loss_fn(p, batch, rng):
        toks = batch["tokens"]
        logits = model.apply({"params": p}, toks[:, :-1])
        return lm_cross_entropy(logits, toks[:, 1:]), {}

    return loss_fn


def _assert_bitwise(tree_a, tree_b, what):
    la, lb = jax.tree.leaves(tree_a), jax.tree.leaves(tree_b)
    assert len(la) == len(lb), what
    for i, (a, b) in enumerate(zip(la, lb)):
        a = np.asarray(jax.device_get(a))
        b = np.asarray(jax.device_get(b))
        assert a.dtype == b.dtype and a.shape == b.shape, (what, i)
        assert a.tobytes() == b.tobytes(), f"{what}: leaf {i} differs"


@pytest.mark.parametrize("zero", [0, 1], ids=["dp", "zero1"])
def test_checkpoint_free_shrink_bitwise(tmp_path, devices, zero):
    """The acceptance invariant: 8 -> 7 via ``reshard_live_state`` (host
    round-trip of the LIVE arrays, no checkpoint anywhere) is bitwise
    identical — params, opt state, step counter — to a 7-device
    ``elastic_restore`` through a real checkpoint of the same state, and
    the two continuations produce bitwise-equal losses."""
    # d_model 28 / vocab 251: park the param count off the chunk
    # alignment so the ZeRO-1 flats' padded sizes differ between 8 and 7.
    cfg = _cfg(vocab_size=251, d_model=28, d_ff=52, num_layers=3)
    model = TransformerLM(cfg)
    params = model.init(
        jax.random.PRNGKey(0), jnp.zeros((1, 16), jnp.int32)
    )["params"]
    tx = optax.adam(1e-2)
    batches = _batches(vocab=251)
    loss_fn = _loss_fn(model)

    def fresh(mesh):
        if zero:
            st = ddp.zero_state(
                apply_fn=model.apply, params=params, tx=tx, mesh=mesh
            )
        else:
            st = ddp.TrainState.create(
                apply_fn=model.apply, params=params, tx=tx
            )
            st = ddp.broadcast_params(st, mesh)
        step = ddp.make_train_step(
            loss_fn, mesh=mesh, zero=bool(zero), donate=False
        )
        return st, step

    mesh8, mesh7 = _mesh(8), _mesh(7)
    st8, step8 = fresh(mesh8)
    for t in batches[:2]:
        st8, _ = step8(
            st8, shard_batch({"tokens": t}, mesh8), jax.random.PRNGKey(0)
        )

    if zero:
        # Precondition: the flat opt shapes REALLY differ across the two
        # topologies, or the reshard under test is vacuous.
        st7_probe = ddp.zero_state(
            apply_fn=model.apply, params=params, tx=tx, mesh=mesh7
        )
        olds = {l.shape for l in jax.tree.leaves(st8.opt_state)
                if l.ndim == 1}
        news = {l.shape for l in jax.tree.leaves(st7_probe.opt_state)
                if l.ndim == 1}
        assert olds != news, (olds, news)
        del st7_probe

    # Path A: checkpoint-free — the live state moves host-side.
    st_live = reshard_live_state(st8, mesh8, mesh7, zero=zero)

    # Path B: the pre-elastic story — save, fresh 7-device state, restore.
    ckpt = Checkpointer(str(tmp_path))
    ckpt.save(
        st8, 0, meta=topology_meta(mesh8, "zero1" if zero else "replicated")
    )
    ckpt.wait()
    st7, step7 = fresh(mesh7)
    st_ckpt, next_epoch = elastic_restore(
        ckpt, st7, mesh7, layout="zero1" if zero else "replicated"
    )
    assert next_epoch == 1

    _assert_bitwise(st_live.params, st_ckpt.params, "params")
    _assert_bitwise(st_live.opt_state, st_ckpt.opt_state, "opt_state")
    assert int(st_live.step) == int(st_ckpt.step) == 2

    # Same executable, bitwise-same inputs -> bitwise-same continuation.
    t = batches[2]
    st_live, m_live = step7(
        st_live, shard_batch({"tokens": t}, mesh7), jax.random.PRNGKey(0)
    )
    st_ckpt, m_ckpt = step7(
        st_ckpt, shard_batch({"tokens": t}, mesh7), jax.random.PRNGKey(0)
    )
    assert float(m_live["loss"]) == float(m_ckpt["loss"])
    _assert_bitwise(st_live.params, st_ckpt.params, "post-step params")


def test_reshard_live_state_rejects_zero23(devices):
    cfg = _cfg()
    model = TransformerLM(cfg)
    params = model.init(
        jax.random.PRNGKey(0), jnp.zeros((1, 16), jnp.int32)
    )["params"]
    mesh8 = _mesh(8)
    st = ddp.TrainState.create(
        apply_fn=model.apply, params=params, tx=optax.sgd(0.1)
    )
    st = ddp.broadcast_params(st, mesh8)
    with pytest.raises(ValueError, match="ZeRO-2/3"):
        reshard_live_state(st, mesh8, _mesh(7), zero=2)


# -- exactly-once data coverage across a mid-epoch resize ----------------


@pytest.mark.parametrize(
    "old_world,new_world,consumed_steps",
    [(8, 7, 3), (8, 7, 0), (4, 3, 5), (4, 6, 2)],
)
def test_resize_plan_exactly_once(old_world, new_world, consumed_steps):
    """The consumed prefix and the resize plan partition the epoch's
    permutation: disjoint, no duplicates, and together they cover every
    sample except the (< B * new_world) drop-last remainder."""
    n, B, seed, epoch = 256, 4, 7, 2
    plan = resize_index_plan(
        n, per_replica_batch=B, old_world=old_world, new_world=new_world,
        consumed_steps=consumed_steps, seed=seed, epoch=epoch,
        membership_epoch=1,
    )
    assert plan.shape[0] == new_world
    assert plan.shape[1] % B == 0

    perm = np.random.default_rng(seed + epoch).permutation(n)
    consumed = set(perm[: consumed_steps * B * old_world].tolist())
    planned = plan.ravel().tolist()
    assert len(planned) == len(set(planned)), "duplicate sample in plan"
    assert not (set(planned) & consumed), "resize replays consumed samples"
    remaining = n - len(consumed)
    dropped = remaining - len(planned)
    assert 0 <= dropped < B * new_world, (remaining, len(planned))
    assert set(planned) | consumed <= set(range(n))


def test_resize_plan_membership_epoch_reshuffles():
    """A second resize in the same data epoch must not replay the first
    resize's order: the tail permutation is keyed on the MEMBERSHIP
    epoch.  Both plans draw only from the unconsumed remainder (which
    samples fall to drop-last shifts with the order — the per-pass
    exactly-once contract is plan ∪ dropped, tested above)."""
    kw = dict(per_replica_batch=4, old_world=8, new_world=7,
              consumed_steps=2, seed=0, epoch=0)
    a = resize_index_plan(256, membership_epoch=1, **kw)
    b = resize_index_plan(256, membership_epoch=2, **kw)
    assert a.shape == b.shape
    assert a.ravel().tolist() != b.ravel().tolist()
    perm = np.random.default_rng(0).permutation(256)
    remaining = set(perm[2 * 4 * 8:].tolist())
    assert set(a.ravel().tolist()) <= remaining
    assert set(b.ravel().tolist()) <= remaining
    # ... and every survivor computes the same plan (pure function).
    assert np.array_equal(a, resize_index_plan(256, membership_epoch=1, **kw))


# -- supervised chaos-kill acceptance run --------------------------------


def test_supervised_worker_kill_resizes_without_restart(tmp_path):
    """The end-to-end acceptance bar: a supervised 8-member CPU gang
    loses one worker to chaos mid-run; the supervisor must RESIZE-respawn
    at 7 (no restart budget burned), the run must finish, and the merged
    timeline must show ``gang_resize`` with no ``restart_attempt`` and
    no checkpoint restore anywhere."""
    from distributeddataparallel_tpu.observability.events import (
        load_timeline,
    )

    repo = str(pathlib.Path(__file__).resolve().parents[1])
    ev = str(tmp_path / "ev")
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    env.pop("_DDP_SUPERVISED", None)
    env.pop("DDP_ELASTIC_WORLD", None)
    r = subprocess.run(
        [
            sys.executable, str(pathlib.Path(repo) / "dpp.py"),
            "--device", "cpu", "--model", "mlp",
            "--fake-devices", "8", "--batch-size", "4",
            "--epochs", "1", "--steps-per-epoch", "10",
            "--elastic",
            # worker-kill tombstones rank 2, preempt kills the gang at
            # the same step: the supervisor sees a shrunk roster and must
            # take the resize path, not the restart path.
            "--chaos", "worker-kill@4:2,preempt@4",
            "--max-restarts", "1",
            "--checkpoint-dir", str(tmp_path / "ck"),
            "--events-dir", ev,
        ],
        capture_output=True, text=True, timeout=300, env=env, cwd=repo,
    )
    assert r.returncode == 0, (r.stdout + r.stderr)[-2000:]
    log = r.stdout + r.stderr
    assert "7 device(s), 7-way DP" in log, log[-2000:]

    records = load_timeline(ev)
    kinds = [rec.get("kind") for rec in records]
    assert kinds.count("gang_resize") == 1, kinds
    assert "resize_downtime" in kinds
    assert "restart_attempt" not in kinds, kinds
    # Checkpoint-free: nothing durable existed for the respawn to read —
    # no ckpt activity anywhere before the resize landed (the epoch-edge
    # save AFTER the resize is normal).
    t_resize = next(rec["ts"] for rec in records
                    if rec.get("kind") == "gang_resize")
    assert not any(
        rec.get("kind") == "span" and "ckpt" in str(rec.get("name"))
        and rec["ts"] <= t_resize
        for rec in records
    ), kinds
    resize = next(rec for rec in records if rec.get("kind") == "gang_resize")
    assert resize["old_size"] == 8 and resize["new_size"] == 7
