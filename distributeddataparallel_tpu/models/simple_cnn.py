"""Toy models for the end-to-end slice (BASELINE config 1).

The reference's model is ``SimpleCNN``: a torchvision ResNet-18 with the FC
head swapped for 10 classes (ref dpp.py:11-18).  The full ResNet lives in
``models.resnet``; this module provides the tiny MLP/CNN the toy CPU config
calls for, in the same Flax idiom the rest of the zoo uses.

TPU notes: NHWC layout (XLA-native on TPU), feature dims padded to
MXU/VPU-friendly multiples where it matters (the toy nets are too small for
the MXU either way — they exist to prove the plumbing, not the FLOPs).
"""

from __future__ import annotations

import flax.linen as nn
import jax.numpy as jnp


class TinyMLP(nn.Module):
    """Minimal MLP on flattened inputs — the fastest plumbing-proof model."""

    features: tuple[int, ...] = (128, 128)
    num_classes: int = 10
    dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, x):
        x = x.reshape((x.shape[0], -1)).astype(self.dtype)
        for f in self.features:
            x = nn.Dense(f, dtype=self.dtype)(x)
            x = nn.relu(x)
        return nn.Dense(self.num_classes, dtype=jnp.float32)(x)


class SimpleCNN(nn.Module):
    """Small conv net for 32×32 images — the toy-CNN variant of config 1.

    Named for the reference's wrapper class (ref dpp.py:11) but sized for
    what that config actually needs: a few conv blocks and a linear head.
    Inputs are NHWC.
    """

    num_classes: int = 10
    widths: tuple[int, ...] = (32, 64)
    dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, x):
        x = x.astype(self.dtype)
        for w in self.widths:
            x = nn.Conv(w, (3, 3), padding="SAME", dtype=self.dtype)(x)
            x = nn.relu(x)
            x = nn.max_pool(x, (2, 2), strides=(2, 2))
        x = x.mean(axis=(1, 2))
        return nn.Dense(self.num_classes, dtype=jnp.float32)(x)
