"""Autoregressive generation with a KV cache (greedy / temperature /
top-k sampling).

The reference is a trainer only (ref dpp.py:27-57 — no inference path
exists); this module completes the LM family's serving story the TPU
way:

- **Static shapes everywhere**: the per-layer KV caches are allocated at
  ``max_seq_len`` up front (``TransformerConfig.decode`` attention), the
  prompt is consumed in ONE prefill call (a big MXU-friendly batched
  matmul, not token-by-token), and the decode loop is a ``lax.scan`` of
  single-token applies — one compiled program for prefill, one for the
  whole decode scan, no per-step retracing.
- Positions are explicit: prefill passes ``arange(P)``, decode step t
  passes the single global position ``P + t``; RoPE / learned positional
  lookups and the cache-insert offset all derive from them.
- Sampling runs in f32 on the final-position logits: greedy argmax when
  ``temperature == 0``, else softmax sampling with optional top-k
  truncation (``jax.random.categorical``).

Works for both LM families (GPT-2 learned-positional MHA, Llama-style
RoPE GQA — the cache stores kv heads at their own count) and for
scanned-layer configs (caches stack along the scan dim).
"""

from __future__ import annotations

import dataclasses
import functools

import jax
import jax.numpy as jnp


def _sample(logits, rng, temperature: float, top_k: int | None):
    """Next-token ids (B,) from final-position logits (B, V)."""
    logits = logits.astype(jnp.float32)
    if temperature == 0.0:
        return jnp.argmax(logits, axis=-1).astype(jnp.int32)
    logits = logits / temperature
    if top_k is not None:
        kth = jax.lax.top_k(logits, top_k)[0][..., -1:]  # (B, 1)
        logits = jnp.where(logits < kth, -jnp.inf, logits)
    return jax.random.categorical(rng, logits, axis=-1).astype(jnp.int32)


def decode_model(model):
    """The decode twin of a TransformerLM: same params, KV-cache
    attention, remat off (the cache is mutable state remat can't replay).

    Sharded-layout configs are rejected: TP/EP params are in the
    Megatron/expert layout, which the (unsharded) decode apply cannot
    consume — gather them to the replicated layout first.
    """
    from distributeddataparallel_tpu.models.transformer import TransformerLM

    if model.cfg.tp_axis is not None or model.cfg.ep_axis is not None:
        raise ValueError(
            "generate() needs replicated params: tp_axis/ep_axis configs "
            "hold sharded layouts the decode apply cannot consume"
        )
    cfg = dataclasses.replace(
        model.cfg, decode=True, remat=False, cp_axis=None, dropout_rate=0.0
    )
    return TransformerLM(cfg)


def _quant_decode_model(model):
    """Decode twin for int8 serving: scanned configs additionally set
    ``quant_serving`` so each scan trip dequantizes only ITS layer slice
    (transformer._ScanBlock) — the int8 stack stays HBM-resident."""
    dm = decode_model(model)
    if dm.cfg.scan_layers:
        from distributeddataparallel_tpu.models.transformer import (
            TransformerLM,
        )

        return TransformerLM(
            dataclasses.replace(dm.cfg, quant_serving=True)
        )
    return dm


def _fix_unstacked_quant(params, dtype):
    """Defensive repair for hand-quantized trees fed to a SCANNED
    model: any 'layers' QuantLeaf whose scale lost the leading layer
    dim (quantized without ``stacked_first_dim``) cannot be sliced by
    nn.scan — serve those leaves dequantized instead (eagerly, outside
    the jit: they are the exception path, and typically the tiny norm
    stacks)."""
    from distributeddataparallel_tpu.ops.quant import (
        QuantLeaf,
        _is_entry,
    )

    if not isinstance(params, dict) or "layers" not in params:
        return params

    def _fix(e):
        if (
            isinstance(e, QuantLeaf)
            and e.scale.shape[0] != e.q.shape[0]
        ):
            return (
                e.q.astype(jnp.float32) * e.scale
            ).astype(dtype)
        return e

    fixed = jax.tree.map(_fix, params["layers"], is_leaf=_is_entry)
    return {**params, "layers": fixed}


def _live_params(cfg, params, quantized: bool):
    """The serving param policy, applied per apply (traced — fuses into
    the consumer jit).

    Quantized trees (ops.quant): dequantize PER APPLY so the bf16
    matrices are produced on-chip inside each matmul's operand fusion
    and decode streams int8 from HBM — hoisting one dequant out would
    re-materialize the bf16 tree and forfeit the bandwidth win.  Scanned
    configs go further: the stacked 'layers' subtree passes through AS
    QuantLeaf nodes and dequantizes per layer slice inside the layer
    scan (cfg.quant_serving / _ScanBlock) — dequantizing the whole stack
    here would materialize it in full per decode step.

    Dense f32 masters: cast to the compute dtype (a no-op identity map
    when the caller already pre-cast — decode is weight-streaming-bound,
    so loops should cast once and reuse; see _generate_jit).
    """
    if quantized:
        from distributeddataparallel_tpu.ops.quant import dequantize

        if cfg.scan_layers:
            return {
                k: (v if k == "layers" else dequantize(v, cfg.dtype))
                for k, v in params.items()
            }
        return dequantize(params, cfg.dtype)
    if cfg.dtype != jnp.float32:
        return jax.tree.map(
            lambda p: p.astype(cfg.dtype)
            if p.dtype == jnp.float32 else p,
            params,
        )
    return params


def init_cache(model, batch_size: int):
    """Allocate the decode twin's KV cache for ``batch_size`` rows.

    Shapes depend only on ``batch_size`` and ``cfg.max_seq_len`` (each
    layer holds ``cached_key``/``cached_value`` of shape
    ``(B, max_seq_len, kv_heads, head_dim)``; scanned configs stack a
    leading layer dim).  The init-time params are discarded — callers
    apply with their own.
    """
    dm = model if model.cfg.decode else decode_model(model)
    return dm.init(
        jax.random.PRNGKey(0),
        jnp.zeros((batch_size, 1), jnp.int32),
        positions=jnp.arange(1),
    )["cache"]


def _step_fns(dm, quantized: bool):
    """(prefill_fn, decode_fn) over an already-built decode twin."""
    cfg = dm.cfg

    def prefill_fn(params, cache, tokens, positions):
        """One prefill apply: ``tokens`` (B, S) at global ``positions``
        (S,) — chunked prefill passes successive chunks with
        ``positions = start + arange(S)``.  Returns ((B, S, V) logits,
        updated cache)."""
        logits, upd = dm.apply(
            {"params": _live_params(cfg, params, quantized),
             "cache": cache},
            tokens, positions=positions, mutable=["cache"],
        )
        return logits, upd["cache"]

    def decode_fn(params, cache, token, pos):
        """One decode step: ``token`` (B, S); ``pos`` is either a shared
        (S,) global position or a per-row (B, S) position matrix
        (continuous batching — every slot at its own length; S > 1 is a
        speculative-verify window of contiguous per-row positions).
        Returns ((B, S, V) logits, updated cache)."""
        logits, upd = dm.apply(
            {"params": _live_params(cfg, params, quantized),
             "cache": cache},
            token, positions=pos, mutable=["cache"],
        )
        return logits, upd["cache"]

    return prefill_fn, decode_fn


def make_step_fns(model, *, quantized: bool = False):
    """Build reusable ``(prefill_fn, decode_fn)`` over ``model``'s
    decode twin, for callers that drive decoding step-by-step (the
    serving engine's continuous-batching loop) instead of through the
    closed ``generate()`` scan.

    Both returned fns are pure ``(params, cache, tokens, positions) ->
    (logits, new_cache)`` — jit them with your own donation/sharding
    policy.  ``params`` follow the ``generate()`` convention: raw
    training params, or an ops.quant int8 tree when ``quantized=True``.
    Allocate ``cache`` with :func:`init_cache`.
    """
    dm = _quant_decode_model(model) if quantized else decode_model(model)
    return _step_fns(dm, quantized)


@functools.partial(
    jax.jit,
    static_argnums=(0, 3),
    static_argnames=("temperature", "top_k", "quantized"),
)
def _generate_jit(
    model, params, prompt, max_new_tokens, rng, *, temperature, top_k,
    quantized=False,
):
    cfg = model.cfg
    B, P = prompt.shape
    prefill_fn, decode_fn = _step_fns(model, quantized)

    if not quantized and cfg.dtype != jnp.float32:
        # Decode is weight-streaming-bound: every step reads the whole
        # matrix stack from HBM.  Cast f32 masters to the compute dtype
        # ONCE here (inside the jit: one fused device pass, amortized
        # over the whole generation) so the scan streams half the
        # bytes; _live_params then sees an already-cast tree and is an
        # identity map.
        params = jax.tree.map(
            lambda p: p.astype(cfg.dtype)
            if p.dtype == jnp.float32 else p,
            params,
        )

    # Cache allocation: shapes depend only on B and cfg.max_seq_len.
    cache = init_cache(model, B)

    # Prefill: the whole prompt in one apply; take the last position.
    logits, cache = prefill_fn(params, cache, prompt, jnp.arange(P))
    rng, sub = jax.random.split(rng)
    next_tok = _sample(
        logits[:, -1], sub, temperature, top_k
    )

    def body(carry, t):
        cache, tok, rng = carry
        logits, cache = decode_fn(params, cache, tok[:, None], t[None])
        rng, sub = jax.random.split(rng)
        nxt = _sample(logits[:, -1], sub, temperature, top_k)
        return (cache, nxt, rng), tok

    # N - 1 decode steps: each emits its incoming carried token (step i's
    # is the token at global position P + i) and samples the next; the
    # final carry is token P + N - 1, so no apply is ever wasted.
    (_, last, _), toks = jax.lax.scan(
        body,
        (cache, next_tok, rng),
        P + jnp.arange(max_new_tokens - 1),
    )
    return jnp.concatenate([prompt, toks.T, last[:, None]], axis=1)


def generate(
    model,
    params,
    prompt: jnp.ndarray,
    max_new_tokens: int,
    *,
    rng: jax.Array | None = None,
    temperature: float = 0.0,
    top_k: int | None = None,
    quantize: str | None = None,
) -> jnp.ndarray:
    """Generate ``max_new_tokens`` continuations of ``prompt`` (B, P).

    ``model`` is any TransformerLM (training config is fine — its decode
    twin is built internally); ``params`` are unchanged training params.
    Returns (B, P + max_new_tokens) int32.  ``temperature=0`` is greedy;
    otherwise pass ``rng`` for sampling (``top_k`` truncates first).
    ``quantize="int8"`` serves the matrices int8-quantized (ops.quant):
    roughly half the per-step HBM weight bytes of bf16 at <1%
    per-channel quantization error.

    Total length must fit the positional tables:
    ``P + max_new_tokens <= cfg.max_seq_len``.
    """
    B, P = prompt.shape
    if max_new_tokens < 1:
        raise ValueError("max_new_tokens must be >= 1")
    if P + max_new_tokens > model.cfg.max_seq_len:
        raise ValueError(
            f"prompt {P} + max_new_tokens {max_new_tokens} exceeds "
            f"max_seq_len {model.cfg.max_seq_len}"
        )
    if temperature < 0.0:
        raise ValueError("temperature must be >= 0")
    if temperature > 0.0 and rng is None:
        raise ValueError("sampling (temperature > 0) requires rng")
    if quantize not in (None, "int8"):
        raise ValueError(f"quantize must be None or 'int8', got {quantize!r}")
    from distributeddataparallel_tpu.ops.quant import is_quantized

    quantized = is_quantized(params)
    if quantize == "int8" and not quantized:
        from distributeddataparallel_tpu.ops.quant import (
            quantize_for_decode,
        )

        # One fused device pass (module-level jit: cached across
        # calls); the int8 tree is what the decode scan keeps resident
        # (ops.quant module docstring).  Serving loops should still
        # quantize ONCE and pass the quantized tree in — it is detected
        # and reused as-is, skipping even the cached dispatch.  Scanned
        # models quantize the stacked 'layers' subtree in stacked mode
        # (every scale keeps the layer dim — nn.scan slices scales
        # alongside q per trip).
        params = quantize_for_decode(params, model.cfg.scan_layers)
        quantized = True
    if quantized and model.cfg.scan_layers:
        params = _fix_unstacked_quant(params, model.cfg.dtype)
    dm = _quant_decode_model(model) if quantized else decode_model(model)
    return _generate_jit(
        dm, params, prompt.astype(jnp.int32), int(max_new_tokens),
        rng if rng is not None else jax.random.PRNGKey(0),
        temperature=float(temperature), top_k=top_k,
        quantized=quantized,
    )
