"""ResNet family, TPU-first (NHWC, bf16-capable, MXU-aligned widths).

The reference's entire model layer is ``models.resnet18(pretrained=True)``
with a 10-class head swap (ref dpp.py:14-15).  This is the TPU-native
equivalent of that torchvision dependency: ResNet-18/34/50 in Flax with

- NHWC layout (XLA's native conv layout on TPU);
- a ``stem`` switch: ``"imagenet"`` = 7×7/2 conv + 3×3/2 maxpool (the
  torchvision topology), ``"cifar"`` = 3×3/1 conv, no maxpool — fixing the
  reference's geometry mismatch of feeding 32×32 CIFAR through the
  ImageNet stem (SURVEY.md §2d.4);
- BatchNorm with framework-managed running stats (see ``training.state``;
  stats are averaged across data-parallel replicas each step — the SPMD
  equivalent of DDP keeping replica buffers consistent);
- ``dtype=bfloat16`` support for MXU throughput, params and BN math in
  float32.

Weight loading from torch-free checkpoints lives in ``models.io``.
"""

from __future__ import annotations

from functools import partial
from typing import Callable, Sequence

import flax.linen as nn
import jax.numpy as jnp

ModuleDef = Callable


class BasicBlock(nn.Module):
    """Two 3×3 convs (ResNet-18/34)."""

    filters: int
    strides: tuple[int, int]
    conv: ModuleDef
    norm: ModuleDef
    expansion: int = 1

    @nn.compact
    def __call__(self, x):
        residual = x
        y = self.conv(self.filters, (3, 3), self.strides)(x)
        y = self.norm()(y)
        y = nn.relu(y)
        y = self.conv(self.filters, (3, 3))(y)
        y = self.norm(scale_init=nn.initializers.zeros)(y)
        if residual.shape != y.shape:
            residual = self.conv(
                self.filters * self.expansion, (1, 1), self.strides,
                name="conv_proj",
            )(residual)
            residual = self.norm(name="norm_proj")(residual)
        return nn.relu(residual + y)


class BottleneckBlock(nn.Module):
    """1×1 → 3×3 → 1×1 (ResNet-50/101/152), v1.5: stride on the 3×3."""

    filters: int
    strides: tuple[int, int]
    conv: ModuleDef
    norm: ModuleDef
    expansion: int = 4

    @nn.compact
    def __call__(self, x):
        residual = x
        y = self.conv(self.filters, (1, 1))(x)
        y = self.norm()(y)
        y = nn.relu(y)
        y = self.conv(self.filters, (3, 3), self.strides)(y)
        y = self.norm()(y)
        y = nn.relu(y)
        y = self.conv(self.filters * self.expansion, (1, 1))(y)
        y = self.norm(scale_init=nn.initializers.zeros)(y)
        if residual.shape != y.shape:
            residual = self.conv(
                self.filters * self.expansion, (1, 1), self.strides,
                name="conv_proj",
            )(residual)
            residual = self.norm(name="norm_proj")(residual)
        return nn.relu(residual + y)


class ResNet(nn.Module):
    stage_sizes: Sequence[int]
    block_cls: ModuleDef
    num_classes: int = 1000
    num_filters: int = 64
    dtype: jnp.dtype = jnp.float32
    stem: str = "imagenet"  # "imagenet" (7x7/2 + maxpool) | "cifar" (3x3/1)
    bn_momentum: float = 0.9
    bn_epsilon: float = 1e-5

    @nn.compact
    def __call__(self, x, train: bool = True):
        conv = partial(
            nn.Conv, use_bias=False, dtype=self.dtype, padding="SAME"
        )
        norm = partial(
            nn.BatchNorm,
            use_running_average=not train,
            momentum=self.bn_momentum,
            epsilon=self.bn_epsilon,
            dtype=self.dtype,
        )
        x = x.astype(self.dtype)
        if self.stem == "imagenet":
            x = conv(self.num_filters, (7, 7), (2, 2), name="conv_init")(x)
            x = norm(name="bn_init")(x)
            x = nn.relu(x)
            x = nn.max_pool(x, (3, 3), strides=(2, 2), padding="SAME")
        elif self.stem == "cifar":
            x = conv(self.num_filters, (3, 3), (1, 1), name="conv_init")(x)
            x = norm(name="bn_init")(x)
            x = nn.relu(x)
        else:
            raise ValueError(f"unknown stem {self.stem!r}")

        for i, num_blocks in enumerate(self.stage_sizes):
            for j in range(num_blocks):
                strides = (2, 2) if i > 0 and j == 0 else (1, 1)
                x = self.block_cls(
                    filters=self.num_filters * 2**i,
                    strides=strides,
                    conv=conv,
                    norm=norm,
                )(x)
        x = jnp.mean(x, axis=(1, 2))
        # Head in float32 (logits precision), like the ref's fresh nn.Linear
        # 512->10 head swap (ref dpp.py:15).
        x = nn.Dense(self.num_classes, dtype=jnp.float32)(x)
        return x


ResNet18 = partial(ResNet, stage_sizes=(2, 2, 2, 2), block_cls=BasicBlock)
ResNet34 = partial(ResNet, stage_sizes=(3, 4, 6, 3), block_cls=BasicBlock)
ResNet50 = partial(ResNet, stage_sizes=(3, 4, 6, 3), block_cls=BottleneckBlock)
ResNet101 = partial(ResNet, stage_sizes=(3, 4, 23, 3), block_cls=BottleneckBlock)
