"""Decoder-only transformer LM family: one stack, GPT-2 and Llama configs.

The reference's model layer is a torchvision ResNet (ref dpp.py:11-18);
the LM models here exist for BASELINE configs 4 (GPT-2 124M pure DP) and
5 (Llama-3 8B, grad accumulation + overlapped all-reduce).  One
``TransformerLM`` covers both families through ``TransformerConfig``:

==============  =====================  =========================
feature         GPT-2                  Llama-3
==============  =====================  =========================
norm            LayerNorm (pre-LN)     RMSNorm
positional      learned embeddings     RoPE (theta 500000)
MLP             GELU, 4×d              SwiGLU, 3 mats
attention       MHA                    GQA (8 kv heads)
embeddings      tied in/out            untied
==============  =====================  =========================

TPU-first choices:

- bf16 activations/matmuls (MXU), f32 norms/softmax/logits (VPU);
  params stay f32 (optimizer math), cast per-use.
- ``scan_layers``: homogeneous blocks run under ``flax.linen.scan`` — one
  layer trace instead of L, an order-of-magnitude compile-time cut for the
  32-layer 8B config.
- ``remat``: per-block ``nn.remat`` (checkpoint) trades recompute for HBM,
  required to fit 8B pure-DP per chip (SURVEY.md §7 hard-part 3).
- attention dispatches through ``ops.attention.attention`` (Pallas flash
  kernel on TPU when shapes allow, XLA reference otherwise).
"""

from __future__ import annotations

import dataclasses
from typing import Any

import flax.linen as nn
import jax
import jax.numpy as jnp

from distributeddataparallel_tpu.ops.attention import (
    apply_rope,
    attention,
    repeat_kv,
    rope_frequencies,
)
from distributeddataparallel_tpu.parallel.tensor_parallel import (
    copy_to_tp,
    reduce_from_tp,
    tp_size,
)


@dataclasses.dataclass(frozen=True)
class TransformerConfig:
    vocab_size: int
    num_layers: int
    num_heads: int
    d_model: int
    d_ff: int
    max_seq_len: int
    num_kv_heads: int | None = None  # None -> MHA (= num_heads)
    head_dim: int | None = None      # None -> d_model // num_heads
    norm: str = "layernorm"          # "layernorm" | "rmsnorm"
    activation: str = "gelu"         # "gelu" | "swiglu"
    positional: str = "learned"      # "learned" | "rope"
    rope_theta: float = 10000.0
    tie_embeddings: bool = True
    dtype: Any = jnp.float32         # activation/matmul dtype
    remat: bool = False
    scan_layers: bool = False
    attn_impl: str = "auto"          # "auto" | "xla" | "pallas"
    dropout_rate: float = 0.0        # residual-branch dropout (GPT-2 style)
    use_bias: bool = True            # proj biases: GPT-2 yes, Llama no
    # Context parallelism: name of the mesh axis the sequence dimension is
    # sharded over.  When set, the model must run inside shard_map with
    # that axis bound; attention becomes collective over the axis and
    # positions default to each shard's global offsets.  ``cp_impl``
    # picks the collective: "ring" (blockwise ppermute ring — memory
    # O(S/N), scales past the head count) or "ulysses" (two all_to_alls
    # to a head-sharded layout — local attention sees the full sequence
    # and can use the Pallas flash kernel; requires num_heads % N == 0).
    cp_axis: str | None = None
    cp_impl: str = "ring"            # "ring" | "ulysses"
    # Tensor parallelism: name of the mesh axis attention heads and MLP
    # hidden units are sharded over (Megatron column/row split, see
    # parallel.tensor_parallel).  When set, the model must run inside
    # shard_map with that axis bound and params sharded by
    # ``tp_param_specs``; unbound (init / direct apply) it degrades to
    # the full unsharded shapes.
    tp_axis: str | None = None
    # Autoregressive decoding: attention layers keep a KV cache sized
    # max_seq_len in the "cache" variable collection and attend against
    # it.  The caller passes explicit global ``positions`` per apply
    # (prefill: arange(P); decode: the single next position) and makes
    # the collection mutable — see ``models.generate``.  Mutually
    # exclusive with cp_axis (sequence-sharded training) and remat.
    decode: bool = False
    # Mixture-of-experts: replace every block's MLP with `moe_experts`
    # expert MLPs routed top-`moe_top_k` (1 = switch, 2 = Mixtral-style
    # with renormalized gates).  `ep_axis` shards the expert dimension
    # over a mesh axis (parallel.expert_parallel).
    #
    # Dispatch is picked by `moe_capacity_factor`:
    # - 0.0 (default): dense einsum dispatch — every token through every
    #   local expert, a (B, S, E) combine tensor blends.  No
    #   gather/scatter, ideal at tiny E; FLOPs scale with E.
    # - > 0: token-choice dispatch (GShard/Switch, ops.moe) — each token
    #   occupies at most K capacity-bounded expert slots, overflow drops
    #   through the residual.  FLOPs scale with K, not E.  Under EP the
    #   token slots are exchanged with a real all_to_all over the
    #   expert axis.
    moe_experts: int = 0
    moe_top_k: int = 1
    ep_axis: str | None = None
    moe_capacity_factor: float = 0.0
    # Data-parallel grad sync INSIDE the backward scan: name of the mesh
    # axis the scanned blocks' param gradients are pmean'd over, per scan
    # iteration, via an identity-with-all-reduce-VJP on the param reads
    # (``parallel.data_parallel.sync_grad_in_backward``).  Scanned models
    # otherwise hold every layer grad inside the backward while-loop
    # where no post-loop all-reduce can overlap them (OVERLAP.md).
    # Requires ``scan_layers``; the train step must skip these leaves in
    # its own sync (``make_train_step(presynced=scanned_param_paths)``).
    # Backward passes must then run inside shard_map with the axis bound.
    grad_sync_axis: str | None = None
    # bf16 comm-hook for the in-scan reduction: the per-layer cotangents
    # cross the wire in bfloat16 (see data_parallel.all_reduce_gradients
    # ``compress``).  Only meaningful with grad_sync_axis.
    grad_sync_compress: str | None = None
    # int8 weight-only serving (ops.quant): the scanned blocks
    # dequantize their per-layer param slice INSIDE the scan body so the
    # int8 stack stays HBM-resident (set by models.generate for
    # quantized decode; see _ScanBlock).
    quant_serving: bool = False

    @property
    def kv_heads(self) -> int:
        return self.num_kv_heads or self.num_heads

    @property
    def dims_per_head(self) -> int:
        return self.head_dim or self.d_model // self.num_heads


# --- Named configs (sizes per the public GPT-2 / Llama-3 papers) ---------

def gpt2_124m(**overrides) -> TransformerConfig:
    """GPT-2 small: 12L/12H/768d, 4×d GELU MLP, 50257 vocab, tied embs."""
    base = dict(
        vocab_size=50257, num_layers=12, num_heads=12, d_model=768,
        d_ff=3072, max_seq_len=1024, norm="layernorm", activation="gelu",
        positional="learned", tie_embeddings=True,
    )
    base.update(overrides)
    return TransformerConfig(**base)


def llama3_8b(**overrides) -> TransformerConfig:
    """Llama-3 8B: 32L/32H(8kv)/4096d, 14336 SwiGLU, 128256 vocab, RoPE."""
    base = dict(
        vocab_size=128256, num_layers=32, num_heads=32, num_kv_heads=8,
        d_model=4096, d_ff=14336, max_seq_len=8192, norm="rmsnorm",
        activation="swiglu", positional="rope", rope_theta=500000.0,
        tie_embeddings=False, dtype=jnp.bfloat16, remat=True,
        scan_layers=True, use_bias=False,
    )
    base.update(overrides)
    return TransformerConfig(**base)


def tiny_lm(**overrides) -> TransformerConfig:
    """Test-sized config (fast CPU init/compile)."""
    base = dict(
        vocab_size=256, num_layers=2, num_heads=2, d_model=32, d_ff=64,
        max_seq_len=128, norm="rmsnorm", activation="swiglu",
        positional="rope", tie_embeddings=True,
    )
    base.update(overrides)
    return TransformerConfig(**base)


def moe_aux_from_intermediates(col) -> Any:
    """Mean of the per-layer sown switch load-balance terms (sow wraps
    each in a tuple; scan stacks them) — layer-count independent.  ONE
    definition shared by every loss path (CP / plain LM / pipeline)."""
    terms = jax.tree.leaves(col)
    return sum(jnp.mean(t) for t in terms) / max(len(terms), 1)


class RMSNorm(nn.Module):
    """Llama-style RMS normalization; stats in f32, scale param f32."""

    epsilon: float = 1e-5

    @nn.compact
    def __call__(self, x):
        dtype = x.dtype
        x = x.astype(jnp.float32)
        scale = self.param("scale", nn.initializers.ones, (x.shape[-1],))
        x = x * jax.lax.rsqrt(jnp.mean(x * x, axis=-1, keepdims=True) + self.epsilon)
        return (x * scale).astype(dtype)


def _make_norm(cfg: TransformerConfig, name: str):
    if cfg.norm == "rmsnorm":
        return RMSNorm(name=name)
    # LayerNorm math in f32 regardless of activation dtype.
    return nn.LayerNorm(epsilon=1e-5, dtype=jnp.float32, name=name)


class _RowParallelOut(nn.Module):
    """Row-parallel output projection (attention o / MLP down).

    Parameter names and full shapes are identical to the DenseGeneral /
    Dense it replaces (``kernel``, optional ``bias``) so checkpoints and
    weight-io never see TP.  Under TP the kernel's leading (input) dims
    are sharded; the partial product is completed with ``reduce_from_tp``
    and the bias — replicated — is added AFTER the psum (adding it per
    position would count it tp× times).
    """

    features: int
    kernel_shape: tuple  # full kernel shape, batch-axes first
    contract_ndim: int   # how many trailing input dims the kernel eats
    use_bias: bool
    dtype: Any
    kernel_init: Any
    tp_axis: Any = None

    @nn.compact
    def __call__(self, x):
        n_tp = tp_size(self.tp_axis)
        shape = (self.kernel_shape[0] // n_tp,) + tuple(self.kernel_shape[1:])
        kernel = self.param("kernel", self.kernel_init, shape, jnp.float32)
        cdims = tuple(range(x.ndim - self.contract_ndim, x.ndim))
        kdims = tuple(range(self.contract_ndim))
        y = jax.lax.dot_general(
            x.astype(self.dtype), kernel.astype(self.dtype),
            ((cdims, kdims), ((), ())),
        )
        if self.tp_axis is not None and n_tp > 1:
            y = reduce_from_tp(y, self.tp_axis)
        if self.use_bias:
            bias = self.param(
                "bias", nn.initializers.zeros, (self.features,), jnp.float32
            )
            y = y + bias.astype(self.dtype)
        return y


class Attention(nn.Module):
    cfg: TransformerConfig

    @nn.compact
    def __call__(self, x, *, positions=None, rope=None, deterministic=True):
        cfg = self.cfg
        B, S, _ = x.shape
        H, Hkv, D = cfg.num_heads, cfg.kv_heads, cfg.dims_per_head
        n_tp = tp_size(cfg.tp_axis)
        if H % n_tp or Hkv % n_tp:
            raise ValueError(
                f"tp={n_tp} must divide num_heads={H} and kv_heads={Hkv}"
            )
        Hl, Hkvl = H // n_tp, Hkv // n_tp  # per-position head counts
        if cfg.tp_axis is not None and n_tp > 1:
            x = copy_to_tp(x, cfg.tp_axis)
        dense = lambda feats, name: nn.DenseGeneral(
            feats, axis=-1, dtype=cfg.dtype, name=name, use_bias=cfg.use_bias,
            kernel_init=nn.initializers.normal(0.02),
        )
        q = dense((Hl, D), "q_proj")(x)
        k = dense((Hkvl, D), "k_proj")(x)
        v = dense((Hkvl, D), "v_proj")(x)
        if cfg.positional == "rope":
            # Tables are computed once in TransformerLM and passed down so
            # they sit outside the scanned/remat'd block body.
            cos, sin = rope if rope is not None else rope_frequencies(
                D, cfg.max_seq_len, theta=cfg.rope_theta
            )
            q = apply_rope(q, cos, sin, positions=positions)
            k = apply_rope(k, cos, sin, positions=positions)
        if cfg.decode:
            # KV-cache attention: insert this call's k/v at the caller's
            # global positions, attend q against the whole cache with a
            # positional mask (static shapes: the cache is always
            # max_seq_len long; future slots sit behind NEG_INF).
            if positions is None:
                raise ValueError(
                    "decode=True requires explicit positions "
                    "(models.generate passes them)"
                )
            from distributeddataparallel_tpu.ops.attention import (
                NEG_INF,
                causal_mask_bias,
                dot_product_attention,
            )

            ck = self.variable(
                "cache", "cached_key", jnp.zeros,
                (B, cfg.max_seq_len, Hkvl, D), k.dtype,
            )
            cv = self.variable(
                "cache", "cached_value", jnp.zeros,
                (B, cfg.max_seq_len, Hkvl, D), v.dtype,
            )
            if positions.ndim == 2:
                # Per-row positions (B, S): continuous-batching decode
                # where every slot sits at its own length (serving
                # engine).  S == 1 is the classic one-token step; S > 1
                # is a speculative-verify window — each row inserts S
                # tokens at ITS OWN contiguous positions and row i
                # attends causally through position[b, i].  Rows past a
                # slot's position hold stale/garbage values, which the
                # finite NEG_INF bias zeroes exactly in the softmax.
                if positions.shape != (B, S):
                    raise ValueError(
                        f"per-row positions must be ({B}, {S}), got "
                        f"{positions.shape}"
                    )
                row = jnp.arange(B)[:, None]  # (B, 1) broadcast index
                ck.value = ck.value.at[row, positions].set(k)
                cv.value = cv.value.at[row, positions].set(v)
                kf = repeat_kv(ck.value, Hl // Hkvl)
                vf = repeat_kv(cv.value, Hl // Hkvl)
                kv_pos = jnp.arange(cfg.max_seq_len)
                bias = jnp.where(
                    kv_pos[None, None, None, :]
                    <= positions[:, None, :, None],
                    0.0, NEG_INF,
                ).astype(jnp.float32)  # (B, 1, S, max_seq_len)
                out = dot_product_attention(
                    q, kf, vf, causal=False, bias=bias
                )
            else:
                pos = positions.reshape(-1)  # (S,) global token positions
                ck.value = jax.lax.dynamic_update_slice(
                    ck.value, k, (0, pos[0], 0, 0)
                )
                cv.value = jax.lax.dynamic_update_slice(
                    cv.value, v, (0, pos[0], 0, 0)
                )
                kf = repeat_kv(ck.value, Hl // Hkvl)
                vf = repeat_kv(cv.value, Hl // Hkvl)
                # Positions are contiguous from pos[0] (the insert
                # offset), so the cache mask is the ordinary causal bias
                # at that q offset.
                bias = causal_mask_bias(
                    S, cfg.max_seq_len, q_offset=pos[0]
                )
                out = dot_product_attention(
                    q, kf, vf, causal=False, bias=bias[None, None]
                )
        elif cfg.cp_axis is not None and cfg.cp_impl == "ulysses":
            from distributeddataparallel_tpu.parallel.context_parallel import (
                ulysses_attention,
            )

            # GQA-native: ulysses exchanges kv at its own head count when
            # the axis divides it, expanding internally otherwise.
            out = ulysses_attention(
                q, k, v, axis_name=cfg.cp_axis, causal=True,
                impl=cfg.attn_impl,
            )
        elif cfg.cp_axis is not None:
            if cfg.cp_impl != "ring":
                raise ValueError(f"unknown cp_impl {cfg.cp_impl!r}")
            from distributeddataparallel_tpu.parallel.context_parallel import (
                ring_attention,
            )

            # Ring attention contracts q and kv headwise: expand GQA here.
            k = repeat_kv(k, Hl // Hkvl)
            v = repeat_kv(v, Hl // Hkvl)
            out = ring_attention(
                q, k, v, axis_name=cfg.cp_axis, causal=True,
                impl=cfg.attn_impl,
            )
        else:
            # GQA kv stays at its own head count: the flash kernel indexes
            # the shared head natively; the XLA path expands internally.
            out = attention(q, k, v, causal=True, impl=cfg.attn_impl)
        return _RowParallelOut(
            features=cfg.d_model,
            kernel_shape=(H, D, cfg.d_model),
            contract_ndim=2,
            use_bias=cfg.use_bias,
            dtype=cfg.dtype,
            kernel_init=nn.initializers.normal(
                0.02 / (2 * cfg.num_layers) ** 0.5
            ),
            tp_axis=cfg.tp_axis,
            name="o_proj",
        )(out)


class MLP(nn.Module):
    cfg: TransformerConfig

    @nn.compact
    def __call__(self, x):
        cfg = self.cfg
        n_tp = tp_size(cfg.tp_axis)
        if cfg.d_ff % n_tp:
            raise ValueError(f"tp={n_tp} must divide d_ff={cfg.d_ff}")
        ffl = cfg.d_ff // n_tp  # per-position hidden width
        if cfg.tp_axis is not None and n_tp > 1:
            x = copy_to_tp(x, cfg.tp_axis)
        dense = lambda feats, name: nn.Dense(
            feats, dtype=cfg.dtype, name=name, use_bias=cfg.use_bias,
            kernel_init=nn.initializers.normal(0.02),
        )
        if cfg.activation == "swiglu":
            gate = dense(ffl, "gate_proj")(x)
            up = dense(ffl, "up_proj")(x)
            h = nn.silu(gate) * up
        elif cfg.activation == "gelu":
            h = nn.gelu(dense(ffl, "up_proj")(x), approximate=True)
        else:
            raise ValueError(f"unknown activation {cfg.activation!r}")
        return _RowParallelOut(
            features=cfg.d_model,
            kernel_shape=(cfg.d_ff, cfg.d_model),
            contract_ndim=1,
            use_bias=cfg.use_bias,
            dtype=cfg.dtype,
            kernel_init=nn.initializers.normal(0.02),
            tp_axis=cfg.tp_axis,
            name="down_proj",
        )(h)


class MoEMLP(nn.Module):
    """Top-k-routed mixture-of-experts MLP.

    Routing: ``cfg.moe_top_k == 1`` is the Switch convention (the raw
    top probability gates the output — that dependence is what trains
    the router); ``k > 1`` is Mixtral-style (probabilities renormalized
    over the selected k, gradients flow through the renormalization).

    Two dispatch strategies (picked by ``cfg.moe_capacity_factor``):

    **Dense einsum** (capacity_factor 0): every token's hidden state is
    pushed through each LOCAL expert as one batched einsum (MXU-friendly
    — no gather/scatter) and a dense (B, S, E) combine-weight tensor
    blends the outputs.  Under EP each mesh position computes its E/n
    experts over ALL tokens and the combine is one psum
    (``reduce_from_tp``).  FLOPs scale with E — right for tiny E, wrong
    at Mixtral scale.

    **Token-choice** (capacity_factor > 0, ``ops.moe``): each token
    occupies at most K slots in a ``(E, capacity)`` buffer; overflow
    drops through the residual.  FLOPs scale with K, not E.  Under EP
    each position routes ITS 1/n slice of the tokens, exchanges slot
    buffers with one ``all_to_all`` over the expert axis (tokens travel
    to their experts — the GShard dataflow), computes its local experts
    on all sources' slots, all_to_alls back, combines its slice, and
    restores replication with an ``all_gather``.

    Gradient completeness: replicated params' grads must come out
    complete and identical on every expert-axis position so the
    data-axis sync needs no EP-awareness.  The dense path achieves this
    with ``copy_to_tp`` (backward psum) on its replicated inputs; the
    token-choice path instead uses the slice/all_gather conjugate pair
    ``ep_shard_tokens``/``ep_unshard_tokens`` — a psum there would
    overcount n× because each position only handles its token slice
    (see parallel.expert_parallel).
    """

    cfg: TransformerConfig

    @nn.compact
    def __call__(self, x):
        from distributeddataparallel_tpu.parallel.tensor_parallel import (
            copy_to_tp,
            reduce_from_tp,
            tp_size,
        )

        cfg = self.cfg
        E, K = cfg.moe_experts, cfg.moe_top_k
        n_ep = tp_size(cfg.ep_axis)
        if E % n_ep:
            raise ValueError(f"ep={n_ep} must divide moe_experts={E}")
        if not 1 <= K <= E:
            raise ValueError(f"moe_top_k={K} must be in [1, {E}]")
        El = E // n_ep
        d, f = cfg.d_model, cfg.d_ff

        # Router runs replicated (its params are tiny); f32 for a stable
        # softmax.
        logits = nn.Dense(
            E, dtype=jnp.float32, use_bias=False, name="router",
            kernel_init=nn.initializers.normal(0.02),
        )(x.astype(jnp.float32))
        probs = jax.nn.softmax(logits, axis=-1)        # (B, S, E)
        vals, idx = jax.lax.top_k(probs, K)            # (B, S, K)
        if K > 1:
            vals = vals / jnp.sum(vals, axis=-1, keepdims=True)
        sel = jax.nn.one_hot(idx, E, dtype=jnp.float32)  # (B, S, K, E)

        # Load-balance auxiliary (Fedus et al. / GShard): E * sum f_e*P_e,
        # f_e = fraction of routing slots assigned to expert e (stop-grad
        # via top_k), P_e = mean router probability.  Minimized at
        # uniform routing; exposed through sow — loss_fns opt in with
        # apply(..., mutable=["intermediates"]) and add moe_aux * weight
        # (the dpp.py CLI does).
        frac = jnp.mean(sel, axis=(0, 1, 2))           # sums to 1/... per slot
        self.sow(
            "intermediates", "moe_aux",
            E * jnp.sum(frac * probs.mean(axis=(0, 1))),
        )

        init = nn.initializers.normal(0.02)
        w_up = self.param("experts_up", init, (El, d, f), jnp.float32)
        w_down = self.param("experts_down", init, (El, f, d), jnp.float32)
        w_gate = (
            self.param("experts_gate", init, (El, d, f), jnp.float32)
            if cfg.activation == "swiglu"
            else None
        )

        def experts(z):
            """Batched expert MLP: (El, n, d) -> (El, n, d)."""
            h = jnp.einsum("end,edf->enf", z, w_up.astype(cfg.dtype))
            if w_gate is not None:
                g = jnp.einsum("end,edf->enf", z, w_gate.astype(cfg.dtype))
                h = nn.silu(g) * h
            else:
                h = nn.gelu(h, approximate=True)
            return jnp.einsum("enf,efd->end", h, w_down.astype(cfg.dtype))

        if cfg.moe_capacity_factor > 0:
            return self._token_choice(x, vals, idx, experts, n_ep)

        # --- Dense einsum dispatch ---------------------------------------
        # Dense combine weights: w[b,s,e] = this token's gate for expert
        # e (0 off the top-k).
        w = jnp.sum(sel * vals[..., None], axis=2)     # (B, S, E)
        if cfg.ep_axis is not None and n_ep > 1:
            x = copy_to_tp(x, cfg.ep_axis)
            w = copy_to_tp(w, cfg.ep_axis)
        xe = x.astype(cfg.dtype)
        # Kept as bsd,edf einsums rather than experts() on a broadcast
        # (El, B*S, d) operand: the einsum guarantees x is never
        # materialised El times in HBM.
        h = jnp.einsum("bsd,edf->ebsf", xe, w_up.astype(cfg.dtype))
        if w_gate is not None:
            g = jnp.einsum("bsd,edf->ebsf", xe, w_gate.astype(cfg.dtype))
            h = nn.silu(g) * h
        else:
            h = nn.gelu(h, approximate=True)
        y = jnp.einsum(
            "ebsf,efd->ebsd", h, w_down.astype(cfg.dtype)
        )  # (El, B, S, d)

        # Local combine: this position's experts are global
        # [ep_rank*El, (ep_rank+1)*El); slice the weight tensor to match
        # and blend, then complete the partial sum over the expert axis.
        first = (
            jax.lax.axis_index(cfg.ep_axis) * El
            if cfg.ep_axis is not None and n_ep > 1
            else 0
        )
        w_local = jax.lax.dynamic_slice_in_dim(w, first, El, axis=2)
        out = jnp.einsum(
            "ebsd,bse->bsd", y, w_local.astype(cfg.dtype)
        )
        if cfg.ep_axis is not None and n_ep > 1:
            out = reduce_from_tp(out, cfg.ep_axis)
        return out

    def _token_choice(self, x, vals, idx, experts, n_ep):
        """Capacity-bounded token-choice dispatch (ops.moe)."""
        from distributeddataparallel_tpu.ops.moe import (
            combine,
            dispatch,
            moe_capacity,
            token_choice_slots,
        )

        cfg = self.cfg
        E, K, El = cfg.moe_experts, cfg.moe_top_k, cfg.moe_experts // n_ep
        B, S, d = x.shape
        T = B * S
        ep = cfg.ep_axis if n_ep > 1 else None
        if ep is not None and T % n_ep:
            raise ValueError(
                f"token-choice EP needs tokens ({T}) divisible by the "
                f"expert-axis size ({n_ep})"
            )
        Tl = T // n_ep
        xt = x.reshape(T, d)
        vt = vals.reshape(T, K)
        it = idx.reshape(T, K)
        if ep is not None:
            # Conjugate entry (parallel.expert_parallel.ep_shard_tokens):
            # slice forward, all_gather backward — x and the gate values
            # carry gradients for upstream replicated params and the
            # router, which must come out complete and identical on
            # every expert-axis position.
            from distributeddataparallel_tpu.parallel.expert_parallel import (
                ep_shard_tokens,
            )

            xt = ep_shard_tokens(xt, ep)
            vt = ep_shard_tokens(vt, ep)
            r = jax.lax.axis_index(ep)
            it = jax.lax.dynamic_slice_in_dim(it, r * Tl, Tl, 0)
        C = moe_capacity(Tl, E, K, cfg.moe_capacity_factor)

        tok_for_slot, gate_for_slot = token_choice_slots(it, vt, E, C)
        z = dispatch(xt.astype(cfg.dtype), tok_for_slot)  # (E*C, d)
        if ep is not None:
            # Tokens travel to their experts: slot buffers for expert
            # block j go to position j; received leading dim indexes the
            # SOURCE position.
            z = jax.lax.all_to_all(
                z.reshape(n_ep, El, C, d), ep, split_axis=0, concat_axis=0
            )
            z = z.transpose(1, 0, 2, 3).reshape(El, n_ep * C, d)
        else:
            z = z.reshape(E, C, d)
        y = experts(z)
        if ep is not None:
            y = y.reshape(El, n_ep, C, d).transpose(1, 0, 2, 3)
            # Outputs travel back: piece s returns to source position s,
            # restoring this position's original (E, C) slot order.
            y = jax.lax.all_to_all(y, ep, split_axis=0, concat_axis=0)
        out = combine(
            y.reshape(E * C, d), tok_for_slot, gate_for_slot, Tl
        )
        if ep is not None:
            # Conjugate exit: all_gather forward restores replication;
            # backward keeps each position's own chunk of the
            # (replicated-identical) cotangent.
            from distributeddataparallel_tpu.parallel.expert_parallel import (
                ep_unshard_tokens,
            )

            out = ep_unshard_tokens(out, ep)
        return out.reshape(B, S, d)


class DecoderBlock(nn.Module):
    cfg: TransformerConfig

    @nn.compact
    def __call__(self, x, positions=None, rope=None, deterministic=True):
        cfg = self.cfg
        drop = nn.Dropout(cfg.dropout_rate, deterministic=deterministic)
        y = _make_norm(cfg, "attn_norm")(x)
        x = x + drop(
            Attention(cfg, name="attn")(
                y, positions=positions, rope=rope, deterministic=deterministic
            )
        )
        y = _make_norm(cfg, "mlp_norm")(x)
        mlp = (
            MoEMLP(cfg, name="mlp") if cfg.moe_experts > 0
            else MLP(cfg, name="mlp")
        )
        x = x + drop(mlp(y))
        return x


class _ScanBlock(nn.Module):
    """DecoderBlock adapted to linen.scan's (carry, *broadcast) shape.

    Under ``cfg.grad_sync_axis`` the block's params are read through
    ``sync_grad_in_backward``: forward identity, backward pmean over the
    data axis — so each scan iteration's param-slice gradient is reduced
    inside the backward while-loop body where the async scheduler can
    hide it under the trip's remaining backward compute (the only
    overlap available to a scanned model; see parallel/overlap.py).

    Under ``cfg.quant_serving`` (int8 weight-only decode, ops.quant) the
    per-layer param SLICE is dequantized here, inside the scan body —
    nn.scan splits the stacked ``QuantLeaf`` nodes along the layer dim
    like any pytree, so each trip dequantizes only its own layer and the
    int8 stack stays HBM-resident.  Dequantizing the whole stack before
    the scan instead measures SLOWER than bf16 (full-stack bf16
    materialization per decode step: +2x the byte traffic it was meant
    to save).
    """

    cfg: TransformerConfig

    @nn.compact
    def __call__(self, x, positions, rope, deterministic):
        cls = DecoderBlock
        trans = []
        if self.cfg.grad_sync_axis is not None:
            from distributeddataparallel_tpu.parallel.data_parallel import (
                sync_grad_in_backward,
            )

            axis = self.cfg.grad_sync_axis
            comp = self.cfg.grad_sync_compress
            trans.append(
                lambda vs: sync_grad_in_backward(vs, axis, compress=comp)
            )
        if self.cfg.quant_serving:
            from distributeddataparallel_tpu.ops.quant import dequantize

            dt = self.cfg.dtype
            trans.append(lambda vs: dequantize(vs, dt))
        if trans:
            def chain(vs, _fns=tuple(trans)):
                for f in _fns:
                    vs = f(vs)
                return vs

            cls = nn.map_variables(
                DecoderBlock,
                "params",
                trans_in_fn=(
                    (lambda vs: vs) if self.is_initializing() else chain
                ),
                init=self.is_initializing(),
            )
        x = cls(self.cfg, name="block")(
            x, positions, rope, deterministic
        )
        return x, None


def scanned_layer_cls(cfg: TransformerConfig, length: int | None = None):
    """The scan-transformed decoder-block class — ONE construction shared
    by TransformerLM and the pipeline-parallel stage runner, so a slice
    of the stacked params always applies under identical scan settings
    (remat wrapper, rng splitting, partition metadata).

    ``length`` overrides the layer count (a PP stage runs
    ``num_layers / n_stages`` of the stack).
    """
    scan_block = (
        nn.remat(_ScanBlock, prevent_cse=False, static_argnums=(4,))
        if cfg.remat
        else _ScanBlock
    )
    return nn.scan(
        scan_block,
        # intermediates: MoE blocks sow their load-balance aux per layer;
        # stacked along the scan dim when the caller makes it mutable
        # (a no-op for dense models / immutable applies).  cache: per-layer
        # KV caches under decode, stacked the same way.
        variable_axes={"params": 0, "intermediates": 0, "cache": 0},
        split_rngs={"params": True, "dropout": True},
        in_axes=(nn.broadcast, nn.broadcast, nn.broadcast),
        length=length if length is not None else cfg.num_layers,
        metadata_params={nn.PARTITION_NAME: "layers"},
    )


class LMHead(nn.Module):
    """Untied output projection: params identical to a bias-free Dense
    (``{"kernel": (d_model, vocab)}`` f32, so checkpoints/weight-io are
    unchanged), but the matmul takes ``compute_dtype`` operands with f32
    MXU accumulation instead of casting operands to f32."""

    vocab_size: int
    compute_dtype: Any

    @nn.compact
    def __call__(self, x):
        kernel = self.param(
            "kernel", nn.initializers.normal(0.02),
            (x.shape[-1], self.vocab_size), jnp.float32,
        )
        return jax.lax.dot_general(
            x.astype(self.compute_dtype), kernel.astype(self.compute_dtype),
            (((x.ndim - 1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )


class TransformerLM(nn.Module):
    """Decoder-only LM: tokens (B, S) int32 -> logits (B, S, vocab) f32."""

    cfg: TransformerConfig

    @nn.compact
    def __call__(self, tokens, *, positions=None, deterministic=True):
        cfg = self.cfg
        B, S = tokens.shape
        if cfg.decode and (cfg.cp_axis is not None or cfg.remat):
            # The KV cache is a mutable collection: remat can't replay it
            # and sequence sharding has no cache layout; generate() builds
            # a decode twin config with both off.
            raise ValueError("decode does not compose with cp_axis/remat")
        # Under CP the model sees a local shard: the bound check must use
        # the GLOBAL length, or out-of-range RoPE/pos_embed lookups get
        # silently clamped by XLA's gather semantics instead of erroring.
        # psum of a literal over a named axis is a trace-time constant
        # (the axis size); outside shard_map the axis is unbound -> treat
        # as unsharded (direct single-device apply / init).
        n_seq_shards = 1
        if cfg.cp_axis is not None:
            try:
                n_seq_shards = int(jax.lax.psum(1, cfg.cp_axis))
            except NameError:
                n_seq_shards = 1
        if S * n_seq_shards > cfg.max_seq_len:
            detail = (
                f"global seq len {S * n_seq_shards} ({S} local x "
                f"{n_seq_shards} {cfg.cp_axis!r} shards)"
                if n_seq_shards > 1
                else f"seq len {S}"
            )
            raise ValueError(f"{detail} > max_seq_len {cfg.max_seq_len}")
        if cfg.cp_axis is not None and positions is None:
            from distributeddataparallel_tpu.parallel.context_parallel import (
                cp_positions,
            )

            # Sequence-sharded run: this shard's global token offsets.
            positions = cp_positions(S, cfg.cp_axis)
        embed = nn.Embed(
            cfg.vocab_size, cfg.d_model, name="token_embed",
            embedding_init=nn.initializers.normal(0.02),
            param_dtype=jnp.float32,
        )
        x = embed(tokens).astype(cfg.dtype)
        if cfg.positional == "learned":
            pos = positions if positions is not None else jnp.arange(S)
            pos_embed = self.param(
                "pos_embed", nn.initializers.normal(0.02),
                (cfg.max_seq_len, cfg.d_model), jnp.float32,
            )
            x = x + pos_embed[pos].astype(cfg.dtype)
        x = nn.Dropout(cfg.dropout_rate, deterministic=deterministic)(x)

        rope = None
        if cfg.positional == "rope":
            rope = rope_frequencies(
                cfg.dims_per_head, cfg.max_seq_len, theta=cfg.rope_theta
            )
        if cfg.grad_sync_axis is not None and not cfg.scan_layers:
            # Unrolled layers emit per-leaf grads at top level, where the
            # train step's own bucketed reduction already overlaps; the
            # in-body sync exists for the scan case only.
            raise ValueError("grad_sync_axis requires scan_layers=True")
        if cfg.scan_layers:
            # One traced layer instead of L (compile time); under scan,
            # remat wraps the scan body (prevent_cse must be False there).
            x, _ = scanned_layer_cls(cfg)(cfg, name="layers")(
                x, positions, rope, deterministic
            )
        else:
            block_cls = (
                nn.remat(DecoderBlock, static_argnums=(4,))
                if cfg.remat
                else DecoderBlock
            )
            for i in range(cfg.num_layers):
                x = block_cls(cfg, name=f"layer_{i}")(
                    x, positions, rope, deterministic
                )

        x = _make_norm(cfg, "final_norm")(x)
        # Logits in f32 (loss precision; analog of the ResNet head rule),
        # but the matmul runs with cfg.dtype OPERANDS and f32 MXU
        # accumulation (preferred_element_type): f32 operands would run
        # the vocab-sized matmul at 1/4 MXU rate — measured ~25% of the
        # whole GPT-2 train step.  Under cfg.dtype=float32 (tests, CPU)
        # the casts are no-ops and this is exactly the f32 matmul.
        if cfg.tie_embeddings:
            w = embed.embedding.astype(cfg.dtype)  # (V, D)
            logits = jax.lax.dot_general(
                x.astype(cfg.dtype), w, (((x.ndim - 1,), (1,)), ((), ())),
                preferred_element_type=jnp.float32,
            )
        else:
            logits = LMHead(cfg.vocab_size, cfg.dtype, name="lm_head")(x)
        return logits
