from distributeddataparallel_tpu.models.simple_cnn import SimpleCNN, TinyMLP  # noqa: F401
from distributeddataparallel_tpu.models.resnet import (  # noqa: F401
    ResNet,
    ResNet18,
    ResNet34,
    ResNet50,
    ResNet101,
)
from distributeddataparallel_tpu.models.transformer import (  # noqa: F401
    TransformerConfig,
    TransformerLM,
    gpt2_124m,
    llama3_8b,
    tiny_lm,
)
from distributeddataparallel_tpu.models.generate import generate  # noqa: F401
