from distributeddataparallel_tpu.models.simple_cnn import SimpleCNN, TinyMLP  # noqa: F401
from distributeddataparallel_tpu.models.resnet import (  # noqa: F401
    ResNet,
    ResNet18,
    ResNet34,
    ResNet50,
    ResNet101,
)
