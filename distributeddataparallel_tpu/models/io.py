"""Pretrained-weight interchange: torch-free storage + torch converters.

The reference's model layer is ``models.resnet18(pretrained=True)``
(ref dpp.py:14) — weights arrive through torchvision's torch-pickle hub
format.  This module gives the TPU framework the same capability without
a torch dependency on the load path:

- ``save_params`` / ``load_params``: flat safetensors files (portable,
  zero-copy, no pickle) keyed by ``/``-joined pytree paths.
- ``convert_gpt2_hf`` / ``convert_llama_hf``: HuggingFace GPT-2 / Llama
  checkpoint tensors → this framework's ``TransformerLM`` param tree
  (both verified logit-level against the HF torch implementations in
  tests/test_io.py), with ``export_llama_hf`` as the Llama inverse.
- ``convert_resnet_torch``: torchvision ResNet ``state_dict`` →
  ``models.resnet.ResNet`` params + batch stats (and ``export_resnet_torch``,
  its inverse, used for round-trip testing and for handing weights back
  to torch users).
- ``load_pretrained``: format-sniffing front door for ``dpp.py
  --pretrained`` (the ref's ``pretrained=True`` fine-tune flow).

torch itself is only needed to *read* .pth files (``load_torch_state_dict``);
all converters operate on plain NumPy arrays.
"""

from __future__ import annotations

from typing import Any, Mapping

import jax
import numpy as np

Pytree = Any
SEP = "/"


# --------------------------- flat safetensors ---------------------------

def flatten_tree(tree: Pytree) -> dict[str, np.ndarray]:
    flat = jax.tree_util.tree_flatten_with_path(tree)[0]
    out = {}
    for path, leaf in flat:
        key = SEP.join(
            str(getattr(p, "key", getattr(p, "idx", p))) for p in path
        )
        out[key] = np.asarray(leaf)
    return out


def unflatten_into(
    like: Pytree, flat: Mapping[str, np.ndarray], *, strict: bool = True
) -> Pytree:
    """Rebuild `like`'s structure from flat keys; shapes must match.

    ``strict`` (default) also rejects checkpoint keys that `like` does not
    consume — a superset checkpoint (different num_layers, wrong model)
    must fail loudly, not half-restore.
    """
    paths, treedef = jax.tree_util.tree_flatten_with_path(like)
    leaves = []
    used = set()
    for path, leaf in paths:
        key = SEP.join(
            str(getattr(p, "key", getattr(p, "idx", p))) for p in path
        )
        if key not in flat:
            raise KeyError(f"missing weight {key!r}")
        used.add(key)
        arr = np.asarray(flat[key])
        if arr.shape != leaf.shape:
            raise ValueError(
                f"{key}: shape {arr.shape} != expected {leaf.shape}"
            )
        leaves.append(arr.astype(leaf.dtype))
    if strict:
        extra = set(flat) - used
        if extra:
            raise ValueError(
                f"checkpoint has {len(extra)} unconsumed keys, e.g. "
                f"{sorted(extra)[:5]} (pass strict=False to ignore)"
            )
    return jax.tree_util.tree_unflatten(treedef, leaves)


def save_params(params: Pytree, path: str) -> None:
    from safetensors.numpy import save_file

    save_file(flatten_tree(params), path)


def load_params(path: str, like: Pytree | None = None) -> Pytree:
    """Load a safetensors file; with ``like``, restore into its structure
    (shape-checked), else return the flat dict."""
    from safetensors.numpy import load_file

    flat = load_file(path)
    if like is None:
        return flat
    return unflatten_into(like, flat)


def load_torch_state_dict(path: str) -> dict[str, np.ndarray]:
    """Read a torch .pth/.pt state_dict into NumPy (CPU, no grad)."""
    import torch

    sd = torch.load(path, map_location="cpu", weights_only=True)
    if hasattr(sd, "state_dict"):
        sd = sd.state_dict()
    return {k: v.detach().numpy() for k, v in sd.items()}


def load_checkpoint_tensors(path: str) -> dict[str, np.ndarray]:
    """Flat name->array dict from either container format: safetensors
    (torch-free) or a torch pickle (.pth/.pt/.bin)."""
    if path.endswith((".safetensors", ".st")):
        from safetensors.numpy import load_file

        return load_file(path)
    return load_torch_state_dict(path)


def stack_scanned_layers(
    params: Pytree, num_layers: int, prefix: str = "layer_"
) -> Pytree:
    """Per-layer param subtrees (``layer_0..layer_{L-1}``, the unscanned
    layout every converter emits) -> the ``scan_layers`` layout: one
    ``layers/block`` subtree whose leaves carry a leading layer dim."""
    stacked = jax.tree.map(
        lambda *xs: np.stack([np.asarray(x) for x in xs]),
        *[params[f"{prefix}{i}"] for i in range(num_layers)],
    )
    rest = {
        k: v for k, v in params.items() if not k.startswith(prefix)
    }
    rest["layers"] = {"block": stacked}
    return rest


def load_pretrained(path: str, model, variables: Pytree) -> Pytree:
    """Initialize ``variables`` (a ``model.init`` result) from a
    pretrained checkpoint — the reference's ``pretrained=True`` analog
    (ref dpp.py:14) driven by ``dpp.py --pretrained``.

    The source format is sniffed from the key names:

    - torchvision ResNet state_dict (``conv1.weight``/``fc.weight``) ->
      ``convert_resnet_torch`` (params + batch stats);
    - HF GPT-2 tensors (``wte.weight``) -> ``convert_gpt2_hf``, stacked
      into the scanned layout when the model scans its layers;
    - HF Llama tensors (``model.embed_tokens.weight``) ->
      ``convert_llama_hf``;
    - otherwise this framework's own flat safetensors (``save_params``
      output, or a full-variables dump with ``collection/`` prefixes).

    Every path shape-checks against ``variables`` before returning.
    """
    flat = load_checkpoint_tensors(path)
    if "conv1.weight" in flat and "fc.weight" in flat:
        from distributeddataparallel_tpu.models.resnet import (
            BottleneckBlock,
        )

        return convert_resnet_torch(
            flat, variables, model.stage_sizes,
            bottleneck=model.block_cls is BottleneckBlock,
        )
    cfg = getattr(model, "cfg", None)
    lm_ckpt = (
        "wte.weight" in flat
        or "transformer.wte.weight" in flat
        or "model.embed_tokens.weight" in flat
    )
    if lm_ckpt and cfg is None:
        # e.g. --model resnet18 --pretrained gpt2.safetensors: a clear
        # format mismatch beats an AttributeError on cfg.scan_layers.
        raise ValueError(
            f"{path!r} looks like a GPT-2/Llama LM checkpoint, but the "
            f"target model ({type(model).__name__}) has no "
            "TransformerConfig — pass a matching --model"
        )
    if "wte.weight" in flat or "transformer.wte.weight" in flat:
        params = convert_gpt2_hf(flat, cfg)
        if cfg.scan_layers:
            params = stack_scanned_layers(params, cfg.num_layers)
        return {
            **variables,
            "params": unflatten_into(variables["params"], flatten_tree(params)),
        }
    if "model.embed_tokens.weight" in flat:
        params = convert_llama_hf(flat, cfg)
        if cfg.scan_layers:
            params = stack_scanned_layers(params, cfg.num_layers)
        return {
            **variables,
            "params": unflatten_into(variables["params"], flatten_tree(params)),
        }
    collections = {"params", "batch_stats", "cache", "intermediates"}
    if flat and all(k.split(SEP, 1)[0] in collections for k in flat):
        # Full-variables dump: route each collection separately.
        nested: dict[str, dict[str, np.ndarray]] = {}
        for k, v in flat.items():
            col, rest = k.split(SEP, 1)
            nested.setdefault(col, {})[rest] = v
        return {
            **variables,
            **{
                col: unflatten_into(variables[col], d)
                for col, d in nested.items()
            },
        }
    return {
        **variables, "params": unflatten_into(variables["params"], flat)
    }


# ----------------------------- GPT-2 (HF) --------------------------------

def convert_gpt2_hf(
    sd: Mapping[str, np.ndarray], cfg
) -> Pytree:
    """HF GPT-2 tensors -> TransformerLM params (cfg from ``gpt2_124m``).

    HF layout notes: Conv1D stores (in, out) so kernels need no
    transpose for x @ W; c_attn packs q,k,v along the output dim;
    lm_head is tied to wte (cfg.tie_embeddings must be True).
    """
    H, D, d = cfg.num_heads, cfg.dims_per_head, cfg.d_model

    def g(key):
        for k in (key, f"transformer.{key}"):
            if k in sd:
                return np.asarray(sd[k])
        raise KeyError(key)

    params: dict[str, Any] = {
        "token_embed": {"embedding": g("wte.weight")},
        "pos_embed": g("wpe.weight")[: cfg.max_seq_len],
        "final_norm": {"scale": g("ln_f.weight"), "bias": g("ln_f.bias")},
    }
    for i in range(cfg.num_layers):
        p = f"h.{i}."
        qkv_w = g(p + "attn.c_attn.weight")  # (d, 3d)
        qkv_b = g(p + "attn.c_attn.bias")    # (3d,)
        qw, kw, vw = np.split(qkv_w, 3, axis=1)
        qb, kb, vb = np.split(qkv_b, 3)
        params[f"layer_{i}"] = {
            "attn_norm": {
                "scale": g(p + "ln_1.weight"), "bias": g(p + "ln_1.bias")
            },
            "attn": {
                "q_proj": {"kernel": qw.reshape(d, H, D),
                           "bias": qb.reshape(H, D)},
                "k_proj": {"kernel": kw.reshape(d, H, D),
                           "bias": kb.reshape(H, D)},
                "v_proj": {"kernel": vw.reshape(d, H, D),
                           "bias": vb.reshape(H, D)},
                "o_proj": {
                    "kernel": g(p + "attn.c_proj.weight").reshape(H, D, d),
                    "bias": g(p + "attn.c_proj.bias"),
                },
            },
            "mlp_norm": {
                "scale": g(p + "ln_2.weight"), "bias": g(p + "ln_2.bias")
            },
            "mlp": {
                "up_proj": {"kernel": g(p + "mlp.c_fc.weight"),
                            "bias": g(p + "mlp.c_fc.bias")},
                "down_proj": {"kernel": g(p + "mlp.c_proj.weight"),
                              "bias": g(p + "mlp.c_proj.bias")},
            },
        }
    return params


# ----------------------------- Llama (HF) --------------------------------

def convert_llama_hf(sd: Mapping[str, np.ndarray], cfg) -> Pytree:
    """HF Llama tensors -> TransformerLM params (cfg from ``llama3_8b``).

    Layout notes: torch Linear stores (out, in) so every kernel
    transposes; q splits (H*D, d) -> (d, H, D) and k/v split at the GQA
    kv-head count (Hkv*D, d) -> (d, Hkv, D); o re-groups (d, H*D) ->
    (H, D, d); SwiGLU is gate/up/down; norms are RMS scales.  No RoPE
    permutation: both HF and ``ops.attention.apply_rope`` use the
    half-split (rotate_half) convention.  ``lm_head.weight`` maps to the
    untied head; a tied config (no ``lm_head`` in sd) reuses the
    embedding, matching ``cfg.tie_embeddings``.
    """
    H, Hkv, D, d = cfg.num_heads, cfg.kv_heads, cfg.dims_per_head, cfg.d_model

    def g(key):
        if key in sd:
            return np.asarray(sd[key])
        raise KeyError(key)

    params: dict[str, Any] = {
        "token_embed": {"embedding": g("model.embed_tokens.weight")},
        "final_norm": {"scale": g("model.norm.weight")},
    }
    if not cfg.tie_embeddings:
        params["lm_head"] = {"kernel": g("lm_head.weight").T}
    for i in range(cfg.num_layers):
        p = f"model.layers.{i}."
        params[f"layer_{i}"] = {
            "attn_norm": {"scale": g(p + "input_layernorm.weight")},
            "attn": {
                "q_proj": {
                    "kernel": g(p + "self_attn.q_proj.weight").T
                    .reshape(d, H, D)
                },
                "k_proj": {
                    "kernel": g(p + "self_attn.k_proj.weight").T
                    .reshape(d, Hkv, D)
                },
                "v_proj": {
                    "kernel": g(p + "self_attn.v_proj.weight").T
                    .reshape(d, Hkv, D)
                },
                "o_proj": {
                    "kernel": g(p + "self_attn.o_proj.weight").T
                    .reshape(H, D, d)
                },
            },
            "mlp_norm": {"scale": g(p + "post_attention_layernorm.weight")},
            "mlp": {
                "gate_proj": {"kernel": g(p + "mlp.gate_proj.weight").T},
                "up_proj": {"kernel": g(p + "mlp.up_proj.weight").T},
                "down_proj": {"kernel": g(p + "mlp.down_proj.weight").T},
            },
        }
    return params


def export_llama_hf(params: Pytree, cfg) -> dict[str, np.ndarray]:
    """Inverse of ``convert_llama_hf`` (round-trip testing / handing
    weights back to HF users).  All outputs are C-contiguous: safetensors
    serializes the raw buffer, so transposed views would save scrambled."""
    H, Hkv, D, d = cfg.num_heads, cfg.kv_heads, cfg.dims_per_head, cfg.d_model
    sd: dict[str, np.ndarray] = {
        "model.embed_tokens.weight": np.asarray(
            params["token_embed"]["embedding"]
        ),
        "model.norm.weight": np.asarray(params["final_norm"]["scale"]),
    }
    if not cfg.tie_embeddings:
        sd["lm_head.weight"] = np.asarray(params["lm_head"]["kernel"]).T
    for i in range(cfg.num_layers):
        lp = params[f"layer_{i}"]
        p = f"model.layers.{i}."
        sd[p + "input_layernorm.weight"] = np.asarray(
            lp["attn_norm"]["scale"]
        )
        sd[p + "self_attn.q_proj.weight"] = (
            np.asarray(lp["attn"]["q_proj"]["kernel"]).reshape(d, H * D).T
        )
        sd[p + "self_attn.k_proj.weight"] = (
            np.asarray(lp["attn"]["k_proj"]["kernel"]).reshape(d, Hkv * D).T
        )
        sd[p + "self_attn.v_proj.weight"] = (
            np.asarray(lp["attn"]["v_proj"]["kernel"]).reshape(d, Hkv * D).T
        )
        sd[p + "self_attn.o_proj.weight"] = (
            np.asarray(lp["attn"]["o_proj"]["kernel"]).reshape(H * D, d).T
        )
        sd[p + "post_attention_layernorm.weight"] = np.asarray(
            lp["mlp_norm"]["scale"]
        )
        for name in ("gate_proj", "up_proj", "down_proj"):
            sd[p + f"mlp.{name}.weight"] = np.asarray(
                lp["mlp"][name]["kernel"]
            ).T
    return {k: np.ascontiguousarray(v) for k, v in sd.items()}


# --------------------------- ResNet (torch) ------------------------------

def convert_resnet_torch(
    sd: Mapping[str, np.ndarray],
    like_variables: Pytree,
    stage_sizes,
    *,
    bottleneck: bool,
) -> Pytree:
    """torchvision ResNet state_dict -> {'params', 'batch_stats'} matching
    ``models.resnet.ResNet`` variables (`like_variables` from model.init).

    Conv kernels transpose OIHW -> HWIO; BN γ/β -> scale/bias and
    running stats -> batch_stats.
    """
    sd = {k: np.asarray(v) for k, v in sd.items()}

    def conv(k):
        return sd[k].transpose(2, 3, 1, 0)  # OIHW -> HWIO

    def bn(prefix):
        return (
            {"scale": sd[prefix + "weight"], "bias": sd[prefix + "bias"]},
            {"mean": sd[prefix + "running_mean"],
             "var": sd[prefix + "running_var"]},
        )

    params: dict[str, Any] = {}
    stats: dict[str, Any] = {}
    params["conv_init"] = {"kernel": conv("conv1.weight")}
    params["bn_init"], stats["bn_init"] = bn("bn1.")

    n_convs = 3 if bottleneck else 2
    block_cls = "BottleneckBlock" if bottleneck else "BasicBlock"
    flat_idx = 0
    for stage, n_blocks in enumerate(stage_sizes):
        for j in range(n_blocks):
            tp = f"layer{stage + 1}.{j}."
            name = f"{block_cls}_{flat_idx}"
            flat_idx += 1
            bp: dict[str, Any] = {}
            bs: dict[str, Any] = {}
            for c in range(n_convs):
                bp[f"Conv_{c}"] = {"kernel": conv(tp + f"conv{c + 1}.weight")}
                bp[f"BatchNorm_{c}"], bs[f"BatchNorm_{c}"] = bn(
                    tp + f"bn{c + 1}."
                )
            if tp + "downsample.0.weight" in sd:
                bp["conv_proj"] = {"kernel": conv(tp + "downsample.0.weight")}
                bp["norm_proj"], bs["norm_proj"] = bn(tp + "downsample.1.")
            params[name] = bp
            stats[name] = bs
    params["Dense_0"] = {
        "kernel": sd["fc.weight"].T, "bias": sd["fc.bias"]
    }

    want = flatten_tree(like_variables)
    got = flatten_tree({"params": params, "batch_stats": stats})
    missing = set(want) - set(got)
    extra = set(got) - set(want)
    if missing or extra:
        raise ValueError(
            f"resnet conversion mismatch: missing={sorted(missing)[:5]} "
            f"extra={sorted(extra)[:5]}"
        )
    return unflatten_into(like_variables, got)


def export_resnet_torch(
    variables: Pytree, stage_sizes, *, bottleneck: bool
) -> dict[str, np.ndarray]:
    """Inverse of ``convert_resnet_torch``: flax variables -> torchvision
    state_dict layout (HWIO -> OIHW, scale/bias -> weight/bias)."""
    params = variables["params"]
    stats = variables["batch_stats"]
    sd: dict[str, np.ndarray] = {}

    def put_conv(key, kern):
        # ascontiguousarray: safetensors serializes the raw buffer, so a
        # transposed VIEW would save scrambled.
        sd[key] = np.ascontiguousarray(np.asarray(kern).transpose(3, 2, 0, 1))

    def put_bn(prefix, p, s):
        sd[prefix + "weight"] = np.asarray(p["scale"])
        sd[prefix + "bias"] = np.asarray(p["bias"])
        sd[prefix + "running_mean"] = np.asarray(s["mean"])
        sd[prefix + "running_var"] = np.asarray(s["var"])

    put_conv("conv1.weight", params["conv_init"]["kernel"])
    put_bn("bn1.", params["bn_init"], stats["bn_init"])
    n_convs = 3 if bottleneck else 2
    block_cls = "BottleneckBlock" if bottleneck else "BasicBlock"
    flat_idx = 0
    for stage, n_blocks in enumerate(stage_sizes):
        for j in range(n_blocks):
            tp = f"layer{stage + 1}.{j}."
            name = f"{block_cls}_{flat_idx}"
            flat_idx += 1
            for c in range(n_convs):
                put_conv(tp + f"conv{c + 1}.weight",
                         params[name][f"Conv_{c}"]["kernel"])
                put_bn(tp + f"bn{c + 1}.", params[name][f"BatchNorm_{c}"],
                       stats[name][f"BatchNorm_{c}"])
            if "conv_proj" in params[name]:
                put_conv(tp + "downsample.0.weight",
                         params[name]["conv_proj"]["kernel"])
                put_bn(tp + "downsample.1.", params[name]["norm_proj"],
                       stats[name]["norm_proj"])
    sd["fc.weight"] = np.ascontiguousarray(
        np.asarray(params["Dense_0"]["kernel"]).T
    )
    sd["fc.bias"] = np.asarray(params["Dense_0"]["bias"])
    return sd
