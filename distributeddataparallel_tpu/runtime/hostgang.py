"""Real-process gang members for the multi-host elastic runtime.

Everywhere else in this repo a "gang" on the CPU simulation is one
process holding N fake devices, because this jaxlib's CPU backend
refuses cross-process collectives.  The MEMBERSHIP protocol has no such
limit — it is pure files/TCP — and this module is where it runs the way
a real fleet runs it: one OS process per host, each hosting exactly one
gang member, driving membership epochs over the rendezvous store while
the launcher supervises the lot.

:class:`HostGangMember` is the per-process driver behind the fault-
matrix tests and the ``scripts/ci.sh`` 3-host chaos smoke:

- joins the store under ``host<rank>``, publishes its launcher-rank
  binding (``rank:<i>`` blob) so the supervisor can tell an absorbed
  in-place resize from an organic crash;
- runs a deterministic step loop: chaos hooks, heartbeat + failure
  detection + epoch transitions through ``ElasticGangCoordinator``;
- publishes its live state (a small counter vector) to the blob board
  every epoch, so a late JOINER catches up from survivors' live state
  instead of a checkpoint — the protocol shape of ROADMAP 3c's
  scale-up warm start;
- when the TCP server dies under it, runs the deterministic re-host
  election (:func:`rendezvous.elect_rehost`) with a liveness fallback:
  if the elected owner never publishes a higher generation, it is
  presumed dead with its host and the next-smallest survivor takes
  over;
- optionally initializes ``jax.distributed`` from the coordinator env
  the launcher already exported (rendezvous works on CPU; collectives
  do not, which is exactly what ``guarded_worker`` maps to a skip).

Transports: ``tcp`` (one member serves a ``TCPRendezvousServer``,
everyone speaks ``TCPRendezvousClient``) or ``file`` (every process
opens the shared-FS ``RendezvousStore`` directly) — the protocol is
identical, which is the point of sharing it.
"""

from __future__ import annotations

import json
import os
import sys
import time

from distributeddataparallel_tpu.runtime.elastic_gang import (
    ElasticGangCoordinator,
)
from distributeddataparallel_tpu.runtime.rendezvous import (
    RETRYABLE_ERRORS,
    AddressBook,
    RendezvousStore,
    RetryPolicy,
    TCPRendezvousClient,
    TCPRendezvousServer,
    elect_rehost,
    rehost_store,
)
from distributeddataparallel_tpu.utils.chaos import (
    FaultInjector,
    PartitionedStoreProxy,
)

__all__ = ["EVICTED_EXIT", "HostGangMember", "hostgang_worker",
           "step_state"]

#: Exit code of a member that discovered its own eviction (tombstoned /
#: partitioned out) — distinct from a crash so tests can assert the
#: victim noticed, and from HOST_KILLED_EXIT so the supervisor's logs
#: tell "shed by the gang" from "chaos killed the host".
EVICTED_EXIT = 78


def _default(cfg: dict, key: str, value):
    return cfg[key] if key in cfg else value


def step_state(acc: float, step: int) -> float:
    """One step of the members' deterministic live-state recurrence.
    Same ops in the same order on every host -> bitwise-identical
    float64 on every member that covered the same step prefix; tests
    replay it as the checkpoint-restore reference."""
    return acc * 1.5 + (step + 1) * 0.125


class HostGangMember:
    """One process = one host = one gang member.

    ``cfg`` keys (all optional unless noted):

    - ``store_root`` (required): shared-FS scratch for the rendezvous
      store, the address book, chaos once-markers, and fault breadcrumbs
    - ``world_size`` (required): gang size at launch
    - ``steps``: step-loop length (default 20); ``step_s``: per-step
      sleep (default 0.05)
    - ``transport``: ``"tcp"`` (default) or ``"file"``
    - ``server_rank``: which rank serves the TCP store (default 0)
    - ``min_size``: resize floor (default 1)
    - ``heartbeat_timeout_s`` / ``suspect_after_s``: failure-detector
      windows (defaults 2.0 / 0.8 — test-fast, not production)
    - ``jax_init``: initialize ``jax.distributed`` from the launcher's
      coordinator env before the loop (default False on CPU sims)
    """

    def __init__(self, rank: int, cfg: dict):
        self.rank = int(rank)
        self.cfg = dict(cfg)
        self.name = f"host{self.rank}"
        self.root = str(cfg["store_root"])
        self.world_size = int(cfg["world_size"])
        self.steps = int(_default(cfg, "steps", 20))
        self.step_s = float(_default(cfg, "step_s", 0.05))
        self.transport = str(_default(cfg, "transport", "tcp"))
        self.server_rank = int(_default(cfg, "server_rank", 0))
        self.min_size = int(_default(cfg, "min_size", 1))
        self.hb_timeout = float(_default(cfg, "heartbeat_timeout_s", 2.0))
        self.suspect_after = float(_default(cfg, "suspect_after_s", 0.8))
        # Must exceed a peer's full RPC retry budget (~1.6s with the
        # 6-attempt policy below) plus its re-host time: the elected
        # owner only STARTS re-hosting after its own poll exhausts
        # retries, and a too-short wait makes the next candidate promote
        # itself over a live owner — a split-brain store.
        self.rehost_wait_s = float(_default(cfg, "rehost_wait_s", 4.0))
        self.book = AddressBook(os.path.join(self.root, "address-book.json"))
        self.server: TCPRendezvousServer | None = None
        self.events = None
        events_dir = os.environ.get("DDP_EVENTS_DIR")
        if events_dir:
            from distributeddataparallel_tpu.observability.events import (
                EventLog,
            )

            self.events = EventLog(
                os.path.join(events_dir, f"events-host{self.rank}.jsonl"),
                self.rank,
            )
        # Chaos: shared once-markers + fault breadcrumbs on the shared
        # scratch, so each entry fires exactly once ACROSS the gang and
        # the supervisor can attribute its verdict.
        self.injector = FaultInjector(
            os.environ.get("DDP_CHAOS", ""),
            state_dir=os.path.join(self.root, ".chaos"),
            events=self.events,
        )
        self.injector.hosts = {str(self.rank): self.name}
        self.injector.abrupt_exit = True
        # Breadcrumbs live INSIDE the store root: that is the path the
        # supervisor was given (spawn(elastic_store=...)), so that is
        # where _last_fault looks for attribution.
        self.injector.fault_log = os.path.join(
            self.root, "store", "faults.jsonl"
        )
        # ``acc`` is the member's live train-state stand-in: a float
        # evolved by a fixed per-step recurrence, so every member that
        # executed (or adopted via catch-up) the same step prefix holds
        # the BITWISE-identical value — the parity the resize tests
        # assert against a checkpoint-restore replay.
        self.state = {"step": 0, "epoch": -1, "resizes": 0, "acc": 0.0}

    # -- store wiring ---------------------------------------------------

    def _make_store(self):
        store_dir = os.path.join(self.root, "store")
        if self.transport == "file":
            return RendezvousStore(
                store_dir,
                heartbeat_timeout_s=self.hb_timeout,
                suspect_after_s=self.suspect_after,
            )
        if self.rank == self.server_rank:
            backing = RendezvousStore(
                store_dir,
                heartbeat_timeout_s=self.hb_timeout,
                suspect_after_s=self.suspect_after,
            )
            # A respawned gang's server must outrank the dead one's
            # book entry, or peers keep resolving to a refused socket.
            prior = self.book.lookup()
            gen = prior[1] + 1 if prior is not None else 0
            self.server = TCPRendezvousServer(
                backing, generation=gen, address_book=self.book
            )
            self.injector.server = self.server
        else:
            # Everyone resolves through the book; the server may not be
            # up yet, so wait for the first publish.
            deadline = time.monotonic() + 30.0
            while self.book.lookup() is None:
                if time.monotonic() >= deadline:
                    raise TimeoutError(
                        "rendezvous server never published its address"
                    )
                time.sleep(0.02)
        # Membership-session span context: one root per member process,
        # deterministic from (gang root, member name) so a respawned
        # incarnation rejoins the SAME trace — its RPCs correlate with
        # the pre-crash ones in the merged timeline.
        from distributeddataparallel_tpu.observability.tracecontext import (
            root_context,
        )

        return TCPRendezvousClient(
            address_book=self.book,
            retry=RetryPolicy(attempts=6, base_s=0.05, max_s=0.4),
            trace=root_context(
                "hostgang", os.path.basename(self.root), self.name
            ).to_fields(),
        )

    # -- lifecycle ------------------------------------------------------

    def run(self) -> dict:
        if self.cfg.get("jax_init"):
            # Membership over jax.distributed: the launcher already
            # exported JAX_COORDINATOR_ADDRESS/JAX_NUM_PROCESSES/
            # JAX_PROCESS_ID for this child; the rendezvous service
            # itself works on CPU (only cross-process COMPUTE does not,
            # which guarded_worker maps to the skip sentinel).
            from distributeddataparallel_tpu.runtime.distributed import (
                init_process_group,
            )

            init_process_group()
        client = self._make_store()
        self.injector.store_root = os.path.join(self.root, "store")
        coord = ElasticGangCoordinator(
            client,
            world=[self.name],
            min_size=self.min_size,
            events=self.events,
            transition_timeout_s=max(8.0, 4 * self.hb_timeout),
        )
        coord.chaos = self.injector
        self.injector.gang = coord
        self.coord = coord
        self.client = client
        try:
            # Join FIRST and wait for the whole launch roster to show up
            # before establishing the epoch: otherwise the first process
            # up proposes epoch 0 over a partial gang and every later
            # joiner forces another epoch — churn that reads exactly like
            # a real resize to the supervisor's ladder.
            self._call(client.join, self.name)
            self._wait_full_gang()
            self._call(coord.start)
            self._call(client.put_blob, f"rank:{self.rank}", self.name)
            self._catch_up()
            self._loop()
            self._call(client.put_blob, f"done:{self.name}",
                       json.dumps(self.state))
            # Collective exit: leave only after every live peer also
            # reported done.  A lone early leaver's tombstone would read
            # as membership drift to a laggard's next poll — a phantom
            # end-of-run resize.
            self._wait_peers_done()
            self._call(coord.stop)
        finally:
            self._shutdown()
        return dict(self.state)

    def _wait_full_gang(self) -> None:
        """Hold the step loop until the launch roster assembled (or a
        late JOINER sees an established epoch and skips the wait): chaos
        step indices stay meaningful relative to a full gang."""
        if self._call(self.client.epoch)["epoch"] >= 0:
            return
        deadline = time.monotonic() + 30.0
        while time.monotonic() < deadline:
            alive = self._call(self.client.alive)
            if alive is not None and len(alive) >= self.world_size:
                return
            self._call(self.client.heartbeat, self.name)
            time.sleep(0.02)
        raise TimeoutError(
            f"gang never assembled {self.world_size} members"
        )

    def _catch_up(self) -> None:
        """Scale-up catch-up: a joiner that lands on an established gang
        adopts the survivors' published live state instead of starting
        from step 0 — the blob board is the live-state channel checkpoint
        restore would otherwise be."""
        blob = self._call(self.client.get_blob, "state")
        if blob:
            try:
                rec = json.loads(blob)
            except json.JSONDecodeError:
                return
            if rec.get("step", 0) > self.state["step"]:
                self.state.update(
                    step=int(rec["step"]),
                    epoch=int(rec.get("epoch", -1)),
                    acc=float(rec.get("acc", 0.0)),
                )

    def _loop(self) -> None:
        while self.state["step"] < self.steps:
            step = self.state["step"]
            self.injector.before_step(step)
            if self.injector.partitioned and not isinstance(
                self.coord.store, PartitionedStoreProxy
            ):
                self.coord.store = PartitionedStoreProxy(self.coord.store)
            decision = self._poll_with_rehost()
            if decision is not None:
                self.state["epoch"] = decision.epoch
                self.state["resizes"] += 1
            self.state["acc"] = step_state(self.state["acc"], step)
            if self._i_publish():
                # Through coord.store, not the raw client: a partitioned
                # member's publishes must vanish with its other writes.
                self._call(
                    self.coord.store.put_blob, "state",
                    json.dumps({
                        "step": step + 1,
                        "epoch": self.state["epoch"],
                        "acc": self.state["acc"],
                    }),
                )
            self.state["step"] = step + 1
            time.sleep(self.step_s)

    def _wait_peers_done(self) -> None:
        deadline = time.monotonic() + 10.0
        while time.monotonic() < deadline:
            try:
                others = [
                    m for m in self.coord.store.alive() if m != self.name
                ]
                if all(
                    self.coord.store.get_blob(f"done:{m}") is not None
                    for m in others
                ):
                    return
                # Keep beating: expiring out while politely waiting for
                # peers would BE the phantom drift this wait prevents.
                self.coord.store.heartbeat(self.name)
            except (*RETRYABLE_ERRORS, RuntimeError):
                return  # store gone or us evicted: nothing to wait for
            time.sleep(0.05)

    def _i_publish(self) -> bool:
        """The smallest member of the agreed roster owns the live-state
        blob (same determinism rule as the proposer/re-host elections)."""
        roster = self.coord.roster
        return bool(roster) and roster[0] == self.name

    def _call(self, fn, *args):
        """One store call with eviction detection: a member that lost
        its own membership exits with EVICTED_EXIT instead of crashing —
        the gang shed us, which for this process is a verdict, not a
        bug."""
        try:
            return fn(*args)
        except RuntimeError as exc:
            msg = str(exc)
            if "lost during" in msg or "not in the surviving" in msg \
                    or "is dead" in msg or "unreachable" in msg:
                self._log("evicted: %s", msg)
                sys.exit(EVICTED_EXIT)
            raise

    def _poll_with_rehost(self):
        try:
            return self._call(self.coord.poll)
        except RETRYABLE_ERRORS:
            if self.transport != "tcp":
                raise  # file transport has no server to re-host
            self._rehost()
            return None

    def _rehost(self) -> None:
        """The store stopped answering past the retry budget: the server
        died.  Deterministic re-host with a liveness fallback — owners
        are tried smallest-first, each given ``rehost_wait_s`` to publish
        a higher generation before the next candidate presumes it dead
        (the server's whole host may have gone down with it)."""
        candidates = list(
            self.coord.roster
            or sorted(self.client.epoch_cache.get(
                max(self.client.epoch_cache, default=-1), {}
            ).get("roster", []))
            or [self.name]
        )
        seen_gen = max(0, self.client.generation_seen)
        while candidates:
            owner = elect_rehost(candidates)
            if owner == self.name:
                gen = seen_gen + 1
                # Seed the new store with the FULL believed roster, not
                # just ourselves: peers re-register through their own
                # heartbeats, but until they do the re-hoster's poll
                # must not read the empty member list as mass death and
                # run a shrinking transition.  A peer that really died
                # with the old server expires out naturally.
                self.server = rehost_store(
                    os.path.join(self.root, f"store-gen{gen}"),
                    self.client.cached_history(),
                    generation=gen,
                    members=list(candidates),
                    address_book=self.book,
                    heartbeat_timeout_s=self.hb_timeout,
                    suspect_after_s=self.suspect_after,
                )
                self.injector.server = self.server
                if self.events is not None:
                    self.events.emit(
                        "rdzv_rehost", generation=gen, owner=self.name
                    )
                self._log("re-hosted rendezvous store at generation %d", gen)
                return
            deadline = time.monotonic() + self.rehost_wait_s
            while time.monotonic() < deadline:
                rec = self.book.lookup()
                if rec is not None and rec[1] > seen_gen:
                    return  # owner came up; client re-resolves via book
                time.sleep(0.05)
            # Owner never published: presume its host died with the
            # server and fall through to the next-smallest survivor.
            candidates = [c for c in candidates if c != owner]
        raise ConnectionError(
            "rendezvous server lost and no surviving candidate re-hosted"
        )

    def _shutdown(self) -> None:
        if self.server is not None:
            # Keep serving until every other roster member reported done
            # or fell out of the live set — the store must outlive its
            # last client.
            deadline = time.monotonic() + 10.0
            store = self.server.store
            while time.monotonic() < deadline:
                others = [
                    m for m in store.alive() if m != self.name
                ]
                if not others:
                    break
                if all(
                    store.get_blob(f"done:{m}") is not None for m in others
                ):
                    break
                time.sleep(0.05)
            try:
                self.server.close()
            except OSError:
                pass
        if self.events is not None:
            self.events.close()

    def _log(self, msg: str, *args) -> None:
        from distributeddataparallel_tpu.utils.logging import get_logger

        get_logger().warning("[%s] " + msg, self.name, *args)


def hostgang_worker(rank: int, cfg: dict) -> None:
    """Module-level launcher target (survives spawn pickling): run one
    :class:`HostGangMember` to completion."""
    HostGangMember(rank, cfg).run()
